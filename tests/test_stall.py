"""Direct StallInspector unit tests with a fake clock.

The inspector's watchdog is a pure function of time (``check_once`` on
an injectable ``clock``), so these tests drive stalls, recoveries, and
the shutdown threshold without sleeping."""

from horovod_tpu.runtime.stall import StallInspector
from horovod_tpu.telemetry import get_registry, instruments


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _stalled_gauge():
    return get_registry().get(instruments.STALLED_RANKS).value


def test_no_warning_before_threshold(caplog):
    clk = FakeClock()
    insp = StallInspector(warning_time=60.0, clock=clk)
    clk.advance(59.0)
    with caplog.at_level("WARNING", logger="horovod_tpu"):
        stalled = insp.check_once()
    assert stalled == []
    assert _stalled_gauge() == 0
    assert not any("stalled" in r.message for r in caplog.records)


def test_warning_fires_once_per_episode(caplog):
    clk = FakeClock()
    insp = StallInspector(warning_time=60.0, clock=clk)
    clk.advance(61.0)
    with caplog.at_level("WARNING", logger="horovod_tpu"):
        insp.check_once()
        insp.check_once()  # same episode: no duplicate warning
    warns = [r for r in caplog.records if "stalled" in r.message]
    assert len(warns) == 1
    assert _stalled_gauge() == 1


def test_progress_resets_episode(caplog):
    clk = FakeClock()
    insp = StallInspector(warning_time=60.0, clock=clk)
    clk.advance(61.0)
    with caplog.at_level("WARNING", logger="horovod_tpu"):
        insp.check_once()
        insp.record_progress(step=1)   # recovery
        assert insp.check_once() == []
        assert _stalled_gauge() == 0
        clk.advance(61.0)              # second stall: warns again
        insp.check_once()
    warns = [r for r in caplog.records if "stalled" in r.message]
    assert len(warns) == 2


def test_shutdown_time_respected():
    clk = FakeClock()
    fired = []
    insp = StallInspector(warning_time=10.0, shutdown_time=30.0,
                          clock=clk, on_shutdown=lambda: fired.append(1))
    clk.advance(15.0)
    insp.check_once()
    assert not insp.shutdown_requested  # warned, below shutdown threshold
    clk.advance(16.0)
    insp.check_once()
    assert insp.shutdown_requested
    assert fired == [1]
    insp.check_once()  # idempotent: the hook fires once
    assert fired == [1]


def test_shutdown_disabled_by_default():
    clk = FakeClock()
    insp = StallInspector(warning_time=10.0, clock=clk)
    clk.advance(1e6)
    insp.check_once()
    assert not insp.shutdown_requested


def test_stalled_ranks_gauge_from_heartbeats():
    """With a cluster heartbeat view, the gauge counts the ranks whose
    last progress is older than the warning threshold — and the warning
    names them."""
    clk = FakeClock(t=100.0)
    beats = {0: 95.0, 1: 20.0, 2: 10.0}  # ranks 1, 2 stalled at t=100
    insp = StallInspector(warning_time=60.0, heartbeat_fn=lambda: beats,
                          clock=clk)
    stalled = insp.check_once()
    assert sorted(stalled) == [1, 2]
    assert _stalled_gauge() == 2


def test_check_interval_independent_of_warning_time():
    """The background loop's cadence is check_interval, not
    warning_time: a 600 s warning threshold with a short interval still
    detects the shutdown threshold promptly. Driven via check_once to
    keep the test clockless."""
    clk = FakeClock()
    insp = StallInspector(warning_time=600.0, shutdown_time=5.0,
                          check_interval=0.01, clock=clk)
    assert insp._check_interval == 0.01  # not derived from warning_time
    clk.advance(6.0)
    insp.check_once()
    # shutdown crossed even though the warning threshold never was
    assert insp.shutdown_requested
    assert _stalled_gauge() == 0


def test_loop_runs_with_real_clock():
    """start/stop smoke: the thread wakes on check_interval and sets
    shutdown_requested from a real (tiny) stall."""
    import time

    insp = StallInspector(warning_time=0.01, shutdown_time=0.02,
                          check_interval=0.01)
    insp.start()
    deadline = time.monotonic() + 5.0
    while not insp.shutdown_requested and time.monotonic() < deadline:
        time.sleep(0.01)
    insp.stop()
    assert insp.shutdown_requested
