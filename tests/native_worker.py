"""Worker process for native-core tests (spawned by test_native_core.py).

Mirrors the reference's test execution model (SURVEY.md §4: the same test
body runs in N processes and differentiates on rank) — but spawned by our
own harness instead of mpirun. Usage:
    python native_worker.py <scenario> <rank> <size> <port>
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from horovod_tpu import _core as core  # noqa: E402


def adasum_combine(a, b):
    dot = float(a @ b)
    na = float(a @ a)
    nb = float(b @ b)
    ca = 1.0 - dot / (2 * na) if na > 0 else 1.0
    cb = 1.0 - dot / (2 * nb) if nb > 0 else 1.0
    return ca * a + cb * b


def adasum_ref(vectors):
    """NumPy reference for the recursive-halving schedule (the
    test_adasum_tensorflow.py:33-63 pattern)."""
    vs = list(vectors)
    while len(vs) > 1:
        vs = [adasum_combine(vs[i], vs[i + 1]) for i in range(0, len(vs), 2)]
    return vs[0]


def scenario_collectives(rank, size):
    # -- allreduce average, fp32
    x = np.arange(8, dtype=np.float32) + rank
    out = core.allreduce(x, "ar.avg", op="average")
    expected = np.arange(8, dtype=np.float32) + (size - 1) / 2.0
    np.testing.assert_allclose(out, expected, rtol=1e-6)

    # -- allreduce sum, int64
    xi = np.full((3, 2), rank + 1, dtype=np.int64)
    out = core.allreduce(xi, "ar.sum", op="sum")
    np.testing.assert_array_equal(out, np.full((3, 2),
                                               size * (size + 1) // 2))

    # -- min / max
    xm = np.array([rank, -rank], dtype=np.float32)
    np.testing.assert_allclose(core.allreduce(xm, "ar.min", op="min"),
                               [0, -(size - 1)])
    np.testing.assert_allclose(core.allreduce(xm, "ar.max", op="max"),
                               [size - 1, 0])

    # -- float16 path
    xh = (np.ones(5) * (rank + 1)).astype(np.float16)
    out = core.allreduce(xh, "ar.f16", op="sum")
    np.testing.assert_allclose(out.astype(np.float32),
                               np.ones(5) * size * (size + 1) / 2)

    # -- fused batch: many small tensors in flight at once
    handles = [core.allreduce_async(
        np.full(4, rank + i, dtype=np.float32), f"fuse.{i}", op="average")
        for i in range(20)]
    for i, h in enumerate(handles):
        np.testing.assert_allclose(h.wait(),
                                   np.full(4, (size - 1) / 2.0 + i),
                                   rtol=1e-6)

    # -- allgatherv: rank r contributes r+1 rows
    xg = np.full((rank + 1, 3), rank, dtype=np.float32)
    out = core.allgather(xg, "ag.v")
    expected = np.concatenate(
        [np.full((r + 1, 3), r, dtype=np.float32) for r in range(size)])
    np.testing.assert_array_equal(out, expected)

    # -- broadcast from root 1
    xb = np.full(6, rank * 10, dtype=np.float64)
    out = core.broadcast(xb, "bc.1", root_rank=1)
    np.testing.assert_array_equal(out, np.full(6, 10.0))

    # -- alltoall
    xa = np.arange(size * 2, dtype=np.int32) + 100 * rank
    out = core.alltoall(xa, "a2a")
    expected = np.concatenate(
        [np.arange(rank * 2, rank * 2 + 2, dtype=np.int32) + 100 * r
         for r in range(size)])
    np.testing.assert_array_equal(out, expected)

    # -- reduce-scatter: rows of the sum, split dim 0 with remainder to
    # the first ranks (NumPy reference slice)
    xr = (np.arange((size + 1) * 3, dtype=np.float32).reshape(size + 1, 3)
          * (rank + 1))
    out = core.reducescatter(xr, "rs.sum", op="sum")
    full = (np.arange((size + 1) * 3, dtype=np.float32)
            .reshape(size + 1, 3) * (size * (size + 1) / 2))
    base, rem = divmod(size + 1, size)
    start = rank * base + min(rank, rem)
    rows = base + (1 if rank < rem else 0)
    np.testing.assert_allclose(out, full[start:start + rows], rtol=1e-6)

    # -- reduce-scatter average
    xr2 = np.full((size, 2), rank + 1.0, dtype=np.float64)
    out = core.reducescatter(xr2, "rs.avg", op="average")
    np.testing.assert_allclose(out, np.full((1, 2), (size + 1) / 2.0))

    # -- barrier
    core.barrier()

    # -- prescale/postscale
    xs = np.ones(4, dtype=np.float32) * (rank + 1)
    out = core.allreduce(xs, "ar.scaled", op="sum", prescale=2.0,
                         postscale=0.5)
    np.testing.assert_allclose(out, np.ones(4) * size * (size + 1) / 2)


def scenario_adasum(rank, size):
    rng = np.random.default_rng(7)
    grads = [rng.standard_normal(33).astype(np.float32)
             for _ in range(size)]
    out = core.allreduce(grads[rank], "adasum.0", op="adasum")
    expected = adasum_ref(grads)
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def scenario_hierarchical_adasum(rank, size):
    """2-level Adasum under a faked multi-host topology (reference:
    adasum_cuda_operations.cc): intra-host SUM reduce-scatter ->
    per-chunk cross-host Adasum tree -> intra-host allgather ->
    divide by local_size. The oracle reproduces the exact schedule,
    including the ring chunk layout with its remainder chunks."""
    L = int(os.environ["HOROVOD_LOCAL_SIZE"])
    C = size // L
    n = 41  # not divisible by L: exercises the remainder chunk layout
    rng = np.random.default_rng(11)
    grads = rng.standard_normal((size, n)).astype(np.float32)
    out = core.allreduce(grads[rank], "hadasum.0", op="adasum")
    # rank = cross_rank * L + local_rank (hvdrun contiguous placement)
    node_sums = grads.reshape(C, L, n).sum(axis=1)
    base, rem = divmod(n, L)
    chunks = []
    for i in range(L):
        start = i * base + min(i, rem)
        ln = base + (1 if i < rem else 0)
        chunks.append(adasum_ref(
            [node_sums[c][start:start + ln] for c in range(C)]))
    expected = np.concatenate(chunks) / L
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)

    # identical per-rank gradients: node sum = L*g, Adasum(L*g,...) = L*g,
    # /L = g — the scale-insensitivity that makes local_size (and not
    # world size) the right divisor (torch/mpi_ops.py:104-110)
    g_vec = rng.standard_normal(17).astype(np.float32)
    out = core.allreduce(g_vec, "hadasum.ident", op="adasum")
    np.testing.assert_allclose(out, g_vec, rtol=1e-5, atol=1e-6)


def scenario_errors(rank, size):
    # shape mismatch across ranks -> negotiated error on every rank
    x = np.ones(4 + rank, dtype=np.float32)
    try:
        core.allreduce(x, "err.shape")
        raise SystemExit("expected shape-mismatch error")
    except RuntimeError as e:
        assert "mismatched shapes" in str(e), str(e)

    # dtype mismatch
    x = (np.ones(4, dtype=np.float32) if rank % 2 == 0
         else np.ones(4, dtype=np.float64))
    try:
        core.allreduce(x, "err.dtype")
        raise SystemExit("expected dtype-mismatch error")
    except RuntimeError as e:
        assert "mismatched dtypes" in str(e), str(e)

    # duplicate name while pending: enqueue two with the same name
    # without waiting (second must fail)
    h1 = core.allreduce_async(np.ones(4, np.float32), "err.dup")
    h2 = core.allreduce_async(np.ones(4, np.float32), "err.dup")
    try:
        h2.wait()
        raise SystemExit("expected duplicate-name error")
    except RuntimeError as e:
        assert "Duplicate" in str(e), str(e)
    h1.wait()
    core.barrier()


def scenario_join(rank, size):
    # all ranks do one allreduce; then ranks >= 2 run out of data and join
    # while 0,1 do one more averaged allreduce (over active ranks only)
    x = np.ones(4, dtype=np.float32) * (rank + 1)
    core.allreduce(x, "join.step0", op="average")
    if rank >= 2:
        last = core.join()
    else:
        out = core.allreduce(x, "join.step1", op="average")
        np.testing.assert_allclose(out, np.ones(4) * 1.5)  # mean of 1,2
        last = core.join()
    # hvd.join() returns the LAST rank to join — one of the stragglers
    # (0/1), and identical on every rank
    assert last in (0, 1), last
    print("JOINLAST", last)


def scenario_join_cached(rank, size):
    """Cache + join interplay: a tensor cached by everyone keeps working
    on the hit path after a rank joins (zero-fill, AND skips joined
    ranks), and the joined rank's cache replica stays consistent."""
    x = np.ones(4, dtype=np.float32) * (rank + 1)
    # two rounds: negotiate + cache, then a pure hit round
    core.allreduce(x.copy(), "jc.a", op="sum")
    core.allreduce(x.copy(), "jc.a", op="sum")
    if rank == size - 1:
        core.join()
    else:
        # cached-tensor allreduce with a joined rank: hit path, zero-fill
        out = core.allreduce(x.copy(), "jc.a", op="average")
        expected = sum(range(1, size)) / (size - 1)
        np.testing.assert_allclose(out, np.ones(4) * expected, rtol=1e-6)
        # a NEW tensor negotiated while a rank is joined (the joined rank
        # must keep its replica in sync even without a local request)
        out = core.allreduce(x.copy(), "jc.b", op="sum")
        np.testing.assert_allclose(out, np.ones(4) * sum(range(1, size)))
        core.join()
    # everyone back: both tensors still usable afterwards
    out = core.allreduce(x.copy(), "jc.a", op="sum")
    np.testing.assert_allclose(out, np.ones(4) * size * (size + 1) / 2)
    out = core.allreduce(x.copy(), "jc.b", op="sum")
    np.testing.assert_allclose(out, np.ones(4) * size * (size + 1) / 2)


def scenario_join_allgather(rank, size):
    # allgather after a rank joined must fail cleanly on every active rank
    # (reference restriction controller.cc:443-447)
    if rank >= size - 1:
        core.join()
    else:
        import time
        time.sleep(0.3)  # let the join land first
        try:
            core.allgather(np.ones((2, 2), np.float32), "jag.x")
            raise SystemExit("expected join+allgather error")
        except RuntimeError as e:
            assert "not supported after a rank has joined" in str(e), str(e)
        core.join()


def scenario_timeline(rank, size):
    x = np.ones(4, dtype=np.float32)
    core.allreduce(x, "tl.a", op="sum")
    core.allreduce(x, "tl.b", op="average")
    core.barrier()


def scenario_cache_bytes(rank, size):
    """Steady-state cache protocol: after warm-up, a 100-tensor workload
    must ride the bitvector path, cutting control-plane bytes/cycle ~10x
    (reference response_cache.h:107-167 short-circuit)."""
    def one_round(tag):
        handles = [core.allreduce_async(
            np.full(8, rank + i, dtype=np.float32), f"cb.{i}", op="sum")
            for i in range(100)]
        for h in handles:
            h.wait()

    one_round("warm")   # negotiates + seeds every rank's cache replica
    core.barrier()
    s0, r0 = core.control_bytes()
    one_round("cold-measure")  # second round: params identical -> hits
    core.barrier()
    s1, r1 = core.control_bytes()
    cold = (s1 - s0) + (r1 - r0)
    for _ in range(3):
        one_round("hot")
        core.barrier()
    s2, r2 = core.control_bytes()
    hot = ((s2 - s1) + (r2 - r1)) / 3.0

    # The very first round ships 100 full requests (+ responses); hit
    # rounds ship a few bitvector words. Compare a hit round against the
    # recorded warm-round traffic.
    core.barrier()
    sw, rw = core.control_bytes()
    # measure a fully-cold equivalent: new names negotiate in full
    handles = [core.allreduce_async(
        np.full(8, rank + i, dtype=np.float32), f"cold.{i}", op="sum")
        for i in range(100)]
    for h in handles:
        h.wait()
    core.barrier()
    sc, rc = core.control_bytes()
    full = (sc - sw) + (rc - rw)
    assert hot * 5 < full, (
        f"steady-state control bytes not reduced: hit-cycle={hot} "
        f"full-cycle={full}")
    # correctness: values still exact on the hit path
    out = core.allreduce(np.full(4, rank + 1.0, dtype=np.float32),
                         "cb.check", op="sum")
    np.testing.assert_allclose(out, np.full(4, size * (size + 1) / 2.0))
    print("CACHEBYTES", json.dumps([cold, hot, full]))


def scenario_cache_invalidation(rank, size):
    """A tensor renegotiates when its params change (shape here): the
    coordinator broadcasts an eviction, ranks re-run the full path, and
    values stay exact."""
    for step in range(3):
        x = np.full(4, rank + 1.0, dtype=np.float32)
        out = core.allreduce(x, "inv.a", op="sum")
        np.testing.assert_allclose(out, np.full(4, size * (size + 1) / 2.0))
    # same name, new shape -> INVALID -> evict + renegotiate
    y = np.full((2, 3), float(rank), dtype=np.float32)
    out = core.allreduce(y, "inv.a", op="sum")
    np.testing.assert_allclose(out, np.full((2, 3), size * (size - 1) / 2.0))
    # and it becomes cacheable again at the new shape
    out = core.allreduce(y, "inv.a", op="sum")
    np.testing.assert_allclose(out, np.full((2, 3), size * (size - 1) / 2.0))


def scenario_zerocopy(rank, size):
    """Borrowed-buffer enqueue: broadcast and single-tensor allreduce
    operate directly in the caller's numpy buffer — the core's memcpy
    counter must not move (the reference wraps framework tensors
    zero-copy, common.h:188-223; this is that guarantee, asserted)."""
    n = 1 << 20  # 4 MB fp32
    x = np.full(n, float(rank), dtype=np.float32)
    core.barrier()
    c0 = core.copy_bytes()
    h = core.broadcast_async(x, "zc.bc", root_rank=0, inplace=True)
    out = h.wait()
    assert out is x
    np.testing.assert_array_equal(x, np.zeros(n, dtype=np.float32))
    c1 = core.copy_bytes()
    assert c1 - c0 == 0, ("broadcast copied", c1 - c0)

    y = np.full(n, rank + 1.0, dtype=np.float32)
    h = core.allreduce_async(y, "zc.ar", op="sum", inplace=True)
    out = h.wait()
    assert out is y
    np.testing.assert_allclose(y, np.full(n, size * (size + 1) / 2.0))
    c2 = core.copy_bytes()
    assert c2 - c1 == 0, ("allreduce copied", c2 - c1)

    # counter sanity: the copying path counts copy-in + copy-out
    z = np.full(n, rank + 1.0, dtype=np.float32)
    core.allreduce(z, "zc.copy", op="sum")
    c3 = core.copy_bytes()
    assert c3 - c2 >= 2 * n * 4, ("copy path under-counted", c3 - c2)

    # the inplace promise is explicit: a non-contiguous array would
    # silently reduce into a hidden copy, so it must refuse instead
    nc = np.ones((8, 8), dtype=np.float32)[:, ::2]
    try:
        core.allreduce_async(nc, "zc.bad", op="sum", inplace=True)
        raise SystemExit("expected inplace ValueError")
    except ValueError as e:
        assert "contiguous" in str(e), str(e)

    # fire-and-forget: the handle is dropped before completion; the
    # borrow registry must keep the buffer alive for the background loop
    core.broadcast_async(np.full(n, float(rank), dtype=np.float32),
                         "zc.ff", root_rank=0, inplace=True)
    core.barrier()  # completes the dropped-handle op safely
    # once complete, the next enqueue sweeps the orphaned handle: the
    # borrow registry and handle table must not grow without bound when
    # callers fire-and-forget (ADVICE r3: eviction not only in wait())
    core.allreduce(np.ones(4, dtype=np.float32), "zc.sweep", op="sum")
    assert core._borrowed_refs == {}, core._borrowed_refs
    assert core._orphaned == set(), core._orphaned


def scenario_hierarchy(rank, size):
    """Fixed collective workload under a faked multi-host topology
    (HOROVOD_LOCAL_SIZE set by the test); values must be exact whether the
    hierarchical gates are on or off, and the final DATABYTES line lets
    the test compare the intra/cross-host traffic split between the two
    modes (reference role: nccl_operations.cc:150 hierarchical schedule +
    MPIHierarchicalAllgather)."""
    n = 64 * 1024  # 256 KB fp32: payload dominates barrier/control noise
    for step in range(3):
        x = np.arange(n, dtype=np.float32) + rank + step
        out = core.allreduce(x, f"h.ar.{step}", op="average")
        np.testing.assert_allclose(
            out, np.arange(n, dtype=np.float32) + (size - 1) / 2.0 + step,
            rtol=1e-6)
    out = core.allreduce(np.full(33, rank + 1.0, dtype=np.float64),
                         "h.sum", op="sum")
    np.testing.assert_allclose(out, np.full(33, size * (size + 1) / 2.0))
    # variable-size allgather: rank r contributes r+1 rows
    xg = np.full((rank + 1, 512), rank, dtype=np.float32)
    out = core.allgather(xg, "h.ag")
    expected = np.concatenate(
        [np.full((r + 1, 512), r, dtype=np.float32) for r in range(size)])
    np.testing.assert_array_equal(out, expected)
    core.barrier()
    lb, cb = core.data_bytes()
    print("DATABYTES", json.dumps([lb, cb]))


def scenario_autotune(rank, size):
    """Run enough allreduces for the Bayesian-opt loop to exhaust its
    sample budget; every rank must end on the coordinator's winning
    (fusion threshold, cycle time)."""
    x = np.ones(1024, dtype=np.float32)
    for i in range(80):
        core.allreduce(x.copy(), f"at.{i % 4}", op="sum")
    st = core.autotune_state()
    assert st["enabled"], st
    if rank == 0:
        assert st["done"], f"tuner did not converge: {st}"
        assert st["samples"] >= int(
            os.environ["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"]), st
    # tuned values must be inside the tuning bounds
    assert 2 ** 16 <= st["fusion_threshold"] <= 2 ** 26, st
    assert 0.5 <= st["cycle_time_ms"] <= 25.0, st
    # one more negotiated cycle so workers definitely saw the final values
    core.barrier()
    st = core.autotune_state()
    print("TUNED", json.dumps([st["fusion_threshold"],
                               round(st["cycle_time_ms"], 6),
                               st["hierarchical"], st["cache"]]))


def scenario_hierarchy_mismatch(rank, size):
    """Only rank 0 exported a multi-host topology (env drift): the
    coordinator-agreed gate must turn hierarchy off for EVERYONE — a
    per-rank decision would run mismatched ring schedules and hang."""
    x = np.arange(256, dtype=np.float32) + rank
    out = core.allreduce(x, "hm.ar", op="average")
    np.testing.assert_allclose(
        out, np.arange(256, dtype=np.float32) + (size - 1) / 2.0, rtol=1e-6)
    out = core.allgather(np.full((rank + 1, 2), rank, dtype=np.float32),
                         "hm.ag")
    expected = np.concatenate(
        [np.full((r + 1, 2), r, dtype=np.float32) for r in range(size)])
    np.testing.assert_array_equal(out, expected)
    core.barrier()


def main():
    scenario, rank, size, port = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]))
    if scenario == "hierarchy_mismatch" and rank == 0:
        # env drift happens BEFORE core init (getenv is read there):
        # rank 0 claims a flat topology while everyone else (test env)
        # claims 2-level and requests hierarchical collectives
        os.environ["HOROVOD_LOCAL_SIZE"] = str(size)
    core.init(rank=rank, size=size, coord_host="127.0.0.1",
              coord_port=port)
    try:
        globals()[f"scenario_{scenario}"](rank, size)
    finally:
        core.shutdown()
    if scenario == "timeline" and rank == 0:
        path = os.environ["HOROVOD_TIMELINE"]
        with open(path) as f:
            events = json.load(f)
        assert any(e.get("name", "").startswith("NEGOTIATE") for e in events)
        assert any(e["tid"] == "tl.a" for e in events)
    print(f"worker {rank} ok")


if __name__ == "__main__":
    main()
