"""Goodput-ledger tests: exclusive-phase accounting on a fake clock, the
/healthz 503 contract through an elastic reset, the 2-rank injected-
stall attribution acceptance run (data_wait + ckpt_stall within 20%,
``hvd-doctor perf`` names the dominant sink), byte-identical compiled
programs with the ledger on/off, and the report/dump round trip."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from horovod_tpu.telemetry import ledger as ledger_lib
from horovod_tpu.telemetry import report as report_mod
from horovod_tpu.telemetry.ledger import PHASES, TimeLedger
from horovod_tpu.telemetry.registry import MetricsRegistry


def fake_ledger(**kw):
    t = [0.0]
    led = TimeLedger(clock=lambda: t[0], registry=MetricsRegistry(),
                     enabled=True, **kw)
    return led, t


# ---------------------------------------------------------------------------
# Ledger unit tests (fake clock)
# ---------------------------------------------------------------------------


def test_step_settle_books_residual_as_compute():
    led, t = fake_ledger()
    led.start()
    t[0] = 1.0
    led.charge("data_wait", 0.3)
    t[0] = 2.0
    led.settle_step()
    snap = led.snapshot()
    assert snap["phases"]["data_wait"] == pytest.approx(0.3)
    assert snap["phases"]["compute"] == pytest.approx(1.7)
    assert snap["wall_seconds"] == pytest.approx(2.0)
    assert snap["unattributed_seconds"] == pytest.approx(0.0)
    assert snap["goodput_ratio"] == pytest.approx(1.7 / 2.0)
    assert snap["steps"] == 1


def test_charges_clipped_to_the_interval():
    """Overlapping measurements cannot manufacture time: pending charges
    larger than the interval scale down proportionally so the phase sum
    still explains the interval exactly once."""
    led, t = fake_ledger()
    led.start()
    led.charge("data_wait", 3.0)
    led.charge("ckpt_stall", 1.0)
    t[0] = 1.0
    led.settle_step()
    snap = led.snapshot()
    assert snap["phases"]["data_wait"] == pytest.approx(0.75)
    assert snap["phases"]["ckpt_stall"] == pytest.approx(0.25)
    assert snap["phases"]["compute"] == pytest.approx(0.0)
    assert sum(snap["phases"].values()) == pytest.approx(1.0)


def test_idle_settle_splits_stall_vs_overhead():
    led, t = fake_ledger()
    led.start()
    t[0] = 0.1  # below the idle threshold -> bookkeeping overhead
    led.settle_idle()
    t[0] = 3.0  # a real unexplained gap -> stall_idle
    led.settle_idle()
    snap = led.snapshot()
    assert snap["phases"]["overhead"] == pytest.approx(0.1)
    assert snap["phases"]["stall_idle"] == pytest.approx(2.9)
    assert snap["phases"]["compute"] == 0.0


def test_phase_bracket_books_elapsed_minus_inner_charges():
    """A recovery bracket charges its span, but sub-stalls measured
    inside it (a ckpt flush during elastic reset) keep their own phase —
    phases stay exclusive, nothing is double-booked."""
    led, t = fake_ledger()
    led.start()
    with led.phase("re-rendezvous", charge="rendezvous_recovery"):
        t[0] = 2.0
        led.charge("ckpt_stall", 0.5)
        t[0] = 3.0
    led.settle_idle()
    snap = led.snapshot()
    assert snap["phases"]["rendezvous_recovery"] == pytest.approx(2.5)
    assert snap["phases"]["ckpt_stall"] == pytest.approx(0.5)
    assert sum(snap["phases"].values()) == pytest.approx(3.0)


def test_preemption_lane_attributes_eviction_time():
    """ISSUE 15: the ledger has a first-class ``preemption`` lane — the
    eviction handler's announce + grace-commit bracket lands there, so
    churn seconds are attributed, never 'unattributed'."""
    assert "preemption" in PHASES
    led, t = fake_ledger()
    led.start()
    t[0] = 1.0
    with led.phase("preemption"):
        t[0] = 1.4  # announce + bounded force-commit
    led.finalize()
    snap = led.snapshot()
    assert snap["phases"]["preemption"] == pytest.approx(0.4)
    assert snap["unattributed_seconds"] == pytest.approx(0.0)
    assert sum(snap["phases"].values()) == \
        pytest.approx(snap["wall_seconds"])
    block = report_mod.goodput_block(ledger=led)
    assert block["phases"]["preemption"] == pytest.approx(0.4)


def test_settle_mid_bracket_accounts_open_span():
    """A scrape-time settle while a rank is parked in recovery books the
    elapsed bracket time instead of leaving it unattributed."""
    led, t = fake_ledger()
    led.start()
    ctx = led.phase("ckpt_restore", charge="rendezvous_recovery")
    ctx.__enter__()
    t[0] = 4.0
    led.settle_idle()
    snap = led.snapshot()
    assert snap["phases"]["rendezvous_recovery"] == pytest.approx(4.0)
    t[0] = 5.0
    ctx.__exit__(None, None, None)
    led.settle_idle()
    assert led.snapshot()["phases"]["rendezvous_recovery"] == \
        pytest.approx(5.0)


def test_settle_mid_nested_brackets_counts_each_second_once():
    """Regression (review finding): a settle firing while NESTED
    brackets are open (re-rendezvous wrapping ckpt_restore — the real
    elastic shape) must book the overlapped span once, and the
    post-settle close path must not re-book or under-book it. Parent
    open t=0, child t=1, settle t=3, child closes t=4, parent t=5 ->
    exactly 5.0s of rendezvous_recovery, nothing else."""
    led, t = fake_ledger()
    led.start()
    parent = led.phase("re-rendezvous", charge="rendezvous_recovery")
    parent.__enter__()
    t[0] = 1.0
    child = led.phase("ckpt_restore", charge="rendezvous_recovery")
    child.__enter__()
    t[0] = 3.0
    # the live view mid-nesting already counts the overlap once
    assert led.snapshot()["phases"]["rendezvous_recovery"] == \
        pytest.approx(3.0)
    led.settle_idle()
    t[0] = 4.0
    child.__exit__(None, None, None)
    t[0] = 5.0
    parent.__exit__(None, None, None)
    snap = led.finalize()
    assert snap["phases"]["rendezvous_recovery"] == pytest.approx(5.0)
    assert snap["phases"]["stall_idle"] == 0.0
    assert sum(snap["phases"].values()) == pytest.approx(5.0)


def test_active_health_label_tracks_bracket_stack():
    led, _t = fake_ledger()
    assert led.active_health_label() is None
    with led.phase("re-rendezvous", charge="rendezvous_recovery"):
        assert led.active_health_label() == "re-rendezvous"
        with led.phase("ckpt_restore", charge="rendezvous_recovery"):
            assert led.active_health_label() == "ckpt_restore"
        assert led.active_health_label() == "re-rendezvous"
    assert led.active_health_label() is None


def test_disabled_ledger_is_inert(monkeypatch):
    led = TimeLedger(registry=MetricsRegistry(), enabled=False)
    led.start()
    led.charge("data_wait", 1.0)
    led.settle_step()
    assert not led.started
    snap = led.snapshot()
    assert snap["wall_seconds"] == 0.0
    assert all(v == 0.0 for v in snap["phases"].values())
    monkeypatch.setenv("HOROVOD_GOODPUT", "0")
    assert not ledger_lib.enabled()
    monkeypatch.setenv("HOROVOD_GOODPUT", "1")
    assert ledger_lib.enabled()


def test_health_brackets_survive_goodput_opt_out():
    """Regression (review finding): HOROVOD_GOODPUT=0 opts out of the
    TIME ACCOUNTING only — the /healthz 503-during-transition contract
    rides the same brackets and must keep working, with nothing
    charged."""
    led = TimeLedger(registry=MetricsRegistry(), enabled=False)
    with led.phase("re-rendezvous", charge="rendezvous_recovery"):
        assert led.active_health_label() == "re-rendezvous"
    assert led.active_health_label() is None
    snap = led.snapshot()
    assert all(v == 0.0 for v in snap["phases"].values())
    assert not led.started


def test_load_dumps_sums_elastic_lives(tmp_path):
    """Regression (review finding): a relaunched elastic worker writes
    one dump per LIFE (per-epoch dump dirs); the report must sum the
    disjoint windows, not keep the newest — dropping the pre-kill life
    hides exactly the recovery cost the report exists to expose."""
    (tmp_path / "epoch-1").mkdir()
    (tmp_path / "epoch-2").mkdir()
    _synth_dump(tmp_path / "epoch-1", 0, {"data_wait": 2.0}, steps=3)
    _synth_dump(tmp_path / "epoch-2", 0,
                {"rendezvous_recovery": 1.0}, steps=4)
    dumps, skipped = report_mod.load_dumps(str(tmp_path))
    assert not skipped and list(dumps) == [0]
    d = dumps[0]
    assert d["lives"] == 2
    assert d["phases"]["data_wait"] == pytest.approx(2.0)
    assert d["phases"]["rendezvous_recovery"] == pytest.approx(1.0)
    assert d["phases"]["compute"] == pytest.approx(2.0)  # 1.0 per life
    assert d["wall_seconds"] == pytest.approx(3.0 + 2.0)
    assert d["steps"] == 7
    report = report_mod.aggregate(dumps)
    assert report["fleet"]["wall_seconds"] == pytest.approx(5.0)
    assert report["fleet"]["dominant_sink"] == "data_wait"


def test_ledger_mirrors_into_registry():
    reg = MetricsRegistry()
    t = [0.0]
    led = TimeLedger(clock=lambda: t[0], registry=reg, enabled=True)
    led.start()
    led.charge("data_wait", 0.25)
    t[0] = 1.0
    led.settle_step()
    from horovod_tpu.telemetry import instruments as ti
    fam = reg.get(ti.TIME_SECONDS)
    sample = fam.sample()
    assert sample[("data_wait",)] == pytest.approx(0.25)
    assert sample[("compute",)] == pytest.approx(0.75)
    ratio = reg.get(ti.GOODPUT_RATIO)
    assert ratio.value == pytest.approx(0.75)


def test_dominant_sink():
    led, t = fake_ledger()
    led.start()
    led.charge("data_wait", 0.6)
    led.charge("ckpt_stall", 0.2)
    t[0] = 2.0
    led.settle_step()
    phase, secs = led.dominant_sink()
    assert phase == "data_wait" and secs == pytest.approx(0.6)


# ---------------------------------------------------------------------------
# Dump -> report -> hvd-doctor perf round trip (fake ledgers)
# ---------------------------------------------------------------------------


def _synth_dump(tmp_path, rank, phases, steps=4):
    led, t = fake_ledger()
    led.start()
    for p, s in phases.items():
        led.charge(p, s)
    t[0] = sum(phases.values()) + 1.0  # +1.0 of compute residual
    led.settle_step()
    led._steps_settled = steps
    path = led.write_dump(str(tmp_path), rank)
    assert path and path.endswith(f"goodput.rank{rank}.json")
    return path


def test_report_aggregates_and_names_dominant_sink(tmp_path, capsys):
    _synth_dump(tmp_path, 0, {"data_wait": 3.0, "ckpt_stall": 0.5})
    _synth_dump(tmp_path, 1, {"data_wait": 2.0, "compile": 1.0})
    dumps, skipped = report_mod.load_dumps(str(tmp_path))
    assert sorted(dumps) == [0, 1] and not skipped
    report = report_mod.aggregate(dumps)
    fleet = report["fleet"]
    assert fleet["dominant_sink"] == "data_wait"
    assert fleet["phases"]["data_wait"] == pytest.approx(5.0)
    assert fleet["phases"]["compute"] == pytest.approx(2.0)
    text = report_mod.format_report(report)
    assert "DOMINANT TIME SINK (fleet): data_wait" in text
    assert "rank 0" in text and "rank 1" in text
    # the hvd-doctor perf mode prints the same report
    from horovod_tpu.diag.doctor import doctor_cli
    assert doctor_cli(["perf", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "DOMINANT TIME SINK (fleet): data_wait" in out


def test_report_crosscheck_against_merged_trace(tmp_path):
    _synth_dump(tmp_path, 0, {"data_wait": 1.0})  # wall = 2.0 s
    # rank 0's trace spans 2.0 s (matches) in trace microseconds
    trace = [{"name": "a", "ph": "i", "ts": 0, "pid": 0},
             {"name": "b", "ph": "i", "ts": 2_000_000, "pid": 0}]
    tpath = tmp_path / "merged.json"
    tpath.write_text(json.dumps(trace))
    dumps, _ = report_mod.load_dumps(str(tmp_path))
    report = report_mod.aggregate(dumps)
    check = report_mod.crosscheck_trace(report, str(tpath))
    assert check["ranks"][0]["ok"] and not check["mismatched"]
    # a wildly shorter trace span is flagged
    tpath.write_text(json.dumps(trace[:1] + [
        {"name": "b", "ph": "i", "ts": 100_000, "pid": 0}]))
    check = report_mod.crosscheck_trace(report, str(tpath))
    assert check["mismatched"] == [0]
    assert "TRACE CROSS-CHECK" in report_mod.format_report(report)


def test_hvdrun_goodput_report_flag(tmp_path):
    from horovod_tpu.run import run as run_mod
    _synth_dump(tmp_path, 0, {"data_wait": 1.0})
    assert run_mod.main(["--goodput-report", str(tmp_path)]) == 0
    # no dumps -> the report says so and exits 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert run_mod.main(["--goodput-report", str(empty)]) == 2


# ---------------------------------------------------------------------------
# /healthz 503 during an elastic transition
# ---------------------------------------------------------------------------


def _get_health(port):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_healthz_503_through_elastic_reset(monkeypatch, tmp_path):
    """The satellite contract: during a re-rendezvous (elastic reset)
    the rank's /healthz flips to 503 with the phase in the body, then
    back to 200 once the rank is serving again. Driven through a REAL
    elastic retry (@hvd.elastic.run) with the real services health_fn."""
    import horovod_tpu as hvd_mod
    from horovod_tpu import basics, elastic
    from horovod_tpu.elastic.exceptions import WorkerFailureError

    monkeypatch.setenv("HOROVOD_METRICS_PORT", "0")
    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        port = basics._state.metrics_server.port
        status, body = _get_health(port)
        assert status == 200 and body["status"] == "ok"
        assert "phase" not in body

        probes = []

        def probe_during_reset():
            probes.append(_get_health(port))

        state = elastic.ObjectState(value=1)
        state.register_reset_callbacks([probe_during_reset])
        calls = [0]

        @elastic.run(retryable=(WorkerFailureError,))
        def train(st):
            calls[0] += 1
            if calls[0] == 1:
                raise WorkerFailureError("injected peer failure")
            return st.value

        assert train(state) == 1
        # the probe ran INSIDE state.on_reset -> saw the 503 + phase
        assert probes, "reset callback never ran"
        status, body = probes[0]
        assert status == 503
        assert body["status"] == "recovering"
        assert body["phase"] == "re-rendezvous"
        # recovered: healthy again
        status, body = _get_health(port)
        assert status == 200 and body["status"] == "ok"
        # and the recovery time landed in the ledger
        snap = hvd_mod.telemetry.get_ledger().snapshot()
        assert snap["phases"]["rendezvous_recovery"] > 0
    finally:
        hvd_mod.shutdown()


# ---------------------------------------------------------------------------
# THE acceptance run: 2 ranks, injected data stall + forced blocking
# checkpoint -> the end-of-run report attributes both within 20% and
# hvd-doctor perf names the dominant sink.
# ---------------------------------------------------------------------------

DATA_DELAY_S = 0.10
N_STEPS = 6
CKPT_SLEEP_S = 0.12
N_SAVES = 2


def _attribution_run(monkeypatch, tmp_path, rank, size, dump_dir):
    import jax
    import optax

    import horovod_tpu as hvd_mod
    from horovod_tpu import training
    from horovod_tpu.ckpt import AsyncCheckpointer
    from horovod_tpu.ckpt import sharded as sharded_lib
    from horovod_tpu.data import ArraySource, PrefetchLoader
    from horovod_tpu.models.simple import MLP

    monkeypatch.setenv("HOROVOD_RANK", str(rank))
    monkeypatch.setenv("HOROVOD_SIZE", str(size))
    monkeypatch.setenv("HOROVOD_FLIGHTREC_DIR", dump_dir)
    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        batch = 8
        n = size * batch * N_STEPS
        rng = np.random.default_rng(0)
        xs = rng.standard_normal((n, 4)).astype(np.float32)
        ys = rng.integers(0, 3, n).astype(np.int32)

        model = MLP(features=(8, 3))
        tx = hvd_mod.DistributedOptimizer(optax.sgd(0.01))
        state = training.create_train_state(
            model, tx, jax.random.PRNGKey(0), xs[:1])

        loader = PrefetchLoader(
            ArraySource([xs, ys], delay_s=DATA_DELAY_S), batch,
            rank=rank, world=size, seed=0, shuffle=False, epochs=None)
        step = training.make_train_step(model, tx, loader=loader,
                                        donate=False)
        for _ in range(N_STEPS):
            state, _loss = step(state)
        loader.close()

        # the forced blocking checkpoint: every shard write sleeps —
        # the training thread sits in save(block=True)'s flush
        real_write = sharded_lib.write_shard

        def slow_write(directory, s, payload):
            time.sleep(CKPT_SLEEP_S)
            return real_write(directory, s, payload)

        monkeypatch.setattr(sharded_lib, "write_shard", slow_write)
        ck = AsyncCheckpointer(str(tmp_path / f"ckpt-r{rank}"), rank=0,
                               world=1)
        tree = {"w": np.arange(64, dtype=np.float32)}
        for s in range(1, N_SAVES + 1):
            ck.save(s, tree, block=True)
        ck.close()
        monkeypatch.setattr(sharded_lib, "write_shard", real_write)
    finally:
        hvd_mod.shutdown()  # writes goodput.rank<rank>.json to dump_dir


def test_two_rank_injected_stall_attribution(monkeypatch, tmp_path,
                                             capsys):
    import optax

    import horovod_tpu as hvd_mod
    from horovod_tpu.diag.doctor import doctor_cli

    # warm the compile caches with the identical step shape so the
    # measured runs' compile phase stays small relative to the injected
    # stalls (the persistent XLA cache in conftest makes this stick)
    warm_dir = tmp_path / "warm"
    warm_dir.mkdir()
    _attribution_run(monkeypatch, tmp_path, 0, 2, str(warm_dir))

    injected_data = N_STEPS * DATA_DELAY_S
    injected_ckpt = N_SAVES * CKPT_SLEEP_S

    # The timing bounds (±20% on the injected stalls, <2% unattributed)
    # flake under CPU contention on the single-core CI box; retry the
    # measured run up to 3× with fresh dirs — the structural asserts
    # (both dumps present, self-describing build_info, doctor exits 0)
    # hold unconditionally on every attempt, only the timing bounds may
    # send us around again (same pattern as test_ckpt.py's async-save
    # stall bound).
    timing_failures = []
    for attempt in range(3):
        base = tmp_path / f"try{attempt}"
        base.mkdir()
        dump_dir = base / "dumps"
        dump_dir.mkdir()
        for rank in (0, 1):
            _attribution_run(monkeypatch, base, rank, 2, str(dump_dir))

        dumps, skipped = report_mod.load_dumps(str(dump_dir))
        assert sorted(dumps) == [0, 1], \
            f"missing dumps (skipped={skipped})"
        report = report_mod.aggregate(dumps)
        assert doctor_cli(["perf", str(dump_dir)]) == 0
        out = capsys.readouterr().out
        # dumps are self-describing (satellite: hvd_build_info)
        bi = report["ranks"][0]["build_info"]
        assert bi and set(bi) == {"version", "jax", "backend", "world"}
        assert bi["world"] == "2"

        try:
            for rank in (0, 1):
                phases = report["ranks"][rank]["phases"]
                assert phases["data_wait"] == pytest.approx(
                    injected_data, rel=0.20), \
                    f"rank {rank} data_wait {phases['data_wait']:.3f}s " \
                    f"vs injected {injected_data:.3f}s"
                assert phases["ckpt_stall"] == pytest.approx(
                    injected_ckpt, rel=0.20), \
                    f"rank {rank} ckpt_stall {phases['ckpt_stall']:.3f}s " \
                    f"vs injected {injected_ckpt:.3f}s"
                # every second explained: the dump was written after a
                # final settle, so the unattributed tail is ~nothing
                assert report["ranks"][rank]["unattributed_seconds"] < \
                    0.02 * report["ranks"][rank]["wall_seconds"] + 1e-6
            # the dominant sink is the injected data stall, fleet-wide
            # and on both ranks — and hvd-doctor perf says so
            assert report["fleet"]["dominant_sink"] == "data_wait"
            for rank in (0, 1):
                assert report["ranks"][rank]["dominant_sink"] == \
                    "data_wait"
            assert "DOMINANT TIME SINK (fleet): data_wait" in out
            return  # timing bounds held
        except AssertionError as e:
            timing_failures.append(f"attempt {attempt}: {e}")

    pytest.fail("timing attribution out of bounds on 3 attempts:\n"
                + "\n".join(timing_failures))


# ---------------------------------------------------------------------------
# Byte-identical compiled programs with the ledger on/off
# ---------------------------------------------------------------------------


def test_compiled_step_byte_identical_ledger_on_off(hvd, monkeypatch):
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd_api
    from horovod_tpu import training
    from horovod_tpu.models.simple import MLP

    def lower_text():
        model = MLP(features=(8, 2))
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.1))
        state = training.create_train_state(
            model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 4)))
        step = training.make_train_step(model, tx, donate=False,
                                        telemetry=False)
        return step.lower(state, jnp.zeros((8, 4), jnp.float32),
                          jnp.zeros((8,), jnp.int32)).as_text()

    monkeypatch.setenv("HOROVOD_GOODPUT", "0")
    ledger_lib.reset_run()
    off = lower_text()
    monkeypatch.setenv("HOROVOD_GOODPUT", "1")
    led = ledger_lib.reset_run()
    on = lower_text()
    assert on == off
    assert led.enabled  # the on-build really ran with the ledger live


# ---------------------------------------------------------------------------
# Overhead: the per-step ledger work stays under the 2% budget (slow).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_ledger_overhead_under_2pct(hvd):
    """The per-step ledger cost — one charge + one settle_step — timed
    in isolation against a real ~10ms compiled step, same protocol as
    the telemetry-instrumentation bound."""
    import jax
    import optax

    import horovod_tpu as hvd_api
    from horovod_tpu import training
    from horovod_tpu.models.simple import MLP

    model = MLP(features=(1024, 1024, 10))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.01))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 512)).astype(np.float32)
    y = rng.integers(0, 10, 256).astype(np.int32)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        x[:1])
    step = training.make_train_step(model, tx, donate=False,
                                    telemetry=False)

    def run(n):
        s = state
        t0 = time.perf_counter()
        for _ in range(n):
            s, loss = step(s, x, y)
        jax.block_until_ready(loss)
        return time.perf_counter() - t0

    run(3)
    iters = 30
    step_s = min(run(iters) for _ in range(3)) / iters

    led = TimeLedger(registry=MetricsRegistry(), enabled=True)
    led.start()
    reps = 2000
    t0 = time.perf_counter()
    for _ in range(reps):
        led.charge("data_wait", 1e-6)
        led.settle_step()
    ledger_s = (time.perf_counter() - t0) / reps
    overhead = ledger_s / step_s
    assert overhead < 0.02, \
        f"ledger overhead {overhead:.2%} >= 2% " \
        f"(settle {ledger_s * 1e6:.1f} us vs step {step_s * 1e3:.2f} ms)"


# ---------------------------------------------------------------------------
# Fleet aggregation: heartbeats -> cluster_view goodput
# ---------------------------------------------------------------------------


def test_cluster_view_aggregates_fleet_goodput():
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.telemetry import get_registry, instruments as ti

    driver = ElasticDriver(FixedHosts({"hostA": 2}), min_np=2)
    beats = {0: {"step": 5, "time": 1.0,
                 "metrics": {"goodput": {"compute": 8.0,
                                         "data_wait": 1.0}}},
             1: {"step": 5, "time": 1.0,
                 "metrics": {"goodput": {"compute": 6.0,
                                         "ckpt_stall": 1.0}}}}
    driver.worker_progress = lambda: beats
    view = driver.cluster_view()
    gp = view["goodput"]
    assert gp["phases"]["compute"] == pytest.approx(14.0)
    assert gp["phases"]["data_wait"] == pytest.approx(1.0)
    assert gp["ratio"] == pytest.approx(14.0 / 16.0)
    assert get_registry().get(ti.GOODPUT_RATIO).value == \
        pytest.approx(14.0 / 16.0)
    driver.stop()


def test_kv_snapshot_carries_goodput_phases():
    from horovod_tpu.telemetry import instruments as ti

    reg = MetricsRegistry()
    t = [0.0]
    led = TimeLedger(clock=lambda: t[0], registry=reg, enabled=True)
    led.start()
    led.charge("data_wait", 0.5)
    t[0] = 2.0
    led.settle_step()
    snap = ti.kv_snapshot(reg)
    assert snap["goodput"]["data_wait"] == pytest.approx(0.5)
    assert snap["goodput"]["compute"] == pytest.approx(1.5)
    assert len(json.dumps(snap)) < 500  # still heartbeat-compact
