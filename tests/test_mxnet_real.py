"""Real-MXNet adapter tests (reference coverage: test/test_mxnet.py — op
correctness across ranks, DistributedOptimizer/DistributedTrainer grad
averaging under the real engine, parameter broadcast incl. gluon
deferred-init materialization).

Every test body runs in fresh worker processes via ``api.run`` so the
real ``mxnet`` import never collides with the in-process fake that
``test_mxnet_adapter.py`` installs into ``sys.modules``. Skipped when
mxnet isn't importable (CI's mxnet job installs it; the dev image does
not ship it).
"""

import importlib.machinery

import numpy as np
import pytest

from horovod_tpu.run import api


def _mx_available():
    try:
        return importlib.machinery.PathFinder.find_spec(
            "mxnet") is not None
    except (ImportError, ValueError):
        return False


pytestmark = pytest.mark.skipif(not _mx_available(),
                                reason="mxnet not installed")

_ENV = {"JAX_PLATFORMS": "cpu", "MXNET_ENGINE_TYPE": "NaiveEngine"}


def test_ops_across_ranks():
    """allreduce/allgather/broadcast on real NDArrays: write-back must
    survive the engine (asnumpy barrier semantics)."""
    def fn():
        import mxnet as mx
        import numpy as np
        import horovod_tpu.mxnet as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        out = {}
        x = mx.nd.array(np.full((2, 3), r + 1.0, np.float32))
        out["ar"] = hvd.allreduce(x, name="r.ar").asnumpy().tolist()
        g = hvd.allgather(mx.nd.array(
            np.full((r + 1, 2), r, np.float32)), name="r.ag")
        out["ag"] = g.asnumpy().tolist()
        b = mx.nd.array(np.full(4, float(r * 10), np.float32))
        hvd.broadcast_(b, root_rank=1, name="r.bc")
        out["bc"] = b.asnumpy().tolist()
        return out

    results = api.run(fn, np=2, extra_env=_ENV, timeout=600)
    for res in results:
        np.testing.assert_allclose(res["ar"], np.full((2, 3), 1.5))
        np.testing.assert_allclose(
            res["ag"], [[0, 0], [1, 1], [1, 1]])
        np.testing.assert_allclose(res["bc"], np.full(4, 10.0))


def test_distributed_trainer_averages_grads():
    """DistributedTrainer on a real gluon block: the update must apply
    the rank-averaged gradient on every rank."""
    def fn():
        import mxnet as mx
        import numpy as np
        import horovod_tpu.mxnet as hvd
        hvd.init()
        r = hvd.rank()

        net = mx.gluon.nn.Dense(1, use_bias=False, in_units=2)
        net.initialize(mx.init.Constant(1.0))
        params = net.collect_params()
        hvd.broadcast_parameters(params, root_rank=0)

        trainer = hvd.DistributedTrainer(params, "sgd",
                                         {"learning_rate": 1.0})
        x = mx.nd.array(np.full((1, 2), r + 1.0, np.float32))
        with mx.autograd.record():
            y = net(x).sum()
        y.backward()
        trainer.step(1)
        w = list(params.values())[0].data().asnumpy()
        return w.tolist()

    results = api.run(fn, np=2, extra_env=_ENV, timeout=600)
    # grad per rank = x = r+1 -> mean 1.5; w = 1 - 1.5 = -0.5
    for res in results:
        np.testing.assert_allclose(res, [[-0.5, -0.5]], rtol=1e-6)


def test_deferred_init_param_broadcasts_at_materialization():
    """A gluon block with deferred shapes: broadcast_parameters arms the
    param so the first forward materializes root's weights on every rank
    (reference mxnet/__init__.py:118-153)."""
    def fn():
        import mxnet as mx
        import numpy as np
        import horovod_tpu.mxnet as hvd
        hvd.init()
        r = hvd.rank()

        net = mx.gluon.nn.Dense(2, use_bias=False)  # in_units deferred
        # rank-divergent init: without the broadcast arm, ranks diverge
        net.initialize(mx.init.Constant(float(r + 1)))
        hvd.broadcast_parameters(net.collect_params(), root_rank=0)
        x = mx.nd.ones((1, 3))
        net(x)  # materializes the deferred weight
        w = list(net.collect_params().values())[0].data().asnumpy()
        return w.tolist()

    results = api.run(fn, np=2, extra_env=_ENV, timeout=600)
    for res in results:  # every rank must hold root's all-ones weight
        np.testing.assert_allclose(res, np.ones((2, 3)))


def test_dtype_sweep_and_inplace():
    """Reference test_horovod_allreduce/_inplace + broadcast dtype
    coverage: the full supported dtype set through real NDArrays, plus
    the in-place variants."""
    def fn():
        import mxnet as mx
        import numpy as np
        import horovod_tpu.mxnet as hvd
        hvd.init()
        r = hvd.rank()
        out = {}
        for dt in ["uint8", "int32", "int64", "float16", "float32",
                   "float64"]:
            x = mx.nd.array(np.full((2, 2), r + 1, dtype=dt), dtype=dt)
            s = hvd.allreduce(x, average=False, name=f"sw.{dt}")
            assert s.dtype == np.dtype(dt), (dt, s.dtype)
            out[f"ar.{dt}"] = s.asnumpy().tolist()
            b = mx.nd.array(np.full(3, r + 5, dtype=dt), dtype=dt)
            hvd.broadcast_(b, root_rank=1, name=f"bc.{dt}")
            out[f"bc.{dt}"] = b.asnumpy().tolist()
        # in-place allreduce writes back into the caller's NDArray
        y = mx.nd.array(np.full(4, float(r + 1), np.float32))
        hvd.allreduce_(y, average=True, name="sw.inplace")
        out["ar_"] = y.asnumpy().tolist()
        return out

    for res in api.run(fn, np=2, extra_env=_ENV, timeout=600):
        for dt in ["uint8", "int32", "int64", "float16", "float32",
                   "float64"]:
            np.testing.assert_allclose(res[f"ar.{dt}"],
                                       np.full((2, 2), 3))
            np.testing.assert_allclose(res[f"bc.{dt}"], np.full(3, 6))
        np.testing.assert_allclose(res["ar_"], np.full(4, 1.5))


def test_cross_rank_mismatch_errors():
    """Reference test_horovod_allreduce_error/_type_error/
    _broadcast_rank_error: shape/dtype disagreements and invalid roots
    must raise, not hang."""
    def fn():
        import mxnet as mx
        import numpy as np
        import horovod_tpu.mxnet as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        out = {}
        shape = (17,) if r == 0 else (17, 17)
        try:
            hvd.allreduce(mx.nd.array(np.ones(shape, np.float32)),
                          name="e.shape")
            out["shape"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["shape"] = str(e)
        val = np.ones(4, np.int32) if r == 0 else np.ones(4, np.float32)
        try:
            hvd.allreduce(mx.nd.array(val, dtype=val.dtype),
                          average=False, name="e.dtype")
            out["dtype"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["dtype"] = str(e)
        try:
            hvd.broadcast(mx.nd.array(np.ones(2, np.float32)),
                          root_rank=n, name="e.root")
            out["root"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["root"] = str(e)
        return out

    for res in api.run(fn, np=2, extra_env=_ENV, timeout=600):
        assert "mismatched shapes" in res["shape"], res["shape"]
        assert "mismatched dtypes" in res["dtype"], res["dtype"]
        assert "outside" in res["root"], res["root"]
