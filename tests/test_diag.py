"""Flight recorder + desync doctor (horovod_tpu/diag/).

Unit suite on fake clocks (no sleeps): ring-buffer wraparound, dump
idempotency under double signals, desync-digest divergence; the doctor's
probable-cause classifications from synthesized dumps; the /flightrec
telemetry endpoint; the byte-identical-compiled-program guarantee; and a
tier-1-safe 2-rank CPU round-trip smoke (recorder -> dump -> doctor).
The dead-rank end-to-end (SIGKILL mid-collective under hvdrun) lives
with the other failure-injection tests in tests/test_launcher.py.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd_api
from horovod_tpu import training
from horovod_tpu.diag import desync, doctor
from horovod_tpu.diag import recorder as recorder_mod
from horovod_tpu.diag.recorder import FlightRecorder
from horovod_tpu.models.simple import MLP

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def make_recorder(tmp_path, rank=0, size=2, capacity=64, t0=50.0):
    clk = FakeClock(t0)
    rec = FlightRecorder(capacity=capacity, rank=rank, size=size,
                         dump_dir=str(tmp_path), clock=clk,
                         wall_clock=lambda: clk.t + 1.7e9)
    return rec, clk


def drive_schedule(rec, clk, ops, complete=True):
    """Enter (and optionally exit) one collective per (op, shape) pair."""
    for op, shape in ops:
        clk.advance(0.01)
        seq = rec.collective_enter(op, name=None, shape=shape,
                                   dtype="float32",
                                   nbytes=int(np.prod(shape)) * 4)
        if complete:
            clk.advance(0.01)
            rec.collective_exit(op, seq)
    return rec


# ---- ring buffer ---------------------------------------------------------

def test_ring_buffer_wraparound(tmp_path):
    rec, clk = make_recorder(tmp_path, capacity=8)
    drive_schedule(rec, clk, [("allreduce", (4,))] * 20)
    events = rec.snapshot()["events"]
    assert len(events) == 8  # bounded forever
    # counters and digest survive the wrap even though events rolled off
    assert rec.collective_seq == 20
    assert rec.last_completed_seq == 20
    assert rec.snapshot()["events_total"] == 1 + 40  # start + 20 B/E pairs
    # the newest events are the ones kept
    seqs = [ev["seq"] for ev in events if ev["k"] == "coll"]
    assert max(seqs) == 20


def test_digest_history_bounded_and_published_compact(tmp_path):
    rec, clk = make_recorder(tmp_path, capacity=16)
    drive_schedule(rec, clk, [("allreduce", (4,))] * 300)
    d = rec.digest()
    assert d["seq"] == 300
    assert len(d["hist"]) <= recorder_mod.DIGEST_PUBLISH
    # history pairs are (seq, hash) with the newest last
    assert d["hist"][-1][0] == 300


# ---- dumps ---------------------------------------------------------------

def test_dump_idempotent_under_double_signal(tmp_path):
    """Two dump triggers racing (launcher SIGTERM + middleman SIGTERM is
    the common double) must both leave a complete, parseable file, with
    the reason history accumulating."""
    rec, clk = make_recorder(tmp_path, rank=3, size=4)
    drive_schedule(rec, clk, [("allreduce", (8,))] * 3, complete=False)
    p1 = rec.dump(reason="signal:15")
    with open(p1) as f:
        first = json.load(f)
    p2 = rec.dump(reason="signal:15")
    assert p1 == p2 == rec.dump_path()
    with open(p2) as f:
        second = json.load(f)
    assert first["rank"] == second["rank"] == 3
    assert second["dump_reasons"] == ["signal:15", "signal:15"]
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    # re-entrant call while the lock is held is skipped, never torn
    rec._dump_lock.acquire()
    try:
        assert rec.dump(reason="signal:15") is None
    finally:
        rec._dump_lock.release()


def test_dump_survives_mid_run_and_final(tmp_path):
    """A stall-triggered dump followed by a crash dump: the final file
    wins and holds the full reason history (the doctor reads one file
    per rank)."""
    rec, clk = make_recorder(tmp_path)
    drive_schedule(rec, clk, [("allreduce", (4,))] * 2)
    rec.dump(reason="stall")
    drive_schedule(rec, clk, [("allgather", (2,))], complete=False)
    rec.dump(reason="exception")
    with open(rec.dump_path()) as f:
        d = json.load(f)
    assert d["dump_reasons"] == ["stall", "exception"]
    assert d["open_collectives"] == {"3": "allgather"}


# ---- desync digests ------------------------------------------------------

def test_desync_divergence_names_minority_rank(tmp_path):
    shared = [("allreduce", (4,)), ("allgather", (2,)), ("allreduce", (4,))]
    recs = {}
    for r in range(3):
        rec, clk = make_recorder(tmp_path, rank=r, size=3)
        drive_schedule(rec, clk, shared)
        recs[r] = (rec, clk)
    # rank 1 diverges (different op at seq 4); 0 and 2 stay in lockstep
    drive_schedule(*recs[0], [("allreduce", (8,))])
    drive_schedule(*recs[1], [("broadcast", (8,))])
    drive_schedule(*recs[2], [("allreduce", (8,))])
    check = desync.cross_check({r: rec.digest()
                                for r, (rec, _c) in recs.items()})
    assert check["desynced"] == [1]
    assert check["last_common_seq"] == 4
    assert "diverged at seq 4" in check["detail"]


def test_desync_same_schedule_is_clean(tmp_path):
    ops = [("allreduce", (4,)), ("reducescatter", (8,))]
    digests = {}
    for r in range(2):
        rec, clk = make_recorder(tmp_path, rank=r)
        drive_schedule(rec, clk, ops)
        digests[r] = rec.digest()
    check = desync.cross_check(digests)
    assert check["desynced"] == []
    assert check["last_common_seq"] == 2


def test_ragged_allgather_does_not_fork_digest(tmp_path):
    """Eager allgather carries allgatherv semantics: per-rank first dims
    may legitimately differ, so the shape must stay out of the schedule
    digest — a ragged (but correct) allgather is NOT a desync."""
    digests = {}
    for r, rows in ((0, 3), (1, 5)):
        rec, clk = make_recorder(tmp_path, rank=r)
        s = rec.collective_enter("allgather", shape=(rows, 2),
                                 dtype="float32", hash_shape=False)
        rec.collective_exit("allgather", s)
        s = rec.collective_enter("allreduce", shape=(4,), dtype="float32")
        rec.collective_exit("allreduce", s)
        digests[r] = rec.digest()
    check = desync.cross_check(digests)
    assert check["desynced"] == []
    assert check["last_common_seq"] == 2


def test_desync_stuck_rank_detection(tmp_path):
    recs = {}
    for r in range(2):
        rec, clk = make_recorder(tmp_path, rank=r)
        drive_schedule(rec, clk, [("allreduce", (4,))] * 3)
        recs[r] = (rec, clk)
    prev = {r: rec.digest() for r, (rec, _c) in recs.items()}
    drive_schedule(*recs[0], [("allreduce", (4,))] * 2)  # rank 1 frozen
    now = {r: rec.digest() for r, (rec, _c) in recs.items()}
    check = desync.cross_check(now, prev=prev)
    assert check["stuck"] == [1]
    assert check["desynced"] == []  # same schedule, just not advancing


# ---- doctor --------------------------------------------------------------

def _dump_ranks(tmp_path, specs):
    """specs: {rank: fn(rec, clk)} -> dumps loaded back from disk."""
    for r, fn in specs.items():
        rec, clk = make_recorder(tmp_path, rank=r,
                                 size=max(specs) + 1, t0=50.0 + r)
        fn(rec, clk)
    dumps, skipped = doctor.load_dumps(str(tmp_path))
    assert skipped == []
    return dumps


def test_doctor_dead_rank_report(tmp_path):
    """The acceptance shape: rank 1 of 3 hard-killed, survivors parked —
    the report names the dead rank, the last common seq and the parked
    collective, and classifies 'dead rank'."""
    def survivor(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,))] * 4)
        drive_schedule(rec, clk, [("allreduce", (4,))], complete=False)
        rec.dump(reason="signal:15")

    dumps = _dump_ranks(tmp_path, {0: survivor, 2: survivor})
    report = doctor.diagnose(dumps, expected_size=3)
    assert report["dead_ranks"] == [1]
    assert report["classification"] == "dead rank"
    assert report["last_common_seq"] == 4
    assert report["per_rank"][0]["parked"] == (5, "allreduce")
    text = doctor.format_report(report)
    assert "DEAD (no flight-recorder dump): rank(s) 1" in text
    assert "last common collective_seq: 4" in text
    assert "PARKED in allreduce (seq 5)" in text
    assert "probable cause: dead rank" in text


def test_doctor_desync_classification(tmp_path):
    def majority(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,)), ("allreduce", (8,))])
        rec.dump(reason="stall")

    def minority(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,)), ("allgather", (8,))])
        rec.dump(reason="stall")

    dumps = _dump_ranks(tmp_path, {0: majority, 1: minority, 2: majority})
    report = doctor.diagnose(dumps)
    assert report["classification"] == "desync"
    assert report["desync"]["desynced"] == [1]


def test_doctor_data_stall_classification(tmp_path):
    def parked(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,))] * 3)
        drive_schedule(rec, clk, [("allreduce", (4,))], complete=False)
        rec.dump(reason="stall")

    def starved(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,))] * 3)
        rec.step_begin(3)
        rec.step_end(3)  # finished its step, never fed the next one
        rec.dump(reason="stall")

    dumps = _dump_ranks(tmp_path, {0: parked, 1: starved})
    report = doctor.diagnose(dumps)
    assert report["classification"] == "data stall"
    assert "1" in report["explanation"]


def test_doctor_compile_stall_classification(tmp_path):
    def parked(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,))] * 3)
        drive_schedule(rec, clk, [("allreduce", (4,))], complete=False)
        rec.dump(reason="stall")

    def compiling(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,))] * 3)
        rec.step_begin(7)  # entered the step, no collective since
        rec.dump(reason="stall")

    dumps = _dump_ranks(tmp_path, {0: parked, 1: compiling})
    report = doctor.diagnose(dumps)
    assert report["classification"] == "compile stall"


def test_doctor_graceful_eviction_classification(tmp_path):
    """A preempted rank's eviction dump must classify as a planned
    drain — never as a dead/hung rank — even while a bystander rank
    sits parked in a collective waiting for the next rendezvous."""
    def evicted(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,))] * 4)
        rec.record("preempt", kind="sigterm", signum=15, host="spot-a",
                   grace=5.0)
        rec.record("preempt", kind="sigterm", outcome="committed",
                   announced=True, commit_seconds=0.4)
        rec.dump(reason="preempt")

    def bystander(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,))] * 4)
        drive_schedule(rec, clk, [("allreduce", (4,))], complete=False)
        rec.dump(reason="stall")

    dumps = _dump_ranks(tmp_path, {0: evicted, 1: bystander})
    report = doctor.diagnose(dumps)
    assert report["classification"] == "graceful eviction"
    assert report["evicted_ranks"] == [0]
    assert report["per_rank"][0]["evicted"]
    assert report["per_rank"][0]["preempt"]["outcome"] == "committed"
    assert not report["per_rank"][1]["evicted"]
    text = doctor.format_report(report)
    assert "EVICTED" in text
    assert "sigterm" in text
    assert "probable cause: graceful eviction" in text


def test_doctor_healthy_classification(tmp_path):
    def clean(rec, clk):
        drive_schedule(rec, clk, [("allreduce", (4,))] * 2)
        rec.dump(reason="exit")

    dumps = _dump_ranks(tmp_path, {0: clean, 1: clean})
    report = doctor.diagnose(dumps)
    assert report["classification"] == "healthy"
    assert report["dead_ranks"] == []


def test_doctor_config_mismatch_flagged(tmp_path):
    from horovod_tpu.config import Config

    def with_cfg(threshold):
        def fn(rec, clk):
            cfg = Config(rank=rec.rank, size=2,
                         fusion_threshold=threshold)
            rec.config_snapshot = {"fusion_threshold": threshold}
            rec.config_crc = recorder_mod.config_fingerprint(cfg)
            drive_schedule(rec, clk, [("allreduce", (4,))])
            rec.dump(reason="exit")
        return fn

    dumps = _dump_ranks(tmp_path, {0: with_cfg(1 << 20),
                                   1: with_cfg(64 << 20)})
    report = doctor.diagnose(dumps)
    assert report["config_mismatch"] is not None
    assert "CONFIG MISMATCH" in doctor.format_report(report)


def test_doctor_cli_module(tmp_path, capsys):
    rec, clk = make_recorder(tmp_path, rank=0, size=1)
    drive_schedule(rec, clk, [("allreduce", (4,))])
    rec.dump(reason="exit")
    assert doctor.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "doctor report" in out
    assert doctor.main([str(tmp_path / "empty_nothing_here")]) == 2


def test_config_fingerprint_ignores_per_rank_identity():
    from horovod_tpu.config import Config
    a = recorder_mod.config_fingerprint(Config(rank=0, local_rank=0,
                                               metrics_port=9090))
    b = recorder_mod.config_fingerprint(Config(rank=3, local_rank=1,
                                               metrics_port=9093))
    assert a == b
    c = recorder_mod.config_fingerprint(Config(rank=0,
                                               fusion_threshold=1 << 20))
    assert a != c


# ---- install / uninstall -------------------------------------------------

def test_install_uninstall_restores_hooks(tmp_path):
    prev_excepthook = sys.excepthook
    prev_term = signal.getsignal(signal.SIGTERM)
    rec = recorder_mod.install(capacity=32, dump_dir=str(tmp_path),
                               rank=0, size=1)
    try:
        assert recorder_mod.get_recorder() is rec
        assert recorder_mod.install() is rec  # idempotent
        assert sys.excepthook is not prev_excepthook
        assert signal.getsignal(signal.SIGTERM) is not prev_term
        seq = recorder_mod.collective_enter("allreduce",
                                            np.ones((4,), np.float32))
        assert seq == 1
        recorder_mod.collective_exit("allreduce", seq)
        assert recorder_mod.dump_now("on_demand") == rec.dump_path()
    finally:
        recorder_mod.uninstall(dump=False)
    assert recorder_mod.get_recorder() is None
    assert sys.excepthook is prev_excepthook
    assert signal.getsignal(signal.SIGTERM) == prev_term
    # module-level hooks are no-ops again
    assert recorder_mod.collective_enter("allreduce", None) == 0
    assert recorder_mod.dump_now() is None


# ---- /flightrec endpoint -------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def test_flightrec_endpoint(tmp_path, monkeypatch):
    from horovod_tpu.telemetry import MetricsServer

    srv = MetricsServer(port=0)
    port = srv.start()
    try:
        # no recorder installed -> 404 with a hint
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(port, "/flightrec")
        assert exc.value.code == 404

        rec, clk = make_recorder(tmp_path, rank=5, size=8)
        drive_schedule(rec, clk, [("allreduce", (16,))] * 2)
        monkeypatch.setattr(recorder_mod, "_recorder", rec)
        status, body = _get(port, "/flightrec")
        assert status == 200
        snap = json.loads(body)
        assert snap["rank"] == 5 and snap["collective_seq"] == 2
        assert not os.path.exists(rec.dump_path())  # plain GET: no disk
        status, _ = _get(port, "/flightrec?dump=1")
        assert status == 200
        assert os.path.exists(rec.dump_path())  # ?dump=1 = on-demand dump
    finally:
        srv.stop()


# ---- byte-identical compiled programs ------------------------------------

def test_compiled_step_byte_identical_with_and_without_recorder(
        hvd, tmp_path, monkeypatch):
    """The acceptance bar: the recorder must never shape the traced
    computation — the lowered train step with a recorder installed is
    byte-identical to the uninstrumented one (HOROVOD_FLIGHTREC=0)."""
    def lower_text():
        model = MLP(features=(8, 2))
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.1))
        state = training.create_train_state(
            model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 4)))
        step = training.make_train_step(model, tx, donate=False,
                                        telemetry=False)
        x = jnp.zeros((8, 4), jnp.float32)
        y = jnp.zeros((8,), jnp.int32)
        return step.lower(state, x, y).as_text()

    baseline = lower_text()
    rec, _clk = make_recorder(tmp_path)
    monkeypatch.setattr(recorder_mod, "_recorder", rec)
    with_recorder = lower_text()
    assert with_recorder == baseline
    # and the recorder actually saw the trace-time dispatches
    assert rec.collective_seq > 0


# ---- 2-rank CPU round-trip smoke (satellite: CI/tooling) -----------------

def test_two_rank_roundtrip_recorder_dump_doctor(tmp_path):
    """Recorder -> dump -> doctor on a real 2-rank CPU run: a healthy
    job leaves per-rank dumps whose doctor report classifies 'healthy'
    (flight recording auto-enables for multi-process jobs)."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        for _ in range(3):
            hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum)
        hvd.shutdown()
    """))
    out_dir = tmp_path / "out"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rv = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--output-dir", str(out_dir), sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert rv.returncode == 0, rv.stdout + rv.stderr
    dumps, skipped = doctor.load_dumps(str(out_dir))
    assert skipped == []
    assert sorted(dumps) == [0, 1]
    report = doctor.diagnose(dumps)
    assert report["classification"] == "healthy"
    assert report["per_rank"][0]["seq"] >= 3
    # both ranks dispatched the same schedule: no desync, no stragglers
    assert report["desync"]["desynced"] == []
    assert report["last_common_seq"] >= 3


# ---- signal-dump vs watcher race (ISSUE 7 satellite fix) -----------------

def test_wait_for_dump_blocks_until_inflight_dump_finishes(tmp_path):
    """``wait_for_dump`` must not return while another thread holds the
    dump lock — the main-thread signal handler calls it before
    re-raising a fatal signal, so the watcher's racing dump can finish
    instead of being torn mid-write."""
    import threading
    import time as _time

    rec = FlightRecorder(capacity=8, rank=0, size=1,
                         dump_dir=str(tmp_path))
    assert rec._dump_lock.acquire(blocking=False)  # "watcher mid-dump"
    released = []

    def release_later():
        _time.sleep(0.2)
        released.append(True)
        rec._dump_lock.release()

    threading.Thread(target=release_later, daemon=True).start()
    t0 = _time.perf_counter()
    rec.wait_for_dump(timeout=5.0)
    assert _time.perf_counter() - t0 >= 0.15
    assert released  # we really waited for the holder, not a timeout


def test_sigterm_in_interruptible_wait_still_dumps(tmp_path):
    """Regression: SIGTERM landing while the main thread sits in an
    interruptible Python wait (e.g. blocked on a starved data loader)
    fires BOTH dump paths — the main-thread handler and the wakeup-fd
    watcher. The handler used to skip (lock held) and immediately
    re-raise the fatal default, SIGTERM-killing the watcher mid-write:
    exit 143 and NO dump at all. The handler now waits for the racing
    dump to finish first."""
    script = tmp_path / "sleeper.py"
    script.write_text(textwrap.dedent("""
        import os, signal, threading, time
        from horovod_tpu.diag import recorder
        recorder.install(dump_dir=os.environ["DUMP_DIR"], rank=0, size=1)
        threading.Timer(0.5, lambda: os.kill(
            os.getpid(), signal.SIGTERM)).start()
        time.sleep(30)
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DUMP_DIR"] = str(tmp_path)
    rv = subprocess.run([sys.executable, str(script)], env=env,
                        capture_output=True, text=True, timeout=60)
    # the signal's intent is honored: death by SIGTERM...
    assert rv.returncode in (-signal.SIGTERM, 128 + signal.SIGTERM)
    # ...but the black box exists and names the signal
    path = tmp_path / "flightrec.rank0.json"
    assert path.is_file(), rv.stderr
    with open(path) as f:
        dump = json.load(f)
    assert any(r.startswith("signal:15") for r in dump["dump_reasons"])
