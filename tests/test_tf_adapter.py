"""TF adapter tests (reference: test/test_tensorflow.py +
test_tensorflow_keras.py — op correctness, IndexedSlices fallback,
DistributedOptimizer compute_gradients averaging, tape wrapping,
load_model optimizer re-wrap). tensorflow is not baked into this image,
so the adapter runs against the numpy-backed stand-in in
``fake_tensorflow.py``; the adapter code paths are identical either way
(tensors bridge through ``.numpy()``/``convert_to_tensor``).
Multi-process cases ride api.run."""

import os

import numpy as np
import pytest

import fake_tensorflow

from horovod_tpu.run import api

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture()
def hvd_tf(hvd):
    fake_tensorflow.install()
    import horovod_tpu.tensorflow as hvd_t
    yield hvd_t
    from horovod_tpu import _core
    _core.shutdown()


@pytest.fixture()
def tf():
    return fake_tensorflow.install()


def _tf_env():
    """Workers must import the fake before horovod_tpu.tensorflow —
    passed via extra_env, never by mutating this process's environ."""
    existing = os.environ.get("PYTHONPATH", "")
    return {"JAX_PLATFORMS": "cpu",
            "PYTHONPATH": os.pathsep.join(
                [p for p in [TESTS_DIR, existing] if p])}


# ---- single-process semantics ------------------------------------------

def test_single_process_ops(hvd_tf, tf):
    x = tf.convert_to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_array_equal(hvd_tf.allreduce(x).numpy(), x.numpy())
    np.testing.assert_array_equal(hvd_tf.allgather(x).numpy(), x.numpy())
    np.testing.assert_array_equal(
        hvd_tf.broadcast(x, root_rank=0).numpy(), x.numpy())


def test_fp16_compression_roundtrip(hvd_tf, tf):
    x = tf.convert_to_tensor(np.linspace(0, 1, 8, dtype=np.float32))
    out = hvd_tf.allreduce(x, compression=hvd_tf.Compression.fp16)
    assert out.numpy().dtype == np.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-3)


def test_broadcast_variables_assigns(hvd_tf, tf):
    v = tf.Variable(np.full(3, 7.0, dtype=np.float32))
    hvd_tf.broadcast_variables([v], root_rank=0)  # size 1: identity
    np.testing.assert_array_equal(v.numpy(), np.full(3, 7.0))


def test_indexed_slices_single(hvd_tf, tf):
    s = tf.IndexedSlices(np.ones((2, 4), np.float32),
                         np.array([1, 3]), dense_shape=(5, 4))
    out = hvd_tf.allreduce(s, op=hvd_tf.Average)
    assert isinstance(out, tf.IndexedSlices)
    np.testing.assert_array_equal(out.indices.numpy(), [1, 3])
    np.testing.assert_allclose(out.values.numpy(), np.ones((2, 4)))


def test_sparse_adasum_rejected(hvd_tf, tf):
    s = tf.IndexedSlices(np.ones((1, 2), np.float32), np.array([0]),
                         dense_shape=(2, 2))
    with pytest.raises(NotImplementedError, match="sparse_as_dense"):
        hvd_tf.allreduce(s, op=hvd_tf.Adasum)


def test_tape_and_optimizer_delegate(hvd_tf, tf):
    v = tf.Variable(np.ones(2, np.float32))
    tape = tf.GradientTape(grads=[tf.convert_to_tensor(
        np.full(2, 4.0, np.float32))])
    dt = hvd_tf.DistributedGradientTape(tape)
    with dt:
        pass
    (g,) = dt.gradient(None, [v])  # size 1: passthrough
    np.testing.assert_array_equal(np.asarray(g), np.full(2, 4.0))

    inner = tf.train.Optimizer(lr=0.5)
    inner._test_grads = [tf.convert_to_tensor(np.full(2, 2.0, np.float32))]
    opt = hvd_tf.DistributedOptimizer(inner)
    opt.minimize(None, var_list=[v])
    np.testing.assert_allclose(v.numpy(), np.zeros(2))  # 1 - 0.5*2
    assert opt.get_slot_names() == []
    assert opt.get_config() == {"lr": 0.5}


def test_keras_load_model_rewraps(hvd_tf, tf, tmp_path):
    import horovod_tpu.tensorflow.keras as hvd_keras
    model = tf.keras.Model({"w": np.ones(3, np.float32)},
                           tf.keras.optimizers.SGD(lr=0.25))
    path = str(tmp_path / "model.bin")
    tf.keras.models.save_model(model, path)

    loaded = hvd_keras.load_model(path)
    # optimizer came back wrapped, with its config preserved
    assert type(loaded.optimizer).__name__ == "DistributedSGD"
    assert loaded.optimizer.get_config() == {"lr": 0.25}
    np.testing.assert_array_equal(loaded.weights["w"], np.ones(3))

    # and a re-save of the wrapped model round-trips (uses _hvd_wrapped)
    tf.keras.models.save_model(loaded, path)
    again = hvd_keras.load_model(path)
    assert type(again.optimizer).__name__ == "DistributedSGD"


# ---- multi-process end-to-end ------------------------------------------

def test_tf_optimizer_averages_across_ranks():
    def fn():
        import numpy as np

        import fake_tensorflow
        tf = fake_tensorflow.install()
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        v = tf.Variable(np.ones(4, np.float32))
        inner = tf.train.Optimizer(lr=0.1)
        inner._test_grads = [tf.convert_to_tensor(
            np.full(4, hvd.rank() + 1.0, np.float32))]
        opt = hvd.DistributedOptimizer(inner)
        opt.minimize(None, var_list=[v])
        return v.numpy().tolist()

    results = api.run(fn, np=2, extra_env=_tf_env())
    # mean grad = 1.5 -> w = 1 - 0.1*1.5 everywhere
    for r in results:
        np.testing.assert_allclose(r, np.full(4, 0.85), rtol=1e-6)


def test_tf_indexed_slices_allgather_across_ranks():
    def fn():
        import numpy as np

        import fake_tensorflow
        tf = fake_tensorflow.install()
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r = hvd.rank()
        # rank r contributes row index r with value (r+1)
        s = tf.IndexedSlices(np.full((1, 2), r + 1.0, np.float32),
                             np.array([r]), dense_shape=(4, 2))
        out = hvd.allreduce(s, op=hvd.Average)
        return (out.values.numpy().tolist(), out.indices.numpy().tolist())

    results = api.run(fn, np=2, extra_env=_tf_env())
    for values, indices in results:
        assert indices == [0, 1]
        np.testing.assert_allclose(values, [[0.5, 0.5], [1.0, 1.0]])


def test_tf_sparse_as_dense_optimizer():
    def fn():
        import numpy as np

        import fake_tensorflow
        tf = fake_tensorflow.install()
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r = hvd.rank()
        v = tf.Variable(np.zeros((2, 2), np.float32))
        inner = tf.train.Optimizer(lr=1.0)
        inner._test_grads = [tf.IndexedSlices(
            np.full((1, 2), r + 1.0, np.float32), np.array([r]),
            dense_shape=(2, 2))]
        opt = hvd.DistributedOptimizer(inner, sparse_as_dense=True)
        opt.minimize(None, var_list=[v])
        return v.numpy().tolist()

    results = api.run(fn, np=2, extra_env=_tf_env())
    # dense grads: rank0 puts 1s in row 0, rank1 puts 2s in row 1;
    # average -> [[.5,.5],[1,1]]; v = 0 - grad
    for r in results:
        np.testing.assert_allclose(r, [[-0.5, -0.5], [-1.0, -1.0]])


def test_tf_broadcast_variables_across_ranks():
    def fn():
        import numpy as np

        import fake_tensorflow
        tf = fake_tensorflow.install()
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        v = tf.Variable(np.full(3, float(hvd.rank() + 1), np.float32))
        hvd.broadcast_variables([v], root_rank=0)
        return v.numpy().tolist()

    results = api.run(fn, np=2, extra_env=_tf_env())
    for r in results:
        np.testing.assert_allclose(r, np.ones(3))


def test_minimize_passes_global_step(hvd_tf, tf):
    v = tf.Variable(np.ones(2, np.float32))
    step = tf.Variable(np.asarray(0, np.int64))
    inner = tf.train.Optimizer(lr=1.0)
    inner._test_grads = [tf.convert_to_tensor(np.ones(2, np.float32))]
    opt = hvd_tf.DistributedOptimizer(inner)
    opt.minimize(None, global_step=step, var_list=[v])
    assert int(step.numpy()) == 1
    np.testing.assert_allclose(v.numpy(), np.zeros(2))


def test_empty_var_list_ok(hvd_tf, tf):
    inner = tf.train.Optimizer(lr=1.0)
    inner._test_grads = []
    opt = hvd_tf.DistributedOptimizer(inner)
    assert opt.compute_gradients(None, var_list=[]) == []


def test_broadcast_global_variables_raises_without_collections(hvd_tf):
    with pytest.raises(NotImplementedError, match="model.variables"):
        hvd_tf.broadcast_global_variables(0)


# ---- keras callbacks against the fake (real-TF runs: test_tf_real) ----

class _Model:
    """Minimal model stub: the callbacks only touch .variables and
    .optimizer."""

    def __init__(self, optimizer, variables=()):
        self.optimizer = optimizer
        self.variables = list(variables)


def test_broadcast_callback_fires_once(hvd_tf, tf):
    from horovod_tpu.tensorflow import callbacks as cb
    v = tf.Variable(np.full(2, 3.0, np.float32))
    inner = tf.train.Optimizer(lr=0.2)
    c = cb.BroadcastGlobalVariablesCallback(0)
    c.set_model(_Model(inner, [v]))
    c.on_train_batch_end(0)  # size 1: broadcast is identity
    assert c.broadcast_done
    np.testing.assert_array_equal(v.numpy(), np.full(2, 3.0))
    c.on_train_batch_end(1)  # second call is a no-op


def test_metric_average_callback_inplace(hvd_tf, tf):
    from horovod_tpu.tensorflow import callbacks as cb
    c = cb.MetricAverageCallback()
    logs = {"loss": 2.0, "acc": 0.5, "name": "not-a-number"}
    c.on_epoch_end(0, logs)
    assert logs["loss"] == 2.0 and logs["acc"] == 0.5  # size 1 identity
    assert isinstance(logs["loss"], float)
    assert logs["name"] == "not-a-number"


def test_lr_schedule_staircase_and_momentum_correction(hvd_tf, tf):
    from horovod_tpu.tensorflow import callbacks as cb
    inner = tf.train.Optimizer(lr=0.2)
    # variable-backed momentum: assignment is visible to a compiled
    # train step, so the callback applies the correction
    inner.momentum = tf.Variable(np.float64(0.9))
    c = cb.LearningRateScheduleCallback(0.5)
    c.set_model(_Model(inner))
    c.on_train_begin()
    c.on_epoch_begin(0)
    c.on_batch_begin(0)
    assert abs(inner.lr - 0.1) < 1e-9
    # momentum scaled by new_lr/old_lr while the batch runs...
    assert abs(float(np.asarray(inner.momentum)) - 0.45) < 1e-9
    c.on_batch_end(0)  # ...and restored afterwards
    assert abs(float(np.asarray(inner.momentum)) - 0.9) < 1e-9
    logs = {}
    c.on_epoch_end(0, logs)
    assert abs(logs["lr"] - 0.1) < 1e-9


def test_lr_schedule_skips_float_momentum_with_warning(hvd_tf, tf):
    """Keras-3-style plain-float momentum is baked into the traced step,
    so the callback must refuse to scale it (and say so once)."""
    import warnings
    from horovod_tpu.tensorflow import callbacks as cb
    inner = tf.train.Optimizer(lr=0.2)
    inner.momentum = 0.9
    c = cb.LearningRateScheduleCallback(0.5)
    c.set_model(_Model(inner))
    c.on_train_begin()
    c.on_epoch_begin(0)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        c.on_batch_begin(0)
        c.on_batch_end(0)
        c.on_batch_begin(1)  # warning fires only once
    assert abs(inner.lr - 0.1) < 1e-9  # lr still adjusted
    assert inner.momentum == 0.9      # momentum untouched
    assert sum("momentum_correction skipped" in str(w.message)
               for w in caught) == 1


def test_lr_schedule_respects_epoch_window(hvd_tf, tf):
    from horovod_tpu.tensorflow import callbacks as cb
    inner = tf.train.Optimizer(lr=0.2)
    c = cb.LearningRateScheduleCallback(
        lambda epoch: 0.5 ** epoch, start_epoch=1, end_epoch=2)
    c.set_model(_Model(inner))
    c.on_train_begin()
    c.on_epoch_begin(0)
    c.on_batch_begin(0)
    assert abs(inner.lr - 0.2) < 1e-9  # before start_epoch: untouched
    c.on_epoch_begin(1)
    c.on_batch_begin(0)
    assert abs(inner.lr - 0.1) < 1e-9  # inside the window
    c.on_epoch_begin(2)
    c.on_batch_begin(0)
    assert abs(inner.lr - 0.1) < 1e-9  # past end_epoch: frozen


def test_lr_warmup_ramps_to_initial(hvd_tf, tf):
    from horovod_tpu.tensorflow import callbacks as cb
    inner = tf.train.Optimizer(lr=0.2)
    c = cb.LearningRateWarmupCallback(warmup_epochs=2, steps_per_epoch=4)
    c.set_model(_Model(inner))
    c.on_train_begin()
    # size()==1: the ramp multiplier is identically 1.0 at every batch
    for epoch in range(2):
        c.on_epoch_begin(epoch)
        for b in range(4):
            c.on_batch_begin(b)
            assert abs(inner.lr - 0.2) < 1e-9
            c.on_batch_end(b)
