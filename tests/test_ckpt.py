"""Async sharded checkpointing (ISSUE 5): N→M reshard-on-load parity
against an unsharded oracle, two-phase manifest torn-write recovery,
and the headline claim — the training-thread stall of an async save is
a small fraction of the synchronous write (asserted through the
``hvd_ckpt_blocking_seconds`` metric, incl. a real 2-rank run with
``checkpoint_every``). See docs/CHECKPOINT.md for the protocol."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import ckpt as ckpt_lib
from horovod_tpu.ckpt import manifest as manifest_lib
from horovod_tpu.ckpt import sharded as sharded_lib
from horovod_tpu.ops import fusion
from horovod_tpu.parallel import zero
from horovod_tpu.run import api

THRESHOLD = 64  # bytes — small, so the tiny test params span 3 buckets


def _params():
    rng = np.random.default_rng(7)
    return {"w1": jnp.asarray(rng.standard_normal(7), jnp.float32),
            "w2": jnp.asarray(rng.standard_normal((3, 5)), jnp.float32),
            "w3": jnp.asarray(rng.standard_normal(9), jnp.float32)}


def _rows_state(tx, params, grads, world, steps=3, threshold=THRESHOLD):
    """A ZeroState for ``world`` after ``steps`` elementwise updates on
    the bucket-row view — built WITHOUT a mesh (the schedule is a pure
    function of leaves/threshold/world), so one process can play any
    rank of any world size."""
    leaves = jax.tree_util.tree_leaves(params)
    sched = fusion.bucket_schedule(leaves, world, threshold_bytes=threshold,
                                   axes=("data",))
    plan = zero.ZeroPlan(schedule=sched)
    zstate = zero.init(tx, params, plan)
    gl = jax.tree_util.tree_leaves(grads)
    grad_rows = {f"b{i}": zero._bucket_rows(sched, i, gl)
                 for i in range(len(sched.buckets))}
    param_rows = {f"b{i}": zero._bucket_rows(sched, i, leaves)
                  for i in range(len(sched.buckets))}
    inner = zstate.inner
    for _ in range(steps):
        _, inner = tx.update(grad_rows, inner, param_rows)
    return zero.ZeroState(inner, plan), sched


def _save_world(root, step, tree, world, meta=None):
    """Play all ``world`` ranks of one save in-process: every rank's
    shard + phase-1 ack, then the two-phase commit."""
    zi = None
    for r in range(world):
        payload, zi = ckpt_lib.snapshot_tree(tree, r, world)
        sharded_lib.write_shard(root, step, payload)
    return manifest_lib.commit(root, step, 0, world, meta=meta,
                               zero_info=zi, keep=None)


# ---- N→M resharded restore --------------------------------------------


@pytest.mark.parametrize("m", [2, 3, 1])
def test_reshard_restore_bitwise_parity(tmp_path, m):
    """Save at world=4, restore at world=m: every bucket's USED prefix
    of the optimizer state (adam mu/nu) must be BITWISE equal to the
    packed unsharded oracle — the same optax state computed with no
    sharding at all — and replicated leaves must round-trip exactly."""
    params = _params()
    grads = jax.tree_util.tree_map(lambda x: jnp.ones_like(x) * 0.37,
                                   params)
    tx = optax.adam(1e-2)

    z4, _ = _rows_state(tx, params, grads, world=4)
    _save_world(str(tmp_path), 10, {"params": params, "opt": z4}, 4,
                meta={"commit": 10})

    # unsharded oracle: plain adam over the full tree, same 3 updates
    full = tx.init(params)
    for _ in range(3):
        _, full = tx.update(grads, full, params)
    mu_leaves = jax.tree_util.tree_leaves(full[0].mu)
    nu_leaves = jax.tree_util.tree_leaves(full[0].nu)

    zm, sched_m = _rows_state(tx, params, grads, world=m, steps=0)
    target = {"params": jax.tree_util.tree_map(jnp.zeros_like, params),
              "opt": zm}
    step, restored, meta = ckpt_lib.restore_sharded(str(tmp_path), target)
    assert step == 10 and meta == {"commit": 10}

    inner = restored["opt"].inner
    assert int(np.asarray(inner[0].count)) == 3
    for i, bucket in enumerate(sched_m.buckets):
        used = int(sum(bucket.sizes))
        for got_rows, oracle in ((inner[0].mu, mu_leaves),
                                 (inner[0].nu, nu_leaves)):
            got = np.asarray(got_rows[f"b{i}"])
            assert got.shape == (m, sched_m.shard_sizes[i])
            np.testing.assert_array_equal(
                got.reshape(-1)[:used],
                np.asarray(fusion._pack(bucket, oracle))[:used])
            # padding beyond the used prefix is zeros, never garbage
            np.testing.assert_array_equal(got.reshape(-1)[used:], 0.0)
    for k, v in params.items():
        np.testing.assert_array_equal(np.asarray(restored["params"][k]),
                                      np.asarray(v))


@pytest.mark.parametrize("m", [3, 2])
def test_gspmd_sharded_state_saved_at_one_process_reshards_bitwise(
        tmp_path, m):
    """The GSPMD hot path (parallel/gspmd.py): ONE process drives the
    whole mesh, so its ZeroState rows are a single ``[world, shard]``
    NamedSharding array and the process owns EVERY row. A save with
    rank=0, world=1 must persist all of them (not just row 0 — the
    pre-GSPMD assumption), and restore at a different world M must stay
    bitwise — the same reshard oracle the explicit path pins."""
    import horovod_tpu as hvd_mod

    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        from horovod_tpu.parallel import gspmd
        mesh = hvd_mod.mesh()
        world = len(jax.devices())
        params = _params()
        grads = jax.tree_util.tree_map(lambda x: jnp.ones_like(x) * 0.37,
                                       params)
        tx = optax.adam(1e-2)
        z8, sched8 = _rows_state(tx, params, grads, world=world)
        # place the rows on the mesh exactly as the spmd step does:
        # P('data') over dim 0, one row per device
        plan = gspmd.derive_plan(mesh)
        z8 = gspmd.place_state(plan, z8)
        row0 = jax.tree_util.tree_leaves(z8.inner)[1]  # mu b0
        assert {s.data.shape[0] for s in row0.addressable_shards} == {1}

        _save_world(str(tmp_path), 4, {"opt": z8}, 1)  # ONE process

        full = tx.init(params)
        for _ in range(3):
            _, full = tx.update(grads, full, params)
        mu_leaves = jax.tree_util.tree_leaves(full[0].mu)
        nu_leaves = jax.tree_util.tree_leaves(full[0].nu)

        zm, sched_m = _rows_state(tx, params, grads, world=m, steps=0)
        step, restored, _ = ckpt_lib.restore_sharded(
            str(tmp_path), {"opt": zm})
        assert step == 4
        inner = restored["opt"].inner
        assert int(np.asarray(inner[0].count)) == 3
        for i, bucket in enumerate(sched_m.buckets):
            used = int(sum(bucket.sizes))
            for got_rows, oracle in ((inner[0].mu, mu_leaves),
                                     (inner[0].nu, nu_leaves)):
                got = np.asarray(got_rows[f"b{i}"])
                assert got.shape == (m, sched_m.shard_sizes[i])
                np.testing.assert_array_equal(
                    got.reshape(-1)[:used],
                    np.asarray(fusion._pack(bucket, oracle))[:used])
                np.testing.assert_array_equal(got.reshape(-1)[used:], 0.0)
    finally:
        hvd_mod.shutdown()


def test_legacy_single_row_checkpoint_loads_into_gspmd_target(tmp_path):
    """A checkpoint written by the pre-GSPMD layout (one UNKEYED row per
    rank shard) restores into a GSPMD-worldsize target bitwise, and the
    restored tree places cleanly onto the plan's NamedShardings — the
    explicit-layout -> GSPMD migration path."""
    import horovod_tpu as hvd_mod

    params = _params()
    grads = jax.tree_util.tree_map(lambda x: jnp.ones_like(x) * 0.5,
                                   params)
    tx = optax.adam(1e-2)
    z4, _ = _rows_state(tx, params, grads, world=4)

    # write world=4 shards in the LEGACY format: rows[key] = bare array
    zi = None
    for r in range(4):
        payload, zi = ckpt_lib.snapshot_tree({"opt": z4}, r, 4)
        for zslot in payload["zero"].values():
            zslot["rows"] = {
                key: rows[str(r)] for key, rows in zslot["rows"].items()}
        sharded_lib.write_shard(str(tmp_path), 2, payload)
    manifest_lib.commit(str(tmp_path), 2, 0, 4, zero_info=zi, keep=None)

    full = tx.init(params)
    for _ in range(3):
        _, full = tx.update(grads, full, params)
    mu_leaves = jax.tree_util.tree_leaves(full[0].mu)

    world = len(jax.devices())
    zt, sched_t = _rows_state(tx, params, grads, world=world, steps=0)
    step, restored, _ = ckpt_lib.restore_sharded(str(tmp_path), {"opt": zt})
    assert step == 2
    inner = restored["opt"].inner
    for i, bucket in enumerate(sched_t.buckets):
        used = int(sum(bucket.sizes))
        got = np.asarray(inner[0].mu[f"b{i}"])
        np.testing.assert_array_equal(
            got.reshape(-1)[:used],
            np.asarray(fusion._pack(bucket, mu_leaves))[:used])

    # the restored host tree must place onto the GSPMD plan's shardings
    import horovod_tpu as hvd_mod2
    hvd_mod2.shutdown()
    hvd_mod2.init()
    try:
        from horovod_tpu.parallel import gspmd
        plan = gspmd.derive_plan(hvd_mod2.mesh())
        placed = gspmd.place_state(plan, restored["opt"])
        leaf = placed.inner[0].mu["b0"]
        assert {s.data.shape[0] for s in leaf.addressable_shards} == {1}
    finally:
        hvd_mod2.shutdown()


def test_reshard_rejects_mismatched_bucket_layout(tmp_path):
    """A different fusion threshold partitions different buckets; the
    manifest's used_sizes must make that restore fail loudly instead of
    re-slicing garbage."""
    params = _params()
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    tx = optax.adam(1e-2)
    z4, _ = _rows_state(tx, params, grads, world=4)
    _save_world(str(tmp_path), 1, {"opt": z4}, 4)
    z2, _ = _rows_state(tx, params, grads, world=2, steps=0,
                        threshold=1 << 20)  # one big bucket
    with pytest.raises(ValueError, match="bucket layout"):
        ckpt_lib.restore_sharded(str(tmp_path), {"opt": z2})


def test_reshard_rejects_mismatched_replicated_leaf(tmp_path):
    """A replicated inner ZeroState leaf whose saved size differs from
    the restore target must fail loudly like every other mismatch, not
    silently install the wrong array."""
    params = _params()
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    tx = optax.adam(1e-2)
    z1, _ = _rows_state(tx, params, grads, world=1)
    _save_world(str(tmp_path), 1, {"opt": z1}, 1)
    ztarget, _ = _rows_state(tx, params, grads, world=1, steps=0)
    man = manifest_lib.read_manifest(str(tmp_path), 1)
    payload = sharded_lib._read_shard(str(tmp_path), 1, 0, 1, None)
    key = next(iter(payload["zero"]["0"]["repl"]))
    payload["zero"]["0"]["repl"][key] = np.zeros(17, np.float32)
    with pytest.raises(ValueError, match="restore target expects"):
        sharded_lib._assemble_zero(ztarget, 0, [payload], man["zero"][0])


# ---- two-phase manifest: torn writes, CRC, retention ------------------


def test_torn_write_recovery(tmp_path):
    """A checkpoint without a manifest never happened: the loader skips
    a newer manifest-less dir (crash mid-save) and restores the last
    complete step; asking for the torn step explicitly fails."""
    root = str(tmp_path)
    tree = {"w": np.arange(8, dtype=np.float32)}
    _save_world(root, 1, tree, 2, meta={"commit": 1})

    # simulate a crash mid-save of step 2: rank 0's shard landed, rank
    # 1's never did, and no MANIFEST was committed
    payload, _ = ckpt_lib.snapshot_tree({"w": tree["w"] * 2}, 0, 2)
    sharded_lib.write_shard(root, 2, payload)
    assert not manifest_lib.is_complete(root, 2)

    assert ckpt_lib.latest_complete_step(root) == 1
    step, restored, _ = ckpt_lib.restore_sharded(
        root, {"w": np.zeros(8, np.float32)})
    assert step == 1
    np.testing.assert_array_equal(restored["w"], tree["w"])
    with pytest.raises(FileNotFoundError, match="incomplete/torn"):
        ckpt_lib.restore_sharded(root, {"w": np.zeros(8, np.float32)},
                                 step=2)

    # GC: the torn dir is NEWER than the newest complete step — it may
    # be an in-flight save, so retention must leave it alone...
    assert ckpt_lib.retention_gc(root, keep=5) == []
    assert os.path.isdir(manifest_lib.step_dir(root, 2))
    # ...but once a newer step commits, the torn dir is dead debris
    _save_world(root, 3, tree, 2)
    assert 2 in ckpt_lib.retention_gc(root, keep=5)
    assert not os.path.isdir(manifest_lib.step_dir(root, 2))


def test_crc_detects_corrupt_shard(tmp_path):
    root = str(tmp_path)
    _save_world(root, 1, {"w": np.arange(64, dtype=np.float32)}, 2)
    path = sharded_lib.shard_path(root, 1, 1, 2)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    with pytest.raises(ckpt_lib.ShardValidationError, match="CRC32"):
        ckpt_lib.restore_sharded(root, {"w": np.zeros(64, np.float32)},
                                 step=1)
    # with no explicit step and nothing to fall back to, still an error
    with pytest.raises(ValueError, match="failed validation"):
        ckpt_lib.restore_sharded(root, {"w": np.zeros(64, np.float32)})


def test_retention_gc_keeps_newest_complete(tmp_path):
    root = str(tmp_path)
    tree = {"w": np.ones(4, np.float32)}
    for s in (1, 2, 3, 4):
        _save_world(root, s, tree, 1)
    ckpt_lib.retention_gc(root, keep=2)
    assert ckpt_lib.list_complete_steps(root) == [3, 4]


def test_retention_gc_spares_inflight_dirs_after_fallback(tmp_path):
    """After a fallback restore past a damaged newest step, resumed
    training re-uses LOWER step numbers: a manifest-less dir below the
    newest complete step whose mtime postdates that step's commit is an
    in-flight save and must survive GC; aged behind the commit time it
    is dead debris again."""
    root = str(tmp_path)
    tree = {"w": np.ones(4, np.float32)}
    _save_world(root, 50, tree, 1, meta={"commit": 50})
    # a peer is writing step 42 RIGHT NOW (post-fallback numbering)
    payload, _ = ckpt_lib.snapshot_tree(tree, 0, 2)
    sharded_lib.write_shard(root, 42, payload)
    assert ckpt_lib.retention_gc(root, keep=5) == []
    assert os.path.isdir(manifest_lib.step_dir(root, 42))
    # age the dir behind the newest commit: now it is a dead torn write
    t50 = float(manifest_lib.read_manifest(root, 50)["time"])
    os.utime(manifest_lib.step_dir(root, 42), (t50 - 10, t50 - 10))
    assert 42 in ckpt_lib.retention_gc(root, keep=5)
    assert not os.path.isdir(manifest_lib.step_dir(root, 42))


def test_stale_ack_cleared_on_resave(tmp_path):
    """Re-saving a torn step (restore + resume re-uses the step number)
    must not let a peer's barrier consume last incarnation's .ok."""
    root = str(tmp_path)
    payload, _ = ckpt_lib.snapshot_tree({"w": np.ones(4, np.float32)}, 0, 2)
    sharded_lib.write_shard(root, 1, payload)  # torn: ok exists, no manifest
    ok = os.path.join(manifest_lib.step_dir(root, 1),
                      manifest_lib.ok_name(0, 2))
    assert os.path.isfile(ok)
    manifest_lib.clear_stale_ack(root, 1, 0, 2)
    assert not os.path.isfile(ok)
    # re-entering a manifest-COMPLETE step (a fallback restore resumed
    # below a damaged newest step) invalidates the old manifest too —
    # the dir is torn again, so no barrier can pair stale acks with it
    _save_world(root, 3, {"w": np.ones(4, np.float32)}, 1)
    manifest_lib.clear_stale_ack(root, 3, 0, 1)
    assert not manifest_lib.is_complete(root, 3)
    assert not os.path.isfile(os.path.join(
        manifest_lib.step_dir(root, 3), manifest_lib.ok_name(0, 1)))


def test_resave_of_damaged_complete_step_invalidates_old_manifest(tmp_path):
    """The full fallback → re-save cycle: the newest complete step rots,
    restore falls back one step, resumed training re-reaches the SAME
    step number. The re-save's clear must tear the damaged manifest
    down — otherwise the commit barrier is satisfied instantly by the
    old acks and a fresh manifest silently mixes old and new shards —
    and the new save then commits a consistent step."""
    root = str(tmp_path)
    _save_world(root, 9, {"w": np.ones(4, np.float32)}, 2,
                meta={"commit": 9})
    _save_world(root, 10, {"w": np.full(4, 2.0, np.float32)}, 2,
                meta={"commit": 10})
    path = sharded_lib.shard_path(root, 10, 1, 2)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    step, _, _ = ckpt_lib.restore_sharded(root, {"w": np.zeros(4,
                                                               np.float32)})
    assert step == 9
    # resumed training re-enters step 10 (each rank clears on save entry)
    manifest_lib.clear_stale_ack(root, 10, 0, 2)
    assert not manifest_lib.is_complete(root, 10)
    tree_new = {"w": np.full(4, 3.0, np.float32)}
    _save_world(root, 10, tree_new, 2, meta={"commit": 10})
    s, restored, meta = ckpt_lib.restore_sharded(
        root, {"w": np.zeros(4, np.float32)})
    assert s == 10 and meta == {"commit": 10}
    np.testing.assert_array_equal(restored["w"], tree_new["w"])


def test_legacy_single_file_checkpoints_still_restore(tmp_path):
    """checkpoint.py keeps its public API as a compatibility shim; the
    pre-subsystem format round-trips and the directory fsync / prune
    path leaves complete files alone while sweeping stale tmp debris."""
    from horovod_tpu import checkpoint
    d = str(tmp_path)
    checkpoint.write_checkpoint(d, 1, {"w": np.ones(2, np.float32)})
    checkpoint.write_checkpoint(d, 2, {"w": np.ones(2, np.float32) * 2})
    # stale tmp debris (crashed write) older than the newest step...
    open(os.path.join(d, "ckpt-1.msgpack.tmp"), "wb").write(b"junk")
    # ...and a NEWER tmp that may be another rank's in-flight write
    open(os.path.join(d, "ckpt-9.msgpack.tmp"), "wb").write(b"junk")
    checkpoint.write_checkpoint(d, 3, {"w": np.ones(2, np.float32) * 3},
                                keep=2)
    assert checkpoint.list_steps(d) == [2, 3]
    assert not os.path.exists(os.path.join(d, "ckpt-1.msgpack.tmp"))
    assert os.path.exists(os.path.join(d, "ckpt-9.msgpack.tmp"))
    params, _opt, _meta = checkpoint.restore_checkpoint(
        d, 3, {"w": np.zeros(2, np.float32)})
    np.testing.assert_array_equal(params["w"], 3.0)


# ---- snapshot-offload: the stall is the copy, not the write -----------


def _big_tree(mb=4):
    rng = np.random.default_rng(0)
    n = mb * (1 << 20) // 4 // 4
    return {f"p{i}": rng.standard_normal(n).astype(np.float32)
            for i in range(4)}


def test_async_blocking_small_fraction_of_sync_write(tmp_path):
    """The acceptance bound: per-save training-thread blocking during an
    async save — read from the ``hvd_ckpt_blocking_seconds`` metric —
    must be < 25% of the synchronous ``write_checkpoint`` wall time for
    the same state. (On this CPU the ratio is ~1%; 25% is the contract.)

    Wall-clock bounds on shared CI flake when an fsync stalls the
    background write into the next save's ``max_inflight`` budget wait
    (a REAL stall the metric must report, but not a subsystem bug), so
    the timing bound gets up to 3 attempts; the structural asserts —
    every save really committed — hold on every attempt."""
    from horovod_tpu import checkpoint
    from horovod_tpu.telemetry import instruments
    from horovod_tpu.telemetry.registry import MetricsRegistry

    tree = _big_tree(mb=4)
    ratios = []
    for attempt in range(3):
        root = tmp_path / f"a{attempt}"
        t0 = time.perf_counter()
        checkpoint.write_checkpoint(str(root / "sync"), 1, tree)
        sync_s = time.perf_counter() - t0

        reg = MetricsRegistry()
        ck = ckpt_lib.AsyncCheckpointer(str(root / "async"), keep=2,
                                        rank=0, world=1, registry=reg)
        for step in (1, 2, 3):
            ck.save(step, tree)
            # training steps run here in a real job; the background
            # write overlaps them (saving back-to-back with no gap would
            # measure the max_inflight budget stall instead — see the
            # budget test)
            time.sleep(max(2 * sync_s, 0.05))
        ck.flush()
        ck.close()
        hist = reg.histogram(instruments.CKPT_BLOCKING_SECONDS, "")
        assert hist.count == 3
        # the full save (overlapped) really did the write + commit
        assert reg.histogram(instruments.CKPT_SAVE_SECONDS, "").count == 3
        assert ckpt_lib.list_complete_steps(str(root / "async")) == [2, 3]
        mean_blocking = hist.sum / hist.count
        ratios.append(mean_blocking / sync_s)
        if mean_blocking < 0.25 * sync_s:
            return
    pytest.fail(f"async saves blocked >= 25% of the sync write on all 3 "
                f"attempts (blocking/sync ratios {ratios}) — the stall "
                "must be the copy, not the write")


def test_background_failure_surfaces_on_flush(tmp_path):
    # the checkpoint root "directory" is a regular file: the background
    # mkdir/write must fail, and the failure must reach the trainer
    blocker = tmp_path / "blocker"
    blocker.write_text("not a directory")
    ck = ckpt_lib.AsyncCheckpointer(str(blocker / "sub"), rank=0, world=1)
    ck.save(1, {"w": np.ones(4, np.float32)})
    with pytest.raises(RuntimeError, match="background checkpoint"):
        ck.flush()
    ck.close()


def test_snapshot_failure_returns_budget_slot(tmp_path):
    """A snapshot that dies on the TRAINING thread (before any job is
    queued) must give its in-flight budget slot back — otherwise the
    next save() parks in the budget wait forever (nothing will ever
    decrement) and the trailing flush() deadlocks the trainer."""
    class _Poison:
        def __array__(self, *a, **kw):
            raise RuntimeError("buffer gone")

    ck = ckpt_lib.AsyncCheckpointer(str(tmp_path), max_inflight=1,
                                    rank=0, world=1)
    with pytest.raises(RuntimeError, match="buffer gone"):
        ck.save(1, {"w": _Poison()})
    # the slot came back: a healthy save must neither block nor inherit
    # a phantom in-flight entry
    ck.save(2, {"w": np.ones(4, np.float32)})
    ck.flush()
    ck.close()
    assert ckpt_lib.list_complete_steps(str(tmp_path)) == [2]


def test_restore_falls_back_past_unrestorable_newest_step(tmp_path):
    """Torn-write philosophy, applied to reads: when the NEWEST
    manifest-complete step is unrestorable — a shard fails its manifest
    CRC (disk rot, or a manifest paired with a stale phase-1 ack by the
    crash-adjacent re-save race) or a shard file is simply gone — the
    default restore falls back to the previous complete step instead of
    stranding the job. An EXPLICIT step still fails loudly, and so does
    damage hitting every step (nothing left to fall back to)."""
    root = str(tmp_path)
    tree5 = {"w": np.arange(8, dtype=np.float32)}
    _save_world(root, 5, tree5, 2, meta={"commit": 5})
    _save_world(root, 10, {"w": np.arange(8, dtype=np.float32) * 2}, 2)

    # newest step's shard 1 is corrupt (CRC mismatch vs its manifest)
    path = sharded_lib.shard_path(root, 10, 1, 2)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))

    step, restored, meta = ckpt_lib.restore_sharded(
        root, {"w": np.zeros(8, np.float32)})
    assert step == 5 and meta == {"commit": 5}
    np.testing.assert_array_equal(restored["w"], tree5["w"])
    with pytest.raises(ckpt_lib.ShardValidationError, match="CRC32"):
        ckpt_lib.restore_sharded(root, {"w": np.zeros(8, np.float32)},
                                 step=10)

    # a MISSING shard file falls back the same way...
    os.remove(path)
    step, _, _ = ckpt_lib.restore_sharded(root,
                                          {"w": np.zeros(8, np.float32)})
    assert step == 5
    # ...and when every complete step is damaged, restore fails loudly
    os.remove(sharded_lib.shard_path(root, 5, 0, 2))
    with pytest.raises(ValueError, match="failed validation"):
        ckpt_lib.restore_sharded(root, {"w": np.zeros(8, np.float32)})


def test_max_inflight_budget_blocks_and_is_metered(tmp_path):
    """With max_inflight=1 a second save must wait for the first commit,
    and that wait is charged to the blocking metric (a budget stall is a
    real training stall)."""
    from horovod_tpu.telemetry import instruments
    from horovod_tpu.telemetry.registry import MetricsRegistry
    reg = MetricsRegistry()
    tree = _big_tree(mb=2)
    ck = ckpt_lib.AsyncCheckpointer(str(tmp_path), max_inflight=1,
                                    rank=0, world=1, registry=reg)
    b1 = ck.save(1, tree)
    b2 = ck.save(2, tree)  # queued while 1 is still serializing
    ck.flush()
    ck.close()
    hist = reg.histogram(instruments.CKPT_BLOCKING_SECONDS, "")
    assert hist.count == 2 and hist.sum >= b1 + b2 - 1e-6
    assert ckpt_lib.latest_complete_step(str(tmp_path)) == 2


def test_snapshot_payload_copies_host_numpy():
    """The payload handed to the background writer must be decoupled
    from live state: numpy-backed state (device_get is identity on it)
    mutated in place during the overlapped write must not reach the
    bytes being serialized — a torn serialization would still CRC as
    valid and commit a state no training step ever produced."""
    w = np.ones(8, np.float32)
    payload, _ = ckpt_lib.snapshot_tree({"w": w}, 0, 1)
    assert not np.shares_memory(payload["repl"]["0"], w)
    w += 1  # the training step the background write overlaps
    np.testing.assert_array_equal(payload["repl"]["0"], 1.0)

    import horovod_tpu.elastic.state as state_mod
    st = state_mod.JaxState(w=w)
    cap = st._capture()
    assert not np.shares_memory(cap["w"], st.w)


def test_flush_timeout_zero_means_dont_wait(tmp_path):
    """flush(timeout=0) is 'abandon immediately', not 'wait forever':
    HOROVOD_CKPT_RESET_TIMEOUT=0 must not park elastic recovery on a
    commit barrier a dead peer already broke."""
    ck = ckpt_lib.AsyncCheckpointer(str(tmp_path), rank=0, world=2,
                                    barrier_timeout=5.0)
    ck.save(1, {"w": np.ones(4, np.float32)})  # parks: no peer shard
    with pytest.raises(TimeoutError, match="still in"):
        ck.flush(timeout=0)
    ck.abandon()
    ck._thread.join(timeout=30)


def test_abandon_drops_queued_saves(tmp_path):
    """abandon() must DROP queued-but-unwritten saves, not drain them: a
    shard the dead writer lands minutes later could pair with a manifest
    the post-reset world commits for the same step. world=2 with no
    peer: save 1 parks in the commit barrier mid-write, save 2 sits
    queued behind it; after abandon(), step 2's dir must never appear."""
    root = str(tmp_path)
    tree = {"w": np.ones(4, np.float32)}
    ck = ckpt_lib.AsyncCheckpointer(root, max_inflight=2, rank=0, world=2,
                                    barrier_timeout=1.0)
    ck.save(1, tree)
    ok1 = os.path.join(manifest_lib.step_dir(root, 1),
                       manifest_lib.ok_name(0, 2))
    deadline = time.monotonic() + 10
    while not os.path.isfile(ok1) and time.monotonic() < deadline:
        time.sleep(0.01)
    assert os.path.isfile(ok1), "save 1 never reached its mid-write park"
    ck.save(2, tree)
    ck.abandon()
    ck._thread.join(timeout=30)
    assert not ck._thread.is_alive()
    assert os.path.isdir(manifest_lib.step_dir(root, 1))  # was mid-write
    assert not os.path.isdir(manifest_lib.step_dir(root, 2))
    with ck._lock:
        assert ck._inflight == 0


# ---- elastic integration: JaxState through the subsystem --------------


def test_jax_state_commit_restore_and_flush_on_reset(tmp_path, monkeypatch):
    """JaxState commits land as sharded manifest-complete checkpoints at
    the checkpoint_every cadence; on_reset (the pre-rendezvous hook)
    flushes in-flight saves; a fresh JaxState restores commit + meta.
    Single process standing in for world=1 (an initialized 8-device hvd
    would make the commit barrier wait for 8 shards)."""
    import horovod_tpu as hvd_mod
    import horovod_tpu.elastic as elastic
    hvd_mod.shutdown()
    monkeypatch.delenv("HOROVOD_RANK", raising=False)
    monkeypatch.delenv("HOROVOD_SIZE", raising=False)

    d = str(tmp_path)
    state = elastic.JaxState(directory=d, keep=5, checkpoint_every=2,
                             w=np.zeros(4, np.float32))
    for c in range(1, 5):
        state.w = state.w + 1
        state.commit()
        state.on_reset()  # must force any async save to durability
        complete = ckpt_lib.list_complete_steps(d)
        assert complete == [s for s in range(1, c + 1) if s % 2 == 0]

    fresh = elastic.JaxState(directory=d, keep=5,
                             w=np.zeros(4, np.float32))
    fresh.restore()
    assert fresh._commit_count == 4
    np.testing.assert_array_equal(fresh.w, 4.0)
    state.flush()
    fresh.flush()


def test_sync_adopts_roots_commit_count(monkeypatch):
    """After a membership change the synced trees are the root's commit;
    the commit COUNTER must ride along — a disk-restored newcomer sits
    at the on-disk count while survivors are in-memory ahead, and ranks
    that disagree would write their next shards under DIFFERENT step
    numbers, a commit barrier that can never complete. Single process:
    the patched collective plane hands back the root's counter."""
    import horovod_tpu.elastic.state as state_mod

    roots_seen = []

    def fake_broadcast(tree, root):
        roots_seen.append(root)
        if isinstance(tree, np.ndarray) and tree.shape == ():
            return np.asarray(7, np.int64)  # the root's counter
        return tree

    monkeypatch.setattr(state_mod, "_broadcast_tree", fake_broadcast)
    monkeypatch.setattr(state_mod, "_elect_root",
                        lambda root_rank, has_commit: 1)

    st = state_mod.JaxState(w=np.zeros(2, np.float32))
    st._saved_state = {"w": np.ones(2, np.float32)}  # a prior commit
    st._commit_count = 4  # disk-restored lag behind the survivors
    assert st.sync() == 1
    assert st._commit_count == 7
    assert roots_seen == [1, 1]  # trees, then the counter — same root


def test_elastic_train_loop_checkpoint_cadence(tmp_path, monkeypatch):
    """``elastic_train_loop(checkpoint_every=3)``: the entry sync's
    baseline save is commit 1, the 4 training steps commit 2..5; disk
    sees [3] by cadence plus the FORCED final commit [5] — and the
    forced commit must not clobber the cadence (an elastic retry
    re-enters the loop with the same state object)."""
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd_mod
    import horovod_tpu.elastic as elastic
    from horovod_tpu.training import TrainState, elastic_train_loop
    hvd_mod.shutdown()
    monkeypatch.delenv("HOROVOD_RANK", raising=False)
    monkeypatch.delenv("HOROVOD_SIZE", raising=False)

    tx = optax.sgd(0.2)
    params = {"w": jnp.zeros(())}
    ts = TrainState(params=params, opt_state=tx.init(params),
                    batch_stats={}, step=jnp.zeros((), jnp.int32))

    def train_step(state, inputs, labels):
        del inputs, labels
        grads = {"w": 2 * (state.params["w"] - 3.0)}
        updates, opt_state = tx.update(grads, state.opt_state,
                                       state.params)
        return TrainState(params=optax.apply_updates(state.params,
                                                     updates),
                          opt_state=opt_state, batch_stats={},
                          step=state.step + 1), \
            (state.params["w"] - 3.0) ** 2

    state = elastic.JaxState(directory=str(tmp_path), train_state=ts)
    final = elastic_train_loop(state, train_step,
                               lambda step: (None, None), num_steps=4,
                               commit_every=1, checkpoint_every=3)
    assert int(final.step) == 4
    assert ckpt_lib.list_complete_steps(str(tmp_path)) == [3, 5]
    assert state.checkpoint_every == 3  # cadence survives the final save
    state._ckpt.close()


def _ckpt_every_worker(ckpt_dir, sync_dir):
    def run():
        import time as _time

        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu import checkpoint
        from horovod_tpu import ckpt as _ckpt
        from horovod_tpu.telemetry import get_registry, instruments
        hvd.init()
        rank = hvd.rank()
        rng = np.random.default_rng(rank)
        w = rng.standard_normal(1 << 19).astype(np.float32)  # 2 MB/rank

        # the synchronous baseline for THE SAME state (rank-local dir)
        t0 = _time.perf_counter()
        checkpoint.write_checkpoint(f"{sync_dir}/r{rank}", 1, {"w": w})
        sync_s = _time.perf_counter() - t0

        state = hvd.elastic.JaxState(directory=ckpt_dir, keep=5,
                                     checkpoint_every=2, w=w)
        for _ in range(4):
            w = w + hvd.allreduce(np.ones_like(w))
            state.w = w
            state.commit()
            _time.sleep(0.3)  # the training work the write overlaps
        state.flush()
        hist = get_registry().histogram(instruments.CKPT_BLOCKING_SECONDS,
                                        "")
        steps = _ckpt.list_complete_steps(ckpt_dir)
        state._ckpt.close()
        return (sync_s, hist.sum, hist.count, steps)
    return run


def test_2rank_checkpoint_every_blocking_under_25pct(tmp_path):
    """The ISSUE 5 acceptance run: 2 CPU ranks committing through
    ``checkpoint_every=2``; per-step blocking time during the async
    saves (``hvd_ckpt_blocking_seconds``) stays under 25% of each
    rank's synchronous ``write_checkpoint`` baseline, and only every
    2nd commit reached disk. The structural asserts hold on every
    attempt; the wall-clock bound (flaky under shared-CI fsync stalls)
    gets up to 3 attempts."""
    worst = []
    for attempt in range(3):
        ckpt_dir = str(tmp_path / f"ck{attempt}")
        sync_dir = str(tmp_path / f"sync{attempt}")
        results = api.run(_ckpt_every_worker(ckpt_dir, sync_dir), np=2,
                          extra_env={"JAX_PLATFORMS": "cpu",
                                     "HOROVOD_CKPT_TIMEOUT": "60"})
        ratios = []
        for rank, (sync_s, blocking_sum, n_saves, steps) \
                in enumerate(results):
            assert n_saves == 2, f"rank {rank}: 4 commits -> 2 disk saves"
            assert steps == [2, 4]
            ratios.append(blocking_sum / n_saves / sync_s)
        worst.append(max(ratios))
        if max(ratios) < 0.25:
            return
    pytest.fail(f"some rank's async blocking was >= 25% of its sync "
                f"write on all 3 attempts (worst blocking/sync ratio "
                f"per attempt: {worst})")


def test_manifest_kv_ack_is_best_effort(tmp_path, monkeypatch):
    """With a rendezvous KV configured but unreachable, commits must
    still succeed — durability never depends on the KV ack."""
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", "1")  # nothing there
    man = _save_world(str(tmp_path), 1, {"w": np.ones(2, np.float32)}, 1)
    assert man["step"] == 1
    assert ckpt_lib.latest_complete_step(str(tmp_path)) == 1


def test_doctor_reports_interrupted_save():
    """A flight-recorder dump holding a ckpt B without its E is surfaced
    by the doctor as an interrupted save (the post-crash story: restore
    falls back to the last complete manifest)."""
    from horovod_tpu.diag import doctor
    dump = {"flightrec": 1, "rank": 0, "size": 1, "collective_seq": 3,
            "last_completed_seq": 3, "open_collectives": {},
            "dump_reasons": ["sigterm"], "digest": {},
            "events": [
                {"k": "ckpt", "t": 1.0, "ph": "B", "step": 4, "rank": 0},
                {"k": "ckpt", "t": 1.2, "ph": "E", "step": 4, "ok": True},
                {"k": "ckpt", "t": 2.0, "ph": "B", "step": 5, "rank": 0},
            ]}
    report = doctor.diagnose({0: dump})
    assert report["interrupted_saves"] == {0: [5]}
    text = doctor.format_report(report)
    assert "INTERRUPTED CHECKPOINT SAVE" in text
    assert "step(s) [5]" in text
    # serializable (the launcher writes reports as json)
    json.dumps(report)

    # B/E pairing is by EVENT ORDER, not step membership: a step whose
    # first save failed and was then re-begun (the torn-step re-save
    # flow) is open again — an old E must not mask the later B
    dump["events"] = [
        {"k": "ckpt", "t": 1.0, "ph": "B", "step": 4, "rank": 0},
        {"k": "ckpt", "t": 1.2, "ph": "E", "step": 4, "ok": False},
        {"k": "ckpt", "t": 2.0, "ph": "B", "step": 4, "rank": 0},
    ]
    assert doctor.diagnose({0: dump})["interrupted_saves"] == {0: [4]}
