"""Tensor-parallel transformer: the GSPMD step must be numerically
identical to the single-device oracle while the big matrices actually
live sharded over the ``model`` axis (beyond-parity feature; SURVEY §2.7
marks TP absent from the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.parallel import tensor as tp
from horovod_tpu.training import TrainState


def _cfg():
    return TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                             d_model=32, d_ff=64, dtype=jnp.float32)


def _tp_mesh():
    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("data", "model"))


@pytest.fixture()
def tokens(rng):
    return jnp.asarray(rng.integers(0, 64, size=(4, 16)), jnp.int32)


def test_tp_step_matches_single_device_oracle(tokens):
    model = Transformer(_cfg())
    mesh = _tp_mesh()
    tx = optax.sgd(0.1)

    state = tp.shard_lm_state(model, tx, jax.random.PRNGKey(0), tokens[:1],
                              mesh)
    step = tp.make_tp_lm_train_step(model, tx, mesh, donate=False)
    new_state, loss = step(state, tokens)

    # oracle: same init, same batch, one device, plain optax
    variables = model.init(jax.random.PRNGKey(0), tokens[:1])
    oparams = variables["params"]

    def oracle_loss(params):
        logits = model.apply({"params": params}, tokens)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        ll = jnp.take_along_axis(logp, tokens[:, 1:][..., None], -1)[..., 0]
        return -jnp.mean(ll)

    oloss, ograds = jax.value_and_grad(oracle_loss)(oparams)
    oopt = tx.init(oparams)
    oupd, _ = tx.update(ograds, oopt, oparams)
    oparams = optax.apply_updates(oparams, oupd)

    np.testing.assert_allclose(float(loss), float(oloss), rtol=1e-5)
    flat_tp = jax.tree_util.tree_leaves_with_path(new_state.params)
    flat_or = dict(jax.tree_util.tree_leaves_with_path(oparams))
    for path, leaf in flat_tp:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_or[path]), rtol=2e-4,
            atol=1e-5, err_msg=jax.tree_util.keystr(path))


def test_tp_params_actually_sharded(tokens):
    model = Transformer(_cfg())
    mesh = _tp_mesh()
    state = tp.shard_lm_state(model, optax.sgd(0.1), jax.random.PRNGKey(0),
                              tokens[:1], mesh)
    p = state.params
    assert p["block_0"]["Dense_0"]["kernel"].sharding.spec == P(None, "model")
    assert p["block_0"]["Dense_1"]["kernel"].sharding.spec == P("model", None)
    assert (p["block_0"]["attn"]["query"]["kernel"].sharding.spec
            == P(None, "model", None))
    assert (p["block_0"]["attn"]["out"]["kernel"].sharding.spec
            == P("model", None, None))
    assert p["lm_head"]["kernel"].sharding.spec == P(None, "model")
    # per-device shard of d_ff kernel is 1/4 of the full matrix
    shard = p["block_0"]["Dense_0"]["kernel"].addressable_shards[0]
    assert shard.data.shape == (32, 64 // 4)


def test_tp_training_reduces_loss(tokens):
    model = Transformer(_cfg())
    mesh = _tp_mesh()
    tx = optax.adam(1e-2)
    state = tp.shard_lm_state(model, tx, jax.random.PRNGKey(0), tokens[:1],
                              mesh)
    step = tp.make_tp_lm_train_step(model, tx, mesh)
    losses = []
    for _ in range(10):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses
    # updates must not have drifted the layout
    assert (state.params["block_0"]["Dense_0"]["kernel"].sharding.spec
            == P(None, "model"))
