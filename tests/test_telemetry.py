"""Telemetry plane tests: registry, Prometheus endpoint, timeline
writer correctness, cross-rank trace merge, and the tier-1 end-to-end
trace-validity run (3-step CPU training with timeline + metrics on).
"""

import json
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu.telemetry import (MetricsRegistry, MetricsServer,
                                   get_registry, load_events, merge_traces)
from horovod_tpu.telemetry import instruments
from horovod_tpu.telemetry.merge import CLOCK_SYNC


# ---------------------------------------------------------------------------
# A tiny Prometheus text-format parser (the test's own, so the scrape
# contract is pinned independently of our renderer).
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$")


def parse_prometheus(text):
    """Parse exposition text into {(name, labels_frozenset): float},
    validating TYPE lines reference real sample families."""
    samples = {}
    types = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE"):
            _, _, name, kind = line.split(None, 3)
            assert kind in ("counter", "gauge", "histogram", "untyped")
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        labels = frozenset(
            tuple(kv.split("=", 1)) for kv in
            (m.group("labels").split(",") if m.group("labels") else []))
        value = float(m.group("value").replace("+Inf", "inf"))
        samples[(m.group("name"), labels)] = value
    for name in types:
        assert any(k[0].startswith(name) for k in samples), \
            f"TYPE {name} has no samples"
    return samples


def names_of(samples):
    return {k[0] for k in samples}


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("t_total", "a counter")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("t_gauge")
    g.set(2.5)
    g.inc()
    g.dec(0.5)
    assert g.value == 3.0
    h = r.histogram("t_hist", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 3 and h.sum == pytest.approx(5.55)
    assert h.quantile(0.5) == 0.5


def test_zero_valued_metric_still_renders():
    r = MetricsRegistry()
    r.counter("never_incremented_total")
    samples = parse_prometheus(r.render_prometheus())
    assert samples[("never_incremented_total", frozenset())] == 0


def test_labels_and_render_roundtrip():
    r = MetricsRegistry()
    c = r.counter("ops_total", "per-op", label_names=("op",))
    c.labels("allreduce").inc(3)
    c.labels("allgather").inc(7)
    samples = parse_prometheus(r.render_prometheus())
    assert samples[("ops_total", frozenset({("op", '"allreduce"')}))] == 3
    assert samples[("ops_total", frozenset({("op", '"allgather"')}))] == 7
    with pytest.raises(ValueError):
        c.labels("a", "b")  # wrong label arity


def test_reregistration_is_get_or_create():
    r = MetricsRegistry()
    a = r.counter("same_total")
    b = r.counter("same_total")
    assert a is b
    with pytest.raises(ValueError):
        r.gauge("same_total")  # kind mismatch
    with pytest.raises(ValueError):
        r.counter("same_total", label_names=("x",))  # label mismatch


def test_deferred_gauge_reads_at_collect_time():
    r = MetricsRegistry()
    g = r.gauge("lazy")
    box = [1.0]
    g.set_function(lambda: box[0])
    box[0] = 42.0
    assert g.value == 42.0  # read NOW, not at set_function time
    g.set(7.0)              # a plain set clears the callback
    assert g.value == 7.0


def test_histogram_cumulative_buckets():
    r = MetricsRegistry()
    h = r.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    s = parse_prometheus(r.render_prometheus())
    le = lambda b: frozenset({("le", f'"{b}"')})  # noqa: E731
    assert s[("lat_bucket", le("0.01"))] == 1
    assert s[("lat_bucket", le("0.1"))] == 2
    assert s[("lat_bucket", le("1"))] == 3
    assert s[("lat_bucket", le("+Inf"))] == 4
    assert s[("lat_count", frozenset())] == 4


def test_histogram_reservoir_bounded():
    r = MetricsRegistry()
    h = r.histogram("res", reservoir_size=16)
    for v in range(10_000):
        h.observe(float(v))
    assert h.count == 10_000
    child = h._self_child()
    assert len(child._res) == 16  # never grew
    assert 0 < h.quantile(0.5) < 10_000


def test_registry_thread_safety():
    r = MetricsRegistry()
    c = r.counter("racy_total")
    h = r.histogram("racy_hist")

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.01)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


def test_snapshot_shapes():
    r = MetricsRegistry()
    r.counter("c_total").inc(2)
    r.histogram("h").observe(1.0)
    r.counter("lab_total", label_names=("op",)).labels("x").inc()
    snap = r.snapshot()
    assert snap["c_total"] == 2
    assert snap["h"]["count"] == 1
    assert snap['lab_total{op="x"}'] == 1


def test_kv_snapshot_compact():
    r = MetricsRegistry()
    r.counter(instruments.STEP_TOTAL).inc(5)
    r.histogram(instruments.STEP_SECONDS).observe(0.1)
    r.gauge(instruments.EXAMPLES_PER_SEC).set(100.0)
    r.counter(instruments.COLLECTIVE_BYTES,
              label_names=("op",)).labels("allreduce").inc(1024)
    snap = instruments.kv_snapshot(r)
    assert snap["step"] == 5
    assert snap["step_seconds_p50"] == pytest.approx(0.1)
    assert snap["examples_per_sec"] == 100.0
    assert snap["collective_bytes"] == 1024
    assert len(json.dumps(snap)) < 500  # compact enough for a heartbeat


# ---------------------------------------------------------------------------
# HTTP server
# ---------------------------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
        return resp.status, resp.read().decode()


def test_metrics_server_scrape_and_health():
    r = MetricsRegistry()
    r.counter("served_total").inc(9)
    srv = MetricsServer(port=0, registry=r,
                        health_fn=lambda: {"rank": 3, "step": 17})
    port = srv.start()
    try:
        status, body = _get(port, "/metrics")
        assert status == 200
        assert parse_prometheus(body)[("served_total", frozenset())] == 9
        status, body = _get(port, "/healthz")
        health = json.loads(body)
        assert health == {"status": "ok", "rank": 3, "step": 17}
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/nope")
        assert e.value.code == 404
    finally:
        srv.stop()


def test_profile_endpoint(tmp_path, monkeypatch):
    """Endpoint contract with the profiler stubbed (a cold
    ``jax.profiler.start_trace`` costs ~16 s; the real capture is
    exercised by the slow-marked test below): immediate 200 with the
    output dir, 409 while a capture is active, guard released after."""
    import jax

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    srv = MetricsServer(port=0, profile_dir=str(tmp_path / "prof"))
    port = srv.start()
    try:
        status, body = _get(port, "/profile?seconds=0.2")
        assert status == 200
        info = json.loads(body)
        assert info["output_dir"] == str(tmp_path / "prof")
        # a second capture while one runs is refused
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/profile?seconds=0.2")
        assert e.value.code == 409
        deadline = time.monotonic() + 10
        while srv._profile_active and time.monotonic() < deadline:
            time.sleep(0.02)
        assert calls == [("start", str(tmp_path / "prof")), ("stop",)]
        # guard released: a new capture is accepted again
        status, _ = _get(port, "/profile?seconds=0.1")
        assert status == 200
    finally:
        srv.stop()


@pytest.mark.slow
def test_profile_endpoint_real_capture(tmp_path):
    """The real jax.profiler round-trip through /profile (slow: a cold
    profiler start takes ~16 s on CPU)."""
    srv = MetricsServer(port=0, profile_dir=str(tmp_path / "prof"))
    port = srv.start()
    try:
        status, _ = _get(port, "/profile?seconds=0.3")
        assert status == 200
        import jax.numpy as jnp
        (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
        deadline = time.monotonic() + 60
        while srv._profile_active and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not srv._profile_active
        assert (tmp_path / "prof").exists()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Timeline writer + merge
# ---------------------------------------------------------------------------


def test_timeline_close_idempotent_and_valid(tmp_path):
    from horovod_tpu.utils.timeline import Timeline

    path = tmp_path / "t.json"
    tl = Timeline(str(path), rank=2, host="worker-a")
    tl.instant("A", args={"k": 1})
    tl.start_activity("tensor0", "ALLREDUCE")
    tl.end_activity("tensor0")
    tl.counter("step", {"step_ms": 12.5})
    fid = tl.flow_start("step_dispatch")
    tl.flow_point("BUCKET_RS", fid)
    tl.flow_end("step_dispatch", fid)
    tl.close()
    tl.close()  # idempotent
    events = json.load(open(path))
    names = [e["name"] for e in events]
    assert "process_name" in names and CLOCK_SYNC in names
    assert {e["pid"] for e in events} == {2}
    meta = next(e for e in events if e["name"] == "process_name")
    assert meta["args"]["name"] == "rank 2 (worker-a)"
    phases = {e["name"]: e["ph"] for e in events}
    assert phases["step"] == "C"
    flows = [e["ph"] for e in events if e.get("cat") == "flow"]
    assert flows == ["s", "t", "f"]


def test_timeline_events_racing_close_not_dropped(tmp_path):
    """Events enqueued concurrently with close() land in the file (the
    writer drains past the sentinel)."""
    from horovod_tpu.utils.timeline import Timeline

    path = tmp_path / "race.json"
    tl = Timeline(str(path))
    n_emitters, per_thread = 4, 50
    barrier = threading.Barrier(n_emitters + 1)

    def emit(tid):
        barrier.wait()
        for i in range(per_thread):
            tl.instant(f"ev_{tid}_{i}")

    threads = [threading.Thread(target=emit, args=(t,))
               for t in range(n_emitters)]
    for t in threads:
        t.start()
    barrier.wait()
    time.sleep(0.005)  # let emitters race the close below
    tl.close()
    for t in threads:
        t.join()
    events = json.load(open(path))  # valid JSON regardless of the race
    emitted = [e for e in events if e["name"].startswith("ev_")]
    # every event enqueued BEFORE close flipped the flag is in the file;
    # the exact count depends on the race, but the file must be valid
    # and must contain a prefix of each thread's sequence
    for t in range(n_emitters):
        seq = [int(e["name"].split("_")[2]) for e in emitted
               if e["name"].startswith(f"ev_{t}_")]
        assert seq == sorted(seq)


def test_timeline_crash_leaves_repairable_file(tmp_path):
    """No close() (a crashed rank): the flushed prefix parses after
    repair and keeps every fully-written event."""
    from horovod_tpu.utils.timeline import Timeline

    path = tmp_path / "crash.json"
    tl = Timeline(str(path), rank=1)
    for i in range(20):
        tl.instant(f"step_{i}")
    # wait for the writer to drain + flush, then "crash" (no close)
    deadline = time.monotonic() + 10
    while not tl._queue.empty() and time.monotonic() < deadline:
        time.sleep(0.01)
    time.sleep(0.05)
    tl._file.flush()
    with pytest.raises(json.JSONDecodeError):
        json.load(open(path))  # truncated: no closing ]
    events = load_events(str(path))
    names = [e["name"] for e in events]
    assert "step_0" in names and "step_19" in names


def test_load_events_repairs_half_written_tail(tmp_path):
    p = tmp_path / "torn.json"
    good = [{"name": "a", "ph": "i", "ts": 1, "pid": 0},
            {"name": "b", "ph": "i", "ts": 2, "pid": 0,
             "args": {"x": {"y": 1}}}]
    text = "[\n" + ",\n".join(json.dumps(e) for e in good)
    p.write_text(text + ',\n{"name": "torn", "ph": "i", "ts": 3, "ar')
    events = load_events(str(p))
    assert [e["name"] for e in events] == ["a", "b"]


def test_load_events_rejects_non_trace(tmp_path):
    p = tmp_path / "notatrace.json"
    p.write_text("hello world")
    with pytest.raises(ValueError):
        load_events(str(p))


def test_merge_aligns_clocks_and_assigns_pids(tmp_path):
    def write_trace(path, rank, unix0_us, events):
        evs = [{"name": CLOCK_SYNC, "ph": "i", "ts": 0, "pid": 0,
                "args": {"unix_time_us": unix0_us, "rank": rank}}]
        evs += events
        path.write_text(json.dumps(evs))

    # rank 1's clock started 1500 us after rank 0's
    a, b = tmp_path / "t.rank0.json", tmp_path / "t.rank1.json"
    write_trace(a, 0, 10_000_000,
                [{"name": "s", "ph": "i", "ts": 100, "pid": 0}])
    write_trace(b, 1, 10_001_500,
                [{"name": "s", "ph": "i", "ts": 100, "pid": 0}])
    out = tmp_path / "merged.json"
    merged = merge_traces([str(a), str(b)], str(out))
    assert json.load(open(out)) == merged
    by_pid = {}
    for e in merged:
        if e["name"] == "s":
            by_pid[e["pid"]] = e["ts"]
    assert set(by_pid) == {0, 1}
    assert by_pid[1] - by_pid[0] == 1500  # clock shift applied
    # both ranks got process metadata
    names = [(e["pid"], e["name"]) for e in merged if e.get("ph") == "M"]
    assert (0, "process_name") in names and (1, "process_name") in names


def test_merge_cli(tmp_path):
    from horovod_tpu.telemetry import merge as merge_mod

    t = tmp_path / "one.rank0.json"
    t.write_text(json.dumps(
        [{"name": "x", "ph": "i", "ts": 5, "pid": 0}]))
    out = tmp_path / "merged.json"
    rc = merge_mod.main(["-o", str(out), str(tmp_path / "*.rank*.json")])
    assert rc == 0
    assert any(e["name"] == "x" for e in json.load(open(out)))


def test_hvdrun_merge_timeline_flag(tmp_path):
    from horovod_tpu.run import run as run_mod

    t = tmp_path / "t.rank0.json"
    t.write_text(json.dumps([{"name": "x", "ph": "i", "ts": 1, "pid": 0}]))
    out = tmp_path / "m.json"
    rc = run_mod.main(["--merge-timeline", str(out), str(t)])
    assert rc == 0
    assert json.load(open(out))


# ---------------------------------------------------------------------------
# allreduce_metrics / MetricAverageCallback edge cases (reference
# semantics: horovod/_keras/callbacks.py:46-85)
# ---------------------------------------------------------------------------


def test_allreduce_metrics_non_numeric_passthrough(hvd):
    from horovod_tpu import hvd_jax

    out = hvd_jax.allreduce_metrics(
        {"loss": 2.0, "run_name": "exp-7", "note": None})
    assert float(np.asarray(out["loss"])) == pytest.approx(2.0)
    assert out["run_name"] == "exp-7"
    assert out["note"] is None


def test_allreduce_metrics_empty_and_nested(hvd):
    from horovod_tpu import hvd_jax

    assert hvd_jax.allreduce_metrics({}) == {}
    nested = {"train": {"loss": 1.0, "acc": 0.5},
              "val": {"loss": [2.0, 3.0]}}
    out = hvd_jax.allreduce_metrics(nested)
    assert float(np.asarray(out["train"]["loss"])) == pytest.approx(1.0)
    assert float(np.asarray(out["val"]["loss"][1])) == pytest.approx(3.0)


def test_allreduce_metrics_sum_single_process(hvd):
    from horovod_tpu import hvd_jax
    from horovod_tpu.ops.reduction import Sum

    out = hvd_jax.allreduce_metrics({"count": np.int32(7)}, op=Sum)
    assert np.asarray(out["count"]).dtype == np.int32
    assert int(out["count"]) == 7  # world size 1: identity


def test_metric_average_callback_edges(hvd):
    from horovod_tpu.callbacks import MetricAverageCallback

    cb = MetricAverageCallback()
    assert cb.on_epoch_end(0, None) is None
    assert cb.on_epoch_end(0, {}) == {}
    out = cb.on_epoch_end(
        0, {"loss": 1.5, "tag": "keep-me", "nested": {"acc": 1}})
    assert out["loss"] == pytest.approx(1.5)
    assert isinstance(out["loss"], float)
    assert out["tag"] == "keep-me"
    assert out["nested"]["acc"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# Elastic driver cluster view / straggler flagging
# ---------------------------------------------------------------------------


def test_cluster_view_flags_two_worker_straggler():
    """Lower-median regression: on a 2-worker cluster the slowest rank
    must still be flaggable (the upper-middle 'median' would BE the
    slowest and the ratio would always read 1.0)."""
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.elastic.driver import ElasticDriver

    driver = ElasticDriver(FixedHosts({"hostA": 2}), min_np=2)
    beats = {0: {"step": 10, "time": 1.0,
                 "metrics": {"step_seconds_p50": 0.1}},
             1: {"step": 4, "time": 1.0,
                 "metrics": {"step_seconds_p50": 1.0}}}
    driver.worker_progress = lambda: beats
    view = driver.cluster_view()
    assert view["straggler_ratio"] == pytest.approx(10.0)
    assert view["stragglers"] == [1]
    assert view["ranks"][0]["step"] == 10
    # flag log is per-epoch rate-limited: second call stays flagged
    assert driver.cluster_view()["stragglers"] == [1]
    driver.stop()


# ---------------------------------------------------------------------------
# End-to-end trace validity (the tier-1 acceptance run): 3-step CPU
# training with timeline + metrics for two "ranks", merged trace loads,
# /metrics scrape parses and carries the catalogued names.
# ---------------------------------------------------------------------------


def _three_step_run(monkeypatch, tmp_path, rank, size):
    import jax
    import optax

    import horovod_tpu as hvd_mod
    from horovod_tpu import basics, training
    from horovod_tpu.models.simple import MLP

    monkeypatch.setenv("HOROVOD_TIMELINE", str(tmp_path / "trace.json"))
    monkeypatch.setenv("HOROVOD_METRICS_PORT", "0")
    monkeypatch.setenv("HOROVOD_RANK", str(rank))
    monkeypatch.setenv("HOROVOD_SIZE", str(size))
    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        model = MLP(features=(16, 10))
        tx = hvd_mod.DistributedOptimizer(optax.sgd(0.01))
        rng = np.random.default_rng(rank)
        x = rng.standard_normal((16, 8)).astype(np.float32)
        y = rng.integers(0, 10, 16).astype(np.int32)
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0), x[:1])
        # overlap pipeline so BUCKET_RS/AG markers hit the trace
        step = training.make_train_step(model, tx, accum_steps=2,
                                        overlap_grads=True)
        for _ in range(3):
            state, loss = step(state, x, y)
        # a membership marker, as the elastic driver emits them
        basics._state.timeline.membership(
            "RENDEZVOUS", {"epoch": 1, "np": size})
        port = basics._state.metrics_server.port
        _, scrape = _get(port, "/metrics")
        _, health = _get(port, "/healthz")
    finally:
        hvd_mod.shutdown()
    return scrape, json.loads(health)


def test_trace_validity_end_to_end(monkeypatch, tmp_path):
    scrapes = {}
    for rank in (0, 1):
        scrape, health = _three_step_run(monkeypatch, tmp_path, rank, 2)
        assert health["status"] == "ok" and health["rank"] == rank
        scrapes[rank] = scrape

    # -- the scrape parses and carries the catalogued names --------------
    samples = parse_prometheus(scrapes[0])
    got = names_of(samples)
    for needed in (instruments.STEP_TOTAL,
                   instruments.EXAMPLES_PER_SEC,
                   instruments.STALLED_RANKS,
                   instruments.GOODPUT_RATIO,
                   instruments.BUILD_INFO):
        assert needed in got, f"scrape missing {needed}"
    assert instruments.STEP_SECONDS + "_count" in got
    # the goodput ledger's per-phase counters ride every scrape
    assert (instruments.TIME_SECONDS,
            frozenset({("phase", '"compute"')})) in samples
    # renamed families still answer to their horovod_* names (one
    # release of scrape-time aliases, docs/OBSERVABILITY.md)
    legacy = instruments.LEGACY_ALIASES[instruments.STEP_TOTAL]
    assert samples[(legacy, frozenset())] == \
        samples[(instruments.STEP_TOTAL, frozenset())]
    assert (instruments.COLLECTIVE_BYTES,
            frozenset({("op", '"bucket_rs"')})) in samples
    assert samples[(instruments.STEP_TOTAL, frozenset())] == 3
    assert samples[(instruments.STALLED_RANKS, frozenset())] == 0

    # -- per-rank trace files merge into one valid trace -----------------
    rank_files = sorted(str(p) for p in tmp_path.glob("trace.rank*.json"))
    assert len(rank_files) == 2
    out = tmp_path / "merged.json"
    merge_traces(rank_files, str(out))
    merged = json.load(open(out))  # json.load()s: the acceptance bar
    names = {e["name"] for e in merged}
    pids = {e["pid"] for e in merged}
    assert pids == {0, 1}, "distinct per-rank pids"
    assert "STEP_DISPATCH" in names          # step events
    assert "BUCKET_RS" in names              # bucket events
    assert any(e.get("ph") == "C" for e in merged)   # counter events
    assert "MEMBERSHIP_RENDEZVOUS" in names  # membership events
    assert any(e["name"] == "process_name" and e["pid"] == 1
               for e in merged)


# ---------------------------------------------------------------------------
# Instrumentation overhead on the hot step path (slow bench smoke).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_instrumentation_overhead_under_2pct(monkeypatch):
    """The acceptance bound: telemetry recording on the hot step path
    costs <2%. Measured directly — the same compiled step driven with
    and without the instrumented wrapper work (recording into the
    registry + deferred gauge stash), on a step big enough (~10 ms+)
    that the bound is meaningful."""
    import jax
    import optax

    import horovod_tpu as hvd_mod
    from horovod_tpu import training
    from horovod_tpu.models.simple import MLP

    monkeypatch.delenv("HOROVOD_METRICS_PORT", raising=False)
    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        model = MLP(features=(1024, 1024, 10))
        tx = hvd_mod.DistributedOptimizer(optax.sgd(0.01))
        rng = np.random.default_rng(0)
        x = rng.standard_normal((256, 512)).astype(np.float32)
        y = rng.integers(0, 10, 256).astype(np.int32)
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0), x[:1])
        step = training.make_train_step(model, tx, donate=False,
                                        telemetry=False)
        instruments_obj = hvd_mod.telemetry.StepInstruments(
            registry=MetricsRegistry())

        def run(n):
            s = state
            t0 = time.perf_counter()
            for _ in range(n):
                s, loss = step(s, x, y)
            jax.block_until_ready(loss)
            return time.perf_counter() - t0

        run(3)  # compile + warm
        iters = 30
        step_s = min(run(iters) for _ in range(3)) / iters

        # the per-step instrumentation work, timed in isolation (an
        # A/B wall-clock diff of whole runs drowns the µs-scale record
        # path in CPU run-to-run noise): everything record_step does,
        # with a live loss array for the deferred gauges
        s2, loss = step(state, x, y)
        jax.block_until_ready(loss)
        reps = 2000
        t0 = time.perf_counter()
        for i in range(reps):
            t1 = time.perf_counter()
            instruments_obj.record_step(
                batch=x.shape[0], dispatch_s=time.perf_counter() - t1,
                loss=loss, grad_norm=loss)
        record_s = (time.perf_counter() - t0) / reps
        overhead = record_s / step_s
        assert overhead < 0.02, \
            f"instrumentation overhead {overhead:.2%} >= 2% " \
            f"(record {record_s * 1e6:.1f} us vs step {step_s * 1e3:.2f} ms)"
    finally:
        hvd_mod.shutdown()


# ---------------------------------------------------------------------------
# Metric-name canonicalization: hvd_* catalogue, legacy aliases, and the
# docs-vs-code drift contract (ISSUE 9 satellites).
# ---------------------------------------------------------------------------


def test_catalogue_is_canonical_hvd_prefixed():
    """One prefix, no drift: every catalogued name is hvd_*, unique, and
    every record-helper constant is in the catalogue."""
    assert len(set(instruments.CATALOGUE)) == len(instruments.CATALOGUE)
    for name in instruments.CATALOGUE:
        assert name.startswith("hvd_"), name
    for canonical, legacy in instruments.LEGACY_ALIASES.items():
        assert canonical in instruments.CATALOGUE
        assert legacy.startswith("horovod_")
        assert legacy.replace("horovod_", "hvd_", 1) == canonical


def test_docs_metric_table_matches_catalogue():
    """The tier-1 drift contract, now a thin wrapper over the hvd-lint
    HVD-METRIC pass (ISSUE 12): the metric tables in
    docs/OBSERVABILITY.md must list EXACTLY the names in
    instruments.CATALOGUE — a metric added (or renamed) in code without
    a catalogue row fails here with its file:line, so does a documented
    ghost (at its table row), and so does a string-literal registration
    of an uncatalogued hvd_* name anywhere in the package (the drift
    the pytest-only version could not see)."""
    import os

    from horovod_tpu.analysis import run_lint

    repo = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                        os.pardir))
    result = run_lint([os.path.join(repo, "horovod_tpu")], root=repo,
                      rules={"HVD-METRIC"},
                      baseline_path=os.path.join(
                          repo, ".hvd-lint-baseline.json"))
    assert result.clean, (
        "metric-name drift (instruments.CATALOGUE is the one "
        "authority — docs/OBSERVABILITY.md and every registration "
        "site must agree):\n"
        + "\n".join(f.format() for f in result.findings)
        + "".join(f"\nstale baseline: {e}"
                  for e in result.stale_baseline))


def test_legacy_aliases_render_on_scrape():
    """Renamed families are still served under their horovod_* names for
    one release: same values, a DEPRECATED HELP line, canonical name
    rendered too. Snapshots stay canonical-only."""
    r = MetricsRegistry()
    r.install_aliases({"hvd_step_total": "horovod_step_total",
                       "hvd_step_latency_seconds":
                           "horovod_step_latency_seconds"})
    r.counter("hvd_step_total", "steps").inc(7)
    r.histogram("hvd_step_latency_seconds").observe(0.5)
    text = r.render_prometheus()
    samples = parse_prometheus(text)
    assert samples[("hvd_step_total", frozenset())] == 7
    assert samples[("horovod_step_total", frozenset())] == 7
    assert ("horovod_step_latency_seconds_count", frozenset()) in samples
    assert "# HELP horovod_step_total DEPRECATED alias of " \
           "hvd_step_total" in text
    snap = r.snapshot()
    assert "hvd_step_total" in snap
    assert "horovod_step_total" not in snap  # aliases are scrape-only


def test_default_registry_serves_legacy_alias_for_live_families():
    """End to end on the process registry: a catalogued family that
    exists renders under both names with equal values."""
    reg = get_registry()
    reg.counter(instruments.STEP_TOTAL, "steps")  # ensure it exists
    samples = parse_prometheus(reg.render_prometheus())
    canonical = samples[(instruments.STEP_TOTAL, frozenset())]
    legacy_name = instruments.LEGACY_ALIASES[instruments.STEP_TOTAL]
    assert samples[(legacy_name, frozenset())] == canonical


def test_build_info_gauge():
    """hvd_build_info: constant 1 with the identity as labels (standard
    Prometheus practice), registered by services when the metrics plane
    is up and embedded in goodput dumps."""
    r = MetricsRegistry()
    instruments.build_info_gauge(registry=r)
    samples = parse_prometheus(r.render_prometheus())
    rows = [(k, v) for k, v in samples.items()
            if k[0] == instruments.BUILD_INFO]
    assert len(rows) == 1
    (name, labels), value = rows[0]
    assert value == 1
    label_names = {kv[0] for kv in labels}
    assert label_names == {"version", "jax", "backend", "world"}
    info = instruments.build_info_labels()
    assert info["backend"] == "cpu"
    assert info["jax"] not in ("", "unknown")
