"""Real-TensorFlow adapter tests (reference coverage classes:
test/test_tensorflow.py:90-995 + test_tensorflow_keras.py — op
correctness across ranks, graph mode under tf.function, registered
gradients, IndexedSlices fallback, Keras-3 optimizer wrapping inside
model.fit, callbacks, save/load_model re-wrap).

Every test body runs in fresh worker processes via ``api.run`` so the
real ``tensorflow`` import never collides with the in-process fake that
``test_tf_adapter.py`` installs into ``sys.modules``. Skipped when
tensorflow isn't importable (it is baked into CI's real-frameworks job
and present in the dev image).
"""

import importlib.machinery

import numpy as np
import pytest

from horovod_tpu.run import api


def _tf_available():
    # PathFinder bypasses sys.modules, so a fake installed by another
    # test module in this process doesn't confuse the probe
    try:
        return importlib.machinery.PathFinder.find_spec(
            "tensorflow") is not None
    except (ImportError, ValueError):
        return False


pytestmark = pytest.mark.skipif(not _tf_available(),
                                reason="tensorflow not installed")

_ENV = {"JAX_PLATFORMS": "cpu", "TF_CPP_MIN_LOG_LEVEL": "3"}


def test_graph_mode_ops_and_gradients_across_ranks():
    """Dense collectives and their registered gradients, eager and under
    tf.function (the reference's mpi_ops.py gradient registrations)."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        out = {}

        # eager allreduce: mean of (r+1) over ranks
        x = tf.constant(np.full(4, r + 1.0, np.float32))
        out["eager_ar"] = hvd.allreduce(x, name="e.ar").numpy().tolist()

        # tf.function allreduce
        @tf.function
        def step(t):
            return hvd.allreduce(t, name="g.ar") * 2.0
        out["graph_ar"] = step(x).numpy().tolist()

        # gradient through allreduce inside tf.function:
        # y = sum(allreduce_avg(v*(r+1))) -> dv = avg-allreduced ones
        # scaled by the local factor (r+1)
        v = tf.Variable(np.ones(3, np.float32))

        @tf.function
        def gstep():
            with tf.GradientTape() as tape:
                y = tf.reduce_sum(hvd.allreduce(v * float(r + 1),
                                                name="g.grad"))
            return tape.gradient(y, v)
        out["ar_grad"] = gstep().numpy().tolist()

        # allgather + its reduce-scatter-shaped gradient: rank r feeds
        # r+1 rows; dy is row-index+1 over the gathered axis, identical
        # on every rank, so grad = 2*dy sliced to this rank's rows
        xg = tf.constant(np.full((r + 1, 2), r + 1.0, np.float32))
        with tf.GradientTape() as tape:
            tape.watch(xg)
            gathered = hvd.allgather(xg, name="e.ag")
            w = tf.reshape(
                tf.range(1.0, tf.cast(tf.shape(gathered)[0], tf.float32)
                         + 1.0), (-1, 1))
            y = tf.reduce_sum(gathered * w)
        out["ag"] = gathered.numpy().tolist()
        out["ag_grad"] = tape.gradient(y, xg).numpy().tolist()

        # broadcast gradient: summed on root, zeros elsewhere
        vb = tf.Variable(np.full(2, r + 1.0, np.float32))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd.broadcast(vb, root_rank=0, name="e.bc"))
        g = tape.gradient(y, vb)
        out["bc_grad"] = g.numpy().tolist()

        # IndexedSlices -> two-allgathers fallback
        s = tf.IndexedSlices(
            tf.constant(np.full((1, 2), r + 1.0, np.float32)),
            tf.constant([r], tf.int64),
            dense_shape=tf.constant([n, 2], tf.int64))
        sa = hvd.allreduce(s, op=hvd.Average, name="e.sp")
        out["sp_idx"] = sa.indices.numpy().tolist()
        out["sp_val"] = sa.values.numpy().tolist()
        return out

    r0, r1 = api.run(fn, np=2, extra_env=_ENV, timeout=600)
    for r, res in enumerate((r0, r1)):
        np.testing.assert_allclose(res["eager_ar"], np.full(4, 1.5))
        np.testing.assert_allclose(res["graph_ar"], np.full(4, 3.0))
        np.testing.assert_allclose(res["ar_grad"], np.full(3, r + 1.0))
        # gathered = rank0's 1 row of 1s then rank1's 2 rows of 2s
        np.testing.assert_allclose(
            res["ag"], [[1, 1], [2, 2], [2, 2]])
        w = np.array([[1.0], [2.0], [3.0]])
        expect = 2 * np.broadcast_to(w, (3, 2))
        rows = slice(0, 1) if r == 0 else slice(1, 3)
        np.testing.assert_allclose(res["ag_grad"], expect[rows])
        np.testing.assert_allclose(
            res["bc_grad"],
            np.full(2, 2.0) if r == 0 else np.zeros(2))
        assert res["sp_idx"] == [0, 1]
        np.testing.assert_allclose(res["sp_val"],
                                   [[0.5, 0.5], [1.0, 1.0]])


def test_keras_fit_synchronizes_ranks():
    """model.fit with DistributedOptimizer + broadcast/metric callbacks:
    ranks start from different weights and see different data, and end
    every epoch bit-identical with identical (averaged) logged loss."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        import horovod_tpu.tensorflow.keras as hvd_keras
        from horovod_tpu.tensorflow.callbacks import (
            BroadcastGlobalVariablesCallback, MetricAverageCallback)
        hvd.init()
        r = hvd.rank()
        tf.keras.utils.set_random_seed(100 + r)  # rank-divergent init

        model = tf.keras.Sequential(
            [tf.keras.Input(shape=(4,)),
             tf.keras.layers.Dense(3, activation="relu"),
             tf.keras.layers.Dense(1)])
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.05, momentum=0.9))
        model.compile(optimizer=opt, loss="mse")

        rng = np.random.default_rng(r)  # rank-disjoint data
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = (x @ np.arange(1.0, 5.0, dtype=np.float32)[:, None]
             + rng.normal(scale=0.01, size=(64, 1)).astype(np.float32))
        hist = model.fit(
            x, y, epochs=2, batch_size=16, verbose=0,
            callbacks=[BroadcastGlobalVariablesCallback(0),
                       MetricAverageCallback()])
        return ([w.tolist() for w in model.get_weights()],
                hist.history["loss"])

    (w0, loss0), (w1, loss1) = api.run(fn, np=2, extra_env=_ENV,
                                       timeout=600)
    for a, b in zip(w0, w1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_allclose(loss0, loss1, rtol=1e-6)


def test_keras_save_load_model_rewraps():
    """.keras round trip: the saved Distributed* optimizer class comes
    back wrapped with its hyperparameters, and the model still trains
    (reference keras/__init__.py:117-150)."""
    def fn():
        import os
        import tempfile
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        import horovod_tpu.tensorflow.keras as hvd_keras
        hvd.init()
        model = tf.keras.Sequential(
            [tf.keras.Input(shape=(4,)),
             tf.keras.layers.Dense(1, use_bias=False)])
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.Adam(learning_rate=0.0125))
        model.compile(optimizer=opt, loss="mse")
        x = np.ones((8, 4), np.float32)
        y = np.ones((8, 1), np.float32)
        model.fit(x, y, epochs=1, batch_size=8, verbose=0)

        path = os.path.join(tempfile.mkdtemp(), "model.keras")
        model.save(path)
        loaded = hvd_keras.load_model(path)
        loaded.fit(x, y, epochs=1, batch_size=8, verbose=0)
        return (type(loaded.optimizer).__name__,
                float(np.asarray(loaded.optimizer.learning_rate)),
                type(loaded.optimizer)._hvd_wrapped.__name__)

    (name, lr, inner), = api.run(fn, np=1, extra_env=_ENV, timeout=600)
    assert name == "DistributedAdam"
    assert inner == "Adam"
    assert abs(lr - 0.0125) < 1e-7


def test_lr_schedule_callbacks_in_fit():
    """LearningRateScheduleCallback staircase + warmup ramp inside a
    real model.fit (reference _keras/callbacks.py:88-185)."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        from horovod_tpu.tensorflow.callbacks import (
            LearningRateScheduleCallback, LearningRateWarmupCallback)
        hvd.init()
        model = tf.keras.Sequential(
            [tf.keras.Input(shape=(2,)),
             tf.keras.layers.Dense(1, use_bias=False)])
        model.compile(
            optimizer=tf.keras.optimizers.SGD(learning_rate=0.1),
            loss="mse")
        x = np.ones((16, 2), np.float32)
        y = np.ones((16, 1), np.float32)

        # staircase halving from epoch 1 onward
        hist = model.fit(
            x, y, epochs=3, batch_size=8, verbose=0,
            callbacks=[LearningRateScheduleCallback(
                lambda epoch: 0.5 ** epoch, start_epoch=1)])
        staircase_lrs = hist.history["lr"]

        # warmup at size 1 must end exactly at the initial lr
        model.compile(
            optimizer=tf.keras.optimizers.SGD(learning_rate=0.1),
            loss="mse")
        hist2 = model.fit(
            x, y, epochs=2, batch_size=8, verbose=0,
            callbacks=[LearningRateWarmupCallback(warmup_epochs=2)])
        warmup_lrs = hist2.history["lr"]
        return staircase_lrs, warmup_lrs

    (staircase, warmup), = api.run(fn, np=1, extra_env=_ENV, timeout=600)
    # epoch 0 untouched (start_epoch=1), then 0.1*0.5^1, 0.1*0.5^2
    np.testing.assert_allclose(staircase, [0.1, 0.05, 0.025], rtol=1e-6)
    # size()==1 -> multiplier is identically 1.0
    np.testing.assert_allclose(warmup, [0.1, 0.1], rtol=1e-6)
