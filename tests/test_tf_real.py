"""Real-TensorFlow adapter tests (reference coverage classes:
test/test_tensorflow.py:90-995 + test_tensorflow_keras.py — op
correctness across ranks, graph mode under tf.function, registered
gradients, IndexedSlices fallback, Keras-3 optimizer wrapping inside
model.fit, callbacks, save/load_model re-wrap).

Every test body runs in fresh worker processes via ``api.run`` so the
real ``tensorflow`` import never collides with the in-process fake that
``test_tf_adapter.py`` installs into ``sys.modules``. Skipped when
tensorflow isn't importable (it is baked into CI's real-frameworks job
and present in the dev image).
"""

import importlib.machinery

import numpy as np
import pytest

from horovod_tpu.run import api


def _tf_available():
    # PathFinder bypasses sys.modules, so a fake installed by another
    # test module in this process doesn't confuse the probe
    try:
        return importlib.machinery.PathFinder.find_spec(
            "tensorflow") is not None
    except (ImportError, ValueError):
        return False


pytestmark = pytest.mark.skipif(not _tf_available(),
                                reason="tensorflow not installed")

_ENV = {"JAX_PLATFORMS": "cpu", "TF_CPP_MIN_LOG_LEVEL": "3"}


def test_graph_mode_ops_and_gradients_across_ranks():
    """Dense collectives and their registered gradients, eager and under
    tf.function (the reference's mpi_ops.py gradient registrations)."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        out = {}

        # eager allreduce: mean of (r+1) over ranks
        x = tf.constant(np.full(4, r + 1.0, np.float32))
        out["eager_ar"] = hvd.allreduce(x, name="e.ar").numpy().tolist()

        # tf.function allreduce
        @tf.function
        def step(t):
            return hvd.allreduce(t, name="g.ar") * 2.0
        out["graph_ar"] = step(x).numpy().tolist()

        # gradient through allreduce inside tf.function:
        # y = sum(allreduce_avg(v*(r+1))) -> dv = avg-allreduced ones
        # scaled by the local factor (r+1)
        v = tf.Variable(np.ones(3, np.float32))

        @tf.function
        def gstep():
            with tf.GradientTape() as tape:
                y = tf.reduce_sum(hvd.allreduce(v * float(r + 1),
                                                name="g.grad"))
            return tape.gradient(y, v)
        out["ar_grad"] = gstep().numpy().tolist()

        # allgather + its reduce-scatter-shaped gradient: rank r feeds
        # r+1 rows; dy is row-index+1 over the gathered axis, identical
        # on every rank, so grad = 2*dy sliced to this rank's rows
        xg = tf.constant(np.full((r + 1, 2), r + 1.0, np.float32))
        with tf.GradientTape() as tape:
            tape.watch(xg)
            gathered = hvd.allgather(xg, name="e.ag")
            w = tf.reshape(
                tf.range(1.0, tf.cast(tf.shape(gathered)[0], tf.float32)
                         + 1.0), (-1, 1))
            y = tf.reduce_sum(gathered * w)
        out["ag"] = gathered.numpy().tolist()
        out["ag_grad"] = tape.gradient(y, xg).numpy().tolist()

        # broadcast gradient: summed on root, zeros elsewhere
        vb = tf.Variable(np.full(2, r + 1.0, np.float32))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(hvd.broadcast(vb, root_rank=0, name="e.bc"))
        g = tape.gradient(y, vb)
        out["bc_grad"] = g.numpy().tolist()

        # IndexedSlices -> two-allgathers fallback
        s = tf.IndexedSlices(
            tf.constant(np.full((1, 2), r + 1.0, np.float32)),
            tf.constant([r], tf.int64),
            dense_shape=tf.constant([n, 2], tf.int64))
        sa = hvd.allreduce(s, op=hvd.Average, name="e.sp")
        out["sp_idx"] = sa.indices.numpy().tolist()
        out["sp_val"] = sa.values.numpy().tolist()
        return out

    r0, r1 = api.run(fn, np=2, extra_env=_ENV, timeout=600)
    for r, res in enumerate((r0, r1)):
        np.testing.assert_allclose(res["eager_ar"], np.full(4, 1.5))
        np.testing.assert_allclose(res["graph_ar"], np.full(4, 3.0))
        np.testing.assert_allclose(res["ar_grad"], np.full(3, r + 1.0))
        # gathered = rank0's 1 row of 1s then rank1's 2 rows of 2s
        np.testing.assert_allclose(
            res["ag"], [[1, 1], [2, 2], [2, 2]])
        w = np.array([[1.0], [2.0], [3.0]])
        expect = 2 * np.broadcast_to(w, (3, 2))
        rows = slice(0, 1) if r == 0 else slice(1, 3)
        np.testing.assert_allclose(res["ag_grad"], expect[rows])
        np.testing.assert_allclose(
            res["bc_grad"],
            np.full(2, 2.0) if r == 0 else np.zeros(2))
        assert res["sp_idx"] == [0, 1]
        np.testing.assert_allclose(res["sp_val"],
                                   [[0.5, 0.5], [1.0, 1.0]])


def test_keras_fit_synchronizes_ranks():
    """model.fit with DistributedOptimizer + broadcast/metric callbacks:
    ranks start from different weights and see different data, and end
    every epoch bit-identical with identical (averaged) logged loss."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        import horovod_tpu.tensorflow.keras as hvd_keras
        from horovod_tpu.tensorflow.callbacks import (
            BroadcastGlobalVariablesCallback, MetricAverageCallback)
        hvd.init()
        r = hvd.rank()
        tf.keras.utils.set_random_seed(100 + r)  # rank-divergent init

        model = tf.keras.Sequential(
            [tf.keras.Input(shape=(4,)),
             tf.keras.layers.Dense(3, activation="relu"),
             tf.keras.layers.Dense(1)])
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.05, momentum=0.9))
        model.compile(optimizer=opt, loss="mse")

        rng = np.random.default_rng(r)  # rank-disjoint data
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = (x @ np.arange(1.0, 5.0, dtype=np.float32)[:, None]
             + rng.normal(scale=0.01, size=(64, 1)).astype(np.float32))
        hist = model.fit(
            x, y, epochs=2, batch_size=16, verbose=0,
            callbacks=[BroadcastGlobalVariablesCallback(0),
                       MetricAverageCallback()])
        return ([w.tolist() for w in model.get_weights()],
                hist.history["loss"])

    (w0, loss0), (w1, loss1) = api.run(fn, np=2, extra_env=_ENV,
                                       timeout=600)
    for a, b in zip(w0, w1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_allclose(loss0, loss1, rtol=1e-6)


def test_keras_save_load_model_rewraps():
    """.keras round trip: the saved Distributed* optimizer class comes
    back wrapped with its hyperparameters, and the model still trains
    (reference keras/__init__.py:117-150)."""
    def fn():
        import os
        import tempfile
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        import horovod_tpu.tensorflow.keras as hvd_keras
        hvd.init()
        model = tf.keras.Sequential(
            [tf.keras.Input(shape=(4,)),
             tf.keras.layers.Dense(1, use_bias=False)])
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.Adam(learning_rate=0.0125))
        model.compile(optimizer=opt, loss="mse")
        x = np.ones((8, 4), np.float32)
        y = np.ones((8, 1), np.float32)
        model.fit(x, y, epochs=1, batch_size=8, verbose=0)

        path = os.path.join(tempfile.mkdtemp(), "model.keras")
        model.save(path)
        loaded = hvd_keras.load_model(path)
        loaded.fit(x, y, epochs=1, batch_size=8, verbose=0)
        return (type(loaded.optimizer).__name__,
                float(np.asarray(loaded.optimizer.learning_rate)),
                type(loaded.optimizer)._hvd_wrapped.__name__)

    (name, lr, inner), = api.run(fn, np=1, extra_env=_ENV, timeout=600)
    assert name == "DistributedAdam"
    assert inner == "Adam"
    assert abs(lr - 0.0125) < 1e-7


def test_allreduce_dtype_sweep_and_fused():
    """Reference test_horovod_allreduce_cpu + _fused (dtype sweep over
    the full supported set, summed, plus many tensors in flight at
    once)."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        out = {}
        dtypes = ["uint8", "int8", "uint16", "int16", "int32", "int64",
                  "float16", "float32", "float64"]
        for dt in dtypes:
            x = tf.constant(np.full((2, 3), r + 1, dtype=dt))
            s = hvd.allreduce(x, op=hvd.Sum, name=f"sweep.{dt}")
            assert s.dtype == tf.as_dtype(dt), (dt, s.dtype)
            out[dt] = np.asarray(s).tolist()
        # fused: 10 tensors of mixed sizes negotiated together
        handles = [hvd.allreduce(
            tf.constant(np.full(i + 1, float(r + i), np.float32)),
            op=hvd.Sum, name=f"fused.{i}") for i in range(10)]
        out["fused"] = [np.asarray(h).tolist() for h in handles]
        # average on floats
        out["avg"] = np.asarray(hvd.allreduce(
            tf.constant(np.full(3, float(r + 1), np.float32)),
            op=hvd.Average, name="sweep.avg")).tolist()
        return out

    results = api.run(fn, np=2, extra_env=_ENV, timeout=600)
    total = sum(range(1, 3))  # ranks contribute 1 and 2
    for res in results:
        for dt, got in res.items():
            if dt == "fused":
                for i, vals in enumerate(got):
                    np.testing.assert_allclose(
                        vals, np.full(i + 1, float(i) + float(i + 1)))
            elif dt == "avg":
                np.testing.assert_allclose(got, np.full(3, 1.5))
            else:
                np.testing.assert_allclose(got, np.full((2, 3), total))


def test_allreduce_cross_rank_mismatch_errors():
    """Reference test_horovod_allreduce_error/_type_error: ranks that
    disagree on shape (or dtype) for the same tensor name must raise a
    mismatch error on every rank, not hang."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r = hvd.rank()
        out = {}
        # shape mismatch: rank0 [17], rank1 [17,17]
        shape = (17,) if r == 0 else (17, 17)
        try:
            hvd.allreduce(tf.constant(np.ones(shape, np.float32)),
                          name="err.shape")
            out["shape"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["shape"] = str(e)
        # dtype mismatch: int32 vs float32
        val = (np.ones(4, np.int32) if r == 0
               else np.ones(4, np.float32))
        try:
            hvd.allreduce(tf.constant(val), name="err.dtype", op=hvd.Sum)
            out["dtype"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["dtype"] = str(e)
        return out

    for res in api.run(fn, np=2, extra_env=_ENV, timeout=600):
        assert "mismatched shapes" in res["shape"], res["shape"]
        assert "mismatched dtypes" in res["dtype"], res["dtype"]


def test_allgather_dtypes_variable_size_and_errors():
    """Reference test_horovod_allgather(+_variable_size/_error/
    _type_error): dtype sweep, rank-varying row counts, and cross-rank
    mismatch errors."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r = hvd.rank()
        out = {}
        for dt in ["uint8", "int32", "int64", "float16", "float32",
                   "float64"]:
            x = tf.constant(np.full((2, 2), r + 1, dtype=dt))
            g = hvd.allgather(x, name=f"ag.{dt}")
            assert g.dtype == tf.as_dtype(dt)
            out[dt] = np.asarray(g).tolist()
        # variable size: rank r contributes r+1 rows
        xv = tf.constant(np.full((r + 1, 2), float(r), np.float32))
        out["var"] = np.asarray(
            hvd.allgather(xv, name="ag.var")).tolist()
        # trailing-dim mismatch must error (only dim 0 may vary)
        bad = (np.ones((2, 3), np.float32) if r == 0
               else np.ones((2, 4), np.float32))
        try:
            hvd.allgather(tf.constant(bad), name="ag.err")
            out["err_shape"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["err_shape"] = str(e)
        badt = (np.ones(4, np.int32) if r == 0
                else np.ones(4, np.float32))
        try:
            hvd.allgather(tf.constant(badt), name="ag.errt")
            out["err_dtype"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["err_dtype"] = str(e)
        return out

    for res in api.run(fn, np=2, extra_env=_ENV, timeout=600):
        for dt in ["uint8", "int32", "int64", "float16", "float32",
                   "float64"]:
            np.testing.assert_allclose(
                res[dt], np.concatenate([np.full((2, 2), 1),
                                         np.full((2, 2), 2)]))
        np.testing.assert_allclose(
            res["var"], np.concatenate([np.zeros((1, 2)),
                                        np.ones((2, 2))]))
        assert "shapes differ beyond the first dim" in res["err_shape"], \
            res["err_shape"]
        assert "mismatched dtypes" in res["err_dtype"], res["err_dtype"]


def test_broadcast_dtypes_and_rank_errors():
    """Reference test_horovod_broadcast(+_error/_rank_error): dtype
    sweep from a non-zero root, out-of-range root raises at enqueue,
    and cross-rank root disagreement raises a mismatch error."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        out = {}
        for dt in ["uint8", "int8", "int32", "int64", "float16",
                   "float32", "float64"]:
            x = tf.constant(np.full((2, 2), r + 5, dtype=dt))
            b = hvd.broadcast(x, root_rank=1, name=f"bc.{dt}")
            assert b.dtype == tf.as_dtype(dt)
            out[dt] = np.asarray(b).tolist()
        # out-of-range root: immediate error, same on every rank
        try:
            hvd.broadcast(tf.constant(np.ones(2, np.float32)),
                          root_rank=n, name="bc.oob")
            out["oob"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["oob"] = str(e)
        # ranks disagree on the root: negotiation must reject
        try:
            hvd.broadcast(tf.constant(np.ones(2, np.float32)),
                          root_rank=r, name="bc.split")
            out["split"] = "NO ERROR"
        except Exception as e:  # noqa: BLE001
            out["split"] = str(e)
        return out

    for res in api.run(fn, np=2, extra_env=_ENV, timeout=600):
        for dt in ["uint8", "int8", "int32", "int64", "float16",
                   "float32", "float64"]:
            np.testing.assert_allclose(res[dt], np.full((2, 2), 6))
        assert "outside" in res["oob"], res["oob"]
        assert "root" in res["split"], res["split"]


def test_gradients_per_dtype():
    """Reference *_grad_cpu classes: allreduce/allgather/broadcast
    gradients checked in float16/float32/float64 through real
    GradientTape."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        out = {}
        for dt in ["float16", "float32", "float64"]:
            # allreduce(average): d/dx sum(allreduce(x)) = averaged ones
            v = tf.Variable(np.ones(3, dtype=dt))
            with tf.GradientTape() as tape:
                y = tf.reduce_sum(hvd.allreduce(v, op=hvd.Average,
                                                name=f"gr.ar.{dt}"))
            out[f"ar.{dt}"] = tape.gradient(y, v).numpy().tolist()

            # allgather: dy = ones over gathered rows -> allreduce-sum
            # sliced back = n * ones
            xg = tf.Variable(np.ones((2, 2), dtype=dt))
            with tf.GradientTape() as tape:
                y = tf.reduce_sum(hvd.allgather(xg, name=f"gr.ag.{dt}"))
            out[f"ag.{dt}"] = tape.gradient(y, xg).numpy().tolist()

            # broadcast: root sums cotangents, others zero
            vb = tf.Variable(np.ones(2, dtype=dt))
            with tf.GradientTape() as tape:
                y = tf.reduce_sum(hvd.broadcast(vb, root_rank=0,
                                                name=f"gr.bc.{dt}"))
            out[f"bc.{dt}"] = tape.gradient(y, vb).numpy().tolist()
        return out

    results = api.run(fn, np=2, extra_env=_ENV, timeout=600)
    for r, res in enumerate(results):
        for dt in ["float16", "float32", "float64"]:
            np.testing.assert_allclose(res[f"ar.{dt}"], np.ones(3))
            np.testing.assert_allclose(res[f"ag.{dt}"],
                                       np.full((2, 2), 2.0))
            np.testing.assert_allclose(
                res[f"bc.{dt}"],
                np.full(2, 2.0) if r == 0 else np.zeros(2))


def test_broadcast_global_variables_hook_tf1_session():
    """The TF1/estimator-era BroadcastGlobalVariablesHook (reference
    tensorflow/__init__.py:194-227): under tf.compat.v1 graph mode +
    MonitoredSession, ranks that initialize differently come out of
    session creation with rank 0's values, and broadcast_global_variables
    works directly on the populated global collection."""
    def fn():
        import numpy as np
        import tensorflow as tf
        tf.compat.v1.disable_eager_execution()
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r = hvd.rank()

        v1 = tf.compat.v1.get_variable(
            "v1", initializer=np.full(3, float(r + 1), np.float32))
        v2 = tf.compat.v1.get_variable(
            "v2", initializer=np.full((2, 2), float(10 * (r + 1)),
                                      np.float32))
        hook = hvd.BroadcastGlobalVariablesHook(0)
        with tf.compat.v1.train.MonitoredSession(
                hooks=[hook]) as sess:
            a, b = sess.run([v1, v2])
        return a.tolist(), b.tolist()

    for (a, b) in api.run(fn, np=2, extra_env=_ENV, timeout=600):
        np.testing.assert_allclose(a, np.full(3, 1.0))
        np.testing.assert_allclose(b, np.full((2, 2), 10.0))


def test_compression_fp16_wire():
    """Reference test_compression_fp16: fp16 wire compression round-trip
    preserves dtype and averages correctly."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        hvd.init()
        r = hvd.rank()
        x = tf.constant(np.full(8, float(r + 1), np.float32))
        out = hvd.allreduce(x, op=hvd.Average, name="comp",
                            compression=hvd.Compression.fp16)
        assert out.dtype == tf.float32
        return np.asarray(out).tolist()

    for res in api.run(fn, np=2, extra_env=_ENV, timeout=600):
        np.testing.assert_allclose(res, np.full(8, 1.5), rtol=1e-3)


def test_lr_schedule_callbacks_in_fit():
    """LearningRateScheduleCallback staircase + warmup ramp inside a
    real model.fit (reference _keras/callbacks.py:88-185)."""
    def fn():
        import numpy as np
        import tensorflow as tf
        import horovod_tpu.tensorflow as hvd
        from horovod_tpu.tensorflow.callbacks import (
            LearningRateScheduleCallback, LearningRateWarmupCallback)
        hvd.init()
        model = tf.keras.Sequential(
            [tf.keras.Input(shape=(2,)),
             tf.keras.layers.Dense(1, use_bias=False)])
        model.compile(
            optimizer=tf.keras.optimizers.SGD(learning_rate=0.1),
            loss="mse")
        x = np.ones((16, 2), np.float32)
        y = np.ones((16, 1), np.float32)

        # staircase halving from epoch 1 onward
        hist = model.fit(
            x, y, epochs=3, batch_size=8, verbose=0,
            callbacks=[LearningRateScheduleCallback(
                lambda epoch: 0.5 ** epoch, start_epoch=1)])
        staircase_lrs = hist.history["lr"]

        # warmup at size 1 must end exactly at the initial lr
        model.compile(
            optimizer=tf.keras.optimizers.SGD(learning_rate=0.1),
            loss="mse")
        hist2 = model.fit(
            x, y, epochs=2, batch_size=8, verbose=0,
            callbacks=[LearningRateWarmupCallback(warmup_epochs=2)])
        warmup_lrs = hist2.history["lr"]
        return staircase_lrs, warmup_lrs

    (staircase, warmup), = api.run(fn, np=1, extra_env=_ENV, timeout=600)
    # epoch 0 untouched (start_epoch=1), then 0.1*0.5^1, 0.1*0.5^2
    np.testing.assert_allclose(staircase, [0.1, 0.05, 0.025], rtol=1e-6)
    # size()==1 -> multiplier is identically 1.0
    np.testing.assert_allclose(warmup, [0.1, 0.1], rtol=1e-6)
