"""Driver/task services: HMAC auth + NIC discovery.

Mirrors the reference's service-layer test intent (driver/task
registration, interface matching, secret checks) with multi-NIC fakes,
per VERDICT round-1 item 4.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from horovod_tpu.run import secret
from horovod_tpu.run.discovery import (DriverService, PingServer, TaskAgent,
                                       discover, host_hash,
                                       local_interfaces, probe)
from horovod_tpu.run.rendezvous import (AUTH_HEADER, KVStoreServer, kv_get,
                                        kv_put, kv_wait)


def test_secret_sign_verify():
    key = secret.make_secret_key()
    sig = secret.sign(key, "PUT", "/a/b", b"payload")
    assert secret.verify(key, "PUT", "/a/b", b"payload", sig)
    assert not secret.verify(key, "PUT", "/a/b", b"tampered", sig)
    assert not secret.verify(key, "GET", "/a/b", b"payload", sig)
    assert not secret.verify(key, "PUT", "/a/c", b"payload", sig)
    assert not secret.verify(key, "PUT", "/a/b", b"payload", None)
    key2 = secret.decode_key(secret.encode_key(key))
    assert key2 == key


def test_kv_rejects_unauthenticated():
    key = secret.make_secret_key()
    kv = KVStoreServer(auth_key=key)
    port = kv.start()
    try:
        # unsigned PUT → 403, store untouched
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/x", data=b"evil", method="PUT")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 403
        assert kv.get("x") is None

        # wrong-key PUT → 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            kv_put("127.0.0.1", port, "x", b"evil",
                   auth_key=secret.make_secret_key())
        assert ei.value.code == 403

        # signed round trip works
        kv_put("127.0.0.1", port, "x", b"good", auth_key=key)
        assert kv_get("127.0.0.1", port, "x", auth_key=key) == b"good"

        # unsigned GET is rejected even for existing keys
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/x", timeout=5)
        assert ei.value.code == 403
    finally:
        kv.stop()


def test_kv_open_when_unkeyed():
    kv = KVStoreServer()
    port = kv.start()
    try:
        kv_put("127.0.0.1", port, "k", b"v")
        assert kv_get("127.0.0.1", port, "k") == b"v"
    finally:
        kv.stop()


def test_ping_server_and_probe():
    key = secret.make_secret_key()
    srv = PingServer("task-0", key, host="127.0.0.1")
    try:
        addrs = {"lo": [("127.0.0.1", srv.port)]}
        local = {"lo": [("127.0.0.1", srv.port)]}
        got = probe(addrs, key, "task-0", match_intf=True,
                    local_addrs=local, timeout=2.0)
        assert got == {"lo": [("127.0.0.1", srv.port)]}

        # wrong service name → filtered
        assert probe(addrs, key, "task-9", local_addrs=local,
                     timeout=2.0) == {}

        # wrong key → server drops the frame, nothing reachable
        assert probe(addrs, secret.make_secret_key(), "task-0",
                     local_addrs=local, timeout=1.0, retries=1) == {}
    finally:
        srv.shutdown()


def test_probe_match_intf_filters_nat():
    """A candidate reached through a DIFFERENT interface than claimed is
    rejected (reference network.py match_intf), simulated by giving the
    prober a local view where 'fakenic' does not own 127.0.0.1."""
    key = secret.make_secret_key()
    srv = PingServer("task-0", key, host="127.0.0.1")
    try:
        addrs = {"fakenic": [("127.0.0.1", srv.port)]}
        local = {"fakenic": [("192.0.2.1", 0)]}  # TEST-NET, not ours
        assert probe(addrs, key, "task-0", match_intf=True,
                     local_addrs=local, timeout=2.0) == {}
    finally:
        srv.shutdown()


def test_local_interfaces_real():
    ifs = local_interfaces(port=1234)
    assert "lo" in ifs
    assert ("127.0.0.1", 1234) in ifs["lo"]
    with pytest.raises(RuntimeError):
        local_interfaces(nic="does-not-exist-0")


def test_discovery_end_to_end_multi_nic():
    """3 fake hosts, each with a routable 'eth0' (loopback-backed) and an
    unroutable 'docker0'; the ring probe + intersection must elect
    exactly eth0, and host hashes must group ranks."""
    key = secret.make_secret_key()
    kv = KVStoreServer(auth_key=key)
    port = kv.start()
    try:
        n = 3
        fake = {"eth0": [("127.0.0.1", 0)],
                "docker0": [("192.0.2.77", 0)]}  # unroutable TEST-NET
        agents = [TaskAgent(i, n, "127.0.0.1", port, key,
                            addresses=dict(fake),
                            host_salt="hostA" if i < 2 else "hostB")
                  for i in range(n)]
        try:
            for a in agents:
                a.register()
            driver = DriverService(n, "127.0.0.1", port, key)
            regs = driver.wait_for_registrations(timeout=20)
            assert set(regs) == {0, 1, 2}
            threads = [threading.Thread(target=a.run_ring_probe,
                                        kwargs={"timeout": 20})
                       for a in agents]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            common = driver.wait_for_probes(timeout=20)
            assert common == ["eth0"]

            groups = driver.host_hash_indices(regs)
            assert sorted(groups.values()) == [[0, 1], [2]]
            assert host_hash("hostA") != host_hash("hostB")

            # every task can read the verdict back
            assert agents[0].common_interfaces(timeout=5) == ["eth0"]
        finally:
            for a in agents:
                a.shutdown()
    finally:
        kv.stop()


def test_discover_helper():
    key = secret.make_secret_key()
    kv = KVStoreServer(auth_key=key)
    port = kv.start()
    try:
        common, groups = discover(2, "127.0.0.1", port, key,
                                  host_salts={0: "h0", 1: "h1"})
        # real interfaces on this machine: loopback is always mutual
        assert "lo" in common
        assert sorted(groups.values()) == [[0], [1]]
    finally:
        kv.stop()


def test_ssh_secret_not_in_argv():
    """The per-run key must never appear in the ssh command line; it ships
    over stdin instead (world-readable /proc/*/cmdline)."""
    from horovod_tpu.run import launcher
    key_hex = secret.encode_key(secret.make_secret_key())
    env = {secret.SECRET_ENV: key_hex, "HOROVOD_RANK": "0"}
    cmd, proc_env, payload = launcher.build_command(
        "remotehost", ["python", "train.py"], env)
    joined = " ".join(cmd)
    assert key_hex not in joined
    assert payload == (key_hex + "\n").encode()
    assert f"read -r {secret.SECRET_ENV}" in joined
    assert "HOROVOD_RANK=0" in joined

    # local slots keep it in the process env (not in any argv)
    cmd2, env2, payload2 = launcher.build_command(
        "localhost", ["python", "train.py"], env)
    assert payload2 is None and env2[secret.SECRET_ENV] == key_hex


def test_driver_liveness_aborts_on_dead_task():
    key = secret.make_secret_key()
    kv = KVStoreServer(auth_key=key)
    port = kv.start()
    try:
        driver = DriverService(1, "127.0.0.1", port, key,
                               liveness=lambda: False)
        with pytest.raises(RuntimeError, match="discovery task exited"):
            driver.wait_for_registrations(timeout=30)
    finally:
        kv.stop()
