"""Real-pyspark smoke (VERDICT r3 #8): ``run_on_cluster`` through the
REAL SparkBackend against a ``local[2]`` SparkContext — the same shape
the reference proves with a local SparkSession
(``/root/reference/horovod/spark/__init__.py:101-236``,
``test/test_spark.py``).

Runs in the CI job that installs pyspark; skips where pyspark is absent
(this image has no network). The stub-backed tests in
``tests/test_cluster.py`` keep in-image coverage of the same code path.
"""

import importlib.machinery

import pytest


def _has_pyspark():
    try:
        return importlib.machinery.PathFinder.find_spec(
            "pyspark") is not None
    except (ImportError, ValueError):
        return False


pytestmark = pytest.mark.skipif(not _has_pyspark(),
                                reason="pyspark not installed")


@pytest.fixture(scope="module")
def sc():
    import pyspark
    conf = pyspark.SparkConf().setMaster("local[2]").setAppName(
        "hvd-tpu-real-spark-test")
    ctx = pyspark.SparkContext(conf=conf)
    yield ctx
    ctx.stop()


def _train(value):
    """Runs in each Spark-launched worker process."""
    import horovod_tpu as hvd
    hvd.init()
    import numpy as np
    out = hvd.allreduce(np.full(4, float(hvd.rank() + 1), np.float32),
                        name="spark.ar", op="sum")
    return {"rank": hvd.rank(), "size": hvd.size(),
            "sum": out.tolist(), "value": value}


def test_run_on_cluster_through_real_spark(sc):
    from horovod_tpu.run.cluster import SparkBackend, run_on_cluster

    results = run_on_cluster(_train, args=(42,), num_proc=2,
                             backend=SparkBackend(sc))
    assert sorted(r["rank"] for r in results) == [0, 1]
    for r in results:
        assert r["size"] == 2
        assert r["value"] == 42
        assert r["sum"] == [3.0, 3.0, 3.0, 3.0]


def test_spark_failure_propagates(sc):
    from horovod_tpu.run.cluster import SparkBackend, run_on_cluster

    def boom(_):
        raise RuntimeError("intentional worker failure")

    with pytest.raises(RuntimeError):
        run_on_cluster(boom, args=(0,), num_proc=2,
                       backend=SparkBackend(sc))
