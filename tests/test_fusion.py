"""Fusion-buffer tests (reference semantics: controller.cc:639-769
FuseResponses + fused allreduce value checks in test_tensorflow.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_api
from horovod_tpu.ops import collective, fusion


def test_plan_buckets_groups_by_dtype():
    leaves = [np.ones((4,), np.float32), np.ones((2,), np.int32),
              np.ones((8,), np.float32)]
    buckets = fusion.plan_buckets(leaves, threshold_bytes=1 << 20)
    dtypes = sorted(str(b.dtype) for b in buckets)
    assert dtypes == ["float32", "int32"]
    f32 = next(b for b in buckets if str(b.dtype) == "float32")
    assert f32.leaf_indices == (0, 2)
    assert f32.sizes == (4, 8)


def test_plan_buckets_respects_threshold():
    leaves = [np.ones((100,), np.float32) for _ in range(10)]  # 400 B each
    buckets = fusion.plan_buckets(leaves, threshold_bytes=1000)
    assert len(buckets) == 5  # 2 leaves per 1000-B bucket
    # a single oversized leaf still gets a bucket
    big = [np.ones((1000,), np.float32)]
    assert len(fusion.plan_buckets(big, threshold_bytes=100)) == 1


def test_plan_buckets_reverse_traversal_order():
    """reverse=True packs back-to-front: backprop readiness order (the
    bucket the last layer's grads land in comes first)."""
    leaves = [np.ones((4,), np.float32), np.ones((8,), np.float32),
              np.ones((2,), np.float32)]
    buckets = fusion.plan_buckets(leaves, threshold_bytes=16, reverse=True)
    assert [b.leaf_indices for b in buckets] == [(2,), (1,), (0,)]
    # forward order for contrast
    fwd = fusion.plan_buckets(leaves, threshold_bytes=16)
    assert fwd[0].leaf_indices[0] == 0


def test_bucket_schedule_pads_to_world():
    leaves = [np.ones((5,), np.float32), np.ones((6,), np.float32)]
    sched = fusion.bucket_schedule(leaves, world=8, threshold_bytes=1 << 20,
                                   axes=("data",))
    assert len(sched.buckets) == 1
    assert sched.padded_sizes == (16,)  # 11 -> 16 (multiple of 8)
    assert sched.shard_sizes == (2,)
    assert sched.axes == ("data",)


def test_bucket_schedule_hierarchical_reorders_ici_first():
    leaves = [np.ones((8,), np.float32)]
    sched = fusion.bucket_schedule(leaves, world=8, threshold_bytes=1 << 20,
                                   axes=("dcn", "data"), hierarchical=True)
    assert sched.axes == ("data", "dcn")  # DCN stage moves 1/ici the bytes


def test_bucket_rs_ag_roundtrip_matches_fused_allreduce(hvd, n_devices):
    """reduce_scatter_bucket + all_gather_bucket + unpack == the fused
    allreduce of the same tree (the pipeline's exchange is the same
    reduction, split at the shard boundary)."""
    tree_template = [np.ones((5,), np.float32), np.ones((3, 2), np.float32)]

    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        leaves = [(r + 1) * jnp.ones((5,)), (r + 2) * jnp.ones((3, 2))]
        sched = fusion.bucket_schedule(leaves, world=n_devices,
                                       threshold_bytes=1 << 20)
        out = [None, None]
        for i in range(len(sched.buckets)):
            shard = fusion.reduce_scatter_bucket(sched, i, leaves,
                                                 op=hvd_api.Average)
            flat = fusion.all_gather_bucket(sched, i, shard)
            for j, arr in fusion.unpack_bucket(sched, i, flat,
                                               leaves).items():
                out[j] = arr
        ref = fusion.fused_allreduce(list(leaves), op=hvd_api.Average)
        return out, ref

    specs = [P() for _ in tree_template]
    out, ref = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                             out_specs=(specs, specs), check_vma=False)()
    for o, e in zip(out, ref):
        np.testing.assert_allclose(np.asarray(o), np.asarray(e), rtol=1e-6)


def test_fused_allreduce_matches_unfused(hvd, n_devices):
    tree_shapes = {"w": (3, 4), "b": (4,), "scale": ()}

    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        tree = {k: (r + 1) * jnp.ones(s) for k, s in tree_shapes.items()}
        fused = fusion.fused_allreduce(tree, op=hvd_api.Average)
        unfused = jax.tree_util.tree_map(
            lambda x: collective.allreduce(x, op=hvd_api.Average), tree)
        return fused, unfused

    specs = {k: P() for k in tree_shapes}
    fused, unfused = jax.shard_map(
        f, mesh=hvd.mesh(), in_specs=(),
        out_specs=(specs, specs), check_vma=False)()
    for k in tree_shapes:
        np.testing.assert_allclose(fused[k], unfused[k], rtol=1e-6)
        expected = np.mean(np.arange(1, n_devices + 1))
        np.testing.assert_allclose(fused[k], expected * np.ones(
            tree_shapes[k]), rtol=1e-6)


def test_fused_allreduce_mixed_dtypes(hvd, n_devices):
    def f():
        r = collective.mesh_rank()
        tree = {"f32": (r + 1).astype(jnp.float32) * jnp.ones((5,)),
                "bf16": (r + 1).astype(jnp.bfloat16) * jnp.ones(
                    (7,), jnp.bfloat16)}
        return fusion.fused_allreduce(tree, op=hvd_api.Sum)

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                        out_specs={"f32": P(), "bf16": P()},
                        check_vma=False)()
    total = sum(range(1, n_devices + 1))
    np.testing.assert_allclose(out["f32"], total * np.ones((5,)))
    assert out["bf16"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out["bf16"], np.float32),
                               total * np.ones((7,)), rtol=1e-1)


def test_fused_allreduce_tiny_threshold_still_correct(hvd, n_devices):
    """Many buckets (threshold smaller than single leaves) == same values."""

    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        tree = [r * jnp.ones((16,)) + i for i in range(6)]
        return fusion.fused_allreduce(tree, op=hvd_api.Average,
                                      threshold_bytes=8)

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                        out_specs=[P()] * 6, check_vma=False)()
    mean_r = np.mean(np.arange(n_devices))
    for i in range(6):
        np.testing.assert_allclose(out[i], mean_r + i, rtol=1e-6)


def test_fused_allreduce_compressed(hvd, n_devices):
    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        tree = {"a": (r + 1) * jnp.ones((4,)), "b": (r + 1) * jnp.ones((2,))}
        return fusion.fused_allreduce(tree, op=hvd_api.Average,
                                      compression=hvd_api.Compression.fp16)

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                        out_specs={"a": P(), "b": P()}, check_vma=False)()
    expected = np.mean(np.arange(1, n_devices + 1))
    np.testing.assert_allclose(out["a"], expected, rtol=1e-2)
    assert out["a"].dtype == jnp.float32


def test_fused_allreduce_hierarchical_on_2d_mesh(hvd2d, n_devices):
    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        tree = {"w": (r + 1) * jnp.ones((9,))}
        return fusion.fused_allreduce(tree, op=hvd_api.Average,
                                      hierarchical=True)

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(),
                        out_specs={"w": P()}, check_vma=False)()
    expected = np.mean(np.arange(1, n_devices + 1))
    np.testing.assert_allclose(out["w"], expected * np.ones((9,)), rtol=1e-6)


def test_hierarchical_rs_ag_pin_the_schedule_contract(hvd2d, n_devices):
    """parallel.hierarchical_reducescatter/allgather and the bucket
    schedule's reordered-axes composition (collective.reducescatter/
    allgather over ('data','dcn')) are two spellings of ONE chunk-
    ownership contract — rank mesh_rank(('data','dcn')) owns chunk r.
    Pinned here so they cannot drift apart: the ICI-first DCN-bytes
    economics in docs/PERFORMANCE.md assumes they agree."""
    from horovod_tpu.parallel import hierarchical as hier

    def f():
        r = collective.mesh_rank(("data", "dcn")).astype(jnp.float32)
        x = (r + 1.0) * (jnp.arange(n_devices * 2, dtype=jnp.float32) + 1.0)
        a = hier.hierarchical_reducescatter(x, ici_axes=("data",),
                                            dcn_axis="dcn", op="average")
        b = collective.reducescatter(x, op=hvd_api.Average,
                                     axes=("data", "dcn"))
        ga = hier.hierarchical_allgather(a, ici_axes=("data",),
                                         dcn_axis="dcn")
        gb = collective.allgather(b, axes=("data", "dcn"))
        return a, b, ga, gb

    shard_spec = P(("data", "dcn"))
    a, b, ga, gb = jax.shard_map(
        f, mesh=hvd2d.mesh(), in_specs=(),
        out_specs=(shard_spec, shard_spec, P(), P()), check_vma=False)()
    # position-dependent payload: the full reduction is mean(r+1)*(i+1),
    # so both the values AND the chunk ownership must agree
    expected = (np.mean(np.arange(1, n_devices + 1))
                * (np.arange(n_devices * 2) + 1.0))
    np.testing.assert_allclose(np.asarray(a), expected, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ga), expected, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb), rtol=1e-6)


def test_fused_allreduce_hierarchical_adasum(hvd2d, n_devices, rng):
    """DistributedOptimizer(op=Adasum, hierarchical=True) semantics: the
    fused hierarchical branch must run the 2-level Adasum COMPOSITE
    (per-chunk Adasum across dcn), never a cross-slice psum."""
    from horovod_tpu.ops import adasum
    data_size = n_devices // 2
    vals = rng.standard_normal((n_devices, 10)).astype(np.float32)
    expected = adasum.hierarchical_adasum_np(
        vals.reshape(2, data_size, 10))

    def f():
        tree = {"g": jnp.asarray(vals)[
            collective.mesh_rank(("dcn", "data"))]}
        return fusion.fused_allreduce(tree, op=hvd_api.Adasum,
                                      axes=("dcn", "data"),
                                      hierarchical=True)

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(),
                        out_specs={"g": P()}, check_vma=False)()
    np.testing.assert_allclose(np.asarray(out["g"]), expected,
                               rtol=1e-4, atol=1e-5)


def test_fused_allreduce_hierarchical_min_falls_through(hvd2d, n_devices):
    """Min/Max have no RS->AR->AG form: with hierarchical=True they must
    fall through to the flat path and stay CORRECT (not raise, not
    silently sum)."""
    def f():
        r = collective.mesh_rank(("dcn", "data")).astype(jnp.float32)
        return fusion.fused_allreduce({"x": r + jnp.zeros((3,))},
                                      op=hvd_api.Min,
                                      axes=("dcn", "data"),
                                      hierarchical=True)

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(),
                        out_specs={"x": P()}, check_vma=False)()
    np.testing.assert_allclose(out["x"], np.zeros((3,)))


def test_fused_allreduce_empty_tree(hvd):
    assert fusion.fused_allreduce({}) == {}


def test_autotune_fusion_threshold(hvd):
    """Timed-trial bucket autotune: returns a candidate, times every
    candidate, and installs the winner as the process default — or
    abstains WITH a reason when the trials carry no rankable signal
    (unresolved upper bounds near the argmin on a loaded CI box)."""
    tree = {"a": jnp.ones((512,)), "b": jnp.ones((256,)),
            "c": jnp.ones((64, 8))}
    candidates = [1 << 10, 1 << 20]
    best, timings = fusion.autotune_fusion_threshold(
        tree, candidates=candidates, trials=2)
    assert set(timings) == set(candidates)
    assert all(t > 0 for t in timings.values())
    from horovod_tpu import basics
    if best is None:
        # abstention is only legal with a reason and an unresolved bound
        assert timings.abstain_reason
        assert any(getattr(t, "upper_bound", False)
                   for t in timings.values())
        return
    assert best in candidates
    assert timings.abstain_reason is None
    assert basics._state.config.fusion_threshold == best
    # the tuned default now drives fused_allreduce's bucket planning
    out = jax.shard_map(
        lambda t: fusion.fused_allreduce(t, op=hvd_api.Sum),
        mesh=hvd.mesh(), in_specs=(jax.tree_util.tree_map(
            lambda _: P(), tree),),
        out_specs=jax.tree_util.tree_map(lambda _: P(), tree),
        check_vma=False)(tree)
    np.testing.assert_allclose(out["a"], 8.0 * np.ones((512,)), rtol=1e-6)


def test_autotune_uses_shared_timing_primitive(hvd, monkeypatch):
    """The autotuner must time through utils.benchmarks.slope_window
    (the readback-slope protocol) — block_until_ready does not
    synchronize through the async tunnel (BENCH_NOTES.md r4) — and must
    thread a fresh salt into every trial call so the tunnel's pure-call
    memoization cannot serve a cached result."""
    from horovod_tpu.utils import benchmarks

    calls = {"n": 0, "salts": []}
    real = benchmarks.slope_window

    def spying(step_once, state, iters, base_iters=2):
        calls["n"] += 1
        seen = []
        calls["salts"].append(seen)

        def spy_step(st):
            seen.append(float(st[1]))
            return step_once(st)

        return real(spy_step, state, iters, base_iters=base_iters)

    monkeypatch.setattr(benchmarks, "slope_window", spying)
    tree = {"a": jnp.ones((64,))}
    fusion.autotune_fusion_threshold(tree, candidates=[1 << 10, 1 << 20],
                                     trials=2, apply=False)
    # at least one slope window per candidate (inverted-window retries —
    # common for these noise-floor-sized trials — may add more)
    assert calls["n"] >= 2
    # every trial call within a window saw a distinct salt (fresh inputs,
    # no memoization)
    for seen in calls["salts"]:
        assert len(set(seen)) == len(seen)


def test_autotune_retries_inverted_windows(hvd, monkeypatch):
    """An inverted slope window is an upper BOUND, not a measurement:
    the autotuner must re-run the trial with 4x-escalated iters instead
    of ranking candidates on it, and surface both the retry count and
    the escalation count on the returned timings (VERDICT r5 #2; the
    BENCH_r05 noise tail was bounds leaking into the ranking because
    doubling crept up too slowly)."""
    from horovod_tpu.utils import benchmarks

    seen = {"iters": []}

    def fake(step_once, state, iters, base_iters=2):
        seen["iters"].append(iters)
        # every first (trials-length) window inverts; the 4x escalation
        # clears the noise floor on its first retry
        return benchmarks.WindowTime(0.1 * iters,
                                     upper_bound=(iters == 2)), state

    monkeypatch.setattr(benchmarks, "slope_window", fake)
    tree = {"a": jnp.ones((64,))}
    best, timings = fusion.autotune_fusion_threshold(
        tree, candidates=[1 << 10, 1 << 20], trials=2, apply=False)
    assert timings.retried == 2  # both candidates hit the inversion
    # retries escalate iters x4 (bounded), one escalation per candidate
    assert seen["iters"] == [2, 8, 2, 8]
    assert timings.slope_window_escalations == 2
    # and the recorded values are normalized back to per-`trials` cost,
    # unflagged (the retry measured cleanly)
    for v in timings.values():
        assert not getattr(v, "upper_bound", False)
        assert v == pytest.approx(0.1 * 2)


def test_autotune_escalation_is_bounded_and_counted(hvd, monkeypatch):
    """A trial that NEVER resolves must stop escalating at the 16x
    bound (two 4x escalations) and keep its upper_bound flag — the
    abstention gate, not endless retrying, owns the hopeless case. A
    cleanly measured run reports zero escalations."""
    from horovod_tpu.utils import benchmarks

    seen = {"iters": []}

    def always_bounded(step_once, state, iters, base_iters=2):
        seen["iters"].append(iters)
        return benchmarks.WindowTime(0.1 * iters, upper_bound=True), state

    monkeypatch.setattr(benchmarks, "slope_window", always_bounded)
    tree = {"a": jnp.ones((64,))}
    best, timings = fusion.autotune_fusion_threshold(
        tree, candidates=[1 << 10], trials=2, apply=False)
    assert best is None  # unresolved bound at the argmin -> abstain
    assert seen["iters"] == [2, 8, 32]  # trials, x4, x16 — then stop
    assert timings.slope_window_escalations == 2

    seen["iters"].clear()

    def clean(step_once, state, iters, base_iters=2):
        seen["iters"].append(iters)
        return benchmarks.WindowTime(0.1 * iters), state

    monkeypatch.setattr(benchmarks, "slope_window", clean)
    best, timings = fusion.autotune_fusion_threshold(
        tree, candidates=[1 << 10], trials=2, apply=False)
    assert timings.slope_window_escalations == 0
    assert timings.retried == 0


def test_autotune_abstains_at_world_one():
    """With one participant over the reduction axes the fused
    collectives are no-ops: the tuner must return (None, timings) with
    a reason instead of installing a noise argmin (VERDICT r5 Weak #2).
    A single-device mesh is the realistic single-chip dev box."""
    from horovod_tpu.parallel import mesh as mesh_lib
    old = mesh_lib._current_mesh
    mesh_lib.set_mesh(mesh_lib.build_mesh(devices=[jax.devices()[0]]))
    try:
        tree = {"a": jnp.ones((64,))}
        best, timings = fusion.autotune_fusion_threshold(
            tree, candidates=[1 << 10, 1 << 20], trials=2)
    finally:
        mesh_lib.set_mesh(old)
    assert best is None
    assert "world size 1" in timings.abstain_reason
    assert timings == {}  # no trials were burned on a no-signal setup


def test_autotune_abstains_on_unresolved_bounds(hvd, monkeypatch):
    """A candidate whose timing is STILL an inverted-window upper bound
    after retries, and which sits within tolerance of the argmin, makes
    the ranking unsound (its true time could be anywhere at or below the
    bound): the tuner must abstain and leave the configured default
    untouched."""
    from horovod_tpu import basics
    from horovod_tpu.utils import benchmarks

    def always_bounded(step_once, state, iters, base_iters=2):
        return benchmarks.WindowTime(0.1 * iters, upper_bound=True), state

    monkeypatch.setattr(benchmarks, "slope_window", always_bounded)
    before = basics._state.config.fusion_threshold
    tree = {"a": jnp.ones((64,))}
    best, timings = fusion.autotune_fusion_threshold(
        tree, candidates=[1 << 10, 1 << 20], trials=2)
    assert best is None
    assert "upper bounds" in timings.abstain_reason
    assert all(t.upper_bound for t in timings.values())
    assert basics._state.config.fusion_threshold == before  # nothing installed


def test_no_block_until_ready_in_package():
    """Round-4 lesson, enforced: jax.block_until_ready does not
    synchronize through an async execution tunnel, so NO code in the
    package may use it for timing or completion. The only allowed
    mention is the benchmarks.py docstring that documents the gotcha."""
    import pathlib

    import horovod_tpu

    pkg = pathlib.Path(horovod_tpu.__file__).parent
    offenders = []
    for path in pkg.rglob("*.py"):
        text = path.read_text()
        if "block_until_ready(" in text:
            offenders.append(str(path.relative_to(pkg)))
    assert offenders == [], (
        f"block_until_ready call found in {offenders}; use "
        "utils.benchmarks.sync/slope_window instead")


def test_one_collective_per_bucket(hvd):
    """The fused path must emit exactly one all-reduce per dtype bucket
    (the whole point of fusion — reference fuses to one NCCL call per
    cycle, nccl_operations.cc:55-105)."""

    def f():
        tree = [jnp.ones((8,)) * i for i in range(10)]
        return fusion.fused_allreduce(tree, op=hvd_api.Sum)

    fn = jax.jit(jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                               out_specs=[P()] * 10, check_vma=False))
    hlo = fn.lower().compile().as_text()
    # count all-reduce instruction DEFINITIONS (an op's result is
    # referenced by every consumer line, so a substring count scales with
    # the number of unpacked leaves, not collectives)
    import re
    defs = re.findall(r"= \S+ all-reduce(?:-start)?\(", hlo)
    assert len(defs) <= 2  # one bucket (plus possible fusion)
