"""Worker body for the elastic integration tests (test_elastic.py).

Runs a tiny deterministic SGD loop (scalar quadratic) under the elastic
state/commit contract, publishing heartbeats through a StallInspector
progress hook. Behavior is driven by env/argv so the test can simulate a
host that keeps dying:

    argv: <ckpt_dir> <log_path> <num_steps> [die_host [die_until_epoch]]

A worker whose HOROVOD_HOSTNAME == die_host and epoch < die_until_epoch
dies after committing one step — the "worker killed mid-training"
scenario. HVD_ELASTIC_TEST_DIE picks how: ``kill`` (default) SIGKILLs
itself; ``evict`` arms the graceful-eviction handler
(elastic/preempt.py) and SIGTERMs itself, so the death runs the planned
drain — announce, bounded commit, EXIT_RENDEZVOUS. Only rank 0 appends
to the loss log, so the log is the single continuous loss trajectory
across incarnations.
"""

import json
import os
import signal
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from horovod_tpu import elastic  # noqa: E402
from horovod_tpu.runtime.stall import StallInspector  # noqa: E402

TARGET = 3.0
LR = 0.2


def main():
    ckpt_dir, log_path, num_steps = (sys.argv[1], sys.argv[2],
                                     int(sys.argv[3]))
    die_host = sys.argv[4] if len(sys.argv) > 4 else None
    die_until_epoch = int(sys.argv[5]) if len(sys.argv) > 5 else 0

    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    host = os.environ.get("HOROVOD_HOSTNAME", "localhost")
    epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0"))

    ctx = elastic.init_worker_context()
    inspector = StallInspector(warning_time=600)
    elastic.attach_progress_reporter(inspector, context=ctx)

    state = elastic.JaxState(directory=ckpt_dir,
                             params={"w": np.float64(0.0)},
                             step=np.int64(0))
    entry_step = {"v": None}

    die_mode = os.environ.get("HVD_ELASTIC_TEST_DIE", "kill")
    if die_mode == "evict":
        # the graceful counterpart of the SIGKILL below: SIGTERM lands in
        # this handler, which announces the doomed host on the KV and
        # force-commits inside the grace window before exiting
        from horovod_tpu.elastic import preempt
        preempt.install(state)

    step_sleep = float(os.environ.get("HVD_ELASTIC_TEST_SLEEP", "0") or 0)

    @elastic.run
    def train(state):
        if entry_step["v"] is None:
            entry_step["v"] = int(state.step)
        while int(state.step) < num_steps:
            if step_sleep:
                time.sleep(step_sleep)
            w = float(state.params["w"])
            loss = (w - TARGET) ** 2
            state.params = {"w": np.float64(w - LR * 2 * (w - TARGET))}
            state.step = np.int64(int(state.step) + 1)
            state.commit()
            inspector.record_progress(int(state.step))
            if rank == 0:
                with open(log_path, "a") as f:
                    f.write(json.dumps({"epoch": epoch, "host": host,
                                        "step": int(state.step),
                                        "loss": loss}) + "\n")
            if (die_host and host == die_host and epoch < die_until_epoch):
                if die_mode == "evict":
                    # a spot preemption notice: the eviction thread owns
                    # the rest of this process's life (commit + exit 75);
                    # park here so no further step races the drain
                    os.kill(os.getpid(), signal.SIGTERM)
                    time.sleep(60)
                    raise SystemExit("eviction never fired")
                # commits are ASYNC now (horovod_tpu/ckpt): the scenario
                # is "crash strikes after the checkpoint reached
                # durability", so force the in-flight save to its
                # manifest before dying (a crash racing the write is
                # test_launcher's SIGKILL-mid-save e2e instead). BOUNDED:
                # ranks run this loop at independent speeds, so the
                # commit barrier may be waiting on a lagging peer's
                # shard — on a loaded box an unbounded flush would delay
                # the death past the test's stall windows
                try:
                    state.flush(timeout=15.0)
                except Exception:
                    pass  # die anyway; restore falls back a step
                os.kill(os.getpid(), signal.SIGKILL)
        return int(state.step)

    final = train(state)
    if rank == 0:
        with open(log_path, "a") as f:
            f.write(json.dumps({"epoch": epoch, "host": host,
                                "done": final,
                                "resumed_from": entry_step["v"]}) + "\n")


if __name__ == "__main__":
    main()
