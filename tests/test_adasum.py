"""Adasum numerics against an independent NumPy reference.

Reference pattern: test/test_adasum_tensorflow.py:33-63 — reimplement the
pairwise formula + log2(n) tree in NumPy, run the distributed op, compare.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops import adasum, collective


def reference_combine(a, b):
    af, bf = a.astype(np.float64).ravel(), b.astype(np.float64).ravel()
    dot = np.dot(af, bf)
    na2, nb2 = np.dot(af, af), np.dot(bf, bf)
    ca = 1.0 - dot / (2 * na2) if na2 > 0 else 1.0
    cb = 1.0 - dot / (2 * nb2) if nb2 > 0 else 1.0
    return (af * ca + bf * cb).reshape(a.shape)


def test_pairwise_combine_orthogonal(rng):
    # Orthogonal gradients: dot = 0 -> plain sum.
    a = np.array([1.0, 0.0], np.float32)
    b = np.array([0.0, 1.0], np.float32)
    out = np.asarray(adasum.adasum_combine(jnp.array(a), jnp.array(b)))
    np.testing.assert_allclose(out, [1.0, 1.0])


def test_pairwise_combine_identical():
    # Identical gradients: dot = |a|^2 = |b|^2 -> each scaled by 1/2 -> a.
    a = np.array([2.0, -3.0, 1.0], np.float32)
    out = np.asarray(adasum.adasum_combine(jnp.array(a), jnp.array(a)))
    np.testing.assert_allclose(out, a, rtol=1e-6)


def test_pairwise_combine_random_matches_numpy(rng):
    a = rng.standard_normal(37).astype(np.float32)
    b = rng.standard_normal(37).astype(np.float32)
    out = np.asarray(adasum.adasum_combine(jnp.array(a), jnp.array(b)))
    np.testing.assert_allclose(out, reference_combine(a, b), rtol=1e-5)


def test_pairwise_combine_zero_norm():
    a = np.zeros((4,), np.float32)
    b = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    out = np.asarray(adasum.adasum_combine(jnp.array(a), jnp.array(b)))
    np.testing.assert_allclose(out, b)


def test_numpy_tree_schedule_properties(rng):
    vecs = [rng.standard_normal(16).astype(np.float32) for _ in range(4)]
    out = adasum.adasum_tree_np(vecs)
    assert out.shape == (16,)
    # All ranks converge to the same result by symmetry of the schedule.
    # (adasum_tree_np returns rank 0's value; recompute at "rank 2" by
    # re-running — the schedule is deterministic.)


def test_distributed_adasum_matches_numpy_tree(hvd, n_devices, rng):
    vals = rng.standard_normal((n_devices, 33)).astype(np.float32)
    expected = adasum.adasum_tree_np([vals[i] for i in range(n_devices)])

    def f():
        r = collective.mesh_rank()
        x = jnp.asarray(vals)[r]
        return adasum.adasum_allreduce(x, ("data",))

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(), out_specs=P(),
                        check_vma=False)()
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                               atol=1e-5)


def test_distributed_adasum_via_allreduce_op(hvd, n_devices, rng):
    import horovod_tpu as hvd_api
    vals = rng.standard_normal((n_devices, 8)).astype(np.float32)
    expected = adasum.adasum_tree_np([vals[i] for i in range(n_devices)])

    def f():
        x = jnp.asarray(vals)[collective.mesh_rank()]
        return collective.allreduce(x, op=hvd_api.Adasum)

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(), out_specs=P(),
                        check_vma=False)()
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                               atol=1e-5)


def test_hierarchical_adasum_2d(hvd2d, n_devices, rng):
    """2-D mesh: the production 2-level composite of
    adasum_cuda_operations.cc — sum-scatter within slice ('data'),
    per-chunk Adasum across slices ('dcn'), gather, /local_size —
    against the NumPy schedule model."""
    data_size = n_devices // 2
    vals = rng.standard_normal((n_devices, 12)).astype(np.float32)
    grid = vals.reshape(2, data_size, 12)
    expected = adasum.hierarchical_adasum_np(grid)

    def f():
        x = jnp.asarray(vals)[collective.mesh_rank()]
        return adasum.adasum_allreduce(x, ("dcn", "data"))

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(), out_specs=P(),
                        check_vma=False)()
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                               atol=1e-5)


def test_hierarchical_adasum_unpadded_chunks(hvd2d, n_devices, rng):
    """Chunk count not divisible by local_size exercises the zero-pad
    scatter path (the reference instead constrains its fusion buffer to
    be divisible by local_size, adasum_cuda_operations.cc:96-116)."""
    data_size = n_devices // 2
    n = 4 * data_size + 3  # forces padding
    vals = rng.standard_normal((n_devices, n)).astype(np.float32)
    expected = adasum.hierarchical_adasum_np(
        vals.reshape(2, data_size, n))

    def f():
        x = jnp.asarray(vals)[collective.mesh_rank()]
        return adasum.hierarchical_adasum_allreduce(
            x, ici_axes=("data",), dcn_axis="dcn")

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(), out_specs=P(),
                        check_vma=False)()
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                               atol=1e-5)


def test_hierarchical_adasum_identical_grads_is_identity(hvd2d, n_devices,
                                                         rng):
    """Adasum of identical node-gradients returns the per-rank gradient:
    node sum = L*g, adasum(L*g, L*g) = L*g, /L = g — the scale-insensitive
    property the local_size division preserves (the reason the reference
    divides by local_size and NOT world size, torch/mpi_ops.py:104-110)."""
    g_vec = rng.standard_normal(16).astype(np.float32)

    def f():
        return adasum.adasum_allreduce(jnp.asarray(g_vec), ("dcn", "data"))

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(), out_specs=P(),
                        check_vma=False)()
    np.testing.assert_allclose(np.asarray(out), g_vec, rtol=1e-5,
                               atol=1e-6)
