"""GSPMD hot path (ISSUE 10): one logical mesh, NamedSharding-compiled
collectives. Pins the plan's spec derivation, the spmd train step's
parity with the explicit overlap+ZeRO pipeline (the dryrun 1b4 contract,
run here as the tier-1 smoke), the compiled-HLO byte accounting, the
compiled-in-place wire compression (the shard_map island for chunked
quantizers, dtype-narrowed constraints for casts — ISSUE 17), the
compat gate — and the tier-1 GUARD that
keeps the hot path ON the mesh: no new ``pmap(``/``shard_map(`` call
sites may appear in ``horovod_tpu/`` outside the pinned baseline
(``compat.py`` and ``parallel/gspmd.py`` excluded as the shim layers)."""

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_api
from horovod_tpu import compat, training
from horovod_tpu.models.simple import MLP
from horovod_tpu.parallel import gspmd
from horovod_tpu.parallel import mesh as mesh_lib

_PKG = os.path.join(os.path.dirname(__file__), os.pardir, "horovod_tpu")


# ---- tier-1 guard: the hot path stays on the mesh ---------------------

# Thin wrapper over the hvd-lint engine's HVD-MESH pass (ISSUE 12): the
# pinned call-site baseline now lives in the committed
# .hvd-lint-baseline.json (dated entries; compat.py and
# parallel/gspmd.py excluded inside the rule) and the engine's
# stale-entry ratchet replaces the hand-rolled shrink check — a removed
# pmap(/shard_map( site fails the run until the baseline is re-written
# (`hvd-lint --baseline write`), so old slack cannot quietly readmit a
# new explicit per-rank call site. Failure messages carry file:line.


def test_guard_no_new_pmap_or_shard_map_call_sites():
    from horovod_tpu.analysis import run_lint

    repo = os.path.abspath(os.path.join(_PKG, os.pardir))
    result = run_lint([_PKG], root=repo, rules={"HVD-MESH"},
                      baseline_path=os.path.join(
                          repo, ".hvd-lint-baseline.json"))
    assert not result.findings, (
        "new explicit pmap(/shard_map( call site(s) off the logical "
        "mesh — express the sharding as NamedSharding / "
        "with_sharding_constraint (parallel/gspmd.py) or justify the "
        "baseline addition in the PR (docs/ANALYSIS.md):\n"
        + "\n".join(f.format() for f in result.findings))
    assert not result.stale_baseline, (
        "HVD-MESH baseline overstates call sites — shrink it "
        "(`hvd-lint --baseline write`) so removed sites cannot "
        f"silently come back: {result.stale_baseline}")


# ---- plan derivation --------------------------------------------------

def test_derive_plan_specs(hvd):
    plan = gspmd.derive_plan()
    assert plan.data_axes == ("data",)
    assert plan.batch_spec == P(("data",))
    assert plan.world() == len(jax.devices())
    with pytest.raises(ValueError, match="model_axis"):
        gspmd.derive_plan(model_axis="nope")


def test_derive_plan_2d_mesh(hvd2d):
    plan = gspmd.derive_plan()
    assert set(plan.data_axes) == {"dcn", "data"}
    assert plan.world() == len(jax.devices())


def test_state_partition_specs_shards_zero_rows(hvd):
    from horovod_tpu.parallel import zero
    params = {"w": jnp.ones((40,)), "b": jnp.ones((8,))}
    tx = hvd_api.DistributedOptimizer(optax.adam(1e-2),
                                      sharded_update=True)
    state = training.create_train_state(MLP(features=(4,)), tx,
                                        jax.random.PRNGKey(0),
                                        jnp.ones((1, 8)))
    del params
    specs = training.state_specs(state)  # delegates to gspmd
    assert isinstance(specs.opt_state, zero.ZeroState)
    row_specs = [s for s in jax.tree_util.tree_leaves(
        specs.opt_state.inner, is_leaf=lambda x: isinstance(x, P))
        if s == P(("data",))]
    assert row_specs, "no ZeRO row leaf got the P('data') spec"
    for s in jax.tree_util.tree_leaves(
            specs.params, is_leaf=lambda x: isinstance(x, P)):
        assert s == P()


# ---- the spmd step: dryrun 1b4 parity as the tier-1 smoke -------------

def test_spmd_step_matches_explicit_overlap_zero1(hvd):
    """The 1b4 contract on the full 8-device mesh: same model/optimizer
    stepped by both hot paths on identical tiled batches -> same loss
    trajectory and params, genuinely sharded ZeRO rows, XLA-inserted
    collectives in the compiled module."""
    import __graft_entry__ as graft
    graft._dryrun_gspmd(jax.devices())


def test_spmd_wire_island_matches_exact_gspmd(hvd):
    """The 1b5 contract (ISSUE 17) as the tier-1 smoke, on a 2-device
    mesh: GSPMD+int8+EF and GSPMD+fp8+EF 8-step trajectories within
    WIRE_EPSILON of the exact fp32 GSPMD path, compression-off programs
    identical, compressed program different."""
    import __graft_entry__ as graft
    graft._dryrun_gspmd_wire(jax.devices()[:2])


def test_spmd_plain_dp_matches_explicit(hvd):
    """Non-sharded (plain DP) GSPMD: tx.update_spmd routes through the
    preserved optimizer chain, so state stays interchangeable."""
    n = len(jax.devices())
    rng = np.random.default_rng(5)
    sx = rng.standard_normal((2, 10))
    sy = rng.integers(0, 3, size=(2,))
    X = jnp.asarray(np.tile(sx, (n, 1)), jnp.float32)
    y = jnp.asarray(np.tile(sy, n), jnp.int32)
    model = MLP(features=(16, 3))

    def run(spmd):
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(1), X[:1])
        step = training.make_train_step(model, tx, donate=False,
                                        spmd=spmd)
        losses = []
        for _ in range(5):
            state, loss = step(state, X, y)
            losses.append(float(loss))
        return np.asarray(losses), state

    ex, ex_state = run(False)
    sp, sp_state = run(True)
    np.testing.assert_allclose(sp, ex, rtol=1e-5, atol=1e-7)
    for a, b in zip(jax.tree_util.tree_leaves(ex_state.opt_state),
                    jax.tree_util.tree_leaves(sp_state.opt_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_spmd_lm_step_matches_explicit(hvd):
    """GSPMD LM step: global-mean next-token loss over batch-sharded
    tokens tracks the explicit LM step's exact sharded loss."""
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    n = len(jax.devices())
    cfg = TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                            d_model=16, d_ff=32, dtype=jnp.float32)
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 32, size=(2 * n, 16)), jnp.int32)

    def run(spmd):
        tx = hvd_api.DistributedOptimizer(optax.adam(1e-2))
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(2),
                                            tokens[:1])
        step = training.make_lm_train_step(model, tx, donate=False,
                                           spmd=spmd)
        losses = []
        for _ in range(4):
            state, loss = step(state, tokens)
            losses.append(float(loss))
        return np.asarray(losses)

    np.testing.assert_allclose(run(True), run(False), rtol=1e-4,
                               atol=1e-6)


def test_spmd_step_with_loader(hvd):
    """make_train_step(spmd=True, loader=...) stages batches to the
    plan's batch sharding and step(state) pulls them."""
    from horovod_tpu.data import ArraySource, PrefetchLoader
    n = len(jax.devices())
    B = 2 * n
    rng = np.random.default_rng(3)
    X = rng.standard_normal((4 * B, 6)).astype(np.float32)
    y = rng.integers(0, 3, size=(4 * B,)).astype(np.int32)
    model = MLP(features=(8, 3))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.05))
    loader = PrefetchLoader(ArraySource([X, y]), B, rank=0, world=1,
                            shuffle=False)
    try:
        step = training.make_train_step(model, tx, donate=False,
                                        spmd=True, loader=loader)
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0),
                                            jnp.asarray(X[:1]))
        # the staging target is introspectable: the plan's batch
        # NamedSharding, so prefetched batches arrive matching the
        # compiled step's in_shardings
        assert isinstance(loader.placement_spec,
                          jax.sharding.NamedSharding)
        assert loader.placement_spec.spec == P(("data",))
        for _ in range(3):
            state, loss = step(state)
        assert np.isfinite(float(loss))
    finally:
        loader.close()


# ---- guards and wire routing ------------------------------------------

def test_spmd_rejects_explicit_pipeline_knobs(hvd):
    model = MLP(features=(4,))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.1))
    with pytest.raises(ValueError, match="explicit pipeline"):
        training.make_train_step(model, tx, spmd=True, accum_steps=2)
    tx_adasum = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                             op=hvd_api.Adasum)
    with pytest.raises(ValueError, match="Average"):
        training.make_train_step(model, tx_adasum, spmd=True)


def test_spmd_wire_compression_compiles_island_in_place(hvd):
    """A chunked wire (int8) under spmd=True compiles IN-PLACE as the
    shard_map island (ISSUE 17) — no fallback warning, the build stays
    the GSPMD step, it trains, and the island's quantized exchange shows
    up in the compiled byte accounting as all-to-all traffic."""
    n = len(jax.devices())
    rng = np.random.default_rng(3)
    X = jnp.asarray(rng.normal(size=(2 * n, 6)), jnp.float32)
    y = jnp.asarray(np.arange(2 * n) % 3, jnp.int32)
    model = MLP(features=(8, 3))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.05),
                                      sharded_update=True,
                                      compression="int8")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        step = training.make_train_step(model, tx, donate=False,
                                        spmd=True)
    assert not any("falling back" in str(x.message) for x in w), (
        [str(x.message) for x in w])
    assert step.spmd  # still the GSPMD build
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        X[:1])
    losses = []
    for _ in range(3):
        state, loss = step(state, X, y)
        losses.append(float(loss))
    assert all(np.isfinite(v) for v in losses)
    if n > 1:
        # the chunked exchange is an alltoall of wire rows + scales —
        # the honest compiled bytes must include it
        assert step.compiled_collectives.get("all-to-all", {}).get(
            "calls", 0) >= 1, step.compiled_collectives


def test_spmd_cast_wire_keeps_annotation_program(hvd):
    """Cast wires (bf16) have an annotation-only form: no island, no
    fallback — the constraint path carries them and the step trains."""
    n = len(jax.devices())
    X = jnp.asarray(np.ones((2 * n, 6)), jnp.float32)
    y = jnp.asarray(np.zeros((2 * n,), np.int32))
    model = MLP(features=(8, 3))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.05),
                                      sharded_update=True,
                                      compression="bf16")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        step = training.make_train_step(model, tx, donate=False,
                                        spmd=True)
    assert not any("falling back" in str(x.message) for x in w)
    assert step.spmd
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        X[:1])
    state, loss = step(state, X, y)
    assert np.isfinite(float(loss))
    # no shard_map island on the cast path: the program stays pure
    # annotation — chunked formats are the only island tenants
    assert step.compiled_collectives.get("all-to-all") is None


def test_spmd_step_retraces_on_new_batch_shape(hvd):
    """A different batch shape (drop_last=False tail batch, an eval
    batch) must compile a second program and keep running — the jit
    wrapper would retrace transparently, and the AOT executable cache
    has to preserve that instead of crashing on a shape mismatch."""
    n = len(jax.devices())
    model = MLP(features=(8, 3))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.05))
    step = training.make_train_step(model, tx, donate=False, spmd=True)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        jnp.ones((1, 6)))
    X1 = jnp.ones((2 * n, 6)); y1 = jnp.zeros((2 * n,), jnp.int32)
    X2 = jnp.ones((4 * n, 6)); y2 = jnp.zeros((4 * n,), jnp.int32)
    state, l1 = step(state, X1, y1)
    state, l2 = step(state, X2, y2)  # new shape: second program
    state, l3 = step(state, X1, y1)  # first program again, cached
    assert all(np.isfinite(float(v)) for v in (l1, l2, l3))


def test_spmd_step_warns_on_late_wire_install(hvd):
    """config.wire_dtype binds late on the explicit path; the GSPMD
    step bakes its (uncompressed) decision at build — installing a wire
    format AFTER building must WARN at the next step instead of
    silently running uncompressed while tx.compression claims int8."""
    from horovod_tpu import basics

    n = len(jax.devices())
    X = jnp.ones((2 * n, 6)); y = jnp.zeros((2 * n,), jnp.int32)
    model = MLP(features=(8, 3))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.05))
    step = training.make_train_step(model, tx, donate=False, spmd=True)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        X[:1])
    state, _ = step(state, X, y)
    old = basics._state.config.wire_dtype
    basics._state.config.wire_dtype = "int8"
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            state, _ = step(state, X, y)
        drift = [str(x.message) for x in w
                 if "built uncompressed" in str(x.message)]
        assert drift
        # ISSUE 17 regression: compression now compiles in-place, so
        # the remedy is REBUILDING the step — the message must say so
        # and must not claim a fallback that no longer happens
        assert any("Rebuild the step" in m for m in drift), drift
        assert not any("fall" in m.lower() for m in drift), drift
    finally:
        basics._state.config.wire_dtype = old


def test_spmd_gate_reports_reason(hvd, monkeypatch):
    monkeypatch.setattr(compat, "gspmd_supported",
                        lambda: (False, "synthetic: no NamedSharding"))
    model = MLP(features=(4,))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.1))
    with pytest.raises(RuntimeError, match="synthetic: no NamedSharding"):
        training.make_train_step(model, tx, spmd=True)


def test_gspmd_supported_on_this_jax():
    ok, reason = compat.gspmd_supported()
    assert ok, reason


# ---- compiled-HLO byte accounting -------------------------------------

def test_collective_bytes_from_hlo_parses_result_shapes():
    hlo = "\n".join([
        "%ar = f32[4,16]{1,0} all-reduce(f32[4,16]{1,0} %x), meta",
        "%ag = bf16[8,8]{1,0} all-gather(bf16[1,8]{1,0} %y), dims={0}",
        "%rs = f32[2]{0} reduce-scatter(f32[16]{0} %z), dims={0}",
        "%dot = f32[4,4]{1,0} dot(f32[4,8] %a, f32[8,4] %b)",
    ])
    got = gspmd.collective_bytes_from_hlo(hlo)
    assert got["all-reduce"] == {"calls": 1, "bytes": 4 * 16 * 4}
    assert got["all-gather"] == {"calls": 1, "bytes": 8 * 8 * 2}
    assert got["reduce-scatter"] == {"calls": 1, "bytes": 2 * 4}
    assert "dot" not in got


def test_collective_bytes_from_hlo_parses_async_start_done_pairs():
    """With the latency-hiding scheduler (the TPU configuration this
    path targets), collectives lower to -start/-done PAIRS: the -start
    must be counted once under the base op name — an async all-gather's
    tuple result counts only its OUTPUT element — and the -done must be
    skipped (counting both would double every collective)."""
    hlo = "\n".join([
        "%ars = f32[4,16]{1,0} all-reduce-start(f32[4,16]{1,0} %x)",
        "%ard = f32[4,16]{1,0} all-reduce-done(f32[4,16]{1,0} %ars)",
        "%ags = (bf16[1,8]{1,0}, bf16[8,8]{1,0}) "
        "all-gather-start(bf16[1,8]{1,0} %y), dimensions={0}",
        "%agd = bf16[8,8]{1,0} all-gather-done((bf16[1,8]{1,0}, "
        "bf16[8,8]{1,0}) %ags)",
    ])
    got = gspmd.collective_bytes_from_hlo(hlo)
    assert got["all-reduce"] == {"calls": 1, "bytes": 4 * 16 * 4}
    assert got["all-gather"] == {"calls": 1, "bytes": 8 * 8 * 2}
    assert set(got) == {"all-reduce", "all-gather"}

    # variadic async (AllReduceCombiner fuses k tensors into ONE
    # -start whose tuple is k aliased inputs + k outputs): the output
    # HALF must be counted, not just the last element
    variadic = ("%vars = (f32[64]{0}, f32[32]{0}, f32[64]{0}, "
                "f32[32]{0}) all-reduce-start(f32[64]{0} %a, "
                "f32[32]{0} %b)")
    got = gspmd.collective_bytes_from_hlo(variadic)
    assert got["all-reduce"] == {"calls": 1, "bytes": (64 + 32) * 4}

    # collective-permute-start carries trailing u32[] context handles
    # after the (operand, output) pair — they are not payload, and the
    # half-split must not land on them
    permute = ("%cps = (f32[16]{0}, f32[16]{0}, u32[], u32[]) "
               "collective-permute-start(f32[16]{0} %p), "
               "source_target_pairs={{0,1}}")
    got = gspmd.collective_bytes_from_hlo(permute)
    assert got["collective-permute"] == {"calls": 1, "bytes": 16 * 4}


def test_spmd_step_records_compiled_collectives(hvd):
    """The compiled path's byte accounting lands in the standard
    hvd_collective_* families under spmd_* op labels — once per
    compile, read off the module XLA actually produced."""
    from horovod_tpu import telemetry
    from horovod_tpu.telemetry import instruments as ti

    n = len(jax.devices())
    X = jnp.asarray(np.ones((2 * n, 6)), jnp.float32)
    y = jnp.asarray(np.zeros((2 * n,)), jnp.int32)
    model = MLP(features=(8, 3))
    tx = hvd_api.DistributedOptimizer(optax.adam(0.05),
                                      sharded_update=True)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        X[:1])
    step = training.make_train_step(model, tx, donate=False, spmd=True)

    def spmd_bytes():
        fam = telemetry.get_registry().get(ti.COLLECTIVE_BYTES)
        s = fam.sample() if fam is not None else {}
        if not isinstance(s, dict):
            return 0.0
        return sum(v for k, v in s.items()
                   if any(str(p).startswith("spmd_") for p in k))

    before = spmd_bytes()
    state, _ = step(state, X, y)
    after = spmd_bytes()
    assert step.compiled_collectives, "no collectives parsed"
    assert after > before
    parsed = sum(t["bytes"] for t in step.compiled_collectives.values())
    assert after - before == pytest.approx(parsed)
    # once per compile, not per step
    state, _ = step(state, X, y)
    assert spmd_bytes() == after


def test_spmd_island_retrace_keeps_per_program_wire_accounting(hvd):
    """A second batch shape under the compressed island compiles a
    second program whose wire bytes are accounted ONCE for that
    program — re-running an already-compiled shape adds nothing
    (ISSUE 17: N-shape retrace keeps per-program wire accounting)."""
    from horovod_tpu import telemetry
    from horovod_tpu.telemetry import instruments as ti

    n = len(jax.devices())
    model = MLP(features=(8, 3))
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.05),
                                      sharded_update=True,
                                      compression="int8")
    step = training.make_train_step(model, tx, donate=False, spmd=True)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        jnp.ones((1, 6)))
    X1 = jnp.ones((2 * n, 6)); y1 = jnp.zeros((2 * n,), jnp.int32)
    X2 = jnp.ones((4 * n, 6)); y2 = jnp.zeros((4 * n,), jnp.int32)

    def spmd_bytes():
        fam = telemetry.get_registry().get(ti.COLLECTIVE_BYTES)
        s = fam.sample() if fam is not None else {}
        if not isinstance(s, dict):
            return 0.0
        return sum(v for k, v in s.items()
                   if any(str(p).startswith("spmd_") for p in k))

    b0 = spmd_bytes()
    state, _ = step(state, X1, y1)
    b1 = spmd_bytes()
    assert b1 > b0  # first program's island bytes recorded
    state, _ = step(state, X2, y2)
    b2 = spmd_bytes()
    assert b2 > b1  # second shape -> second program, its own bytes
    state, l3 = step(state, X1, y1)  # cached program: no new bytes
    assert spmd_bytes() == b2
    assert np.isfinite(float(l3))


def test_spmd_zero1_checkpoint_interchangeable_with_explicit(hvd):
    """ZeRO-1 optimizer state written by the explicit compressed
    pipeline restores bit-for-bit into the compiled island step and
    vice versa (ISSUE 17) — same tree structure, same leaf
    shapes/dtypes, and each path trains on from the other's state."""
    n = len(jax.devices())
    rng = np.random.default_rng(11)
    X = jnp.asarray(rng.normal(size=(2 * n, 6)), jnp.float32)
    y = jnp.asarray(np.arange(2 * n) % 3, jnp.int32)
    model = MLP(features=(8, 3))

    def build(spmd):
        tx = hvd_api.DistributedOptimizer(optax.adam(0.05),
                                          sharded_update=True,
                                          compression="int8")
        step = training.make_train_step(model, tx, donate=False,
                                        spmd=spmd)
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0), X[:1])
        return step, state

    exp_step, exp_state = build(spmd=False)
    spmd_step, spmd_state = build(spmd=True)

    for _ in range(2):
        exp_state, _ = exp_step(exp_state, X, y)
        spmd_state, _ = spmd_step(spmd_state, X, y)

    # identical checkpoint payload: same treedef, same leaf shape/dtype
    e_leaves, e_def = jax.tree_util.tree_flatten(exp_state)
    s_leaves, s_def = jax.tree_util.tree_flatten(spmd_state)
    assert e_def == s_def
    for e, s in zip(e_leaves, s_leaves):
        assert e.shape == s.shape and e.dtype == s.dtype

    # "save" on one path, "restore" on the other, keep training
    host = [np.asarray(jax.device_get(v)) for v in e_leaves]
    restored = jax.tree_util.tree_unflatten(
        s_def, [jnp.asarray(v) for v in host])
    restored, loss_s = spmd_step(restored, X, y)
    assert np.isfinite(float(loss_s))

    host_b = [np.asarray(jax.device_get(v)) for v in s_leaves]
    restored_b = jax.tree_util.tree_unflatten(
        e_def, [jnp.asarray(v) for v in host_b])
    restored_b, loss_e = exp_step(restored_b, X, y)
    assert np.isfinite(float(loss_e))


def test_spmd_state_place_roundtrip(hvd):
    """place_state puts ZeRO rows on their NamedShardings; re-placing
    is a no-op (stable input shardings — no recompiles)."""
    model = MLP(features=(8, 3))
    tx = hvd_api.DistributedOptimizer(optax.adam(0.05),
                                      sharded_update=True)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        jnp.ones((1, 6)))
    plan = gspmd.derive_plan()
    placed = gspmd.place_state(plan, state)
    row = placed.opt_state.inner[0].mu["b0"]
    assert {s.data.shape[0] for s in row.addressable_shards} == {1}
    again = gspmd.place_state(plan, placed)
    assert again.opt_state.inner[0].mu["b0"].sharding == row.sharding
