"""hvd-lint (ISSUE 12): the static-analysis engine and its passes.

Three layers, mirroring docs/ANALYSIS.md's contract:

1. every rule is itself regression-tested against small positive AND
   negative fixture snippets (a pass that silently stops firing is a
   lint bug, not a clean tree);
2. the engine mechanics — suppressions need justifications, the
   baseline is a dated shrink-only ratchet, the CLI exit codes are
   0 clean / 1 findings / 2 engine error;
3. the tier-1 gate: the full engine over ``horovod_tpu/``,
   ``examples/`` and ``bench*.py`` reports ZERO unbaselined findings
   and zero stale baseline entries.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.analysis import (LintError, default_targets, engine,
                                  run_lint)
from horovod_tpu.analysis import cli as lint_cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, ".hvd-lint-baseline.json")


def lint_src(tmp_path, src, name="mod.py", rules=None, **kw):
    """Lint one fixture snippet; returns the LintResult."""
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return run_lint([str(tmp_path)], root=str(tmp_path), rules=rules,
                    **kw)


def rules_of(result):
    return sorted({f.rule for f in result.findings})


# ---------------------------------------------------------------------------
# HVD-DESYNC


def test_desync_flags_collective_under_rank_branch(tmp_path):
    r = lint_src(tmp_path, """
        import horovod_tpu as hvd
        def save(x):
            if hvd.rank() == 0:
                hvd.allreduce(x)
    """)
    assert rules_of(r) == ["HVD-DESYNC"]
    assert r.findings[0].line == 5
    assert "rank-dependent" in r.findings[0].message


def test_desync_flags_rank_conditional_early_exit(tmp_path):
    r = lint_src(tmp_path, """
        import horovod_tpu as hvd
        def save(x, local_rank):
            if local_rank != 0:
                return None
            return hvd.broadcast(x, root_rank=0)
    """)
    assert rules_of(r) == ["HVD-DESYNC"]
    assert "early exit" in r.findings[0].message


def test_desync_flags_nested_early_exit(tmp_path):
    """A rank-conditional return buried under a `with` (or any
    non-def nesting) still exits the function for those ranks — the
    collective after it must be flagged."""
    r = lint_src(tmp_path, """
        import contextlib
        import horovod_tpu as hvd
        def fn(x, rank):
            if rank != 0:
                with contextlib.nullcontext():
                    return None
            return hvd.allreduce(x)
    """)
    assert rules_of(r) == ["HVD-DESYNC"]
    assert "early exit" in r.findings[0].message


def test_desync_flags_boolop_short_circuit(tmp_path):
    r = lint_src(tmp_path, """
        import horovod_tpu as hvd
        def maybe(x, rank):
            return rank == 0 and hvd.allgather(x)
    """)
    assert rules_of(r) == ["HVD-DESYNC"]


def test_desync_negative_world_common_and_target_rank(tmp_path):
    """No finding for world-common conditions, target-rank parameters
    (``root_rank`` names WHICH rank, every rank passes the same value),
    plural rank collections, or rank use that never gates a
    collective."""
    r = lint_src(tmp_path, """
        import horovod_tpu as hvd
        def fine(x, size, root_rank, stalled_ranks):
            if size > 1:
                x = hvd.allreduce(x)
            if root_rank is not None:
                x = hvd.broadcast(x, root_rank=root_rank)
            if stalled_ranks:
                x = hvd.allreduce(x)
            if hvd.rank() == 0:
                print("only logging here")
            return x
    """)
    assert r.findings == []


def test_desync_break_continue_taint_only_their_loop(tmp_path):
    """``continue``/``break`` end an iteration, not the function: a
    collective AFTER the loop is reached by every rank (no finding),
    while one later in the SAME loop body is skipped per-rank (finding).
    A loop over a rank-dependent range is rank-conditional wholesale."""
    clean = lint_src(tmp_path, """
        import horovod_tpu as hvd
        def fn(x, items):
            for i in items:
                if hvd.rank() == i:
                    continue
            return hvd.allreduce(x)
    """)
    assert clean.findings == []
    dirty = lint_src(tmp_path, """
        import horovod_tpu as hvd
        def fn(x, items):
            for i in items:
                if hvd.rank() == i:
                    continue
                x = hvd.allreduce(x)
            return x
    """, name="dirty.py")
    assert rules_of(dirty) == ["HVD-DESYNC"]
    ranged = lint_src(tmp_path, """
        import horovod_tpu as hvd
        def fn(x):
            for _ in range(hvd.rank()):
                x = hvd.allreduce(x)
            return x
    """, name="ranged.py")
    assert rules_of(ranged) == ["HVD-DESYNC"]


def test_desync_scope_is_per_function(tmp_path):
    """A rank-conditional early exit in one function does not taint a
    collective in a nested (separately-called) function."""
    r = lint_src(tmp_path, """
        import horovod_tpu as hvd
        def outer(x, rank):
            if rank != 0:
                return None
            def inner(y):
                return hvd.allreduce(y)
            return inner
    """)
    assert r.findings == []


# ---------------------------------------------------------------------------
# HVD-HOSTSYNC


def test_hostsync_flags_syncs_in_jitted_fn(tmp_path):
    r = lint_src(tmp_path, """
        import jax, numpy as np
        def loss(params, batch):
            v = params.mean()
            print("dbg", v)
            host = np.asarray(v)
            jax.device_get(v)
            return float(v) + host.item()
        step = jax.jit(loss)
    """)
    assert rules_of(r) == ["HVD-HOSTSYNC"]
    kinds = " ".join(f.message for f in r.findings)
    for marker in ("print", "np.asarray", "device_get", "float",
                   ".item()"):
        assert marker in kinds, marker


def test_hostsync_decorator_and_step_builder_entries(tmp_path):
    r = lint_src(tmp_path, """
        import jax
        from functools import partial
        from horovod_tpu import training

        @jax.jit
        def a(x):
            return float(x)

        @partial(jax.jit, donate_argnums=(0,))
        def b(x):
            return x.item()

        def loss_fn(p, batch):
            return p.tolist()
        step = training.make_train_step(loss_fn, None)
    """)
    assert len(r.findings) == 3
    assert rules_of(r) == ["HVD-HOSTSYNC"]


def test_hostsync_negative_outside_jit(tmp_path):
    r = lint_src(tmp_path, """
        import jax, numpy as np
        def logger_hook(state):
            return float(np.asarray(state.loss).item())
        def traced(x):
            return x * 2
        step = jax.jit(traced)
    """)
    assert r.findings == []


# ---------------------------------------------------------------------------
# HVD-LOCKORDER


def test_lockorder_flags_join_and_bounded_put_under_lock(tmp_path):
    r = lint_src(tmp_path, """
        import threading, queue
        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = queue.Queue(maxsize=2)
                self._thread = threading.Thread(target=lambda: None)
            def stop(self):
                with self._lock:
                    self._thread.join(timeout=1)
            def emit(self, ev):
                with self._lock:
                    self._queue.put(ev)
    """)
    assert rules_of(r) == ["HVD-LOCKORDER"]
    msgs = " ".join(f.message for f in r.findings)
    assert ".join()" in msgs and ".put()" in msgs


def test_lockorder_flags_collective_under_lock(tmp_path):
    r = lint_src(tmp_path, """
        import threading
        import horovod_tpu as hvd
        _lock = threading.Lock()
        def publish(x):
            with _lock:
                return hvd.allreduce(x)
    """)
    assert rules_of(r) == ["HVD-LOCKORDER"]
    assert "collective dispatch" in r.findings[0].message


def test_lockorder_detects_cross_file_cycle(tmp_path):
    (tmp_path / "a.py").write_text(textwrap.dedent("""
        import threading
        lock_a = threading.Lock()
        lock_b = threading.Lock()
        def one():
            with lock_a:
                with lock_b:
                    pass
    """))
    (tmp_path / "b.py").write_text(textwrap.dedent("""
        from a import lock_a, lock_b
        def two():
            with lock_b:
                with lock_a:
                    pass
    """))
    r = run_lint([str(tmp_path)], root=str(tmp_path))
    cyc = [f for f in r.findings if "cycle" in f.message]
    assert cyc, [f.message for f in r.findings]
    assert "lock_a" in cyc[0].message and "lock_b" in cyc[0].message


def test_lockorder_negatives(tmp_path):
    """str.join, dict.get, Condition-style self-wait (releases while
    parked), and closures defined (not run) under the lock are all
    clean."""
    r = lint_src(tmp_path, """
        import threading
        class W:
            def __init__(self):
                self._lock = threading.Condition()
                self._mu = threading.Lock()
                self._cfg = {}
            def fmt(self, parts):
                with self._mu:
                    return ", ".join(parts) + str(self._cfg.get("k"))
            def park(self):
                with self._lock:
                    self._lock.wait()
            def deferred(self):
                with self._mu:
                    def later():
                        import time
                        time.sleep(1)
                    return later
    """)
    assert r.findings == []


# ---------------------------------------------------------------------------
# HVD-SIGSAFE


def test_sigsafe_flags_blocking_handler(tmp_path):
    r = lint_src(tmp_path, """
        import signal, threading, logging
        logger = logging.getLogger(__name__)
        _dump_lock = threading.Lock()
        def _handler(signum, frame):
            with _dump_lock:
                open("/tmp/dump", "w").write("x")
            logger.warning("dying")
        signal.signal(signal.SIGTERM, _handler)
    """)
    assert rules_of(r) == ["HVD-SIGSAFE"]
    msgs = " ".join(f.message for f in r.findings)
    assert "with _dump_lock" in msgs and "open()" in msgs \
        and "logging" in msgs


def test_sigsafe_negative_nested_def_in_handler(tmp_path):
    """The rule's own recommended fix — define the work in a nested
    function and run it on a watcher thread — must not be flagged: a
    def inside the handler does not execute in the handler."""
    r = lint_src(tmp_path, """
        import signal, threading, time
        def _handler(signum, frame):
            def _later():
                time.sleep(1)
                open("/tmp/dump", "w").write("x")
            threading.Thread(target=_later, daemon=True).start()
        signal.signal(signal.SIGTERM, _handler)
    """)
    assert r.findings == []


def test_sigsafe_negative_flag_style_handler(tmp_path):
    """Set-a-flag / non-blocking-acquire handlers (the recorder's
    compliant pattern) are clean; so are modules with no handlers."""
    r = lint_src(tmp_path, """
        import signal, threading
        done = threading.Event()
        _dump_lock = threading.Lock()
        def _handler(signum, frame):
            if _dump_lock.acquire(blocking=False):
                _dump_lock.release()
            done.set()
        signal.signal(signal.SIGTERM, _handler)
        def not_a_handler():
            open("/tmp/x", "w")
    """)
    assert r.findings == []


# ---------------------------------------------------------------------------
# HVD-EXCEPT


def test_except_flags_broad_and_bare(tmp_path):
    r = lint_src(tmp_path, """
        def a():
            try:
                return 1
            except Exception:
                return 0
        def b():
            try:
                return 1
            except:
                return 0
        def c():
            try:
                return 1
            except BaseException:
                return 0
    """)
    assert len(r.findings) == 3
    assert rules_of(r) == ["HVD-EXCEPT"]
    bare = [f for f in r.findings if "bare" in f.message]
    assert bare and "KeyboardInterrupt" in bare[0].message


def test_except_negative_reraise_and_narrow(tmp_path):
    r = lint_src(tmp_path, """
        def a():
            try:
                return 1
            except Exception as e:
                raise RuntimeError("wrapped") from e
        def b():
            try:
                return 1
            except (ValueError, OSError):
                return 0
    """)
    assert r.findings == []


# ---------------------------------------------------------------------------
# HVD-MESH


def test_mesh_flags_pmap_but_not_shim_layers(tmp_path):
    pkg = tmp_path / "horovod_tpu"
    pkg.mkdir()
    (pkg / "hot.py").write_text("import jax\nf = jax.pmap(lambda x: x)\n")
    (pkg / "compat.py").write_text(
        "import jax\ng = jax.shard_map(lambda x: x)\n")
    r = run_lint([str(pkg)], root=str(tmp_path))
    assert [f.file for f in r.findings if f.rule == "HVD-MESH"] == \
        [os.path.join("horovod_tpu", "hot.py")]


# ---------------------------------------------------------------------------
# HVD-DISTINIT


def test_distinit_flags_rogue_initialize_but_not_the_entry_point(
        tmp_path):
    pkg = tmp_path / "horovod_tpu"
    cluster = pkg / "cluster"
    cluster.mkdir(parents=True)
    (pkg / "rogue.py").write_text(
        "import jax\n"
        "jax.distributed.initialize(coordinator_address='h:1',\n"
        "                           num_processes=2, process_id=0)\n")
    (cluster / "procmesh.py").write_text(
        "import jax\n"
        "def ensure_distributed():\n"
        "    jax.distributed.initialize()\n")
    r = run_lint([str(pkg)], root=str(tmp_path))
    hits = [f for f in r.findings if f.rule == "HVD-DISTINIT"]
    assert [f.file for f in hits] == \
        [os.path.join("horovod_tpu", "rogue.py")]
    assert "ensure_distributed" in hits[0].hint


def test_distinit_negative_other_initializers(tmp_path):
    pkg = tmp_path / "horovod_tpu"
    pkg.mkdir()
    (pkg / "fine.py").write_text(
        "import logging\n"
        "def setup(app, dist):\n"
        "    logging.initialize()\n"        # wrong receiver
        "    app.distributed.configure()\n"  # wrong method
        "    dist.initialize()\n")           # receiver not 'distributed'
    r = run_lint([str(pkg)], root=str(tmp_path))
    assert [f for f in r.findings if f.rule == "HVD-DISTINIT"] == []


def test_distinit_catches_aliased_module_attribute(tmp_path):
    pkg = tmp_path / "horovod_tpu"
    pkg.mkdir()
    (pkg / "sneaky.py").write_text(
        "from jax import distributed\n"
        "distributed.initialize(num_processes=2)\n")
    r = run_lint([str(pkg)], root=str(tmp_path))
    assert [f.rule for f in r.findings] == ["HVD-DISTINIT"]


# ---------------------------------------------------------------------------
# HVD-METRIC (fixture project tree)


def _metric_tree(tmp_path, doc_rows, register_name):
    pkg = tmp_path / "horovod_tpu" / "telemetry"
    pkg.mkdir(parents=True)
    (pkg / "instruments.py").write_text(textwrap.dedent("""
        STEP_TOTAL = "hvd_step_total"
        LOSS = "hvd_loss"
        CATALOGUE = (STEP_TOTAL, LOSS)
        LEGACY_ALIASES = {STEP_TOTAL: "horovod_step_total"}
    """))
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "| metric | type |\n|---|---|\n" +
        "".join(f"| `{n}` | counter |\n" for n in doc_rows))
    (tmp_path / "horovod_tpu" / "user.py").write_text(textwrap.dedent(f"""
        def install(registry):
            return registry.counter({register_name!r}, "help")
    """))
    return run_lint([str(tmp_path / "horovod_tpu")],
                    root=str(tmp_path))


def test_metric_clean_tree(tmp_path):
    r = _metric_tree(tmp_path, ["hvd_step_total", "hvd_loss"],
                     "hvd_step_total")
    assert r.findings == []


def test_metric_catalogue_accepts_string_literal_elements(tmp_path):
    """A direct string element in CATALOGUE is as catalogued as a
    named constant — it must not surface as a documented ghost."""
    pkg = tmp_path / "horovod_tpu" / "telemetry"
    pkg.mkdir(parents=True)
    (pkg / "instruments.py").write_text(textwrap.dedent("""
        STEP_TOTAL = "hvd_step_total"
        CATALOGUE = (STEP_TOTAL, "hvd_literal_total")
        LEGACY_ALIASES = {}
    """))
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "| metric | type |\n|---|---|\n"
        "| `hvd_step_total` | counter |\n"
        "| `hvd_literal_total` | counter |\n")
    r = run_lint([str(tmp_path / "horovod_tpu")], root=str(tmp_path))
    assert r.findings == []


def test_metric_flags_ghost_missing_and_uncatalogued_use(tmp_path):
    r = _metric_tree(tmp_path, ["hvd_step_total", "hvd_ghost_total"],
                     "hvd_rogue_total")
    msgs = {f.message.split("`")[1]: f for f in r.findings}
    assert set(msgs) == {"hvd_ghost_total", "hvd_loss",
                         "hvd_rogue_total"}
    # the ghost anchors at its table row, the use-site at its call
    assert msgs["hvd_ghost_total"].file == "docs/OBSERVABILITY.md"
    assert msgs["hvd_ghost_total"].line == 4
    assert msgs["hvd_rogue_total"].file.endswith("user.py")


def test_metric_doc_findings_are_baselinable(tmp_path):
    """Findings anchored in the (never-walked) docs file must spend
    baseline budget like any other — and repeated ``--baseline write``
    must not duplicate their entries (the doc is in the pass's
    scope_files, so the entry is in scope on both the read and the
    write path)."""
    r = _metric_tree(tmp_path, ["hvd_step_total", "hvd_loss",
                                "hvd_ghost_total"], "hvd_step_total")
    assert len(r.findings) == 1  # the documented ghost
    base = tmp_path / "base.json"
    engine.write_baseline(str(base), r.all_findings)

    def rerun():
        return run_lint([str(tmp_path / "horovod_tpu")],
                        root=str(tmp_path), baseline_path=str(base))

    r2 = rerun()
    assert r2.clean and len(r2.baselined) == 1
    # a second write-from-current-state keeps exactly one entry
    previous = engine.load_baseline(str(base))
    engine.write_baseline(
        str(base), r2.all_findings, previous=previous,
        keep=[e for e in previous
              if not engine.entry_in_scope(e, r2, str(tmp_path))])
    assert len(engine.load_baseline(str(base))) == 1
    assert rerun().clean


def test_overlapping_targets_parse_each_file_once(tmp_path):
    (tmp_path / "m.py").write_text(textwrap.dedent(_EXCEPT_SRC))
    r = run_lint([str(tmp_path), str(tmp_path / "m.py")],
                 root=str(tmp_path))
    assert r.files == 1 and len(r.findings) == 1


# ---------------------------------------------------------------------------
# engine mechanics: suppressions


def test_suppression_same_line_and_line_above(tmp_path):
    r = lint_src(tmp_path, """
        def a():
            try:
                return 1
            except Exception:  # hvd-lint: disable=HVD-EXCEPT -- probe, absence is the answer
                return 0
        def b():
            try:
                return 1
            # hvd-lint: disable=HVD-EXCEPT -- forensics must never throw
            except Exception:
                return 0
    """)
    assert r.findings == []
    assert len(r.suppressed) == 2


def test_suppression_requires_justification(tmp_path):
    r = lint_src(tmp_path, """
        def a():
            try:
                return 1
            except Exception:  # hvd-lint: disable=HVD-EXCEPT
                return 0
    """)
    rules = rules_of(r)
    assert "HVD-SUPPRESS" in rules  # the bare disable is itself flagged
    assert "HVD-EXCEPT" in rules    # and does NOT suppress


def test_suppression_text_inside_strings_is_inert(tmp_path):
    """Suppression-shaped text inside docstrings/string literals (e.g.
    documentation of the syntax) must neither suppress nor be flagged
    as malformed — only real comment tokens count."""
    r = lint_src(tmp_path, '''
        DOC = """write `# hvd-lint: disable=HVD-EXCEPT` to suppress"""
        def a():
            try:
                return 1
            except Exception:
                return 0
    ''')
    assert rules_of(r) == ["HVD-EXCEPT"]  # no HVD-SUPPRESS phantom
    r2 = lint_src(tmp_path, '''
        def a():
            try:
                return 1
            except Exception: s = "# hvd-lint: disable=HVD-EXCEPT -- justified?"
    ''', name="strsup.py")
    # the string ON the finding line must NOT have suppressed it
    assert any(f.rule == "HVD-EXCEPT" and f.file.endswith("strsup.py")
               for f in r2.findings)


def test_suppression_is_rule_scoped(tmp_path):
    r = lint_src(tmp_path, """
        def a():
            try:
                return 1
            except Exception:  # hvd-lint: disable=HVD-DESYNC -- wrong rule
                return 0
    """)
    assert rules_of(r) == ["HVD-EXCEPT"]


# ---------------------------------------------------------------------------
# engine mechanics: baseline ratchet


_EXCEPT_SRC = """
    def a():
        try:
            return 1
        except Exception:
            return 0
"""

_CLEAN_SRC = """
    def a():
        try:
            return 1
        except ValueError:
            return 0
"""


def test_baseline_absorbs_then_ratchets(tmp_path):
    base = tmp_path / "base.json"
    r = lint_src(tmp_path, _EXCEPT_SRC)
    assert len(r.all_findings) == 1
    engine.write_baseline(str(base), r.all_findings)
    entries = engine.load_baseline(str(base))
    assert all(e["date"] for e in entries)  # every entry is dated

    # baselined: clean run, finding accounted
    r2 = lint_src(tmp_path, _EXCEPT_SRC, baseline_path=str(base))
    assert r2.clean and len(r2.baselined) == 1

    # a NEW identical finding in another file is NOT covered
    (tmp_path / "other.py").write_text(textwrap.dedent(_EXCEPT_SRC))
    r3 = run_lint([str(tmp_path)], root=str(tmp_path),
                  baseline_path=str(base))
    assert not r3.clean and len(r3.findings) == 1

    # fixing the baselined finding makes the entry STALE: the ratchet
    # fails the run until the baseline is re-written
    os.remove(tmp_path / "other.py")
    r4 = lint_src(tmp_path, _CLEAN_SRC, baseline_path=str(base))
    assert not r4.clean and r4.stale_baseline \
        and r4.stale_baseline[0]["rule"] == "HVD-EXCEPT"
    engine.write_baseline(str(base), r4.all_findings,
                          previous=engine.load_baseline(str(base)))
    r5 = lint_src(tmp_path, _CLEAN_SRC, baseline_path=str(base))
    assert r5.clean


def test_baseline_keeps_original_dates(tmp_path):
    base = tmp_path / "base.json"
    r = lint_src(tmp_path, _EXCEPT_SRC)
    engine.write_baseline(str(base), r.all_findings, date="2020-01-01")
    engine.write_baseline(str(base), r.all_findings,
                          previous=engine.load_baseline(str(base)))
    assert engine.load_baseline(str(base))[0]["date"] == "2020-01-01"


def test_baseline_ignores_unwalked_files(tmp_path):
    """A partial-target run must not trip the ratchet on entries for
    files that exist under the root but were not linted."""
    base = tmp_path / "base.json"
    (tmp_path / "a.py").write_text(textwrap.dedent(_EXCEPT_SRC))
    (tmp_path / "b.py").write_text(textwrap.dedent(_EXCEPT_SRC))
    r = run_lint([str(tmp_path)], root=str(tmp_path))
    engine.write_baseline(str(base), r.all_findings)
    r2 = run_lint([str(tmp_path / "a.py")], root=str(tmp_path),
                  baseline_path=str(base))
    assert r2.clean, (r2.findings, r2.stale_baseline)


def test_baseline_write_preserves_out_of_scope_entries(tmp_path):
    """A partial-target (or --rules-restricted) ``--baseline write``
    must not delete another subtree's debt: out-of-scope entries are
    written back verbatim, dates intact."""
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    (tmp_path / "a" / "m.py").write_text(textwrap.dedent(_EXCEPT_SRC))
    (tmp_path / "b" / "m.py").write_text(textwrap.dedent(_EXCEPT_SRC))
    base = tmp_path / ".hvd-lint-baseline.json"
    full = run_lint([str(tmp_path / "a"), str(tmp_path / "b")],
                    root=str(tmp_path))
    engine.write_baseline(str(base), full.all_findings,
                          date="2020-01-01")
    # re-write from a run that only walked a/ — b/'s entry must survive
    part = run_lint([str(tmp_path / "a")], root=str(tmp_path),
                    baseline_path=str(base))
    assert part.clean
    previous = engine.load_baseline(str(base))
    engine.write_baseline(
        str(base), part.all_findings, previous=previous,
        keep=[e for e in previous
              if not engine.entry_in_scope(e, part, str(tmp_path))])
    entries = engine.load_baseline(str(base))
    assert {e["file"] for e in entries} == \
        {os.path.join("a", "m.py"), os.path.join("b", "m.py")}
    assert all(e["date"] == "2020-01-01" for e in entries)
    # and the full run is still clean under the merged ledger
    assert run_lint([str(tmp_path / "a"), str(tmp_path / "b")],
                    root=str(tmp_path), baseline_path=str(base)).clean


def test_lockorder_multi_item_with_orders_left_to_right(tmp_path):
    """``with a, b:`` acquires a then b — the a→b edge must register,
    so the opposite nesting elsewhere closes a detectable cycle."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""
        import threading
        lock_a = threading.Lock()
        lock_b = threading.Lock()
        def one():
            with lock_a, lock_b:
                pass
    """))
    (tmp_path / "b.py").write_text(textwrap.dedent("""
        from a import lock_a, lock_b
        def two():
            with lock_b:
                with lock_a:
                    pass
    """))
    r = run_lint([str(tmp_path)], root=str(tmp_path))
    assert any("cycle" in f.message for f in r.findings), \
        [f.message for f in r.findings]


def test_positional_nonblocking_forms_are_clean(tmp_path):
    """``lock.acquire(False)`` / ``q.put(ev, False)`` are the same
    non-blocking request as their keyword spellings — neither
    HVD-SIGSAFE nor HVD-LOCKORDER may flag them."""
    r = lint_src(tmp_path, """
        import signal, threading, queue
        _dump_lock = threading.Lock()
        _mu = threading.Lock()
        _queue = queue.Queue(maxsize=2)
        def _handler(signum, frame):
            if _dump_lock.acquire(False):
                _dump_lock.release()
        signal.signal(signal.SIGTERM, _handler)
        def emit(ev):
            with _mu:
                _queue.put(ev, False)
    """)
    assert r.findings == []


def test_parallel_walk_matches_sequential(tmp_path):
    for i in range(6):
        (tmp_path / f"m{i}.py").write_text(textwrap.dedent(_EXCEPT_SRC))
    seq = run_lint([str(tmp_path)], root=str(tmp_path), jobs=1)
    par = run_lint([str(tmp_path)], root=str(tmp_path), jobs=4)
    assert [f.as_json() for f in seq.findings] == \
        [f.as_json() for f in par.findings]


def test_unparseable_file_is_engine_error(tmp_path):
    (tmp_path / "bad.py").write_text("def broken(:\n")
    with pytest.raises(LintError, match="cannot parse"):
        run_lint([str(tmp_path)], root=str(tmp_path))


# ---------------------------------------------------------------------------
# the CLI: exit codes and formats


def _cli(tmp_path, *argv):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hvd-lint"),
         "--root", str(tmp_path)] + list(argv),
        capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=REPO))
    return out


def test_cli_exit_codes_and_json(tmp_path):
    (tmp_path / "horovod_tpu").mkdir()
    mod = tmp_path / "horovod_tpu" / "m.py"
    mod.write_text(textwrap.dedent(_EXCEPT_SRC))

    out = _cli(tmp_path)
    assert out.returncode == 1  # findings
    assert "HVD-EXCEPT" in out.stdout and "m.py:5" in out.stdout

    out = _cli(tmp_path, "--format", "json")
    data = json.loads(out.stdout)
    assert data["clean"] is False
    assert data["findings"][0]["rule"] == "HVD-EXCEPT"

    out = _cli(tmp_path, "--baseline", "write")
    assert out.returncode == 0
    assert os.path.exists(tmp_path / ".hvd-lint-baseline.json")
    out = _cli(tmp_path)
    assert out.returncode == 0  # baselined -> clean

    # the ratchet through the CLI: fix the finding, stale entry -> 1
    mod.write_text(textwrap.dedent(_CLEAN_SRC))
    out = _cli(tmp_path)
    assert out.returncode == 1 and "STALE-BASELINE" in out.stdout

    mod.write_text("def broken(:\n")
    out = _cli(tmp_path)
    assert out.returncode == 2  # engine error
    assert "cannot parse" in out.stderr

    out = _cli(tmp_path, "--rules", "NOT-A-RULE")
    assert out.returncode == 2


def test_cli_environment_failures_are_exit_2(tmp_path):
    """An unwritable baseline or a missing root is an ENGINE error
    (exit 2 + message), never a traceback masquerading as exit 1."""
    (tmp_path / "horovod_tpu").mkdir()
    (tmp_path / "horovod_tpu" / "m.py").write_text(
        textwrap.dedent(_EXCEPT_SRC))
    out = _cli(tmp_path, "--baseline", "write",
               "--baseline-file", str(tmp_path / "nodir" / "base.json"))
    assert out.returncode == 2, (out.stdout, out.stderr)
    assert "hvd-lint: error:" in out.stderr
    assert "Traceback" not in out.stderr

    out = _cli(tmp_path / "missing-root")
    assert out.returncode == 2, (out.stdout, out.stderr)
    assert "Traceback" not in out.stderr


# ---------------------------------------------------------------------------
# the tier-1 gate: the real tree is clean under the committed baseline


def test_tree_is_clean_under_committed_baseline():
    """ZERO unbaselined findings and zero stale entries over
    horovod_tpu/, examples/ and bench*.py — the ISSUE 12 acceptance
    gate. Every suppression in the tree carries a justification (a bare
    disable surfaces as HVD-SUPPRESS right here) and every baseline
    entry is dated."""
    result = run_lint(default_targets(REPO), root=REPO,
                      baseline_path=BASELINE)
    assert result.clean, (
        "hvd-lint found unbaselined findings (fix, suppress with a "
        "justification, or — for pre-existing debt only — re-ratchet "
        "with `hvd-lint --baseline write`):\n"
        + "\n".join(f.format() for f in result.findings)
        + "".join(f"\nstale baseline: {e}"
                  for e in result.stale_baseline))
    for e in engine.load_baseline(BASELINE):
        assert len(e["date"]) == 10 and e["date"].count("-") == 2, \
            f"undated baseline entry: {e}"


def test_bin_hvd_lint_runs_without_jax(tmp_path):
    """The analysis package is pure stdlib and bin/hvd-lint pre-seeds a
    stub parent package, so a lint-only CI job on a machine WITHOUT
    jax still lints (the metric pass AST-parses instruments.py, no
    imports)."""
    shadow = tmp_path / "shadow"
    shadow.mkdir()
    (shadow / "jax.py").write_text(
        "raise ImportError('no jax on this machine')\n")
    (tmp_path / "horovod_tpu").mkdir()
    (tmp_path / "horovod_tpu" / "m.py").write_text(
        textwrap.dedent(_EXCEPT_SRC))
    env = dict(os.environ, PYTHONPATH=str(shadow))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hvd-lint"),
         "--root", str(tmp_path)],
        capture_output=True, text=True, env=env)
    assert out.returncode == 1, (out.stdout, out.stderr)
    assert "HVD-EXCEPT" in out.stdout
    assert "no jax" not in out.stderr


def test_tree_default_targets_cover_the_acceptance_surface():
    targets = {os.path.relpath(t, REPO) for t in default_targets(REPO)}
    assert "horovod_tpu" in targets and "examples" in targets
    assert any(t.startswith("bench") for t in targets)
