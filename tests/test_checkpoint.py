"""Checkpoint/resume conventions (VERDICT item 10; reference
``examples/keras_imagenet_resnet50.py:85-103``): rank-0-only writes,
broadcast resume step, broadcast params/opt_state on restore. The kill
test crashes a 2-proc run mid-training and verifies the resumed run
reproduces the uninterrupted run's losses exactly."""

import os

import numpy as np
import pytest

from horovod_tpu.run import api


def _make_train(ckpt_dir, crash_at):
    def train():
        import jax
        import numpy as np
        import optax

        import horovod_tpu as hvd
        from horovod_tpu import checkpoint
        hvd.init()
        rank, size = hvd.rank(), hvd.size()

        rng = np.random.RandomState(0)
        X = rng.randn(64, 4).astype(np.float32)
        W = np.array([[2.0], [-3.0], [0.5], [1.0]], dtype=np.float32)
        Y = X @ W
        xs, ys = X[rank::size], Y[rank::size]

        params = {"w": np.zeros((4, 1), dtype=np.float32)}
        opt = hvd.DistributedOptimizer(optax.adam(0.1))
        state = opt.init(params)

        step, params, state, meta = checkpoint.restore_or_init(
            ckpt_dir, params, state)
        if step > 0 and hvd.rank() == 0:
            assert meta == {"note": "test"}  # saved meta comes back

        @jax.jit
        def loss_and_grad(p):
            def f(p):
                import jax.numpy as jnp
                return jnp.mean((xs @ p["w"] - ys) ** 2)
            return jax.value_and_grad(f)(p)

        losses = []
        for i in range(step, 10):
            loss, grads = loss_and_grad(params)
            updates, state = opt.update(grads, state, params)
            params = optax.apply_updates(params, updates)
            losses.append(float(loss))
            checkpoint.save_checkpoint(ckpt_dir, i + 1, params, state,
                                       meta={"note": "test"}, keep=3)
            if crash_at is not None and i + 1 == crash_at:
                os_mod = __import__("os")
                os_mod._exit(17)  # simulate a hard crash mid-job
        return step, losses
    return train


def test_kill_and_resume_2proc(tmp_path):
    env = {"JAX_PLATFORMS": "cpu"}
    golden_dir = str(tmp_path / "golden")
    crash_dir = str(tmp_path / "crash")

    # uninterrupted golden run
    golden = api.run(_make_train(golden_dir, None), np=2, extra_env=env)
    g_start, g_losses = golden[0]
    assert g_start == 0 and len(g_losses) == 10

    # run that dies hard at step 6 (both ranks _exit after saving ckpt-6)
    with pytest.raises(RuntimeError):
        api.run(_make_train(crash_dir, 6), np=2, extra_env=env)
    from horovod_tpu import checkpoint
    assert checkpoint.list_steps(crash_dir)[-1] == 6

    # resume: must pick up at step 6 and reproduce the golden tail
    # (losses are shard-local → compare rank against rank)
    resumed = api.run(_make_train(crash_dir, None), np=2, extra_env=env)
    for (r_start, r_losses), (_, rank_golden) in zip(resumed, golden):
        assert r_start == 6
        np.testing.assert_allclose(r_losses, rank_golden[6:], rtol=1e-6)


def test_rank0_only_writes(tmp_path):
    ckpt_dir = str(tmp_path / "ck")

    def probe():
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu import checkpoint
        hvd.init()
        # distinct params per rank: after restore_or_init all ranks must
        # hold rank 0's values (broadcast-from-root discipline)
        params = {"w": np.full((3,), float(hvd.rank() + 1),
                               dtype=np.float32)}
        path = checkpoint.save_checkpoint(ckpt_dir, 5, params)
        step, params, _, _meta = checkpoint.restore_or_init(ckpt_dir,
                                                            params)
        return (path is not None, step, float(params["w"][0]))

    results = api.run(probe, np=2, extra_env={"JAX_PLATFORMS": "cpu"})
    wrote = [w for w, _, _ in results]
    assert wrote == [True, False]  # only rank 0 wrote
    for _, step, val in results:
        assert step == 5
        assert val == 1.0  # rank 0's params everywhere


def test_keep_prunes_old_checkpoints(tmp_path, monkeypatch):
    # single-process: rank()==0 without init via basics? simplest: run
    # through the API contract directly in-process
    import horovod_tpu as hvd
    from horovod_tpu import checkpoint
    hvd.init()
    try:
        d = str(tmp_path)
        for s in range(1, 6):
            checkpoint.save_checkpoint(d, s, {"w": np.ones(2)}, keep=2)
        assert checkpoint.list_steps(d) == [4, 5]
        params, _opt, meta = checkpoint.restore_checkpoint(
            d, 5, {"w": np.zeros(2)})
        np.testing.assert_allclose(params["w"], 1.0)
        # meta round-trips (flax target-structure pitfall)
        checkpoint.save_checkpoint(d, 7, {"w": np.ones(2)},
                                   meta={"epoch": 3, "note": "x"})
        _p, _o, meta = checkpoint.restore_checkpoint(d, 7,
                                                     {"w": np.zeros(2)})
        assert meta == {"epoch": 3, "note": "x"}
    finally:
        hvd.shutdown()


def test_atomic_write_no_partial(tmp_path):
    import horovod_tpu as hvd
    from horovod_tpu import checkpoint
    hvd.init()
    try:
        d = str(tmp_path)
        checkpoint.save_checkpoint(d, 1, {"w": np.ones(4)})
        # a stale tmp file (crashed mid-write) must not count as a step
        open(os.path.join(d, "ckpt-2.msgpack.tmp"), "wb").write(b"junk")
        assert checkpoint.list_steps(d) == [1]
        assert checkpoint.resume_step(d) == 1
    finally:
        hvd.shutdown()
