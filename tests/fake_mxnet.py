"""Minimal numpy-backed stand-in for mxnet, enough to exercise the
horovod_tpu.mxnet adapter logic in-image (mxnet itself is not baked
into the environment). Mirrors the slivers of API the adapter touches:
``nd.array``/NDArray with ``asnumpy`` + slice assignment,
``optimizer.Optimizer``, and a gluon ``Trainer``/``Parameter`` pair.
"""

import importlib.machinery
import sys
import types

import numpy as np


class NDArray:
    def __init__(self, data):
        self._data = np.array(data, copy=True)

    def asnumpy(self):
        return self._data.copy()

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        self._data[key] = value

    def __getitem__(self, key):
        return NDArray(self._data[key])

    @property
    def shape(self):
        return self._data.shape

    @property
    def dtype(self):
        return self._data.dtype

    @classmethod
    def from_numpy(cls, arr):
        return cls(arr)


def _nd_array(data, dtype=None, **_):
    arr = np.array(data)
    if dtype is not None:
        arr = arr.astype(dtype)
    return NDArray(arr)


class Optimizer:
    def __init__(self, learning_rate=0.01):
        self.lr = learning_rate

    def update(self, index, weight, grad, state):
        weight[:] = weight.asnumpy() - self.lr * grad.asnumpy()

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.lr = lr

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return self.create_state(index, weight)


class DeferredInitializationError(Exception):
    """Raised by Parameter.data() before the engine materializes a
    shape-deferred parameter (mirrors gluon's exception of the same
    name)."""


class Parameter:
    def __init__(self, name, data=None, grad_req="write"):
        self.name = name
        self.grad_req = grad_req
        if data is None:  # deferred init: shape unknown until forward
            self._data = None
            self._grad = None
        else:
            self._data = NDArray(data)
            self._grad = NDArray(np.zeros_like(self._data.asnumpy()))

    def data(self):
        if self._data is None:
            raise DeferredInitializationError(self.name)
        return self._data

    def _init_impl(self, data):
        self._data = NDArray(data)
        self._grad = NDArray(np.zeros_like(self._data.asnumpy()))

    def _finish_deferred_init(self, data):
        """What the gluon engine does at first forward once shapes are
        known: run the initializer through _init_impl."""
        self._init_impl(data)

    def list_grad(self):
        return [self._grad]


class Trainer:
    """Sliver of gluon.Trainer: step() aggregates grads then updates."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore=None):
        if isinstance(params, dict):
            params = list(params.values())
        self._params = list(params)
        if isinstance(optimizer, str):
            optimizer = Optimizer(**(optimizer_params or {}))
        self._optimizer = optimizer
        self._scale = 1.0

    def _allreduce_grads(self):
        pass

    def step(self, batch_size):
        self._allreduce_grads()
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            g = NDArray(p.list_grad()[0].asnumpy() *
                        (self._scale / batch_size))
            self._optimizer.update(i, p.data(), g, None)


def install():
    """Install the fake as ``sys.modules['mxnet']`` (idempotent)."""
    if "mxnet" in sys.modules:
        return sys.modules["mxnet"]
    mx = types.ModuleType("mxnet")
    mx.nd = types.ModuleType("mxnet.nd")
    mx.nd.array = _nd_array
    mx.nd.NDArray = NDArray
    mx.optimizer = types.ModuleType("mxnet.optimizer")
    mx.optimizer.Optimizer = Optimizer
    mx.gluon = types.ModuleType("mxnet.gluon")
    mx.gluon.Trainer = Trainer
    mx.gluon.Parameter = Parameter
    mx.gluon.parameter = types.ModuleType("mxnet.gluon.parameter")
    mx.gluon.parameter.Parameter = Parameter
    mx.gluon.parameter.DeferredInitializationError = \
        DeferredInitializationError
    mods = {"mxnet": mx, "mxnet.nd": mx.nd,
            "mxnet.optimizer": mx.optimizer, "mxnet.gluon": mx.gluon,
            "mxnet.gluon.parameter": mx.gluon.parameter}
    for name, mod in mods.items():
        # None __spec__ breaks importlib.util.find_spec probes elsewhere
        mod.__spec__ = importlib.machinery.ModuleSpec(name, None)
        sys.modules[name] = mod
    return mx
