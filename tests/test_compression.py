"""Wire-compressed collectives (ISSUE 6): chunked fp8/int8 quantizers,
the compressed bucketed reduce-scatter/all-gather pipeline, per-bucket
error feedback, the autotuner's wire-dtype axis, and the logical-vs-wire
telemetry accounting.

The load-bearing contracts pinned here:

* chunked quantizers round-trip within their format's error bound, pad
  chunk-indivisible buckets correctly, and pass non-float leaves through
  **bit-exactly**;
* the compressed reduce-scatter's all-to-all exchange preserves shard
  ownership (rank-varying inputs reduce to the same shards as the exact
  path);
* the two stale guards are gone — ``overlap_grads`` + compression and
  ``sharded_update`` + compression compose — while genuinely unsupported
  combos (chunked wire + Adasum/Min/Max, chunked wire in a plain
  ``allreduce``) raise loudly;
* error feedback is **load-bearing**: on a 30-step quadratic bowl whose
  gradient absmax is dominated by one outlier coordinate, int8+EF lands
  on the fp32 oracle's parameters while int8 without EF measurably does
  not;
* with compression off, the compiled train step is byte-identical to a
  build with the residual plumbing compiled out.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import horovod_tpu as hvd_api  # noqa: E402
from horovod_tpu import training  # noqa: E402
from horovod_tpu.models.simple import MLP  # noqa: E402
from horovod_tpu.ops import collective, fusion  # noqa: E402
from horovod_tpu.ops import compression as clib  # noqa: E402
from horovod_tpu.parallel import mesh as mesh_lib  # noqa: E402

Compression = clib.Compression

# Per-format round-trip error bound, as a fraction of the chunk absmax:
# bf16 has 8 mantissa bits (2^-8 relative), fp16 11, e4m3 3 bits of
# mantissa (2^-3 relative at the top of the scaled range), e5m2 2 bits,
# int8 one part in 254 of absmax (round-to-nearest over [-127, 127]).
ERR_BOUND = {
    "bf16": 1 / 256,
    "float16": 1 / 2048,
    "fp8_e4m3": 1 / 8,
    "fp8_e5m2": 1 / 4,
    "int8": 1 / 250,
}


# ---------------------------------------------------------------------------
# quantizer unit tests


@pytest.mark.parametrize("name", sorted(ERR_BOUND))
def test_roundtrip_within_format_bound(name):
    c = clib.by_name(name)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(1000),
                    jnp.float32)
    wire, ctx = c.compress(x)
    back = c.decompress(wire, ctx)
    assert back.shape == x.shape and back.dtype == x.dtype
    err = float(jnp.max(jnp.abs(back - x)))
    absmax = float(jnp.max(jnp.abs(x)))
    assert err <= absmax * ERR_BOUND[name], (name, err, absmax)


def test_chunk_size_does_not_divide_bucket():
    """Bucket-boundary case (satellite): n=1000 against chunk=256 pads to
    1024 on the wire; decompress slices the pad back off and the payload
    survives within the int8 bound."""
    q = Compression.int8
    assert q.chunk == clib.DEFAULT_CHUNK == 256
    x = jnp.asarray(np.random.default_rng(1).standard_normal(1000),
                    jnp.float32)
    wire, scales = q.compress_flat(x)
    assert wire.shape == (1024,) and wire.dtype == jnp.int8
    assert scales.shape == (4,) and scales.dtype == jnp.float32
    back = q.decompress_flat(wire, scales, jnp.float32, n=1000)
    assert back.shape == (1000,)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=float(jnp.max(jnp.abs(x))) / 250)
    # wire_bytes accounts the pad AND the scales that ride along
    assert q.wire_bytes(1000, jnp.float32) == 1024 * 1 + 4 * 4


def test_for_length_clamps_chunk_to_shard():
    """A reduce-scatter shard smaller than the configured chunk must not
    ship chunk-rounding padding: for_length clamps, and both ends derive
    the same clamped quantizer from the same static shard size."""
    q = Compression.int8
    small = q.for_length(5)
    assert small.chunk == 5 and small.wire_dtype == q.wire_dtype
    assert q.for_length(1000) is q  # no clamp needed
    x = jnp.asarray([1.0, -2.0, 3.0, -4.0, 5.0], jnp.float32)
    wire, scales = small.compress_flat(x)
    assert wire.shape == (5,) and scales.shape == (1,)
    back = small.decompress_flat(wire, scales, jnp.float32, n=5)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=0.03)


def test_multi_row_compress_preserves_leading_axes():
    """The fusion pipeline quantizes [world, shard] rows; chunks must
    never straddle the row (= shard ownership) boundary."""
    q = Compression.fp8_e4m3
    rows = jnp.asarray(
        np.random.default_rng(2).standard_normal((4, 300)), jnp.float32)
    qq = q.for_length(300)
    wire, scales = qq.compress_flat(rows)
    assert wire.shape[0] == 4 and scales.shape[0] == 4
    back = qq.decompress_flat(wire, scales, jnp.float32, n=300)
    assert back.shape == (4, 300)
    for r in range(4):
        absmax = float(jnp.max(jnp.abs(rows[r])))
        assert float(jnp.max(jnp.abs(back[r] - rows[r]))) <= absmax / 8


@pytest.mark.parametrize("name", ["bf16", "int8", "fp8_e4m3"])
@pytest.mark.parametrize("dtype", [jnp.int32, jnp.int8, jnp.bool_])
def test_nonfloat_leaves_roundtrip_bit_exact(name, dtype):
    """Integer/bool gradients are never narrowed (satellite): they pass
    through both compressor interfaces bit-exactly at their own dtype,
    and wire_bytes accounts them at FULL width — no phantom compression
    ratio for payloads that were not compressed."""
    c = clib.by_name(name)
    x = jnp.asarray(np.asarray([0, 1, 1, 0, 1, 0, 0, 1] * 4), dtype)
    wire, ctx = c.compress(x)
    assert wire.dtype == x.dtype
    back = c.decompress(wire, ctx)
    assert back.dtype == x.dtype
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
    wire_f, scales = c.compress_flat(x)
    assert wire_f.dtype == x.dtype and scales is None
    np.testing.assert_array_equal(
        np.asarray(c.decompress_flat(wire_f, None, x.dtype, n=x.shape[-1])),
        np.asarray(x))
    # full-width accounting for the uncompressed leaf
    assert c.wire_bytes(32, dtype) == 32 * np.dtype(dtype).itemsize


def test_wire_bytes_accounting_float():
    assert Compression.bf16.wire_bytes(100, jnp.float32) == 200
    assert Compression.float16.wire_bytes(100, jnp.float32) == 200
    # 100 elems pad to 256 (one chunk) + one fp32 scale
    assert Compression.int8.wire_bytes(100, jnp.float32) == 256 + 4
    assert Compression.fp8_e4m3.wire_bytes(100, jnp.float32) == 256 + 4


def test_by_name_resolution():
    assert clib.by_name(None) is None
    assert clib.by_name("none") is None
    assert clib.by_name("fp16") is Compression.bf16  # TPU-native alias
    assert clib.by_name("fp8") is Compression.fp8_e4m3
    with pytest.raises(ValueError, match="unknown wire dtype"):
        clib.by_name("fp4")


# ---------------------------------------------------------------------------
# collective/pipeline composition


def test_plain_allreduce_rejects_chunked_wire(hvd):
    """A chunked quantizer's per-chunk scales cannot be summed in flight:
    the plain allreduce must refuse instead of computing garbage."""
    with pytest.raises(ValueError, match="chunked"):
        collective.allreduce(jnp.ones(8), compression=Compression.int8)


def test_chunked_wire_rejects_nonlinear_reductions(hvd):
    tree = {"a": jnp.ones(64)}
    spec = {"a": P()}

    def f(t):
        return fusion.fused_allreduce(t, op=hvd_api.Min, compression="int8")

    g = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(spec,), out_specs=spec,
                      check_vma=False)
    with pytest.raises(ValueError, match="Sum/Average"):
        g(tree)


def test_distributed_optimizer_adasum_rejects_chunked():
    with pytest.raises(ValueError, match="Adasum"):
        hvd_api.DistributedOptimizer(optax.sgd(0.1), op=hvd_api.Adasum,
                                     compression="int8")
    # cast wire (reducible) stays legal with Adasum
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.1), op=hvd_api.Adasum,
                                      compression="bf16")
    assert tx.compression is Compression.bf16


def test_fused_allreduce_mixed_pytree_all_formats(hvd):
    """Satellite: mixed-dtype pytrees through the compressed fused
    allreduce — float leaves within the wire format's bound, non-float
    leaves BIT-exact."""
    rng = np.random.default_rng(5)
    tree = {
        "w": jnp.asarray(rng.standard_normal(257), jnp.float32),
        "b": jnp.asarray(rng.standard_normal((3, 7)), jnp.float32),
        "counts": jnp.asarray(rng.integers(0, 100, 13), jnp.int32),
    }
    spec = jax.tree_util.tree_map(lambda _: P(), tree)
    world = len(jax.devices())

    def run(wire):
        f = jax.shard_map(
            lambda t: fusion.fused_allreduce(t, op=hvd_api.Sum,
                                             compression=wire),
            mesh=hvd.mesh(), in_specs=(spec,), out_specs=spec,
            check_vma=False)
        return f(tree)

    exact = run(None)
    for name in ("bf16", "fp8_e4m3", "int8"):
        got = run(name)
        for key in ("w", "b"):
            assert got[key].dtype == tree[key].dtype
            absmax = float(jnp.max(jnp.abs(exact[key])))
            err = float(jnp.max(jnp.abs(got[key] - exact[key])))
            assert err <= absmax * ERR_BOUND[name] * 2, (name, key, err)
        np.testing.assert_array_equal(np.asarray(got["counts"]),
                                      np.asarray(exact["counts"]))
        np.testing.assert_array_equal(np.asarray(got["counts"]),
                                      world * np.asarray(tree["counts"]))


def test_compressed_reduce_scatter_shard_ownership(hvd):
    """Rank-VARYING inputs: the compressed path's all-to-all must deliver
    rank r's quantized contribution of MY shard to me, in mesh-rank
    order — the same ownership contract as reducescatter. A scrambled
    exchange produces garbage far outside the quantization bound."""
    world = len(jax.devices())
    n = 64

    def body(_):
        r = collective.mesh_rank()
        # distinct, rank-dependent payload
        leaf = (jnp.arange(n, dtype=jnp.float32) + 100.0 * r) / 10.0
        leaves = [leaf]
        schedule = fusion.bucket_schedule(leaves, world=world)
        exact = fusion.reduce_scatter_bucket(schedule, 0, leaves,
                                             op=collective.Average)
        comp, _res = fusion.reduce_scatter_bucket_compressed(
            schedule, 0, leaves, Compression.int8, op=collective.Average)
        return exact, comp

    f = jax.shard_map(body, mesh=hvd.mesh(), in_specs=(P(),),
                      out_specs=(P("data"), P("data")), check_vma=False)
    exact, comp = f(jnp.zeros(world))
    # int8 bound: per-rank error <= chunk_absmax/254, averaged over world
    atol = (100.0 * world / 10.0) / 250
    np.testing.assert_allclose(np.asarray(comp), np.asarray(exact),
                               atol=atol)


def test_overlap_pipeline_guards_lifted(hvd):
    """The two stale refusals are gone: overlap_grads + compression and
    sharded_update + compression now build AND run."""
    model = MLP(features=(10, 3))
    X = jnp.asarray(np.random.default_rng(0).standard_normal((16, 5)),
                    jnp.float32)
    y = jnp.asarray(np.random.default_rng(1).integers(0, 3, 16), jnp.int32)
    for sharded in (False, True):
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.05),
                                          sharded_update=sharded,
                                          compression="int8")
        state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                            X[:1])
        step = training.make_train_step(model, tx, accum_steps=2,
                                        overlap_grads=True, donate=False)
        for _ in range(2):
            state, loss = step(state, X, y)
            assert np.isfinite(float(loss))


def test_config_wire_dtype_is_the_default(hvd):
    """DistributedOptimizer(compression=None) defers to config.wire_dtype
    (the autotuner's wire-axis install target); an explicit "none" forces
    uncompressed regardless of config."""
    from horovod_tpu import basics
    cfg = basics._state.config
    old = cfg.wire_dtype
    try:
        cfg.wire_dtype = "fp8_e5m2"
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.1))
        assert tx.compression is Compression.fp8_e5m2
        tx_off = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                              compression="none")
        assert tx_off.compression is None
    finally:
        cfg.wire_dtype = old


# ---------------------------------------------------------------------------
# error feedback


def _bowl_mesh(n_ranks=2):
    devices = jax.devices()[:n_ranks]
    mesh = mesh_lib.build_mesh(devices=devices, num_slices=1)
    mesh_lib.set_mesh(mesh)
    return mesh, mesh_lib.data_axis_names(mesh), len(devices)


def test_error_feedback_is_load_bearing_quadratic_bowl():
    """Satellite: 30-step quadratic bowl on CPU. The design matrix is
    orthogonal (per-coordinate curvature 2 — a perfectly conditioned
    bowl) and the true optimum has one outlier coordinate at 300, so the
    early gradient absmax is dominated by that coordinate and every
    small-gradient chunk-mate quantizes to ZERO at int8. Without error
    feedback those coordinates receive no update while the outlier
    dominates, and the trajectory deviation they accumulate has a
    component in the problem's one flat direction (bias vs kernel) that
    never decays — the final parameters land measurably off the fp32
    oracle. WITH error feedback the residual carries the rounded-away
    gradients into later steps, and the final parameters land on the
    oracle to ~1e-5: the residual is load-bearing, not decorative."""
    mesh, axes, n = _bowl_mesh(2)
    D = 32
    rng = np.random.default_rng(3)
    Q, _ = np.linalg.qr(rng.standard_normal((D, D)))
    shard_X = Q * np.sqrt(D)  # X^T X = D*I
    w_true = np.ones(D)
    w_true[0] = 300.0
    shard_y = shard_X @ w_true
    X = jnp.asarray(np.tile(shard_X, (n, 1)), jnp.float32)
    y = jnp.asarray(np.tile(shard_y, n), jnp.float32)
    model = MLP(features=(1,))

    def mse(logits, labels):
        return jnp.mean((logits[:, 0] - labels) ** 2)

    def run(wire, ef):
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.4), axes=axes,
                                          compression=wire)
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0), X[:1])
        step = training.make_train_step(model, tx, mesh=mesh, loss_fn=mse,
                                        donate=False, overlap_grads=True,
                                        error_feedback=ef)
        for _ in range(30):
            state, loss = step(state, X, y)
        return float(loss), state.params

    loss_exact, p_exact = run("none", True)
    loss_ef, p_ef = run("int8", True)
    loss_noef, p_noef = run("int8", False)
    assert loss_exact < 1e-6  # the bowl is solvable and solved

    def gap(p):
        return max(float(jnp.max(jnp.abs(a - b))) for a, b in
                   zip(jax.tree_util.tree_leaves(p),
                       jax.tree_util.tree_leaves(p_exact)))

    g_ef, g_noef = gap(p_ef), gap(p_noef)
    # int8+EF lands on the oracle; int8 without EF measurably does not
    # (two orders of magnitude of separation, asserted with margin both
    # ways so neither platform noise nor a broken residual can slip by)
    assert g_ef < 3e-3, f"EF failed to land on the oracle: gap {g_ef}"
    assert g_noef > 3e-2, (
        f"no-EF landed on the oracle (gap {g_noef}) — the bowl no longer "
        "exercises the stall, or EF leaked into the ef=False build")
    assert g_noef > 10 * g_ef


def test_ef_residual_changes_compiled_program_only_when_compressed(hvd):
    """With compression OFF the residual plumbing must vanish: the
    lowered step with error_feedback=True is byte-identical to one with
    it disabled (acceptance: no regression to the uncompressed path)."""
    model = MLP(features=(8, 3))
    X = jnp.zeros((16, 4), jnp.float32)
    y = jnp.zeros((16,), jnp.int32)
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.1), compression="none")
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        X[:1])
    texts = []
    for ef in (True, False):
        step = training.make_train_step(model, tx, donate=False,
                                        overlap_grads=True,
                                        error_feedback=ef)
        texts.append(step.lower(state, X, y).as_text())
    assert texts[0] == texts[1]
    # ...and the same build WITH a wire format is a different program —
    # the off-vs-off identity above is structural (wire=None makes
    # error_feedback select the same build), so this is the direction
    # that catches compression silently not being applied
    tx_on = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                         compression="int8")
    state_on = training.create_train_state(model, tx_on,
                                           jax.random.PRNGKey(0), X[:1])
    step_on = training.make_train_step(model, tx_on, donate=False,
                                       overlap_grads=True)
    assert step_on.lower(state_on, X, y).as_text() != texts[0]


# ---------------------------------------------------------------------------
# the compiled shard_map island (ISSUE 17)


def test_spmd_island_quantizer_bitwise_parity(hvd):
    """The quantizer inside the GSPMD shard_map island is the SAME math
    as the eager ChunkedQuantizer on the same buckets: the quantized
    int8 rows that cross the wire must match BITWISE between the
    compiled island and an eager compress_flat on identical packed
    rows. The fp32 sidecar (per-chunk scales, decode-sum-average,
    gather-decode) is pinned to ulp tolerance instead: XLA may fuse the
    scale divide / decode arithmetic with FMA or a reciprocal multiply,
    which moves the last bit but nothing else."""
    from horovod_tpu.parallel import gspmd

    mesh, axes, world = _bowl_mesh(2)
    plan = gspmd.derive_plan(mesh)
    rng = np.random.default_rng(5)
    leaves = [jnp.asarray(rng.normal(size=s) * 10.0, jnp.float32)
              for s in [(7, 5), (300,), (4, 4)]]
    schedule = fusion.bucket_schedule(leaves, world=world, axes=axes)
    wire = Compression.int8

    def island_fn(*ls):
        encs, shards, flats = [], [], []
        for i in range(len(schedule.buckets)):
            shard = schedule.shard_sizes[i]
            rows = fusion._pack_padded(schedule, i, list(ls)).reshape(
                world, shard)
            q = wire.for_length(shard)
            encs.append(q.compress_flat(rows))
            s, _ = fusion.reduce_scatter_bucket_compressed(
                schedule, i, list(ls), wire, op=collective.Average)
            f, _ = fusion.all_gather_bucket_compressed(
                schedule, i, s, wire)
            shards.append(s[None])
            flats.append(f)
        return tuple(encs), tuple(shards), tuple(flats)

    fn = gspmd.shard_map_island(
        island_fn, plan,
        in_specs=tuple(P() for _ in leaves),
        out_specs=(tuple((P(), P()) for _ in schedule.buckets),
                   tuple(P(tuple(axes)) for _ in schedule.buckets),
                   tuple(P() for _ in schedule.buckets)))
    got_encs, got_shards, got_flats = jax.jit(fn)(*leaves)

    for i in range(len(schedule.buckets)):
        shard = schedule.shard_sizes[i]
        flat = fusion._pack_padded(schedule, i, leaves)
        rows = flat.reshape(world, shard)
        q = wire.for_length(shard)
        wire_rows, scales = q.compress_flat(rows)
        # the wire payload is bit-identical compiled vs eager
        np.testing.assert_array_equal(
            np.asarray(got_encs[i][0]), np.asarray(wire_rows),
            err_msg=f"bucket {i}: island wire rows != eager quantizer")
        np.testing.assert_allclose(
            np.asarray(got_encs[i][1]), np.asarray(scales),
            rtol=1e-6,
            err_msg=f"bucket {i}: island scales != eager quantizer")
        # ...and the decoded data plane matches to the last fused bit
        exp_shards = []
        for k in range(world):
            # every peer contributes the identical encoded row k
            recv_rows = jnp.stack([wire_rows[k]] * world)
            recv_scales = jnp.stack([scales[k]] * world)
            vals = q.decompress_flat(recv_rows, recv_scales,
                                     jnp.float32, n=shard)
            exp_shards.append(jnp.sum(vals, axis=0) / world)
        np.testing.assert_allclose(
            np.asarray(got_shards[i]), np.stack(exp_shards),
            rtol=1e-6, atol=1e-5,
            err_msg=f"bucket {i}: island RS != eager quantizer")
        enc = [q.compress_flat(s) for s in exp_shards]
        exp_flat = q.decompress_flat(
            jnp.stack([e[0] for e in enc]),
            jnp.stack([e[1] for e in enc]),
            jnp.float32, n=shard).reshape(world * shard)
        np.testing.assert_allclose(
            np.asarray(got_flats[i]), np.asarray(exp_flat),
            rtol=1e-6, atol=1e-5,
            err_msg=f"bucket {i}: island AG != eager quantizer")


def test_spmd_error_feedback_is_load_bearing_quadratic_bowl(hvd):
    """The explicit path's EF-is-load-bearing bowl, run through the
    compiled island (spmd=True): int8+EF lands on the fp32 oracle,
    int8 without EF measurably stalls — the residual carry threaded
    through the jit argument is doing real work, not decoration."""
    mesh, axes, n = _bowl_mesh(2)
    D = 32
    rng = np.random.default_rng(3)
    Q, _ = np.linalg.qr(rng.standard_normal((D, D)))
    shard_X = Q * np.sqrt(D)  # X^T X = D*I
    w_true = np.ones(D)
    w_true[0] = 300.0
    shard_y = shard_X @ w_true
    X = jnp.asarray(np.tile(shard_X, (n, 1)), jnp.float32)
    y = jnp.asarray(np.tile(shard_y, n), jnp.float32)
    model = MLP(features=(1,))

    def mse(logits, labels):
        return jnp.mean((logits[:, 0] - labels) ** 2)

    def run(wire, ef):
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.4), axes=axes,
                                          compression=wire)
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0), X[:1])
        step = training.make_train_step(model, tx, mesh=mesh,
                                        loss_fn=mse, donate=False,
                                        spmd=True, error_feedback=ef)
        for _ in range(30):
            state, loss = step(state, X, y)
        return float(loss), state.params

    loss_exact, p_exact = run("none", True)
    loss_ef, p_ef = run("int8", True)
    loss_noef, p_noef = run("int8", False)
    assert loss_exact < 1e-6

    def gap(p):
        return max(float(jnp.max(jnp.abs(a - b))) for a, b in
                   zip(jax.tree_util.tree_leaves(p),
                       jax.tree_util.tree_leaves(p_exact)))

    g_ef, g_noef = gap(p_ef), gap(p_noef)
    assert g_ef < 3e-3, f"EF failed to land on the oracle: gap {g_ef}"
    assert g_noef > 3e-2, (
        f"no-EF landed on the oracle (gap {g_noef}) — the island no "
        "longer exercises the stall, or EF leaked into ef=False")
    assert g_noef > 10 * g_ef


# ---------------------------------------------------------------------------
# autotune wire axis


def test_autotune_joint_wire_axis(hvd):
    """wire_candidates turns the search grid into the (threshold, wire)
    cross product, reusing the abstain machinery; apply installs BOTH
    config.fusion_threshold and config.wire_dtype."""
    from horovod_tpu import basics
    tree = {"a": jnp.ones((512,)), "b": jnp.ones((64, 8))}
    candidates = [1 << 10, 1 << 20]
    wires = ["none", "int8"]
    best, timings = fusion.autotune_fusion_threshold(
        tree, candidates=candidates, trials=2, wire_candidates=wires)
    assert set(timings) == {(t, w) for t in candidates for w in wires}
    assert all(float(v) > 0 for v in timings.values())
    if best is None:
        assert timings.abstain_reason
        return
    thr, wire = best
    assert thr in candidates and wire in wires
    assert basics._state.config.fusion_threshold == thr
    assert basics._state.config.wire_dtype == (None if wire == "none"
                                               else wire)


def test_autotune_wire_axis_rejects_typo():
    with pytest.raises(ValueError, match="unknown wire dtype"):
        fusion.autotune_fusion_threshold(
            {"a": jnp.ones(8)}, candidates=[1 << 20], trials=1,
            wire_candidates=["int9"])


# ---------------------------------------------------------------------------
# telemetry accounting


def test_record_collective_logical_vs_wire_bytes():
    from horovod_tpu import telemetry
    from horovod_tpu.telemetry import instruments
    reg = telemetry.get_registry()

    def total(name, op):
        fam = reg.get(name)
        if fam is None:
            return 0.0
        s = fam.sample()
        return float(s.get((op,), 0.0)) if isinstance(s, dict) else float(s)

    w0 = total(instruments.COLLECTIVE_BYTES, "testop")
    l0 = total(instruments.COLLECTIVE_LOGICAL_BYTES, "testop")
    instruments.record_collective("testop", 512, logical_nbytes=2048)
    assert total(instruments.COLLECTIVE_BYTES, "testop") - w0 == 512
    assert total(instruments.COLLECTIVE_LOGICAL_BYTES, "testop") - l0 == 2048
    # without logical_nbytes the two families advance in lockstep
    instruments.record_collective("testop", 100)
    assert total(instruments.COLLECTIVE_BYTES, "testop") - w0 == 612
    assert total(instruments.COLLECTIVE_LOGICAL_BYTES, "testop") - l0 == 2148
    # the ratio gauge is derived from the same counters at collect time
    fam = reg.get(instruments.WIRE_COMPRESSION_RATIO)
    assert fam is not None
    assert float(fam.sample()) >= 1.0


def test_record_bucket_per_dtype_wire_accounting():
    from horovod_tpu import telemetry
    from horovod_tpu.telemetry import instruments
    reg = telemetry.get_registry()

    def total(name, dtype):
        fam = reg.get(name)
        if fam is None:
            return 0.0
        s = fam.sample()
        return float(s.get((dtype,), 0.0)) if isinstance(s, dict) \
            else float(s)

    key = "float32"
    w0 = total(instruments.WIRE_BYTES, key)
    l0 = total(instruments.WIRE_LOGICAL_BYTES, key)
    instruments.record_bucket("rs", 1.0, 260, logical_nbytes=1024,
                              dtype=jnp.dtype(jnp.float32))
    assert total(instruments.WIRE_BYTES, key) - w0 == 260
    assert total(instruments.WIRE_LOGICAL_BYTES, key) - l0 == 1024


def test_compressed_pipeline_reports_compressed_bytes(hvd):
    """End to end: a compressed fused allreduce advances the wire-bytes
    counter by LESS than the logical-bytes counter (the per-op
    compression ratio is derivable from /metrics)."""
    from horovod_tpu import telemetry
    from horovod_tpu.telemetry import instruments
    reg = telemetry.get_registry()

    def totals():
        out = []
        for name in (instruments.COLLECTIVE_BYTES,
                     instruments.COLLECTIVE_LOGICAL_BYTES):
            fam = reg.get(name)
            s = fam.sample() if fam is not None else {}
            out.append(sum(s.values()) if isinstance(s, dict)
                       else float(s or 0.0))
        return out

    tree = {"w": jnp.ones(4096, jnp.float32)}
    spec = {"w": P()}
    w0, l0 = totals()
    f = jax.shard_map(
        lambda t: fusion.fused_allreduce(t, op=hvd_api.Sum,
                                         compression="int8"),
        mesh=hvd.mesh(), in_specs=(spec,), out_specs=spec, check_vma=False)
    f(tree)
    w1, l1 = totals()
    assert l1 - l0 > 0
    # int8 wire: ~1/4 the logical fp32 bytes (plus scales). The bound is
    # over the CUMULATIVE families — the bucket aggregates and the inner
    # alltoall/allgather dispatches they wrap must agree on what was
    # narrowed (the inner collectives record their logical width too;
    # scales ride as logical-0 overhead), or the ratio degrades toward 2.
    ratio = (l1 - l0) / (w1 - w0)
    assert ratio > 3.0, f"cumulative logical/wire ratio {ratio:.2f}"


def test_chunked_rs_wire_bytes_counts_per_row_padding(hvd):
    """The chunked reduce-scatter's wire-byte record must price what the
    alltoall actually ships: EACH of the world [shard]-rows pads to a
    chunk multiple and carries its own scales — pricing one flat-bucket
    encode undercounts whenever chunk does not divide the shard."""
    from horovod_tpu import telemetry
    from horovod_tpu.telemetry import instruments
    mesh, axes, world = _bowl_mesh(2)
    reg = telemetry.get_registry()

    def total():
        fam = reg.get(instruments.COLLECTIVE_BYTES)
        s = fam.sample() if fam is not None else {}
        return float(s.get(("bucket_rs",), 0.0))

    leaves = [jnp.zeros(600, jnp.float32)]  # shard=300: 256 !| 300
    schedule = fusion.bucket_schedule(leaves, world=world,
                                      threshold_bytes=1 << 30, axes=axes)
    q = Compression.int8

    def body(x):
        shard, _ = fusion.reduce_scatter_bucket_compressed(
            schedule, 0, [x], q, op=hvd_api.Sum)
        return shard

    b0 = total()
    jax.shard_map(body, mesh=mesh, in_specs=(P(),),
                  out_specs=P(axes), check_vma=False)(leaves[0])
    # per row: padded(300)=512 int8 bytes + 2 fp32 scales, x world rows
    assert total() - b0 == (512 + 2 * 4) * world


def test_config_wire_dtype_binds_late(hvd):
    """The config deferral resolves at ACCESS time, not construction: an
    optimizer built before the autotuner installs its wire-axis winner
    (or before hvd.init() populates the config) still picks it up."""
    from horovod_tpu import basics
    cfg = basics._state.config
    old = cfg.wire_dtype
    try:
        cfg.wire_dtype = None
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.1))
        assert tx.compression is None
        cfg.wire_dtype = "int8"          # autotune installs after build
        assert tx.compression is Compression.int8
        cfg.wire_dtype = None
        assert tx.compression is None
        # an explicit "none" given at construction stays pinned off
        tx_off = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                              compression="none")
        cfg.wire_dtype = "fp8_e4m3"
        assert tx_off.compression is None
        # the non-sharded chained transform must not freeze a stale
        # resolution at init(): install-after-init rebuilds the chain
        # with the new wire (regression: init() -> autotune installs ->
        # update() trained uncompressed while tx.compression lied)
        cfg.wire_dtype = None
        tx2 = hvd_api.DistributedOptimizer(optax.sgd(0.1))
        tx2.init({"w": jnp.ones(4)})
        assert tx2._transform_wire is None
        cfg.wire_dtype = "int8"
        tx2._ensure_transform()
        assert tx2._transform_wire is Compression.int8
    finally:
        cfg.wire_dtype = old


def test_step_failure_does_not_brick_error_feedback(hvd):
    """The EF residuals are donated into each dispatch; a step call that
    raises must drop the carried buffers so the NEXT call (the elastic
    retry path) rebuilds zeros instead of dying on deleted arrays, and
    reset_error_feedback() gives rollbacks an explicit restart."""
    model = MLP(features=(10, 3))
    X = jnp.asarray(np.random.default_rng(0).standard_normal((16, 5)),
                    jnp.float32)
    y = jnp.asarray(np.random.default_rng(1).integers(0, 3, 16), jnp.int32)
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.05), sharded_update=True,
                                      compression="int8")
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        X[:1])
    step = training.make_train_step(model, tx, accum_steps=2,
                                    overlap_grads=True)  # donate=True
    state, _ = step(state, X, y)  # populates + donates the residuals
    with pytest.raises(Exception):
        step(state, X[:, :3], y)  # wrong feature width — dispatch fails
    state, loss = step(state, X, y)  # must NOT raise "Array has been deleted"
    assert np.isfinite(float(loss))
    step.reset_error_feedback()
    state, loss = step(state, X, y)
    assert np.isfinite(float(loss))


def test_overlap_step_warns_on_wire_drift(hvd):
    """The overlapped step bakes the wire format at build time; a config
    install AFTER the build cannot apply — the step must warn at the
    next call instead of silently training at the stale format while
    tx.compression reports the new one."""
    from horovod_tpu import basics
    cfg = basics._state.config
    old = cfg.wire_dtype
    try:
        cfg.wire_dtype = None
        model = MLP(features=(8, 3))
        X = jnp.zeros((16, 4), jnp.float32)
        y = jnp.zeros((16,), jnp.int32)
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.1))
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0), X[:1])
        step = training.make_train_step(model, tx, donate=False,
                                        overlap_grads=True)
        state, _ = step(state, X, y)  # no drift yet: no warning
        cfg.wire_dtype = "int8"       # autotune installs after build
        with pytest.warns(UserWarning, match="baked into the compiled"):
            step(state, X, y)
    finally:
        cfg.wire_dtype = old


def test_error_feedback_residual_stays_fp32_for_bf16_grads(hvd):
    """The EF carry must not be truncated to the gradient dtype: for
    bf16 gradients the int8 quantization error sits at or below the
    bf16 ulp, so compensation done AT bf16 would round away entirely."""
    mesh, axes, world = _bowl_mesh(2)
    vals = np.linspace(0.5, 1.0, 512, dtype=np.float32)
    leaves = [jnp.asarray(vals, jnp.bfloat16)]
    schedule = fusion.bucket_schedule(leaves, world=world,
                                      threshold_bytes=1 << 30, axes=axes)
    shard = schedule.shard_sizes[0]
    res0 = jnp.zeros((schedule.padded_sizes[0],), jnp.float32)

    def body(x, r):
        out, new_r = fusion.reduce_scatter_bucket_compressed(
            schedule, 0, [x], Compression.int8, op=hvd_api.Sum,
            residual=r)
        return out, new_r

    out, new_r = jax.shard_map(
        body, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P(axes), P()), check_vma=False)(leaves[0], res0)
    assert out.dtype == jnp.bfloat16          # output stays at grad dtype
    assert new_r.dtype == jnp.float32         # carry stays fp32
    # replicate the pipeline's fp32 math: the carry must be the EXACT
    # fp32 quantization error of the bf16-representable inputs, not a
    # bf16-rounded version of it (which would be ~all zeros here)
    rows32 = np.asarray(leaves[0], np.float32).reshape(world, shard)
    q = Compression.int8.for_length(shard)
    _, _, deq = q.roundtrip(jnp.asarray(rows32))
    expected = rows32 - np.asarray(deq, np.float32)
    got = np.asarray(new_r, np.float32).reshape(world, shard)
    np.testing.assert_array_equal(got, expected)
    assert np.abs(expected).max() > 0  # the signal exists to be kept


def test_ef_residuals_follow_the_step_mesh_not_the_global(hvd):
    """The residual buffers must be shaped against the mesh the step was
    BUILT on: a sub-mesh step built while a bigger global mesh is set
    would otherwise allocate [global_world, n] buffers against a
    [sub_world]-sharded schedule."""
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs a sub-mesh smaller than the global mesh")
    sub = mesh_lib.build_mesh(devices=devs[:2], num_slices=1)
    axes = mesh_lib.data_axis_names(sub)
    model = MLP(features=(8, 3))
    X = jnp.zeros((8, 4), jnp.float32)
    y = jnp.zeros((8,), jnp.int32)
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.1), axes=axes,
                                      compression="int8")
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        X[:1])
    # global mesh (all devices) stays set; the step gets the sub-mesh
    step = training.make_train_step(model, tx, mesh=sub, donate=False,
                                    overlap_grads=True)
    state, loss = step(state, X, y)
    assert np.isfinite(float(loss))


def test_autotune_eager_fallback_abstains_on_chunked_only(monkeypatch):
    """The eager (no-mesh) fallback cannot time chunked quantizers; an
    all-chunked wire grid must warn + abstain instead of dying mid-trial
    on 'needs the compiled mesh path'."""
    from horovod_tpu import _core
    from horovod_tpu.parallel import mesh as pmesh

    def no_mesh():
        raise RuntimeError("no mesh")

    monkeypatch.setattr(pmesh, "get_mesh", no_mesh)
    monkeypatch.setattr(_core, "is_initialized", lambda: True)
    monkeypatch.setattr(_core, "size", lambda: 2)
    tree = {"w": jnp.ones(64, jnp.float32)}
    with pytest.warns(UserWarning, match="dropping chunked"):
        best, timings = fusion.autotune_fusion_threshold(
            tree, candidates=[1 << 20], apply=False,
            wire_candidates=["int8", "fp8_e4m3"])
    assert best is None
    assert "chunked" in timings.abstain_reason


def test_config_wire_incompatible_with_op_is_ignored_with_warning(hvd):
    """A config-INSTALLED default wire that cannot ride this optimizer's
    op must be ignored (warned), not retroactively brick training; only
    an explicit argument hard-errors."""
    from horovod_tpu import basics
    cfg = basics._state.config
    old = cfg.wire_dtype
    try:
        cfg.wire_dtype = "int8"
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                          op=hvd_api.Adasum)
        with pytest.warns(UserWarning, match="ignoring config.wire_dtype"):
            assert tx.compression is None
        assert tx.compression is None  # warned once, stays ignored
    finally:
        cfg.wire_dtype = old


def test_hierarchical_cast_dispatch_keeps_logical_attribution(hvd2d):
    """The hierarchical branch composes raw lax collectives that record
    nothing; the dispatch-level record must keep a cast-compressed
    payload's wire-vs-logical split."""
    from horovod_tpu import telemetry
    from horovod_tpu.telemetry import instruments
    reg = telemetry.get_registry()

    def totals():
        out = []
        for name in (instruments.COLLECTIVE_BYTES,
                     instruments.COLLECTIVE_LOGICAL_BYTES):
            fam = reg.get(name)
            s = fam.sample() if fam is not None else {}
            out.append(float(s.get(("hier_allreduce",), 0.0)))
        return out

    tree = {"w": jnp.ones(512, jnp.float32)}
    spec = {"w": P()}
    w0, l0 = totals()
    jax.shard_map(
        lambda t: fusion.fused_allreduce(t, op=hvd_api.Sum,
                                         compression="bf16",
                                         hierarchical=True),
        mesh=hvd2d.mesh(), in_specs=(spec,), out_specs=spec,
        check_vma=False)(tree)
    w1, l1 = totals()
    assert l1 - l0 == 512 * 4          # logical fp32 width
    assert w1 - w0 == 512 * 2          # bf16 on the wire


def test_hierarchical_ignored_for_chunked_wire_warns(hvd2d):
    """fused_allreduce(hierarchical=True) with a chunked wire on a
    dcn-bearing mesh warns that the two-level reduction is dropped
    instead of silently eating the knob."""
    tree = {"w": jnp.ones(512, jnp.float32)}
    spec = {"w": P()}

    def body(t):
        return fusion.fused_allreduce(t, op=hvd_api.Sum,
                                      compression="int8",
                                      hierarchical=True)

    f = jax.shard_map(body, mesh=hvd2d.mesh(), in_specs=(spec,),
                      out_specs=spec, check_vma=False)
    with pytest.warns(UserWarning, match="hierarchical"):
        f(tree)


# ---------------------------------------------------------------------------
# tier-1 smoke: the dryrun's compressed parity section


@pytest.mark.slow
def test_dryrun_compressed_parity_section():
    """Satellite (bench/CI): the dryrun oracle-parity harness's wire-
    compression section — int8+EF and fp8+EF trajectories within the
    documented epsilon of the exact fp32 path, byte-identical compiled
    program with compression off — passes on the CPU image."""
    import __graft_entry__ as graft
    graft._dryrun_wire_compression(jax.devices()[:2])
