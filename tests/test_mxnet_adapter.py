"""MXNet adapter tests (reference: test/test_mxnet.py — op correctness,
DistributedOptimizer grad averaging, DistributedTrainer, parameter
broadcast). mxnet is not baked into this image, so the adapter runs
against the numpy-backed stand-in in ``fake_mxnet.py`` — the adapter
code paths are identical either way (NDArrays bridge through
``asnumpy``/slice-assign). Multi-process cases ride api.run."""

import os

import numpy as np
import pytest

import fake_mxnet

from horovod_tpu.run import api

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture()
def hvd_mx(hvd):
    fake_mxnet.install()
    import horovod_tpu.mxnet as hvd_m
    yield hvd_m
    from horovod_tpu import _core
    _core.shutdown()


@pytest.fixture()
def mx():
    return fake_mxnet.install()


def _mx_env():
    """Workers must import the fake before horovod_tpu.mxnet — passed
    via extra_env, never by mutating this process's environ."""
    existing = os.environ.get("PYTHONPATH", "")
    return {"JAX_PLATFORMS": "cpu",
            "PYTHONPATH": os.pathsep.join(
                [p for p in [TESTS_DIR, existing] if p])}


# ---- single-process semantics ------------------------------------------

def test_single_process_ops(hvd_mx, mx):
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = hvd_mx.allreduce(x)
    np.testing.assert_array_equal(out.asnumpy(), x.asnumpy())
    out = hvd_mx.allgather(x)
    np.testing.assert_array_equal(out.asnumpy(), x.asnumpy())
    y = mx.nd.array(x.asnumpy())
    hvd_mx.broadcast_(y, root_rank=0)
    np.testing.assert_array_equal(y.asnumpy(), x.asnumpy())


def test_optimizer_wraps_inner(hvd_mx, mx):
    opt = hvd_mx.DistributedOptimizer(mx.optimizer.Optimizer(0.5))
    w = mx.nd.array(np.ones(4, dtype=np.float32))
    g = mx.nd.array(np.full(4, 2.0, dtype=np.float32))
    opt.update(0, w, g, None)
    np.testing.assert_allclose(w.asnumpy(), np.zeros(4))  # 1 - 0.5*2
    opt.set_learning_rate(0.1)
    assert opt._optimizer.lr == 0.1
    assert opt.create_state(0, w) is None


def test_broadcast_parameters_dict(hvd_mx, mx):
    params = {"w": mx.nd.array(np.ones(3)), "b": mx.nd.array(np.zeros(2))}
    hvd_mx.broadcast_parameters(params, root_rank=0)  # size 1: identity
    np.testing.assert_array_equal(params["w"].asnumpy(), np.ones(3))
    with pytest.raises(ValueError, match="invalid params type"):
        hvd_mx.broadcast_parameters([1, 2, 3])


# ---- multi-process end-to-end ------------------------------------------

def test_mxnet_distributed_optimizer_averages():
    def fn():
        import numpy as np

        import fake_mxnet
        mx = fake_mxnet.install()
        import horovod_tpu.mxnet as hvd
        hvd.init()
        opt = hvd.DistributedOptimizer(mx.optimizer.Optimizer(0.1))
        w = mx.nd.array(np.ones(4, dtype=np.float32))
        g = mx.nd.array(np.full(4, hvd.rank() + 1.0, dtype=np.float32))
        opt.update(0, w, g, None)
        return w.asnumpy().tolist()

    results = api.run(fn, np=2, extra_env=_mx_env())
    # mean grad = 1.5 -> w = 1 - 0.1*1.5 on every rank
    for r in results:
        np.testing.assert_allclose(r, np.full(4, 0.85), rtol=1e-6)


def test_mxnet_trainer_and_broadcast():
    def fn():
        import numpy as np

        import fake_mxnet
        mx = fake_mxnet.install()
        import horovod_tpu.mxnet as hvd
        hvd.init()

        w = mx.gluon.Parameter(
            "w", np.full(3, float(hvd.rank()), dtype=np.float32))
        hvd.broadcast_parameters({"w": w.data()}, root_rank=0)

        trainer = hvd.DistributedTrainer(
            [w], mx.optimizer.Optimizer(learning_rate=1.0))
        w.list_grad()[0][:] = np.full(3, hvd.rank() + 1.0, dtype=np.float32)
        trainer.step(batch_size=1)
        return w.data().asnumpy().tolist()

    results = api.run(fn, np=2, extra_env=_mx_env())
    # broadcast: w=0 everywhere; allreduce(sum) grads = 3, scale 1/size
    # -> effective mean grad 1.5 -> w = 0 - 1.5
    for r in results:
        np.testing.assert_allclose(r, np.full(3, -1.5), rtol=1e-6)


def test_mxnet_deferred_init_broadcasts_at_materialization():
    """A shape-deferred gluon parameter must be armed by
    broadcast_parameters so that when the engine materializes it (first
    forward), every rank ends up with root's values — not its own random
    init (reference mxnet/__init__.py:118-153 _append_broadcast_init)."""
    def fn():
        import numpy as np

        import fake_mxnet
        mx = fake_mxnet.install()
        import horovod_tpu.mxnet as hvd
        hvd.init()

        ready = mx.gluon.Parameter(
            "ready", np.full(2, float(hvd.rank()), dtype=np.float32))
        deferred = mx.gluon.Parameter("emb", data=None)  # shape unknown

        class ParamDict:  # gluon's ParameterDict is not a dict subclass
            def __init__(self, **kw):
                self._p = kw

            def items(self):
                return self._p.items()

        hvd.broadcast_parameters(
            ParamDict(ready=ready, emb=deferred), root_rank=0)
        # the ready param synced immediately; the deferred one is armed
        before = ready.data().asnumpy().tolist()

        # engine materializes at first forward with rank-divergent init
        deferred._finish_deferred_init(
            np.full((2, 3), 10.0 + hvd.rank(), dtype=np.float32))
        after = deferred.data().asnumpy().tolist()
        return before, after

    results = api.run(fn, np=2, extra_env=_mx_env())
    for before, after in results:
        np.testing.assert_allclose(before, np.zeros(2))      # root's 0s
        np.testing.assert_allclose(after, np.full((2, 3), 10.0))
