"""Data-plane tests: PrefetchLoader determinism, overlap, cursor
checkpointing, elastic resharding, sources, and the doctor's
producer-naming data-stall verdict (ISSUE 7 / docs/DATA.md).

The determinism battery never relies on thread timing: which indices
make up batch b is a pure function of (cursor, membership), so streams
are compared bit-for-bit. The overlap proof is the one wall-clock test
(injected per-batch latency; retried like the other timing tests — the
structural asserts run every attempt)."""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from horovod_tpu.data import (ArraySource, FileSource, PrefetchLoader,
                              segment)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect(loader, n=None):
    """Consume up to ``n`` batches (all, when None) as a list."""
    out = []
    for batch in loader:
        out.append(batch)
        if n is not None and len(out) >= n:
            break
    return out


def flat(batches):
    return [x for b in batches for x in np.asarray(b[0]).ravel().tolist()]


def make_xy(n=48):
    xs = np.arange(n, dtype=np.float32)
    return ArraySource([xs, xs * 10])


# ---- stream determinism / coverage ---------------------------------------

def test_loader_covers_epoch_disjointly_across_ranks():
    streams = {}
    for r in range(2):
        ld = PrefetchLoader(make_xy(), 4, rank=r, world=2, seed=7,
                            epochs=1)
        streams[r] = collect(ld)
        ld.close()
    assert all(len(v) == 6 for v in streams.values())
    seen = flat(streams[0]) + flat(streams[1])
    assert sorted(seen) == list(np.arange(48.0))
    # labels ride along row-aligned
    for b in streams[0]:
        np.testing.assert_array_equal(b[1], b[0] * 10)


def test_loader_stream_is_deterministic():
    a = PrefetchLoader(make_xy(), 4, rank=1, world=2, seed=3, epochs=2)
    b = PrefetchLoader(make_xy(), 4, rank=1, world=2, seed=3, epochs=2)
    sa, sb = collect(a), collect(b)
    a.close(), b.close()
    assert len(sa) == len(sb) > 0
    for x, y in zip(sa, sb):
        np.testing.assert_array_equal(x[0], y[0])


def test_loader_epochs_reshuffle_and_stop():
    ld = PrefetchLoader(make_xy(), 8, rank=0, world=1, seed=0, epochs=2)
    batches = collect(ld)
    assert len(batches) == 12  # 48/8 per epoch x 2 epochs
    e0, e1 = flat(batches[:6]), flat(batches[6:])
    assert sorted(e0) == sorted(e1)
    assert e0 != e1  # epoch-keyed reshuffle
    with pytest.raises(StopIteration):  # exhausted stays exhausted
        next(ld)
    ld.close()
    with pytest.raises(RuntimeError, match="closed"):
        next(ld)


def test_loader_zero_batch_config_raises():
    ld = PrefetchLoader(make_xy(8), 16, rank=0, world=1, epochs=1)
    with pytest.raises(ValueError, match="zero full batches"):
        next(ld)
    ld.close()


# ---- mid-epoch resume (satellite: resume determinism) --------------------

def test_cursor_resume_is_bit_identical_mid_epoch():
    ref = PrefetchLoader(make_xy(), 4, rank=0, world=2, seed=7, epochs=1)
    reference = collect(ref)
    ref.close()

    first = PrefetchLoader(make_xy(), 4, rank=0, world=2, seed=7,
                           epochs=1)
    head = collect(first, 2)
    cur = first.cursor()
    first.close()  # "the run died here"; prefetched batches are lost

    resumed = PrefetchLoader(make_xy(), 4, rank=0, world=2, seed=7,
                             epochs=1)
    resumed.set_cursor(json.loads(json.dumps(cur)))  # manifest roundtrip
    tail = collect(resumed)
    resumed.close()

    got = head + tail
    assert len(got) == len(reference)
    for a, b in zip(got, reference):
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])


def test_cursor_resume_across_epoch_boundary():
    ref = PrefetchLoader(make_xy(), 8, rank=0, world=1, seed=1, epochs=2)
    reference = collect(ref)
    ref.close()
    first = PrefetchLoader(make_xy(), 8, rank=0, world=1, seed=1,
                           epochs=2)
    head = collect(first, 7)  # one past the first epoch's 6 batches
    cur = first.cursor()
    first.close()
    assert cur["epoch"] == 1 and cur["batch_index"] == 1
    resumed = PrefetchLoader(make_xy(), 8, rank=0, world=1, seed=1,
                             epochs=2)
    resumed.set_cursor(cur)
    tail = collect(resumed)
    resumed.close()
    for a, b in zip(head + tail, reference):
        np.testing.assert_array_equal(a[0], b[0])


def test_cursor_rejects_mismatched_batch_size():
    ld = PrefetchLoader(make_xy(), 4, rank=0, world=1)
    cur = ld.cursor()
    ld.close()
    other = PrefetchLoader(make_xy(), 8, rank=0, world=1)
    with pytest.raises(ValueError, match="batch_size"):
        other.set_cursor(cur)
    other.close()


# ---- elastic resharding (satellite: 2->3 exactly once) -------------------

def test_elastic_2_to_3_reshard_visits_remaining_exactly_once():
    n, B = 64, 4
    mk = lambda: ArraySource([np.arange(n)])  # noqa: E731
    old = [PrefetchLoader(mk(), B, rank=r, world=2, seed=1, epochs=1,
                          drop_last=False) for r in range(2)]
    seen = []
    for ld in old:
        seen += flat(collect(ld, 2))  # 2 batches per rank pre-reshard
    cursors = [ld.cursor() for ld in old]
    for ld in old:
        ld.close()
    assert cursors[0] == cursors[1]  # membership-invariant cursor
    assert len(seen) == 2 * 2 * B

    # a NEW 3-rank membership restores the 2-rank cursor: consumption
    # retires into offset, the remaining 48 examples re-stride over 3
    new = [PrefetchLoader(mk(), B, rank=r, world=3, seed=1, epochs=1,
                          drop_last=False) for r in range(3)]
    after = []
    for ld in new:
        ld.set_cursor(cursors[0])
        after += flat(collect(ld))
        ld.close()
    total = seen + after
    assert len(total) == n
    assert sorted(total) == list(range(n))  # exactly once, none dropped


def test_on_reset_reshards_survivors_without_loss():
    n, B = 60, 5
    mk = lambda: ArraySource([np.arange(n)])  # noqa: E731
    lds = [PrefetchLoader(mk(), B, rank=r, world=2, seed=1, epochs=1,
                          drop_last=False) for r in range(2)]
    seen = []
    for ld in lds:
        seen += flat(collect(ld, 3))
    lds[0].on_reset(new_world=1, new_rank=0)  # rank 1 died
    rest = flat(collect(lds[0]))
    for ld in lds:
        ld.close()
    assert sorted(seen + rest) == list(range(n))


def test_drop_last_false_pads_at_global_batch_granularity():
    # 10 examples, world 2, batch 3 -> one global batch is 6; the epoch
    # pads 10 -> 12 (2 wrap duplicates), drops nothing
    seg = segment(10, world=2, batch_size=3, shuffle=False,
                  drop_last=False)
    assert len(seg) == 12
    assert sorted(set(seg.tolist())) == list(range(10))
    seg = segment(10, world=2, batch_size=3, shuffle=False,
                  drop_last=True)
    assert len(seg) == 6  # trimmed to full global batches


# ---- overlap (satellite: CI fake-clock overlap proof) --------------------

def test_prefetch_overlaps_load_with_compute():
    """The tentpole claim, measured: with per-batch injected source
    latency L and per-step consumer compute C, wall time must be ~
    max-leg (first-load fill + N*C here, C >= L), NOT the serial sum
    N*(L+C). Retried up to 3x for wall-clock noise (shared CI);
    structural asserts run every attempt."""
    L = C = 0.02
    nb, B = 8, 8
    last_dt = None
    for _attempt in range(3):
        src = ArraySource([np.arange(nb * B, dtype=np.float32)],
                          delay_s=L)
        ld = PrefetchLoader(src, B, rank=0, world=1, epochs=1, depth=2,
                            shuffle=False)
        t0 = time.perf_counter()
        got = 0
        for _batch in ld:
            time.sleep(C)  # the "train step"
            got += 1
        dt = time.perf_counter() - t0
        ld.close()
        assert got == nb
        serial = nb * (L + C)
        overlapped_bound = 0.75 * serial  # true target: L + nb*C ~= 0.18
        assert dt >= nb * C - 0.005  # the compute leg is irreducible
        last_dt = dt
        if dt < overlapped_bound:
            return
    pytest.fail(
        f"no overlap: {nb} batches of load={L}s + compute={C}s took "
        f"{last_dt:.3f}s, >= 75% of the serial {serial:.3f}s")


def test_wait_metric_counts_genuine_stalls_only():
    from horovod_tpu.telemetry import DataInstruments
    from horovod_tpu.telemetry.registry import MetricsRegistry

    inst = DataInstruments(MetricsRegistry())
    src = ArraySource([np.arange(32, dtype=np.float32)], delay_s=0.03)
    ld = PrefetchLoader(src, 8, rank=0, world=1, epochs=1, depth=2,
                        telemetry=inst)
    for _batch in ld:
        time.sleep(0.05)  # compute-bound: producer always ahead
    ld.close()
    assert inst.batches.value == 4
    assert inst.bytes_staged.value == 32 * 4
    # after the first fill, the queue had a batch ready: per-fetch wait
    # must be far below the 30ms load latency on average
    assert inst.wait_seconds.count == 4
    assert inst.wait_seconds.sum < 0.08  # ~one initial fill, not 4x30ms


# ---- sources -------------------------------------------------------------

def test_file_source_matches_array_source(tmp_path):
    rng = np.random.default_rng(0)
    imgs = rng.standard_normal((20, 3)).astype(np.float32)
    lbls = rng.integers(0, 9, size=(20,)).astype(np.int32)
    # uneven volumes, boundaries at 7 and 12
    paths = {"images": [], "labels": []}
    for i, (a, b) in enumerate(((0, 7), (7, 12), (12, 20))):
        pi = tmp_path / f"img{i}.npy"
        pl = tmp_path / f"lbl{i}.npy"
        np.save(pi, imgs[a:b])
        np.save(pl, lbls[a:b])
        paths["images"].append(str(pi))
        paths["labels"].append(str(pl))
    fs = FileSource(paths)
    assert len(fs) == 20
    idx = np.array([3, 6, 7, 11, 12, 19, 0])  # crosses both boundaries
    got = fs.batch(idx)
    np.testing.assert_array_equal(got["images"], imgs[idx])
    np.testing.assert_array_equal(got["labels"], lbls[idx])


def test_file_source_through_loader(tmp_path):
    xs = np.arange(24, dtype=np.float32)
    p0, p1 = tmp_path / "a.npy", tmp_path / "b.npy"
    np.save(p0, xs[:10])
    np.save(p1, xs[10:])
    ld = PrefetchLoader(FileSource([str(p0), str(p1)]), 6, rank=0,
                        world=1, epochs=1)
    seen = flat(collect(ld))
    ld.close()
    assert sorted(seen) == xs.tolist()


def test_file_source_validates_parallel_fields(tmp_path):
    np.save(tmp_path / "a.npy", np.zeros(3))
    np.save(tmp_path / "b.npy", np.zeros(3))
    np.save(tmp_path / "c7.npy", np.zeros(7))
    np.save(tmp_path / "c3.npy", np.zeros(3))
    np.save(tmp_path / "c4.npy", np.zeros(4))
    with pytest.raises(ValueError, match="at least one file"):
        FileSource({"x": [str(tmp_path / "a.npy")], "y": []})
    with pytest.raises(ValueError, match="same number of files"):
        FileSource({"x": [str(tmp_path / "a.npy")],
                    "y": [str(tmp_path / "a.npy"),
                          str(tmp_path / "b.npy")]})
    # same file count and even the same TOTAL, split differently:
    # index->(file,row) would pair rows of one field with the wrong
    # rows of the other — must die at construction
    with pytest.raises(ValueError, match="split identically"):
        FileSource({"x": [str(tmp_path / "c7.npy"),
                          str(tmp_path / "c3.npy")],
                    "y": [str(tmp_path / "c4.npy"),
                          str(tmp_path / "c7.npy")]})


def test_source_error_surfaces_on_training_thread():
    class Boom(ArraySource):
        def batch(self, indices):
            raise RuntimeError("storage exploded")

    ld = PrefetchLoader(Boom([np.arange(8)]), 4, rank=0, world=1)
    with pytest.raises(RuntimeError, match="storage exploded"):
        next(ld)
    ld.close()


def test_halt_does_not_hold_lock_across_producer_join():
    """hvd-lint HVD-LOCKORDER regression: ``_halt_producer`` used to
    hold ``self._lock`` while join-looping on the producer thread, so a
    producer parked in a slow storage read held every other loader
    entry point (including the elastic reset path, whose recovery time
    is otherwise bounded) hostage for the whole read. The halt must
    only take the lock to detach the stream."""
    import threading

    release = threading.Event()
    in_read = threading.Event()

    class Slow(ArraySource):
        def batch(self, indices):
            in_read.set()
            assert release.wait(10), "test stalled"
            return super().batch(indices)

    ld = PrefetchLoader(Slow([np.arange(32, dtype=np.float32)]), 4,
                        rank=0, world=1, seed=3)
    ld._ensure_producer()
    assert in_read.wait(10)  # producer is parked in the storage read

    halt_done = threading.Event()
    halter = threading.Thread(
        target=lambda: (ld._halt_producer(), halt_done.set()),
        daemon=True)
    halter.start()
    try:
        # while the halt is join-looping on the parked producer, the
        # loader's lock must be free for other threads
        deadline = time.time() + 5
        acquired = False
        while time.time() < deadline and not acquired:
            acquired = ld._lock.acquire(timeout=0.1)
            if acquired:
                ld._lock.release()
                break
        assert acquired, ("loader lock held across the producer join — "
                          "the HVD-LOCKORDER deadlock shape is back")
        assert not halt_done.is_set()  # the join really was in flight
    finally:
        release.set()
        halter.join(timeout=10)
    assert halt_done.is_set()
    # and the stream restarts correctly on the next generation
    first = np.asarray(next(ld)[0])
    assert first.shape == (4,)
    ld.close()


def test_concurrent_halts_serialize_until_the_producer_dies():
    """The other half of the _halt_producer contract: every halt caller
    mutates cursor/source state right after it returns (set_cursor /
    on_reset / close), so a SECOND halter must park until the previous
    halt's producer has really died — it must not skip ahead on seeing
    the stream already detached and mutate the source under a zombie's
    in-flight batch() read."""
    import threading

    release = threading.Event()
    in_read = threading.Event()

    class Slow(ArraySource):
        def batch(self, indices):
            in_read.set()
            assert release.wait(10), "test stalled"
            return super().batch(indices)

    ld = PrefetchLoader(Slow([np.arange(32, dtype=np.float32)]), 4,
                        rank=0, world=1, seed=3)
    cur = ld.cursor()
    ld._ensure_producer()
    assert in_read.wait(10)  # producer parked in the storage read

    halt_a_done = threading.Event()
    halter_a = threading.Thread(
        target=lambda: (ld._halt_producer(), halt_a_done.set()),
        daemon=True)
    halter_a.start()
    # give A time to detach and enter its join loop
    deadline = time.time() + 5
    while ld._thread is not None and time.time() < deadline:
        time.sleep(0.01)
    assert ld._thread is None

    set_cursor_done = threading.Event()
    halter_b = threading.Thread(
        target=lambda: (ld.set_cursor(cur), set_cursor_done.set()),
        daemon=True)
    halter_b.start()
    try:
        # B must be parked behind A's in-flight join, not mutating
        # stream state while the producer is still inside batch()
        assert not set_cursor_done.wait(0.5)
    finally:
        release.set()
        halter_a.join(timeout=10)
        halter_b.join(timeout=10)
    assert halt_a_done.is_set() and set_cursor_done.is_set()
    # and the repositioned stream is intact
    np.testing.assert_array_equal(
        np.asarray(next(ld)[0]),
        np.asarray(next(PrefetchLoader(
            ArraySource([np.arange(32, dtype=np.float32)]), 4, rank=0,
            world=1, seed=3))[0]))
    ld.close()


def test_consumer_steady_path_skips_halt_coordination():
    """With a LIVE producer, the consumer's _ensure_producer must not
    touch _halt_lock — the hot path stays unblocked even while some
    other loader operation holds the halt serialization."""
    ld = PrefetchLoader(make_xy(), 4, rank=0, world=1, seed=7)
    ld._ensure_producer()          # producer up and producing
    time.sleep(0.05)
    assert ld._halt_lock.acquire(timeout=1)
    try:
        # a batch pull with a live producer completes while the halt
        # lock is held elsewhere
        batch = np.asarray(next(ld)[0])
        assert batch.shape == (4,)
    finally:
        ld._halt_lock.release()
    ld.close()


def test_no_producer_survives_close_racing_a_consumer():
    """A consumer racing close() must not spawn a post-close producer:
    _ensure_producer parks behind the in-flight halt and then observes
    the close (closed is set BEFORE the halt), so after close() no
    prefetch thread may be left doing I/O on the source."""
    import threading

    release = threading.Event()
    in_read = threading.Event()
    preexisting = set(threading.enumerate())

    class Slow(ArraySource):
        def batch(self, indices):
            in_read.set()
            assert release.wait(10), "test stalled"
            return super().batch(indices)

    ld = PrefetchLoader(Slow([np.arange(32, dtype=np.float32)]), 4,
                        rank=0, world=1, seed=3)
    ld._ensure_producer()
    assert in_read.wait(10)

    closer = threading.Thread(target=ld.close, daemon=True)
    closer.start()
    time.sleep(0.1)  # closer is inside the halt join

    consumer_err = []

    def consume():
        try:
            next(ld)
        except Exception as e:
            consumer_err.append(e)

    consumer = threading.Thread(target=consume, daemon=True)
    consumer.start()
    time.sleep(0.1)
    release.set()
    closer.join(timeout=10)
    consumer.join(timeout=10)
    assert consumer_err and "closed" in str(consumer_err[0])
    # the evidence the review probe demanded: no prefetch thread
    # STARTED DURING THIS TEST is left alive after close()
    for t in set(threading.enumerate()) - preexisting:
        assert not (t.name.startswith("hvd_data_prefetch")
                    and t.is_alive()), t.name


# ---- JaxState integration: cursor rides commit/restore/manifest ----------

def _jax_state(ckpt_dir, loader, **kw):
    from horovod_tpu import elastic
    return elastic.JaxState(directory=str(ckpt_dir), loader=loader,
                            w=np.zeros(2, np.float32), **kw)


def test_jaxstate_commit_puts_cursor_in_manifest(tmp_path, monkeypatch):
    from horovod_tpu import ckpt as ckpt_lib
    import horovod_tpu as hvd
    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_RANK", "0")
    monkeypatch.setenv("HOROVOD_SIZE", "1")
    ld = PrefetchLoader(make_xy(), 4, rank=0, world=1, seed=5, epochs=2)
    state = _jax_state(tmp_path, ld)
    collect(ld, 3)
    state.commit()
    state.flush()
    step = ckpt_lib.latest_complete_step(str(tmp_path))
    man = ckpt_lib.read_manifest(str(tmp_path), step)
    cur = man["meta"]["data_cursor"]
    assert cur["batch_index"] == 3 and cur["seed"] == 5
    assert cur == ld.cursor()
    ld.close()
    state._abandon_pending_saves()


def test_jaxstate_restore_rolls_the_stream_back(tmp_path, monkeypatch):
    import horovod_tpu as hvd
    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_RANK", "0")
    monkeypatch.setenv("HOROVOD_SIZE", "1")
    ld = PrefetchLoader(make_xy(), 4, rank=0, world=1, seed=5, epochs=1)
    state = _jax_state(tmp_path, ld)
    head = collect(ld, 2)
    state.commit()  # cursor points at batch 2
    mid = collect(ld, 3)  # "half-applied" work past the commit
    state.restore()  # worker failure: roll back state AND stream
    replay = collect(ld, 3)
    ld.close()
    state._abandon_pending_saves()
    for a, b in zip(mid, replay):
        np.testing.assert_array_equal(a[0], b[0])
    assert len(head) == 2


def test_two_rank_kill_restore_resumes_bit_identical(tmp_path,
                                                     monkeypatch):
    """The satellite e2e, in process: a simulated 2-rank run commits
    through the sharded manifest subsystem mid-epoch and 'dies'; fresh
    JaxStates + loaders restore from the MANIFEST (not memory) and the
    post-resume stream is bit-identical to an uninterrupted run."""
    import horovod_tpu as hvd
    hvd.shutdown()

    def at_rank(r):
        monkeypatch.setenv("HOROVOD_RANK", str(r))
        monkeypatch.setenv("HOROVOD_SIZE", "2")

    mk = lambda r: PrefetchLoader(make_xy(), 4, rank=r, world=2,  # noqa: E731
                                  seed=9, epochs=1)
    reference = {}
    for r in range(2):
        ld = mk(r)
        reference[r] = collect(ld)
        ld.close()

    # the doomed run: 2 commits apart, dies after consuming 3 batches
    loaders, states = {}, {}
    for r in range(2):
        at_rank(r)
        loaders[r] = mk(r)
        states[r] = _jax_state(tmp_path, loaders[r])
        states[r]._checkpointer()  # bind rank under the right env
    consumed = {}
    for r in range(2):
        at_rank(r)
        consumed[r] = collect(loaders[r], 3)
        states[r].save()
    for r in range(2):
        at_rank(r)
        states[r].flush()
    for r in range(2):  # batches consumed past the commit die with it
        collect(loaders[r], 1)
        loaders[r].close()
        states[r]._abandon_pending_saves()

    # relaunch: fresh processes restore from the manifest
    for r in range(2):
        at_rank(r)
        ld = mk(r)
        st = _jax_state(tmp_path, ld)
        st.restore()
        tail = collect(ld)
        ld.close()
        st._abandon_pending_saves()
        got = consumed[r] + tail
        assert len(got) == len(reference[r])
        for a, b in zip(got, reference[r]):
            np.testing.assert_array_equal(a[0], b[0])
            np.testing.assert_array_equal(a[1], b[1])


# ---- training integration ------------------------------------------------

def _mlp_step(hvd_mod, loader=None, telemetry=False):
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    from horovod_tpu import training

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(8)(x)
            return nn.Dense(4)(x)

    model = MLP()
    tx = hvd_mod.DistributedOptimizer(optax.sgd(0.1))
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        jnp.zeros((1, 4)))
    step = training.make_train_step(model, tx, donate=False,
                                    telemetry=telemetry, loader=loader)
    return step, state


def test_compiled_step_byte_identical_with_and_without_loader(hvd):
    """Acceptance bar: the loader changes who FEEDS the program, never
    the program — lowered text identical with a loader wired in."""
    import jax.numpy as jnp

    ndev = hvd.num_devices()
    x = jnp.zeros((8 * ndev, 4), jnp.float32)
    y = jnp.zeros((8 * ndev,), jnp.int32)

    step0, state0 = _mlp_step(hvd)
    baseline = step0.lower(state0, x, y).as_text()

    src = ArraySource([np.zeros((8 * ndev * 4, 4), np.float32),
                       np.zeros((8 * ndev * 4,), np.int32)])
    loader = PrefetchLoader(src, 8 * ndev, rank=0, world=1, epochs=1)
    step1, state1 = _mlp_step(hvd, loader=loader)
    with_loader = step1.lower(state1, x, y).as_text()
    loader.close()
    assert with_loader == baseline


def test_step_pulls_and_stages_from_loader(hvd):
    """step(state) consumes prefetched batches; the producer stages them
    to the step's mesh placement (device arrays, data-axis sharded)."""
    import jax

    ndev = hvd.num_devices()
    B = 2 * ndev
    rng = np.random.default_rng(0)
    src = ArraySource([rng.standard_normal((B * 4, 4)).astype(np.float32),
                       rng.integers(0, 4, size=(B * 4,)).astype(np.int32)])
    loader = PrefetchLoader(src, B, rank=0, world=1, epochs=1)
    step, state = _mlp_step(hvd, loader=loader)
    # the attached placement stages on the producer thread
    staged = next(loader)
    assert isinstance(staged[0], jax.Array)
    assert len(staged[0].sharding.device_set) == ndev
    losses = []
    for _ in range(3):
        state, loss = step(state)
        losses.append(float(jax.device_get(loss)))
    loader.close()
    assert all(np.isfinite(losses))
    assert step.loader is loader


def test_loader_fed_matches_hand_fed_losses(hvd):
    """Same stream, two feeders, same numerics: driving the step through
    the loader reproduces hand-fed losses exactly."""
    import jax

    ndev = hvd.num_devices()
    B = 2 * ndev
    rng = np.random.default_rng(3)
    xs = rng.standard_normal((B * 3, 4)).astype(np.float32)
    ys = rng.integers(0, 4, size=(B * 3,)).astype(np.int32)

    step_a, state_a = _mlp_step(hvd)
    hand = []
    ld_plan = PrefetchLoader(ArraySource([xs, ys]), B, rank=0, world=1,
                             seed=0, epochs=1)
    batches = collect(ld_plan)
    ld_plan.close()
    for x, y in batches:
        state_a, loss = step_a(state_a, x, y)
        hand.append(float(jax.device_get(loss)))

    loader = PrefetchLoader(ArraySource([xs, ys]), B, rank=0, world=1,
                            seed=0, epochs=1)
    step_b, state_b = _mlp_step(hvd, loader=loader)
    fed = []
    for _ in range(len(hand)):
        state_b, loss = step_b(state_b)
        fed.append(float(jax.device_get(loss)))
    loader.close()
    np.testing.assert_allclose(fed, hand, rtol=0, atol=0)


# ---- doctor: the data-stall verdict names the producer -------------------

def test_doctor_data_stall_names_the_producer(tmp_path):
    from horovod_tpu.diag import doctor
    from horovod_tpu.diag.recorder import FlightRecorder

    t = [0.0]

    def clock():
        t[0] += 0.25
        return t[0]

    # rank 0: finished step 2, training thread starved by its producer
    r0 = FlightRecorder(capacity=64, rank=0, size=2, clock=clock,
                        wall_clock=clock)
    seq = r0.collective_enter("allreduce", shape=(4,), dtype="float32")
    r0.collective_exit("allreduce", seq)
    r0.step_begin(2)
    r0.step_end(2)
    r0.record("data", ph="B", epoch=0, batch=3, source="FileSource")
    r0.record("data_wait", ph="B", epoch=0, batch=3, source="FileSource")
    # rank 1: parked in the step-3 allreduce rank 0 never reached
    r1 = FlightRecorder(capacity=64, rank=1, size=2, clock=clock,
                        wall_clock=clock)
    seq = r1.collective_enter("allreduce", shape=(4,), dtype="float32")
    r1.collective_exit("allreduce", seq)
    r1.collective_enter("allreduce", shape=(4,), dtype="float32")

    dumps = {0: r0.snapshot(), 1: r1.snapshot()}
    report = doctor.diagnose(dumps, expected_size=2)
    assert report["classification"] == "data stall"
    why = report["explanation"]
    assert "FileSource" in why  # the producer is INDICTED by name
    assert "batch 3" in why
    text = doctor.format_report(report)
    assert "data stall" in text and "FileSource" in text


@pytest.mark.slow
def test_e2e_starved_rank_diagnosed_as_data_stall(tmp_path):
    """The satellite e2e: a real 2-rank hvdrun where rank 0's producer
    starves mid-run; rank 1 parks in the collective rank 0 never
    reaches; the auto-doctor attributes the hang to 'data stall' and
    names the producer class."""
    script = tmp_path / "starve.py"
    script.write_text(textwrap.dedent("""
        import os, signal, threading, time
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu.data import ArraySource, PrefetchLoader

        class GlacialSource(ArraySource):
            def __init__(self, arrays, slow_after):
                super().__init__(arrays)
                self.calls = 0
                self.slow_after = slow_after
            def batch(self, indices):
                self.calls += 1
                if self.slow_after and self.calls > self.slow_after:
                    time.sleep(600)  # "object storage went away"
                return super().batch(indices)

        hvd.init()
        rank = hvd.rank()
        # rank 0's storage dies after 2 batches; rank 1's stays healthy
        src = GlacialSource([np.arange(64, dtype=np.float32)],
                            slow_after=2 if rank == 0 else 0)
        loader = PrefetchLoader(src, 8, rank=0, world=1, depth=1,
                                shuffle=False)
        if rank == 1:
            # the job is wedged by design: rank 1 sits PARKED in the
            # step-3 allreduce rank 0 never reaches. SIGTERM ourselves
            # so the black boxes capture exactly that shape — rank 1
            # dumps parked-in-collective (watcher thread), the
            # launcher's fan-out then dumps starved rank 0 with its
            # data_wait still open
            threading.Timer(6.0, lambda: os.kill(
                os.getpid(), signal.SIGTERM)).start()
        for step in range(6):
            (x,) = next(loader)
            hvd.allreduce(np.asarray(x), op=hvd.Sum)
        time.sleep(120)
    """))
    out_dir = tmp_path / "out"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rv = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--output-dir", str(out_dir), sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=150)
    assert rv.returncode != 0
    assert "doctor report" in rv.stderr
    assert "probable cause: data stall" in rv.stderr
    assert "GlacialSource" in rv.stderr  # the producer, by name


def test_sync_hands_newcomer_the_roots_cursor(monkeypatch):
    """A respawned worker with no disk access adopts the elected root's
    data cursor over the collective plane (length broadcast sizes the
    JSON buffer), so its batch stream resumes at the survivors'
    position — patched collective, single process."""
    import horovod_tpu.elastic.state as state_mod

    root_cur = {"version": 1, "seed": 3, "shuffle": True,
                "drop_last": True, "batch_size": 4, "world": 2,
                "epoch": 0, "offset": 0, "batch_index": 5, "source": {}}
    payload = json.dumps(root_cur, sort_keys=True).encode()
    scalars = [0]

    def fake_broadcast(tree, root):
        if not isinstance(tree, np.ndarray):
            return tree  # the state trees ride through unchanged
        if tree.shape == ():
            scalars[0] += 1  # 1st scalar: commit count; 2nd: length
            return (np.asarray(9, np.int64) if scalars[0] == 1
                    else np.asarray(len(payload), np.int64))
        if tree.dtype == np.uint8:
            return np.frombuffer(payload, np.uint8)
        return tree

    monkeypatch.setattr(state_mod, "_broadcast_tree", fake_broadcast)
    monkeypatch.setattr(state_mod, "_elect_root",
                        lambda root_rank, has_commit: 0)
    ld = PrefetchLoader(make_xy(), 4, rank=1, world=2)
    st = state_mod.JaxState(loader=ld, w=np.zeros(2, np.float32))
    assert st.sync() == 0
    assert st._commit_count == 9
    cur = ld.cursor()
    assert cur["batch_index"] == 5 and cur["seed"] == 3
    ld.close()


def test_elastic_train_loop_drives_a_loader(hvd, tmp_path):
    """``elastic_train_loop`` handed a PrefetchLoader as its batch
    source: pulls prefetched batches, auto-attaches the loader to the
    JaxState (so the cursor rides every commit into the manifest), and
    the final manifest records the exact stream position."""
    import jax

    from horovod_tpu import ckpt as ckpt_lib
    from horovod_tpu import elastic, training

    ndev = hvd.num_devices()
    B = 2 * ndev
    rng = np.random.default_rng(1)
    xs = rng.standard_normal((B * 8, 4)).astype(np.float32)
    ys = rng.integers(0, 4, size=(B * 8,)).astype(np.int32)
    loader = PrefetchLoader(ArraySource([xs, ys]), B, rank=0, world=1,
                            seed=2)

    step, ts = _mlp_step(hvd)
    es = elastic.JaxState(directory=str(tmp_path), train_state=ts)
    final = training.elastic_train_loop(es, step, loader, num_steps=4,
                                        commit_every=2,
                                        checkpoint_every=1)
    assert es._loader is loader
    assert int(jax.device_get(final.step)) == 4
    newest = ckpt_lib.latest_complete_step(str(tmp_path))
    man = ckpt_lib.read_manifest(str(tmp_path), newest)
    cur = man["meta"]["data_cursor"]
    assert cur == loader.cursor()  # the committed position IS the live one
    assert cur["batch_index"] == 4 and cur["seed"] == 2
    loader.close()
    es._abandon_pending_saves()


def test_manifest_restore_into_bigger_world_reshards_stream(tmp_path,
                                                            monkeypatch):
    """Acceptance: mid-epoch manifest restore ACROSS an elastic N->M
    membership change. A 2-rank run commits its cursor to the manifest
    mid-epoch; a 3-rank relaunch restores the same manifest — each new
    rank's JaxState hands the 2-rank cursor to its 3-rank loader, which
    retires the old membership's consumption and re-strides the
    remaining epoch: every remaining example visited exactly once."""
    import horovod_tpu as hvd
    hvd.shutdown()
    n, B = 64, 4

    def mk(r, w):
        return PrefetchLoader(ArraySource([np.arange(n)]), B, rank=r,
                              world=w, seed=11, epochs=1,
                              drop_last=False)

    def at(r, w):
        monkeypatch.setenv("HOROVOD_RANK", str(r))
        monkeypatch.setenv("HOROVOD_SIZE", str(w))

    # the doomed 2-rank run: 2 batches per rank, then a commit, then death
    seen, states, loaders = [], {}, {}
    for r in range(2):
        at(r, 2)
        loaders[r] = mk(r, 2)
        states[r] = _jax_state(tmp_path, loaders[r])
        states[r]._checkpointer()
    for r in range(2):
        at(r, 2)
        seen += flat(collect(loaders[r], 2))
        states[r].save()
    for r in range(2):
        at(r, 2)
        states[r].flush()
        loaders[r].close()
        states[r]._abandon_pending_saves()

    # relaunch at world 3: restore_sharded reshards the STATE (2->3) and
    # hands back the cursor; the loader reshards the STREAM
    after = []
    for r in range(3):
        at(r, 3)
        ld = mk(r, 3)
        st = _jax_state(tmp_path, ld)
        st.restore()
        after += flat(collect(ld))
        ld.close()
        st._abandon_pending_saves()
    total = seen + after
    assert len(total) == n
    assert sorted(total) == list(range(n))  # exactly once, none dropped
