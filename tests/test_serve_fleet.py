"""Serve fleet (ISSUE 16): replica router over N engines, queue-depth/
KV-headroom dispatch, spot-preemption drain reusing elastic/preempt.py,
zero-drop re-dispatch of cut-off streams, rolling fleet-wide weight
reload, and the fleet HTTP frontend. The tier-1 e2e here is the chaos
contract: a 2-replica fleet on disjoint CPU submeshes, concurrent
streams, one replica evicted mid-stream — zero dropped requests and
every stream token-identical to the single-shot oracle. See
docs/SERVING.md ("Serve fleet")."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from test_serve import _kv, _model, _oracle, _run_until

from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.serve.engine import RequestError, ServeEngine
from horovod_tpu.serve.fleet import FleetRouter, FleetServer
from horovod_tpu.telemetry import instruments as instruments_lib
from horovod_tpu.telemetry.registry import MetricsRegistry


def _fleet(model, params, cfg, reg, grace=5.0, max_slots=4,
           notice_files=(None, None), **kv_kw):
    """Two replicas on DISJOINT submeshes (a real fleet is one replica
    per slice; concurrent SPMD dispatch over shared devices can
    deadlock collectives), behind a started router."""
    devs = jax.devices()
    half = max(1, len(devs) // 2)
    meshes = [mesh_lib.build_mesh(devs[:half]),
              mesh_lib.build_mesh(devs[half:] or devs[:half])]
    engines = [ServeEngine(model, params, _kv(cfg, **kv_kw),
                           mesh=meshes[i], max_slots=max_slots,
                           prefill_chunk=4, registry=reg, name=f"r{i}")
               for i in range(2)]
    router = FleetRouter(registry=reg, grace=grace)
    for i, eng in enumerate(engines):
        router.add_replica(f"r{i}", eng, env={},
                           notice_file=notice_files[i],
                           poll_interval=0.01)
    router.start()
    return router, engines


def _gauge(reg, state):
    return instruments_lib.serve_replicas_gauge(reg).labels(state).value


def test_fleet_dispatch_skips_draining_replica_and_counts_states():
    cfg, model, params = _model()
    reg = MetricsRegistry()
    router, engines = _fleet(model, params, cfg, reg)
    try:
        rng = np.random.default_rng(40)
        assert _gauge(reg, "ready") == 2
        router.drain_traffic("r0", grace=0.5)
        assert engines[0].draining
        assert _gauge(reg, "ready") == 1 and _gauge(reg, "draining") == 1
        assert router.healthz()["status"] == "ok"     # r1 still admits
        reqs = [router.generate(list(map(int, rng.integers(0, 64, 4))), 4)
                for _ in range(2)]
        for r in reqs:
            assert r.result(timeout=120) == _oracle(model, params,
                                                    r.prompt, 4)
            assert r.replica == "r1"                  # never the drained
        router.evict("r0")
        assert _gauge(reg, "dead") == 1
        h = router.healthz()
        assert h["replicas"]["r0"]["state"] == "dead"
        assert h["status"] == "ok" and h["ready_replicas"] == 1
    finally:
        router.stop()


def test_replica_headroom_counts_only_sole_ref_cache_entries():
    """A cache entry whose block a live sequence also maps frees no
    pool block when released — scoring it as headroom would dispatch a
    request into engine backpressure while another replica had real
    room."""
    from horovod_tpu.serve.fleet.replica import Replica

    cfg, model, params = _model()
    eng = ServeEngine(model, params,
                      _kv(cfg, num_blocks=8, block_size=4, mbps=8),
                      max_slots=2, prefill_chunk=4,
                      registry=MetricsRegistry())
    rep = Replica("r", eng)
    assert rep.headroom_for(7)                    # capacity 7, all free
    r1 = eng.generate(list(range(8)), 8)          # 4 blocks
    for _ in range(10):                           # run prefill only
        eng.step()
        if r1.state == "decode":
            break
    assert eng.prefix_cache.size == 2             # r1's full blocks
    assert eng.prefix_cache.reclaimable() == 0    # r1 still maps them
    assert rep.headroom_for(3)                    # the 3 free blocks
    assert not rep.headroom_for(4)                # cache is NOT headroom
    _run_until(eng, [r1])
    assert eng.prefix_cache.reclaimable() == 2    # sole-ref now
    assert rep.headroom_for(7)                    # free + reclaimable


def test_fleet_request_timestamps_use_router_clock():
    """Client-latency stamps (arrival, token times) follow the
    router's injectable clock — one time base fleet-wide under a fake
    clock."""
    t = [100.0]
    router = FleetRouter(registry=MetricsRegistry(), clock=lambda: t[0])
    freq = router.generate([1, 2, 3], 4)
    assert freq.arrival == 100.0
    t[0] = 101.5
    freq._emit("token", 7)
    assert freq.first_token_time == 101.5
    assert freq.token_times == [101.5]
    router.stop()


def test_fleet_e2e_chaos_eviction_mid_stream_zero_drop():
    """The tier-1 chaos contract: concurrent streams across both
    replicas, r0 killed mid-stream — every request finishes, the
    re-dispatched continuations are token-identical to the oracle
    (the position-keyed sampling makes the hop invisible), and the
    drop counter stays at zero."""
    cfg, model, params = _model()
    reg = MetricsRegistry()
    router, engines = _fleet(model, params, cfg, reg, num_blocks=128)
    try:
        rng = np.random.default_rng(41)
        n_new = 24
        reqs = [router.generate(list(map(int, rng.integers(0, 64, 5))),
                                n_new)
                for _ in range(5)]
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(r.replica == "r0" and r.generated for r in reqs) \
                    and any(r.replica == "r1" for r in reqs):
                break
            time.sleep(0.005)
        assert any(r.replica == "r0" and len(r.generated) < n_new
                   for r in reqs), "no stream in flight on the victim"
        router.evict("r0")                            # chaos: no grace
        outs = [r.result(timeout=120) for r in reqs]
        assert router.dropped == 0
        assert router.redispatched >= 1               # a stream WAS cut
        assert all(len(o) == n_new for o in outs)
        for r, o in zip(reqs, outs):
            assert o == _oracle(model, params, r.prompt, n_new), \
                f"{r.id} diverged after {r.hops} hop(s)"
        # the fleet keeps serving on the survivor
        extra = router.generate(list(map(int, rng.integers(0, 64, 4))), 4)
        assert extra.result(timeout=120) == _oracle(model, params,
                                                    extra.prompt, 4)
    finally:
        router.stop()


def test_fleet_spot_notice_file_drains_gracefully(tmp_path):
    """The spot-capacity path end to end: the per-replica preemption
    handler (elastic/preempt.py machinery) polls a notice file; when
    it appears, traffic drains off the doomed replica inside the grace
    budget and the replica exits rotation — zero drops, no client ever
    sees the eviction."""
    cfg, model, params = _model()
    reg = MetricsRegistry()
    notice = tmp_path / "preempt-notice"
    router, engines = _fleet(model, params, cfg, reg, grace=30.0,
                             notice_files=(str(notice), None))
    try:
        rng = np.random.default_rng(42)
        reqs = [router.generate(list(map(int, rng.integers(0, 64, 5))), 6)
                for _ in range(4)]
        notice.write_text("preempted\n")              # the spot notice
        outs = [r.result(timeout=120) for r in reqs]
        deadline = time.time() + 60
        while router.replica("r0").state != "dead" \
                and time.time() < deadline:
            time.sleep(0.01)
        assert router.replica("r0").state == "dead"
        assert router.dropped == 0
        for r, o in zip(reqs, outs):
            assert o == _oracle(model, params, r.prompt, 6)
        assert router.healthz()["ready_replicas"] == 1
    finally:
        router.stop()


def test_fleet_rolling_reload_never_closes_admission():
    """install_weights stages one replica at a time: while the roll is
    in progress the fleet never reports "down", requests keep being
    admitted, and both replicas converge on the new version."""
    cfg, model, params = _model()
    reg = MetricsRegistry()
    router, engines = _fleet(model, params, cfg, reg)
    try:
        rng = np.random.default_rng(43)
        statuses, stop_probe = [], threading.Event()

        def probe():
            while not stop_probe.is_set():
                statuses.append(router.healthz()["status"])
                time.sleep(0.002)

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        background = [router.generate(
            list(map(int, rng.integers(0, 64, 4))), 12)
            for _ in range(3)]
        router.install_weights(params, version=5)     # same values
        during = router.generate(list(map(int, rng.integers(0, 64, 4))), 4)
        stop_probe.set()
        t.join(timeout=30)
        assert router.weights_version == 5
        assert all(e.weights_version == 5 for e in engines)
        assert statuses and "down" not in statuses
        for r in background + [during]:
            assert r.result(timeout=120) == _oracle(
                model, params, r.prompt, r.max_new_tokens)
        assert router.dropped == 0
    finally:
        router.stop()


def test_fleet_frontend_http_stream_health_and_all_dead(hvd):
    cfg, model, params = _model()
    reg = MetricsRegistry()
    router, engines = _fleet(model, params, cfg, reg)
    server = FleetServer(router, port=0)
    port = server.start()
    try:
        rng = np.random.default_rng(44)
        p = list(map(int, rng.integers(0, 64, 5)))
        body = json.dumps({"tokens": p, "max_new_tokens": 6}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            lines = [json.loads(ln) for ln in resp]
        assert lines[-1]["done"]
        assert lines[-1]["tokens"] == _oracle(model, params, p, 6)
        assert lines[-1]["hops"] == 0
        toks = [ln["token"] for ln in lines[:-1]]
        assert toks == lines[-1]["tokens"]            # streamed == final

        # seeded sampling through the frontend is reproducible
        sbody = json.dumps({"tokens": p, "max_new_tokens": 6,
                            "temperature": 0.9, "top_p": 0.8,
                            "seed": 11}).encode()
        runs = []
        for _ in range(2):
            sreq = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=sbody,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(sreq, timeout=120) as resp:
                runs.append(json.loads(list(resp)[-1])["tokens"])
        assert runs[0] == runs[1]

        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert h["status"] == "ok" and h["ready_replicas"] == 2
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "hvd_serve_replicas" in scrape
        assert "hvd_serve_cached_prefill_tokens_total" in scrape

        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=b'{"tokens": [1], "temperature": -1}')
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(bad, timeout=10)
        assert e.value.code == 400

        router.evict("r0")
        router.evict("r1")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert e.value.code == 503
        assert json.loads(e.value.read())["status"] == "down"
        with urllib.request.urlopen(req, timeout=120) as resp:
            lines = [json.loads(ln) for ln in resp]
        assert "no live replica" in lines[-1]["error"]
    finally:
        server.stop()
        router.stop()


def test_fleet_submit_after_stop_is_loud():
    cfg, model, params = _model()
    reg = MetricsRegistry()
    router, _ = _fleet(model, params, cfg, reg)
    router.stop()
    with pytest.raises(RequestError, match="stopped"):
        router.generate([1, 2, 3], 2)
