"""Minimal numpy-backed stand-in for tensorflow, enough to exercise the
horovod_tpu.tensorflow adapter logic in-image (TF is not baked into the
environment). Mirrors the slivers of API the adapter touches:
``convert_to_tensor``/Tensor with ``.numpy()``, ``IndexedSlices``,
``Variable`` with ``assign``/``value``, a preset-gradient
``GradientTape``, a TF1-style optimizer, and keras
``optimizers.SGD`` + pickle-backed ``models.save_model/load_model``
with ``custom_objects`` resolution (what hvd's load_model hooks into).
"""

import pickle
import importlib.machinery
import sys
import types

import numpy as np


class Tensor:
    def __init__(self, data):
        self._data = np.asarray(data)

    def numpy(self):
        return self._data.copy()

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def shape(self):
        return self._data.shape

    def __truediv__(self, other):
        return Tensor(self._data / other)

    def __array__(self, dtype=None):
        return self._data if dtype is None else self._data.astype(dtype)


def convert_to_tensor(x):
    if isinstance(x, Tensor):
        return x
    if isinstance(x, Variable):
        return Tensor(x.numpy())
    return Tensor(np.asarray(x))


class IndexedSlices:
    """Sparse gradient triple (reference tf.IndexedSlices)."""

    def __init__(self, values, indices, dense_shape=None):
        self.values = (values if isinstance(values, Tensor)
                       else Tensor(values))
        self.indices = (indices if isinstance(indices, Tensor)
                        else Tensor(indices))
        self.dense_shape = dense_shape


class Variable:
    def __init__(self, data):
        self._data = np.array(data, copy=True)

    def numpy(self):
        return self._data.copy()

    def value(self):
        return Tensor(self._data)

    def assign(self, value):
        self._data = np.array(np.asarray(value), copy=True)
        return self

    def __array__(self, dtype=None):
        return self._data if dtype is None else self._data.astype(dtype)


class GradientTape:
    """Preset-gradient tape: real autodiff is TF's business, the adapter
    only post-processes what gradient() returns."""

    def __init__(self, grads=None):
        self._grads = grads or []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def watch(self, t):
        pass

    def gradient(self, target, sources, output_gradients=None):
        return list(self._grads)


class _V1Optimizer:
    """TF1-style optimizer: compute_gradients/apply_gradients. Gradients
    are preset by tests (``_test_grads``)."""

    def __init__(self, lr=0.1):
        self.lr = lr
        self._test_grads = []

    def compute_gradients(self, loss=None, var_list=None):
        return list(zip(self._test_grads, var_list))

    def apply_gradients(self, grads_and_vars, global_step=None,
                        name=None):
        if global_step is not None:
            global_step.assign(np.asarray(global_step.numpy()) + 1)
        for g, v in grads_and_vars:
            if g is None:
                continue
            v.assign(v.numpy() - self.lr * np.asarray(g))

    def get_slot(self, *a, **k):
        return None

    def get_slot_names(self):
        return []

    def variables(self):
        return []

    def get_config(self):
        return {"lr": self.lr}


class SGD:
    def __init__(self, lr=0.1):
        self.lr = lr
        self._test_grads = []

    def get_config(self):
        return {"lr": self.lr}

    def get_gradients(self, loss, params):
        return list(self._test_grads)

    @classmethod
    def from_config(cls, config):
        return cls(**config)


class _KerasModel:
    def __init__(self, weights, optimizer):
        self.weights = dict(weights)
        self.optimizer = optimizer


def _save_model(model, filepath):
    blob = {"weights": {k: np.asarray(v) for k, v in
                        model.weights.items()},
            "optimizer_class": type(model.optimizer).__name__
            if not hasattr(type(model.optimizer), "_hvd_wrapped")
            else type(model.optimizer)._hvd_wrapped.__name__,
            "optimizer_config": model.optimizer.get_config()}
    with open(filepath, "wb") as f:
        pickle.dump(blob, f)


def _load_model(filepath, custom_objects=None):
    with open(filepath, "rb") as f:
        blob = pickle.load(f)
    name = blob["optimizer_class"]
    factory = (custom_objects or {}).get(name)
    if factory is None:
        factory = _REGISTRY[name]
    opt = factory(**blob["optimizer_config"])
    return _KerasModel(blob["weights"], opt)


_REGISTRY = {"SGD": SGD}


def install():
    """Install the fake as ``sys.modules['tensorflow']`` (idempotent)."""
    if "tensorflow" in sys.modules:
        return sys.modules["tensorflow"]
    tf = types.ModuleType("tensorflow")
    tf.Tensor = Tensor
    tf.convert_to_tensor = convert_to_tensor
    tf.IndexedSlices = IndexedSlices
    tf.Variable = Variable
    tf.GradientTape = GradientTape
    tf.train = types.ModuleType("tensorflow.train")
    tf.train.Optimizer = _V1Optimizer
    tf.keras = types.ModuleType("tensorflow.keras")
    tf.keras.optimizers = types.ModuleType("tensorflow.keras.optimizers")
    tf.keras.optimizers.SGD = SGD
    tf.keras.models = types.ModuleType("tensorflow.keras.models")
    tf.keras.models.save_model = _save_model
    tf.keras.models.load_model = _load_model
    tf.keras.Model = _KerasModel
    mods = {"tensorflow": tf, "tensorflow.train": tf.train,
            "tensorflow.keras": tf.keras,
            "tensorflow.keras.optimizers": tf.keras.optimizers,
            "tensorflow.keras.models": tf.keras.models}
    for name, mod in mods.items():
        # a None __spec__ makes importlib.util.find_spec raise for any
        # OTHER library probing for tensorflow (torch does)
        mod.__spec__ = importlib.machinery.ModuleSpec(name, None)
        sys.modules[name] = mod
    return tf
