"""Pallas flash-attention kernel vs the plain-XLA oracle (CPU runs the
kernel in interpret mode; on TPU the same code compiles via Mosaic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import (Transformer, TransformerConfig,
                                            dense_attention)
from horovod_tpu.ops import flash_attention as fa


def _qkv(rng, b=2, s=256, h=4, d=64):
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


def _oracle(q, k, v, causal=True):
    b, s, h, d = q.shape
    pos = jnp.broadcast_to(jnp.arange(s), (b, s))
    return dense_attention(q, k, v, causal=causal, q_positions=pos,
                           kv_positions=pos)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_oracle(causal):
    q, k, v = _qkv(np.random.default_rng(0))
    out = fa.flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_oracle(q, k, v, causal)),
                               atol=2e-5)


def test_gradients_match_oracle():
    q, k, v = _qkv(np.random.default_rng(1), s=128)

    def f_flash(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_oracle(q, k, v) ** 2)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4)


def test_offsets_mask_correctly():
    """Ring-style shifted K/V block: only keys with absolute position <=
    query position may attend."""
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, s=128)
    # queries are the SECOND shard (positions 128..255), keys the first
    out = fa.flash_attention(q, k, v, causal=True, q_offset=128,
                             kv_offset=0)
    # every key position (0..127) <= every query position -> full attend,
    # equals non-causal
    ref = fa.flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    # reversed roles: no key is visible -> output must be exactly zero
    # (not a spurious mean of V)
    out2 = fa.flash_attention(q, k, v, causal=True, q_offset=0,
                              kv_offset=128)
    np.testing.assert_array_equal(np.asarray(out2), 0.0)


def test_traced_offsets_under_jit():
    """Offsets ride scalar prefetch, so traced values work — what a
    sequence-parallel shard passes for a rotated K/V block."""
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, s=128)

    @jax.jit
    def f(q, k, v, qo):
        return fa.flash_attention(q, k, v, causal=True, q_offset=qo,
                                  kv_offset=0)

    out = f(q, k, v, jnp.int32(128))
    ref = fa.flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_attention_fallback_on_odd_shapes():
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((1, 100, 2, 32)), jnp.float32)
    k, v = q + 1, q - 1
    out = fa.attention(q, k, v, causal=True)  # 100 % 100 == 0 -> kernel
    assert out.shape == q.shape
    # S=100 with block min(128,100)=100 divides; also exercise fallback
    q2 = jnp.asarray(rng.standard_normal((1, 90, 2, 30)), jnp.float32)
    out2 = fa.attention(q2, q2, q2, causal=True)  # d%8 != 0 -> jnp path
    np.testing.assert_allclose(np.asarray(out2),
                               np.asarray(_oracle(q2, q2, q2)), atol=2e-5)


def test_transformer_flash_matches_dense():
    cfg_dense = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                  d_model=32, d_ff=64, dtype=jnp.float32)
    cfg_flash = TransformerConfig(**{**cfg_dense.__dict__,
                                     "flash_attention": True})
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, 64, size=(2, 128)), jnp.int32)
    m_dense, m_flash = Transformer(cfg_dense), Transformer(cfg_flash)
    params = m_dense.init(jax.random.PRNGKey(0), tokens, train=False)
    out_d = m_dense.apply(params, tokens, train=False)
    out_f = m_flash.apply(params, tokens, train=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               atol=5e-5)
