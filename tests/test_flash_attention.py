"""Pallas flash-attention kernel vs the plain-XLA oracle (CPU runs the
kernel in interpret mode; on TPU the same code compiles via Mosaic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import (Transformer, TransformerConfig,
                                            dense_attention)
from horovod_tpu.ops import flash_attention as fa


def _qkv(rng, b=2, s=256, h=4, d=64):
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.standard_normal((b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


def _oracle(q, k, v, causal=True):
    b, s, h, d = q.shape
    pos = jnp.broadcast_to(jnp.arange(s), (b, s))
    return dense_attention(q, k, v, causal=causal, q_positions=pos,
                           kv_positions=pos)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_oracle(causal):
    q, k, v = _qkv(np.random.default_rng(0))
    out = fa.flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_oracle(q, k, v, causal)),
                               atol=2e-5)


def test_block_fit_non_pow2_sequences():
    """Sequences that are multiples of 128 but not of the 512 default
    block (1280, 1152) must still run the kernel: the block fits DOWN to
    the largest divisor instead of rejecting the shape."""
    assert fa._fit_block(1280, 512) == 256
    assert fa._fit_block(1152, 512) == 128
    assert fa._fit_block(2048, 512) == 512
    assert fa._fit_block(48, 512) == 48
    assert fa._fit_block(12, 512) == 0  # not a multiple of 8
    assert fa.kernel_supported(1280, 1280, 64)


def test_mxu_block_floor_routes_degenerate_tilings_to_fallback():
    """ADVICE round 5: a long sequence whose only fitting block is tiny
    (1048 = 8 * 131 -> block 8) would run an MXU-starved 8-wide kernel;
    kernel_supported must reject it so `attention` takes the dense XLA
    fallback. Short sequences that fit in ONE block stay on the kernel."""
    assert fa._fit_block(1048, 512) == 8       # fits, but degenerate
    assert not fa.kernel_supported(1048, 1048, 64)
    assert not fa.kernel_supported(512, 1048, 64)   # either side gates
    # whole-sequence blocks below 128 are still fine (96 = one block)
    assert fa.kernel_supported(96, 96, 32)
    assert fa.kernel_supported(1280, 1280, 64)      # floor met (256)
    q, k, v = _qkv(np.random.default_rng(3), s=160)  # 160 = 32*5
    out = fa.flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_oracle(q, k, v)), atol=2e-5)


def test_decode_shapes_route_to_dense_path():
    """ISSUE 11 satellite: q_len == 1 (incremental decode — one new
    token against a long cached K/V, the serve/engine.py hot loop) can
    never tile onto an MXU-floor block; kernel_supported must route it
    to the dense path EXPLICITLY — for every cache length, including
    ones whose kv side alone would tile — and the `attention` dispatch
    wrapper must produce oracle values there, not a Mosaic rejection."""
    for skv in (1, 7, 96, 512, 2048, 4096):
        assert not fa.kernel_supported(1, skv, 64), skv
    assert not fa.kernel_supported(512, 1, 64)  # kv side gates too
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((2, 1, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 512, 4, 64)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 512, 4, 64)), jnp.float32)
    # the decoding query sits at the END of the cached context
    out = fa.attention(q, k, v, causal=True, q_offset=511)
    q_pos = jnp.full((2, 1), 511)
    kv_pos = jnp.broadcast_to(jnp.arange(512), (2, 512))
    oracle = dense_attention(q, k, v, causal=True, q_positions=q_pos,
                             kv_positions=kv_pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle),
                               atol=2e-5)


def test_bf16_forward_and_grads_match_f32_oracle():
    """bf16 inputs run the MXU-native path (matmul operands stay bf16,
    accumulation/softmax fp32) — values must track the f32 oracle within
    bf16 tolerance. Pins the perf-critical no-upcast behavior: fp32
    operands would run the MXU at a fraction of peak."""
    rng = np.random.default_rng(7)
    q32, k32, v32 = _qkv(rng, s=128)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q32, k32, v32))
    out = fa.flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(_oracle(q32, k32, v32)),
        atol=5e-2)

    def f(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v).astype(jnp.float32) ** 2)

    def f32(q, k, v):
        return jnp.sum(_oracle(q, k, v) ** 2)

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g32 = jax.grad(f32, argnums=(0, 1, 2))(q32, k32, v32)
    for a, b in zip(g, g32):
        assert a.dtype == jnp.bfloat16
        scale = np.maximum(np.abs(np.asarray(b)), 1.0)
        np.testing.assert_allclose(
            np.asarray(a, np.float32) / scale, np.asarray(b) / scale,
            atol=8e-2)


def test_gradients_match_oracle():
    q, k, v = _qkv(np.random.default_rng(1), s=128)

    def f_flash(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_oracle(q, k, v) ** 2)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4)


def test_offsets_mask_correctly():
    """Ring-style shifted K/V block: only keys with absolute position <=
    query position may attend."""
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, s=128)
    # queries are the SECOND shard (positions 128..255), keys the first
    out = fa.flash_attention(q, k, v, causal=True, q_offset=128,
                             kv_offset=0)
    # every key position (0..127) <= every query position -> full attend,
    # equals non-causal
    ref = fa.flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    # reversed roles: no key is visible -> output must be exactly zero
    # (not a spurious mean of V)
    out2 = fa.flash_attention(q, k, v, causal=True, q_offset=0,
                              kv_offset=128)
    np.testing.assert_array_equal(np.asarray(out2), 0.0)


def test_traced_offsets_under_jit():
    """Offsets ride scalar prefetch, so traced values work — what a
    sequence-parallel shard passes for a rotated K/V block."""
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, s=128)

    @jax.jit
    def f(q, k, v, qo):
        return fa.flash_attention(q, k, v, causal=True, q_offset=qo,
                                  kv_offset=0)

    out = f(q, k, v, jnp.int32(128))
    ref = fa.flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_attention_fallback_on_odd_shapes():
    rng = np.random.default_rng(3)
    # S=100: block would be 100, not sublane-aligned -> must fall back
    assert not fa.kernel_supported(100, 100, 32)
    q = jnp.asarray(rng.standard_normal((1, 100, 2, 32)), jnp.float32)
    k, v = q + 1, q - 1
    out = fa.attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_oracle(q, k, v)), atol=2e-5)
    # d % 8 != 0 -> jnp path
    assert not fa.kernel_supported(128, 128, 30)
    q2 = jnp.asarray(rng.standard_normal((1, 90, 2, 30)), jnp.float32)
    out2 = fa.attention(q2, q2, q2, causal=True)
    np.testing.assert_allclose(np.asarray(out2),
                               np.asarray(_oracle(q2, q2, q2)), atol=2e-5)
    # aligned sub-128 sequences DO take the kernel
    assert fa.kernel_supported(96, 96, 32)


def test_transformer_flash_matches_dense():
    cfg_dense = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                                  d_model=32, d_ff=64, dtype=jnp.float32)
    cfg_flash = TransformerConfig(**{**cfg_dense.__dict__,
                                     "flash_attention": True})
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, 64, size=(2, 128)), jnp.int32)
    m_dense, m_flash = Transformer(cfg_dense), Transformer(cfg_flash)
    params = m_dense.init(jax.random.PRNGKey(0), tokens, train=False)
    out_d = m_dense.apply(params, tokens, train=False)
    out_f = m_flash.apply(params, tokens, train=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               atol=5e-5)


def _shard_ring(fn, mesh, n):
    from jax.sharding import PartitionSpec as P
    return jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(P(None, "seq"), P(None, "seq"),
                                 P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))


def test_ring_flash_matches_jnp_ring(n_devices):
    """Flash-ring (pallas per block + lse merge) equals the jnp ring and
    the full-sequence oracle, values and gradients."""
    if n_devices < 4:
        pytest.skip("needs 4+ devices")
    from horovod_tpu.parallel import ring
    n = 4
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("seq",))
    rng = np.random.default_rng(7)
    b, s, h, d = 2, 4 * 128, 2, 32
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

    flash = _shard_ring(
        lambda q, k, v: ring.ring_attention(q, k, v, "seq", causal=True,
                                            use_flash=True), mesh, n)
    plain = _shard_ring(
        lambda q, k, v: ring.ring_attention(q, k, v, "seq", causal=True),
        mesh, n)
    out_f, out_p = flash(q, k, v), plain(q, k, v)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_p),
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(out_f),
                               np.asarray(_oracle(q, k, v)), atol=2e-5)

    # all three gradients: dq accumulates locally, dk/dv rotate home
    # with their blocks — the fused ring backward must match the dense
    # jnp-ring VJP exactly
    g_f = jax.grad(lambda q, k, v: jnp.sum(flash(q, k, v) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    g_p = jax.grad(lambda q, k, v: jnp.sum(plain(q, k, v) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4)


def test_ring_flash_backward_memory_bounded(n_devices):
    """The fused ring backward must not materialize S_local x S_local
    score blocks: compiled temp memory stays well under the dense
    jnp-ring VJP's (which pays O(S_local^2) per scan step)."""
    if n_devices < 4:
        pytest.skip("needs 4+ devices")
    from horovod_tpu.parallel import ring
    n = 4
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("seq",))
    b, s, h, d = 1, 4 * 512, 2, 64  # S_local = 512

    def shard(fn):
        return _shard_ring(fn, mesh, n)

    flash = shard(lambda q, k, v: ring.ring_attention(
        q, k, v, "seq", causal=True, use_flash=True))
    plain = shard(lambda q, k, v: ring.ring_attention(
        q, k, v, "seq", causal=True))
    q = jnp.zeros((b, s, h, d), jnp.float32)

    def temp_bytes(f):
        g = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(f(q, k, v) ** 2), argnums=(0, 1, 2)))
        ma = g.lower(q, q, q).compile().memory_analysis()
        return getattr(ma, "temp_size_in_bytes", None)

    t_flash, t_plain = temp_bytes(flash), temp_bytes(plain)
    if t_flash is None or t_plain is None:
        pytest.skip("backend exposes no memory analysis")
    # observed ~9x on the CPU backend; require at least 2x headroom so
    # the assert is about the asymptotic class, not compiler noise
    assert t_flash * 2 < t_plain, (t_flash, t_plain)


def test_transformer_ring_flash_trains(hvd, n_devices):
    if n_devices < 4:
        pytest.skip("needs 4+ devices")
    import optax

    from horovod_tpu import hvd_jax, training
    ndata, nseq = 2, 2
    devs = np.asarray(jax.devices()[:4]).reshape(ndata, nseq)
    mesh = jax.sharding.Mesh(devs, ("data", "seq"))
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            d_model=32, d_ff=64, dtype=jnp.float32,
                            sequence_axis="seq", flash_attention=True)
    init_cfg = TransformerConfig(**{**cfg.__dict__, "sequence_axis": None,
                                    "flash_attention": False})
    tx = hvd_jax.DistributedOptimizer(optax.adam(0.01),
                                      axes=("data", "seq"))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(4, nseq * 128)),
        jnp.int32)
    st = training.create_train_state(Transformer(init_cfg), tx,
                                     jax.random.PRNGKey(0), tokens[:1])
    step = training.make_lm_train_step(Transformer(cfg), tx, mesh=mesh,
                                       batch_axis="data", seq_axis="seq",
                                       donate=False)
    losses = []
    for _ in range(5):
        st, loss = step(st, tokens)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_gradients_multi_block_and_offsets():
    """s=512 with block 128 -> 4x4 backward grid: exercises scratch
    init/finalize, cross-block accumulation, and the causal block-skip;
    offset variant exercises the shifted-mask gradient paths."""
    rng = np.random.default_rng(9)
    q, k, v = _qkv(rng, s=512, h=2, d=32)

    def f_flash(q, k, v, qo=0, ko=0):
        return jnp.sum(fa.flash_attention(q, k, v, q_offset=qo,
                                          kv_offset=ko) ** 2)

    def f_ref(q, k, v, qo=0, ko=0):
        b, s, h, d = q.shape
        bh = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
        off = jnp.asarray([qo, ko], jnp.int32)
        r = fa._reference_attention(bh(q), bh(k), bh(v), off, True,
                                    1.0 / (d ** 0.5))
        return jnp.sum(r ** 2)

    for qo, ko in [(0, 0), (512, 0), (256, 256)]:
        gf = jax.grad(lambda q, k, v: f_flash(q, k, v, qo, ko),
                      argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: f_ref(q, k, v, qo, ko),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-3)
