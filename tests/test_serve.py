"""Serving plane (ISSUE 11): paged KV cache, continuous-batching
engine, params-only manifest loading, rolling reload, and the tier-1
e2e contract — train → commit manifest → serve over HTTP on a CPU mesh
with continuous-batched decode token-identical to a hand-fed
single-shot decode, and a rolling weight reload dropping no in-flight
request. See docs/SERVING.md."""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import ckpt as ckpt_lib
from horovod_tpu.ckpt import manifest as manifest_lib
from horovod_tpu.ckpt import sharded as sharded_lib
from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.ops import fusion
from horovod_tpu.parallel import zero
from horovod_tpu.serve import kvcache, loader
from horovod_tpu.serve.engine import Request, RequestError, ServeEngine
from horovod_tpu.serve.server import ServeServer
from horovod_tpu.telemetry.registry import MetricsRegistry
from horovod_tpu.training import TrainState


def _model(vocab=64, layers=2, heads=2, d_model=32, d_ff=64, seed=0):
    cfg = TransformerConfig(vocab_size=vocab, num_layers=layers,
                            num_heads=heads, d_model=d_model, d_ff=d_ff,
                            dtype=jnp.float32, flash_attention=False)
    model = Transformer(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), toks)["params"]
    return cfg, model, params


def _kv(cfg, num_blocks=64, block_size=4, mbps=16):
    return kvcache.KVCacheConfig(
        num_blocks=num_blocks, block_size=block_size,
        num_layers=cfg.num_layers, num_heads=cfg.num_heads,
        head_dim=cfg.d_model // cfg.num_heads,
        max_blocks_per_seq=mbps, dtype=jnp.float32)


def _oracle(model, params, prompt, n):
    """Hand-fed single-shot greedy decode: the full forward re-run per
    token, no cache — the reference the engine must match."""
    out = list(prompt)
    for _ in range(n):
        logits = model.apply({"params": params},
                             jnp.asarray([out], jnp.int32))
        out.append(int(jnp.argmax(logits[0, -1])))
    return out[len(prompt):]


def _assert_no_leak(eng):
    """Every allocated block is either gone or held ONLY by the prefix
    cache; clearing the cache must return the pool to empty."""
    cached = eng.prefix_cache.size if eng.prefix_cache is not None else 0
    assert eng.allocator.in_use == cached
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
    assert eng.allocator.in_use == 0
    assert eng.allocator.available == eng.allocator.capacity


def _run_until(eng, reqs, max_steps=500):
    for _ in range(max_steps):
        if all(r.state in ("done", "failed") for r in reqs):
            return
        eng.step()
    raise AssertionError(
        f"requests not finished after {max_steps} scheduler iterations: "
        f"{[(r.id, r.state) for r in reqs]}")


def _save_world(root, step, tree, world, meta=None):
    """Play all ``world`` ranks of one save in-process (the test_ckpt
    pattern): every rank's shard + phase-1 ack, then the commit."""
    zi = None
    for r in range(world):
        payload, zi = ckpt_lib.snapshot_tree(tree, r, world)
        sharded_lib.write_shard(root, step, payload)
    return manifest_lib.commit(root, step, 0, world, meta=meta,
                               zero_info=zi, keep=None)


# ---------------------------------------------------------------------------
# Paged KV cache
# ---------------------------------------------------------------------------


def test_allocator_roundtrip_exhaustion_and_double_free():
    a = kvcache.BlockAllocator(8)  # block 0 reserved -> capacity 7
    assert a.capacity == 7 and a.available == 7 and a.in_use == 0
    b1 = a.alloc(3)
    b2 = a.alloc(4)
    assert len(b1) == 3 and len(b2) == 4 and a.available == 0
    assert kvcache.NULL_BLOCK not in b1 + b2  # block 0 never handed out
    assert a.alloc(1) is None            # all-or-nothing exhaustion
    assert a.in_use == 7
    a.free(b1)
    assert a.available == 3 and a.alloc(3) is not None
    with pytest.raises(ValueError, match="double free"):
        a.free(b2 + b2[:1])  # freeing b2 once consumes it; the dup trips


def test_kvcache_write_gather_roundtrip():
    cfg = kvcache.KVCacheConfig(num_blocks=6, block_size=4, num_layers=2,
                                num_heads=2, head_dim=8,
                                max_blocks_per_seq=3, dtype=jnp.float32)
    pool = kvcache.init_pool(cfg)
    rng = np.random.default_rng(0)
    # two sequences: 6 tokens into blocks (1,2), 3 tokens into (3,)
    table = jnp.asarray([[1, 2, 0], [3, 0, 0]], jnp.int32)
    nk = jnp.asarray(rng.standard_normal((2, 2, 6, 2, 8)), jnp.float32)
    nv = jnp.asarray(rng.standard_normal((2, 2, 6, 2, 8)), jnp.float32)
    mask = jnp.asarray([[True] * 6, [True] * 3 + [False] * 3])
    pool = kvcache.write_tokens(pool, table, jnp.asarray([0, 0]),
                                nk, nv, mask=mask)
    k_ctx, v_ctx = kvcache.gather_context(pool, table)
    assert k_ctx.shape == (2, 2, 12, 2, 8)
    np.testing.assert_array_equal(np.asarray(k_ctx[:, 0, :6]),
                                  np.asarray(nk[:, 0]))
    np.testing.assert_array_equal(np.asarray(v_ctx[:, 1, :3]),
                                  np.asarray(nv[:, 1, :3]))
    # positions: real slots 0..len-1, pads carry the mask-out sentinel
    pos = kvcache.context_positions(jnp.asarray([6, 3]), cfg.max_context)
    assert pos.shape == (2, 12)
    np.testing.assert_array_equal(np.asarray(pos[0, :6]), np.arange(6))
    assert int(pos[0, 6]) == int(kvcache.PAD_POSITION)
    assert int(pos[1, 3]) == int(kvcache.PAD_POSITION)
    # pool sizing math of docs/SERVING.md
    assert cfg.pool_bytes() == 2 * 2 * 6 * 4 * 2 * 8 * 4
    assert cfg.blocks_for(9) == 3 and cfg.blocks_for(8) == 2


def test_incremental_decode_matches_full_forward():
    """The model-level contract under the engine: feeding tokens one at
    a time through kv_cache reproduces the full forward's logits."""
    cfg, model, params = _model()
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(0, 64, (1, 10)), jnp.int32)
    full = model.apply({"params": params}, toks)
    L, H, D = cfg.num_layers, cfg.num_heads, cfg.d_model // cfg.num_heads
    ck = jnp.zeros((L, 1, 16, H, D), jnp.float32)
    cv = jnp.zeros_like(ck)
    for t in range(10):
        cpos = kvcache.context_positions(jnp.asarray([t]), 16)
        logits, (nk, nv) = model.apply(
            {"params": params}, toks[:, t:t + 1],
            positions=jnp.asarray([[t]], jnp.int32),
            kv_cache=(ck, cv, cpos))
        np.testing.assert_allclose(np.asarray(logits[0, 0]),
                                   np.asarray(full[0, t]), atol=1e-4)
        ck = ck.at[:, :, t].set(nk[:, :, 0])
        cv = cv.at[:, :, t].set(nv[:, :, 0])


def test_decode_mode_guards():
    cfg, model, params = _model()
    cache = (jnp.zeros((2, 1, 4, 2, 16)), jnp.zeros((2, 1, 4, 2, 16)),
             jnp.zeros((1, 4), jnp.int32))
    with pytest.raises(ValueError, match="positions"):
        model.apply({"params": params}, jnp.zeros((1, 1), jnp.int32),
                    kv_cache=cache)


# ---------------------------------------------------------------------------
# Continuous-batching engine vs the single-shot oracle
# ---------------------------------------------------------------------------


def test_engine_matches_single_shot_oracle_with_midflight_joins():
    """Iteration-level admission: requests joining a RUNNING decode
    batch still produce token streams identical to their own hand-fed
    single-shot decode."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=4,
                      prefill_chunk=4, registry=MetricsRegistry())
    rng = np.random.default_rng(2)
    p1 = list(map(int, rng.integers(0, 64, 5)))
    r1 = eng.generate(p1, 8)
    for _ in range(4):  # r1 is mid-generation when the others join
        eng.step()
    assert r1.state == "decode"
    p2 = list(map(int, rng.integers(0, 64, 9)))
    p3 = list(map(int, rng.integers(0, 64, 2)))
    r2, r3 = eng.generate(p2, 8), eng.generate(p3, 8)
    _run_until(eng, [r1, r2, r3])
    for p, r in ((p1, r1), (p2, r2), (p3, r3)):
        assert r.generated == _oracle(model, params, p, 8)
        assert r.result(timeout=5) == r.generated  # stream sees the same
        assert r.finish_reason == "length"
    _assert_no_leak(eng)


def test_engine_sharded_decode_batch_matches_oracle(hvd, n_devices):
    """max_slots == device count: the decode batch is SHARDED over the
    mesh's data axes (the TPU-relevant placement) and the tokens must
    still equal the single-shot oracle."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg, num_blocks=128),
                      max_slots=n_devices, prefill_chunk=4,
                      registry=MetricsRegistry())
    from jax.sharding import PartitionSpec as P
    assert eng._batch_sharding.spec == P(eng.plan.data_axes)
    rng = np.random.default_rng(3)
    prompts = [list(map(int, rng.integers(0, 64, 3 + i)))
               for i in range(n_devices)]
    reqs = [eng.generate(p, 4) for p in prompts]
    _run_until(eng, reqs)
    for p, r in zip(prompts, reqs):
        assert r.generated == _oracle(model, params, p, 4)


def test_engine_eos_stops_early():
    cfg, model, params = _model()
    rng = np.random.default_rng(4)
    p = list(map(int, rng.integers(0, 64, 6)))
    first = _oracle(model, params, p, 1)[0]
    eng = ServeEngine(model, params, _kv(cfg), max_slots=2,
                      prefill_chunk=4, registry=MetricsRegistry())
    r = eng.generate(p, 50, eos_id=first)  # first sampled token IS eos
    _run_until(eng, [r])
    assert r.generated == [first] and r.finish_reason == "eos"
    _assert_no_leak(eng)


# ---------------------------------------------------------------------------
# Scheduler semantics on a fake clock
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def test_admission_is_fifo_order():
    """max_slots=1: three queued requests are served strictly in
    arrival order."""
    cfg, model, params = _model()
    clk = _Clock()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=1,
                      prefill_chunk=4, clock=clk,
                      registry=MetricsRegistry())
    rng = np.random.default_rng(5)
    reqs = []
    for _ in range(3):
        reqs.append(eng.generate(list(map(int, rng.integers(0, 64, 4))),
                                 3))
        clk.advance(1.0)
    finish_order = []
    for _ in range(200):
        if all(r.state == "done" for r in reqs):
            break
        eng.step()
        clk.advance(0.01)
        for r in reqs:
            if r.state == "done" and r.id not in finish_order:
                finish_order.append(r.id)
    assert finish_order == [r.id for r in reqs]
    # while r0 ran, the others were queue-depth visible
    assert eng.instruments.queue_depth.value == 0


def test_longest_waiting_prefill_preempts_newer_ones():
    """Two admitted prefills: every chunk goes to the earliest-arrival
    (longest-waiting) one until its prompt is done; only then does the
    newer request get its first chunk."""
    cfg, model, params = _model()
    clk = _Clock()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=2,
                      prefill_chunk=4, clock=clk,
                      registry=MetricsRegistry())
    rng = np.random.default_rng(6)
    r_long = eng.generate(list(map(int, rng.integers(0, 64, 12))), 2)
    clk.advance(1.0)
    r_short = eng.generate(list(map(int, rng.integers(0, 64, 3))), 2)
    prefill_seq = []
    for _ in range(10):
        stats = eng.step()
        clk.advance(0.01)
        if "prefilled" in stats:
            prefill_seq.append(stats["prefilled"])
        if r_long.state == "done" and r_short.state == "done":
            break
    # 12-token prompt at chunk 4 = 3 chunks, all before r_short's one
    assert prefill_seq[:4] == [r_long.id] * 3 + [r_short.id]


def test_prefill_advances_alongside_decode():
    """A waiting prefill is never starved by a busy decode batch — one
    iteration advances both (the chunked-prefill scheduling claim)."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=2,
                      prefill_chunk=4, registry=MetricsRegistry())
    rng = np.random.default_rng(7)
    r1 = eng.generate(list(map(int, rng.integers(0, 64, 4))), 30)
    for _ in range(3):
        eng.step()
    assert r1.state == "decode"
    tokens_before = len(r1.generated)
    r2 = eng.generate(list(map(int, rng.integers(0, 64, 12))), 2)
    stats = eng.step()
    assert stats.get("prefilled") == r2.id, stats
    assert stats.get("decoded") == 1
    assert len(r1.generated) == tokens_before + 1
    assert r2.prefilled == 4


def test_kv_exhaustion_backpressure_then_eviction_readmits():
    """A request that cannot reserve its KV blocks waits in the queue
    (backpressure); the finished request's eviction returns its blocks
    and the waiter admits. Blocks all return to the pool at the end."""
    cfg, model, params = _model()
    # capacity 4 blocks of 4 tokens: one (4 prompt + 8 new) request
    # needs 3 blocks, so two can never run together
    eng = ServeEngine(model, params, _kv(cfg, num_blocks=5, mbps=4),
                      max_slots=4, prefill_chunk=4,
                      registry=MetricsRegistry())
    rng = np.random.default_rng(8)
    r1 = eng.generate(list(map(int, rng.integers(0, 64, 4))), 8)
    r2 = eng.generate(list(map(int, rng.integers(0, 64, 4))), 8)
    eng.step()  # r1 admits + prefills its single chunk; r2 cannot
    assert r1.state == "decode" and r2.state == "queued"
    assert eng.queue_depth == 1
    assert eng.instruments.queue_depth.value == 1
    assert eng.instruments.kv_blocks.value == 3
    while r1.state != "done":
        eng.step()
        assert r2.state == "queued"  # backpressured the whole time
    _run_until(eng, [r2])
    assert r2.generated == _oracle(model, params, r2.prompt, 8)
    # the only blocks still held are the prefix cache's claim on the
    # two finished prompts' full blocks
    _assert_no_leak(eng)


def test_submit_rejects_unsatisfiable_reservation():
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg, num_blocks=5, mbps=4),
                      max_slots=1, prefill_chunk=4,
                      registry=MetricsRegistry())
    req = Request([1, 2, 3], 1000)  # needs far more than 4 blocks
    with pytest.raises(RequestError, match="KV blocks"):
        eng.submit(req)
    assert req.state == "failed"
    with pytest.raises(RequestError):
        req.result(timeout=1)
    assert eng.instruments.failed.value == 1


def test_serve_metrics_families_advance():
    cfg, model, params = _model()
    reg = MetricsRegistry()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=2,
                      prefill_chunk=4, registry=reg)
    rng = np.random.default_rng(9)
    reqs = [eng.generate(list(map(int, rng.integers(0, 64, 4))), 5)
            for _ in range(2)]
    _run_until(eng, reqs)
    ins = eng.instruments
    assert ins.submitted.value == 2 and ins.completed.value == 2
    assert ins.tokens.value == 10
    assert ins.ttft_seconds.count == 2
    assert ins.inter_token_seconds.count == 8  # 4 gaps per request
    # the family renders under the catalogued names
    text = reg.render_prometheus()
    assert 'hvd_serve_requests_total{event="completed"} 2' in text
    assert "hvd_serve_ttft_seconds_count 2" in text


# ---------------------------------------------------------------------------
# Manifest probe + params-only loading + rolling reload
# ---------------------------------------------------------------------------


def test_latest_manifest_probe_ignores_torn_dirs(tmp_path):
    root = str(tmp_path)
    assert manifest_lib.latest_manifest(root) is None
    _, model, params = _model()
    state = TrainState(params=params, opt_state=optax.adam(1e-2).init(
        params), batch_stats={}, step=jnp.asarray(1, jnp.int32))
    _save_world(root, 1, state, 1)
    probe = manifest_lib.latest_manifest(root)
    assert probe is not None and probe[0] == 1
    assert probe[1] == manifest_lib.manifest_mtime(root, 1)
    # a torn (manifest-less) newer dir never happened: shard + ok but
    # no MANIFEST — the probe must keep answering step 1
    payload, _ = ckpt_lib.snapshot_tree(state, 0, 1)
    sharded_lib.write_shard(root, 7, payload)
    assert manifest_lib.manifest_mtime(root, 7) is None
    assert manifest_lib.latest_manifest(root)[0] == 1


def test_load_params_skips_zero_rows_bitwise(tmp_path):
    """The headline loader contract: a TrainState checkpoint whose
    optimizer state is ZeRO-sharded loads params-only, bitwise, from an
    N=4 training world onto this (different-world) process — no
    optimizer reconstruction, no row assembly."""
    cfg, model, params = _model()
    leaves = jax.tree_util.tree_leaves(params)
    sched = fusion.bucket_schedule(leaves, 4, threshold_bytes=4096,
                                   axes=("data",))
    zstate = zero.init(optax.adam(1e-2), params,
                       zero.ZeroPlan(schedule=sched))
    state = TrainState(params=params, opt_state=zstate, batch_stats={},
                       step=jnp.asarray(5, jnp.int32))
    _save_world(str(tmp_path), 5, state, 4,
                meta={"model_config": {"d_model": cfg.d_model}})
    target = loader.abstract_params(model)
    step, got, meta = loader.load_params(str(tmp_path), target)
    assert step == 5 and meta["model_config"]["d_model"] == cfg.d_model
    got_l = jax.tree_util.tree_leaves(got)
    assert len(got_l) == len(leaves)
    for a, b in zip(got_l, leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_params_shape_mismatch_is_loud(tmp_path):
    _, model, params = _model()
    state = TrainState(params=params, opt_state=optax.sgd(0.1).init(
        params), batch_stats={}, step=jnp.asarray(0, jnp.int32))
    _save_world(str(tmp_path), 0, state, 2)
    _, wrong_model, _ = _model(d_model=48, heads=3)
    with pytest.raises(ValueError, match="wrong model config"):
        loader.load_params(str(tmp_path),
                           loader.abstract_params(wrong_model))


def test_load_params_falls_back_past_corrupt_newest(tmp_path):
    _, model, params = _model()
    tx = optax.sgd(0.1)
    mk = lambda s: TrainState(  # noqa: E731
        params=jax.tree_util.tree_map(lambda x: x + s, params),
        opt_state=tx.init(params), batch_stats={},
        step=jnp.asarray(s, jnp.int32))
    root = str(tmp_path)
    _save_world(root, 1, mk(0), 2)
    _save_world(root, 2, mk(1), 2)
    # rot a byte of a step-2 shard: its manifest CRC no longer matches
    path = sharded_lib.shard_path(root, 2, 0, 2)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    target = loader.abstract_params(model)
    step, got, _ = loader.load_params(root, target)
    assert step == 1  # fell back, torn-write philosophy on the read side
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(got)[0]),
        np.asarray(jax.tree_util.tree_leaves(params)[0]))
    with pytest.raises(sharded_lib.ShardValidationError):
        loader.load_params(root, target, step=2)  # explicit stays loud


class _FakeEngine:
    def __init__(self):
        self.installed = []

    def install_weights(self, params, version=None):
        self.installed.append(version)


def test_reload_watcher_poll_cycle(tmp_path):
    cfg, model, params = _model()
    tx = optax.sgd(0.1)
    root = str(tmp_path)
    state = TrainState(params=params, opt_state=tx.init(params),
                       batch_stats={}, step=jnp.asarray(1, jnp.int32))
    _save_world(root, 1, state, 1)
    eng = _FakeEngine()
    w = loader.ReloadWatcher(root, eng, loader.abstract_params(model))
    w.mark_current(1)
    assert w.poll_once() is None          # nothing new
    # torn newer dir: invisible to the probe
    payload, _ = ckpt_lib.snapshot_tree(state, 0, 1)
    sharded_lib.write_shard(root, 9, payload)
    assert w.poll_once() is None
    # a real newer manifest reloads
    _save_world(root, 2, state, 1)
    assert w.poll_once() == 2
    assert eng.installed == [2]
    assert w.poll_once() is None          # installed; no re-load
    # re-commit of the SAME step number (post-fallback numbering runs
    # backwards): the mtime half of the probe key catches it
    time.sleep(0.05)
    manifest_lib.clear_stale_ack(root, 2, 0, 1)
    _save_world(root, 2, state, 1)
    assert w.poll_once() == 2
    assert eng.installed == [2, 2]


def test_reload_watcher_survives_corrupt_highest_step(tmp_path):
    """The backwards-step-numbering case the manifest protocol
    documents: the highest-NUMBERED step is manifest-complete but its
    shards are unloadable (training fell back below it and resumed),
    and fresh LOWER-numbered commits carry newer mtimes. The watcher
    ranks candidates by commit time, so the fresh commits roll in —
    ranking by step number would pin it on the damaged step forever."""
    _, model, params = _model()
    tx = optax.sgd(0.1)
    root = str(tmp_path)
    state = TrainState(params=params, opt_state=tx.init(params),
                       batch_stats={}, step=jnp.asarray(1, jnp.int32))
    _save_world(root, 1, state, 1)
    eng = _FakeEngine()
    w = loader.ReloadWatcher(root, eng, loader.abstract_params(model))
    w.mark_current(1)
    time.sleep(0.02)
    _save_world(root, 10, state, 1)
    path = sharded_lib.shard_path(root, 10, 0, 1)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    assert w.poll_once() is None          # newest-by-mtime is damaged
    assert w.poll_once() is None          # remembered, not retried
    assert eng.installed == []
    time.sleep(0.02)
    _save_world(root, 6, state, 1)        # fresh, LOWER step number
    assert w.poll_once() == 6             # recency = commit time
    assert eng.installed == [6]


# ---------------------------------------------------------------------------
# The tier-1 e2e: train -> manifest -> HTTP serving -> rolling reload
# ---------------------------------------------------------------------------


def _http_generate(port, prompt, n, timeout=120):
    body = json.dumps({"tokens": [int(t) for t in prompt],
                       "max_new_tokens": n}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body,
        headers={"Content-Type": "application/json"})
    toks, done = [], None
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        for line in resp:
            obj = json.loads(line)
            if "token" in obj:
                toks.append(obj["token"])
            elif obj.get("done"):
                done = obj
            else:
                raise AssertionError(f"stream error: {obj}")
    return toks, done


def test_serve_e2e_http_from_manifest(tmp_path, hvd):
    """The acceptance run: train 2 steps on the 8-device mesh, commit a
    2-rank manifest, serve it (N=2 → M=8), drive 3 concurrent streaming
    HTTP requests whose tokens must equal a hand-fed single-shot
    decode, then drop a newer manifest and watch the rolling reload
    swap weights under a live request without failing it."""
    import horovod_tpu as hvd_mod
    from horovod_tpu import training

    cfg, model, params0 = _model(vocab=64)
    root = str(tmp_path)
    rng = np.random.default_rng(12)

    # -- 1. really train 2 steps (explicit LM path on the live mesh) ----
    tx = hvd_mod.DistributedOptimizer(optax.adam(1e-2))
    state = training.TrainState(
        params=params0, opt_state=tx.init(params0), batch_stats={},
        step=jnp.zeros((), jnp.int32))
    step_fn = training.make_lm_train_step(model, tx, donate=False)
    toks = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
    for _ in range(2):
        state, _ = step_fn(state, toks)
    state = jax.device_get(state)
    _save_world(root, 2, state, 2)  # an N=2 training world's manifest

    trained = jax.device_get(state.params)

    # -- 2. load params-only onto the serving mesh + start the stack ----
    target = loader.abstract_params(model)
    step, params, _ = loader.load_params(root, target)
    assert step == 2
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(trained)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    eng = ServeEngine(model, params, _kv(cfg, num_blocks=257, mbps=64),
                      max_slots=4, prefill_chunk=4, weights_version=2,
                      registry=MetricsRegistry())
    watcher = loader.ReloadWatcher(root, eng, target, poll_s=0.05)
    watcher.mark_current(2)
    server = ServeServer(eng, port=0)
    port = server.start()
    eng.start()
    watcher.start()
    try:
        # -- 3. three concurrent streamed generations == oracle ---------
        prompts = [list(map(int, rng.integers(0, 64, n)))
                   for n in (3, 7, 10)]
        results = [None] * len(prompts)

        def worker(i, p):
            results[i] = _http_generate(port, p, 6)

        threads = [threading.Thread(target=worker, args=(i, p))
                   for i, p in enumerate(prompts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        for p, res in zip(prompts, results):
            assert res is not None, "request thread did not finish"
            got, done = res
            want = _oracle(model, trained, p, 6)
            assert got == want, (got, want)
            assert done["tokens"] == got
            assert done["finish_reason"] == "length"

        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert h["status"] == "ok" and h["weights_version"] == 2
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "hvd_serve_tokens_total" in scrape

        # -- 4. rolling reload under a live request ---------------------
        long_prompt = prompts[0]
        long_result = {}

        def long_worker():
            long_result["r"] = _http_generate(port, long_prompt, 200)

        lt = threading.Thread(target=long_worker)
        lt.start()
        deadline = time.time() + 60
        while not eng.active_count and time.time() < deadline:
            time.sleep(0.01)  # wait until it is genuinely in flight
        assert eng.active_count, "long request never started"

        state2 = training.TrainState(
            params=jax.tree_util.tree_map(lambda x: x * 1.01, trained),
            opt_state=tx.init(trained), batch_stats={},
            step=jnp.asarray(3, jnp.int32))
        _save_world(root, 3, state2, 1)  # a DIFFERENT world's commit

        while eng.weights_version != 3 and time.time() < deadline:
            time.sleep(0.02)
        assert eng.weights_version == 3, "reload never swapped in"
        in_flight_at_swap = eng.active_count

        lt.join(timeout=180)
        assert "r" in long_result, "long request did not complete"
        got, done = long_result["r"]
        assert done is not None and done["finish_reason"] == "length"
        assert len(got) == 200           # zero dropped/failed requests
        assert in_flight_at_swap >= 1, \
            "weights swapped only after the request finished — the " \
            "rolling-reload claim was not exercised"
        assert eng.instruments.failed.value == 0
    finally:
        watcher.stop()
        server.stop()
        eng.stop()


def test_http_bad_requests_get_400(hvd):
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=1,
                      prefill_chunk=4, registry=MetricsRegistry())
    server = ServeServer(eng, port=0)
    port = server.start()
    eng.start()
    try:
        for body in (b"{}", b'{"tokens": "nope"}',
                     b'{"tokens": [1], "eos_id": "x"}',
                     b'{"tokens": [1], "max_new_tokens": "many"}',
                     b'{"tokens": [1], "temperature": -0.5}',
                     b'{"tokens": [1], "top_p": 0}',
                     b'{"tokens": [1], "seed": "lucky"}',
                     json.dumps({"tokens": [1], "max_new_tokens":
                                 10 ** 6}).encode()):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body)
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 400
    finally:
        server.stop()
        eng.stop()


def test_cli_parser_and_meta_check():
    from horovod_tpu.serve import cli

    args = cli.build_parser().parse_args(
        ["--ckpt-dir", "/tmp/x", "--num-layers", "2", "--d-model", "32",
         "--num-heads", "2", "--d-ff", "64"])
    assert args.num_layers == 2 and args.ckpt_dir == "/tmp/x"
    cli._check_meta({"model_config": {"d_model": 32}}, args)  # matches
    cli._check_meta({}, args)                                 # absent ok
    with pytest.raises(SystemExit, match="mismatched architecture"):
        cli._check_meta({"model_config": {"d_model": 512}}, args)


# ---------------------------------------------------------------------------
# ISSUE 16: ref-counted allocator, prefix caching / CoW, real sampling
# ---------------------------------------------------------------------------


def test_allocator_free_unallocated_and_retain_validation():
    a = kvcache.BlockAllocator(8)
    with pytest.raises(ValueError, match="allocated: no"):
        a.free([3])
    with pytest.raises(ValueError, match="retain"):
        a.retain([5])
    b = a.alloc(2)
    a.retain(b)                      # refs 2
    a.free(b)                        # refs 1 — still allocated
    assert a.in_use == 2 and all(a.ref_count(x) == 1 for x in b)
    a.free(b)                        # refs 0 — returned to the pool
    assert a.in_use == 0 and a.available == a.capacity
    with pytest.raises(ValueError, match="double free"):
        a.free(b)
    # validate-first: a bad free is ATOMIC — nothing is half-freed
    c = a.alloc(1)
    with pytest.raises(ValueError, match="allocated: no"):
        a.free(c + [99])
    assert a.in_use == 1 and a.ref_count(c[0]) == 1
    a.free(c)


def test_allocator_invariant_fuzz():
    """Randomized alloc/retain/free against a shadow refcount model:
    conservation (available + in_use == capacity) and per-block
    refcounts hold after every operation."""
    from collections import Counter

    rng = np.random.default_rng(123)
    a = kvcache.BlockAllocator(33)
    refs = Counter()
    for _ in range(2000):
        op = int(rng.integers(0, 3))
        if op == 0:
            n = int(rng.integers(1, 5))
            got = a.alloc(n)
            if got is None:
                assert a.available < n    # refuses only when it must
            else:
                assert len(set(got)) == n
                for b in got:
                    assert refs[b] == 0   # never hands out a live block
                    refs[b] += 1
        elif op == 1 and refs:
            b = int(rng.choice(list(refs.keys())))
            a.retain([b])
            refs[b] += 1
        elif op == 2 and refs:
            b = int(rng.choice(list(refs.keys())))
            a.free([b])
            refs[b] -= 1
            if not refs[b]:
                del refs[b]
        assert a.in_use == len(refs)
        assert a.available + a.in_use == a.capacity
        for b, n in refs.items():
            assert a.ref_count(b) == n
    for b, n in list(refs.items()):
        a.free([b] * n)                   # dups within one call are fine
    assert a.in_use == 0 and a.available == a.capacity


def test_prefix_cache_chain_match_insert_release():
    a = kvcache.BlockAllocator(16)
    pc = kvcache.PrefixCache(a, block_size=4)
    toks = list(range(10))                # 2 full blocks + a partial
    assert pc.match(toks) == (0, [])
    blocks = a.alloc(3)
    pc.insert(toks, blocks[:2])           # full blocks only, per contract
    assert all(a.ref_count(b) == 2 for b in blocks[:2])  # cache holds refs
    assert pc.reclaimable() == 0          # a live holder: eviction frees 0
    n, shared = pc.match(toks)
    assert n == 8 and shared == blocks[:2]
    n2, s2 = pc.match(toks[:7])           # shorter prompt: prefix chain
    assert n2 == 4 and s2 == blocks[:1]
    assert pc.match([99] + toks[1:]) == (0, [])   # diverging first block
    # chained hashing: same 2nd-block CONTENT behind a different 1st
    # block must not match (the chain key includes the predecessor)
    other = [7] * 4 + toks[4:8]
    assert pc.match(other) == (0, [])
    # release-under-pressure evicts LRU entries until `need` fits
    a.free(blocks)                        # drop our refs; cache keeps its 2
    assert a.available == a.capacity - 2
    assert pc.reclaimable() == 2          # cache is the sole holder now
    pc.release(a.capacity)                # need everything -> evict all
    assert pc.size == 0 and a.available == a.capacity
    assert pc.match(toks) == (0, [])


def test_engine_prefix_cache_hits_match_oracle():
    """Requests sharing a system prompt skip cached prefill chunks and
    still produce oracle-identical tokens; the cached-token accounting
    and metric advance together."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=4,
                      prefill_chunk=4, registry=MetricsRegistry())
    rng = np.random.default_rng(21)
    system = list(map(int, rng.integers(0, 64, 9)))   # 2 full blocks + 1
    r1 = eng.generate(system + [5], 6)
    _run_until(eng, [r1])
    assert r1.cached_prompt_tokens == 0               # first writer: miss
    assert r1.generated == _oracle(model, params, r1.prompt, 6)
    r2 = eng.generate(system + [7, 8], 6)
    _run_until(eng, [r2])
    assert r2.cached_prompt_tokens == 8               # both full blocks
    assert r2.generated == _oracle(model, params, r2.prompt, 6)
    assert eng.cached_prefill_tokens == 8
    assert eng.instruments.cached_prefill_tokens.value == 8
    assert eng.prompt_tokens == len(r1.prompt) + len(r2.prompt)
    _assert_no_leak(eng)


def test_engine_cow_fork_keeps_cached_blocks_immutable():
    """Exact resubmission of a block-aligned prompt: the final prompt
    token must re-prefill (its logits seed generation), which WRITES
    into the last shared block — copy-on-write forks it so the cache's
    copy stays pristine for the next hit."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=4,
                      prefill_chunk=4, registry=MetricsRegistry())
    rng = np.random.default_rng(22)
    p = list(map(int, rng.integers(0, 64, 8)))        # exactly 2 blocks
    want = _oracle(model, params, p, 5)
    r1 = eng.generate(p, 5)
    _run_until(eng, [r1])
    assert r1.generated == want
    r2 = eng.generate(p, 5)                           # exact resubmit
    _run_until(eng, [r2])
    assert r2.cached_prompt_tokens == 7               # len(prompt) - 1
    assert r2.generated == want
    r3 = eng.generate(p, 5)                           # cache still intact
    _run_until(eng, [r3])
    assert r3.cached_prompt_tokens == 7 and r3.generated == want
    _assert_no_leak(eng)


def test_engine_prefix_cache_evicts_under_allocator_pressure():
    """A full cache yields its blocks (LRU-first) when a new admission
    cannot reserve fresh ones — correctness beats reuse."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params,
                      _kv(cfg, num_blocks=9, block_size=4, mbps=8),
                      max_slots=1, prefill_chunk=4,
                      registry=MetricsRegistry())
    rng = np.random.default_rng(23)
    p1 = list(map(int, rng.integers(0, 64, 8)))
    r1 = eng.generate(p1, 4)                          # 3 blocks; caches 2
    _run_until(eng, [r1])
    assert eng.prefix_cache.size == 2
    p2 = list(map(int, rng.integers(0, 64, 26)))      # needs 8 blocks
    r2 = eng.generate(p2, 4)
    _run_until(eng, [r2])
    assert r2.generated == _oracle(model, params, p2, 4)
    assert eng.prefix_cache.match(p1) == (0, [])      # LRU gave blocks up
    assert eng.prefix_cache.match(p2)[0] > 0          # newest prompt cached
    _assert_no_leak(eng)


def test_admit_release_under_pressure_never_frees_matched_blocks():
    """Regression: admission matched cached prefix blocks, then a
    release() under KV pressure evicted those very entries (the cache
    held their only reference), returned the blocks to the free list,
    and the retry alloc handed them back as fresh WRITABLE blocks —
    duplicate block-table entries, decode writing into the cached
    prefix. The match must be pinned before any release; an admission
    still backpressured after the release drops the pin and retries
    later."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params,
                      _kv(cfg, num_blocks=10, block_size=4, mbps=8),
                      max_slots=2, prefill_chunk=4,
                      registry=MetricsRegistry())
    p1 = list(range(8))                       # 2 full blocks
    r1 = eng.generate(p1, 4)                  # 3 blocks; caches 2
    _run_until(eng, [r1])
    assert eng.prefix_cache.size == 2
    assert eng.prefix_cache.reclaimable() == 2    # cache is sole holder
    # a live sequence takes 4 of the 7 free blocks, so the next one
    # (6 blocks total: 2 matched + 4 fresh > 3 free) forces release()
    # to eat into its OWN matched entries
    r_live = eng.generate([9] * 8, 8)             # blocks_for(16) = 4
    p3 = p1 + list(range(16, 25))                 # 17 tokens, 6 blocks
    r3 = eng.generate(p3, 4)
    for _ in range(200):
        eng.step()
        live = [r for r in eng._slots if r is not None]
        for r in live:
            # a block table never repeats a block — every position is
            # distinct KV storage
            assert len(set(r.blocks)) == len(r.blocks), r.blocks
        for i, a in enumerate(live):              # p3 shares nothing
            for b in live[i + 1:]:                # with [9]*8: disjoint
                assert not set(a.blocks) & set(b.blocks)
        if all(r.state == "done" for r in (r_live, r3)):
            break
    else:
        raise AssertionError("requests did not finish")
    assert r_live.generated == _oracle(model, params, r_live.prompt, 8)
    assert r3.generated == _oracle(model, params, p3, 4)
    # the pressured admission rescinded its match (pin dropped on the
    # backpressure path) and later admitted uncached
    assert r3.cached_prompt_tokens == 0
    _assert_no_leak(eng)


def test_sampling_temperature_zero_is_bitwise_greedy():
    from horovod_tpu.serve.sampling import SamplingParams

    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=2,
                      prefill_chunk=4, registry=MetricsRegistry())
    rng = np.random.default_rng(31)
    p = list(map(int, rng.integers(0, 64, 6)))
    r = eng.generate(p, 10, sampling=SamplingParams(temperature=0.0,
                                                    top_p=0.7, seed=99))
    _run_until(eng, [r])
    assert r.generated == _oracle(model, params, p, 10)


def test_seeded_sampling_deterministic_across_replicas_and_reload():
    """Same (seed, prompt) → identical stream on two independent
    engines, across a mid-flight weight reload (same values, new
    version), and across a continuation re-dispatch (prompt + already-
    generated tokens, remaining budget) — the position-keyed RNG makes
    the stream independent of WHERE and in how many hops it ran."""
    from horovod_tpu.serve.sampling import SamplingParams

    cfg, model, params = _model()
    rng = np.random.default_rng(32)
    p = list(map(int, rng.integers(0, 64, 6)))
    sp = SamplingParams(temperature=0.9, top_p=0.8, seed=7)

    def fresh():
        return ServeEngine(model, params, _kv(cfg), max_slots=2,
                           prefill_chunk=4, registry=MetricsRegistry())

    e1, e2 = fresh(), fresh()
    r1 = e1.generate(p, 12, sampling=sp)
    _run_until(e1, [r1])
    r2 = e2.generate(p, 12, sampling=sp)
    _run_until(e2, [r2])
    assert r1.generated == r2.generated           # replica-independent
    r3 = e1.generate(p, 12, sampling=SamplingParams(temperature=0.9,
                                                    top_p=0.8, seed=8))
    _run_until(e1, [r3])
    assert r3.generated != r1.generated           # the seed is live

    e3 = fresh()
    r4 = e3.generate(p, 12, sampling=sp)
    while len(r4.generated) < 6:                  # mid-flight...
        e3.step()
    e3.install_weights(params, version=9)         # ...reload (same values)
    _run_until(e3, [r4])
    assert e3.weights_version == 9
    assert r4.generated == r1.generated           # stream unchanged

    k = 5                                          # continuation hop
    r5 = e1.generate(p + r1.generated[:k], 12 - k, sampling=sp)
    _run_until(e1, [r5])
    assert r5.generated == r1.generated[k:]


def test_healthz_draining_is_503_and_refuses_admission(hvd):
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=1,
                      prefill_chunk=4, registry=MetricsRegistry())
    server = ServeServer(eng, port=0)
    port = server.start()
    eng.start()
    try:
        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert h["status"] == "ok"
        eng.set_draining(True)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert e.value.code == 503
        assert json.loads(e.value.read())["status"] == "draining"
        with pytest.raises(RequestError, match="draining"):
            eng.submit(Request([1, 2], 2))
        eng.set_draining(False)
        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert h["status"] == "ok"                # admission restored
        assert eng.generate([1, 2], 2).result(timeout=60)
    finally:
        server.stop()
        eng.stop()
