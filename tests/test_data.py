"""Input-sharding tests (reference behavior model:
torch.utils.data.distributed.DistributedSampler as used by
examples/pytorch_imagenet_resnet50.py — disjoint per-rank shards, padded
equal lengths, epoch-seeded reshuffle, full-epoch coverage)."""

import numpy as np
import pytest

from horovod_tpu import data


def test_shard_indices_disjoint_and_cover():
    n, k = 64, 4
    shards = [data.shard_indices(n, k, r, shuffle=True, epoch=1)
              for r in range(k)]
    flat = np.concatenate(shards)
    assert len(flat) == n
    assert sorted(flat.tolist()) == list(range(n))  # disjoint + complete


def test_shard_indices_padding_covers_everything():
    n, k = 10, 4  # 10 % 4 != 0 -> pad by wrapping
    shards = [data.shard_indices(n, k, r, shuffle=False) for r in range(k)]
    assert all(len(s) == 3 for s in shards)  # equal per-rank count
    assert set(np.concatenate(shards).tolist()) == set(range(n))


def test_shard_indices_drop_last_trims():
    n, k = 10, 4
    shards = [data.shard_indices(n, k, r, shuffle=False, drop_last=True)
              for r in range(k)]
    flat = np.concatenate(shards)
    assert len(flat) == 8
    assert len(set(flat.tolist())) == 8


def test_epoch_reshuffle_changes_order_not_coverage():
    n, k = 32, 2
    e0 = [data.shard_indices(n, k, r, epoch=0) for r in range(k)]
    e1 = [data.shard_indices(n, k, r, epoch=1) for r in range(k)]
    assert not np.array_equal(e0[0], e1[0])  # reshuffled
    for e in (e0, e1):
        assert sorted(np.concatenate(e).tolist()) == list(range(n))
    # deterministic: same (seed, epoch) -> same order on every "rank"
    np.testing.assert_array_equal(
        e1[0], data.shard_indices(n, k, 0, epoch=1))


def test_shard_indices_validates_shard_id():
    with pytest.raises(ValueError):
        data.shard_indices(8, 2, 2)


def test_distributed_sampler_protocol():
    s = data.DistributedSampler(10, num_replicas=4, rank=1)
    assert len(s) == 3
    i0 = list(s)
    s.set_epoch(1)
    i1 = list(s)
    assert len(i0) == len(i1) == 3
    assert i0 != i1
    assert all(isinstance(i, int) for i in i0)


def test_distributed_sampler_with_torch_dataloader():
    """The sampler drives a REAL torch DataLoader: per-rank loaders see
    disjoint examples and together cover the dataset (the
    pytorch_imagenet_resnet50.py wiring)."""
    torch = pytest.importorskip("torch")
    xs = torch.arange(12, dtype=torch.float32).reshape(12, 1)
    seen = []
    for r in range(3):
        sampler = data.DistributedSampler(12, num_replicas=3, rank=r,
                                          shuffle=True)
        sampler.set_epoch(5)
        loader = torch.utils.data.DataLoader(
            torch.utils.data.TensorDataset(xs), batch_size=2,
            sampler=sampler)
        got = torch.cat([b[0] for b in loader]).ravel().tolist()
        assert len(got) == 4
        seen.extend(got)
    assert sorted(seen) == list(range(12))


def test_shard_dataset_delegates_to_shard():
    class FakeDS:
        def shard(self, num_shards, index):
            return ("sharded", num_shards, index)

    assert data.shard_dataset(FakeDS(), 4, 2) == ("sharded", 4, 2)


def test_local_batches_disjoint_across_ranks():
    xs = np.arange(24, dtype=np.float32)
    ys = xs * 10
    seen = []
    for r in range(2):
        for bx, by in data.local_batches([xs, ys], batch_size=4,
                                         num_shards=2, shard_id=r,
                                         epoch=3):
            assert bx.shape == (4,)
            np.testing.assert_array_equal(by, bx * 10)
            seen.extend(bx.tolist())
    assert sorted(seen) == list(range(24))


def test_local_batches_drop_last_never_duplicates_within_epoch():
    """Regression (ISSUE 7 satellite): ``drop_last=True`` must thread
    through to ``shard_indices`` — previously the shard was wrap-padded
    FIRST, so with n % num_shards != 0 the job trained on duplicated
    examples in the same epoch (the padded tail re-issues head examples
    to other ranks) despite asking for the trimming semantics."""
    xs = np.arange(10, dtype=np.float32)  # 10 % 4 != 0 -> pad or trim
    seen = []
    for r in range(4):
        for (bx,) in data.local_batches([xs], batch_size=1, num_shards=4,
                                        shard_id=r, shuffle=True,
                                        epoch=0, drop_last=True):
            seen.extend(bx.tolist())
    assert len(seen) == 8  # tail trimmed, not padded
    assert len(seen) == len(set(seen)), \
        f"epoch trained duplicated examples: {sorted(seen)}"
    # drop_last=False keeps the wrap-padded full-coverage semantics
    all_seen = []
    for r in range(4):
        for (bx,) in data.local_batches([xs], batch_size=3, num_shards=4,
                                        shard_id=r, shuffle=False,
                                        drop_last=False):
            all_seen.extend(bx.tolist())
    assert set(all_seen) == set(xs.tolist())


def test_world_defaults_without_init():
    import horovod_tpu as hvd
    hvd.shutdown()  # another module's test may have left hvd live
    # uninitialized horovod -> world of 1, shard 0 (identity sharding)
    idx = data.shard_indices(6, shuffle=False)
    np.testing.assert_array_equal(idx, np.arange(6))
    # but num_shards > 1 with no shard_id must NOT default to shard 0
    # (every process would silently train on the same slice)
    with pytest.raises(ValueError, match="shard_id"):
        data.shard_indices(8, num_shards=4)
