"""Chaos harness tests (horovod_tpu/chaos/).

Fast tier: seeded-plan determinism, the --chaos spec grammar (inline,
JSON knobs, pre-expanded injections) and its rejection paths, and the
ChaosMonkey's targeting/retargeting/stall semantics on fake clocks and
fake processes — no subprocesses, no sleeps.

Slow tier: the np=3 chaos soak — ``hvdrun --chaos`` SIGTERMs a random
rank of a live CPU-mesh elastic job; the run must complete with a
bit-identical loss trajectory, the eviction must drain (not crash) the
epoch, and the flight-recorder dumps must show zero hang verdicts.
"""

import json
import os
import signal
import sys
import time

import pytest

from horovod_tpu.chaos import ChaosMonkey, ChaosPlan, Injection, parse_spec
from horovod_tpu.chaos.plan import KINDS

WORKER = os.path.join(os.path.dirname(__file__), "chaos_train_worker.py")

TARGET = 3.0
LR = 0.2


# ---------------------------------------------------------------------------
# plans: seeded determinism + spec grammar
# ---------------------------------------------------------------------------

def test_plan_generation_deterministic():
    a = ChaosPlan.generate(seed=7, interval=2.5, jitter=0.5,
                           kinds=("sigterm", "sigkill"), count=6)
    b = ChaosPlan.generate(seed=7, interval=2.5, jitter=0.5,
                           kinds=("sigterm", "sigkill"), count=6)
    assert [i.as_dict() for i in a.injections] == \
        [i.as_dict() for i in b.injections]
    assert len(a.injections) == 6
    # times strictly increase (jitter never reorders the schedule)
    ats = [i.at for i in a.injections]
    assert ats == sorted(ats) and ats[0] > 0
    # a different seed must actually change the schedule
    c = ChaosPlan.generate(seed=8, interval=2.5, jitter=0.5,
                           kinds=("sigterm", "sigkill"), count=6)
    assert [i.as_dict() for i in a.injections] != \
        [i.as_dict() for i in c.injections]


def test_plan_durations_only_for_pausing_kinds():
    plan = ChaosPlan.generate(seed=1, kinds=KINDS, count=40, duration=3.0)
    for inj in plan.injections:
        if inj.kind in ("stall", "slow_disk"):
            assert inj.duration == 3.0
        else:
            assert inj.duration == 0.0


def test_plan_validation():
    with pytest.raises(ValueError, match="unknown kind"):
        ChaosPlan.generate(kinds=("sigterm", "meteor"))
    with pytest.raises(ValueError, match="interval"):
        ChaosPlan.generate(interval=0.0)
    with pytest.raises(ValueError, match="jitter"):
        ChaosPlan.generate(jitter=1.5)
    with pytest.raises(ValueError, match="unknown injection kind"):
        ChaosPlan([Injection(at=1.0, kind="meteor", rank=0)])


def test_parse_spec_inline():
    plan = parse_spec("seed=7,interval=2.5,kinds=sigterm+sigkill,count=6")
    assert len(plan.injections) == 6
    assert {i.kind for i in plan.injections} <= {"sigterm", "sigkill"}
    # inline spec == the equivalent generate() call, byte for byte
    ref = ChaosPlan.generate(seed=7, interval=2.5,
                             kinds=("sigterm", "sigkill"), count=6)
    assert [i.as_dict() for i in plan.injections] == \
        [i.as_dict() for i in ref.injections]


def test_parse_spec_json_file_forms(tmp_path):
    knobs = tmp_path / "knobs.json"
    knobs.write_text(json.dumps({"seed": 3, "interval": 1.0, "count": 4,
                                 "kinds": ["sigkill"]}))
    plan = parse_spec(str(knobs))
    assert len(plan.injections) == 4
    assert all(i.kind == "sigkill" for i in plan.injections)

    expanded = tmp_path / "plan.json"
    expanded.write_text(json.dumps({"injections": [
        {"at": 2.0, "kind": "stall", "rank": 5, "duration": 1.5},
        {"at": 1.0, "kind": "sigterm"}]}))
    plan = parse_spec(str(expanded))
    assert [i.kind for i in plan.injections] == ["sigterm", "stall"]  # sorted
    assert plan.injections[1].duration == 1.5


def test_parse_spec_rejects_malformed(tmp_path):
    for bad in ("", "   ", "seed", "seed=x", "volume=11",
                "kinds=sigterm+meteor", "interval=0", "jitter=2"):
        with pytest.raises(ValueError):
            parse_spec(bad)
    notjson = tmp_path / "broken.json"
    notjson.write_text("{nope")
    with pytest.raises(ValueError, match="not valid JSON"):
        parse_spec(str(notjson))
    listjson = tmp_path / "list.json"
    listjson.write_text("[1, 2]")
    with pytest.raises(ValueError, match="JSON object"):
        parse_spec(str(listjson))
    badkey = tmp_path / "badkey.json"
    badkey.write_text(json.dumps({"volume": 11}))
    with pytest.raises(ValueError, match="unknown spec key"):
        parse_spec(str(badkey))


def test_cli_rejects_malformed_chaos_spec():
    from horovod_tpu.run.run import parse_args

    ok = parse_args(["-np", "2", "--chaos", "seed=1,count=2",
                     "python", "t.py"])
    assert ok.chaos == "seed=1,count=2"
    for bad in ("volume=11", "kinds=meteor", ""):
        with pytest.raises(SystemExit):
            parse_args(["-np", "2", "--chaos", bad, "python", "t.py"])


# ---------------------------------------------------------------------------
# the monkey, on fake clocks and fake processes
# ---------------------------------------------------------------------------

class FakeProc:
    def __init__(self, pid):
        self.pid = pid
        self.signals = []
        self.rc = None

    def poll(self):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)

    def kill(self):
        self.signals.append(signal.SIGKILL)
        self.rc = -9


class FakeJob:
    def __init__(self, n, pid0=100):
        self.procs = [FakeProc(pid0 + i) for i in range(n)]


def _wait_until(fn, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(0.005)
    return False


def test_monkey_fake_clock_schedule():
    """The whole schedule runs in fake time: sleeps advance a fake clock,
    injections land in order, targets follow rank % live."""
    now = {"t": 0.0}

    def sleep(dt):
        now["t"] += dt

    plan = ChaosPlan([Injection(at=10.0, kind="sigterm", rank=1),
                      Injection(at=20.0, kind="sigkill", rank=5)])
    job = FakeJob(3)
    monkey = ChaosMonkey(plan, clock=lambda: now["t"], sleep=sleep)
    monkey.attach(job)
    assert _wait_until(monkey.done)
    monkey.stop()

    done = [(inj.kind, rank) for inj, rank, _pid in monkey.injections_done]
    # rank draws 1 and 5 over 3 live procs -> ranks 1 and 2
    assert done == [("sigterm", 1), ("sigkill", 2)]
    assert job.procs[1].signals == [signal.SIGTERM]
    assert job.procs[2].signals == [signal.SIGKILL]
    assert job.procs[0].signals == []


def test_monkey_targets_only_live_procs():
    """A dead process leaves the target pool: the modulo re-maps the
    draw onto the survivors instead of signalling a corpse."""
    job = FakeJob(3)
    job.procs[0].rc = -9  # already dead
    monkey = ChaosMonkey(ChaosPlan([]), clock=lambda: 0.0,
                         sleep=lambda dt: None)
    monkey._job = job  # targeting unit test: no scheduler thread
    monkey._apply(Injection(at=0.0, kind="sigterm", rank=0))
    assert job.procs[1].signals == [signal.SIGTERM]
    assert job.procs[0].signals == []


def test_monkey_no_live_procs_skips():
    job = FakeJob(2)
    for p in job.procs:
        p.rc = 0
    monkey = ChaosMonkey(ChaosPlan([]), clock=lambda: 0.0,
                         sleep=lambda dt: None)
    monkey._job = job
    monkey._apply(Injection(at=0.0, kind="sigkill", rank=0))
    assert monkey.injections_done == []


def test_monkey_stall_freezes_then_unfreezes():
    now = {"t": 0.0}
    monkey = ChaosMonkey(ChaosPlan([]), clock=lambda: now["t"],
                         sleep=lambda dt: now.__setitem__(
                             "t", now["t"] + dt))
    job = FakeJob(1)
    monkey._job = job
    monkey._apply(Injection(at=0.0, kind="stall", rank=0, duration=2.0))
    assert job.procs[0].signals == [signal.SIGSTOP, signal.SIGCONT]
    assert now["t"] >= 2.0


def test_monkey_retargets_on_reattach():
    """Elastic epochs replace the job; attach() must point the REMAINING
    injections at the new epoch's workers."""
    plan = ChaosPlan([Injection(at=10_000.0, kind="sigterm", rank=0)])
    monkey = ChaosMonkey(plan)  # real clock: the injection never fires
    job1, job2 = FakeJob(2), FakeJob(2, pid0=200)
    try:
        monkey.attach(job1)
        monkey.attach(job2)
        monkey._apply(Injection(at=0.0, kind="sigterm", rank=0))
        assert job2.procs[0].signals == [signal.SIGTERM]
        assert all(p.signals == [] for p in job1.procs)
    finally:
        monkey.stop()


def test_monkey_stop_aborts_pending_injections():
    plan = ChaosPlan([Injection(at=10_000.0, kind="sigkill", rank=0)])
    monkey = ChaosMonkey(plan)
    job = FakeJob(1)
    monkey.attach(job)
    monkey.stop()
    assert monkey.done()
    assert job.procs[0].signals == []


# ---------------------------------------------------------------------------
# host-granularity targeting: one draw fells EVERY rank of one host
# ---------------------------------------------------------------------------

class FakeSlot:
    def __init__(self, hostname):
        self.hostname = hostname


class FakeHostJob(FakeJob):
    """A job whose rank->host map says ranks share machines, the same
    shape run/launcher.py publishes via ``Job.slots``."""

    def __init__(self, hostnames, pid0=100):
        super().__init__(len(hostnames), pid0=pid0)
        self.slots = [FakeSlot(h) for h in hostnames]


def _host_monkey(job):
    monkey = ChaosMonkey(ChaosPlan([]), clock=lambda: 0.0,
                         sleep=lambda dt: None)
    monkey._job = job  # targeting unit test: no scheduler thread
    return monkey


def test_monkey_host_sigterm_fells_whole_host_and_only_that_host():
    """The draw picks a HOST, not a rank: every rank co-resident on it
    is signalled, ranks on other hosts are untouched."""
    job = FakeHostJob(["node-a", "node-a", "node-b", "node-b"])
    monkey = _host_monkey(job)
    monkey._apply(Injection(at=0.0, kind="host_sigterm", rank=0))
    # sorted hosts [node-a, node-b], draw 0 -> node-a == ranks 0 and 1
    assert job.procs[0].signals == [signal.SIGTERM]
    assert job.procs[1].signals == [signal.SIGTERM]
    assert job.procs[2].signals == []
    assert job.procs[3].signals == []
    # one injection, one done-entry PER felled rank
    done = [(rank, pid) for _inj, rank, pid in monkey.injections_done]
    assert done == [(0, 100), (1, 101)]


def test_monkey_host_sigkill_uses_kill():
    job = FakeHostJob(["node-a", "node-a", "node-b", "node-b"])
    monkey = _host_monkey(job)
    monkey._apply(Injection(at=0.0, kind="host_sigkill", rank=1))
    # draw 1 over sorted [node-a, node-b] -> node-b
    assert job.procs[2].signals == [signal.SIGKILL]
    assert job.procs[3].signals == [signal.SIGKILL]
    assert job.procs[2].rc == -9 and job.procs[3].rc == -9
    assert job.procs[0].signals == [] and job.procs[1].signals == []


def test_monkey_host_kind_skips_already_dead_ranks():
    job = FakeHostJob(["node-a", "node-a"])
    job.procs[0].rc = -9  # already a corpse
    monkey = _host_monkey(job)
    monkey._apply(Injection(at=0.0, kind="host_sigterm", rank=0))
    assert job.procs[0].signals == []
    assert job.procs[1].signals == [signal.SIGTERM]
    assert [rank for _i, rank, _p in monkey.injections_done] == [1]


def test_monkey_host_kind_without_slots_is_one_local_host():
    """No slot map (plain local launch): the whole job counts as one
    host, so a host kind fells every live rank."""
    job = FakeJob(3)
    monkey = _host_monkey(job)
    monkey._apply(Injection(at=0.0, kind="host_sigterm", rank=0))
    assert all(p.signals == [signal.SIGTERM] for p in job.procs)
    assert [rank for _i, rank, _p in monkey.injections_done] == [0, 1, 2]


def test_monkey_host_injection_counts_once_toward_done():
    """A single host injection appends one done-entry per felled rank;
    done() must still see ONE plan item consumed, not wait forever nor
    claim completion early."""
    now = {"t": 0.0}
    plan = ChaosPlan([
        Injection(at=10.0, kind="host_sigterm", rank=0),
        Injection(at=20.0, kind="host_sigkill", rank=1)])
    job = FakeHostJob(["node-a", "node-a", "node-b", "node-b"])
    monkey = ChaosMonkey(plan, clock=lambda: now["t"],
                         sleep=lambda dt: now.__setitem__(
                             "t", now["t"] + dt))
    monkey.attach(job)
    assert _wait_until(monkey.done)
    monkey.stop()
    kinds = [(inj.kind, rank)
             for inj, rank, _pid in monkey.injections_done]
    assert kinds == [("host_sigterm", 0), ("host_sigterm", 1),
                     ("host_sigkill", 2), ("host_sigkill", 3)]


def test_blacklist_host_drain_is_not_a_crash():
    """The elastic contract behind host chaos: a host whose eviction
    was ANNOUNCED departs via record_drain — observable, zero penalty —
    while an unannounced death backs the host off and eventually
    blacklists it. Chaos host kills must read as the former when the
    preempt announcement lands first (driver.py keys both on the
    hostname, not the rank)."""
    from horovod_tpu.elastic.driver import Blacklist

    now = {"t": 0.0}
    bl = Blacklist(threshold=3, base_delay=5.0,
                   clock=lambda: now["t"])
    # drained host: any number of planned departures, never excluded
    for _ in range(5):
        bl.record_drain("node-a")
    assert bl.drains("node-a") == 5
    assert bl.count("node-a") == 0
    assert not bl.excluded("node-a")
    # crashed host: first failure opens a backoff window...
    bl.record_failure("node-b")
    assert bl.excluded("node-b") and not bl.blacklisted("node-b")
    # ...and reaching the threshold excludes it permanently
    bl.record_failure("node-b")
    bl.record_failure("node-b")
    assert bl.blacklisted("node-b")
    now["t"] = 10_000.0
    assert bl.excluded("node-b")      # permanent: no cooldown escape
    assert not bl.excluded("node-a")  # drained host still schedulable


# ---------------------------------------------------------------------------
# the np=3 soak: hvdrun --chaos against a live elastic CPU-mesh job
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_np3_sigterm_resumes_bit_identical(tmp_path,
                                                      monkeypatch):
    """ISSUE 15 acceptance: a seeded --chaos plan SIGTERMs a rank of a
    live 3-rank elastic job mid-training. The evicted worker grace-
    commits and announces its drain, the driver re-rendezvouses, and the
    job completes with every step's loss equal to the uninterrupted
    oracle — bit-identical resumability. The final flight-recorder dumps
    must carry no hang verdict."""
    from horovod_tpu.diag import doctor
    from horovod_tpu.run.run import main

    ckpt_dir = tmp_path / "ckpt"
    log = tmp_path / "losses.jsonl"
    dump_dir = tmp_path / "flightrec"
    dump_dir.mkdir()
    num_steps = 600

    from horovod_tpu.run import launcher

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("PYTHONPATH", launcher.repo_pythonpath())
    monkeypatch.setenv("HOROVOD_GRACE_SECONDS", "5")
    monkeypatch.setenv("HOROVOD_FLIGHTREC_DIR", str(dump_dir))
    monkeypatch.setenv("HVD_CHAOS_TEST_SLEEP", "0.05")
    # one SIGTERM at t+18s: past worker cold-start (~6s warm, >10s on a
    # loaded box) yet well inside the ~30s training window (jitter=0
    # pins the time; seed pins the target)
    rc = main(["-np", "3", "--min-np", "3",
               "--chaos", "seed=5,interval=18,jitter=0,kinds=sigterm,count=1",
               "--", sys.executable, WORKER, str(ckpt_dir), str(log),
               str(num_steps)])
    assert rc == 0

    with open(log) as f:
        records = [json.loads(line) for line in f if line.strip()]
    done = [r for r in records if "done" in r]
    steps = [r for r in records if "step" in r]
    assert done and done[-1]["done"] == num_steps

    # the chaos SIGTERM forced at least one re-rendezvous mid-run
    assert {r["epoch"] for r in steps} >= {1, 2}

    # bit-identical resumability: the loss at step s must equal the
    # uninterrupted oracle for every record — including a step replayed
    # because its commit had not reached a complete manifest when the
    # eviction struck (restore legitimately falls back to the last
    # complete step; what it must never do is diverge)
    oracle = {}
    w = 0.0
    for s in range(1, num_steps + 1):
        oracle[s] = (w - TARGET) ** 2
        w = w - LR * 2 * (w - TARGET)
    for r in steps:
        assert r["loss"] == pytest.approx(oracle[r["step"]], abs=1e-12), \
            f"step {r['step']} diverged from the oracle"
    assert {r["step"] for r in steps} == set(range(1, num_steps + 1))

    # zero hang reports: the final dumps describe a healthy (or evicted)
    # job, never a collective hang / dead rank
    dumps, _skipped = doctor.load_dumps(str(dump_dir))
    if dumps:
        report = doctor.diagnose(dumps)
        assert report["classification"] in ("healthy", "graceful eviction"), \
            doctor.format_report(report)
