"""Orphan-reaping middleman (reference safe_shell_exec.py): launcher
death — even SIGKILL — must terminate the whole training process tree,
including grandchildren that re-setsid'd."""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu.run import launcher
from horovod_tpu.run.safe_exec import descendants

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


def _wait_dead(pid, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not _alive(pid):
            return True
        time.sleep(0.2)
    return False


def test_exit_code_propagates():
    # stdin must stay open: EOF on it IS the launcher-death signal
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.run.safe_exec",
         "--watch-stdin", "--", sys.executable, "-c", "raise SystemExit(7)"],
        env=_env(), stdin=subprocess.PIPE)
    assert proc.wait(timeout=60) == 7


def test_descendants_walks_proc():
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import subprocess,sys,time;"
         "p=subprocess.Popen([sys.executable,'-c','import time;time.sleep(60)']);"
         "time.sleep(60)"])
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if descendants(proc.pid):
                break
            time.sleep(0.1)
        kids = descendants(proc.pid)
        assert len(kids) >= 1
    finally:
        for p in descendants(proc.pid):
            os.kill(p, signal.SIGKILL)
        proc.kill()
        proc.wait()


def _spawn_guarded_tree(tmp_path, kill_parent_how):
    """Start parent -> middleman -> worker -> grandchild(setsid); return
    (parent Popen, grandchild pid)."""
    pidfile = str(tmp_path / "gc.pid")
    worker = textwrap.dedent(f"""
        import os, subprocess, sys, time
        gc = subprocess.Popen([sys.executable, '-c',
                               'import time; time.sleep(300)'],
                              start_new_session=True)  # escapes the group
        open({pidfile!r}, 'w').write(str(gc.pid))
        time.sleep(300)
    """)
    parent = textwrap.dedent(f"""
        import os, subprocess, sys, time
        r, w = os.pipe()
        mid = subprocess.Popen(
            [sys.executable, '-m', 'horovod_tpu.run.safe_exec', str(r),
             '--', sys.executable, '-c', {worker!r}],
            pass_fds=(r,))
        os.close(r)
        time.sleep(300)
    """)
    proc = subprocess.Popen([sys.executable, "-c", parent], env=_env())
    deadline = time.time() + 20
    while time.time() < deadline and not os.path.exists(pidfile):
        time.sleep(0.1)
    assert os.path.exists(pidfile), "worker never started"
    time.sleep(0.2)
    gc_pid = int(open(pidfile).read())
    assert _alive(gc_pid)
    return proc, gc_pid


def test_sigkill_of_launcher_reaps_grandchildren(tmp_path):
    proc, gc_pid = _spawn_guarded_tree(tmp_path, "SIGKILL")
    proc.send_signal(signal.SIGKILL)  # launcher dies without cleanup
    proc.wait()
    assert _wait_dead(gc_pid), "grandchild survived launcher SIGKILL"


def test_sigterm_to_middleman_reaps(tmp_path):
    pidfile = str(tmp_path / "gc.pid")
    worker = textwrap.dedent(f"""
        import os, subprocess, sys, time
        gc = subprocess.Popen([sys.executable, '-c',
                               'import time; time.sleep(300)'])
        open({pidfile!r}, 'w').write(str(gc.pid))
        time.sleep(300)
    """)
    mid = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.run.safe_exec",
         "--watch-stdin", "--", sys.executable, "-c", worker],
        env=_env(), stdin=subprocess.PIPE)
    deadline = time.time() + 20
    while time.time() < deadline and not os.path.exists(pidfile):
        time.sleep(0.1)
    gc_pid = int(open(pidfile).read())
    mid.send_signal(signal.SIGTERM)
    assert _wait_dead(gc_pid), "grandchild survived middleman SIGTERM"
    mid.wait()


def test_launcher_spawn_middleman_roundtrip():
    """spawn(middleman=True) still propagates exit codes and env."""
    proc = launcher.spawn(
        "localhost",
        [sys.executable, "-c",
         "import os,sys; sys.exit(int(os.environ['WANT_RC']))"],
        {"WANT_RC": "5", "PYTHONPATH": launcher.repo_pythonpath()},
        middleman=True)
    assert proc.wait(timeout=60) == 5


def test_reparented_escapee_reaped(tmp_path):
    """A grandchild whose parent exited (reparented to init) is invisible
    to a /proc ppid walk; the middleman's tracker must still reap it."""
    pidfile = str(tmp_path / "esc.pid")
    # worker spawns an intermediate that setsid-spawns the escapee and
    # then exits, severing the ppid chain
    intermediate = textwrap.dedent(f"""
        import subprocess, sys, time
        gc = subprocess.Popen([sys.executable, '-c',
                               'import time; time.sleep(300)'],
                              start_new_session=True)
        open({pidfile!r}, 'w').write(str(gc.pid))
        time.sleep(3)  # stay alive long enough for the 1s tracker poll
    """)
    worker = textwrap.dedent(f"""
        import subprocess, sys, time
        subprocess.run([sys.executable, '-c', {intermediate!r}])
        time.sleep(300)
    """)
    parent = textwrap.dedent(f"""
        import os, subprocess, sys, time
        r, w = os.pipe()
        subprocess.Popen(
            [sys.executable, '-m', 'horovod_tpu.run.safe_exec', str(r),
             '--', sys.executable, '-c', {worker!r}],
            pass_fds=(r,))
        os.close(r)
        time.sleep(300)
    """)
    proc = subprocess.Popen([sys.executable, "-c", parent], env=_env())
    deadline = time.time() + 20
    while time.time() < deadline and not os.path.exists(pidfile):
        time.sleep(0.1)
    assert os.path.exists(pidfile), "escapee never started"
    gc_pid = int(open(pidfile).read())
    time.sleep(5)  # intermediate exits; tracker has polled by now
    assert _alive(gc_pid)
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    assert _wait_dead(gc_pid), "reparented escapee survived launcher death"
