"""Cluster-backend integration (reference test/test_spark.py intent):
run a real allreduce job through the cluster callback protocol with a
fake (local-subprocess) cluster, and unit-check the rank grouping."""

import os

import numpy as np
import pytest

from horovod_tpu.run.cluster import LocalProcessBackend, run_on_cluster


@pytest.fixture(autouse=True)
def _isolate_environ():
    """cluster_task mutates os.environ (correct inside a real executor
    process); the stub SparkContext runs it in THIS process's threads, so
    snapshot/restore the environment or rank-specific HOROVOD_* leaks
    poison every later test that calls hvd.init()."""
    snapshot = os.environ.copy()
    yield
    os.environ.clear()
    os.environ.update(snapshot)


def _make_train(scale):
    # defined as a closure so cloudpickle ships it by VALUE — the
    # executor subprocess cannot import this test module
    def _train():
        import numpy as np

        import horovod_tpu as hvd
        hvd.init()
        x = np.ones(4, dtype=np.float32) * (hvd.rank() + 1) * scale
        out = hvd.allreduce(x, op=hvd.Average)
        return (float(np.asarray(out)[0]), hvd.rank(), hvd.size(),
                hvd.local_rank(), hvd.cross_rank())
    return _train


def test_cluster_run_end_to_end():
    results = run_on_cluster(_make_train(2.0), num_proc=2,
                             backend=LocalProcessBackend(
                                 env={"JAX_PLATFORMS": "cpu"}),
                             start_timeout=120)
    vals, ranks, sizes = zip(*[(v, r, s) for v, r, s, _, _ in results])
    np.testing.assert_allclose(vals, [3.0, 3.0])  # mean of 2,4
    assert list(ranks) == [0, 1]
    assert set(sizes) == {2}


def test_cluster_rank_grouping_by_host_hash():
    """Indices 0,2 fake host A; 1 fakes host B → ranks must be contiguous
    per host with index 0 as rank 0 (reference barrel shift +
    host-hash grouping, spark/__init__.py:190-203)."""
    salts = {0: "hostA", 1: "hostB", 2: "hostA"}
    results = run_on_cluster(_make_train(1.0), num_proc=3,
                             backend=LocalProcessBackend(
                                 host_salts=salts,
                                 env={"JAX_PLATFORMS": "cpu"}),
                             start_timeout=120)
    # rank order: hostA gets ranks 0,1 (indices 0,2), hostB rank 2
    by_rank = {r: (lr, cr) for _, r, _, lr, cr in results}
    assert by_rank[0] == (0, 0)
    assert by_rank[1] == (1, 0)   # same host as rank 0 → local_rank 1
    assert by_rank[2] == (0, 1)   # other host → cross_rank 1
    vals = [v for v, *_ in results]
    np.testing.assert_allclose(vals, [2.0] * 3)  # mean of 1,2,3


def test_cluster_failure_propagates():
    def bad():
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() == 1:
            raise ValueError("executor boom")
        return hvd.rank()

    with pytest.raises(RuntimeError, match="executor boom"):
        run_on_cluster(bad, num_proc=2,
                       backend=LocalProcessBackend(
                           env={"JAX_PLATFORMS": "cpu"}),
                       start_timeout=120)


# ---- SparkBackend against a stub SparkContext --------------------------

class _FakeRDD:
    """The three-call sliver of pyspark RDD that SparkBackend touches."""

    def __init__(self, sc, n):
        self._sc = sc
        self._n = n
        self._mapper = None

    def mapPartitionsWithIndex(self, f):
        self._mapper = f
        return self

    def collect(self):
        import threading
        if self._sc.fail_with is not None:
            raise self._sc.fail_with
        results = [None] * self._n
        errors = []

        def part(i):
            try:
                results[i] = list(self._mapper(i, iter(())))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors.append(e)

        threads = [threading.Thread(target=part, args=(i,))
                   for i in range(self._n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return [x for r in results for x in (r or [])]


class _FakeSparkContext:
    """range/mapPartitionsWithIndex/collect/cancelAllJobs, partitions in
    threads — what the reference's test_spark.py fakes with a local
    SparkSession."""

    def __init__(self, fail_with=None):
        self.fail_with = fail_with
        self.cancelled = 0

    def range(self, start, end, numSlices=None):
        return _FakeRDD(self, end - start)

    def cancelAllJobs(self):
        self.cancelled += 1


def test_spark_backend_end_to_end_with_stub_context():
    from horovod_tpu.run.cluster import SparkBackend

    def fn():
        return "partition-ok"

    sc = _FakeSparkContext()
    results = run_on_cluster(fn, num_proc=2, backend=SparkBackend(sc),
                             kv_host="127.0.0.1", kv_addr="127.0.0.1",
                             start_timeout=120)
    assert results == ["partition-ok", "partition-ok"]


def test_spark_backend_propagates_job_failure():
    """A failed Spark job surfaces through alive()/wait(): the driver's
    liveness hook aborts the run instead of hanging on registrations."""
    from horovod_tpu.run.cluster import SparkBackend

    sc = _FakeSparkContext(fail_with=RuntimeError("stage lost"))
    backend = SparkBackend(sc)
    with pytest.raises(RuntimeError):
        run_on_cluster(lambda: 0, num_proc=2, backend=backend,
                       kv_host="127.0.0.1", kv_addr="127.0.0.1",
                       start_timeout=30)
    assert not backend.alive()
    with pytest.raises(RuntimeError, match="stage lost"):
        backend.wait()


def test_spark_backend_cancel_cancels_all_jobs():
    from horovod_tpu.run.cluster import SparkBackend

    sc = _FakeSparkContext()
    backend = SparkBackend(sc)
    backend.cancel()
    assert sc.cancelled == 1


def test_spark_backend_requires_active_context():
    from horovod_tpu.run.cluster import SparkBackend

    with pytest.raises((RuntimeError, ImportError)):
        SparkBackend(None)
