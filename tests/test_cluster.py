"""Cluster-backend integration (reference test/test_spark.py intent):
run a real allreduce job through the cluster callback protocol with a
fake (local-subprocess) cluster, and unit-check the rank grouping."""

import os
import sys

import numpy as np
import pytest

from horovod_tpu.run.cluster import LocalProcessBackend, run_on_cluster


@pytest.fixture(autouse=True)
def _isolate_environ():
    """cluster_task mutates os.environ (correct inside a real executor
    process); the stub SparkContext runs it in THIS process's threads, so
    snapshot/restore the environment or rank-specific HOROVOD_* leaks
    poison every later test that calls hvd.init()."""
    snapshot = os.environ.copy()
    yield
    os.environ.clear()
    os.environ.update(snapshot)


def _make_train(scale):
    # defined as a closure so cloudpickle ships it by VALUE — the
    # executor subprocess cannot import this test module
    def _train():
        import numpy as np

        import horovod_tpu as hvd
        hvd.init()
        x = np.ones(4, dtype=np.float32) * (hvd.rank() + 1) * scale
        out = hvd.allreduce(x, op=hvd.Average)
        return (float(np.asarray(out)[0]), hvd.rank(), hvd.size(),
                hvd.local_rank(), hvd.cross_rank())
    return _train


def test_cluster_run_end_to_end():
    results = run_on_cluster(_make_train(2.0), num_proc=2,
                             backend=LocalProcessBackend(
                                 env={"JAX_PLATFORMS": "cpu"}),
                             start_timeout=120)
    vals, ranks, sizes = zip(*[(v, r, s) for v, r, s, _, _ in results])
    np.testing.assert_allclose(vals, [3.0, 3.0])  # mean of 2,4
    assert list(ranks) == [0, 1]
    assert set(sizes) == {2}


def test_cluster_rank_grouping_by_host_hash():
    """Indices 0,2 fake host A; 1 fakes host B → ranks must be contiguous
    per host with index 0 as rank 0 (reference barrel shift +
    host-hash grouping, spark/__init__.py:190-203)."""
    salts = {0: "hostA", 1: "hostB", 2: "hostA"}
    results = run_on_cluster(_make_train(1.0), num_proc=3,
                             backend=LocalProcessBackend(
                                 host_salts=salts,
                                 env={"JAX_PLATFORMS": "cpu"}),
                             start_timeout=120)
    # rank order: hostA gets ranks 0,1 (indices 0,2), hostB rank 2
    by_rank = {r: (lr, cr) for _, r, _, lr, cr in results}
    assert by_rank[0] == (0, 0)
    assert by_rank[1] == (1, 0)   # same host as rank 0 → local_rank 1
    assert by_rank[2] == (0, 1)   # other host → cross_rank 1
    vals = [v for v, *_ in results]
    np.testing.assert_allclose(vals, [2.0] * 3)  # mean of 1,2,3


def test_exec_and_publish_publishes_and_reraises_control_flow():
    """hvd-lint HVD-EXCEPT regression: ``cluster_task`` used to catch
    ``BaseException``, publish the traceback, and RETURN NORMALLY — a
    KeyboardInterrupt / SystemExit inside ``fn`` became a clean task
    exit, the 'rank told to die keeps running' shape. The shared policy
    (run/task_exec.py) must publish the failure (the launcher stops
    waiting) and then re-raise control flow."""
    import pickle

    from horovod_tpu.run.task_exec import exec_and_publish

    published = []

    # ordinary success: payload published, True returned
    assert exec_and_publish(lambda: 41 + 1, (), {}, published.append)
    assert pickle.loads(published[-1]) == (True, 42)

    # ordinary failure: traceback published, False returned, no raise
    def boom():
        raise ValueError("executor boom")

    assert not exec_and_publish(boom, (), {}, published.append)
    ok, tb = pickle.loads(published[-1])
    assert not ok and "executor boom" in tb

    # control flow: STILL published, then re-raised
    def interrupted():
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        exec_and_publish(interrupted, (), {}, published.append)
    ok, tb = pickle.loads(published[-1])
    assert not ok and "KeyboardInterrupt" in tb

    with pytest.raises(SystemExit):
        exec_and_publish(lambda: sys.exit(3), (), {}, published.append)
    ok, tb = pickle.loads(published[-1])
    assert not ok and "SystemExit" in tb


def test_cluster_failure_propagates():
    def bad():
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() == 1:
            raise ValueError("executor boom")
        return hvd.rank()

    with pytest.raises(RuntimeError, match="executor boom"):
        run_on_cluster(bad, num_proc=2,
                       backend=LocalProcessBackend(
                           env={"JAX_PLATFORMS": "cpu"}),
                       start_timeout=120)


# ---- SparkBackend against a stub SparkContext --------------------------

class _FakeRDD:
    """The three-call sliver of pyspark RDD that SparkBackend touches."""

    def __init__(self, sc, n):
        self._sc = sc
        self._n = n
        self._mapper = None

    def mapPartitionsWithIndex(self, f):
        self._mapper = f
        return self

    def collect(self):
        import threading
        if self._sc.fail_with is not None:
            raise self._sc.fail_with
        results = [None] * self._n
        errors = []

        def part(i):
            try:
                results[i] = list(self._mapper(i, iter(())))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors.append(e)

        threads = [threading.Thread(target=part, args=(i,))
                   for i in range(self._n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return [x for r in results for x in (r or [])]


class _FakeSparkContext:
    """range/mapPartitionsWithIndex/collect/cancelAllJobs, partitions in
    threads — what the reference's test_spark.py fakes with a local
    SparkSession."""

    def __init__(self, fail_with=None):
        self.fail_with = fail_with
        self.cancelled = 0

    def range(self, start, end, numSlices=None):
        return _FakeRDD(self, end - start)

    def cancelAllJobs(self):
        self.cancelled += 1


def test_spark_backend_end_to_end_with_stub_context():
    from horovod_tpu.run.cluster import SparkBackend

    def fn():
        return "partition-ok"

    sc = _FakeSparkContext()
    results = run_on_cluster(fn, num_proc=2, backend=SparkBackend(sc),
                             kv_host="127.0.0.1", kv_addr="127.0.0.1",
                             start_timeout=120)
    assert results == ["partition-ok", "partition-ok"]


def test_spark_backend_control_flow_publishes_without_task_retry():
    """hvd-lint HVD-EXCEPT follow-up: a SystemExit inside the user fn
    under the Spark backend must surface to the LAUNCHER as the
    published failure payload — but must NOT escape the mapper as an
    exception, because a failed Spark task is automatically RETRIED
    (re-running the whole user fn against a completed rendezvous).
    Process death is the subprocess backends' semantic, not Spark's."""
    import sys as _sys

    from horovod_tpu.run.cluster import SparkBackend

    def fn():
        _sys.exit(3)

    sc = _FakeSparkContext()
    backend = SparkBackend(sc)
    with pytest.raises(RuntimeError, match="SystemExit"):
        run_on_cluster(fn, num_proc=2, backend=backend,
                       kv_host="127.0.0.1", kv_addr="127.0.0.1",
                       start_timeout=120)
    backend.wait()  # no exception escaped the mapper into the backend
    assert backend.alive()


def test_cluster_task_control_flow_scoping(monkeypatch):
    """The no-retry swallow applies ONLY to control flow that
    exec_and_publish has already published: pre-publish interrupts
    (during rendezvous setup — nothing on the KV yet) must propagate
    even with reraise_control_flow=False, or the launcher spins on a
    result key that will never appear."""
    import pickle

    from horovod_tpu.run import cluster

    puts = {}

    class _StubAgent:
        def __init__(self, *a, **k):
            pass

        def register(self):
            pass

        def run_ring_probe(self, timeout=None):
            pass

        def common_interfaces(self, timeout=None):
            pass

        def shutdown(self):
            pass

    def fake_kv_wait(addr, port, key, timeout=None, auth_key=None):
        if key.startswith("cluster/assign/"):
            return b'{"HOROVOD_RANK": "0"}'
        if key == "runfunc/func":
            return pickle.dumps((_boom, (), {}))
        raise AssertionError(key)

    monkeypatch.setattr(cluster, "TaskAgent", _StubAgent)
    monkeypatch.setattr(cluster, "kv_wait", fake_kv_wait)
    monkeypatch.setattr(
        cluster, "kv_put",
        lambda addr, port, key, payload, auth_key=None:
        puts.__setitem__(key, payload))
    monkeypatch.setattr(cluster._secret, "decode_key", lambda k: b"k")
    ctx = {"key": "00", "kv_addr": "127.0.0.1", "kv_port": 1}

    # post-publish control flow: swallowed only with the Spark policy
    with pytest.raises(SystemExit):
        cluster.cluster_task(0, 1, ctx)  # subprocess default: re-raise
    ok, tb = pickle.loads(puts.pop("runfunc/result/0"))
    assert not ok and "SystemExit" in tb

    assert cluster.cluster_task(0, 1, ctx,
                                reraise_control_flow=False) == 0
    ok, _ = pickle.loads(puts.pop("runfunc/result/0"))
    assert not ok  # payload published even though nothing raised

    # PRE-publish control flow: propagates regardless of the policy
    monkeypatch.setattr(
        _StubAgent, "register",
        lambda self: (_ for _ in ()).throw(KeyboardInterrupt()))
    with pytest.raises(KeyboardInterrupt):
        cluster.cluster_task(0, 1, ctx, reraise_control_flow=False)
    assert "runfunc/result/0" not in puts


def _boom():
    raise SystemExit(3)


def test_spark_backend_propagates_job_failure():
    """A failed Spark job surfaces through alive()/wait(): the driver's
    liveness hook aborts the run instead of hanging on registrations."""
    from horovod_tpu.run.cluster import SparkBackend

    sc = _FakeSparkContext(fail_with=RuntimeError("stage lost"))
    backend = SparkBackend(sc)
    with pytest.raises(RuntimeError):
        run_on_cluster(lambda: 0, num_proc=2, backend=backend,
                       kv_host="127.0.0.1", kv_addr="127.0.0.1",
                       start_timeout=30)
    assert not backend.alive()
    with pytest.raises(RuntimeError, match="stage lost"):
        backend.wait()


def test_spark_backend_cancel_cancels_all_jobs():
    from horovod_tpu.run.cluster import SparkBackend

    sc = _FakeSparkContext()
    backend = SparkBackend(sc)
    backend.cancel()
    assert sc.cancelled == 1


def test_spark_backend_requires_active_context():
    from horovod_tpu.run.cluster import SparkBackend

    with pytest.raises((RuntimeError, ImportError)):
        SparkBackend(None)
