"""Tier-1 multi-process e2e: a REAL 2-process ``jax.distributed`` job
on the CPU stand-in (gloo collectives, 2 forced local devices per
process) trains on ONE logical ``(dcn, data)`` mesh and must agree
with the single-process GSPMD oracle.

Three contracts, each the load-bearing half of a subsystem:

* **loss parity** — the 2x2 process mesh computes the same training
  trajectory as a 4-device single-process mesh: the global batch, the
  sharded gradients and the compiled collectives are world-layout
  invariants, not layout accidents.
* **checkpoint world elasticity, bitwise** — a ckpt written by 2
  processes restores in 1 process bitwise, and one written by 1
  process restores under 2; the process-contiguous row contract
  (cluster.assert_process_contiguous) is what makes the rank/world
  keying line up.
* **goodput across processes** — every rank drops its flight-recorder
  dump at shutdown and ``telemetry.report.aggregate`` joins them into
  one fleet view with the right world size.

The deterministic workload lives in tests/multiproc_worker.py; this
module imports it so oracle and workers run THE SAME functions.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

import multiproc_worker as mpw  # noqa: E402

_WORKER = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
_PROCS = 2
_LOCAL = 2
_TIMEOUT_S = 240.0


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _world_env(rank, out_dir, coord):
    env = dict(os.environ)
    env.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(_PROCS),
        "HOROVOD_LOCAL_RANK": str(rank),
        "HOROVOD_LOCAL_SIZE": str(_PROCS),
        "HOROVOD_CROSS_RANK": "0",
        "HOROVOD_CROSS_SIZE": "1",
        "HOROVOD_SPMD_PROCS": str(_PROCS),
        "HOROVOD_SPMD_LOCAL_DEVICES": str(_LOCAL),
        "HOROVOD_COORDINATOR_ADDR": coord,
        "HOROVOD_FLIGHTREC": "1",
        "HOROVOD_FLIGHTREC_DIR": out_dir,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": " ".join(
            [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
            + [f"--xla_force_host_platform_device_count={_LOCAL}"]),
    })
    return env


def _run_world(mode, out_dir, extra_args=()):
    """Launch the 2-process world and wait; raises with both rank logs
    on any failure."""
    os.makedirs(out_dir, exist_ok=True)
    coord = f"127.0.0.1:{_free_port()}"
    cmd = [sys.executable, _WORKER, "--mode", mode, "--out", out_dir]
    cmd += list(extra_args)
    procs, logs = [], []
    for rank in range(_PROCS):
        log_path = os.path.join(out_dir, f"rank.{rank}.log")
        log = open(log_path, "wb")
        logs.append((log_path, log))
        procs.append(subprocess.Popen(
            cmd, env=_world_env(rank, out_dir, coord),
            stdout=log, stderr=subprocess.STDOUT))
    try:
        rcs = [p.wait(timeout=_TIMEOUT_S) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for _path, log in logs:
            log.close()
    if any(rc != 0 for rc in rcs):
        tails = []
        for rank, (path, _log) in enumerate(logs):
            with open(path, "rb") as f:
                tails.append(f"--- rank {rank} (exit {rcs[rank]}) ---\n"
                             + f.read()[-2000:].decode("utf-8",
                                                       "replace"))
        raise RuntimeError(f"{mode} world failed:\n" + "\n".join(tails))


@pytest.fixture(scope="module")
def train_world(tmp_path_factory):
    """ONE 2-process training run shared by the parity and goodput
    tests (a real jax.distributed launch is the expensive part)."""
    out = str(tmp_path_factory.mktemp("mp_train"))
    _run_world("train", out, extra_args=("--steps", "3"))
    return out


def _oracle_losses(steps=3):
    """The single-process GSPMD trajectory on a 4-device mesh built
    from this test process's devices — same functions, same data, same
    seeds as the workers; only the process topology differs."""
    import jax

    from horovod_tpu.cluster import procmesh
    mesh = procmesh.build_process_mesh(
        jax.devices()[:_PROCS * _LOCAL])
    _state, losses = mpw.train_steps(mesh, steps)
    return losses


def test_two_process_loss_parity_with_single_process_oracle(
        hvd, train_world):
    with open(os.path.join(train_world, "losses.json")) as f:
        got = json.load(f)
    assert got["procs"] == _PROCS
    assert got["devices"] == _PROCS * _LOCAL
    assert got["mesh_axes"] == ["dcn", "data"]
    want = _oracle_losses()
    assert len(got["losses"]) == len(want) == 3
    # same data, same init, one logical mesh: the trajectories match to
    # reduction-order noise
    np.testing.assert_allclose(got["losses"], want, rtol=1e-4)
    # and the model actually trained
    assert got["losses"][-1] < got["losses"][0]


def test_goodput_dumps_aggregate_across_processes(train_world):
    from horovod_tpu.telemetry import report as report_mod
    dumps, skipped = report_mod.load_dumps(train_world)
    assert not skipped
    assert sorted(dumps) == [0, 1]
    agg = report_mod.aggregate(dumps)
    assert sorted(agg["ranks"]) == [0, 1]
    for rank_info in agg["ranks"].values():
        assert rank_info["build_info"].get("world") == str(_PROCS)
        assert rank_info["wall_seconds"] > 0
    assert agg["fleet"]["wall_seconds"] > 0
    assert agg["fleet"]["dominant_sink"]


def test_ckpt_saved_by_two_processes_restores_in_one_bitwise(
        hvd, tmp_path):
    out = str(tmp_path / "mp_save")
    _run_world("save", out, extra_args=("--steps", "2"))
    reference = dict(np.load(os.path.join(out, "reference.npz")))

    import jax

    from horovod_tpu.cluster import procmesh
    from horovod_tpu.ckpt import sharded
    mesh = procmesh.build_process_mesh(jax.devices()[:_PROCS * _LOCAL])
    state, _step = mpw.build_state_and_step(mesh)
    step_no, tree, _meta = sharded.restore_sharded(
        os.path.join(out, "ckpt"), mpw.host_state(state))
    assert step_no == 2
    restored = mpw.flat_arrays(tree)
    assert sorted(restored) == sorted(reference)
    for key in reference:
        np.testing.assert_array_equal(
            restored[key], reference[key],
            err_msg=f"leaf {key} not bitwise-identical after 2->1 "
                    "restore")


def test_ckpt_saved_by_one_process_restores_under_two_bitwise(
        hvd, tmp_path):
    out = str(tmp_path / "mp_restore")
    os.makedirs(out, exist_ok=True)

    import jax

    from horovod_tpu.cluster import procmesh
    from horovod_tpu.ckpt import sharded
    mesh = procmesh.build_process_mesh(jax.devices()[:_PROCS * _LOCAL])
    state, losses = mpw.train_steps(mesh, 1)
    host = mpw.host_state(state)
    sharded.save_sharded(os.path.join(out, "ckpt"), 1, host,
                         rank=0, world=1)
    _run_world("restore", out, extra_args=("--ckpt-step", "1"))
    with open(os.path.join(out, "restored_step.json")) as f:
        assert json.load(f)["step"] == 1
    restored = dict(np.load(os.path.join(out, "restored.npz")))
    reference = mpw.flat_arrays(host)
    assert sorted(restored) == sorted(reference)
    for key in reference:
        np.testing.assert_array_equal(
            restored[key], reference[key],
            err_msg=f"leaf {key} not bitwise-identical after 1->2 "
                    "restore")
