"""Launcher tests (reference: test/test_run.py — parsing/allocation/env
construction as unit tests, plus a real interactive-run end-to-end like
test/test_interactiverun.py)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from horovod_tpu.run import allocation, api, config_parser, launcher
from horovod_tpu.run.run import parse_args

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


# ---- allocation (reference gloo_run.py:53-111) -------------------------

def test_parse_hosts():
    hosts = allocation.parse_hosts("h1:4,h2:2,h3")
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("h1", 4), ("h2", 2), ("h3", 1)]


def test_parse_hostfile(tmp_path):
    p = tmp_path / "hosts"
    p.write_text("h1 slots=4\n# comment\nh2 slots=2\nh3\n")
    hosts = allocation.parse_hostfile(str(p))
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("h1", 4), ("h2", 2), ("h3", 1)]


def test_allocate_two_hosts():
    slots = allocation.allocate(allocation.parse_hosts("h1:2,h2:2"), 4)
    assert [(s.rank, s.hostname, s.local_rank, s.local_size,
             s.cross_rank, s.cross_size) for s in slots] == [
        (0, "h1", 0, 2, 0, 2), (1, "h1", 1, 2, 0, 2),
        (2, "h2", 0, 2, 1, 2), (3, "h2", 1, 2, 1, 2)]


def test_allocate_uneven():
    slots = allocation.allocate(allocation.parse_hosts("h1:3,h2:1"), 4)
    by_rank = {s.rank: s for s in slots}
    assert by_rank[2].hostname == "h1" and by_rank[2].local_rank == 2
    # local_rank 2 exists only on h1 -> cross_size 1
    assert by_rank[2].cross_size == 1
    # local_rank 0 exists on both hosts
    assert by_rank[0].cross_size == 2 and by_rank[3].cross_rank == 1


def test_allocate_too_many():
    with pytest.raises(ValueError, match="only 2 slots"):
        allocation.allocate(allocation.parse_hosts("h1:2"), 3)


# ---- SIGTERM fan-out escalation (ISSUE 15) -----------------------------

class _FakeProc:
    def __init__(self):
        self.rc = None
        self.killed = False

    def poll(self):
        return self.rc

    def kill(self):
        self.killed = True
        self.rc = -9


def test_escalate_after_grace_kills_only_survivors():
    """Fake clock: one proc drains inside the grace window, one ignores
    the SIGTERM — only the survivor is SIGKILLed, and its rank is
    reported."""
    now = {"t": 0.0}
    drains, stubborn = _FakeProc(), _FakeProc()

    def sleep(dt):
        now["t"] += dt
        if now["t"] >= 2.0 and drains.rc is None:
            drains.rc = 75  # a clean grace-commit exit mid-window

    job = launcher.Job()
    job.procs = [drains, stubborn]
    killed = job.escalate_after_grace(grace=10.0,
                                      clock=lambda: now["t"], sleep=sleep)
    assert killed == [1]
    assert stubborn.killed and not drains.killed
    assert now["t"] >= 10.0  # the full grace was honored first


def test_escalate_after_grace_noop_when_all_exit():
    now = {"t": 0.0}
    a, b = _FakeProc(), _FakeProc()

    def sleep(dt):
        now["t"] += dt
        a.rc = b.rc = 0

    job = launcher.Job()
    job.procs = [a, b]
    killed = job.escalate_after_grace(grace=30.0,
                                      clock=lambda: now["t"], sleep=sleep)
    assert killed == []
    assert not a.killed and not b.killed
    assert now["t"] < 30.0  # returns as soon as everyone is gone


def test_launcher_grace_seconds_env():
    assert launcher.grace_seconds({}) == 30.0
    assert launcher.grace_seconds({"HOROVOD_GRACE_SECONDS": "7"}) == 7.0
    assert launcher.grace_seconds({"HOROVOD_GRACE_SECONDS": "bad"}) == 30.0


# ---- CLI / env mapping (reference test_run.py:68-233) ------------------

def test_args_to_env():
    args = parse_args(["-np", "2", "--fusion-threshold-mb", "32",
                       "--cycle-time-ms", "2.5", "--autotune",
                       "--timeline-filename", "/tmp/t.json",
                       "python", "train.py"])
    env = config_parser.args_to_env(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HOROVOD_CYCLE_TIME"] == "2.5"
    assert env["HOROVOD_AUTOTUNE"] == "1"
    assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
    assert args.command == ["python", "train.py"]


def test_command_separator_and_disable_cache():
    # `hvdrun -np 2 -- python train.py` (the reference accepts both forms)
    args = parse_args(["-np", "2", "--disable-cache", "--",
                       "python", "train.py"])
    assert args.command == ["python", "train.py"]
    assert config_parser.args_to_env(args)["HOROVOD_CACHE_CAPACITY"] == "0"


def test_check_build_prints_planes(capsys):
    from horovod_tpu.run.run import main
    assert main(["--check-build"]) == 0
    out = capsys.readouterr().out
    assert "Available frameworks" in out
    assert "[X] JAX" in out
    assert "TCP (native host core)" in out


def test_config_file(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(textwrap.dedent("""
        fusion-threshold-mb: 16
        autotune: true
        stall-warning-time-seconds: 30
    """))
    args = parse_args(["-np", "2", "--config-file", str(cfg),
                       "--fusion-threshold-mb", "8",  # CLI wins
                       "python", "x.py"])
    env = config_parser.args_to_env(args)
    assert env["HOROVOD_FUSION_THRESHOLD"] == str(8 * 1024 * 1024)
    assert env["HOROVOD_AUTOTUNE"] == "1"
    assert env["HOROVOD_STALL_CHECK_TIME_SECONDS"] == "30"


def test_slot_env_contract():
    slot = allocation.Slot(rank=3, hostname="h2", local_rank=1,
                           local_size=2, cross_rank=1, cross_size=2, size=4)
    env = launcher.slot_env(slot, "10.0.0.1", 9999,
                            rendezvous_addr="10.0.0.1",
                            rendezvous_port=8888)
    assert env["HOROVOD_RANK"] == "3"
    assert env["HOROVOD_LOCAL_RANK"] == "1"
    assert env["HOROVOD_CROSS_RANK"] == "1"
    assert env["HOROVOD_CONTROLLER_ADDR"] == "10.0.0.1"
    assert env["HOROVOD_GLOO_RENDEZVOUS_PORT"] == "8888"


def test_build_command_ssh():
    slot = allocation.Slot(rank=2, hostname="remotehost", local_rank=0,
                           local_size=2, cross_rank=1, cross_size=2, size=4)
    cmd, env, payload = launcher.build_command(
        slot.hostname, ["python", "train.py"], {"HOROVOD_RANK": "2"},
        ssh_port=2222)
    assert payload is None
    assert cmd[0] == "ssh"
    assert "-p" in cmd and "2222" in cmd
    assert cmd[-2] == "remotehost"
    assert "HOROVOD_RANK=2" in cmd[-1] and "python train.py" in cmd[-1]
    assert env == {}


def test_build_command_local():
    slot = allocation.Slot(rank=0, hostname="localhost", local_rank=0,
                           local_size=1, cross_rank=0, cross_size=1, size=1)
    cmd, env, payload = launcher.build_command(
        slot.hostname, ["python", "t.py"], {"HOROVOD_RANK": "0"})
    assert payload is None
    assert cmd == ["python", "t.py"]
    assert env["HOROVOD_RANK"] == "0"


# ---- end-to-end (reference test_interactiverun.py) ---------------------

def test_programmatic_run():
    def hvd_fn(scale):
        import numpy as np

        import horovod_tpu as hvd
        hvd.init()
        x = np.ones(4, dtype=np.float32) * (hvd.rank() + 1) * scale
        out = hvd.allreduce(x, op=hvd.Average)
        return float(np.asarray(out)[0]), hvd.rank(), hvd.size()

    results = api.run(hvd_fn, args=(2.0,), np=3,
                      extra_env={"JAX_PLATFORMS": "cpu"})
    vals = [v for v, _, _ in results]
    ranks = [r for _, r, _ in results]
    # mean of 2,4,6 = 4.0 on every rank
    np.testing.assert_allclose(vals, [4.0] * 3)
    assert ranks == [0, 1, 2]
    assert all(s == 3 for _, _, s in results)


def test_programmatic_run_failure():
    def bad(_):
        raise ValueError("boom on purpose")

    with pytest.raises(RuntimeError):
        api.run(bad, args=(1,), np=2,
                extra_env={"JAX_PLATFORMS": "cpu"})


def test_cli_end_to_end(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        x = np.ones(3, dtype=np.float32) * (hvd.rank() + 1)
        out = hvd.allreduce(x, op=hvd.Sum)
        assert np.allclose(np.asarray(out), 3.0), out  # 1+2
        g = hvd.allgather(np.array([hvd.rank()], dtype=np.int32))
        assert list(np.asarray(g)) == [0, 1], g
        print(f"rank {hvd.rank()} OK")
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rv = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert rv.returncode == 0, rv.stdout + rv.stderr


def test_preflight_cache_roundtrip_ttl_and_corruption(tmp_path):
    """Launcher pre-flight cache (reference run/util/cache.py): NIC
    discovery results persist for the TTL, expire after it, and a
    corrupt cache file can never fail a launch."""
    from horovod_tpu.run import cache as run_cache
    c = run_cache.Cache(folder=str(tmp_path), ttl=3600)
    assert c.get("nics:a,b") is None
    c.put("nics:a,b", ["eth0", "ib0"])
    assert c.get("nics:a,b") == ["eth0", "ib0"]
    # expired entries are misses
    expired = run_cache.Cache(folder=str(tmp_path), ttl=0)
    assert expired.get("nics:a,b") is None
    # corruption tolerance
    with open(str(tmp_path / "cache.json"), "w") as f:
        f.write("{not json")
    assert c.get("nics:a,b") is None
    c.put("nics:a,b", ["eth0"])  # rewrites over the corrupt file
    assert c.get("nics:a,b") == ["eth0"]


def test_worker_killed_mid_step_fans_out(tmp_path):
    """Failure injection (reference test_interactiverun.py:62 pattern):
    rank 1 dies by SIGKILL mid-job while rank 0 blocks in a collective
    that can now never complete. The launcher's monitor must fan the
    kill out to rank 0 and propagate a nonzero exit — WITHOUT waiting
    for rank 0's 120 s sleep."""
    script = tmp_path / "die.py"
    script.write_text(textwrap.dedent("""
        import os, signal, time
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        hvd.allreduce(np.ones(2, np.float32))  # both ranks healthy
        if hvd.rank() == 1:
            os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, no exit code
        hvd.allreduce(np.ones(2, np.float32))  # rank 0 blocks here
        time.sleep(120)
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rv = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=90)
    assert rv.returncode == 1
    # SIGKILL death surfaces as 128+9 through the safe_exec middleman
    assert "exited with code 137" in rv.stderr
    assert "remaining processes were terminated" in rv.stderr


def test_stalled_rank_named_before_death(tmp_path):
    """A rank that stops participating (but stays alive) must be NAMED
    by the stall inspector on the coordinator's stderr (reference
    stall_inspector.cc: 'missing ranks' warning) before the job dies;
    the laggard's eventual failure still fans out and propagates."""
    script = tmp_path / "stall.py"
    script.write_text(textwrap.dedent("""
        import sys, time
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        hvd.allreduce(np.ones(2, np.float32))
        if hvd.rank() == 1:
            time.sleep(8)   # stops participating; stall warn fires at ~1s
            sys.exit(5)
        hvd.allreduce(np.ones(2, np.float32))  # rank 0 waits on rank 1
        time.sleep(120)
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "1"
    rv = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=90)
    assert rv.returncode == 1
    assert "missing ranks: 1" in (rv.stderr + rv.stdout)
    # either failure may win the monitor race: rank 1's exit(5), or
    # rank 0's RuntimeError (exit 1) when rank 1's shutdown breaks the
    # pending collective — both propagate and terminate the job
    assert ("exited with code 5" in rv.stderr
            or "exited with code 1" in rv.stderr)
    assert "remaining processes were terminated" in rv.stderr


def test_sigkilled_rank_diagnosed_by_doctor(tmp_path):
    """The flight-recorder acceptance path (ISSUE 4 e2e): under
    ``hvdrun -np 3`` on CPU, SIGKILLing rank 1 mid-step leaves
    flight-recorder dumps from the survivors; hvdrun auto-runs the
    doctor, whose report names rank 1 as dead, identifies the last
    common collective_seq and the collective the survivors are parked
    in, and classifies the cause as 'dead rank'. A standalone doctor run
    over the logdir reproduces the same verdict."""
    from horovod_tpu.diag import doctor

    script = tmp_path / "die.py"
    script.write_text(textwrap.dedent("""
        import os, signal, time
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        for step in range(50):
            hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum)
            if hvd.rank() == 1 and step == 3:
                os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, no dump
        time.sleep(120)
    """))
    out_dir = tmp_path / "out"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rv = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "3",
         "--output-dir", str(out_dir), sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert rv.returncode == 1
    assert "exited with code 137" in rv.stderr
    # the auto-doctor report on hvdrun's stderr names the whole story
    assert "doctor report" in rv.stderr
    assert "DEAD (no flight-recorder dump): rank(s) 1" in rv.stderr
    assert "last common collective_seq: 4" in rv.stderr
    # each survivor either PARKED in the seq-5 allreduce (still waiting)
    # or saw it FAIL under it when the dead rank's socket dropped —
    # either way the report names the collective the dead rank missed
    assert ("PARKED in allreduce (seq 5)" in rv.stderr
            or "FAILED in allreduce (seq 5)" in rv.stderr)
    assert "probable cause: dead rank" in rv.stderr
    # survivors (not the SIGKILLed rank) left dumps next to the rank logs
    dumps, _skipped = doctor.load_dumps(str(out_dir))
    assert 1 not in dumps and len(dumps) == 2
    # the standalone doctor over the logdir reaches the same verdict
    report = doctor.diagnose(dumps, expected_size=3)
    assert report["classification"] == "dead rank"
    assert report["dead_ranks"] == [1]
    assert report["last_common_seq"] == 4
    stuck = [i["parked"] or i["failed"]
             for i in report["per_rank"].values()]
    assert any(x == (5, "allreduce") for x in stuck)


def test_sigkill_mid_save_resumes_from_last_manifest(tmp_path):
    """The ISSUE 5 checkpoint e2e: a 2-rank run commits through the
    async sharded subsystem; rank 1 SIGKILLs itself right after
    initiating commit 3 — its 16 MB shard write is still in flight, so
    step 3 can never reach a manifest. The auto-doctor must name the
    interrupted save; a relaunch must resume from the last COMMITTED
    manifest (step 2), re-save the torn step, and finish with state
    identical to an uninterrupted run."""
    from horovod_tpu import ckpt as ckpt_lib
    from horovod_tpu.ckpt import manifest as manifest_lib

    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""
        import os, signal
        import numpy as np
        import horovod_tpu as hvd
        hvd.init()
        rank = hvd.rank()
        ckpt_dir = os.environ["CKPT_DIR"]
        kill_at = int(os.environ.get("KILL_AT", "0"))
        state = hvd.elastic.JaxState(
            directory=ckpt_dir, keep=10,
            w=np.zeros(1 << 22, np.float32))  # 16 MB: the write is slow
        state.restore()  # newest manifest-complete commit, or fresh
        start = state._commit_count
        print(f"START {rank} {start}", flush=True)
        for c in range(start + 1, 7):
            state.w = state.w + np.float32(
                np.asarray(hvd.allreduce(np.ones(4, np.float32)))[0])
            state.commit()
            if kill_at and rank == 1 and c == kill_at:
                # the commit is ASYNC: our shard for step c is still
                # being serialized in the background — a SIGKILL now is
                # a save torn mid-write, no cleanup, no dump
                os.kill(os.getpid(), signal.SIGKILL)
        state.flush()
        print(f"DONE {rank} {float(np.asarray(state.w)[0]):.1f}",
              flush=True)
    """))
    ckpt_dir = tmp_path / "ck"
    out_dir = tmp_path / "out"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["CKPT_DIR"] = str(ckpt_dir)
    env["KILL_AT"] = "3"
    rv = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--output-dir", str(out_dir), sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=150)
    assert rv.returncode == 1
    assert "exited with code 137" in rv.stderr
    # commits 1 and 2 are manifest-complete; 3 is a torn, invisible dir
    assert ckpt_lib.latest_complete_step(str(ckpt_dir)) == 2
    assert os.path.isdir(manifest_lib.step_dir(str(ckpt_dir), 3))
    assert not manifest_lib.is_complete(str(ckpt_dir), 3)
    # the auto-doctor names the save the crash interrupted (rank 0's
    # dump holds a ckpt B for step 3 whose commit never happened)
    assert "doctor report" in rv.stderr
    assert "INTERRUPTED CHECKPOINT SAVE" in rv.stderr
    assert "step(s) [3]" in rv.stderr

    # relaunch: resume from the last COMMITTED manifest and run out
    env["KILL_AT"] = "0"
    rv2 = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=150)
    assert rv2.returncode == 0, rv2.stderr[-2000:]
    for rank in (0, 1):
        assert f"START {rank} 2" in rv2.stdout  # resumed at commit 2
        assert f"DONE {rank} 6.0" in rv2.stdout  # identical final state
    # the torn step was re-saved and committed on the way through
    assert manifest_lib.is_complete(str(ckpt_dir), 3)
    assert ckpt_lib.latest_complete_step(str(ckpt_dir)) == 6


def test_hvdrun_doctor_flag(tmp_path):
    """hvdrun --doctor <logdir> == python -m horovod_tpu.diag.doctor."""
    from horovod_tpu.diag.recorder import FlightRecorder
    rec = FlightRecorder(capacity=8, rank=0, size=1,
                         dump_dir=str(tmp_path))
    rec.collective_enter("allreduce", shape=(4,), dtype="float32")
    rec.dump(reason="exit")
    from horovod_tpu.run.run import main
    assert main(["--doctor", str(tmp_path)]) == 0
    assert main(["--doctor", str(tmp_path / "nope")]) == 2


def test_cli_failure_kills_job(tmp_path):
    script = tmp_path / "crash.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        import horovod_tpu as hvd
        hvd.init()
        if hvd.rank() == 1:
            sys.exit(3)
        time.sleep(60)  # would hang forever without failure fan-out
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rv = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         sys.executable, str(script)],
        env=env, capture_output=True, text=True, timeout=90)
    assert rv.returncode == 1
    assert "exited with code 3" in rv.stderr
