"""Compiled-step X-ray: trace attribution, the doctor, and the trend
tool (telemetry/xprof.py, diag/xray.py, telemetry/trend.py).

Tier-1 drives the parser on checked-in synthetic trace fixtures
(tests/fixtures/xray/) so classification is exercised without a
profiler run; the one real CPU-backend capture round-trip is marked
slow (a cold ``jax.profiler`` start costs ~16 s).
"""

import gzip
import json
import os
import shutil

import pytest

from horovod_tpu.diag import xray as xray_doctor
from horovod_tpu.parallel.gspmd import (COLLECTIVE_OPS, collective_kind,
                                        collective_label)
from horovod_tpu.telemetry import trend, xprof

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "xray")


def _fixture_events(name):
    return xprof.load_trace_file(os.path.join(FIXTURES, name))


def _capture_dir(tmp_path, *fixtures):
    """Lay fixtures out in the profiler's on-disk shape
    (``<dir>/plugins/profile/<run>/*.trace.json``)."""
    run = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    run.mkdir(parents=True)
    for f in fixtures:
        shutil.copy(os.path.join(FIXTURES, f), run / f)
    return str(tmp_path)


# -- the shared classifier ---------------------------------------------------

def test_collective_kind_matches_every_priced_op():
    """The ONE classifier covers every op the byte parser prices,
    sync and async edges both."""
    for op in COLLECTIVE_OPS:
        assert collective_kind(f"{op}.1") == (op, None)
        assert collective_kind(op) == (op, None)
        assert collective_kind(f"{op}-start.2") == (op, "start")
        assert collective_kind(f"{op}-done.2") == (op, "done")
        # variadic fused instances keep plain numbering
        assert collective_kind(f"{op}.17") == (op, None)


def test_collective_kind_rejects_non_collectives():
    for name in ("all-reducer.1", "dot.3", "reduce.1", "reduce-window.2",
                 "collective-permute-start-done-ish", "copy.1", ""):
        kind, edge = collective_kind(name)
        assert kind is None or name.startswith(kind)
    assert collective_kind("all-reducer.1") == (None, None)
    assert collective_kind("reduce.4") == (None, None)


def test_classify_device_event_buckets():
    assert xprof.classify_device_event("all-reduce.3", True) == "all_reduce"
    assert xprof.classify_device_event("reduce-scatter-start.1",
                                       True) == "reduce_scatter"
    assert xprof.classify_device_event("dot.7", True) == "matmul_conv"
    assert xprof.classify_device_event("convolution.2",
                                       True) == "matmul_conv"
    assert xprof.classify_device_event("loop_fusion.9", True) == "fusion"
    assert xprof.classify_device_event("multiply_add_fusion",
                                       True) == "fusion"
    assert xprof.classify_device_event("copy.1", True) == "copy"
    assert xprof.classify_device_event("copy-start.2", True) == "copy"
    assert xprof.classify_device_event("D2D Dispatch", False) == "copy"
    assert xprof.classify_device_event("tanh.4", True) == "other_op"
    assert xprof.classify_device_event("ThunkExecutor::Execute",
                                       False) == "runtime"
    assert xprof.classify_device_event("ThreadpoolListener::StartRegion",
                                       False) == "runtime"
    # the honesty bucket: an unknown non-hlo event is NOT silently
    # binned — it degrades the gated fraction
    assert xprof.classify_device_event("SomeNewRuntimeThing",
                                       False) == "unattributed"


# -- fixture-driven attribution ----------------------------------------------

def test_overlapped_collective_hides_behind_compute():
    """Async all-reduce (-start @10µs … -done ends @120µs) fully inside
    the compute union (dot 0–100, fusion 100–140): zero exposed."""
    s = xprof.attribute(_fixture_events("overlapped.trace.json"))
    ar = s["collectives"]["all_reduce"]
    assert ar["events"] == 1
    assert ar["seconds"] == pytest.approx(110e-6, rel=1e-6)
    assert ar["overlapped_seconds"] == pytest.approx(110e-6, rel=1e-6)
    assert ar["exposed_seconds"] == 0.0
    assert s["verdict"] == "compute-bound"
    assert s["bucketed_fraction"] == pytest.approx(1.0)


def test_exposed_collective_with_no_compute_behind_it():
    """Sync all-gather + reduce-scatter after compute ended: fully
    exposed, and the verdict calls the step comms-bound."""
    s = xprof.attribute(_fixture_events("exposed.trace.json"))
    ag = s["collectives"]["all_gather"]
    rs = s["collectives"]["reduce_scatter"]
    assert ag["exposed_seconds"] == pytest.approx(30e-6, rel=1e-6)
    assert ag["overlapped_seconds"] == 0.0
    assert rs["exposed_seconds"] == pytest.approx(10e-6, rel=1e-6)
    assert s["verdict"] == "comms-bound"
    # the wrapper ThunkExecutor span self-times to ~0 under its hlo
    # children (innermost wins) — runtime must not double-count
    assert s["device_seconds"]["runtime"] == pytest.approx(0.0, abs=1e-9)
    assert s["device_seconds"]["matmul_conv"] == pytest.approx(
        40e-6, rel=1e-6)
    assert s["device_seconds"]["copy"] == pytest.approx(10e-6, rel=1e-6)
    assert s["device_seconds"]["idle"] == pytest.approx(10e-6, rel=1e-6)


def test_host_python_lane_is_not_a_device_lane():
    """The python thread annotates a few dispatch events with hlo_op
    args; its 200µs host span must not land in device attribution."""
    s = xprof.attribute(_fixture_events("overlapped.trace.json"))
    # only the two /device: lanes count
    assert s["device_lanes"] == 2
    total = sum(s["device_seconds"].values())
    assert total < 150e-6  # the 200µs PjitFunction span stayed out


def test_async_pair_torn_capture_degrades_to_start_span():
    """A -start with no -done (capture stopped mid-flight) charges its
    own event span instead of an unbounded window."""
    events = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 100, "dur": 5,
         "name": "all-reduce-start.1",
         "args": {"hlo_op": "all-reduce-start.1"}},
    ]
    s = xprof.attribute(events)
    assert s["collectives"]["all_reduce"]["seconds"] == pytest.approx(
        5e-6, rel=1e-6)


def test_unattributed_device_time_fails_the_gate():
    """A device lane dominated by an unknown event family pushes
    bucketed_fraction under the bench gate — loud, not silent."""
    events = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 10,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 10, "dur": 90,
         "name": "BrandNewBackendThing"},
    ]
    s = xprof.attribute(events)
    assert s["device_seconds"]["unattributed"] == pytest.approx(
        90e-6, rel=1e-6)
    assert s["bucketed_fraction"] < xprof.BUCKETED_GATE


def test_empty_and_torn_captures(tmp_path):
    s = xprof.attribute(_fixture_events("empty.trace.json"))
    assert s["verdict"] == "empty-capture"
    assert s["device_lanes"] == 0
    with pytest.raises(ValueError):
        _fixture_events("torn.trace.json")
    # a capture dir with ONLY a torn file raises; torn + good parses
    # the good file and reports the torn one
    d = _capture_dir(tmp_path, "torn.trace.json")
    with pytest.raises(ValueError):
        xprof.analyze_capture(d)
    d2 = _capture_dir(tmp_path / "b", "torn.trace.json",
                      "exposed.trace.json")
    s2 = xprof.analyze_capture(d2)
    assert s2["verdict"] == "comms-bound"
    assert len(s2["torn_files"]) == 1


def test_analyze_capture_picks_newest_run_and_reads_gz(tmp_path):
    old = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    new = tmp_path / "plugins" / "profile" / "2026_01_02_00_00_00"
    old.mkdir(parents=True)
    new.mkdir(parents=True)
    shutil.copy(os.path.join(FIXTURES, "overlapped.trace.json"),
                old / "host.trace.json")
    with open(os.path.join(FIXTURES, "exposed.trace.json"), "rb") as f:
        with gzip.open(new / "host.trace.json.gz", "wb") as g:
            g.write(f.read())
    s = xprof.analyze_capture(str(tmp_path))
    assert s["capture_dir"] == str(new)
    assert s["verdict"] == "comms-bound"  # the exposed fixture


def test_self_time_innermost_wins():
    """Nested events: parent is charged only its uncovered remainder."""
    events = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 100,
         "name": "fusion.1", "args": {"hlo_op": "fusion.1"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 20, "dur": 30,
         "name": "dot.2", "args": {"hlo_op": "dot.2"}},
    ]
    s = xprof.attribute(events)
    assert s["device_seconds"]["fusion"] == pytest.approx(70e-6, rel=1e-6)
    assert s["device_seconds"]["matmul_conv"] == pytest.approx(
        30e-6, rel=1e-6)


def test_verdict_rules():
    def summary(cats, colls):
        base = {c: 0.0 for c in xprof.CATEGORIES}
        base.update(cats)
        return {"device_lanes": 1, "device_seconds": base,
                "collectives": colls}

    assert xprof.verdict(summary({"matmul_conv": 1.0}, {})) == \
        "compute-bound"
    assert xprof.verdict(summary(
        {"matmul_conv": 1.0, "all_reduce": 0.5},
        {"all_reduce": {"seconds": 0.5, "exposed_seconds": 0.5,
                        "overlapped_seconds": 0.0}})) == "comms-bound"
    # modest collective share, but over half exposed: overlap-broken
    assert xprof.verdict(summary(
        {"matmul_conv": 1.0, "all_reduce": 0.15},
        {"all_reduce": {"seconds": 0.15, "exposed_seconds": 0.12,
                        "overlapped_seconds": 0.03}})) == "overlap-broken"
    assert xprof.verdict(summary(
        {"matmul_conv": 1.0, "copy": 0.3}, {})) == "copy-bound"
    assert xprof.verdict(summary(
        {"matmul_conv": 1.0, "idle": 0.8}, {})) == "idle-bound"
    assert xprof.verdict(summary({}, {})) == "empty-capture"


def test_bandwidth_join_accepts_both_label_forms():
    events = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 40,
         "name": "all-reduce.1", "args": {"hlo_op": "all-reduce.1"}},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 20,
         "name": "all-gather.1", "args": {"hlo_op": "all-gather.1"}},
    ]
    s = xprof.attribute(events, steps=2)
    xprof.join_collective_bytes(
        s, {"spmd_all_reduce": {"calls": 1, "bytes": 1_000_000},
            "all-gather": {"calls": 1, "bytes": 500_000}}, steps=2)
    ar = s["collectives"]["all_reduce"]
    ag = s["collectives"]["all_gather"]
    assert ar["bytes_per_step"] == 1_000_000
    # 1MB x 2 steps x 2 lanes / 40µs = 100 GB/s
    assert ar["effective_gbps"] == pytest.approx(100.0)
    assert ag["bytes_per_step"] == 500_000
    assert ag["effective_gbps"] == pytest.approx(100.0)


def test_xray_gauges_land_in_catalogue_registry():
    from horovod_tpu.telemetry import instruments as tele
    from horovod_tpu.telemetry.registry import MetricsRegistry
    r = MetricsRegistry()
    s = xprof.attribute(_fixture_events("exposed.trace.json"))
    xprof.join_collective_bytes(s, {"all-gather": {"bytes": 1000}},
                                steps=1)
    tele.record_xray(s, registry=r)
    text = r.render_prometheus()
    assert tele.XRAY_DEVICE_SECONDS in text
    assert tele.XRAY_BUCKETED_FRACTION in text
    assert tele.XRAY_EXPOSED_SECONDS in text
    assert tele.XRAY_COLLECTIVE_GBPS in text
    assert 'category="idle"' in text


# -- the doctor --------------------------------------------------------------

def test_doctor_xray_on_raw_capture(tmp_path, capsys):
    d = _capture_dir(tmp_path, "exposed.trace.json")
    rc = xray_doctor.main([d, "--json"])
    assert rc == 0
    out = capsys.readouterr()
    summary = json.loads(out.out)
    assert summary["verdict"] == "comms-bound"
    assert "VERDICT: comms-bound" in out.err
    assert "dominant sink" in out.err


def test_doctor_xray_prefers_written_summaries(tmp_path, capsys):
    s = xprof.attribute(_fixture_events("overlapped.trace.json"))
    path = xprof.write_summary(s, str(tmp_path), rank=3)
    assert path.endswith("xray.rank3.json")
    rc = xray_doctor.main([str(tmp_path), "--json"])
    assert rc == 0
    reread = json.loads(capsys.readouterr().out)
    assert reread["verdict"] == "compute-bound"
    assert reread["rank"] == 3


def test_doctor_xray_empty_dir_exits_2(tmp_path, capsys):
    assert xray_doctor.main([str(tmp_path)]) == 2


def test_doctor_cli_dispatch_table():
    from horovod_tpu.diag.doctor import SUBCOMMANDS
    assert set(SUBCOMMANDS) == {"hang", "perf", "serve", "xray"}


def test_doctor_cli_routes_xray(tmp_path, capsys):
    from horovod_tpu.diag.doctor import doctor_cli
    d = _capture_dir(tmp_path, "exposed.trace.json")
    assert doctor_cli(["xray", d, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["verdict"] == "comms-bound"


# -- ledger compiled-path annotation -----------------------------------------

def test_ledger_compiled_path_flag_reaches_report(tmp_path, capsys):
    from horovod_tpu.telemetry import report as report_mod
    from horovod_tpu.telemetry.ledger import TimeLedger
    led = TimeLedger(enabled=True)
    led.start()
    led.note_compiled_path()
    led.settle_step()
    assert led.snapshot()["compiled_path"] is True
    path = led.write_dump(str(tmp_path), rank=0)
    assert json.load(open(path))["compiled_path"] is True
    report = report_mod.run(str(tmp_path))
    assert report["fleet"]["compiled_path"] is True
    text = report_mod.format_report(report)
    assert "hvd-doctor xray" in text  # the silent-zero annotation
    # an eager-path run gets no annotation
    led2 = TimeLedger(enabled=True)
    led2.start()
    led2.settle_step()
    led2.write_dump(str(tmp_path / "eager"), rank=0)
    report2 = report_mod.run(str(tmp_path / "eager"))
    assert report2["fleet"]["compiled_path"] is False
    assert "hvd-doctor xray" not in report_mod.format_report(report2)


# -- the trend tool ----------------------------------------------------------

def test_trend_direction_inference():
    assert trend.direction("step_ms_gspmd") == -1
    assert trend.direction("lm_gspmd_over_explicit_step_time") == -1
    assert trend.direction("ttft_ms") == -1
    assert trend.direction("tokens_per_sec") == 1
    assert trend.direction("mfu_vs_empirical_peak_pct") == 1
    assert trend.direction("goodput.goodput_ratio") == 1


def test_trend_flags_regressions_by_direction(tmp_path):
    r1 = tmp_path / "BENCH_r01.json"
    r2 = tmp_path / "BENCH_r02.json"
    r1.write_text(json.dumps({"parsed": {
        "step_ms_gspmd": 100.0, "tokens_per_sec": 1000.0,
        "goodput": {"goodput_ratio": 0.95}}}))
    r2.write_text(json.dumps({"parsed": {
        "step_ms_gspmd": 110.0,        # +10% step time: regression
        "tokens_per_sec": 940.0,       # -6% throughput: regression
        "goodput": {"goodput_ratio": 0.96}}}))  # better: fine
    report = trend.compare(trend.load_rounds(
        trend.find_rounds([str(tmp_path)]))[0])
    assert set(report["regressions"]) == {"step_ms_gspmd",
                                          "tokens_per_sec"}
    m = report["metrics"]["step_ms_gspmd"]
    assert m["change_pct"] == pytest.approx(10.0)
    assert "REGRESSION" in trend.format_trend(report)
    # improvements in the lower-is-better direction are not flagged
    r3 = tmp_path / "BENCH_r03.json"
    r3.write_text(json.dumps({"parsed": {"step_ms_gspmd": 90.0}}))
    report2 = trend.compare(trend.load_rounds(
        trend.find_rounds([str(tmp_path)]))[0])
    assert "step_ms_gspmd" not in report2["regressions"]


def test_trend_on_checked_in_rounds():
    """The real nine rounds parse and produce a multi-metric trend —
    the tool must keep reading what the repo actually checks in."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rounds, skipped = trend.load_rounds(trend.find_rounds([repo]))
    assert len(rounds) >= 9
    assert not skipped
    report = trend.compare(rounds)
    assert "step_ms_gspmd" in report["metrics"]
    assert "goodput.goodput_ratio" in report["metrics"]


def test_trend_cli_too_few_rounds(tmp_path):
    assert trend.main([str(tmp_path)]) == 2


def _scaling_doc(eff_2x2, unattr=0.01):
    return {"bench": "scaling", "model": "resnet18",
            "baseline_world": "1x1",
            "worlds": [
                {"world": "1x1", "efficiency": 1.0,
                 "img_per_sec_per_chip": 10.0, "step_ms_median": 100.0,
                 "goodput": {"ratio": 0.9, "unattributed_frac": unattr}},
                {"world": "2x2", "efficiency": eff_2x2,
                 "img_per_sec_per_chip": 10.0 * eff_2x2,
                 "step_ms_median": 100.0 / max(eff_2x2, 1e-9),
                 "goodput": {"ratio": 0.85,
                             "unattributed_frac": unattr}},
            ],
            "efficiency_curve": {"1x1": 1.0, "2x2": eff_2x2}}


def test_trend_reads_scaling_rounds_per_world(tmp_path):
    """SCALING_*.json sweeps join the trend as per-world series:
    a bent efficiency curve is a regression (higher-is-better), a
    cheaper step is not."""
    (tmp_path / "SCALING_r01.json").write_text(
        json.dumps(_scaling_doc(0.90)))
    (tmp_path / "SCALING_r02.json").write_text(
        json.dumps(_scaling_doc(0.70)))  # curve bent >5%: regression
    paths = trend.find_rounds([str(tmp_path)])
    assert [os.path.basename(p) for p in paths] == \
        ["SCALING_r01.json", "SCALING_r02.json"]
    report = trend.compare(trend.load_rounds(paths)[0])
    assert "scaling.2x2.efficiency" in report["regressions"]
    # step_ms got worse with the efficiency; lower-is-better catches it
    assert "scaling.2x2.step_ms_median" in report["regressions"]
    assert "scaling.1x1.efficiency" not in report["regressions"]
    assert trend.direction("scaling.2x2.efficiency") == 1
    assert trend.direction("scaling.2x2.goodput.unattributed_frac") == -1


def test_trend_mixes_bench_and_scaling_rounds(tmp_path):
    """BENCH and SCALING families coexist: disjoint key spaces, one
    report."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"step_ms_gspmd": 100.0}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"step_ms_gspmd": 101.0}}))
    (tmp_path / "SCALING_r01.json").write_text(
        json.dumps(_scaling_doc(0.9)))
    (tmp_path / "SCALING_r02.json").write_text(
        json.dumps(_scaling_doc(0.91)))
    report = trend.compare(trend.load_rounds(
        trend.find_rounds([str(tmp_path)]))[0])
    assert "step_ms_gspmd" in report["metrics"]
    assert "scaling.2x2.efficiency" in report["metrics"]
    assert report["regressions"] == []


def test_trend_on_checked_in_scaling_round():
    """The checked-in SCALING_r01.json parses into per-world metrics —
    the sweep the repo ships must keep feeding the trend tool."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "SCALING_r01.json")
    with open(path) as f:
        metrics = trend.extract_metrics(json.load(f))
    worlds = {k.split(".")[1] for k in metrics if k.startswith("scaling.")}
    assert len(worlds) >= 2
    for w in worlds:
        assert f"scaling.{w}.efficiency" in metrics
        assert metrics[f"scaling.{w}.goodput.unattributed_frac"] <= 0.02


# -- the real capture (slow) -------------------------------------------------

@pytest.mark.slow
def test_step_xray_end_to_end_and_byte_identical_programs(hvd, tmp_path):
    """The acceptance round-trip on the 8-device CPU mesh: ``step.xray``
    on real ResNet and LM GSPMD steps names a dominant sink, buckets
    >=95% of device time, joins bandwidth from HLO bytes — and the
    compiled programs are byte-identical with X-ray off (capture wraps
    the AOT executable; nothing reaches the traced function)."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd_api
    from horovod_tpu import training
    from horovod_tpu.utils.benchmarks import (make_lm_bench, make_model,
                                              synthetic_batch)

    n = len(jax.devices())

    # ResNet half
    model = make_model("resnet18")
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.05))
    step = training.make_train_step(model, tx, donate=False, spmd=True)
    X, y = synthetic_batch(n, 32)
    state = training.create_train_state(model, tx,
                                        jax.random.PRNGKey(0), X[:1])
    baseline_hlo = step.lower(state, X, y).compile().as_text()
    state, _ = step(state, X, y)
    state, summary = step.xray(state, X, y, k=2,
                               profile_dir=str(tmp_path / "resnet"))
    assert summary["verdict"] in xprof.VERDICTS
    # on the CPU backend "device lanes" are executor threadpool lanes —
    # at least one per virtual device, sometimes more
    assert summary["device_lanes"] >= n
    assert summary["bucketed_fraction"] >= xprof.BUCKETED_GATE
    sink, sink_s = xprof.dominant_sink(summary)
    assert sink is not None and sink_s > 0
    ar = summary["collectives"]["all_reduce"]
    assert ar["bytes_per_step"] > 0 and "effective_gbps" in ar
    # X-ray left the compiled program untouched
    assert step.lower(state, X, y).compile().as_text() == baseline_hlo

    # LM half
    lm_step, lm_state, tokens = make_lm_bench(
        mesh=hvd_api.mesh(), seq_axis=None, flash=None, spmd=True,
        batch=2 * n, seq_len=32, layers=1, d_model=32, heads=4,
        vocab=128)
    lm_baseline = lm_step.lower(lm_state, tokens).compile().as_text()
    lm_state, _ = lm_step(lm_state, tokens)
    lm_state, lm_summary = lm_step.xray(
        lm_state, tokens, k=2, profile_dir=str(tmp_path / "lm"))
    assert lm_summary["verdict"] in xprof.VERDICTS
    assert lm_summary["bucketed_fraction"] >= xprof.BUCKETED_GATE
    assert lm_summary["collectives"]  # the fused AR is visible
    assert lm_step.lower(lm_state, tokens).compile().as_text() == \
        lm_baseline
    # the doctor reads the written summary
    assert xray_doctor.main([str(tmp_path / "resnet")]) == 0
