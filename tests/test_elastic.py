"""Elastic training subsystem: discovery diffing, blacklist/backoff,
state commit/restore/sync, worker notification, the retry loop, and the
CPU-only worker-death -> blacklist -> re-rendezvous -> resume
integration scenario (ISSUE 1 acceptance)."""

import json
import os
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from horovod_tpu import elastic
from horovod_tpu.elastic.discovery import (FixedHosts, HostDiscoveryPoller,
                                           HostUpdateResult, ScriptDiscovery,
                                           diff_hosts)
from horovod_tpu.elastic.driver import (EXIT_RENDEZVOUS, Blacklist,
                                        ElasticDriver)
from horovod_tpu.elastic.exceptions import (HostsUpdatedInterrupt,
                                            WorkerFailureError)
from horovod_tpu.elastic.notification import (WorkerNotificationClient,
                                              WorkerNotificationManager,
                                              WorkerNotificationService)
from horovod_tpu.elastic.state import JaxState, ObjectState
from horovod_tpu.run import launcher
from horovod_tpu.run.rendezvous import KVStoreServer

WORKER = os.path.join(os.path.dirname(__file__), "elastic_train_worker.py")


# ---------------------------------------------------------------------------
# host discovery
# ---------------------------------------------------------------------------

def test_fixed_hosts_accepts_spec_dict_and_list():
    assert FixedHosts("h1:4,h2").find_available_hosts_and_slots() == \
        {"h1": 4, "h2": 1}
    assert FixedHosts({"a": 2}).find_available_hosts_and_slots() == {"a": 2}
    from horovod_tpu.run.allocation import HostSlots
    fh = FixedHosts([HostSlots("x", 3)])
    assert fh.find_available_hosts_and_slots() == {"x": 3}
    fh.set({"y": 1})
    assert fh.find_available_hosts_and_slots() == {"y": 1}


def test_script_discovery(tmp_path):
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho hostA:2\necho '# comment'\n"
                      "echo hostB\n")
    script.chmod(0o755)
    d = ScriptDiscovery(str(script))
    assert d.find_available_hosts_and_slots() == {"hostA": 2, "hostB": 1}

    bad = tmp_path / "bad.sh"
    bad.write_text("#!/bin/sh\nexit 3\n")
    bad.chmod(0o755)
    # a failing script reports an empty set, never crashes the poller
    assert ScriptDiscovery(str(bad)).find_available_hosts_and_slots() == {}

    malformed = tmp_path / "malformed.sh"
    malformed.write_text("#!/bin/sh\necho hostA:2\necho 'hostB:'\n")
    malformed.chmod(0o755)
    # malformed output = flaky poll (same contract as a non-zero exit)
    assert ScriptDiscovery(
        str(malformed)).find_available_hosts_and_slots() == {}


def test_diff_hosts():
    old = {"a": 2, "b": 1, "c": 1}
    new = {"a": 2, "b": 2, "d": 1}
    added, removed, res = diff_hosts(old, new)
    assert added == ["b", "d"]      # b grew, d is new
    assert removed == ["c"]
    assert res == HostUpdateResult.MIXED
    assert diff_hosts(old, dict(old)) == ([], [], HostUpdateResult.NO_UPDATE)
    # a shrinking host counts as removed
    assert diff_hosts({"a": 2}, {"a": 1})[1] == ["a"]


def test_poller_detects_membership_change():
    fh = FixedHosts({"a": 1})
    seen = []
    done = threading.Event()

    def on_update(added, removed, current, res):
        seen.append((added, removed, res))
        done.set()

    poller = HostDiscoveryPoller(fh, poll_interval=0.02,
                                 on_update=on_update)
    poller.start()
    try:
        assert poller.current() == {"a": 1}
        fh.set({"a": 1, "b": 2})
        assert done.wait(5), "poller never reported the added host"
    finally:
        poller.stop()
    assert seen[0] == (["b"], [], HostUpdateResult.ADDED)


# ---------------------------------------------------------------------------
# blacklist / backoff
# ---------------------------------------------------------------------------

def test_blacklist_exponential_backoff_then_permanent():
    now = {"t": 0.0}
    bl = Blacklist(threshold=3, base_delay=10.0, max_delay=1000.0,
                   clock=lambda: now["t"])
    assert not bl.excluded("h")

    bl.record_failure("h")               # backoff 10s
    assert bl.excluded("h") and not bl.blacklisted("h")
    now["t"] = 11.0
    assert not bl.excluded("h")          # cooled down, usable again

    bl.record_failure("h")               # backoff 20s (exponential)
    now["t"] = 25.0
    assert bl.excluded("h")              # 11 + 20 = 31 > 25
    now["t"] = 35.0
    assert not bl.excluded("h")

    bl.record_failure("h")               # third strike: permanent
    now["t"] = 1e9
    assert bl.blacklisted("h") and bl.excluded("h")
    assert bl.hosts == {"h"}
    assert not bl.excluded("other")


def test_driver_waits_for_min_np_and_times_out():
    driver = ElasticDriver(FixedHosts({"a": 1}), min_np=1,
                           poll_interval=0.05, hopeless_grace=0.5)
    assert driver.wait_for_available_slots(1, timeout=5) == {"a": 1}
    driver.blacklist.record_failure("a")
    driver.blacklist.record_failure("a")
    driver.blacklist.record_failure("a")  # default threshold is 3
    with pytest.raises(TimeoutError, match="blacklisted=\\['a'\\]"):
        driver.wait_for_available_slots(1, timeout=0.3)
    # every host permanently blacklisted -> fail fast (short grace),
    # never burn a long start timeout on an unreachable target
    start = time.monotonic()
    with pytest.raises(TimeoutError):
        driver.wait_for_available_slots(1, timeout=600)
    assert time.monotonic() - start < 30
    driver.stop()


def test_driver_rejects_bad_np_bounds():
    with pytest.raises(ValueError, match="min_np"):
        ElasticDriver(FixedHosts({"a": 1}), min_np=0)
    with pytest.raises(ValueError, match="max_np"):
        ElasticDriver(FixedHosts({"a": 1}), min_np=4, max_np=2)


# ---------------------------------------------------------------------------
# worker notification plane
# ---------------------------------------------------------------------------

def test_notification_roundtrip_and_commit_interrupt():
    manager = WorkerNotificationManager()
    service = WorkerNotificationService(manager=manager, host="127.0.0.1")
    try:
        client = WorkerNotificationClient("127.0.0.1", service.port)
        assert client.ping()
        assert client.notify_hosts_updated("added")

        state = ObjectState(notification_manager=manager, x=1)
        with pytest.raises(HostsUpdatedInterrupt) as ei:
            state.commit()
        assert ei.value.res == "added"
        # the interrupt drained the mailbox; progress was still saved
        state.commit()
        assert state.has_commit()
    finally:
        service.shutdown()


def test_notification_requires_matching_key():
    manager = WorkerNotificationManager()
    service = WorkerNotificationService(key=b"right-key", manager=manager,
                                        host="127.0.0.1")
    try:
        bad = WorkerNotificationClient("127.0.0.1", service.port,
                                       key=b"wrong-key")
        # server drops the bad frame; client sees a closed/empty reply
        assert not bad.notify_hosts_updated()
        assert manager.poll() is None
    finally:
        service.shutdown()


# ---------------------------------------------------------------------------
# state commit / restore / sync
# ---------------------------------------------------------------------------

def test_object_state_commit_restore():
    state = ObjectState(counter=0, blob={"k": [1, 2]})
    state.commit()
    state.counter = 7
    state.blob["k"].append(3)
    state.restore()
    assert state.counter == 0 and state.blob == {"k": [1, 2]}


def test_jax_state_commit_restore_sync_roundtrip(tmp_path):
    import jax.numpy as jnp
    state = JaxState(directory=str(tmp_path),
                     params={"w": jnp.ones((3,)), "b": jnp.zeros(())},
                     step=np.int64(0))
    state.commit()
    state.params = {"w": jnp.full((3,), 9.0), "b": jnp.asarray(1.0)}
    state.step = np.int64(5)
    state.restore()
    np.testing.assert_allclose(np.asarray(state.params["w"]), 1.0)
    assert int(state.step) == 0

    # disk-backed: a FRESH process (new object) restores the last commit.
    # Commits are ASYNC now (horovod_tpu/ckpt): the old incarnation must
    # flush before another reader consumes the directory — exactly what
    # the elastic loop does before every re-rendezvous (State.on_reset)
    state.params = {"w": jnp.full((3,), 2.0), "b": jnp.asarray(4.0)}
    state.step = np.int64(3)
    state.commit()
    state.flush()
    fresh = JaxState(directory=str(tmp_path),
                     params={"w": jnp.zeros((3,)), "b": jnp.zeros(())},
                     step=np.int64(0))
    fresh.restore()
    np.testing.assert_allclose(np.asarray(fresh.params["w"]), 2.0)
    assert int(fresh.step) == 3

    # sync on a single process is a no-op broadcast that re-baselines
    fresh.sync()
    np.testing.assert_allclose(np.asarray(fresh.params["w"]), 2.0)
    assert int(fresh.step) == 3


def test_jax_state_rank_gate_blocks_nonzero_rank_writes(tmp_path,
                                                        monkeypatch):
    """Under the sharded subsystem every rank writes its OWN shard —
    but the MANIFEST (what makes a checkpoint exist) is still rank 0's
    alone: a lone rank 1 leaves only a torn, restore-invisible dir, and
    its flush surfaces the missing phase-2 commit as an error."""
    monkeypatch.setenv("HOROVOD_RANK", "1")
    monkeypatch.setenv("HOROVOD_SIZE", "2")
    monkeypatch.setenv("HOROVOD_CKPT_TIMEOUT", "1")
    from horovod_tpu import ckpt as ckpt_lib
    from horovod_tpu.ckpt import manifest as manifest_lib
    state = JaxState(directory=str(tmp_path), x=np.asarray(1.0))
    state.commit()
    with pytest.raises(RuntimeError, match="MANIFEST"):
        state.flush()  # rank 0 never committed phase 2
    sdir = manifest_lib.step_dir(str(tmp_path), 1)
    assert os.path.isfile(os.path.join(sdir, manifest_lib.shard_name(1, 2)))
    assert not manifest_lib.is_complete(str(tmp_path), 1)
    assert ckpt_lib.latest_complete_step(str(tmp_path)) is None
    state._ckpt.close()


# ---------------------------------------------------------------------------
# the retry loop
# ---------------------------------------------------------------------------

def test_run_decorator_restores_on_worker_failure():
    state = ObjectState(value=0)
    calls = {"n": 0}

    @elastic.run
    def train(state):
        calls["n"] += 1
        state.value += 10
        if calls["n"] == 1:
            raise WorkerFailureError("peer died")  # before any commit
        state.commit()
        return state.value

    # failure rolls back the half-applied batch: the second attempt
    # starts from the committed (initial) value, not from 10
    assert train(state) == 10
    assert calls["n"] == 2


def test_run_decorator_keeps_progress_on_hosts_updated():
    manager = WorkerNotificationManager()
    state = ObjectState(notification_manager=manager, step=0)
    resets = []
    state.register_reset_callbacks([lambda: resets.append(True)])

    @elastic.run
    def train(state):
        while state.step < 4:
            state.step += 1
            if state.step == 2:
                manager.handle_hosts_updated("added")
            state.commit()  # raises at step 2, progress kept
        return state.step

    assert train(state) == 4
    assert resets == [True]  # one reset, for the membership interrupt


def test_run_decorator_reset_limit(monkeypatch):
    monkeypatch.setenv("HOROVOD_ELASTIC_RESET_LIMIT", "2")
    state = ObjectState(x=0)

    @elastic.run
    def train(state):
        raise WorkerFailureError("always")

    with pytest.raises(WorkerFailureError, match="giving up after 2"):
        train(state)


def test_elastic_train_loop_recovers_mid_run():
    """training.py's elastic loop variant: a membership interrupt midway
    re-syncs and finishes; committed progress is never recomputed."""
    import jax.numpy as jnp
    import optax

    from horovod_tpu.training import TrainState, elastic_train_loop

    tx = optax.sgd(0.2)
    params = {"w": jnp.zeros(())}
    ts = TrainState(params=params, opt_state=tx.init(params),
                    batch_stats={}, step=jnp.zeros((), jnp.int32))

    def train_step(state, inputs, labels):
        del inputs, labels
        grads = {"w": 2 * (state.params["w"] - 3.0)}
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        loss = (state.params["w"] - 3.0) ** 2
        return TrainState(params=new_params, opt_state=opt_state,
                          batch_stats={}, step=state.step + 1), loss

    manager = WorkerNotificationManager()
    state = JaxState(notification_manager=manager, train_state=ts)
    seen = []

    def on_step(step, loss):
        seen.append((step, loss))
        if step == 3:
            manager.handle_hosts_updated("removed")

    final = elastic_train_loop(state, train_step,
                               lambda step: (None, None), num_steps=6,
                               commit_every=1, on_step=on_step)
    assert int(final.step) == 6
    steps = [s for s, _ in seen]
    assert steps == [1, 2, 3, 4, 5, 6]  # no step recomputed after resync
    losses = [l for _, l in seen]
    assert losses == sorted(losses, reverse=True)  # monotone convergence


# ---------------------------------------------------------------------------
# CLI flags
# ---------------------------------------------------------------------------

def test_cli_elastic_flag_validation(tmp_path):
    from horovod_tpu.run.run import parse_args

    ok = parse_args(["--min-np", "1", "--max-np", "4", "-np", "2",
                     "python", "t.py"])
    assert ok.elastic and (ok.min_np, ok.max_np, ok.num_proc) == (1, 4, 2)
    # -np defaults from --min-np in elastic mode
    assert parse_args(["--min-np", "3", "python", "t.py"]).num_proc == 3

    script = tmp_path / "d.sh"
    script.write_text("#!/bin/sh\necho localhost:2\n")
    script.chmod(0o755)
    ok2 = parse_args(["--host-discovery-script", str(script),
                      "--min-np", "2", "python", "t.py"])
    assert ok2.elastic and ok2.num_proc == 2

    def rejects(argv):
        with pytest.raises(SystemExit):
            parse_args(argv)

    rejects(["--min-np", "4", "--max-np", "2", "python", "t.py"])
    rejects(["--min-np", "0", "python", "t.py"])
    rejects(["--min-np", "2", "-np", "1", "python", "t.py"])
    rejects(["--min-np", "1", "--max-np", "2", "-np", "3",
             "python", "t.py"])
    rejects(["--max-np", "2", "python", "t.py"])  # no min-np, no -np
    rejects(["--host-discovery-script", "/nonexistent-script",
             "--min-np", "1", "python", "t.py"])
    rejects(["--host-discovery-script", str(script), "-H", "h1:2",
             "--min-np", "1", "python", "t.py"])
    unexec = tmp_path / "plain.txt"
    unexec.write_text("not a script")
    rejects(["--host-discovery-script", str(unexec), "--min-np", "1",
             "python", "t.py"])


def test_nic_cache_key_and_sorted_export(monkeypatch):
    """ADVICE round 5: the NIC pre-flight cache key must include the
    launcher host, and fresh discovery must export sorted(common) so the
    first and cached launches agree."""
    import socket

    from horovod_tpu.run import run as run_mod
    from horovod_tpu.run.allocation import HostSlots

    hosts = [HostSlots("b", 1), HostSlots("a", 1)]
    key = run_mod._nic_cache_key(hosts)
    assert socket.gethostname() in key
    assert key.endswith("a,b")

    store = {}

    class FakeCache:
        def __init__(self, *a, **k):
            pass

        def get(self, k):
            return store.get(k)

        def put(self, k, v):
            store[k] = v

    monkeypatch.setattr(run_mod.run_cache, "Cache", FakeCache)
    args = types.SimpleNamespace(disable_cache=False, verbose=False)
    fresh = run_mod._common_interfaces(args, hosts,
                                       lambda: ["eth1", "eth0"])
    assert fresh == ["eth0", "eth1"]  # sorted on the fresh path
    cached = run_mod._common_interfaces(
        args, hosts, lambda: pytest.fail("cache should have served this"))
    assert cached == fresh


def test_cli_elastic_smoke_local():
    """hvdrun end-to-end with elastic flags: one localhost worker, one
    epoch, clean exit."""
    from horovod_tpu.run.run import main
    rc = main(["--min-np", "1", "-np", "1", "--",
               sys.executable, "-c", "print('elastic-ok')"])
    assert rc == 0


# ---------------------------------------------------------------------------
# integration: worker death -> blacklist -> re-rendezvous -> resume
# ---------------------------------------------------------------------------

def _spawn_launch_fn(kv_port, worker_args, step_sleep=None):
    """launch_fn for ElasticDriver that maps EVERY (possibly fake) host
    to a local subprocess, with the real launcher env contract."""

    def launch(slots, epoch, elastic_env):
        job = launcher.Job()
        for slot in slots:
            env = dict(os.environ)
            env.update({
                "HOROVOD_RANK": str(slot.rank),
                "HOROVOD_SIZE": str(slot.size),
                "HOROVOD_LOCAL_RANK": str(slot.local_rank),
                "HOROVOD_HOSTNAME": slot.hostname,
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(kv_port),
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": launcher.repo_pythonpath(),
            })
            env.update(elastic_env)
            if step_sleep:
                env["HVD_ELASTIC_TEST_SLEEP"] = str(step_sleep)
            job.procs.append(subprocess.Popen(
                [sys.executable, WORKER] + [str(a) for a in worker_args],
                env=env))
        return job

    return launch


def _read_log(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_worker_death_blacklist_rerendezvous_resume(tmp_path):
    """The acceptance scenario: rank 0's host SIGKILLs itself mid-training
    in epochs 1 and 2 -> the driver blames and (threshold 2) blacklists
    it -> epoch 3 re-rendezvouses on the surviving host (>= min-np=1),
    restores the last committed JaxState from disk, and finishes. The
    logged loss trajectory must equal an uninterrupted run's exactly."""
    ckpt_dir = tmp_path / "ckpt"
    log = tmp_path / "losses.jsonl"
    num_steps = 8

    kv = KVStoreServer()
    kv_port = kv.start()
    try:
        driver = ElasticDriver(
            FixedHosts({"hostA": 1, "hostB": 1}), min_np=1, max_np=2,
            blacklist=Blacklist(threshold=2, base_delay=0.0),
            kv=kv, poll_interval=0.2)
        launch = _spawn_launch_fn(
            kv_port, [ckpt_dir, log, num_steps, "hostA", 3])
        epochs = driver.run_job(launch, max_epochs=6)
    finally:
        kv.stop()

    assert epochs == 3
    assert driver.blacklist.blacklisted("hostA")
    assert not driver.blacklist.excluded("hostB")

    records = _read_log(str(log))
    done = [r for r in records if "done" in r]
    steps = [r for r in records if "step" in r]
    # epochs 1 and 2 each commit exactly one step on hostA before dying;
    # epoch 3 resumes ON hostB from the last committed step
    assert [r["host"] for r in steps[:2]] == ["hostA", "hostA"]
    assert all(r["host"] == "hostB" for r in steps[2:])
    assert done and done[0]["resumed_from"] == 2 and \
        done[0]["done"] == num_steps

    # loss continuity: every step computed exactly once, and the whole
    # recovered trajectory equals the uninterrupted oracle
    assert [r["step"] for r in steps] == list(range(1, num_steps + 1))
    w = 0.0
    for r in steps:
        assert r["loss"] == pytest.approx((w - 3.0) ** 2, abs=1e-12)
        w = w - 0.2 * 2 * (w - 3.0)

    # the driver's liveness view saw the surviving worker's heartbeats
    progress = driver.worker_progress()
    assert 0 in progress and progress[0]["step"] == num_steps


def test_membership_change_graceful_rerendezvous(tmp_path):
    """A host added mid-run: the poller diffs the set, the driver posts a
    notification, the worker drains at a commit boundary with
    EXIT_RENDEZVOUS (no blame), and the next epoch runs on the grown
    world from the committed step. Timeline gets MEMBERSHIP markers."""
    from horovod_tpu.utils.timeline import Timeline

    ckpt_dir = tmp_path / "ckpt"
    log = tmp_path / "losses.jsonl"
    tl_path = tmp_path / "timeline.json"
    num_steps = 120  # ~5s alone: plenty of window for the interrupt

    discovery = FixedHosts({"hostA": 1})
    kv = KVStoreServer()
    kv_port = kv.start()
    timeline = Timeline(str(tl_path))
    grown = threading.Event()

    def grow_later():
        # grow only once epoch 1's worker is demonstrably mid-training
        # (first heartbeat on the KV), so the interrupt lands in-loop
        deadline = time.time() + 60
        while time.time() < deadline:
            if kv.get("elastic/heartbeat/1/0") is not None:
                break
            time.sleep(0.1)
        discovery.set({"hostA": 1, "hostB": 1})
        grown.set()

    try:
        driver = ElasticDriver(discovery, min_np=1, max_np=2, kv=kv,
                               poll_interval=0.1, timeline=timeline)
        launch = _spawn_launch_fn(kv_port, [ckpt_dir, log, num_steps],
                                  step_sleep=0.04)
        threading.Thread(target=grow_later, daemon=True).start()
        epochs = driver.run_job(launch, max_epochs=4)
    finally:
        kv.stop()
        timeline.close()

    assert grown.is_set()
    assert epochs == 2, "the added host should force exactly one " \
        "graceful re-rendezvous"
    assert driver.blacklist.hosts == set()  # graceful exits: no blame

    records = _read_log(str(log))
    done = [r for r in records if "done" in r]
    assert done and done[0]["done"] == num_steps
    assert done[0]["resumed_from"] > 0, \
        "epoch 2 must resume from committed progress, not step 0"
    steps = [r["step"] for r in records if "step" in r]
    assert steps == sorted(steps) and len(steps) == len(set(steps))

    events = json.loads(tl_path.read_text())
    names = {e["name"] for e in events}
    assert "MEMBERSHIP_UPDATED" in names
    assert "MEMBERSHIP_RENDEZVOUS" in names
