"""Worker process of the tier-1 multi-process e2e
(tests/test_multiprocess.py): one rank of a real 2-process
``jax.distributed`` job on the CPU stand-in (gloo collectives, forced
local device count).

The deterministic workload lives HERE — the test process imports this
module to run the very same functions single-process, so the oracle
and the multi-process run can only differ by the process mesh.

Modes (``--mode``):

* ``train``    — N GSPMD steps on the process mesh; rank 0 writes
  ``losses.json``. Flight-recorder env makes every rank drop a
  ``goodput.rank<r>.json`` at shutdown.
* ``save``     — train N steps, then every rank saves its shard of the
  state (``ckpt.sharded``, rank/world keyed); rank 0 also writes
  ``reference.npz``, the full host state for bitwise comparison.
* ``restore``  — restore a checkpoint (written by ANY world size) into
  a fresh state; rank 0 writes ``restored.npz``.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

GLOBAL_ROWS = 8
N_FEATURES = 16
N_CLASSES = 3


def build_batch():
    """The deterministic global batch — identical on every process."""
    import numpy as np
    rng = np.random.RandomState(0)
    x = rng.normal(size=(GLOBAL_ROWS, N_FEATURES)).astype(np.float32)
    y = rng.randint(0, N_CLASSES, size=(GLOBAL_ROWS,)).astype(np.int32)
    return x, y


def build_state_and_step(mesh):
    """Model, optimizer, init state and the compiled GSPMD step — one
    construction shared by worker ranks and the in-test oracle."""
    import jax
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.models.simple import MLP

    model = MLP(features=(8, N_CLASSES))
    tx = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9))
    x, _ = build_batch()
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        x[:1])
    step = training.make_train_step(model, tx, mesh=mesh, donate=False,
                                    spmd=True)
    return state, step


def train_steps(mesh, steps):
    state, step = build_state_and_step(mesh)
    x, y = build_batch()
    losses = []
    for _ in range(steps):
        state, loss = step(state, x, y)
        losses.append(float(loss))
    return state, losses


def host_state(state):
    """The full state tree as host numpy — every leaf is replicated or
    addressable-row-0-complete, so ``addressable_data(0)`` has the
    whole value on every process."""
    import jax
    import numpy as np

    def fetch(x):
        if isinstance(x, jax.Array):
            return np.asarray(x.addressable_data(0))
        return np.asarray(x)

    return jax.tree_util.tree_map(fetch, state)


def flat_arrays(tree):
    """``{leaf_path: ndarray}`` for npz round-trips."""
    import jax
    import numpy as np
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[jax.tree_util.keystr(path)] = np.asarray(leaf)
    return flat


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", required=True,
                   choices=("train", "save", "restore"))
    p.add_argument("--out", required=True)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--ckpt-step", type=int, default=None)
    args = p.parse_args()

    import numpy as np

    import horovod_tpu as hvd
    hvd.init()

    import jax
    mesh = hvd.mesh()
    rank = int(jax.process_index())

    if args.mode == "train":
        state, losses = train_steps(mesh, args.steps)
        if rank == 0:
            with open(os.path.join(args.out, "losses.json"), "w") as f:
                json.dump({"losses": losses,
                           "procs": int(jax.process_count()),
                           "devices": int(jax.device_count()),
                           "mesh_axes": list(mesh.axis_names)}, f)
    elif args.mode == "save":
        from horovod_tpu.ckpt import sharded
        state, losses = train_steps(mesh, args.steps)
        host = host_state(state)
        sharded.save_sharded(
            os.path.join(args.out, "ckpt"), args.steps, host,
            rank=rank, world=int(jax.process_count()))
        if rank == 0:
            np.savez(os.path.join(args.out, "reference.npz"),
                     **flat_arrays(host))
            with open(os.path.join(args.out, "losses.json"), "w") as f:
                json.dump({"losses": losses}, f)
    else:  # restore
        from horovod_tpu.ckpt import sharded
        state, _step = build_state_and_step(mesh)
        step_no, tree, _meta = sharded.restore_sharded(
            os.path.join(args.out, "ckpt"), host_state(state),
            step=args.ckpt_step)
        if rank == 0:
            np.savez(os.path.join(args.out, "restored.npz"),
                     **flat_arrays(tree))
            with open(os.path.join(args.out, "restored_step.json"),
                      "w") as f:
                json.dump({"step": int(step_no)}, f)

    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
