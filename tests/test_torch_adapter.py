"""Torch adapter tests (reference: test/test_torch.py — op correctness,
optimizer grad averaging, parameter/optimizer-state broadcast) plus the
callback suite. Multi-process cases ride the programmatic launcher
(api.run), dogfooding hvdrun."""

import numpy as np
import pytest
import torch

from horovod_tpu.run import api


@pytest.fixture()
def hvd_torch(hvd):
    """Single-process torch adapter on top of the initialized hvd."""
    import horovod_tpu.torch as hvd_t
    yield hvd_t
    from horovod_tpu import _core
    _core.shutdown()


# ---- single-process semantics (world size 1 == identity) ---------------

def test_single_process_ops(hvd_torch):
    x = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd_torch.allreduce(x)
    assert torch.equal(out, x)
    out = hvd_torch.allgather(x)
    assert torch.equal(out, x)
    y = x.clone()
    hvd_torch.broadcast_(y, root_rank=0)
    assert torch.equal(y, x)
    assert hvd_torch.broadcast_object({"a": 1}) == {"a": 1}


def test_differentiable_collectives_single_process(hvd_torch):
    """Grad THROUGH hvd ops (reference torch/mpi_ops.py:158-385 autograd
    Functions): size 1 — allreduce/allgather are identities with identity
    jacobians, broadcast from the only (root) rank passes grads through."""
    x = torch.ones(3, requires_grad=True)
    y = hvd_torch.allreduce(x * 2.0, average=True).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full(3, 2.0))

    x = torch.ones(2, 2, requires_grad=True)
    hvd_torch.allgather(x * 3.0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 3.0))

    x = torch.ones(4, requires_grad=True)
    hvd_torch.broadcast(x * 5.0, root_rank=0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full(4, 5.0))


def test_differentiable_collectives_multi_process():
    def fn():
        import numpy as np
        import torch

        import horovod_tpu.torch as hvd
        hvd.init()
        r, n = hvd.rank(), hvd.size()
        out = {}
        # y = sum(allreduce_avg(x * (r+1))): dy/dx = avg-allreduced
        # ones * (r+1)
        x = torch.ones(3, requires_grad=True)
        hvd.allreduce(x * float(r + 1), average=True).sum().backward()
        out["ar"] = x.grad.numpy().tolist()
        # allgather: rank r feeds r+1 rows, weighted by gathered-row
        # index+1; grad = that rank's slice of the weights
        xg = torch.ones(r + 1, 2, requires_grad=True)
        g = hvd.allgather(xg * 2.0)
        w = torch.arange(1.0, g.shape[0] + 1).reshape(-1, 1)
        (g * w).sum().backward()
        out["ag"] = xg.grad.numpy().tolist()
        # broadcast: grads sum on root, zero elsewhere
        xb = torch.ones(2, requires_grad=True)
        hvd.broadcast(xb, root_rank=0).sum().backward()
        out["bc"] = xb.grad.numpy().tolist()
        return out

    r0, r1 = api.run(fn, np=2, extra_env={"JAX_PLATFORMS": "cpu"})
    for r, res in enumerate((r0, r1)):
        np.testing.assert_allclose(res["ar"], np.full(3, r + 1.0))
        # every rank computes the same per-rank loss, and each loss
        # depends on MY rows through the gather — the backward sums the
        # cotangents across ranks (reference mpi_ops.py:300), so grad =
        # n_ranks * 2 * weights-for-my-rows
        want = [[4.0, 4.0]] if r == 0 else [[8.0, 8.0], [12.0, 12.0]]
        np.testing.assert_allclose(res["ag"], want)
        np.testing.assert_allclose(res["bc"],
                                   np.full(2, 2.0 if r == 0 else 0.0))


def test_join_exposed(hvd_torch):
    """size 1: join returns immediately (reference hvd.join contract)."""
    hvd_torch.join()


def test_single_process_optimizer_matches_plain(hvd_torch):
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 2)
    ref = torch.nn.Linear(4, 2)
    ref.load_state_dict(model.state_dict())
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    x = torch.randn(8, 4)
    y = torch.randn(8, 2)
    for _ in range(3):
        opt.zero_grad()
        torch.nn.functional.mse_loss(model(x), y).backward()
        opt.step()
        ref_opt.zero_grad()
        torch.nn.functional.mse_loss(ref(x), y).backward()
        ref_opt.step()
    for p, q in zip(model.parameters(), ref.parameters()):
        assert torch.allclose(p, q, atol=1e-6)


def test_backward_passes_per_step_accumulates(hvd_torch):
    """Documented Horovod usage: N backwards then one step() must apply
    the accumulated (allreduced) gradient — step() never silently no-ops
    (reference torch/__init__.py:57-212)."""
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 1, bias=False)
    ref = torch.nn.Linear(4, 1, bias=False)
    ref.load_state_dict(model.state_dict())
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2)
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    xs = [torch.randn(8, 4) for _ in range(2)]
    for x in xs:
        model(x).sum().backward()
    opt.step()  # must synchronize + step, not skip
    for x in xs:
        ref(x).sum().backward()
    ref_opt.step()
    assert torch.allclose(model.weight, ref.weight, atol=1e-6)


def test_step_syncs_even_with_pending_delay(hvd_torch):
    """step() after a single backward with backward_passes_per_step=2
    still allreduces the pending gradient and steps (reference
    synchronize() missing-handle fallback)."""
    model = torch.nn.Linear(4, 1, bias=False)
    before = model.weight.detach().clone()
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2)
    model(torch.ones(2, 4)).sum().backward()
    opt.step()
    assert not torch.allclose(model.weight, before)


def test_zero_grad_with_outstanding_handles_raises(hvd_torch):
    model = torch.nn.Linear(4, 1, bias=False)
    opt = hvd_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    model(torch.ones(2, 4)).sum().backward()  # hook fired, handle pending
    with pytest.raises(AssertionError, match="zero_grad"):
        opt.zero_grad()
    opt.synchronize()
    opt.zero_grad()  # fine after synchronize


def test_duplicate_parameter_names_rejected(hvd_torch):
    model = torch.nn.Linear(2, 2)
    with pytest.raises(ValueError, match="duplicate parameter names"):
        hvd_torch.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=[("w", model.weight), ("w", model.bias)])


# ---- callbacks ---------------------------------------------------------

def test_warmup_callback(hvd_torch):
    model = torch.nn.Linear(2, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.4)
    cb = __import__("horovod_tpu.callbacks", fromlist=["x"]) \
        .LearningRateWarmupCallback(opt, initial_lr=0.4, warmup_epochs=4)
    # size() == 1 here, so target == initial; with explicit target math:
    cb.target_lr = 0.8
    lrs = []
    for epoch in range(6):
        cb.on_epoch_begin(epoch)
        lrs.append(opt.param_groups[0]["lr"])
    np.testing.assert_allclose(lrs, [0.4, 0.5, 0.6, 0.7, 0.8, 0.8])


def test_schedule_callback(hvd_torch):
    from horovod_tpu.callbacks import LearningRateScheduleCallback
    model = torch.nn.Linear(2, 2)
    opt = torch.optim.SGD(model.parameters(), lr=1.0)
    cb = LearningRateScheduleCallback(
        opt, multiplier=lambda e: 0.1 ** (e // 2), start_epoch=0)
    got = []
    for epoch in range(5):
        cb.on_epoch_begin(epoch)
        got.append(round(opt.param_groups[0]["lr"], 6))
    assert got == [1.0, 1.0, 0.1, 0.1, 0.01]


def test_optax_warmup_schedule(hvd):
    from horovod_tpu.callbacks import warmup_schedule
    sched = warmup_schedule(0.1, size=8, warmup_steps=10)
    assert float(sched(0)) == pytest.approx(0.1)
    assert float(sched(5)) == pytest.approx(0.45)
    assert float(sched(10)) == pytest.approx(0.8)
    assert float(sched(1000)) == pytest.approx(0.8)


# ---- multi-process end-to-end ------------------------------------------

def test_torch_distributed_training():
    def train():
        import numpy as np
        import torch

        import horovod_tpu.torch as hvd
        hvd.init()
        torch.manual_seed(1234 + hvd.rank())  # different init per rank
        model = torch.nn.Linear(6, 1, bias=False)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters())
        # sync initial params from root (the Horovod contract)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        hvd.broadcast_optimizer_state(opt, root_rank=0)

        rng = np.random.default_rng(7)  # same data plan on all ranks
        w_true = rng.standard_normal(6).astype(np.float32)
        X = rng.standard_normal((64, 6)).astype(np.float32)
        y = X @ w_true
        Xl = torch.from_numpy(X[hvd.rank()::hvd.size()])
        yl = torch.from_numpy(y[hvd.rank()::hvd.size()])[:, None]

        for _ in range(200):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(Xl), yl)
            loss.backward()
            opt.step()
        w = model.weight.detach().numpy().ravel()
        err = float(np.abs(w - w_true).max())
        return err, w.tolist()

    results = api.run(train, np=2, extra_env={"JAX_PLATFORMS": "cpu"})
    errs = [e for e, _ in results]
    ws = [w for _, w in results]
    assert max(errs) < 1e-2, errs
    np.testing.assert_allclose(ws[0], ws[1], atol=1e-6)  # ranks in sync


def test_torch_fp16_compression_and_backward_passes():
    def train():
        import torch

        import horovod_tpu.torch as hvd
        hvd.init()
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 1, bias=False)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            compression=hvd.Compression.fp16,
            backward_passes_per_step=2)
        x = torch.ones(4, 4) * (hvd.rank() + 1)
        for _ in range(2):  # 2 real steps of 2 accumulated backwards
            for _ in range(2):
                model(x).sum().backward()  # 2nd backward fires fp16 hook
            opt.step()
            opt.zero_grad()
        return model.weight.detach().numpy().ravel().tolist()

    results = api.run(train, np=2, extra_env={"JAX_PLATFORMS": "cpu"})
    np.testing.assert_allclose(results[0], results[1], atol=1e-3)


def test_broadcast_optimizer_state_fresh_nonroot():
    """Canonical restore scenario: root has momentum state (stepped),
    non-root is fresh with EMPTY state. Root drives the broadcast set;
    non-root materializes missing tensors instead of stalling."""
    def fn():
        import torch

        import horovod_tpu.torch as hvd
        hvd.init()
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 1, bias=False)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        if hvd.rank() == 0:
            # root "restored from checkpoint": momentum buffers exist
            model(torch.ones(2, 4)).sum().backward()
            opt.step()
            opt.zero_grad()
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        sd = opt.state_dict()
        bufs = [v for st in sd["state"].values()
                for k, v in st.items() if torch.is_tensor(v)]
        return [b.numpy().ravel().tolist() for b in bufs]

    results = api.run(fn, np=2, extra_env={"JAX_PLATFORMS": "cpu"})
    assert results[0], "root should have momentum buffers"
    assert len(results[0]) == len(results[1])
    for a, b in zip(results[0], results[1]):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_metric_average_callback_multiprocess():
    def fn():
        import horovod_tpu as hvd
        from horovod_tpu.callbacks import MetricAverageCallback
        hvd.init()
        cb = MetricAverageCallback()
        out = cb.on_epoch_end(0, {"loss": float(hvd.rank()),
                                  "acc": 2.0 * hvd.rank()})
        return out

    results = api.run(fn, np=2, extra_env={"JAX_PLATFORMS": "cpu"})
    for out in results:
        assert out["loss"] == pytest.approx(0.5)
        assert out["acc"] == pytest.approx(1.0)
