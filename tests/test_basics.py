"""Lifecycle + identity tests (reference pattern: test/test_common.py and
the rank/size checks at the top of test/test_tensorflow.py)."""

import pytest


def test_init_idempotent(hvd):
    hvd.init()
    hvd.init()
    assert hvd.is_initialized()


def test_single_process_identity(hvd):
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.cross_size() == 1


def test_mesh_shape(hvd, n_devices):
    assert hvd.num_devices() == n_devices
    assert hvd.mesh().axis_names == ("data",)
    assert hvd.data_axes() == ("data",)


def test_mesh_2d(hvd2d, n_devices):
    m = hvd2d.mesh()
    assert m.axis_names == ("dcn", "data")
    assert m.devices.shape == (2, n_devices // 2)
    assert hvd2d.data_axes() == ("dcn", "data")


def test_uninitialized_raises():
    import horovod_tpu as hvd
    hvd.shutdown()
    with pytest.raises(RuntimeError):
        hvd.rank()
    with pytest.raises(RuntimeError):
        hvd.mesh()


def test_env_contract(monkeypatch):
    import horovod_tpu as hvd
    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_RANK", "3")
    monkeypatch.setenv("HOROVOD_SIZE", "8")
    monkeypatch.setenv("HOROVOD_LOCAL_RANK", "1")
    monkeypatch.setenv("HOROVOD_LOCAL_SIZE", "4")
    monkeypatch.setenv("HOROVOD_CROSS_RANK", "1")
    monkeypatch.setenv("HOROVOD_CROSS_SIZE", "2")
    # No coordinator addr -> stays single-process JAX but identity comes
    # from the env contract (what the launcher guarantees).
    hvd.init()
    try:
        assert hvd.rank() == 3
        assert hvd.size() == 8
        assert hvd.local_rank() == 1
        assert hvd.local_size() == 4
        assert hvd.cross_rank() == 1
        assert hvd.cross_size() == 2
        # cross_size=2 -> hierarchical 2-D mesh
        assert hvd.mesh().axis_names == ("dcn", "data")
    finally:
        hvd.shutdown()


def test_config_knobs(monkeypatch):
    from horovod_tpu.config import Config
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1048576")
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "3.5")
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "30")
    cfg = Config.from_env()
    assert cfg.fusion_threshold == 1048576
    assert cfg.cycle_time_ms == 3.5
    assert cfg.hierarchical_allreduce is True
    assert cfg.stall_warning_time == 30.0


def test_mpi_threads_supported(hvd):
    assert hvd.mpi_threads_supported() is False


def test_built_probes():
    """Reference *_built() capability probes (basics.py:162-189): the
    MPI-era backends report absent, the roles that exist here report
    by their actual availability."""
    import horovod_tpu as hvd
    assert hvd.mpi_built() is False
    assert hvd.mpi_enabled() is False
    assert hvd.ddl_built() is False
    assert hvd.ccl_built() is False
    assert hvd.gloo_built() is True      # native TCP core ships built-in
    # int like the reference's version-code contract: 0 = no live TPU
    assert hvd.nccl_built() in (0, 1)


def test_nccl_built_preinit_warns_once(caplog):
    """ADVICE round 5: probing nccl_built() before init() silently says
    "not built"; it must warn — exactly once — so pre-init callers know
    the 0 is about timing, not capability."""
    import logging

    import horovod_tpu as hvd
    from horovod_tpu import basics

    hvd.shutdown()
    basics._nccl_preinit_warned = False  # fresh process-lifetime flag
    with caplog.at_level(logging.WARNING, logger="horovod_tpu"):
        assert hvd.nccl_built() == 0
        assert hvd.nccl_built() == 0
    warnings = [r for r in caplog.records
                if "probed before" in r.getMessage()]
    assert len(warnings) == 1
