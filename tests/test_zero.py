"""Overlapped bucket pipeline + ZeRO-1 sharded-update tests.

The contract under test (ISSUE 2 acceptance): ``make_train_step(...,
accum_steps=K, overlap_grads=True)`` with and without
``DistributedOptimizer(..., sharded_update=True)`` reproduces the baseline
step's params/loss trajectory within reduction-order tolerance on the
virtual 8-device mesh — including a parameter count that does NOT divide
by the rank count (the padded-remainder path) — while the optimizer state
is genuinely sharded 1/N per device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_api
from horovod_tpu import training
from horovod_tpu.models.simple import MLP
from horovod_tpu.ops import collective, fusion
from horovod_tpu.parallel import zero


# MLP(10, 7, 3) on 5-dim inputs: 161 params — NOT divisible by 8 ranks
# (padded to 168, 21/rank). No dropout, no BatchNorm: the baseline and the
# microbatched pipeline compute the identical mathematical gradient.
REMAINDER_FEATURES = (10, 7, 3)


def _data(n=32, d=5, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    X = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    y = jnp.asarray(rng.integers(0, classes, size=(n,)), jnp.int32)
    return X, y


def _build(hvd, features, sharded, accum, overlap, tx_factory=None):
    model = MLP(features=features)
    make_tx = tx_factory or (lambda: optax.adamw(1e-2))
    tx = hvd_api.DistributedOptimizer(make_tx(), sharded_update=sharded)
    X, y = _data()
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        X[:1])
    step = training.make_train_step(model, tx, accum_steps=accum,
                                    overlap_grads=overlap)
    return step, state, X, y


def _run(step, state, X, y, steps=5):
    losses = []
    for _ in range(steps):
        state, loss = step(state, X, y)
        losses.append(float(loss))
    return state, losses


@pytest.mark.parametrize("sharded,accum,overlap", [
    (False, 4, True),   # overlapped RS pipeline, plain optimizer
    (True, 4, True),    # overlapped RS pipeline + ZeRO-1
    (True, 1, False),   # ZeRO-1 through the generic tx.update path
    (False, 4, False),  # plain accumulation (fused AR after the loop)
])
def test_pipeline_matches_baseline_trajectory(hvd, sharded, accum, overlap):
    """5-step params/loss parity against the default step, non-divisible
    161-param model (the padded bucket/rank remainder case)."""
    step0, st0, X, y = _build(hvd, REMAINDER_FEATURES, False, 1, False)
    step1, st1, _, _ = _build(hvd, REMAINDER_FEATURES, sharded, accum,
                              overlap)
    st0, losses0 = _run(step0, st0, X, y)
    st1, losses1 = _run(step1, st1, X, y)
    np.testing.assert_allclose(losses1, losses0, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(st0.params),
                    jax.tree_util.tree_leaves(st1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_zero_state_is_sharded_one_over_n(hvd, n_devices):
    """The memory claim, read off the live arrays: every param-shaped
    optimizer-state leaf is [world, shard] with a 1-row local shard."""
    step, state, X, y = _build(hvd, REMAINDER_FEATURES, True, 2, True)
    state, _ = step(state, X, y)
    schedule = state.opt_state.plan.schedule
    assert schedule.world == n_devices
    dev0 = jax.local_devices()[0]
    sharded_leaves = 0
    for leaf in jax.tree_util.tree_leaves(state.opt_state.inner):
        if leaf.ndim >= 1 and leaf.shape[0] == n_devices:
            sharded_leaves += 1
            local = [s for s in leaf.addressable_shards if s.device == dev0]
            assert local[0].data.shape[0] == 1  # one row of world rows
    assert sharded_leaves >= 2  # adamw: mu and nu at least

    # padded remainder: 161 params -> 168 = 8 * 21
    assert sum(schedule.padded_sizes) % n_devices == 0
    assert schedule.shard_sizes == tuple(
        p // n_devices for p in schedule.padded_sizes)

    # the accounting helper agrees with ~1/N of the replicated footprint
    n_params = sum(np.prod(np.shape(p)) for p in
                   jax.tree_util.tree_leaves(state.params))
    replicated = 2 * n_params * 4  # adamw mu+nu, f32
    assert zero.local_state_bytes(state.opt_state) < replicated / 2


def test_sharded_update_equals_full_update_inside_shard_map(hvd, n_devices):
    """zero.sharded_update == reduce-then-full-adam, leaf for leaf."""
    inner = optax.adam(0.1)
    params = {"w": jnp.arange(10.0) / 10, "b": jnp.ones((3,))}
    plan = zero.make_plan(params, op=hvd_api.Average)
    zstate0 = zero.init(inner, params, plan)
    full_state0 = inner.init(params)

    def f(zinner):
        r = collective.mesh_rank().astype(jnp.float32)
        grads = jax.tree_util.tree_map(
            lambda p: (r + 1.0) * jnp.ones_like(p), params)
        zst = zero.ZeroState(zinner, plan)
        updates, new_z = zero.sharded_update(inner, grads, zst, params)
        mean_grads = jax.tree_util.tree_map(
            lambda g: collective.allreduce(g, op=hvd_api.Average), grads)
        ref_updates, _ = inner.update(mean_grads, full_state0, params)
        return updates, ref_updates, new_z.inner

    zspecs = jax.tree_util.tree_map(
        lambda l: P("data") if (jnp.ndim(l) and
                                jnp.shape(l)[0] == n_devices) else P(),
        zstate0.inner)
    pspec = jax.tree_util.tree_map(lambda _: P(), params)
    out, ref, _ = jax.shard_map(
        f, mesh=hvd.mesh(), in_specs=(zspecs,),
        out_specs=(pspec, pspec, zspecs), check_vma=False)(zstate0.inner)
    for k in params:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(ref[k]),
                                   rtol=1e-5, atol=1e-7)


def test_zero_state_checkpoint_roundtrip(hvd, tmp_path):
    """ZeroState must survive the repo's own checkpoint path (flax
    msgpack knows it via the registered serialization handlers; the
    static plan is rebuilt from the restore target)."""
    from horovod_tpu import checkpoint

    step, state, X, y = _build(hvd, REMAINDER_FEATURES, True, 2, True)
    state, _ = step(state, X, y)
    checkpoint.write_checkpoint(str(tmp_path), 1, state.params,
                                opt_state=state.opt_state)
    target_step, st2, X2, y2 = _build(hvd, REMAINDER_FEATURES, True, 2, True)
    params2, opt2, _ = checkpoint.restore_checkpoint(
        str(tmp_path), 1, st2.params, opt_state=st2.opt_state)
    for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                    jax.tree_util.tree_leaves(opt2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert opt2.plan == state.opt_state.plan
    # and the restored state actually drives the next step
    st3 = st2.__class__(params=params2, opt_state=opt2,
                        batch_stats=st2.batch_stats, step=st2.step)
    target_step(st3, X2, y2)


def test_zero_plan_validates():
    with pytest.raises(ValueError, match="Sum or Average"):
        zero.make_plan({"w": jnp.ones(4)}, op=hvd_api.Adasum)
    with pytest.raises(ValueError, match="non-empty"):
        zero.make_plan({}, op=hvd_api.Average)


def test_distributed_optimizer_sharded_rejects_bad_combos():
    # the PR-6 contract: sharded_update COMPOSES with wire compression
    # (the old blanket refusal is gone) ...
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                      compression=hvd_api.Compression.fp16)
    assert tx.compression is hvd_api.Compression.bf16
    # ... but genuinely unsupported combos stay loud: a chunked quantizer
    # cannot ride Adasum's dot-product composition
    with pytest.raises(ValueError, match="Adasum"):
        hvd_api.DistributedOptimizer(optax.sgd(0.1), op=hvd_api.Adasum,
                                     compression=hvd_api.Compression.int8)
    with pytest.raises(ValueError, match="backward_passes_per_step"):
        hvd_api.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                     backward_passes_per_step=2)
    with pytest.raises(ValueError, match="Sum or Average"):
        hvd_api.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                     op=hvd_api.Adasum)


def test_make_train_step_pipeline_validations(hvd):
    model = MLP(features=(4, 3))
    with pytest.raises(ValueError, match="DistributedOptimizer"):
        training.make_train_step(model, optax.sgd(0.1), accum_steps=2)
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.1),
                                      backward_passes_per_step=2)
    with pytest.raises(ValueError, match="accum_steps"):
        training.make_train_step(model, tx, accum_steps=2)


def test_pipeline_rejects_indivisible_microbatch(hvd):
    step, state, X, y = _build(hvd, (6, 3), False, 3, True)
    with pytest.raises(ValueError, match="microbatch"):
        step(state, X, y)  # 32/8 = 4 per shard, not divisible by 3


def test_overlap_emits_reduce_scatter_not_allreduce(hvd):
    """The pipeline's exchange must be reduce-scatter (+ all-gather), not
    a post-hoc fused allreduce: one RS per bucket per microbatch in the
    compiled module."""
    step, state, X, y = _build(hvd, (6, 3), False, 2, True)
    hlo = step.lower(state, X, y).compile().as_text()
    assert "reduce-scatter" in hlo
