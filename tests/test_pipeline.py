"""Pipeline parallelism: the GPipe schedule must equal running the layer
stack sequentially on one device — forward AND gradients (reverse-mode
routes through the transposed ppermutes)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.pipeline import pipelined_forward, stack_params


class Layer(nn.Module):
    d: int

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(2 * self.d, use_bias=False)(x)
        return x + nn.Dense(self.d, use_bias=False)(nn.gelu(h))


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("stage",))


def _setup(rng, n_layers=4, d=8, batch=8):
    layer = Layer(d)
    x = jnp.asarray(rng.standard_normal((batch, d)), jnp.float32)
    trees = [layer.init(jax.random.PRNGKey(i), x)["params"]
             for i in range(n_layers)]
    block_fn = lambda p, v: layer.apply({"params": p}, v)  # noqa: E731
    return block_fn, stack_params(trees), x


def _oracle(block_fn, stacked, x):
    return jax.lax.scan(lambda c, p: (block_fn(p, c), None), x, stacked)[0]


@pytest.mark.parametrize("n_stages,n_layers,n_micro", [
    (4, 4, 4),   # one layer per stage
    (2, 4, 8),   # two layers per stage, more microbatches than stages
    (4, 8, 2),   # fewer microbatches than stages
])
def test_pipeline_matches_sequential(rng, n_stages, n_layers, n_micro):
    block_fn, stacked, x = _setup(rng, n_layers=n_layers)
    out = pipelined_forward(block_fn, stacked, x, mesh=_mesh(n_stages),
                            n_micro=n_micro)
    want = _oracle(block_fn, stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("n_stages,n_layers,n_micro", [
    (4, 4, 4),   # one layer per stage
    (2, 4, 8),   # two layers per stage, more microbatches than stages
    (4, 8, 2),   # fewer microbatches than stages (drain-tick clipping)
])
def test_pipeline_gradients_match(rng, n_stages, n_layers, n_micro):
    block_fn, stacked, x = _setup(rng, n_layers=n_layers)
    mesh = _mesh(n_stages)

    def pp_loss(params):
        return jnp.mean(pipelined_forward(block_fn, params, x, mesh=mesh,
                                          n_micro=n_micro) ** 2)

    def oracle_loss(params):
        return jnp.mean(_oracle(block_fn, params, x) ** 2)

    lp, gp = jax.value_and_grad(pp_loss)(stacked)
    lo, go = jax.value_and_grad(oracle_loss)(stacked)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-6)
    for path, leaf in jax.tree_util.tree_leaves_with_path(gp):
        want = dict(jax.tree_util.tree_leaves_with_path(go))[path]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want),
                                   rtol=2e-4, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(path))


def test_pipeline_composes_with_data_parallel(rng):
    """PP x DP on a (data x stage) mesh: each data slice pipelines its
    batch shard; stacked-param gradients come back psum'd over the data
    axis by the shard_map transpose — identical to the global oracle."""
    block_fn, stacked, x = _setup(rng, n_layers=4, batch=8)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                ("data", "stage"))

    def pp_loss(params):
        out = pipelined_forward(block_fn, params, x, mesh=mesh,
                                batch_axis="data", n_micro=2)
        return jnp.mean(out ** 2)

    def oracle_loss(params):
        return jnp.mean(_oracle(block_fn, params, x) ** 2)

    lp, gp = jax.value_and_grad(pp_loss)(stacked)
    lo, go = jax.value_and_grad(oracle_loss)(stacked)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-6)
    for path, leaf in jax.tree_util.tree_leaves_with_path(gp):
        want = dict(jax.tree_util.tree_leaves_with_path(go))[path]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want),
                                   rtol=2e-4, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(path))


class _NormLayer(nn.Module):
    """vjp of x/||x|| is NaN at x=0: the regression class for bubble
    seeding (a zeros-seeded schedule returns finite loss, NaN grads)."""

    @nn.compact
    def __call__(self, x):
        y = nn.Dense(x.shape[-1], use_bias=False)(x)
        return y / jnp.linalg.norm(y, axis=-1, keepdims=True)


def test_pipeline_grads_finite_for_norm_blocks(rng):
    layer = _NormLayer()
    x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    trees = [layer.init(jax.random.PRNGKey(i), x)["params"]
             for i in range(4)]
    stacked = stack_params(trees)
    block_fn = lambda p, v: layer.apply({"params": p}, v)  # noqa: E731
    mesh = _mesh(4)

    def pp_loss(params):
        return jnp.mean(
            pipelined_forward(block_fn, params, x, mesh=mesh) ** 2)

    def oracle_loss(params):
        return jnp.mean(_oracle(block_fn, params, x) ** 2)

    lp, gp = jax.value_and_grad(pp_loss)(stacked)
    lo, go = jax.value_and_grad(oracle_loss)(stacked)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-6)
    for path, leaf in jax.tree_util.tree_leaves_with_path(gp):
        assert np.isfinite(np.asarray(leaf)).all(), (
            f"NaN grads through bubble ticks: {jax.tree_util.keystr(path)}")
        want = dict(jax.tree_util.tree_leaves_with_path(go))[path]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want),
                                   rtol=2e-4, atol=1e-6)


def test_pipeline_rejects_indivisible_shapes(rng):
    block_fn, stacked, x = _setup(rng, n_layers=4, batch=8)
    with pytest.raises(ValueError, match="not divisible"):
        pipelined_forward(block_fn, stacked, x, mesh=_mesh(4), n_micro=3)
    with pytest.raises(ValueError, match="layers not divisible"):
        pipelined_forward(block_fn, stacked, x, mesh=_mesh(3), n_micro=4)
