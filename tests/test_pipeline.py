"""Pipeline parallelism: the GPipe and 1F1B schedules must equal running
the layer stack sequentially on one device — forward AND gradients
(GPipe via reverse-mode through the transposed ppermutes; 1F1B via its
explicit per-microbatch vjp schedule)."""

import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from horovod_tpu import compat
from horovod_tpu.parallel.pipeline import (_schedule_1f1b,
                                           pipeline_train_1f1b,
                                           pipelined_forward, stack_params)


class Layer(nn.Module):
    d: int

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(2 * self.d, use_bias=False)(x)
        return x + nn.Dense(self.d, use_bias=False)(nn.gelu(h))


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("stage",))


def _setup(rng, n_layers=4, d=8, batch=8):
    layer = Layer(d)
    x = jnp.asarray(rng.standard_normal((batch, d)), jnp.float32)
    trees = [layer.init(jax.random.PRNGKey(i), x)["params"]
             for i in range(n_layers)]
    block_fn = lambda p, v: layer.apply({"params": p}, v)  # noqa: E731
    return block_fn, stack_params(trees), x


def _oracle(block_fn, stacked, x):
    return jax.lax.scan(lambda c, p: (block_fn(p, c), None), x, stacked)[0]


@pytest.mark.parametrize("n_stages,n_layers,n_micro", [
    (4, 4, 4),   # one layer per stage
    (2, 4, 8),   # two layers per stage, more microbatches than stages
    (4, 8, 2),   # fewer microbatches than stages
])
def test_pipeline_matches_sequential(rng, n_stages, n_layers, n_micro):
    block_fn, stacked, x = _setup(rng, n_layers=n_layers)
    out = pipelined_forward(block_fn, stacked, x, mesh=_mesh(n_stages),
                            n_micro=n_micro)
    want = _oracle(block_fn, stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("n_stages,n_layers,n_micro", [
    (4, 4, 4),   # one layer per stage
    (2, 4, 8),   # two layers per stage, more microbatches than stages
    (4, 8, 2),   # fewer microbatches than stages (drain-tick clipping)
])
def test_pipeline_gradients_match(rng, n_stages, n_layers, n_micro):
    block_fn, stacked, x = _setup(rng, n_layers=n_layers)
    mesh = _mesh(n_stages)

    def pp_loss(params):
        return jnp.mean(pipelined_forward(block_fn, params, x, mesh=mesh,
                                          n_micro=n_micro) ** 2)

    def oracle_loss(params):
        return jnp.mean(_oracle(block_fn, params, x) ** 2)

    lp, gp = jax.value_and_grad(pp_loss)(stacked)
    lo, go = jax.value_and_grad(oracle_loss)(stacked)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-6)
    for path, leaf in jax.tree_util.tree_leaves_with_path(gp):
        want = dict(jax.tree_util.tree_leaves_with_path(go))[path]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want),
                                   rtol=2e-4, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(path))


def test_pipeline_composes_with_data_parallel(rng):
    """PP x DP on a (data x stage) mesh: each data slice pipelines its
    batch shard; stacked-param gradients come back psum'd over the data
    axis by the shard_map transpose — identical to the global oracle."""
    block_fn, stacked, x = _setup(rng, n_layers=4, batch=8)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                ("data", "stage"))

    def pp_loss(params):
        out = pipelined_forward(block_fn, params, x, mesh=mesh,
                                batch_axis="data", n_micro=2)
        return jnp.mean(out ** 2)

    def oracle_loss(params):
        return jnp.mean(_oracle(block_fn, params, x) ** 2)

    lp, gp = jax.value_and_grad(pp_loss)(stacked)
    lo, go = jax.value_and_grad(oracle_loss)(stacked)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-6)
    for path, leaf in jax.tree_util.tree_leaves_with_path(gp):
        want = dict(jax.tree_util.tree_leaves_with_path(go))[path]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want),
                                   rtol=2e-4, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(path))


class _NormLayer(nn.Module):
    """vjp of x/||x|| is NaN at x=0: the regression class for bubble
    seeding (a zeros-seeded schedule returns finite loss, NaN grads)."""

    @nn.compact
    def __call__(self, x):
        y = nn.Dense(x.shape[-1], use_bias=False)(x)
        return y / jnp.linalg.norm(y, axis=-1, keepdims=True)


def test_pipeline_grads_finite_for_norm_blocks(rng):
    layer = _NormLayer()
    x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    trees = [layer.init(jax.random.PRNGKey(i), x)["params"]
             for i in range(4)]
    stacked = stack_params(trees)
    block_fn = lambda p, v: layer.apply({"params": p}, v)  # noqa: E731
    mesh = _mesh(4)

    def pp_loss(params):
        return jnp.mean(
            pipelined_forward(block_fn, params, x, mesh=mesh) ** 2)

    def oracle_loss(params):
        return jnp.mean(_oracle(block_fn, params, x) ** 2)

    lp, gp = jax.value_and_grad(pp_loss)(stacked)
    lo, go = jax.value_and_grad(oracle_loss)(stacked)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-6)
    for path, leaf in jax.tree_util.tree_leaves_with_path(gp):
        assert np.isfinite(np.asarray(leaf)).all(), (
            f"NaN grads through bubble ticks: {jax.tree_util.keystr(path)}")
        want = dict(jax.tree_util.tree_leaves_with_path(go))[path]
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(want),
                                   rtol=2e-4, atol=1e-6)


def test_pipeline_rejects_indivisible_shapes(rng):
    block_fn, stacked, x = _setup(rng, n_layers=4, batch=8)
    with pytest.raises(ValueError, match="not divisible"):
        pipelined_forward(block_fn, stacked, x, mesh=_mesh(4), n_micro=3)
    with pytest.raises(ValueError, match="layers not divisible"):
        pipelined_forward(block_fn, stacked, x, mesh=_mesh(3), n_micro=4)


# ---- 1F1B -----------------------------------------------------------------

def _grads_match(got, want, **kw):
    wm = dict(jax.tree_util.tree_leaves_with_path(want))
    for path, leaf in jax.tree_util.tree_leaves_with_path(got):
        np.testing.assert_allclose(np.asarray(leaf), np.asarray(wm[path]),
                                   err_msg=jax.tree_util.keystr(path), **kw)


@pytest.mark.parametrize("n_stages,n_micro", [(2, 2), (2, 8), (4, 4),
                                              (4, 8), (4, 16)])
def test_1f1b_schedule_properties(n_stages, n_micro):
    """Every stage forwards and backwards each microbatch exactly once,
    in-flight stays within min(n_micro, n_stages - s), and the total
    tick count is the classic 2*(n_micro + n_stages - 1)."""
    fwd, bwd = _schedule_1f1b(n_stages, n_micro)
    assert fwd.shape[0] == 2 * (n_micro + n_stages - 1)
    for s in range(n_stages):
        assert sorted(m for m in fwd[:, s] if m >= 0) == list(range(n_micro))
        assert sorted(m for m in bwd[:, s] if m >= 0) == list(range(n_micro))
        inflight = 0
        peak = 0
        for t in range(fwd.shape[0]):
            inflight += int(fwd[t, s] >= 0) - int(bwd[t, s] >= 0)
            peak = max(peak, inflight)
        assert peak <= min(n_micro, n_stages - s), (s, peak)


@pytest.mark.parametrize("n_stages,n_layers,n_micro", [
    (4, 4, 4),   # one layer per stage
    (2, 4, 8),   # two layers per stage, ring-buffer reuse (M > S)
    (4, 8, 2),   # fewer microbatches than stages
    (4, 4, 16),  # deep microbatching
])
def test_1f1b_matches_sequential(rng, n_stages, n_layers, n_micro):
    block_fn, stacked, x = _setup(rng, n_layers=n_layers, batch=16)
    mesh = _mesh(n_stages)
    loss, grads = pipeline_train_1f1b(
        block_fn, stacked, x, lambda y, m: jnp.sum(y ** 2), mesh=mesh,
        n_micro=n_micro)
    lo, go = jax.value_and_grad(
        lambda p: jnp.sum(_oracle(block_fn, p, x) ** 2))(stacked)
    np.testing.assert_allclose(float(loss), float(lo), rtol=1e-5)
    _grads_match(grads, go, rtol=2e-4, atol=1e-5)


def test_1f1b_input_grad_matches(rng):
    block_fn, stacked, x = _setup(rng, n_layers=4, batch=16)
    mesh = _mesh(4)
    loss, grads, dh = pipeline_train_1f1b(
        block_fn, stacked, x, lambda y, m: jnp.sum(y ** 2), mesh=mesh,
        n_micro=4, with_input_grad=True)
    _, pull = jax.vjp(lambda v: jnp.sum(_oracle(block_fn, stacked, v) ** 2),
                      x)
    (want,) = pull(jnp.ones(()))
    np.testing.assert_allclose(np.asarray(dh), np.asarray(want),
                               rtol=2e-4, atol=1e-5)


@pytest.mark.skipif(not compat.NATIVE_VMA, reason=(
    "1F1B composed with a data axis relies on the vma pcast<->psum AD "
    "transpose pair; pre-vma jax has no faithful equivalent"))
def test_1f1b_composes_with_data_parallel(rng):
    block_fn, stacked, x = _setup(rng, n_layers=4, batch=16)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4),
                ("data", "stage"))
    loss, grads = pipeline_train_1f1b(
        block_fn, stacked, x, lambda y, m: jnp.sum(y ** 2), mesh=mesh,
        n_micro=2, batch_axis="data")
    lo, go = jax.value_and_grad(
        lambda p: jnp.sum(_oracle(block_fn, p, x) ** 2))(stacked)
    np.testing.assert_allclose(float(loss), float(lo), rtol=1e-5)
    _grads_match(grads, go, rtol=2e-4, atol=1e-5)


def _tp_block(p, x):
    """Megatron column/row pair, vma-correct: pcast-to-varying feeds the
    column matmul, psum closes the row product (their transposes — psum
    and pcast — are what 1F1B's inner vjp relies on)."""
    xv = jax.lax.pcast(x, "model", to="varying")
    return x + jax.lax.psum(jax.nn.gelu(xv @ p["w1"]) @ p["w2"], "model")


def _tp_dense(p, x):
    return x + jax.nn.gelu(x @ p["w1"]) @ p["w2"]


def _tp_setup(rng, d=8, ff=16, n_layers=4):
    trees = [{"w1": jnp.asarray(rng.standard_normal((d, ff)) / d ** 0.5,
                                jnp.float32),
              "w2": jnp.asarray(rng.standard_normal((ff, d)) / ff ** 0.5,
                                jnp.float32)} for _ in range(n_layers)]
    x = jnp.asarray(rng.standard_normal((16, d)), jnp.float32)
    specs = {"w1": P(None, "model"), "w2": P("model", None)}
    return stack_params(trees), x, specs


@pytest.mark.skipif(not compat.NATIVE_VMA, reason=(
    "1F1B composed with a data axis relies on the vma pcast<->psum AD "
    "transpose pair; pre-vma jax has no faithful equivalent"))
def test_1f1b_composes_with_tensor_and_data_parallel(rng):
    """PP x TP x DP on a (data, stage, model) mesh: loss and grads equal
    the single-device dense oracle."""
    stacked, x, specs = _tp_setup(rng)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "stage", "model"))
    loss, grads = pipeline_train_1f1b(
        _tp_block, stacked, x, lambda y, m: jnp.sum(y ** 2), mesh=mesh,
        n_micro=4, batch_axis="data", param_specs=specs)
    lo, go = jax.value_and_grad(
        lambda p: jnp.sum(_oracle(_tp_dense, p, x) ** 2))(stacked)
    np.testing.assert_allclose(float(loss), float(lo), rtol=1e-5)
    _grads_match(grads, go, rtol=2e-3, atol=1e-4)


def test_gpipe_composes_with_tensor_parallel(rng):
    """param_specs on the GPipe path: the shard_map AD transpose places
    the TP backward collectives."""
    stacked, x, specs = _tp_setup(rng)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "stage", "model"))

    def pp_loss(p):
        out = pipelined_forward(_tp_block, p, x, mesh=mesh, n_micro=2,
                                batch_axis="data", param_specs=specs)
        return jnp.sum(out ** 2)

    lp, gp = jax.value_and_grad(pp_loss)(stacked)
    lo, go = jax.value_and_grad(
        lambda p: jnp.sum(_oracle(_tp_dense, p, x) ** 2))(stacked)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-5)
    _grads_match(gp, go, rtol=2e-3, atol=1e-4)


def test_gpipe_remat_matches_and_cuts_memory(rng):
    """remat=True: same loss/grads as plain GPipe, with the scan's
    saved residuals cut to per-layer boundaries."""
    block_fn, stacked, x = _setup(rng, n_layers=4, batch=16)
    mesh = _mesh(4)

    def loss(p, remat):
        out = pipelined_forward(block_fn, p, x, mesh=mesh, n_micro=4,
                                remat=remat)
        return jnp.sum(out ** 2)

    lp, gp = jax.value_and_grad(lambda p: loss(p, True))(stacked)
    lo, go = jax.value_and_grad(lambda p: loss(p, False))(stacked)
    np.testing.assert_allclose(float(lp), float(lo), rtol=1e-6)
    _grads_match(gp, go, rtol=2e-4, atol=1e-6)

    # memory: at wide layers + many microbatches, remat residuals are
    # a fraction of the full-activation residuals
    d, L, M = 128, 4, 16

    class Wide(nn.Module):
        @nn.compact
        def __call__(self, v):
            hdn = nn.Dense(4 * d, use_bias=False)(v)
            return v + nn.Dense(d, use_bias=False)(nn.gelu(hdn))

    layer = Wide()
    x0 = jnp.ones((8, d), jnp.float32)
    trees = [layer.init(jax.random.PRNGKey(i), x0)["params"]
             for i in range(L)]
    st = stack_params(trees)
    blk = lambda p, v: layer.apply({"params": p}, v)  # noqa: E731
    xw = jnp.ones((32 * M, d), jnp.float32)

    def mem(remat):
        f = jax.jit(jax.value_and_grad(lambda p: jnp.sum(pipelined_forward(
            blk, p, xw, mesh=mesh, n_micro=M, remat=remat) ** 2)))
        m = f.lower(st).compile().memory_analysis()
        return None if m is None else m.temp_size_in_bytes

    m_plain, m_remat = mem(False), mem(True)
    if m_plain is None:
        pytest.skip("backend reports no memory analysis")
    assert m_remat < m_plain, (m_remat, m_plain)


def test_1f1b_memory_bounded_vs_gpipe(rng):
    """THE point of 1F1B: activation memory O(n_stages), not O(n_micro).
    At n_micro=32 the compiled 1F1B step's temporaries must be far below
    GPipe-AD's (which saves residuals for every schedule tick)."""
    d, L, S, M = 128, 4, 4, 32

    class Wide(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.Dense(4 * d, use_bias=False)(x)
            return x + nn.Dense(d, use_bias=False)(nn.gelu(h))

    layer = Wide()
    x0 = jnp.ones((8, d), jnp.float32)
    trees = [layer.init(jax.random.PRNGKey(i), x0)["params"]
             for i in range(L)]
    stacked = stack_params(trees)
    block_fn = lambda p, v: layer.apply({"params": p}, v)  # noqa: E731
    mesh = _mesh(S)
    x = jnp.ones((64 * M, d), jnp.float32)

    gp = jax.jit(jax.value_and_grad(lambda p: jnp.sum(pipelined_forward(
        block_fn, p, x, mesh=mesh, n_micro=M) ** 2)))
    f1 = jax.jit(lambda p: pipeline_train_1f1b(
        block_fn, p, x, lambda y, m: jnp.sum(y ** 2), mesh=mesh,
        n_micro=M))
    mg = gp.lower(stacked).compile().memory_analysis()
    m1 = f1.lower(stacked).compile().memory_analysis()
    if mg is None or m1 is None:
        pytest.skip("backend reports no memory analysis")
    # measured: ~259 MiB (GPipe) vs ~6 MiB (1F1B); 4x margin
    assert m1.temp_size_in_bytes * 4 < mg.temp_size_in_bytes, (
        m1.temp_size_in_bytes, mg.temp_size_in_bytes)


@pytest.mark.skipif("HVD_PERF_TESTS" not in __import__("os").environ,
                    reason="wall-clock perf assertion: opt-in via "
                           "HVD_PERF_TESTS=1 (flaky on loaded machines)")
def test_1f1b_throughput_beats_gpipe(rng):
    """At n_micro=8 on the virtual mesh, the explicitly scheduled step
    outruns differentiating the GPipe scan (measured ~2.8x; assert a
    conservative margin to stay robust to CI noise)."""
    d, L, S, M = 128, 4, 4, 8

    class Wide(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.Dense(4 * d, use_bias=False)(x)
            return x + nn.Dense(d, use_bias=False)(nn.gelu(h))

    layer = Wide()
    x0 = jnp.ones((8, d), jnp.float32)
    trees = [layer.init(jax.random.PRNGKey(i), x0)["params"]
             for i in range(L)]
    stacked = stack_params(trees)
    block_fn = lambda p, v: layer.apply({"params": p}, v)  # noqa: E731
    mesh = _mesh(S)
    x = jnp.ones((64 * M, d), jnp.float32)

    gp = jax.jit(jax.value_and_grad(lambda p: jnp.sum(pipelined_forward(
        block_fn, p, x, mesh=mesh, n_micro=M) ** 2)))
    f1 = jax.jit(lambda p: pipeline_train_1f1b(
        block_fn, p, x, lambda y, m: jnp.sum(y ** 2), mesh=mesh,
        n_micro=M))

    def timeit(fn):
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), fn(stacked))
        t0 = time.perf_counter()
        for _ in range(5):
            r = fn(stacked)
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), r)
        return time.perf_counter() - t0

    t_gp, t_f1 = timeit(gp), timeit(f1)
    assert t_f1 < t_gp * 1.2, (t_f1, t_gp)
