"""Examples as smoke tests (reference CI pattern: examples run under
mpirun/horovodrun in the Buildkite pipeline, gen-pipeline.sh:127-168)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


def _run(cmd, extra_env=None, timeout=300, virtual_mesh=False):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if virtual_mesh:  # the standard 8-device CPU mesh recipe
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["PALLAS_AXON_POOL_IPS"] = ""
    env.update(extra_env or {})
    rv = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        timeout=timeout, cwd=REPO)
    assert rv.returncode == 0, rv.stdout + "\n" + rv.stderr
    return rv.stdout


def test_jax_mnist_example():
    out = _run([sys.executable, "examples/jax_mnist.py"],
               virtual_mesh=True)
    assert "done" in out


def test_pytorch_mnist_example_under_hvdrun():
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                sys.executable, "examples/pytorch_mnist.py"])


@pytest.mark.slow  # ~80 s CPU: full slope-window bench subprocess
def test_synthetic_benchmark_tiny():
    out = _run([sys.executable, "examples/jax_synthetic_benchmark.py",
                "--model", "resnet18", "--batch-size", "2",
                "--image-size", "32", "--num-warmup-batches", "1",
                "--num-batches-per-iter", "2", "--num-iters", "2"],
               virtual_mesh=True)
    assert "Img/sec per chip" in out


def test_elastic_train_example(tmp_path):
    """The elastic example (ISSUE 1): commit/restore under
    @hvd.elastic.run, CPU-safe, resumes from the committed step when
    re-run after an interruption."""
    env = {"ELASTIC_CKPT_DIR": str(tmp_path / "ck")}
    out = _run([sys.executable, "examples/elastic_train.py"],
               extra_env=env, virtual_mesh=True)
    assert "done at step 30" in out
    # second run starts from the final committed step: no retraining
    out2 = _run([sys.executable, "examples/elastic_train.py"],
                extra_env=env, virtual_mesh=True)
    assert "done at step 30" in out2
    assert "step   1" not in out2


def test_imagenet_resnet50_example_under_hvdrun(tmp_path):
    """The real-data flagship example (reference:
    pytorch_imagenet_resnet50.py): per-rank disjoint sharding via
    DistributedSampler, fused eager gradient averaging, rank-0
    checkpointing + broadcast resume — at smoke scale with the
    synthetic-data fallback."""
    ckpt = str(tmp_path / "ck")
    smoke = ["--depth", "18", "--num-filters", "4", "--image-size", "32",
             "--num-classes", "4", "--num-examples", "16",
             "--batch-size", "2", "--ckpt-dir", ckpt]
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                sys.executable, "examples/jax_imagenet_resnet50.py",
                "--epochs", "1"] + smoke)
    # each rank sees 8 of 16 examples; together a full epoch
    assert "(16 examples/epoch across 2 ranks)" in out
    assert "epoch 1" in out
    # resume leg: restores epoch 1, runs epoch 2
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                sys.executable, "examples/jax_imagenet_resnet50.py",
                "--epochs", "2"] + smoke)
    assert "resuming from epoch 1" in out and "epoch 2" in out


def test_checkpoint_resume_example(tmp_path):
    ckpt = str(tmp_path / "ck")
    # first leg: 4 epochs
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                sys.executable, "examples/jax_checkpoint_resume.py",
                "--ckpt-dir", ckpt, "--epochs", "4"])
    assert "epoch 4" in out
    # second leg resumes at 4 and finishes
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                sys.executable, "examples/jax_checkpoint_resume.py",
                "--ckpt-dir", ckpt, "--epochs", "8"])
    assert "resuming from step 4" in out and "epoch 8" in out


def test_lm_seq_parallel_example():
    out = _run([sys.executable, "examples/jax_lm_seq_parallel.py",
                "--steps", "15", "--seq-len", "128"],
               virtual_mesh=True)
    assert "data x seq" in out


def test_lm_tensor_parallel_example():
    out = _run([sys.executable, "examples/jax_lm_tensor_parallel.py",
                "--steps", "6", "--d-model", "32", "--seq-len", "32"],
               virtual_mesh=True)
    assert "d_ff kernel sharding: PartitionSpec(None, 'model')" in out
    assert "done" in out


def test_lm_moe_example():
    out = _run([sys.executable, "examples/jax_lm_moe.py",
                "--steps", "6", "--d-model", "32", "--seq-len", "32"],
               virtual_mesh=True)
    assert "w_in sharding: PartitionSpec('expert'" in out
    assert "done" in out


@pytest.mark.slow  # ~80 s CPU: weak-scaling sweep subprocess
def test_scaling_harness_tiny():
    out = _run([sys.executable, "bench_scaling.py", "--model", "resnet18",
                "--batch-size", "2", "--image-size", "32",
                "--num-warmup", "1", "--num-iters", "2"],
               virtual_mesh=True)
    assert "weak_scaling_efficiency" in out


def test_hierarchical_example():
    out = _run([sys.executable, "examples/jax_hierarchical_allreduce.py",
                "--steps", "3"],
               virtual_mesh=True)
    assert "reduce-scatter" in out and "done" in out


def test_lm_benchmark_tiny():
    out = _run([sys.executable, "examples/jax_lm_benchmark.py",
                "--data", "2", "--seq", "4", "--steps", "2", "--warmup", "1",
                "--layers", "2", "--d-model", "64", "--heads", "4",
                "--vocab", "128", "--seq-len", "512", "--batch", "4"],
               virtual_mesh=True)
    assert "transformer_lm_tokens_per_sec" in out


def _has_module(name):
    import importlib.machinery
    try:
        return importlib.machinery.PathFinder.find_spec(name) is not None
    except (ImportError, ValueError):
        return False


def test_tf_keras_mnist_example_under_hvdrun():
    """The reference's tensorflow2_keras_mnist CI smoke: 2 processes
    under hvdrun, DistributedOptimizer + callbacks + rank-0 checkpoint
    (reference gen-pipeline.sh:127-168 example-run pattern)."""
    import pytest
    if not _has_module("tensorflow"):
        pytest.skip("tensorflow not installed")
    import tempfile
    ckpt_dir = tempfile.mkdtemp()
    env = {"TF_CPP_MIN_LOG_LEVEL": "3", "CKPT_DIR": ckpt_dir}
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                "-H", "localhost:2", sys.executable,
                "examples/tensorflow2_keras_mnist.py", "--epochs", "1",
                "--samples", "64"], extra_env=env, timeout=600)
    assert out.count("done") == 2
    assert "checkpoints: ['ckpt-1.keras']" in out
    # resume conventions: a second run against the same CKPT_DIR must
    # discover epoch 1, broadcast it, and continue to epoch 2
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                "-H", "localhost:2", sys.executable,
                "examples/tensorflow2_keras_mnist.py", "--epochs", "2",
                "--samples", "64"], extra_env=env, timeout=600)
    assert out.count("done") == 2
    assert "resuming from epoch 1" in out
    assert "'ckpt-2.keras'" in out


def test_mxnet_mnist_example_under_hvdrun():
    """The reference's mxnet_mnist CI smoke (runs in the real-mxnet CI
    job; skipped where mxnet has no wheel, e.g. this py3.12 image)."""
    import pytest
    if not _has_module("mxnet"):
        pytest.skip("mxnet not installed")
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                "-H", "localhost:2", sys.executable,
                "examples/mxnet_mnist.py", "--epochs", "1",
                "--samples", "64"], timeout=600)
    assert out.count("done") == 2


def test_tf2_custom_loop_example_under_hvdrun():
    """The reference's tensorflow2_mnist CI smoke: custom GradientTape
    loop with DistributedGradientTape, post-step-1 variable broadcast,
    rank-0 checkpoint, weight-digest sync proof."""
    import pytest
    if not _has_module("tensorflow"):
        pytest.skip("tensorflow not installed")
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                "-H", "localhost:2", sys.executable,
                "examples/tensorflow2_mnist.py", "--steps", "12"],
               extra_env={"TF_CPP_MIN_LOG_LEVEL": "3"}, timeout=600)
    assert out.count("done") == 2
    assert "checkpoint: model.weights.h5" in out


def test_pytorch_synthetic_benchmark_under_hvdrun():
    """The reference's pytorch_synthetic_benchmark CI smoke, 2-proc on
    the host plane."""
    out = _run([sys.executable, "-m", "horovod_tpu.run", "-np", "2",
                "-H", "localhost:2", sys.executable,
                "examples/pytorch_synthetic_benchmark.py",
                "--num-iters", "2", "--num-batches-per-iter", "3"],
               timeout=600)
    assert out.count("done") == 2
    assert "Total img/sec on 2 processes" in out
