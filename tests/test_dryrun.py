"""The driver-facing dry run must PROVE parity, not just finiteness:
every parallelism section compares its step against a single-device
oracle replay (VERDICT r4 #6). These tests pin both directions — a clean
run passes, a deliberately broken sharding fails the parity gate."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import __graft_entry__ as graft  # noqa: E402


def test_dryrun_body_2dev_passes():
    graft._dryrun_body(2)


def test_dryrun_parity_catches_broken_sharding(monkeypatch):
    """Break the hierarchical allreduce (sum where average belongs — a
    classic wrong-divisor sharding bug): the updated params diverge from
    the single-device oracle and the parity assertion must fire."""
    from horovod_tpu.parallel import hierarchical as hier

    real = hier.hierarchical_allreduce

    def broken(x, ici_axes=("data",), dcn_axis="dcn", op="average"):
        del op  # drop the divisor: gradients arrive size-times too big
        return real(x, ici_axes=ici_axes, dcn_axis=dcn_axis, op="sum")

    monkeypatch.setattr(hier, "hierarchical_allreduce", broken)
    with pytest.raises(AssertionError, match="oracle"):
        graft._dryrun_body(2)
