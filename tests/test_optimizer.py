"""DistributedOptimizer / distributed_grad / broadcast / join tests.

Reference pattern: the optimizer tests in test/test_torch.py (grad averaging
across ranks, broadcast_parameters, broadcast_optimizer_state) and the Join
zero-fill semantics (controller.cc:209-220)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_api
from horovod_tpu.ops import collective


def test_distributed_grad_averages(hvd, n_devices):
    def loss(w, x):
        return jnp.sum(w * x)

    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        w = jnp.ones((4,))
        x = (r + 1) * jnp.ones((4,))  # per-shard data
        g = hvd_api.distributed_grad(loss)(w, x)
        return g

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(), out_specs=P(),
                        check_vma=False)()
    expected = np.mean(np.arange(1, n_devices + 1))
    np.testing.assert_allclose(out, expected * np.ones((4,)), rtol=1e-6)


def test_distributed_optimizer_step_equals_mean_grad_sgd(hvd, n_devices):
    lr = 0.1
    tx = hvd_api.DistributedOptimizer(optax.sgd(lr))

    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        w = jnp.ones((3,))
        local_grad = (r + 1) * jnp.ones((3,))
        state = tx.init(w)
        updates, _ = tx.update(local_grad, state, w)
        return optax.apply_updates(w, updates)

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(), out_specs=P(),
                        check_vma=False)()
    mean_g = np.mean(np.arange(1, n_devices + 1))
    np.testing.assert_allclose(out, 1.0 - lr * mean_g, rtol=1e-6)


def test_distributed_optimizer_training_converges(hvd, n_devices):
    """End-to-end: per-shard data, replicated params, SPMD training step.
    This is the Horovod programming model (local grads + allreduce) compiled
    into one XLA program — the 'minimum end-to-end slice' of SURVEY.md §7."""
    rng = np.random.default_rng(0)
    w_true = rng.standard_normal(8).astype(np.float32)
    X = rng.standard_normal((n_devices * 16, 8)).astype(np.float32)
    y = X @ w_true

    tx = hvd_api.DistributedOptimizer(optax.adam(0.1))

    def local_loss(w, xb, yb):
        pred = xb @ w
        return jnp.mean((pred - yb) ** 2)

    def step(w, opt_state, xb, yb):
        g = jax.grad(local_loss)(w, xb, yb)  # local gradient
        updates, opt_state = tx.update(g, opt_state, w)  # allreduce inside
        return optax.apply_updates(w, updates), opt_state

    w0 = jnp.zeros((8,))
    opt_state0 = tx.init(w0)

    sharded_step = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(),
        in_specs=(P(), jax.tree_util.tree_map(lambda _: P(), opt_state0),
                  P("data"), P("data")),
        out_specs=(P(), jax.tree_util.tree_map(lambda _: P(), opt_state0)),
        check_vma=False))

    w, opt_state = w0, opt_state0
    for _ in range(200):
        w, opt_state = sharded_step(w, opt_state, X, y)
    np.testing.assert_allclose(np.asarray(w), w_true, atol=1e-2)


def test_broadcast_variables(hvd, n_devices):
    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        params = {"w": (r + 1) * jnp.ones((4,)), "b": r * jnp.ones((2,))}
        return hvd_api.broadcast_variables(params, root_rank=0)

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                        out_specs={"w": P(), "b": P()}, check_vma=False)()
    np.testing.assert_allclose(out["w"], np.ones((4,)))
    np.testing.assert_allclose(out["b"], np.zeros((2,)))


def test_broadcast_optimizer_state(hvd, n_devices):
    tx = optax.adam(1e-3)

    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        w = (r + 1) * jnp.ones((3,))
        state = tx.init(w)
        state = jax.tree_util.tree_map(
            lambda x: x + r if jnp.issubdtype(x.dtype, jnp.floating) else x,
            state)
        return hvd_api.broadcast_optimizer_state(state, root_rank=0)

    state0 = tx.init(jnp.ones((3,)))
    specs = jax.tree_util.tree_map(lambda _: P(), state0)
    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(), out_specs=specs,
                        check_vma=False)()
    # root is rank 0 whose floats were +0 -> identical to fresh init
    ref_leaves = jax.tree_util.tree_leaves(state0)
    out_leaves = jax.tree_util.tree_leaves(out)
    for a, b in zip(out_leaves, ref_leaves):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32))


def test_join_uneven_data(hvd, n_devices):
    """Shards beyond rank 2 have exhausted data: mean over active only
    (zero-fill semantics of the reference Join op)."""
    n_active = 3

    def f():
        r = collective.mesh_rank()
        active = r < n_active
        g = {"w": (r + 1).astype(jnp.float32) * jnp.ones((4,))}
        reduced, count = hvd_api.join(g, active)
        return reduced, count

    out, count = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                               out_specs=({"w": P()}, P()),
                               check_vma=False)()
    assert float(count) == n_active
    expected = np.mean(np.arange(1, n_active + 1))
    np.testing.assert_allclose(out["w"], expected * np.ones((4,)), rtol=1e-6)


def test_allreduce_metrics(hvd, n_devices):
    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        return hvd_api.allreduce_metrics({"loss": r, "acc": 2 * r})

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                        out_specs={"loss": P(), "acc": P()},
                        check_vma=False)()
    mean_r = np.mean(np.arange(n_devices))
    np.testing.assert_allclose(out["loss"], mean_r)
    np.testing.assert_allclose(out["acc"], 2 * mean_r)


def test_allreduce_metrics_sum_keeps_int_dtype(hvd, n_devices):
    """op=Sum totals int-valued metrics exactly in their own dtype
    (sample counts stay ints); Average still yields the fp32 mean."""
    from horovod_tpu.ops.reduction import Sum

    def f():
        n = collective.mesh_rank().astype(jnp.int32) + 1
        return hvd_api.allreduce_metrics({"count": n}, op=Sum)

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(),
                        out_specs={"count": P()}, check_vma=False)()
    assert out["count"].dtype == jnp.int32
    assert int(out["count"]) == n_devices * (n_devices + 1) // 2


def test_backward_passes_per_step(hvd, n_devices):
    tx = hvd_api.DistributedOptimizer(optax.sgd(1.0),
                                      backward_passes_per_step=2)

    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        w = jnp.zeros((2,))
        state = tx.init(w)
        g = (r + 1) * jnp.ones((2,))
        u1, state = tx.update(g, state, w)
        w = optax.apply_updates(w, u1)
        u2, state = tx.update(g, state, w)
        w = optax.apply_updates(w, u2)
        return w

    out = jax.shard_map(f, mesh=hvd.mesh(), in_specs=(), out_specs=P(),
                        check_vma=False)()
    # after 2 micro-steps: one real step with the mean over accumulated grads
    mean_g = np.mean(np.arange(1, n_devices + 1))
    np.testing.assert_allclose(np.asarray(out), -mean_g * np.ones((2,)),
                               rtol=1e-6)
