"""Test harness: 8 virtual CPU devices standing in for an 8-chip TPU slice.

Reference test strategy (SURVEY.md §4): everything end-to-end through the
Python API with small world sizes. Here the "world" is a virtual 8-device
mesh (``--xla_force_host_platform_device_count=8``), matching how the driver
dry-runs the multi-chip path. Multi-process controller/launcher tests spawn
real localhost processes and don't need devices at all.
"""

import os

# Must happen before jax is imported anywhere.
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # disable the axon TPU plugin hook
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache, shared BY INHERITANCE with every
# subprocess the suite spawns (elastic workers, hvdrun example runs, the
# dryrun's virtual-mesh subprocess): those re-compile the same small
# models over and over, and with the whole suite actually exercising the
# compiled data plane the repeated compiles dominate suite wall-time.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/horovod_tpu_test_xla_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

import jax  # noqa: E402

# The axon sitecustomize may already have forced jax_platforms=axon,cpu;
# override it before any backend is initialized.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def n_devices():
    import jax
    return len(jax.devices())


@pytest.fixture()
def hvd():
    """An initialized horovod_tpu with a fresh 1-D mesh."""
    import horovod_tpu as hvd_mod
    hvd_mod.shutdown()
    hvd_mod.init()
    yield hvd_mod
    hvd_mod.shutdown()


@pytest.fixture()
def hvd2d():
    """An initialized horovod_tpu with a 2-D (dcn=2, data=4) mesh."""
    import horovod_tpu as hvd_mod
    hvd_mod.shutdown()
    hvd_mod.init(num_slices=2)
    yield hvd_mod
    hvd_mod.shutdown()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
