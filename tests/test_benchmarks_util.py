"""Unit tests for the shared timing scaffold (utils/benchmarks.py) —
the measurement discipline every bench path rides (BENCH_NOTES.md)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.utils import benchmarks


def test_window_time_is_a_float_with_flag():
    t = benchmarks.WindowTime(1.5)
    assert t == 1.5 and t + 0.5 == 2.0
    assert t.upper_bound is False
    b = benchmarks.WindowTime(2.0, upper_bound=True)
    assert b.upper_bound is True
    assert isinstance(b * 2, float)


def test_sync_forces_scalar_readback():
    out = benchmarks.sync({"a": jnp.arange(4.0)})
    assert isinstance(out, float) and out == 0.0


def test_slope_window_measures_per_iteration_cost():
    """A step with a known sleep: the slope (difference of windows)
    must recover the per-iteration cost, cancelling fixed overhead."""
    def step(state):
        time.sleep(0.01)
        return state + 1, jnp.asarray(float(state))

    dt, state = benchmarks.slope_window(step, 0, iters=5, base_iters=1)
    assert isinstance(dt, benchmarks.WindowTime)
    assert not dt.upper_bound
    assert 0.03 < dt < 0.3  # ~5 * 10 ms, generous bounds for CI noise
    # state threads through every call: one attempt = 7 calls, a single
    # jitter-inversion retry = 14 (retry is legal, a THIRD is not)
    assert state in (7, 14)


def test_slope_window_inverted_marks_upper_bound():
    """When the 'work' is pure jitter (longer window measured FASTER),
    the fallback reports the full window and FLAGS it — bound samples
    must be distinguishable from measurements (ADVICE r4)."""
    calls = {"n": 0}

    def step(state):
        calls["n"] += 1
        # calls 1 and 5 are the two BASE windows (attempt + retry):
        # making only those slow guarantees both inversions
        time.sleep(0.03 if calls["n"] in (1, 5) else 0.0)
        return state, jnp.asarray(0.0)

    with pytest.warns(UserWarning, match="inverted twice"):
        dt, _ = benchmarks.slope_window(step, 0, iters=2, base_iters=1)
    assert dt.upper_bound is True
    assert dt > 0


def test_repeat_throughput_propagates_window_times():
    def step(state, images, labels):
        return state, jnp.asarray(0.0)

    imgs = np.zeros((4, 1))
    runs = benchmarks.repeat_throughput(step, 0, imgs, None, warmup=0,
                                        iters=3, repeats=2)
    assert len(runs) == 2
    for rate, dt in runs:
        assert isinstance(dt, benchmarks.WindowTime)
        assert rate > 0
