"""Unit tests for the shared timing scaffold (utils/benchmarks.py) —
the measurement discipline every bench path rides (BENCH_NOTES.md)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.utils import benchmarks


def test_window_time_is_a_float_with_flag():
    t = benchmarks.WindowTime(1.5)
    assert t == 1.5 and t + 0.5 == 2.0
    assert t.upper_bound is False
    assert t.asymmetric is False
    b = benchmarks.WindowTime(2.0, upper_bound=True)
    assert b.upper_bound is True
    a = benchmarks.WindowTime(2.0, asymmetric=True)
    assert a.asymmetric is True
    assert isinstance(b * 2, float)


def test_sync_forces_scalar_readback():
    out = benchmarks.sync({"a": jnp.arange(4.0)})
    assert isinstance(out, float) and out == 0.0


def test_slope_window_measures_per_iteration_cost():
    """A step with a known sleep: the median pairwise slope across the
    interleaved windows must recover the per-iteration cost, cancelling
    fixed overhead. A warmup sync first: pending async work left by
    earlier tests in the process must drain OUTSIDE the timed windows
    (the old single base/full pair let it deflate the slope — the
    reproducible suite failure, VERDICT r5 Weak #1)."""
    benchmarks.sync(jnp.zeros(()))  # warmup: flush pending device work

    def step(state):
        time.sleep(0.01)
        return state + 1, jnp.asarray(float(state))

    dt, state = benchmarks.slope_window(step, 0, iters=5, base_iters=1)
    assert isinstance(dt, benchmarks.WindowTime)
    assert not dt.upper_bound
    assert 0.03 < dt < 0.3  # ~5 * 10 ms, generous bounds for CI noise
    # state threads through every call: 1 flush + 3 rounds of
    # (1 + 3 + 6)-iteration windows; a single jitter-inversion retry
    # adds one more full set (a THIRD is not legal)
    assert state in (31, 61)


def test_slope_window_inverted_marks_upper_bound():
    """When the 'work' is pure jitter (longer windows measured FASTER),
    the fallback reports the median full window and FLAGS it — bound
    samples must be distinguishable from measurements (ADVICE r4)."""
    calls = {"n": 0}

    def step(state):
        calls["n"] += 1
        # rounds=1, iters=2, base_iters=1 -> windows of 1/2/3 iters:
        # call 1 is the untimed flush; calls 2 and 8 are the two BASE
        # windows (attempt + retry). Making only those slow drives the
        # median pairwise slope negative both times.
        time.sleep(0.05 if calls["n"] in (2, 8) else 0.001)
        return state, jnp.asarray(0.0)

    with pytest.warns(UserWarning, match="inverted twice"):
        dt, _ = benchmarks.slope_window(step, 0, iters=2, base_iters=1,
                                        rounds=1)
    assert dt.upper_bound is True
    assert dt > 0


def test_slope_window_flags_asymmetric_fixed_cost():
    """A fixed cost that attaches to SOME window lengths only (here: the
    mid-length window) deflates one segment rate and inflates the other;
    the disagreement between the implied per-iteration rates must be
    flagged — the sample is not a clean slope (VERDICT r5 Weak #1)."""
    calls = {"n": 0}

    def step(state):
        calls["n"] += 1
        # rounds=1, iters=2, base_iters=1 -> flush(1), base(2), mid(3-4),
        # full(5-7): call 3 opens the mid window — give it a fixed extra
        extra = 0.05 if calls["n"] == 3 else 0.0
        time.sleep(0.01 + extra)
        return state + 1, jnp.asarray(0.0)

    with pytest.warns(UserWarning, match="asymmetrically"):
        dt, _ = benchmarks.slope_window(step, 0, iters=2, base_iters=1,
                                        rounds=1)
    assert dt.asymmetric is True
    assert not dt.upper_bound


def test_slope_window_sane_after_autotune_in_process(hvd):
    """Regression for the VERDICT r5 sharpest finding: running the fusion
    autotuner and then the timing primitive IN THE SAME PROCESS
    under-measured a 10 ms/iter step 4x (dt=0.0127 s for 5 iters) with
    upper_bound=False — autotune warm-up residue drained inside the next
    slope_window's single base window. The untimed flush iteration now
    pins that residue outside both windows; this test is the two-suite
    repro (test_fusion -> test_benchmarks_util) distilled into one."""
    from horovod_tpu.ops import fusion

    tree = {"a": jnp.ones((256,)), "b": jnp.ones((64, 4))}
    fusion.autotune_fusion_threshold(tree, candidates=[1 << 10, 1 << 20],
                                     trials=2, apply=False)

    def step(state):
        time.sleep(0.01)
        return state + 1, jnp.asarray(float(state))

    dt, _ = benchmarks.slope_window(step, 0, iters=5, base_iters=1)
    assert not dt.upper_bound
    assert 0.03 < dt < 0.3  # ~5 * 10 ms; a 4x under-measure would be .012


def test_repeat_throughput_propagates_window_times():
    def step(state, images, labels):
        return state, jnp.asarray(0.0)

    imgs = np.zeros((4, 1))
    runs = benchmarks.repeat_throughput(step, 0, imgs, None, warmup=0,
                                        iters=3, repeats=2)
    assert len(runs) == 2
    for rate, dt in runs:
        assert isinstance(dt, benchmarks.WindowTime)
        assert rate > 0


def test_overlap_variants_extend_with_wire_formats():
    """The --overlap/--compression combined mode (ISSUE 7 satellite):
    bare --overlap keeps the three-variant matrix; adding --compression
    appends an overlap+ZeRO-1 variant per wire format (the full
    pipeline in one run); a bogus format dies before any compile."""
    import sys

    import pytest

    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    import bench

    base, fmts = bench.overlap_variants(None)
    assert list(base) == ["baseline_fused_ar", "overlap_rs",
                          "overlap_rs_zero1"]
    assert fmts == []
    combined, fmts = bench.overlap_variants(["none", "int8", "fp8"])
    assert fmts == ["int8", "fp8"]
    assert combined["overlap_rs_zero1_int8"] == dict(
        sharded=True, overlap=True, wire="int8")
    assert combined["overlap_rs_zero1_fp8"]["wire"] == "fp8"
    # bare --compression (empty list) means the full format sweep
    _, fmts = bench.overlap_variants([])
    assert fmts == ["bf16", "fp8", "int8"]
    with pytest.raises(Exception):
        bench.overlap_variants(["float3"])


def test_lm_roofline_emits_bound_json(hvd, capsys, monkeypatch):
    """bench_roofline --lm (ISSUE 10 satellite): the d2048 LM MFU must
    be judged against the step's ACTUAL roofline bound. Runs the real
    compiled-step + cost_analysis machinery on a tiny transformer with
    the ceiling calibrations stubbed (a CPU box cannot sweep 8192-cubed
    bf16 matmuls in a unit test) and checks the JSON contract:
    lm_roofline_achieved_over_bound with the bound fields populated."""
    import argparse
    import json
    import sys

    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    import bench
    import bench_roofline

    monkeypatch.setattr(bench, "calibrate_peak_tflops",
                        lambda repeats=3: (100.0, 4096))
    monkeypatch.setattr(bench_roofline, "measure_hbm_bandwidth",
                        lambda *a, **k: 500.0)
    args = argparse.Namespace(lm_batch=2, lm_seq_len=64, lm_layers=1,
                              lm_heads=2, lm_d_model=32, lm_vocab=64,
                              num_iters=2, repeats=1)
    bench_roofline.lm_roofline(args)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "lm_roofline_achieved_over_bound"
    assert out["unit"] == "ratio"
    assert out["t_bound_ms"] == pytest.approx(
        max(out["t_compute_ms"], out["t_memory_ms"]))
    assert out["bound_by"] in ("compute", "memory")
    assert out["lm_d_model"] == 32 and out["tokens_per_sec"] > 0
    if out["flops_per_step"] > 0:
        assert out["value"] is not None
        assert out["mfu_bound_pct"] <= 100.0


def test_spmd_bench_mode_is_exclusive():
    """bench.py --spmd is its own comparison mode: combining it with
    --overlap/--compression/--data-plane must die at argument parsing,
    before any compile."""
    import subprocess
    import sys

    repo = __file__.rsplit("/tests/", 1)[0]
    proc = subprocess.run(
        [sys.executable, "bench.py", "--spmd", "--overlap"],
        cwd=repo, capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0
    assert "--spmd is its own comparison mode" in proc.stderr


def test_churn_bench_mode_is_exclusive():
    """bench.py --churn is its own comparison mode (the goodput-under-
    churn SLO gate): combining it with --overlap etc. dies at parsing."""
    import subprocess
    import sys

    repo = __file__.rsplit("/tests/", 1)[0]
    proc = subprocess.run(
        [sys.executable, "bench.py", "--churn", "--overlap"],
        cwd=repo, capture_output=True, text=True, timeout=120)
    assert proc.returncode != 0
    assert "--churn is its own comparison mode" in proc.stderr


def test_churn_slo_gate_smoke():
    """ISSUE 15 acceptance: ``bench.py --churn`` runs a scripted
    preemption schedule, attributes every lost second (non-zero
    preemption lane, sum≈wall), and PASSes its goodput budget."""
    import json
    import os
    import subprocess
    import sys

    repo = __file__.rsplit("/tests/", 1)[0]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--churn", "--churn-steps", "24",
         "--churn-preemptions", "2", "--churn-budget", "0.05",
         "--churn-drain-ms", "10"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["metric"] == "goodput_under_churn"
    assert out["slo"] == "PASS"
    assert out["preemptions"] == 2
    assert len(out["preempted_at_steps"]) == 2
    goodput = out["goodput"]
    assert goodput["phases"]["preemption"] > 0  # churn is attributed...
    assert sum(goodput["phases"].values()) == pytest.approx(
        goodput["wall_seconds"], rel=0.02)  # ...and nothing is lost
    assert out["value"] >= out["budget"]


def test_goodput_block_invariant_validation():
    """The BENCH `goodput` block contract (ISSUE 9 satellite): the phase
    sum must explain ~100% of wall time — an unattributed gap >2% (or a
    double-charged sum above wall) is a loud error, never silence."""
    from horovod_tpu.telemetry import report as report_mod

    good = {"wall_seconds": 10.0,
            "phases": {"compute": 9.5, "data_wait": 0.45}}
    assert report_mod.validate_goodput_block(good) is good

    with pytest.raises(report_mod.GoodputInvariantError,
                       match="unattributed"):
        report_mod.validate_goodput_block(
            {"wall_seconds": 10.0, "phases": {"compute": 9.0}})
    with pytest.raises(report_mod.GoodputInvariantError,
                       match="MORE than"):
        report_mod.validate_goodput_block(
            {"wall_seconds": 10.0,
             "phases": {"compute": 9.0, "data_wait": 2.0}})
    with pytest.raises(report_mod.GoodputInvariantError,
                       match="no wall time"):
        report_mod.validate_goodput_block({"wall_seconds": 0.0,
                                           "phases": {}})
    # right at the tolerance boundary: 2% unattributed passes
    report_mod.validate_goodput_block(
        {"wall_seconds": 10.0, "phases": {"compute": 9.8}})


def test_goodput_block_from_live_ledger():
    """report.goodput_block() finalizes the ledger and the emitted block
    passes its own validator (what every bench mode attaches)."""
    from horovod_tpu.telemetry import report as report_mod
    from horovod_tpu.telemetry.ledger import TimeLedger
    from horovod_tpu.telemetry.registry import MetricsRegistry

    t = [0.0]
    led = TimeLedger(clock=lambda: t[0], registry=MetricsRegistry(),
                     enabled=True)
    led.start()
    led.charge("data_wait", 0.4)
    t[0] = 1.0
    led.settle_step()
    t[0] = 1.1
    block = report_mod.goodput_block(ledger=led)
    assert block["phases"]["data_wait"] == pytest.approx(0.4)
    assert block["phases"]["compute"] == pytest.approx(0.6)
    assert block["wall_seconds"] == pytest.approx(1.1)
    assert block["unattributed_seconds"] == pytest.approx(0.0)
    assert block["steps"] == 1


def test_bench_attach_goodput_records_violation_loudly(capsys,
                                                       monkeypatch):
    """bench._attach_goodput never silently drops the invariant: a
    violating block yields a goodput_error field + a stderr shout, a
    healthy ledger yields the block, and HOROVOD_GOODPUT=0 (a
    documented opt-out) is skipped quietly — no false alarms."""
    import sys

    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    import bench

    from horovod_tpu.telemetry import ledger as ledger_lib
    from horovod_tpu.telemetry import report as report_mod
    from horovod_tpu.telemetry.ledger import TimeLedger
    from horovod_tpu.telemetry.registry import MetricsRegistry

    old = ledger_lib._ledger
    try:
        # healthy: real clock, one settled interval
        led = TimeLedger(registry=MetricsRegistry(), enabled=True)
        led.start()
        time.sleep(0.01)
        led.settle_step()
        ledger_lib._ledger = led
        result = {}
        bench._attach_goodput(result)
        assert "goodput" in result and "goodput_error" not in result

        # violating (an unattributed gap a phase hook failed to charge)
        def broken_block():
            raise report_mod.GoodputInvariantError("8.0% unattributed")

        monkeypatch.setattr(report_mod, "goodput_block", broken_block)
        result = {}
        bench._attach_goodput(result)
        assert "goodput" not in result
        assert "unattributed" in result["goodput_error"]
        assert "GOODPUT INVARIANT VIOLATED" in capsys.readouterr().err
        monkeypatch.undo()

        # opt-out: disabled ledger -> no block, no error, no shout
        ledger_lib._ledger = TimeLedger(registry=MetricsRegistry(),
                                        enabled=False)
        result = {}
        bench._attach_goodput(result)
        assert "goodput" not in result and "goodput_error" not in result
        assert capsys.readouterr().err == ""
    finally:
        ledger_lib._ledger = old
