"""Unit tests for the shared timing scaffold (utils/benchmarks.py) —
the measurement discipline every bench path rides (BENCH_NOTES.md)."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.utils import benchmarks


def test_window_time_is_a_float_with_flag():
    t = benchmarks.WindowTime(1.5)
    assert t == 1.5 and t + 0.5 == 2.0
    assert t.upper_bound is False
    b = benchmarks.WindowTime(2.0, upper_bound=True)
    assert b.upper_bound is True
    assert isinstance(b * 2, float)


def test_sync_forces_scalar_readback():
    out = benchmarks.sync({"a": jnp.arange(4.0)})
    assert isinstance(out, float) and out == 0.0


def test_slope_window_measures_per_iteration_cost():
    """A step with a known sleep: the slope (difference of windows)
    must recover the per-iteration cost, cancelling fixed overhead."""
    def step(state):
        time.sleep(0.01)
        return state + 1, jnp.asarray(float(state))

    dt, state = benchmarks.slope_window(step, 0, iters=5, base_iters=1)
    assert isinstance(dt, benchmarks.WindowTime)
    assert not dt.upper_bound
    assert 0.03 < dt < 0.3  # ~5 * 10 ms, generous bounds for CI noise
    # state threads through every call: one attempt = 1 flush + 7 timed
    # calls, a single jitter-inversion retry = +7 (retry is legal, a
    # THIRD is not)
    assert state in (8, 15)


def test_slope_window_inverted_marks_upper_bound():
    """When the 'work' is pure jitter (longer window measured FASTER),
    the fallback reports the full window and FLAGS it — bound samples
    must be distinguishable from measurements (ADVICE r4)."""
    calls = {"n": 0}

    def step(state):
        calls["n"] += 1
        # call 1 is the untimed flush; calls 2 and 6 are the two BASE
        # windows (attempt + retry): making only those slow guarantees
        # both inversions
        time.sleep(0.03 if calls["n"] in (2, 6) else 0.0)
        return state, jnp.asarray(0.0)

    with pytest.warns(UserWarning, match="inverted twice"):
        dt, _ = benchmarks.slope_window(step, 0, iters=2, base_iters=1)
    assert dt.upper_bound is True
    assert dt > 0


def test_slope_window_sane_after_autotune_in_process(hvd):
    """Regression for the VERDICT r5 sharpest finding: running the fusion
    autotuner and then the timing primitive IN THE SAME PROCESS
    under-measured a 10 ms/iter step 4x (dt=0.0127 s for 5 iters) with
    upper_bound=False — autotune warm-up residue drained inside the next
    slope_window's single base window. The untimed flush iteration now
    pins that residue outside both windows; this test is the two-suite
    repro (test_fusion -> test_benchmarks_util) distilled into one."""
    from horovod_tpu.ops import fusion

    tree = {"a": jnp.ones((256,)), "b": jnp.ones((64, 4))}
    fusion.autotune_fusion_threshold(tree, candidates=[1 << 10, 1 << 20],
                                     trials=2, apply=False)

    def step(state):
        time.sleep(0.01)
        return state + 1, jnp.asarray(float(state))

    dt, _ = benchmarks.slope_window(step, 0, iters=5, base_iters=1)
    assert not dt.upper_bound
    assert 0.03 < dt < 0.3  # ~5 * 10 ms; a 4x under-measure would be .012


def test_repeat_throughput_propagates_window_times():
    def step(state, images, labels):
        return state, jnp.asarray(0.0)

    imgs = np.zeros((4, 1))
    runs = benchmarks.repeat_throughput(step, 0, imgs, None, warmup=0,
                                        iters=3, repeats=2)
    assert len(runs) == 2
    for rate, dt in runs:
        assert isinstance(dt, benchmarks.WindowTime)
        assert rate > 0
