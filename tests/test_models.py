"""Model zoo + training-step builder tests.

Pattern per SURVEY.md §4: end-to-end through the public API on the virtual
8-device mesh; numerical references computed locally (the Adasum-test
pattern, test_adasum_tensorflow.py:33-63, applied to ring attention)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_api
from horovod_tpu import training
from horovod_tpu.models import (MLP, MNISTConvNet, ResNet18, ResNet50,
                                Transformer, TransformerConfig, VGG16)
from horovod_tpu.models.transformer import dense_attention
from horovod_tpu.parallel import ring


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def test_resnet18_forward_shape():
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    y = model.apply(variables, x, train=False)
    assert y.shape == (2, 10)
    assert y.dtype == jnp.float32


def test_resnet50_param_count():
    """ResNet-50 has ~25.5M params — the standard architecture checksum."""
    model = ResNet50(num_classes=1000, dtype=jnp.float32)
    x = jnp.ones((1, 64, 64, 3))
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x, train=False))
    n = _param_count(variables["params"])
    assert 25.4e6 < n < 25.7e6, n


def test_vgg16_forward_shape():
    model = VGG16(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    y = model.apply(variables, x, train=False)
    assert y.shape == (2, 10)


def test_mnist_convnet_forward():
    model = MNISTConvNet(dtype=jnp.float32)
    x = jnp.ones((4, 28, 28, 1))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    y = model.apply(variables, x, train=False)
    assert y.shape == (4, 10)


def test_transformer_forward_shape():
    cfg = TransformerConfig(vocab_size=100, num_layers=2, num_heads=4,
                            d_model=64, d_ff=128, dtype=jnp.float32)
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens, train=False)
    y = model.apply(variables, tokens)
    assert y.shape == (2, 16, 100)


def test_train_step_mlp_converges(hvd):
    """End-to-end: replicated params, sharded batch, fused grad allreduce."""
    rng = np.random.default_rng(0)
    X = rng.standard_normal((64, 8)).astype(np.float32)
    w_true = rng.standard_normal((8,)).astype(np.float32)
    y = (X @ w_true > 0).astype(np.int32)

    model = MLP(features=(16, 2))
    tx = hvd_api.DistributedOptimizer(optax.adam(0.05))
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        jnp.zeros((1, 8)))
    step = training.make_train_step(model, tx, donate=False)
    losses = []
    for _ in range(60):
        state, loss = step(state, jnp.asarray(X), jnp.asarray(y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_train_step_batchnorm_model(hvd):
    """BN models thread batch_stats through the SPMD step."""
    model = ResNet18(num_classes=4, num_filters=8, dtype=jnp.float32)
    tx = hvd_api.DistributedOptimizer(optax.sgd(0.01))
    x = jnp.ones((8, 16, 16, 3))
    labels = jnp.zeros((8,), jnp.int32)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        x[:1])
    assert state.batch_stats
    step = training.make_train_step(model, tx, donate=False)
    state2, loss = step(state, x, labels)
    assert int(state2.step) == 1
    assert np.isfinite(float(loss))
    # stats actually updated
    before = jax.tree_util.tree_leaves(state.batch_stats)
    after = jax.tree_util.tree_leaves(state2.batch_stats)
    assert any(not np.allclose(a, b) for a, b in zip(before, after))


def _ring_vs_dense(attn_fn, n_devices, heads=8):
    """Reference check: sharded attention == dense attention on full seq."""
    b, s, h, d = 2, 8 * n_devices, heads, 16
    rng = np.random.default_rng(1)
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    positions = np.broadcast_to(np.arange(s)[None, :], (b, s)).copy()

    mesh = jax.sharding.Mesh(np.asarray(jax.devices()), ("seq",))

    def f(q, k, v, pos):
        return attn_fn(q, k, v, axis_name="seq", causal=True,
                       q_positions=pos, kv_positions=pos)

    out = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq"),
                  P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))(q, k, v, positions)

    ref = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=True, q_positions=jnp.asarray(positions),
                          kv_positions=jnp.asarray(positions))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_matches_dense(hvd, n_devices):
    _ring_vs_dense(ring.ring_attention, n_devices)


def test_ulysses_attention_matches_dense(hvd, n_devices):
    _ring_vs_dense(ring.ulysses_attention, n_devices)


def test_ulysses_rejects_bad_heads(hvd, n_devices):
    if n_devices < 2:
        pytest.skip("needs multiple devices")
    with pytest.raises(Exception):
        _ring_vs_dense(ring.ulysses_attention, n_devices,
                       heads=n_devices + 1)


def test_lm_loss_exact_under_seq_parallel(hvd, n_devices):
    """Seq-parallel next-token loss/grads equal the single-device values.

    Uses a positionwise LM (logits depend only on the local token) so the
    only cross-shard coupling is the loss stitching itself: shard i's final
    target must be shard i+1's first token, and normalization must be by
    the global target count (VERDICT r1 item 8)."""
    import flax.linen as nn

    ndata = 2
    nseq = n_devices // ndata
    if nseq < 2:
        pytest.skip("needs >=4 devices")

    class PositionwiseLM(nn.Module):
        vocab: int

        @nn.compact
        def __call__(self, tokens, train=False):
            emb = self.param("emb", nn.initializers.normal(1.0),
                             (self.vocab, self.vocab))
            return emb[tokens]

    model = PositionwiseLM(vocab=16)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 16, size=(ndata * 2, nseq * 4)),
        jnp.int32)

    def run(mesh, axes, batch_axis, seq_axis):
        tx = hvd_api.DistributedOptimizer(optax.sgd(0.1), axes=axes)
        state = training.create_train_state(
            model, tx, jax.random.PRNGKey(7), tokens[:1])
        step = training.make_lm_train_step(
            model, tx, mesh=mesh, batch_axis=batch_axis, seq_axis=seq_axis,
            donate=False)
        state, loss = step(state, tokens)
        return float(loss), state.params

    mesh1 = jax.sharding.Mesh(np.asarray(jax.devices()[:1]), ("data",))
    loss_ref, params_ref = run(mesh1, ("data",), "data", None)

    devs = np.asarray(jax.devices()).reshape(ndata, nseq)
    mesh2 = jax.sharding.Mesh(devs, ("data", "seq"))
    loss_par, params_par = run(mesh2, ("data", "seq"), "data", "seq")

    np.testing.assert_allclose(loss_par, loss_ref, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5, atol=1e-6),
        params_par, params_ref)


def test_lm_train_step_sequence_parallel(hvd, n_devices):
    """Transformer with ring attention over a (data, seq) mesh trains."""
    ndata = 2
    nseq = n_devices // ndata
    if nseq < 2:
        pytest.skip("needs >=4 devices")
    devs = np.asarray(jax.devices()).reshape(ndata, nseq)
    mesh = jax.sharding.Mesh(devs, ("data", "seq"))

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            d_model=32, d_ff=64, dtype=jnp.float32,
                            sequence_axis="seq")
    model = Transformer(cfg)
    # init outside shard_map: use a dense-attention clone (same params)
    init_model = Transformer(
        TransformerConfig(**{**cfg.__dict__, "sequence_axis": None}))
    tx = hvd_api.DistributedOptimizer(optax.adam(0.01),
                                      axes=("data", "seq"))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(ndata * 2, nseq * 8)),
        jnp.int32)
    state = training.create_train_state(init_model, tx, jax.random.PRNGKey(0),
                                        tokens[:1])
    step = training.make_lm_train_step(model, tx, mesh=mesh,
                                       batch_axis="data", seq_axis="seq",
                                       donate=False)
    losses = []
    for _ in range(20):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
