"""Expert-parallel MoE: the sharded program must equal the single-device
oracle (dispatch math is global, so 1-device IS the oracle), experts must
actually live sharded, and capacity overflow must drop cleanly."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from horovod_tpu.models.moe import MoE, shard_moe_params


def _mesh():
    return Mesh(np.asarray(jax.devices()[:8]), ("expert",))


@pytest.fixture()
def x(rng):
    return jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)


def test_expert_parallel_matches_single_device(x):
    mesh = _mesh()
    kwargs = dict(num_experts=8, d_model=16, d_ff=32)
    oracle = MoE(**kwargs)
    params = oracle.init(jax.random.PRNGKey(0), x)["params"]
    want = oracle.apply({"params": params}, x)

    ep = MoE(**kwargs, mesh=mesh)
    sharded = shard_moe_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert", None)))
    got = jax.jit(lambda p, v: ep.apply({"params": p}, v))(sharded, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-6)


def test_expert_weights_actually_sharded(x):
    mesh = _mesh()
    moe = MoE(num_experts=8, d_model=16, d_ff=32, mesh=mesh)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    sharded = shard_moe_params(params, mesh)
    assert sharded["w_in"].sharding.spec == P("expert", None, None)
    # each device holds exactly one expert's weights
    assert sharded["w_in"].addressable_shards[0].data.shape == (1, 16, 32)
    assert sharded["gate"].sharding.spec == P()


def test_capacity_overflow_drops_not_crashes(rng):
    # all tokens prefer one expert: capacity C = ceil(T/E * cf) drops the
    # overflow; output rows past capacity are exactly zero (residual
    # connections carry them in a full model)
    moe = MoE(num_experts=4, d_model=8, d_ff=16, capacity_factor=1.0)
    x = jnp.ones((16, 8), jnp.float32)  # identical tokens, same argmax
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    out = moe.apply({"params": params}, x)
    assert np.isfinite(np.asarray(out)).all()
    nonzero_rows = np.abs(np.asarray(out)).sum(axis=-1) > 0
    assert nonzero_rows.sum() == 4  # C = 16/4 * 1.0 = 4 kept


def test_moe_transformer_matches_dense_mesh_oracle(rng):
    """Switch-style MoE-LM (cfg.moe_every): expert-parallel over a
    (data x expert) mesh equals the single-device oracle, and the expert
    weights actually live sharded."""
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.parallel import tensor as tp

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    base = dict(vocab_size=64, num_layers=2, num_heads=4, d_model=16,
                d_ff=32, dtype=jnp.float32, moe_every=2, num_experts=8)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 8)), jnp.int32)

    oracle = Transformer(TransformerConfig(**base))
    params = oracle.init(jax.random.PRNGKey(0), tokens)["params"]
    want = oracle.apply({"params": params}, tokens)

    ep = Transformer(TransformerConfig(**base, expert_mesh=mesh))
    specs = tp.transformer_param_specs(params, model_axis=None,
                                       expert_axis="expert")
    sharded = jax.device_put(params, jax.tree_util.tree_map(
        lambda s: jax.sharding.NamedSharding(mesh, s), specs,
        is_leaf=lambda v: isinstance(v, P)))
    assert sharded["block_1"]["moe"]["w_in"].sharding.spec == \
        P("expert", None, None)
    ts = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
    got = jax.jit(lambda p, t: ep.apply({"params": p}, t))(sharded, ts)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=1e-5)


def test_moe_transformer_trains_expert_parallel(rng):
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.parallel import tensor as tp

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            d_model=16, d_ff=32, dtype=jnp.float32,
                            moe_every=2, num_experts=8, expert_mesh=mesh)
    model = Transformer(cfg)
    tx = optax.adam(1e-2)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 8)), jnp.int32)
    state = tp.shard_lm_state(model, tx, jax.random.PRNGKey(0),
                              tokens[:1], mesh, model_axis=None,
                              expert_axis="expert")
    step = tp.make_tp_lm_train_step(model, tx, mesh, model_axis=None,
                                    expert_axis="expert")
    losses = []
    for _ in range(12):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # with_sharding_constraint normalizes trailing Nones away
    spec = state.params["block_1"]["moe"]["w_in"].sharding.spec
    assert tuple(spec) in (("expert",), ("expert", None, None)), spec


def test_moe_custom_axis_name(rng):
    """The expert axis name is configurable end-to-end: a mesh whose
    axis is 'ep' must work (regression: the constraint used to hardcode
    'expert' and trace-fail far from the config)."""
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("ep",))
    moe = MoE(num_experts=8, d_model=16, d_ff=32, mesh=mesh,
              expert_axis="ep")
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    sharded = shard_moe_params(params, mesh, expert_axis="ep")
    xs = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
    out = jax.jit(lambda p, v: moe.apply({"params": p}, v))(sharded, xs)
    want = MoE(num_experts=8, d_model=16, d_ff=32).apply(
        {"params": params}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=1e-6)


def test_moe_trains(x):
    mesh = _mesh()
    moe = MoE(num_experts=8, d_model=16, d_ff=32, mesh=mesh)
    params = shard_moe_params(
        moe.init(jax.random.PRNGKey(0), x)["params"], mesh)
    tx = optax.adam(1e-2)
    opt = jax.jit(tx.init)(params)
    target = jnp.asarray(
        np.random.default_rng(1).standard_normal((64, 16)), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert", None)))

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            out = moe.apply({"params": p}, xs)
            return jnp.mean((out - target) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    losses = []
    for _ in range(15):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
