"""Expert-parallel MoE: the sharded program must equal the single-device
oracle (dispatch math is global, so 1-device IS the oracle), experts must
actually live sharded, and capacity overflow must drop cleanly."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from horovod_tpu.models.moe import MoE, aux_loss, shard_moe_params


def _mesh():
    return Mesh(np.asarray(jax.devices()[:8]), ("expert",))


@pytest.fixture()
def x(rng):
    return jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)


def test_expert_parallel_matches_single_device(x):
    mesh = _mesh()
    kwargs = dict(num_experts=8, d_model=16, d_ff=32)
    oracle = MoE(**kwargs)
    params = oracle.init(jax.random.PRNGKey(0), x)["params"]
    want = oracle.apply({"params": params}, x)

    ep = MoE(**kwargs, mesh=mesh)
    sharded = shard_moe_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert", None)))
    got = jax.jit(lambda p, v: ep.apply({"params": p}, v))(sharded, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-6)


def test_expert_weights_actually_sharded(x):
    mesh = _mesh()
    moe = MoE(num_experts=8, d_model=16, d_ff=32, mesh=mesh)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    sharded = shard_moe_params(params, mesh)
    assert sharded["w_in"].sharding.spec == P("expert", None, None)
    # each device holds exactly one expert's weights
    assert sharded["w_in"].addressable_shards[0].data.shape == (1, 16, 32)
    assert sharded["gate"].sharding.spec == P()


def test_capacity_overflow_drops_not_crashes(rng):
    # all tokens prefer one expert: capacity C = ceil(T/E * cf) drops the
    # overflow; output rows past capacity are exactly zero (residual
    # connections carry them in a full model)
    moe = MoE(num_experts=4, d_model=8, d_ff=16, capacity_factor=1.0)
    x = jnp.ones((16, 8), jnp.float32)  # identical tokens, same argmax
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    out = moe.apply({"params": params}, x)
    assert np.isfinite(np.asarray(out)).all()
    nonzero_rows = np.abs(np.asarray(out)).sum(axis=-1) > 0
    assert nonzero_rows.sum() == 4  # C = 16/4 * 1.0 = 4 kept


def test_moe_transformer_matches_dense_mesh_oracle(rng):
    """Switch-style MoE-LM (cfg.moe_every): expert-parallel over a
    (data x expert) mesh equals the single-device oracle, and the expert
    weights actually live sharded."""
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.parallel import tensor as tp

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    base = dict(vocab_size=64, num_layers=2, num_heads=4, d_model=16,
                d_ff=32, dtype=jnp.float32, moe_every=2, num_experts=8)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 8)), jnp.int32)

    oracle = Transformer(TransformerConfig(**base))
    params = oracle.init(jax.random.PRNGKey(0), tokens)["params"]
    want = oracle.apply({"params": params}, tokens)

    ep = Transformer(TransformerConfig(**base, expert_mesh=mesh))
    specs = tp.transformer_param_specs(params, model_axis=None,
                                       expert_axis="expert")
    sharded = jax.device_put(params, jax.tree_util.tree_map(
        lambda s: jax.sharding.NamedSharding(mesh, s), specs,
        is_leaf=lambda v: isinstance(v, P)))
    assert sharded["block_1"]["moe"]["w_in"].sharding.spec == \
        P("expert", None, None)
    ts = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
    got = jax.jit(lambda p, t: ep.apply({"params": p}, t))(sharded, ts)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=1e-5)


def test_moe_transformer_trains_expert_parallel(rng):
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.parallel import tensor as tp

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            d_model=16, d_ff=32, dtype=jnp.float32,
                            moe_every=2, num_experts=8, expert_mesh=mesh)
    model = Transformer(cfg)
    tx = optax.adam(1e-2)
    tokens = jnp.asarray(rng.integers(0, 64, size=(4, 8)), jnp.int32)
    state = tp.shard_lm_state(model, tx, jax.random.PRNGKey(0),
                              tokens[:1], mesh, model_axis=None,
                              expert_axis="expert")
    step = tp.make_tp_lm_train_step(model, tx, mesh, model_axis=None,
                                    expert_axis="expert")
    losses = []
    for _ in range(12):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # with_sharding_constraint normalizes trailing Nones away
    spec = state.params["block_1"]["moe"]["w_in"].sharding.spec
    assert tuple(spec) in (("expert",), ("expert", None, None)), spec


def test_moe_custom_axis_name(rng):
    """The expert axis name is configurable end-to-end: a mesh whose
    axis is 'ep' must work (regression: the constraint used to hardcode
    'expert' and trace-fail far from the config)."""
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("ep",))
    moe = MoE(num_experts=8, d_model=16, d_ff=32, mesh=mesh,
              expert_axis="ep")
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    sharded = shard_moe_params(params, mesh, expert_axis="ep")
    xs = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
    out = jax.jit(lambda p, v: moe.apply({"params": p}, v))(sharded, xs)
    want = MoE(num_experts=8, d_model=16, d_ff=32).apply(
        {"params": params}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=1e-6)


def test_grouped_dispatch_matches_single_device(x):
    """GShard grouping (num_groups>1): capacity/cumsum are per-group but
    mesh-independent, so the 1-device run with the same G is still the
    oracle for the expert-parallel run."""
    mesh = _mesh()
    kwargs = dict(num_experts=8, d_model=16, d_ff=32, num_groups=4)
    oracle = MoE(**kwargs)
    params = oracle.init(jax.random.PRNGKey(0), x)["params"]
    want = oracle.apply({"params": params}, x)

    ep = MoE(**kwargs, mesh=mesh)
    sharded = shard_moe_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert", None)))
    got = jax.jit(lambda p, v: ep.apply({"params": p}, v))(sharded, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-6)


def test_grouped_dispatch_indivisible_falls_back():
    """num_groups is an upper bound: T=16 with num_groups=3 uses the
    largest divisor (2), so an init sample whose B*S doesn't divide the
    configured G never crashes (the shard_lm_state batch-1 case)."""
    moe = MoE(num_experts=4, d_model=8, d_ff=16, num_groups=3)
    x = jnp.ones((16, 8), jnp.float32)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    out = moe.apply({"params": params}, x)
    assert out.shape == (16, 8)
    # effective G=2 equals an explicit num_groups=2 run bit-for-bit
    want = MoE(num_experts=4, d_model=8, d_ff=16,
               num_groups=2).apply({"params": params}, x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


def test_grouped_dispatch_memory_scales_down():
    """The point of grouping: at LM scale (T=32k) the compiled program's
    temporaries must stay bounded — the un-grouped dispatch tensor alone
    would be T*E*C = 5.4 GB in fp32; with G=64 it is ~84 MB."""
    T, E, G = 32768, 8, 64
    moe = MoE(num_experts=E, d_model=32, d_ff=64, capacity_factor=1.25,
              num_groups=G)
    x = jnp.ones((T, 32), jnp.float32)
    params = jax.eval_shape(
        lambda: moe.init(jax.random.PRNGKey(0), jnp.ones((64, 32))))
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), params)["params"]
    compiled = jax.jit(
        lambda p, v: moe.apply({"params": p}, v)).lower(params, x).compile()
    mem = compiled.memory_analysis()
    if mem is None or not hasattr(mem, "temp_size_in_bytes"):
        pytest.skip("backend reports no memory analysis")
    assert mem.temp_size_in_bytes < 1 * 2 ** 30, mem.temp_size_in_bytes


def test_aux_loss_sown_and_summed(x):
    """__call__ sows Switch load-balance + router-z terms; aux_loss sums
    them with weights; near-uniform routing at init puts load_balance
    near its minimum of 1.0."""
    moe = MoE(num_experts=8, d_model=16, d_ff=32)
    variables = moe.init(jax.random.PRNGKey(0), x)
    out, mutated = moe.apply({"params": variables["params"]}, x,
                             mutable=["losses"])
    losses = mutated["losses"]
    (lb,) = losses["load_balance"]
    (z,) = losses["router_z"]
    assert lb.dtype == jnp.float32 and z.dtype == jnp.float32
    assert 0.9 <= float(lb) < 4.0, float(lb)   # E * sum(f*p), min 1.0
    assert float(z) >= 0.0
    total = aux_loss(mutated, load_balance_weight=0.5, router_z_weight=0.0)
    np.testing.assert_allclose(float(total), 0.5 * float(lb), rtol=1e-6)
    # dense path: nothing sown -> exactly zero, so callers can add it
    # unconditionally
    assert float(aux_loss({})) == 0.0


def test_aux_loss_prevents_collapse():
    """Train a Switch MoE-LM ~50 steps with the aux loss in the train
    step (make_tp_lm_train_step wires it); expert utilization must stay
    spread — no single expert takes the majority of tokens."""
    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.parallel import tensor as tp

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    cfg = TransformerConfig(vocab_size=32, num_layers=2, num_heads=2,
                            d_model=16, d_ff=32, dtype=jnp.float32,
                            moe_every=2, num_experts=8, expert_mesh=mesh,
                            moe_num_groups=2)
    model = Transformer(cfg)
    tx = optax.adam(3e-3)
    rng = np.random.default_rng(0)
    # skewed token stream (zipf-ish) — the pressure that collapses
    # routing when no balancing term exists
    probs = 1.0 / np.arange(1, 33)
    probs /= probs.sum()
    tokens = jnp.asarray(rng.choice(32, size=(8, 16), p=probs), jnp.int32)
    state = tp.shard_lm_state(model, tx, jax.random.PRNGKey(0),
                              tokens[:1], mesh, model_axis=None,
                              expert_axis="expert")
    step = tp.make_tp_lm_train_step(model, tx, mesh, model_axis=None,
                                    expert_axis="expert")
    first = None
    for _ in range(50):
        state, loss = step(state, tokens)
        first = float(loss) if first is None else first
    assert float(loss) < first, (float(loss), first)

    # measure routing: fraction of tokens argmax-routed to each expert
    # in the MoE block's gate
    emb = state.params["embed"]["embedding"]
    x = emb[np.asarray(tokens).reshape(-1)]
    gate = state.params["block_1"]["moe"]["gate"]
    top1 = np.asarray(jnp.argmax(x @ gate, axis=-1))
    frac = np.bincount(top1, minlength=8) / top1.size
    assert frac.max() < 0.5, frac        # no majority collapse
    assert (frac > 0.01).sum() >= 4, frac  # at least half the experts used


def test_top2_routing_matches_single_device(x):
    """GShard top-2: expert-parallel equals the 1-device oracle, and
    each kept token is served by (up to) two experts with weights that
    sum to 1."""
    mesh = _mesh()
    kwargs = dict(num_experts=8, d_model=16, d_ff=32, top_k=2,
                  num_groups=2)
    oracle = MoE(**kwargs)
    params = oracle.init(jax.random.PRNGKey(0), x)["params"]
    want = oracle.apply({"params": params}, x)

    ep = MoE(**kwargs, mesh=mesh)
    sharded = shard_moe_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert", None)))
    got = jax.jit(lambda p, v: ep.apply({"params": p}, v))(sharded, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-6)


def test_top2_uses_two_experts_per_token(rng):
    """With ample capacity, a top-2 layer must route every token to its
    two highest-prob experts with normalized weights — verified against
    a direct numpy computation of the expected output."""
    E, d, f = 4, 8, 16
    moe = MoE(num_experts=E, d_model=d, d_ff=f, top_k=2,
              capacity_factor=float(E))  # capacity >= all tokens
    x = jnp.asarray(rng.standard_normal((12, d)), jnp.float32)
    params = moe.init(jax.random.PRNGKey(1), x)["params"]
    out = moe.apply({"params": params}, x)

    gate = np.asarray(params["gate"], np.float64)
    w_in = np.asarray(params["w_in"], np.float64)
    w_out = np.asarray(params["w_out"], np.float64)
    logits = np.asarray(x, np.float64) @ gate
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.zeros((12, d))
    gelu = lambda v: np.asarray(  # noqa: E731 — reuse jax's exact gelu
        jax.nn.gelu(jnp.asarray(v, jnp.float32)), np.float64)
    for ti in range(12):
        order = np.argsort(-p[ti])
        e1, e2 = order[0], order[1]
        wsum = p[ti, e1] + p[ti, e2]
        for e, w in ((e1, p[ti, e1] / wsum), (e2, p[ti, e2] / wsum)):
            h = gelu(np.asarray(x[ti], np.float64) @ w_in[e])
            want[ti] += w * (h @ w_out[e])
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4,
                               atol=1e-5)


def test_moe_trains(x):
    mesh = _mesh()
    moe = MoE(num_experts=8, d_model=16, d_ff=32, mesh=mesh)
    params = shard_moe_params(
        moe.init(jax.random.PRNGKey(0), x)["params"], mesh)
    tx = optax.adam(1e-2)
    opt = jax.jit(tx.init)(params)
    target = jnp.asarray(
        np.random.default_rng(1).standard_normal((64, 16)), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert", None)))

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            out = moe.apply({"params": p}, xs)
            return jnp.mean((out - target) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    losses = []
    for _ in range(15):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
