"""Collective op tests over the virtual 8-device mesh.

Reference pattern: test/test_tensorflow.py:90-995 — allreduce/allgather/
broadcast across ranks with value checks; here "ranks" are mesh shards
inside a shard_map (the compiled data plane)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_api
from horovod_tpu.ops import collective


def shard_apply(hvd, fn, out_specs=P()):
    """Run fn() per-shard over the full mesh, no inputs."""
    return jax.shard_map(fn, mesh=hvd.mesh(),
                         in_specs=(), out_specs=out_specs, check_vma=False)()


def test_allreduce_sum(hvd, n_devices):
    def f():
        x = (collective.mesh_rank().astype(jnp.float32) + 1.0) * jnp.ones((4,))
        return collective.allreduce(x, op=hvd_api.Sum)

    out = shard_apply(hvd, f)
    expected = sum(range(1, n_devices + 1))
    np.testing.assert_allclose(out, expected * np.ones((4,)))


def test_allreduce_average(hvd, n_devices):
    def f():
        x = collective.mesh_rank().astype(jnp.float32) * jnp.ones((3, 2))
        return collective.allreduce(x, op=hvd_api.Average)

    out = shard_apply(hvd, f)
    expected = np.mean(np.arange(n_devices))
    np.testing.assert_allclose(out, expected * np.ones((3, 2)))


def test_allreduce_min_max(hvd, n_devices):
    def f():
        x = collective.mesh_rank().astype(jnp.float32)
        return (collective.allreduce(x, op=hvd_api.Min),
                collective.allreduce(x, op=hvd_api.Max))

    mn, mx = shard_apply(hvd, f, out_specs=(P(), P()))
    assert mn == 0.0
    assert mx == float(n_devices - 1)


def test_allreduce_compressed(hvd, n_devices):
    def f():
        x = (collective.mesh_rank().astype(jnp.float32) + 0.5) * jnp.ones((8,))
        return collective.allreduce(x, op=hvd_api.Sum,
                                    compression=hvd_api.Compression.fp16)

    out = shard_apply(hvd, f)
    assert out.dtype == jnp.float32  # decompressed back
    expected = sum(r + 0.5 for r in range(n_devices))
    np.testing.assert_allclose(out, expected * np.ones((8,)), rtol=1e-2)


def test_allgather(hvd, n_devices):
    def f():
        x = collective.mesh_rank().astype(jnp.float32) * jnp.ones((2, 3))
        return collective.allgather(x)

    out = shard_apply(hvd, f)
    assert out.shape == (2 * n_devices, 3)
    for r in range(n_devices):
        np.testing.assert_allclose(out[2 * r:2 * r + 2], r)


def test_broadcast(hvd, n_devices):
    root = n_devices - 1

    def f():
        x = (collective.mesh_rank().astype(jnp.float32) + 1.0) * jnp.ones((5,))
        return collective.broadcast(x, root_rank=root)

    out = shard_apply(hvd, f)
    np.testing.assert_allclose(out, float(root + 1) * np.ones((5,)))


def test_broadcast_matches_root_on_every_shard(hvd, n_devices):
    def f():
        x = collective.mesh_rank().astype(jnp.float32).reshape(1)
        out = collective.broadcast(x, root_rank=2)
        return collective.allgather(out)

    gathered = shard_apply(hvd, f)
    np.testing.assert_allclose(gathered, 2.0 * np.ones((n_devices,)))


def test_reducescatter(hvd, n_devices):
    def f():
        x = jnp.arange(n_devices * 2, dtype=jnp.float32)
        return collective.reducescatter(x, op=hvd_api.Sum)

    out = shard_apply(hvd, f, out_specs=P("data"))
    expected = np.arange(n_devices * 2, dtype=np.float32) * n_devices
    np.testing.assert_allclose(np.asarray(out), expected)


def test_reducescatter_average(hvd, n_devices):
    def f():
        r = collective.mesh_rank().astype(jnp.float32)
        x = (r + 1.0) * jnp.ones((n_devices,))
        return collective.reducescatter(x, op=hvd_api.Average)

    out = shard_apply(hvd, f, out_specs=P("data"))
    expected = np.mean(np.arange(1, n_devices + 1))
    np.testing.assert_allclose(np.asarray(out),
                               expected * np.ones(n_devices), rtol=1e-6)


def test_reducescatter_eager_fallback_single_process(hvd):
    """The eager fallback (it was the ONLY collective without one — calling
    it at top level used to die inside lax.psum_scatter): one launched
    process => world size 1 => the whole reduced array, like its
    siblings."""
    x = np.arange(8.0, dtype=np.float32).reshape(4, 2)
    out = hvd.reducescatter(x)
    np.testing.assert_allclose(np.asarray(out), x)
    out = hvd.reducescatter(x, op=hvd_api.Average)
    np.testing.assert_allclose(np.asarray(out), x)
    with pytest.raises(ValueError, match="Sum or Average"):
        hvd.reducescatter(x, op=hvd_api.Min)


def test_proc_mesh_invalidated_on_shutdown():
    """Elastic re-rendezvous / re-init must not reuse an eager proc mesh
    built from the previous device set (stale jax.devices())."""
    import horovod_tpu as hvd_mod
    hvd_mod.shutdown()
    hvd_mod.init()
    collective._proc_mesh()
    assert collective._proc_mesh.cache_info().currsize == 1
    hvd_mod.shutdown()  # must drop the cache (device set may change)
    assert collective._proc_mesh.cache_info().currsize == 0
    # the elastic reset path clears it too
    from horovod_tpu.elastic.state import ObjectState
    collective._proc_mesh()
    assert collective._proc_mesh.cache_info().currsize == 1
    ObjectState(value=0).on_reset()
    assert collective._proc_mesh.cache_info().currsize == 0


def test_alltoall(hvd, n_devices):
    def f():
        me = collective.mesh_rank().astype(jnp.float32)
        x = me * jnp.ones((n_devices,)) + jnp.arange(n_devices) * 0.1
        out = collective.alltoall(x)
        return collective.allgather(out[None])

    out = shard_apply(hvd, f)
    # shard j's row i = sender i's chunk j = i + 0.1*j
    for j in range(n_devices):
        np.testing.assert_allclose(
            out[j], np.arange(n_devices) + 0.1 * j, rtol=1e-6)


def test_mesh_rank_and_size(hvd, n_devices):
    def f():
        return (collective.mesh_rank().astype(jnp.float32).reshape(1),
                jnp.full((1,), collective.mesh_size(), jnp.float32))

    ranks, sizes = jax.shard_map(
        f, mesh=hvd.mesh(), in_specs=(),
        out_specs=(P("data"), P("data")), check_vma=False)()
    np.testing.assert_allclose(ranks, np.arange(n_devices))
    np.testing.assert_allclose(sizes, n_devices)


def test_2d_mesh_allreduce(hvd2d, n_devices):
    def f():
        x = collective.mesh_rank().astype(jnp.float32) + 1.0
        return collective.allreduce(x.reshape(1), op=hvd_api.Sum)

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(),
                        out_specs=P(), check_vma=False)()
    np.testing.assert_allclose(out, sum(range(1, n_devices + 1)))


def test_2d_mesh_single_axis_reduce(hvd2d, n_devices):
    data_size = n_devices // 2

    def f():
        x = collective.mesh_rank().astype(jnp.float32) + 1.0
        # reduce only over 'data' (within-slice): each dcn row sums its own
        return collective.allreduce(x.reshape(1), op=hvd_api.Sum,
                                    axes=("data",))

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(),
                        out_specs=P("dcn"), check_vma=False)()
    row0 = sum(range(1, data_size + 1))
    row1 = sum(range(data_size + 1, n_devices + 1))
    np.testing.assert_allclose(np.asarray(out), [row0, row1])


def test_eager_single_process_semantics(hvd):
    # One launched process => Horovod world of size 1 => identity,
    # for every reduction op including Adasum (eager-surface uniformity).
    x = np.arange(6.0, dtype=np.float32).reshape(2, 3)
    np.testing.assert_allclose(hvd.allreduce(x), x)
    for op in (hvd_api.Sum, hvd_api.Average, hvd_api.Min, hvd_api.Max,
               hvd_api.Adasum):
        np.testing.assert_allclose(hvd.allreduce(x, op=op), x)
    np.testing.assert_allclose(hvd.allgather(x), x)
    np.testing.assert_allclose(hvd.broadcast(x, root_rank=0), x)
    np.testing.assert_allclose(hvd.alltoall(x), x)


def test_eager_adasum_duplicate_collapse(hvd, n_devices, rng):
    """Correctness basis of the staged eager Adasum path
    (collective._eager_allreduce): each process's value is replicated on
    its local devices, and since adasum(v, v) = v the first tree levels
    collapse the duplicates — the all-device XOR tree equals the tree
    over unique per-process values."""
    from horovod_tpu.ops import adasum
    nproc = n_devices // 2
    vals = rng.standard_normal((nproc, 9)).astype(np.float32)
    dup = np.repeat(vals, 2, axis=0)  # device-major staging layout

    def f():
        x = jnp.asarray(dup)[collective.mesh_rank()]
        return adasum.adasum_allreduce(x, ("data",))

    out = shard_apply(hvd, f)
    expected = adasum.adasum_tree_np([vals[i] for i in range(nproc)])
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                               atol=1e-5)


def test_eager_adasum_rejects_noncontiguous_device_layout():
    """ADVICE round 5: the staged eager Adasum tree silently corrupts
    results unless device i is owned by process i // nldev (the
    duplicate-collapse levels would pair DIFFERENT processes' values).
    The layout gate must refuse loudly; the contiguous layout passes."""
    import types

    def dev(pidx):
        return types.SimpleNamespace(process_index=pidx)

    ok = [dev(0), dev(0), dev(1), dev(1)]
    collective._assert_contiguous_process_layout(ok, nldev=2)

    interleaved = [dev(0), dev(1), dev(0), dev(1)]
    with pytest.raises(RuntimeError, match="contiguous nldev-aligned"):
        collective._assert_contiguous_process_layout(interleaved, nldev=2)


def test_alltoall_multi_axis(hvd2d, n_devices):
    """alltoall over BOTH mesh axes: the participant set is the
    linearized (dcn, data) rank order, matching mesh_rank."""
    def f():
        me = collective.mesh_rank(("dcn", "data")).astype(jnp.float32)
        x = me * jnp.ones((n_devices,)) + jnp.arange(n_devices) * 0.1
        out = collective.alltoall(x, axes=("dcn", "data"))
        return collective.allgather(out[None], axes=("dcn", "data"))

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(),
                        out_specs=P(("dcn", "data")), check_vma=False)()
    out = np.asarray(out)
    for j in range(n_devices):
        np.testing.assert_allclose(
            out[j], np.arange(n_devices) + 0.1 * j, rtol=1e-6)


def test_hierarchical_allreduce_matches_flat(hvd2d, n_devices):
    from horovod_tpu.parallel.hierarchical import hierarchical_allreduce

    def f():
        x = (collective.mesh_rank().astype(jnp.float32) + 1.0) * \
            jnp.arange(1.0, 11.0)  # length 10: exercises padding (not /4)
        return hierarchical_allreduce(x, ici_axes=("data",), dcn_axis="dcn",
                                      op="average")

    out = jax.shard_map(f, mesh=hvd2d.mesh(), in_specs=(),
                        out_specs=P(), check_vma=False)()
    expected = np.mean(np.arange(1, n_devices + 1)) * np.arange(1.0, 11.0)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.int32])
def test_allreduce_dtypes(hvd, n_devices, dtype):
    def f():
        x = jnp.ones((4,), dtype) * (collective.mesh_rank() + 1).astype(dtype)
        return collective.allreduce(x, op=hvd_api.Sum)

    out = shard_apply(hvd, f)
    assert out.dtype == dtype
    np.testing.assert_allclose(np.asarray(out, np.float64),
                               sum(range(1, n_devices + 1)), rtol=1e-2)
