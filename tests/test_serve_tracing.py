"""Per-request tracing + tail-latency doctor (ISSUE 18): the span
recorder tiles every traced request's latency with named spans and
classified gaps, the fleet router threads ONE trace across re-dispatch
hops, ``hvd-doctor serve`` names each slow request's dominant stall,
the Chrome export merges into one multi-pid trace with cross-replica
flow arrows, and tracing OFF leaves the compiled programs
byte-identical and the hot path untouched. See docs/OBSERVABILITY.md,
"Debugging a slow request"."""

import io
import json
import time

import jax
import numpy as np
import pytest

from test_serve import _kv, _model, _oracle, _run_until

from horovod_tpu.diag import serve_doctor
from horovod_tpu.serve import tracing
from horovod_tpu.serve.engine import Request, ServeEngine
from horovod_tpu.serve.tracing import RequestTrace, ServeTracer
from horovod_tpu.telemetry.registry import MetricsRegistry


# ---- RequestTrace unit behavior ------------------------------------------

def test_trace_tiles_latency_and_classifies_gaps():
    """Solid spans + complement gaps (classified by the phase in force
    when each opens) tile [start, end] exactly: attributed_fraction is
    1.0 whenever every gap falls under a known phase."""
    tr = RequestTrace("r-1", clock=lambda: 0.0)
    tr.phase(0.0, "queued")
    tr.span("dispatch", 1.0, 1.2, actor="router")
    tr.phase(1.2, "prefilling")
    tr.span("prefill", 1.4, 2.0, actor="r0")
    tr.phase(2.0, "decoding")
    tr.span("decode", 2.0, 3.0, actor="r0")
    res = tr.finalize(end=4.0)
    assert res["latency_s"] == pytest.approx(4.0)
    assert res["attributed_fraction"] == pytest.approx(1.0)
    gaps = {(s["t0"], s["t1"]): s["kind"]
            for s in res["spans"] if s.get("gap")}
    assert gaps[(0.0, 1.0)] == "queue"          # phase "queued"
    assert gaps[(1.2, 1.4)] == "prefill_wait"   # phase "prefilling"
    assert gaps[(3.0, 4.0)] == "decode_wait"    # phase "decoding"
    # spans sorted, finalize idempotent
    assert res is tr.finalize()
    ts = [s["t0"] for s in res["spans"]]
    assert ts == sorted(ts)


def test_trace_without_phase_marks_counts_unattributed():
    tr = RequestTrace("r-2", clock=lambda: 0.0)
    tr.span("decode", 1.0, 2.0)
    res = tr.finalize(end=4.0)
    # gaps [0,1] and [2,4] have no phase in force -> unattributed
    assert res["attributed_fraction"] == pytest.approx(1.0 / 4.0)
    kinds = {s["kind"] for s in res["spans"] if s.get("gap")}
    assert kinds == {tracing.UNATTRIBUTED}


def test_hop_window_reaches_back_to_drain_notice():
    """A stream cut after sitting on a DRAINING replica charges its
    whole doomed residency to the hop — the window opens at the drain
    notice, not the grace-expiry cut — so the doctor names
    redispatch_hop dominant for eviction victims even when they never
    ran a single iteration on the victim."""
    tr = RequestTrace("r-3", clock=lambda: 0.0)
    tr.phase(0.0, "queued")
    tr.event("submit", 0.0, actor="r0")
    tr.event("drain", 0.1, actor="r0", on=True)
    tr.event("cut", 2.0, actor="r0")
    tr.phase(2.0, "redispatching")
    tr.event("resumed", 2.5, actor="r1")
    tr.phase(2.5, "decoding")
    tr.span("decode", 2.5, 3.0, actor="r1")
    res = tr.finalize(end=3.0)
    assert res["hops"] == 1
    assert res["hop_windows"] == [[0.1, 2.5]]
    totals = serve_doctor.phase_totals(res)
    dom, dom_s = serve_doctor.dominant_stall(totals)
    assert dom == "redispatch_hop"
    assert dom_s == pytest.approx(2.4)
    # a drain on a DIFFERENT replica does not pull the window open
    tr2 = RequestTrace("r-4", clock=lambda: 0.0)
    tr2.event("drain", 0.1, actor="r9", on=True)
    tr2.event("cut", 2.0, actor="r0")
    tr2.event("resumed", 2.5, actor="r1")
    assert tr2.finalize(end=3.0)["hop_windows"] == [[2.0, 2.5]]


def test_span_table_matches_doctor_classifier_both_ways():
    """The drift contract hvd-lint HVD-METRIC enforces statically,
    asserted directly: every span kind classifiable, no ghost
    entries."""
    assert set(tracing.SPAN_KINDS) == set(serve_doctor.PHASE_OF_KIND)
    for phase in serve_doctor.STALL_PHASES:
        assert phase in set(serve_doctor.PHASE_OF_KIND.values())


# ---- sampling / SLO / env knobs ------------------------------------------

def test_tracer_sampling_is_deterministic_fraction():
    t = ServeTracer(sample=0.25, clock=lambda: 0.0)
    traced = [t.begin(i) is not None for i in range(100)]
    assert sum(traced) == 25
    assert ServeTracer(sample=0.0).begin("x") is None
    assert ServeTracer(sample=0.0).begin("x", force=True) is not None


def test_tracer_slo_keeps_only_the_slow_tail():
    clk = {"t": 0.0}
    t = ServeTracer(sample=0.0, slo_ms=100.0, clock=lambda: clk["t"])
    fast = t.begin("fast")
    assert fast is not None and not fast.keep  # armed, not yet kept
    clk["t"] = 0.05
    assert t.finish(fast) is None              # under SLO: dropped
    slow = t.begin("slow")
    clk["t"] = 0.25
    res = t.finish(slow)
    assert res is not None and res["slo_exceeded"]
    assert [tr["request_id"] for tr in t.traces()] == ["slow"]


def test_tracer_from_env_knobs():
    assert ServeTracer.from_env(env={}) is None
    assert ServeTracer.from_env(env={tracing.TRACE_ENV: "0"}) is None
    t = ServeTracer.from_env(env={tracing.TRACE_ENV: "1"})
    assert t is not None and t.sample == 1.0
    t = ServeTracer.from_env(env={tracing.TRACE_ENV: "0.5"})
    assert t is not None and t.sample == 0.5
    # SLO or a dump dir alone arms tail/forced tracing at sample 0
    t = ServeTracer.from_env(env={tracing.TRACE_SLO_ENV: "250"})
    assert t is not None and t.sample == 0.0 and t.slo_ms == 250.0
    t = ServeTracer.from_env(env={}, out_dir="/tmp/x")
    assert t is not None and t.sample == 0.0 and t.out_dir == "/tmp/x"


# ---- engine integration ---------------------------------------------------

def test_traced_engine_matches_untraced_and_programs_byte_identical():
    """The acceptance bar: tracing must never shape the computation.
    Same workload on a traced and an untraced engine -> identical
    tokens, and every AOT-compiled program (prefill, decode) lowers to
    byte-identical HLO text."""
    cfg, model, params = _model()
    rng = np.random.default_rng(7)
    prompts = [list(map(int, rng.integers(0, 64, 6))) for _ in range(3)]

    def run(tracer):
        eng = ServeEngine(model, params, _kv(cfg), max_slots=2,
                          prefill_chunk=4, registry=MetricsRegistry(),
                          tracer=tracer)
        reqs = [Request(p, 5) for p in prompts]
        for r in reqs:
            eng.submit(r)
        _run_until(eng, reqs)
        return eng, [r.generated for r in reqs]

    eng_off, toks_off = run(None)
    eng_on, toks_on = run(ServeTracer(sample=1.0))
    assert toks_on == toks_off
    for prog in ("_prefill", "_decode"):
        off = getattr(eng_off, prog)._cache._programs
        on = getattr(eng_on, prog)._cache._programs
        assert set(off) == set(on)  # same shape signatures compiled
        for key in off:
            assert off[key][0].as_text() == on[key][0].as_text(), \
                f"{prog} HLO differs with tracing on"


def test_engine_trace_covers_latency_and_reports_cache_hits():
    """Engine-owned traces: full lifecycle spans recorded, ≥98% of
    latency attributed, the admitted event carries the prefix-cache
    hit count, and TTFT from admission is stamped for every request."""
    cfg, model, params = _model()
    tracer = ServeTracer(sample=1.0)
    eng = ServeEngine(model, params, _kv(cfg), max_slots=2,
                      prefill_chunk=4, registry=MetricsRegistry(),
                      tracer=tracer)
    rng = np.random.default_rng(8)
    shared = list(map(int, rng.integers(0, 64, 8)))
    reqs = [Request(shared + list(map(int, rng.integers(0, 64, 3))), 4)
            for _ in range(3)]
    for r in reqs:
        eng.submit(r)
    _run_until(eng, reqs)
    traces = tracer.traces()
    assert len(traces) == len(reqs)
    for tr in traces:
        assert tr["attributed_fraction"] >= 0.98
        kinds = {s["kind"] for s in tr["spans"] if not s.get("gap")}
        assert {"prefill", "decode"} <= kinds
        events = {e["name"]: e for e in tr["events"]}
        assert {"submit", "admitted", "done"} <= set(events)
    # later requests hit the prefix cache the first one seeded
    cached = [e["cached_tokens"] for tr in traces
              for e in tr["events"] if e["name"] == "admitted"]
    assert max(cached) > 0
    for r in reqs:
        assert r.admitted_at is not None
        assert r.first_token_time >= r.admitted_at >= r.arrival


def test_untraced_hot_path_records_nothing():
    """tracer=None: no trace objects, no live-trace counter activity —
    the zero-cost default."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg), max_slots=2,
                      prefill_chunk=4, registry=MetricsRegistry())
    r = Request(list(range(5)), 4)
    eng.submit(r)
    _run_until(eng, [r])
    assert r.trace is None
    assert eng._live_traces == 0
    # admitted_at is stamped regardless: the TTFT satellite needs it
    assert r.admitted_at is not None


def test_attribution_snapshot_delta_windows_under_concurrent_streams():
    """A bench window bounded by attribution_snapshot() deltas stays
    consistent while streams run concurrently on the engine thread:
    per-phase deltas are non-negative and their sum tracks the window's
    wall clock (the in-progress idle tick is charged to the boundary it
    lands inside, not dropped)."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg, num_blocks=128),
                      max_slots=4, prefill_chunk=4,
                      registry=MetricsRegistry()).start()
    try:
        rng = np.random.default_rng(9)
        warm = eng.generate(list(map(int, rng.integers(0, 64, 4))), 2)
        warm.result(timeout=120)
        base = eng.attribution_snapshot()
        t0 = time.monotonic()
        reqs = [eng.generate(list(map(int, rng.integers(0, 64, 5))), 8)
                for _ in range(6)]
        mid = eng.attribution_snapshot()   # streams still in flight
        for r in reqs:
            r.result(timeout=120)
        time.sleep(0.05)                   # an idle tick inside window
        end = eng.attribution_snapshot()
        wall = time.monotonic() - t0
        assert set(end) == set(base)
        for k in end:
            assert end[k] >= mid[k] - 1e-9 >= base[k] - 2e-9
        explained = sum(end[k] - base[k] for k in end)
        # generous tolerance: CPU-mesh timing, but the window must be
        # mostly explained and never over-explained by more than noise
        assert explained <= wall + 0.25
        assert explained >= 0.5 * wall
    finally:
        eng.stop()


# ---- fleet e2e: one trace across a hop, doctor, Chrome merge -------------

def test_fleet_chaos_trace_hop_doctor_and_chrome_merge(tmp_path):
    """The e2e: 2-replica fleet, streams cut by an eviction — the cut
    stream's ONE trace spans both replicas, ndjson lines parse, the
    doctor names redispatch_hop dominant for hopped requests, and the
    merged Chrome trace loads cleanly with a cross-pid flow arrow
    linking cut -> resume."""
    from test_serve_fleet import _fleet

    cfg, model, params = _model()
    reg = MetricsRegistry()
    out_dir = tmp_path / "st"
    tracer = ServeTracer(sample=1.0, out_dir=str(out_dir))
    router, engines = _fleet(model, params, cfg, reg, num_blocks=128)
    router._tracer = tracer  # _fleet predates the tracer kwarg
    try:
        rng = np.random.default_rng(41)
        n_new = 24
        reqs = [router.generate(
                    list(map(int, rng.integers(0, 64, 5))), n_new)
                for _ in range(5)]
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(r.replica == "r0" and r.generated for r in reqs) \
                    and any(r.replica == "r1" for r in reqs):
                break
            time.sleep(0.005)
        router.evict("r0")
        for r in reqs:
            assert r.result(timeout=120) == _oracle(model, params,
                                                    r.prompt, n_new)
        assert router.dropped == 0
    finally:
        router.stop()
        tracer.close()

    traces = tracer.traces()
    assert len(traces) == len(reqs)
    hopped = [tr for tr in traces if tr["hops"]]
    assert hopped, "eviction cut no stream — the e2e tested nothing"
    for tr in hopped:
        actors = {s.get("actor") for s in tr["spans"]} | \
            {e.get("actor") for e in tr["events"]}
        assert {"r0", "r1"} <= actors  # ONE trace, both replicas
        assert tr["attributed_fraction"] >= 0.98
        # everything inside the cut->resume window is charged to the
        # hop, whatever the span kinds say (on an UNLOADED survivor
        # the hop is fast and need not dominate — dominance under load
        # is the chaos bench gate, bench_serve._tail_attribution)
        totals = serve_doctor.phase_totals(tr)
        window = sum(b - a for a, b in tr["hop_windows"])
        assert totals.get("redispatch_hop", 0.0) == \
            pytest.approx(window, rel=0.05, abs=1e-4)

    # ndjson streamed live by finish(); doctor CLI reads it
    ndjson = out_dir / tracing.NDJSON_NAME
    lines = [json.loads(ln) for ln in
             ndjson.read_text().splitlines() if ln]
    assert {t["request_id"] for t in lines} == \
        {t["request_id"] for t in traces}
    buf = io.StringIO()
    report = serve_doctor.run(str(out_dir), stream=buf)
    assert report["requests"] == len(reqs)
    assert "hvd-doctor serve" in buf.getvalue()
    assert serve_doctor.main([str(out_dir)]) == 0

    # merged Chrome trace: json.loads clean, one pid per replica,
    # request-scoped flow events crossing pids with one shared id
    merged_path = out_dir / "servetrace.merged.json"
    tracer.write_chrome(str(merged_path))
    merged = json.loads(merged_path.read_text())
    events = (merged["traceEvents"] if isinstance(merged, dict)
              else merged)
    names = {e["args"]["name"]: e["pid"] for e in events
             if e.get("name") == "process_name"}
    assert {"serve r0", "serve r1"} <= set(names)
    flows = [e for e in events if e.get("ph") in ("s", "f")]
    assert flows, "no flow arrow for the hop"
    by_id = {}
    for e in flows:
        assert e["cat"] == "hvd_global_flow"
        by_id.setdefault(e["id"], []).append(e)
    assert any(len(pair) == 2 and pair[0]["pid"] != pair[1]["pid"]
               for pair in by_id.values()), \
        "flow arrow does not cross replica pids"


def test_fleet_redispatch_and_swap_metrics_advance():
    """Satellite metrics: every hop increments
    hvd_serve_redispatch_total; a rolling reload observes a
    hvd_serve_weight_swap_seconds window."""
    import jax.numpy as jnp

    from test_serve_fleet import _fleet

    from horovod_tpu.telemetry import instruments as instruments_lib

    cfg, model, params = _model()
    reg = MetricsRegistry()
    router, engines = _fleet(model, params, cfg, reg, num_blocks=128)
    try:
        rng = np.random.default_rng(42)
        reqs = [router.generate(
                    list(map(int, rng.integers(0, 64, 5))), 24)
                for _ in range(4)]
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(r.replica == "r0" and r.generated for r in reqs):
                break
            time.sleep(0.005)
        router.evict("r0")
        for r in reqs:
            r.result(timeout=120)
        counter = instruments_lib.serve_redispatch_counter(reg)
        assert counter.value == router.redispatched >= 1

        hist = instruments_lib.serve_weight_swap_histogram(reg)
        before = hist.count
        bumped = jax.tree_util.tree_map(lambda a: a + jnp.ones_like(a),
                                        params)
        router.install_weights(bumped, version=2)
        assert hist.count > before  # the rolling-reload window observed
    finally:
        router.stop()


# ---- overhead bound (slow) -----------------------------------------------

@pytest.mark.slow
def test_tracing_overhead_under_2pct():
    """The sampled-request bound: the host-side cost of recording one
    decode iteration's spans (one span per active slot + the phase
    bookkeeping) must be <2% of a measured decode step. Measured as a
    microbenchmark against the engine's real decode-step wall time."""
    cfg, model, params = _model()
    eng = ServeEngine(model, params, _kv(cfg, num_blocks=128),
                      max_slots=4, prefill_chunk=4,
                      registry=MetricsRegistry())
    rng = np.random.default_rng(11)
    reqs = [Request(list(map(int, rng.integers(0, 64, 5))), 40)
            for _ in range(4)]
    for r in reqs:
        eng.submit(r)
    # warm into steady decode, then time pure decode steps
    for _ in range(30):
        eng.step()
    assert all(r.state == "decode" for r in reqs)
    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        eng.step()
    step_s = (time.perf_counter() - t0) / iters

    # per-iteration recording cost: max_slots span records + one
    # phase/event pair, measured tight-loop
    tr = RequestTrace("bench", clock=time.monotonic)
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        tr.span("decode", 0.0, 1.0, actor="r0", batch=4)
    record_s = (time.perf_counter() - t0) / n
    per_iter = record_s * (eng.max_slots + 2)
    assert per_iter < 0.02 * step_s, \
        (f"tracing records cost {per_iter * 1e6:.1f}us/iter vs decode "
         f"step {step_s * 1e6:.1f}us — over the 2% bound")
