"""Worker body for the ``hvdrun --chaos`` soak (tests/test_chaos.py).

Unlike elastic_train_worker.py (which wires its elastic context by
hand), this worker goes through the full product path — ``hvd.init()``
arms the flight recorder AND the graceful-eviction handler
(runtime/services.py), so the chaos monkey's SIGTERM exercises the real
preemption plane: recorder wakeup-fd watcher -> bounded grace commit ->
doomed-host announcement -> clean EXIT_RENDEZVOUS.

    argv: <ckpt_dir> <log_path> <num_steps>

Deterministic scalar SGD (same oracle as elastic_train_worker.py); only
rank 0 appends to the loss log. HVD_CHAOS_TEST_SLEEP paces the steps so
the chaos schedule lands mid-training.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import elastic  # noqa: E402

TARGET = 3.0
LR = 0.2


def main():
    ckpt_dir, log_path, num_steps = (sys.argv[1], sys.argv[2],
                                     int(sys.argv[3]))
    step_sleep = float(os.environ.get("HVD_CHAOS_TEST_SLEEP", "0.05"))

    hvd.init()
    rank = hvd.rank()
    epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0"))

    state = elastic.JaxState(directory=ckpt_dir,
                             params={"w": np.float64(0.0)},
                             step=np.int64(0))

    @elastic.run
    def train(state):
        while int(state.step) < num_steps:
            if step_sleep:
                time.sleep(step_sleep)
            w = float(state.params["w"])
            loss = (w - TARGET) ** 2
            state.params = {"w": np.float64(w - LR * 2 * (w - TARGET))}
            state.step = np.int64(int(state.step) + 1)
            state.commit()
            if rank == 0:
                with open(log_path, "a") as f:
                    f.write(json.dumps({"epoch": epoch,
                                        "step": int(state.step),
                                        "loss": loss}) + "\n")
        return int(state.step)

    final = train(state)
    if rank == 0:
        with open(log_path, "a") as f:
            f.write(json.dumps({"epoch": epoch, "done": final}) + "\n")
    hvd.shutdown()


if __name__ == "__main__":
    main()
