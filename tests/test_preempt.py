"""Preemption-native spot training (ISSUE 15): the graceful-eviction
handler (elastic/preempt.py), the doomed-host plane through the elastic
driver, drained-vs-crashed blame accounting, and blacklist decay on
sustained health. Fast tier runs on fake clocks / a loopback KV; the
drained-vs-SIGKILL recovery-cost comparison spawns real workers and is
slow-marked."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.elastic import preempt
from horovod_tpu.elastic.discovery import FixedHosts
from horovod_tpu.elastic.driver import (DOOMED_TTL_S, EXIT_RENDEZVOUS,
                                        Blacklist, ElasticDriver)
from horovod_tpu.elastic.preempt import (DOOMED_KEY_PREFIX,
                                         DOOMED_MARKER_KEY,
                                         GracefulEvictionHandler)
from horovod_tpu.run import launcher
from horovod_tpu.run.rendezvous import KVStoreServer

WORKER = os.path.join(os.path.dirname(__file__), "elastic_train_worker.py")


# ---------------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------------

def test_grace_seconds_env_parsing():
    assert preempt.grace_seconds({}) == preempt.DEFAULT_GRACE_SECONDS
    assert preempt.grace_seconds({"HOROVOD_GRACE_SECONDS": "12.5"}) == 12.5
    assert preempt.grace_seconds({"HOROVOD_GRACE_SECONDS": "-3"}) == 0.0
    assert preempt.grace_seconds({"HOROVOD_GRACE_SECONDS": "nope"}) == \
        preempt.DEFAULT_GRACE_SECONDS


def test_configured_requires_an_explicit_opt_in():
    assert not preempt.configured({})
    assert preempt.configured({"HOROVOD_GRACE_SECONDS": "10"})
    assert preempt.configured({"HOROVOD_PREEMPT_NOTICE_FILE": "/p"})
    assert preempt.configured({"HOROVOD_PREEMPT_NOTICE_URL": "http://x"})


# ---------------------------------------------------------------------------
# the eviction path, unit-level (fake state / clock / exit)
# ---------------------------------------------------------------------------

class FakeState:
    def __init__(self, error=None):
        self.flush_timeouts = []
        self._error = error

    def flush(self, timeout=None):
        self.flush_timeouts.append(timeout)
        if self._error is not None:
            raise self._error


def _run_eviction(kind="sigterm", state="default", env=None, grace=5.0):
    codes = []
    handler = GracefulEvictionHandler(
        state=FakeState() if state == "default" else state,
        grace=grace, env=env if env is not None else {},
        exit_fn=codes.append)
    t = handler.trigger(kind)
    assert t is not None
    t.join(10.0)
    assert handler.finished.is_set()
    return handler, codes


def test_eviction_commits_within_grace_and_exits_clean():
    handler, codes = _run_eviction()
    assert handler.last["kind"] == "sigterm"
    assert handler.last["outcome"] == "committed"
    assert handler._state.flush_timeouts and \
        handler._state.flush_timeouts[0] <= 5.0
    assert codes == [0]  # no elastic epoch in env -> plain clean exit


def test_eviction_exit_code_is_rendezvous_under_a_driver():
    _handler, codes = _run_eviction(env={"HOROVOD_ELASTIC_EPOCH": "2"})
    assert codes == [EXIT_RENDEZVOUS]


def test_eviction_timeout_and_error_outcomes():
    handler, codes = _run_eviction(state=FakeState(error=TimeoutError()))
    assert handler.last["outcome"] == "timeout"
    assert codes == [0]  # a blown grace budget still exits clean

    handler, _ = _run_eviction(state=FakeState(error=RuntimeError("disk")))
    assert handler.last["outcome"] == "error"

    handler, _ = _run_eviction(state=None)
    assert handler.last["outcome"] == "no-state"


def test_eviction_is_idempotent():
    codes = []
    handler = GracefulEvictionHandler(state=FakeState(), grace=1.0, env={},
                                      exit_fn=codes.append)
    first = handler.trigger("sigterm")
    assert handler.trigger("sigterm") is None  # second notice: no-op
    first.join(10.0)
    assert codes == [0]
    assert len(handler._state.flush_timeouts) == 1


def test_eviction_announces_doomed_host_on_kv():
    kv = KVStoreServer()
    port = kv.start()
    try:
        env = {"HOROVOD_HOSTNAME": "spot-a", "HOROVOD_RANK": "1",
               "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
               "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port)}
        handler, _codes = _run_eviction(env=env)
        assert handler.last["announced"]
        raw = kv.get(DOOMED_KEY_PREFIX + "spot-a")
        assert raw is not None
        info = json.loads(raw)
        assert info["host"] == "spot-a" and info["kind"] == "sigterm"
        assert info["rank"] == 1 and info["time"] > 0
        marker = json.loads(kv.get(DOOMED_MARKER_KEY))
        assert marker["host"] == "spot-a"
    finally:
        kv.stop()


def test_teardown_fanout_suppresses_second_announcement():
    """A SIGTERM right after ANOTHER host's doomed announcement is the
    launcher recycling the epoch, not a second preemption: the rank must
    still grace-commit and exit clean, but NOT announce its own host."""
    kv = KVStoreServer()
    port = kv.start()
    try:
        kv.put(DOOMED_MARKER_KEY, json.dumps(
            {"host": "spot-b", "time": time.time()}).encode())
        env = {"HOROVOD_HOSTNAME": "spot-a",
               "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
               "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
               "HOROVOD_ELASTIC_EPOCH": "3"}
        handler, codes = _run_eviction(env=env)
        assert handler.last["kind"] == "teardown"
        assert not handler.last["announced"]
        assert handler.last["outcome"] == "committed"  # commit still runs
        assert kv.get(DOOMED_KEY_PREFIX + "spot-a") is None
        assert codes == [EXIT_RENDEZVOUS]
    finally:
        kv.stop()


def test_stale_marker_from_other_host_does_not_suppress():
    kv = KVStoreServer()
    port = kv.start()
    try:
        kv.put(DOOMED_MARKER_KEY, json.dumps(
            {"host": "spot-b",
             "time": time.time() - 2 * preempt.TEARDOWN_WINDOW_S}).encode())
        env = {"HOROVOD_HOSTNAME": "spot-a",
               "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
               "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port)}
        handler, _ = _run_eviction(env=env)
        assert handler.last["kind"] == "sigterm"
        assert handler.last["announced"]
    finally:
        kv.stop()


def test_notice_file_polling_triggers_eviction(tmp_path):
    """The cloud spot-notice shape: a file appearing at the configured
    path starts the eviction from the poller thread."""
    notice = tmp_path / "preempted"
    codes = []
    handler = GracefulEvictionHandler(
        state=FakeState(), grace=2.0, notice_file=str(notice),
        poll_interval=0.02, env={}, exit_fn=codes.append)
    handler.install()
    try:
        time.sleep(0.1)
        assert not handler.finished.is_set()  # no notice yet
        notice.write_text("TRUE")
        assert handler.finished.wait(10.0)
        assert handler.last["kind"] == "notice:file"
        assert codes == [0]
    finally:
        handler.uninstall()


def test_install_idempotent_and_module_singleton():
    codes = []
    try:
        h1 = preempt.install(state=FakeState(), grace=1.0, env={},
                             exit_fn=codes.append)
        h2 = preempt.install()
        assert h1 is h2 is preempt.get_handler()
        fresh = FakeState()
        preempt.attach_state(fresh)
        assert h1._state is fresh
    finally:
        preempt.uninstall()
    assert preempt.get_handler() is None


# ---------------------------------------------------------------------------
# blacklist: drained != crashed, decay on sustained health
# ---------------------------------------------------------------------------

def test_blacklist_drain_carries_no_penalty():
    now = {"t": 0.0}
    bl = Blacklist(threshold=3, base_delay=10.0, clock=lambda: now["t"])
    bl.record_drain("h")
    bl.record_drain("h")
    assert bl.drains("h") == 2
    assert bl.count("h") == 0
    assert not bl.excluded("h")  # the crash path would back off here
    bl.record_failure("h")
    assert bl.excluded("h")  # ...like this


def test_blacklist_decay_forgives_failures_on_sustained_health():
    now = {"t": 0.0}
    bl = Blacklist(threshold=3, base_delay=1.0, clock=lambda: now["t"],
                   decay_window=100.0)
    bl.record_failure("h")
    bl.record_failure("h")
    assert bl.count("h") == 2

    bl.observe_health({"h"})           # streak starts at t=0
    now["t"] = 99.0
    bl.observe_health({"h"})
    assert bl.count("h") == 2          # window not yet full
    now["t"] = 100.0
    bl.observe_health({"h"})
    assert bl.count("h") == 1          # one failure forgiven
    now["t"] = 200.0
    bl.observe_health({"h"})
    assert bl.count("h") == 0          # fully forgiven
    assert not bl.excluded("h")


def test_blacklist_health_streak_broken_by_absence_or_failure():
    now = {"t": 0.0}
    bl = Blacklist(threshold=3, base_delay=1.0, clock=lambda: now["t"],
                   decay_window=100.0)
    bl.record_failure("h")
    bl.observe_health({"h"})
    now["t"] = 90.0
    bl.observe_health(set())           # absent: streak lost
    now["t"] = 110.0
    bl.observe_health({"h"})           # streak restarts at t=110
    assert bl.count("h") == 1
    now["t"] = 209.0
    bl.observe_health({"h"})
    assert bl.count("h") == 1          # 99s < window
    now["t"] = 215.0
    bl.observe_health({"h"})
    assert bl.count("h") == 0

    # a new failure breaks the streak too
    bl.record_failure("h")
    bl.observe_health({"h"})           # anchor at 215
    now["t"] = 250.0
    bl.record_failure("h")             # streak gone
    now["t"] = 320.0
    bl.observe_health({"h"})           # restarts at 320
    now["t"] = 400.0
    bl.observe_health({"h"})
    assert bl.count("h") == 2          # 80s < window: nothing forgiven


def test_blacklist_permanent_exclusion_never_decays():
    now = {"t": 0.0}
    bl = Blacklist(threshold=2, base_delay=1.0, clock=lambda: now["t"],
                   decay_window=10.0)
    bl.record_failure("h")
    bl.record_failure("h")
    assert bl.blacklisted("h")
    for t in (100.0, 1000.0, 1e6):
        now["t"] = t
        bl.observe_health({"h"})
    assert bl.blacklisted("h") and bl.count("h") == 2


def test_blacklist_decay_disabled_without_window():
    now = {"t": 0.0}
    bl = Blacklist(threshold=3, base_delay=1.0, clock=lambda: now["t"])
    bl.record_failure("h")
    now["t"] = 1e6
    bl.observe_health({"h"})
    assert bl.count("h") == 1  # observe_health is a no-op


# ---------------------------------------------------------------------------
# the driver's doomed-host plane
# ---------------------------------------------------------------------------

def _put_doomed(kv, host, kind="sigterm", ts=None):
    payload = json.dumps({"host": host, "rank": 0, "kind": kind,
                          "time": time.time() if ts is None else ts,
                          "grace": 5.0}).encode()
    kv.put(DOOMED_KEY_PREFIX + host, payload)
    kv.put(DOOMED_MARKER_KEY, payload)


def test_rendezvous_drains_announced_doomed_host():
    kv = KVStoreServer()
    kv.start()
    try:
        driver = ElasticDriver(FixedHosts({"hostA": 1, "hostB": 1}),
                               min_np=1, kv=kv, poll_interval=0.05)
        _put_doomed(kv, "hostA")
        slots = driver.rendezvous()
        assert {s.hostname for s in slots} == {"hostB"}
        # one-shot: the announcement is consumed, not re-applied
        assert kv.get(DOOMED_KEY_PREFIX + "hostA") is None
        assert kv.get(DOOMED_MARKER_KEY) is None
        slots = driver.rendezvous()
        assert "hostA" in {s.hostname for s in slots}
        driver.stop()
    finally:
        kv.stop()


def test_rendezvous_reuses_doomed_host_below_min_np():
    kv = KVStoreServer()
    kv.start()
    try:
        driver = ElasticDriver(FixedHosts({"hostA": 1}), min_np=1, kv=kv,
                               poll_interval=0.05)
        _put_doomed(kv, "hostA")
        slots = driver.rendezvous()
        # losing the host would end the job: knowingly reused instead
        assert {s.hostname for s in slots} == {"hostA"}
        assert kv.get(DOOMED_KEY_PREFIX + "hostA") is None  # still consumed
        driver.stop()
    finally:
        kv.stop()


def test_stale_doomed_announcement_is_dropped():
    kv = KVStoreServer()
    kv.start()
    try:
        driver = ElasticDriver(FixedHosts({"hostA": 1, "hostB": 1}),
                               min_np=1, kv=kv, poll_interval=0.05)
        _put_doomed(kv, "hostA", ts=time.time() - DOOMED_TTL_S - 60)
        slots = driver.rendezvous()
        # a reclaimed host that came back must not stay excluded on a
        # leftover key — and the stale key is garbage-collected
        assert "hostA" in {s.hostname for s in slots}
        assert kv.get(DOOMED_KEY_PREFIX + "hostA") is None
        driver.stop()
    finally:
        kv.stop()


class FakeJob:
    def __init__(self, rcs):
        self.rcs = rcs
        self.first_failure = next(
            ((r, c) for r, c in sorted(rcs.items()) if c != 0), None)

    def join(self):
        return dict(self.rcs)


def test_run_job_drain_blame_on_graceful_eviction():
    """EXIT_RENDEZVOUS backed by a doomed announcement is planned churn:
    record_drain (no backoff), then the job finishes on the reused
    capacity."""
    kv = KVStoreServer()
    kv.start()
    try:
        driver = ElasticDriver(FixedHosts({"hostA": 1}), min_np=1, kv=kv,
                               poll_interval=0.05)

        def launch(slots, epoch, env):
            assert env["HOROVOD_ELASTIC"] == "1"
            if epoch == 1:
                _put_doomed(kv, "hostA")  # the worker announced, then...
                return FakeJob({0: EXIT_RENDEZVOUS})  # ...drained
            return FakeJob({0: 0})

        epochs = driver.run_job(launch, max_epochs=4)
    finally:
        kv.stop()
    assert epochs == 2
    assert driver.blacklist.drains("hostA") == 1
    assert driver.blacklist.count("hostA") == 0
    assert not driver.blacklist.excluded("hostA")


def test_run_job_drain_blame_when_sigkill_beats_the_grace_window():
    """The host died mid-eviction (crash exit code, but its doom was
    announced): still planned churn — drain accounting, no backoff."""
    kv = KVStoreServer()
    kv.start()
    try:
        driver = ElasticDriver(FixedHosts({"hostA": 1}), min_np=1, kv=kv,
                               poll_interval=0.05)

        def launch(slots, epoch, env):
            if epoch == 1:
                _put_doomed(kv, "hostA")
                return FakeJob({0: -9})  # SIGKILL won the race
            return FakeJob({0: 0})

        epochs = driver.run_job(launch, max_epochs=4)
    finally:
        kv.stop()
    assert epochs == 2
    assert driver.blacklist.drains("hostA") == 1
    assert driver.blacklist.count("hostA") == 0


def test_run_job_crash_without_announcement_still_blames():
    kv = KVStoreServer()
    kv.start()
    try:
        driver = ElasticDriver(
            FixedHosts({"hostA": 1}), min_np=1, kv=kv, poll_interval=0.05,
            blacklist=Blacklist(threshold=3, base_delay=0.0))

        def launch(slots, epoch, env):
            return FakeJob({0: 1} if epoch == 1 else {0: 0})

        epochs = driver.run_job(launch, max_epochs=4)
    finally:
        kv.stop()
    assert epochs == 2
    assert driver.blacklist.count("hostA") == 1
    assert driver.blacklist.drains("hostA") == 0


# ---------------------------------------------------------------------------
# integration: drained recovery vs SIGKILL recovery
# ---------------------------------------------------------------------------

def _spawn_launch_fn(kv_port, worker_args, die_mode):
    def launch(slots, epoch, elastic_env):
        job = launcher.Job()
        for slot in slots:
            env = dict(os.environ)
            env.update({
                "HOROVOD_RANK": str(slot.rank),
                "HOROVOD_SIZE": str(slot.size),
                "HOROVOD_LOCAL_RANK": str(slot.local_rank),
                "HOROVOD_HOSTNAME": slot.hostname,
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
                "HOROVOD_GLOO_RENDEZVOUS_PORT": str(kv_port),
                "HVD_ELASTIC_TEST_DIE": die_mode,
                "HOROVOD_GRACE_SECONDS": "10",
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": launcher.repo_pythonpath(),
            })
            env.update(elastic_env)
            job.procs.append(subprocess.Popen(
                [sys.executable, WORKER] + [str(a) for a in worker_args],
                env=env))
        return job

    return launch


@pytest.mark.slow
def test_drained_recovery_cheaper_than_sigkill(tmp_path):
    """ISSUE 15 acceptance: the same mid-training death, once as a
    graceful eviction (SIGTERM -> announce -> commit -> exit 75) and
    once as a hard SIGKILL. The drained run must recover without blame
    or backoff — measurably cheaper wall-clock than the crash run,
    whose host sits out the backoff window first."""
    results = {}
    for mode in ("evict", "kill"):
        ckpt = tmp_path / mode / "ckpt"
        log = tmp_path / mode / "losses.jsonl"
        log.parent.mkdir(parents=True)
        kv = KVStoreServer()
        kv_port = kv.start()
        try:
            driver = ElasticDriver(
                FixedHosts({"hostA": 1}), min_np=1, kv=kv,
                poll_interval=0.1,
                blacklist=Blacklist(threshold=3, base_delay=4.0))
            launch = _spawn_launch_fn(kv_port, [ckpt, log, 6, "hostA", 2],
                                      die_mode=mode)
            t0 = time.monotonic()
            epochs = driver.run_job(launch, max_epochs=4)
            wall = time.monotonic() - t0
        finally:
            kv.stop()
        assert epochs == 2
        with open(log) as f:
            records = [json.loads(line) for line in f if line.strip()]
        done = [r for r in records if "done" in r]
        assert done and done[0]["done"] == 6
        assert done[0]["resumed_from"] >= 1
        results[mode] = (wall, driver.blacklist)

    wall_evict, bl_evict = results["evict"]
    wall_kill, bl_kill = results["kill"]
    # blame split: the eviction drained, the SIGKILL got charged
    assert bl_evict.drains("hostA") == 1 and bl_evict.count("hostA") == 0
    assert bl_kill.count("hostA") == 1 and bl_kill.drains("hostA") == 0
    # and the drain is cheaper: no backoff window before re-rendezvous
    assert wall_evict < wall_kill, (
        f"drained recovery ({wall_evict:.1f}s) should beat the SIGKILL "
        f"path ({wall_kill:.1f}s, which pays the 4s backoff)")
