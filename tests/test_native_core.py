"""Native core (cxx/) end-to-end tests: real localhost processes.

Reference strategy (SURVEY.md §4): collectives are tested multi-process on
localhost, never mocked. Here the harness spawns N python workers itself
(no mpirun on TPU VMs — that's the point of the TCP control plane)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(__file__), os.pardir)
WORKER = os.path.join(os.path.dirname(__file__), "native_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(scenario, size, env_extra=None, timeout=90):
    port = _free_port()
    # drop any HOROVOD_* inherited from the pytest process (an earlier
    # test may have initialized an adapter or leaked launcher vars) so a
    # scenario's topology/tuning env is exactly env_extra
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("HOROVOD_")}
    env["JAX_PLATFORMS"] = "cpu"  # workers never need a device
    env.update(env_extra or {})
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, scenario, str(r), str(size), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for r in range(size)
    ]
    results = [p.communicate(timeout=timeout) for p in procs]
    for r, (p, (out, err)) in enumerate(zip(procs, results)):
        assert p.returncode == 0, (
            f"rank {r} failed (rc={p.returncode}):\n{out}\n{err}")
    return results


@pytest.mark.parametrize("size", [2, 4])
def test_collectives(size):
    _run_workers("collectives", size)


@pytest.mark.parametrize("size", [2, 4])
def test_adasum_matches_numpy_reference(size):
    _run_workers("adasum", size)


@pytest.mark.parametrize("size,local_size", [(4, 2), (8, 2), (8, 4)])
def test_hierarchical_adasum_matches_schedule_model(size, local_size):
    """op=adasum under an agreed 2-level topology takes the
    RS -> per-chunk Adasum -> AG -> /local_size composite
    (adasum_cuda_operations.cc role); the worker checks the values
    against the exact NumPy schedule model. (8,2) runs a 2-level
    cross tree; (8,4) runs 4 concurrent chunk trees."""
    _run_workers("hierarchical_adasum", size,
                 env_extra={"HOROVOD_LOCAL_SIZE": str(local_size)})


def test_errors_negotiated(tmp_path):
    _run_workers("errors", 2)


@pytest.mark.parametrize("size", [2, 4])
def test_cache_bitvector_cuts_control_bytes(size):
    """Steady state rides the hit-bitvector path: control-plane bytes per
    cycle drop >5x vs full negotiation on a 100-tensor workload."""
    _run_workers("cache_bytes", size, timeout=180)


def test_cache_invalidation_renegotiates():
    _run_workers("cache_invalidation", 2)


def test_autotune_converges_and_syncs(tmp_path):
    """hvdrun --autotune end-to-end: the coordinator's BO loop converges
    within its sample budget and every rank adopts identical tuned
    parameters (reference parameter_manager + SynchronizeParameters)."""
    log = tmp_path / "autotune.csv"
    results = _run_workers("autotune", 4, env_extra={
        "HOROVOD_AUTOTUNE": "1",
        "HOROVOD_AUTOTUNE_LOG": str(log),
        "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
        "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "2",
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "8",
    }, timeout=180)
    import json as _json
    tuned = []
    for out, _ in results:
        line = [l for l in out.splitlines() if l.startswith("TUNED ")][0]
        tuned.append(tuple(_json.loads(line[len("TUNED "):])))
    assert len(set(tuned)) == 1, f"ranks disagree on tuned params: {tuned}"
    # --autotune-log-file wrote header + per-sample rows + converged row
    lines = log.read_text().strip().splitlines()
    assert lines[0].startswith("sample,fusion_threshold,cycle_time_ms")
    assert any(l.startswith("converged,") for l in lines)
    assert len([l for l in lines if not l.startswith(("sample", "converged"))
                ]) >= 8


def _cross_traffic(results):
    import json as _json
    local = cross = 0
    for out, _ in results:
        line = [l for l in out.splitlines() if l.startswith("DATABYTES ")][0]
        lb, cb = _json.loads(line[len("DATABYTES "):])
        local += lb
        cross += cb
    return local, cross


def test_hierarchical_cuts_cross_host_traffic():
    """Faked 2-host x 4-rank topology: the same workload run flat vs
    hierarchical must produce identical values (asserted in the worker)
    while the hierarchical schedule's cross-host bytes drop to about
    1/local_size of the flat ring's total traffic (reference
    nccl_operations.cc:150 schedule + MPIHierarchicalAllgather role)."""
    topo = {"HOROVOD_LOCAL_SIZE": "4"}
    flat = _run_workers("hierarchy", 8, env_extra=topo, timeout=180)
    hier = _run_workers("hierarchy", 8, env_extra={
        **topo,
        "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
        "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
    }, timeout=180)
    flat_local, flat_cross = _cross_traffic(flat)
    hier_local, hier_cross = _cross_traffic(hier)
    assert hier_cross < flat_cross, (
        f"hierarchical cross-host traffic not reduced: "
        f"hier={hier_cross} flat={flat_cross}")
    local_size = 4
    flat_total = flat_local + flat_cross
    assert hier_cross <= flat_total / local_size * 1.25, (
        f"cross-host bytes {hier_cross} not ~1/{local_size} of the flat "
        f"ring's total {flat_total}")


def test_autotune_categorical_dims_explored_and_synced(tmp_path):
    """With a faked 2x2 topology the BO loop searches the categorical
    hierarchical/cache dims alongside (fusion, cycle): the log must show
    both values of each categorical tried, and all ranks must agree on
    the winning combination (reference parameter_manager.h:186-220)."""
    log = tmp_path / "autotune.csv"
    results = _run_workers("autotune", 4, env_extra={
        "HOROVOD_AUTOTUNE": "1",
        "HOROVOD_AUTOTUNE_LOG": str(log),
        "HOROVOD_AUTOTUNE_WARMUP_SAMPLES": "1",
        "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE": "2",
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "8",
        "HOROVOD_LOCAL_SIZE": "2",
    }, timeout=180)
    import json as _json
    tuned = []
    for out, _ in results:
        line = [l for l in out.splitlines() if l.startswith("TUNED ")][0]
        tuned.append(tuple(_json.loads(line[len("TUNED "):])))
    assert len(set(tuned)) == 1, f"ranks disagree on tuned params: {tuned}"
    rows = [l.split(",") for l in log.read_text().strip().splitlines()
            if not l.startswith(("sample", "converged"))]
    hier_vals = {r[3] for r in rows}
    cache_vals = {r[4] for r in rows}
    assert hier_vals == {"0", "1"}, f"hierarchical dim not explored: {rows}"
    assert cache_vals == {"0", "1"}, f"cache dim not explored: {rows}"


def test_hierarchical_gate_agreed_not_split_on_env_drift():
    """Every rank requests hierarchical collectives but rank 0's topology
    env drifted (claims flat): the coordinator must turn the gates off
    for the whole job — a per-rank decision would deadlock mismatched
    ring schedules. The workload completing with exact values IS the
    assertion (a split decision hangs into the timeout)."""
    _run_workers("hierarchy_mismatch", 8, env_extra={
        "HOROVOD_LOCAL_SIZE": "4",
        "HOROVOD_HIERARCHICAL_ALLREDUCE": "1",
        "HOROVOD_HIERARCHICAL_ALLGATHER": "1",
    }, timeout=120)


@pytest.mark.parametrize("size", [2, 4])
def test_zero_copy_enqueue(size):
    """Borrowed buffers move zero host-side memcpy bytes for broadcast
    and single-tensor allreduce (asserted in the worker via the core's
    copy counter)."""
    _run_workers("zerocopy", size)


def test_join_uneven_ranks():
    results = _run_workers("join", 4)
    last = {l for out, _ in results for l in out.splitlines()
            if l.startswith("JOINLAST ")}
    assert len(last) == 1, f"ranks disagree on the last-joined rank: {last}"


@pytest.mark.parametrize("size", [3, 4])
def test_join_with_cached_tensors(size):
    """Hit-path tensors survive a rank joining; new tensors negotiated
    while a rank is joined keep every cache replica in lockstep."""
    _run_workers("join_cached", size, timeout=120)


def test_join_rejects_allgather():
    _run_workers("join_allgather", 3)


def test_timeline_written(tmp_path):
    tl = str(tmp_path / "timeline.json")
    _run_workers("timeline", 2, env_extra={"HOROVOD_TIMELINE": tl})
    assert os.path.exists(tl)


def test_single_process_local():
    """size=1: everything is a local no-op (Horovod semantics)."""
    sys.path.insert(0, REPO)
    from horovod_tpu import _core as core
    core.init(rank=0, size=1)
    try:
        x = np.arange(5, dtype=np.float32)
        np.testing.assert_array_equal(core.allreduce(x, "sp.a"), x)
        np.testing.assert_array_equal(core.allgather(x, "sp.b"), x)
        np.testing.assert_array_equal(core.broadcast(x, "sp.c"), x)
        core.barrier()
    finally:
        core.shutdown()


def test_cxx_unit_tests():
    """The in-process C++ component tests (message/negotiator/cache/...)."""
    rv = subprocess.run(["make", "-C", os.path.join(REPO, "cxx"), "test"],
                        capture_output=True, text=True)
    assert rv.returncode == 0, rv.stdout + rv.stderr
    assert "ALL CXX UNIT TESTS PASSED" in rv.stdout
