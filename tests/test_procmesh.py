"""Unit tests for the process-mesh subsystem (cluster/procmesh):
coordinator-spec env gating, ensure_distributed idempotence, the
(process, local_device) grid and its ICI-first mesh, the contiguous
row-block contract the ckpt/loader paths key on, collective-free
placement, and the per-axis HLO collective attribution that prices the
DCN tier separately from ICI in SCALING_*.json.

Everything here is single-process: multi-process jax.distributed
behaviour is monkeypatched at the seams (fake devices with a
``process_index``, a recorded ``initialize``); the real 2-process
end-to-end contract lives in tests/test_multiprocess.py.
"""

import types

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.cluster import procmesh
from horovod_tpu.parallel import gspmd as gspmd_lib
from horovod_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS


class FakeDevice:
    """The two attributes procmesh reads off a jax device."""

    def __init__(self, device_id, process_index):
        self.id = device_id
        self.process_index = process_index

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"d{self.id}@p{self.process_index}"


def _fake_devices(procs, local):
    return [FakeDevice(p * local + i, p)
            for p in range(procs) for i in range(local)]


# ---------------------------------------------------------------------------
# coordinator_spec: the hvdrun env contract
# ---------------------------------------------------------------------------

class TestCoordinatorSpec:
    def test_no_coordinator_means_single_process(self):
        assert procmesh.coordinator_spec(env={}) is None

    def test_world_of_one_means_single_process(self):
        env = {"HOROVOD_COORDINATOR_ADDR": "127.0.0.1:7777",
               "HOROVOD_SPMD_PROCS": "1"}
        assert procmesh.coordinator_spec(env=env) is None

    def test_spec_from_env(self):
        env = {"HOROVOD_COORDINATOR_ADDR": "127.0.0.1:7777",
               "HOROVOD_SPMD_PROCS": "4", "HOROVOD_RANK": "2"}
        assert procmesh.coordinator_spec(env=env) == \
            ("127.0.0.1:7777", 4, 2)

    def test_procs_defaults_to_world_size(self):
        env = {"HOROVOD_COORDINATOR_ADDR": "h0:1234",
               "HOROVOD_SIZE": "2", "HOROVOD_RANK": "1"}
        assert procmesh.coordinator_spec(env=env) == ("h0:1234", 2, 1)


# ---------------------------------------------------------------------------
# ensure_distributed: the ONE initialize call site, idempotent
# ---------------------------------------------------------------------------

class _DistStub:
    """Recorded seams of ensure_distributed: initialize calls and
    jax.config updates. Patching ``jax.config.update`` matters beyond
    bookkeeping — the real call would set the gloo CPU collectives
    implementation in THIS process, and every later backend init in
    the test session would then demand a distributed client."""

    def __init__(self):
        self.init_calls = []
        self.config_updates = []


@pytest.fixture
def dist_stub(monkeypatch):
    procmesh._reset_for_tests()
    stub = _DistStub()
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: stub.init_calls.append(kw))
    monkeypatch.setattr(jax.config, "update",
                        lambda k, v: stub.config_updates.append((k, v)))
    monkeypatch.setattr(procmesh, "_backend_live", lambda: False)
    monkeypatch.setattr(procmesh, "_foreign_distributed", lambda: False)
    yield stub
    procmesh._reset_for_tests()


class TestEnsureDistributed:
    def test_single_process_is_a_noop(self, dist_stub):
        assert procmesh.ensure_distributed(env={}) is False
        assert dist_stub.init_calls == []
        assert procmesh.is_multiprocess() is False

    def test_joins_once_then_remembers(self, dist_stub):
        env = {"HOROVOD_COORDINATOR_ADDR": "127.0.0.1:7777",
               "HOROVOD_SPMD_PROCS": "2", "HOROVOD_RANK": "0",
               "JAX_PLATFORMS": "cpu"}
        assert procmesh.ensure_distributed(env=env) is True
        assert procmesh.ensure_distributed(env=env) is True
        assert dist_stub.init_calls == [
            {"coordinator_address": "127.0.0.1:7777",
             "num_processes": 2, "process_id": 0}]
        assert ("jax_cpu_collectives_implementation", "gloo") in \
            dist_stub.config_updates
        assert procmesh.is_multiprocess() is True

    def test_rejoining_a_different_coordinator_raises(self, dist_stub):
        env = {"HOROVOD_COORDINATOR_ADDR": "127.0.0.1:7777",
               "HOROVOD_SPMD_PROCS": "2", "HOROVOD_RANK": "0",
               "JAX_PLATFORMS": "cpu"}
        procmesh.ensure_distributed(env=env)
        env["HOROVOD_COORDINATOR_ADDR"] = "127.0.0.1:8888"
        with pytest.raises(RuntimeError, match="cannot re-join"):
            procmesh.ensure_distributed(env=env)

    def test_live_backend_with_coordinator_raises(
            self, dist_stub, monkeypatch):
        monkeypatch.setattr(procmesh, "_backend_live", lambda: True)
        env = {"HOROVOD_COORDINATOR_ADDR": "127.0.0.1:7777",
               "HOROVOD_SPMD_PROCS": "2", "HOROVOD_RANK": "0"}
        with pytest.raises(RuntimeError, match="already initialized"):
            procmesh.ensure_distributed(env=env)

    def test_foreign_init_is_adopted(self, dist_stub, monkeypatch):
        monkeypatch.setattr(procmesh, "_foreign_distributed",
                            lambda: True)
        assert procmesh.ensure_distributed(env={}) is True
        assert dist_stub.init_calls == []  # adopted, not re-initialized
        assert procmesh.is_multiprocess() is True

    def test_cpu_device_count_merged_into_xla_flags(self, dist_stub):
        env = {"HOROVOD_COORDINATOR_ADDR": "h:1", "HOROVOD_SPMD_PROCS":
               "2", "HOROVOD_RANK": "0", "JAX_PLATFORMS": "cpu",
               "HOROVOD_SPMD_LOCAL_DEVICES": "4"}
        procmesh.ensure_distributed(env=env)
        assert "--xla_force_host_platform_device_count=4" in \
            env["XLA_FLAGS"]

    def test_user_set_device_count_wins(self, dist_stub):
        env = {"HOROVOD_COORDINATOR_ADDR": "h:1", "HOROVOD_SPMD_PROCS":
               "2", "HOROVOD_RANK": "0", "JAX_PLATFORMS": "cpu",
               "HOROVOD_SPMD_LOCAL_DEVICES": "4",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
        procmesh.ensure_distributed(env=env)
        assert env["XLA_FLAGS"] == \
            "--xla_force_host_platform_device_count=8"


# ---------------------------------------------------------------------------
# process_grid / build_process_mesh / tiers / contiguity
# ---------------------------------------------------------------------------

class TestProcessGrid:
    def test_rows_are_processes_in_id_order(self):
        grid = procmesh.process_grid(_fake_devices(2, 4))
        assert grid.shape == (2, 4)
        assert [[d.id for d in row] for row in grid] == \
            [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert [{d.process_index for d in row} for row in grid] == \
            [{0}, {1}]

    def test_shuffled_input_still_sorts(self):
        devs = _fake_devices(2, 2)
        grid = procmesh.process_grid(devs[::-1])
        assert [[d.id for d in row] for row in grid] == [[0, 1], [2, 3]]

    def test_ragged_process_counts_raise(self):
        devs = _fake_devices(2, 2) + [FakeDevice(9, 1)]
        with pytest.raises(ValueError, match="ragged"):
            procmesh.process_grid(devs)

    def test_single_process_mesh_is_1d_data(self):
        # the real in-process devices: conftest forces 8 CPU chips
        mesh = procmesh.build_process_mesh()
        assert mesh.axis_names == (DATA_AXIS,)
        assert mesh.devices.shape == (len(jax.devices()),)

    def test_mesh_tiers_two_tier(self):
        grid = procmesh.process_grid(_fake_devices(2, 4))
        mesh = types.SimpleNamespace(devices=grid,
                                     axis_names=(DCN_AXIS, DATA_AXIS))
        tiers = procmesh.mesh_tiers(mesh)
        assert [(t["axis"], t["size"], t["tier"]) for t in tiers] == \
            [(DCN_AXIS, 2, "dcn"), (DATA_AXIS, 4, "ici")]

    def test_mesh_tiers_single_tier(self):
        mesh = procmesh.build_process_mesh()
        (tier,) = procmesh.mesh_tiers(mesh)
        assert tier["tier"] == "ici"

    def test_contiguous_mesh_passes(self):
        grid = procmesh.process_grid(_fake_devices(2, 4))
        mesh = types.SimpleNamespace(devices=grid,
                                     axis_names=(DCN_AXIS, DATA_AXIS))
        procmesh.assert_process_contiguous(mesh)

    def test_row_spanning_two_processes_raises(self):
        grid = procmesh.process_grid(_fake_devices(2, 2))
        scrambled = grid.copy()
        scrambled[0, 1], scrambled[1, 0] = grid[1, 0], grid[0, 1]
        mesh = types.SimpleNamespace(devices=scrambled,
                                     axis_names=(DCN_AXIS, DATA_AXIS))
        with pytest.raises(ValueError, match="spans processes"):
            procmesh.assert_process_contiguous(mesh)

    def test_rows_out_of_process_order_raise(self):
        grid = procmesh.process_grid(_fake_devices(2, 2))
        mesh = types.SimpleNamespace(devices=grid[::-1],
                                     axis_names=(DCN_AXIS, DATA_AXIS))
        with pytest.raises(ValueError, match="process order"):
            procmesh.assert_process_contiguous(mesh)


class TestLocalRowBlock:
    def test_single_process_owns_everything(self):
        assert procmesh.local_row_block(16) == (0, 16)

    def test_block_is_the_process_slice(self, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        monkeypatch.setattr(jax, "process_index", lambda: 2)
        assert procmesh.local_row_block(16) == (8, 12)

    def test_indivisible_batch_raises(self, monkeypatch):
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        with pytest.raises(ValueError, match="not divisible"):
            procmesh.local_row_block(10)


# ---------------------------------------------------------------------------
# placement: shard_from_global / place (single-process semantics; the
# cross-process no-collective property is exercised in
# tests/test_multiprocess.py where it is actually load-bearing)
# ---------------------------------------------------------------------------

class TestPlacement:
    def test_shard_from_global_reassembles_the_value(self):
        mesh = procmesh.build_process_mesh()
        sharding = NamedSharding(mesh, P(DATA_AXIS))
        x = np.arange(32, dtype=np.float32).reshape(8, 4)
        g = procmesh.shard_from_global(x, sharding)
        assert g.shape == x.shape
        np.testing.assert_array_equal(np.asarray(g), x)
        # committed to the sharding — stepping on it won't re-place
        assert g.sharding == sharding

    def test_place_matches_device_put_single_process(self):
        mesh = procmesh.build_process_mesh()
        sharding = NamedSharding(mesh, P(DATA_AXIS))
        x = np.arange(16, dtype=np.float32).reshape(8, 2)
        np.testing.assert_array_equal(
            np.asarray(procmesh.place(x, sharding)),
            np.asarray(jax.device_put(x, sharding)))

    def test_place_is_stable_on_committed_arrays(self):
        mesh = procmesh.build_process_mesh()
        sharding = NamedSharding(mesh, P())
        x = procmesh.place(np.float32(3.5), sharding)
        y = procmesh.place(x, sharding)
        assert float(np.asarray(y)) == 3.5


# ---------------------------------------------------------------------------
# per-axis collective attribution (gspmd.collective_axis_bytes_from_hlo)
# against the replica-group formats this XLA actually emits
# ---------------------------------------------------------------------------

def _tier_mesh():
    """A fake (2, 4) (dcn, data) mesh — group_axes only reads
    ``devices.shape`` and ``axis_names``."""
    return types.SimpleNamespace(
        devices=np.empty((2, 4), dtype=object),
        axis_names=(DCN_AXIS, DATA_AXIS))


class TestGroupAxes:
    def test_explicit_groups_within_rows_are_data(self):
        groups = gspmd_lib._parse_device_groups(
            "  x = f32[4] all-reduce(y), replica_groups={{0,1,2,3},"
            "{4,5,6,7}}, to_apply=add")
        assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert gspmd_lib.group_axes(groups, _tier_mesh()) == (DATA_AXIS,)

    def test_column_pairs_are_dcn(self):
        groups = gspmd_lib._parse_device_groups(
            "  x = f32[4] collective-permute(y), "
            "source_target_pairs={{0,4},{4,0}}")
        assert gspmd_lib.group_axes(groups, _tier_mesh()) == (DCN_AXIS,)

    def test_global_group_spans_both_tiers(self):
        groups = [[0, 1, 2, 3, 4, 5, 6, 7]]
        assert gspmd_lib.group_axes(groups, _tier_mesh()) == \
            (DCN_AXIS, DATA_AXIS)

    def test_iota_v2_groups(self):
        groups = gspmd_lib._parse_device_groups(
            "  ar = f32[8] all-reduce(p), replica_groups=[2,4]<=[8], "
            "to_apply=add")
        assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_iota_v2_transposed_groups(self):
        groups = gspmd_lib._parse_device_groups(
            "  ar = f32[8] all-reduce(p), "
            "replica_groups=[4,2]<=[2,4]T(1,0), to_apply=add")
        # transpose pairs device p of row 0 with device p of row 1:
        # the cross-process (dcn) tier
        assert groups == [[0, 4], [1, 5], [2, 6], [3, 7]]
        assert gspmd_lib.group_axes(groups, _tier_mesh()) == (DCN_AXIS,)

    def test_line_without_groups_is_none(self):
        assert gspmd_lib._parse_device_groups(
            "  add = f32[4] add(a, b)") is None


class TestCollectiveAxisBytes:
    def test_labels_split_by_tier(self):
        hlo = "\n".join([
            "ENTRY main {",
            "  ar0 = f32[1024]{0} all-reduce(g), "
            "replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=add",
            "  ar1 = f32[256]{0} all-reduce(h), "
            "replica_groups={{0,4},{1,5},{2,6},{3,7}}, to_apply=add",
            "  ar2 = f32[16]{0} all-reduce(i), "
            "replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=add",
            "}",
        ])
        out = gspmd_lib.collective_axis_bytes_from_hlo(hlo, _tier_mesh())
        assert set(out) == {DATA_AXIS, DCN_AXIS,
                            f"{DCN_AXIS}+{DATA_AXIS}"}
        assert out[DATA_AXIS]["bytes"] == 4096
        assert out[DCN_AXIS]["bytes"] == 1024
        assert out[f"{DCN_AXIS}+{DATA_AXIS}"]["bytes"] == 64
        assert out[DATA_AXIS]["ops"] == {"all-reduce": 4096}

    def test_groupless_collective_lands_in_replica(self):
        hlo = ("  ar = f32[64]{0} all-reduce(g), to_apply=add\n")
        out = gspmd_lib.collective_axis_bytes_from_hlo(hlo, _tier_mesh())
        assert out == {"replica": {"calls": 1, "bytes": 256,
                                   "ops": {"all-reduce": 256}}}

    def test_agrees_with_untiered_totals(self):
        """The per-axis split must partition the flat accounting —
        same lines, same byte semantics, just bucketed."""
        hlo = "\n".join([
            "  ar = f32[1024]{0} all-reduce(g), "
            "replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=add",
            "  ag = f32[2048]{0} all-gather(p), "
            "replica_groups={{0,4},{1,5},{2,6},{3,7}}, dimensions={0}",
        ])
        flat = gspmd_lib.collective_bytes_from_hlo(hlo)
        tiered = gspmd_lib.collective_axis_bytes_from_hlo(
            hlo, _tier_mesh())
        assert sum(v["bytes"] for v in tiered.values()) == \
            sum(v["bytes"] for v in flat.values())
        assert sum(v["calls"] for v in tiered.values()) == \
            sum(v["calls"] for v in flat.values())


# ---------------------------------------------------------------------------
# bench_scaling world parsing
# ---------------------------------------------------------------------------

def _bench_scaling():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "bench_scaling.py")
    spec = importlib.util.spec_from_file_location("bench_scaling", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestParseWorlds:
    def test_parses_the_sweep_grammar(self):
        bs = _bench_scaling()
        assert bs.parse_worlds("1x1,1x2,2x1,2x2") == \
            [(1, 1), (1, 2), (2, 1), (2, 2)]

    def test_rejects_garbage(self):
        bs = _bench_scaling()
        with pytest.raises(SystemExit):
            bs.parse_worlds("2by2")
        with pytest.raises(SystemExit):
            bs.parse_worlds("0x2")
        with pytest.raises(SystemExit):
            bs.parse_worlds("")
