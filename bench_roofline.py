"""HBM roofline for the headline ResNet step — and, with ``--lm``, for
the MXU-saturating d2048 transformer LM step.

Is the measured MFU the hardware bound or a software gap? This script
answers with numbers, not claims:

* per-device ``flops`` and ``bytes accessed`` of the ACTUAL compiled
  train step, from XLA's own cost analysis;
* the chip's empirical bf16 matmul peak (``bench.calibrate_peak_tflops``
  — a measured ceiling, not a datasheet number);
* the chip's empirical HBM bandwidth: a streaming elementwise chain with
  ``optimization_barrier`` between iterations (defeats loop fusion, so
  every iteration really moves read+write bytes), timed by the readback
  slope protocol;
* the roofline bound ``t >= max(flops/peak, bytes/bw)`` vs the measured
  step time, and the achieved/bound ratio.

``--lm`` (VERDICT weak #3) judges the LM MFU against its ACTUAL bound:
the same compiled ``cost_analysis()`` flops+bytes for the d2048
flash-attention transformer step (the ``lm_d2048`` workload bench.py's
LM MFU line runs) against the same empirical ceilings, emitting
``lm_roofline_achieved_over_bound`` — so a ~63% LM MFU can be read as
"x% of what this step could physically do", not against the matmul peak
alone.

Prints ONE JSON line per invocation. Findings are recorded in
BENCH_NOTES.md.
"""

import argparse
import json
import statistics

import jax
import jax.numpy as jnp
import numpy as np
import optax


def measure_hbm_bandwidth(nbytes=1 << 29, chain=8, repeats=3):
    """Empirical streaming bandwidth: x <- x + 1 on an nbytes buffer,
    ``chain`` barrier-separated iterations per call (each moves
    2*nbytes: one read + one write), slope-timed."""
    from horovod_tpu.utils.benchmarks import slope_window, sync

    n = nbytes // 2  # bf16
    x = jnp.zeros((n,), jnp.bfloat16)

    @jax.jit
    def stream(x):
        for _ in range(chain):
            x = jax.lax.optimization_barrier(x + jnp.bfloat16(1.0))
        return x

    x = stream(x)
    sync(x)
    samples = []
    for _ in range(repeats):
        dt, x = slope_window(lambda v: (stream(v),) * 2, x, iters=4,
                             base_iters=1)
        samples.append(4 * chain * 2 * nbytes / dt / 1e9)
    return statistics.median(samples)


def _roofline_result(metric, flops, bytes_accessed, peak_tf, bw_gbs,
                     step_s):
    """The shared roofline arithmetic + JSON shape for both workloads:
    one copy, so the ResNet and LM lines cannot compute their bound or
    MFU fields differently."""
    # publish what WAS measurable even when a ceiling calibration fails
    # (peak/bandwidth of 0 would otherwise divide-by-zero)
    t_compute = flops / (peak_tf * 1e12) if peak_tf > 0 else 0.0
    t_memory = bytes_accessed / (bw_gbs * 1e9) if bw_gbs > 0 else 0.0
    t_bound = max(t_compute, t_memory)
    result = {
        "metric": metric,
        "value": round(t_bound / step_s, 3) if t_bound else None,
        "unit": "ratio",
        "flops_per_step": flops,
        "bytes_accessed_per_step": bytes_accessed,
        "arithmetic_intensity_flops_per_byte": round(
            flops / bytes_accessed, 2) if bytes_accessed else None,
        "empirical_peak_tflops_bf16": round(peak_tf, 1),
        "empirical_hbm_gbs": round(bw_gbs, 1),
        "t_compute_ms": round(1e3 * t_compute, 2),
        "t_memory_ms": round(1e3 * t_memory, 2),
        "t_bound_ms": round(1e3 * t_bound, 2),
        "t_measured_ms": round(1e3 * step_s, 2),
        "bound_by": "memory" if t_memory > t_compute else "compute",
    }
    if peak_tf > 0:
        result["mfu_vs_empirical_peak_pct"] = round(
            100 * flops / step_s / (peak_tf * 1e12), 1)
    if t_bound > 0:
        result["mfu_bound_pct"] = round(100 * t_compute / t_bound, 1)
    return result


def resnet_roofline(args):
    import bench
    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.utils.benchmarks import (make_model, repeat_throughput,
                                              synthetic_batch)

    hvd.init()
    model = make_model(args.model)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    images, labels = synthetic_batch(args.batch_size * hvd.num_devices(),
                                     args.image_size)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        images[:1])
    step = training.make_train_step(model, tx, donate=True)
    from horovod_tpu.utils.benchmarks import cost_analysis_dict
    cost = cost_analysis_dict(
        step.lower(state, images, labels).compile())
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))

    peak_tf, _ = bench.calibrate_peak_tflops()
    bw_gbs = measure_hbm_bandwidth()

    runs = repeat_throughput(step, state, images, labels, warmup=3,
                             iters=args.num_iters, repeats=args.repeats)
    step_s = statistics.median(r[1] for r in runs) / args.num_iters
    print(json.dumps(_roofline_result(
        f"{args.model}_roofline_achieved_over_bound", flops,
        bytes_accessed, peak_tf, bw_gbs, step_s)))


def lm_roofline(args):
    """``--lm``: the d2048 flash-attention transformer step (the exact
    ``lm_d2048`` workload carrying bench.py's LM MFU) against the same
    empirical ceilings — its ~63% MFU judged against the step's ACTUAL
    roofline bound, not the pure-matmul peak (VERDICT weak #3)."""
    import bench
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.utils.benchmarks import (make_lm_bench,
                                              repeat_step_windows)

    hvd.init()
    devs = np.asarray(jax.devices())
    mesh = jax.sharding.Mesh(devs[:1].reshape(1, 1), ("data", "seq"))
    step, state, tokens = make_lm_bench(
        mesh=mesh, seq_axis=None, batch=args.lm_batch,
        seq_len=args.lm_seq_len, layers=args.lm_layers,
        d_model=args.lm_d_model, heads=args.lm_heads,
        vocab=args.lm_vocab, flash=True)
    from horovod_tpu.utils.benchmarks import cost_analysis_dict
    cost = cost_analysis_dict(step.lower(state, tokens).compile())
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))

    peak_tf, _ = bench.calibrate_peak_tflops()
    bw_gbs = measure_hbm_bandwidth()

    dts, state = repeat_step_windows(
        lambda st: step(st, tokens), state, 2, args.num_iters,
        max(1, args.repeats))
    step_s = statistics.median(float(d) for d in dts) / args.num_iters
    result = _roofline_result(
        "lm_roofline_achieved_over_bound", flops, bytes_accessed,
        peak_tf, bw_gbs, step_s)
    n_bound = sum(1 for d in dts if getattr(d, "upper_bound", False))
    if n_bound:  # inverted-window fallbacks: bounds, not measurements
        result["upper_bound_windows"] = n_bound
    result.update({
        "lm_d_model": args.lm_d_model, "lm_layers": args.lm_layers,
        "lm_heads": args.lm_heads, "lm_seq_len": args.lm_seq_len,
        "lm_batch": args.lm_batch,
        "tokens_per_sec": round(args.lm_batch * args.lm_seq_len / step_s,
                                1),
    })
    print(json.dumps(result))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet101")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--lm", action="store_true",
                    help="roofline the d2048 transformer LM step instead "
                         "of the ResNet step (the bench.py LM MFU "
                         "workload; emits lm_roofline_achieved_over_bound)")
    ap.add_argument("--lm-d-model", type=int, default=2048)
    ap.add_argument("--lm-layers", type=int, default=8)
    ap.add_argument("--lm-heads", type=int, default=16)
    ap.add_argument("--lm-seq-len", type=int, default=2048)
    ap.add_argument("--lm-batch", type=int, default=8)
    ap.add_argument("--lm-vocab", type=int, default=32000)
    args = ap.parse_args()

    if args.lm:
        lm_roofline(args)
        return
    resnet_roofline(args)


if __name__ == "__main__":
    main()
