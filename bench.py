"""Headline benchmark: synthetic ResNet img/sec through the full framework
hot path (DistributedOptimizer -> fused allreduce -> optimizer update,
compiled over the global mesh).

The TPU analogue of the reference's synthetic benchmarks
(``/root/reference/examples/pytorch_synthetic_benchmark.py``: timed batches
after warmup, img/sec) and of ``tf_cnn_benchmarks`` as used for the
published numbers (``docs/benchmarks.rst:16-42``).

Baseline for ``vs_baseline``: the reference's documented sample output —
ResNet-101, batch 64/GPU, 16 Pascal GPUs: "total images/sec: 1656.82"
(``docs/benchmarks.rst:28-42``), i.e. **103.55 img/s per chip**. We run the
same workload (ResNet-101, synthetic data) per TPU chip.

Per-chip batch defaults to 256: the reference protocol is "the batch that
keeps the accelerator busy" (64 filled a 2017 P100); ``--batch-size 64``
reproduces the literal reference configuration.

MEASUREMENT PROTOCOL (corrected in round 4): all windows are timed by a
forced host READBACK and reported as the difference of a short and a
long window (``utils/benchmarks.repeat_throughput``). Rounds 1-3 ended
windows with ``jax.block_until_ready``, which does NOT synchronize
through the async execution tunnel — it inflated img/s ~6x (r03
reported 10,719 img/s/chip = 486 "achieved TF/s", physically impossible
on silicon whose best pure bf16 matmul sustains ~180 TF/s). The slope
method cancels both the enqueue undercount and the ~100 ms readback
cost; the honest number on this chip is ~1,760 img/s (~80 cost-TF/s,
~43% of the empirically calibrated matmul peak). See BENCH_NOTES.md.

Prints ONE JSON line with metric/value/unit/vs_baseline plus achieved
TFLOP/s, the empirically calibrated peak (``--calibrate`` runs only the
calibration), MFU against that peak, and LM tokens/sec with the flash
kernel on/off. ``--repeats`` (default 5) reports the MEDIAN window with
min/max spread.
"""

import argparse
import json
import statistics

import jax
import optax

# reference docs/benchmarks.rst:28-42 — 1656.82 img/s over 16 Pascal GPUs
BASELINE_IMG_PER_SEC_PER_CHIP = 1656.82 / 16


def calibrate_peak_tflops(repeats=3):
    """Empirical bf16 MXU peak: best sustained TFLOP/s of a pure-matmul
    chain, timed by the readback slope method (utils/benchmarks.sync —
    block_until_ready does not synchronize through the async tunnel).
    The denominator for an honest MFU is measured, not looked up:
    nothing this chip runs can exceed its own best matmul."""
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.utils.benchmarks import sync
    best = 0.0
    best_shape = None
    steps = 32
    rng = np.random.default_rng(0)
    for n in (4096, 8192):
        # near-unit spectral radius keeps the chain finite in bf16
        b = jnp.asarray(rng.standard_normal((n, n)) / (n ** 0.5),
                        jnp.bfloat16)
        x0 = jnp.ones((n, n), jnp.bfloat16)

        @jax.jit
        def chain(x, b=b):
            for _ in range(steps):
                x = jax.lax.dot(x, b,
                                preferred_element_type=jnp.bfloat16)
            return x

        x = chain(x0)
        sync(x)  # compile + true sync

        from horovod_tpu.utils.benchmarks import slope_window
        flops_per_chain = 2.0 * n * n * n * steps
        samples = []
        for _ in range(repeats):
            # step_once threads x (fresh inputs every call) and yields
            # it as the syncable too
            dt, x = slope_window(lambda v: (chain(v),) * 2, x,
                                 iters=4, base_iters=1)
            samples.append(4 * flops_per_chain / dt / 1e12)
        # median per shape (a best-of on noisy slopes biases high),
        # best shape wins
        tf_s = statistics.median(samples)
        if tf_s > best:
            best, best_shape = tf_s, n
    return best, best_shape


def lm_tokens_per_sec(flash, *, seq_len=2048, batch=8, layers=12,
                      d_model=768, heads=12, vocab=32000, steps=10,
                      warmup=3, seq_parallel=False):
    """Single-window LM training throughput (the shared
    ``make_lm_bench`` workload — exactly what jax_lm_benchmark.py
    runs). Returns ``(tokens_per_sec, achieved_tflops)`` where the
    TFLOP/s come from XLA's own per-device cost analysis of the step
    (0.0 when unavailable) — the LM MFU numerator."""
    import numpy as np

    from horovod_tpu.utils.benchmarks import (make_lm_bench, slope_window,
                                              sync)

    devs = np.asarray(jax.devices())
    n_seq = devs.size if seq_parallel and devs.size > 1 else 1
    mesh = jax.sharding.Mesh(devs[:n_seq].reshape(1, n_seq),
                             ("data", "seq"))
    step, state, tokens = make_lm_bench(
        mesh=mesh, seq_axis="seq" if n_seq > 1 else None, batch=batch,
        seq_len=seq_len, layers=layers, d_model=d_model, heads=heads,
        vocab=vocab, flash=flash)
    flops_per_step = 0.0
    try:
        from horovod_tpu.utils.benchmarks import cost_analysis_dict
        cost = cost_analysis_dict(step.lower(state, tokens).compile())
        flops_per_step = float(cost.get("flops", 0.0))
    # hvd-lint: disable=HVD-EXCEPT -- cost model is optional: missing flops only disables MFU
    except Exception:
        pass
    for _ in range(warmup):
        state, loss = step(state, tokens)
        sync(loss)
    dt, _ = slope_window(lambda st: step(st, tokens), state, steps)
    return (batch * seq_len * steps / dt,
            flops_per_step * steps / dt / 1e12)


def _opt_state_bytes_per_device(opt_state):
    """Measured per-device optimizer-state bytes: the bytes of every
    state leaf's shards resident on device 0 (replicated leaves count in
    full, ZeRO-sharded bucket rows count 1/N) — the ZeRO-1 memory claim
    read off the real arrays, not computed from the plan."""
    import jax as _jax
    dev0 = _jax.local_devices()[0]
    total = 0
    for leaf in _jax.tree_util.tree_leaves(opt_state):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            total += np_nbytes(leaf)
            continue
        total += sum(s.data.nbytes for s in shards if s.device == dev0)
    return total


def np_nbytes(x):
    import numpy as np
    a = np.asarray(x)
    return a.size * a.dtype.itemsize


def _record_step_time(args, step, state, images, labels, result, suffix):
    """Shared timing summary for the comparison modes: median
    slope-window step time into ``step_ms_<suffix>`` plus the
    conservative-bound count — one implementation so --overlap and
    --compression can never report inconsistently computed numbers."""
    from horovod_tpu.utils.benchmarks import repeat_throughput

    runs = repeat_throughput(step, state, images, labels,
                             max(args.num_warmup - 1, 0),
                             args.num_iters, args.repeats)
    dts = sorted(float(r[1]) for r in runs)
    dt = dts[len(dts) // 2]
    result[f"step_ms_{suffix}"] = round(1000 * dt / args.num_iters, 2)
    n_bound = sum(1 for r in runs
                  if getattr(r[1], "upper_bound", False))
    if n_bound:
        result[f"upper_bound_windows_{suffix}"] = n_bound


def overlap_variants(compression=None):
    """The ``--overlap`` comparison matrix: the three exchange variants,
    extended with ``overlap_rs_zero1_<fmt>`` (the FULL pipeline —
    overlapped exchange + ZeRO-1 + compressed wire) for each requested
    wire format. Formats are validated here so a typo dies before any
    compile. One function so the CLI contract and its test cannot
    drift."""
    from horovod_tpu.ops import compression as compression_lib

    variants = {
        "baseline_fused_ar": dict(sharded=False, overlap=False),
        "overlap_rs": dict(sharded=False, overlap=True),
        "overlap_rs_zero1": dict(sharded=True, overlap=True),
    }
    wire_formats = []
    if compression is not None:
        wire_formats = [f for f in (list(compression)
                                    or ["bf16", "fp8", "int8"])
                        if f != "none"]
        for f in wire_formats:
            compression_lib.by_name(f)  # fail fast on a typo
        for fmt in wire_formats:
            variants[f"overlap_rs_zero1_{fmt}"] = dict(
                sharded=True, overlap=True, wire=fmt)
    return variants, wire_formats


def overlap_comparison(args):
    """``--overlap``: step time for {baseline fused-allreduce, overlapped
    reduce-scatter pipeline, overlapped + ZeRO-1 sharded update} on the
    same comm-heavy workload (same model, same global batch, same
    accum_steps), plus measured per-device optimizer-state bytes.
    Combined with ``--compression`` the matrix extends with the FULL
    pipeline — overlapped + ZeRO-1 at each requested wire format
    (``overlap_rs_zero1_<fmt>``) — so prefetch-era rounds can benchmark
    the whole exchange (overlap + compressed wire) in one run instead of
    two mutually-exclusive modes. One JSON line, same contract as the
    headline bench."""
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.utils.benchmarks import make_model, synthetic_batch

    variants, wire_formats = overlap_variants(args.compression)

    hvd.init()
    ndev = hvd.num_devices()
    K = args.accum_steps
    global_batch = args.batch_size * ndev
    images, labels = synthetic_batch(global_batch, args.image_size)

    result = {"metric": f"{args.model}_overlap_pipeline_step_ms",
              "unit": "ms/step", "accum_steps": K, "devices": ndev,
              "per_chip_batch": args.batch_size, "repeats": args.repeats}
    if wire_formats:
        result["wire_formats"] = wire_formats
    for name, kind in variants.items():
        # adamw: momentum + second moment = the optimizer state ZeRO-1
        # shards; a fresh model+tx per variant so donation can't alias
        model = make_model(args.model)
        tx = hvd.DistributedOptimizer(optax.adamw(1e-3),
                                      sharded_update=kind["sharded"],
                                      compression=kind.get("wire"))
        step = training.make_train_step(model, tx, donate=True,
                                        accum_steps=K,
                                        overlap_grads=kind["overlap"])
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0),
                                            images[:1])
        # run one real step to materialize the placed/donated state, then
        # read the optimizer-state footprint off the live arrays
        state, _ = step(state, images, labels)
        result[f"opt_state_bytes_per_device_{name}"] = (
            _opt_state_bytes_per_device(state.opt_state))
        _record_step_time(args, step, state, images, labels, result, name)
    base = result.get("opt_state_bytes_per_device_baseline_fused_ar", 0)
    z1 = result.get("opt_state_bytes_per_device_overlap_rs_zero1", 0)
    if base and z1:
        result["zero1_opt_state_shrink_factor"] = round(base / z1, 2)
    if result.get("step_ms_baseline_fused_ar", 0):
        for name in variants:
            if name != "baseline_fused_ar" and \
                    result.get(f"step_ms_{name}"):
                result[f"speedup_{name}_vs_baseline"] = round(
                    result["step_ms_baseline_fused_ar"] /
                    result[f"step_ms_{name}"], 3)
    result["telemetry"] = _telemetry_block()
    _attach_goodput(result)
    print(json.dumps(result))


def compression_comparison(args):
    """``--compression``: the overlapped bucket pipeline at each requested
    wire format on the same workload — step time, bytes-on-wire, and the
    logical/wire compression ratio per format (docs/PERFORMANCE.md,
    "Wire compression"). Bytes come from the telemetry counters, which
    advance at TRACE time on the compiled path: the delta across the
    first (tracing) step call is the wire volume baked into one compiled
    step. One JSON line, same contract as the headline bench."""
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import telemetry, training
    from horovod_tpu.ops import compression as compression_lib
    from horovod_tpu.telemetry import instruments
    from horovod_tpu.utils.benchmarks import make_model, synthetic_batch

    formats = list(args.compression) or ["none", "bf16", "fp8", "int8"]
    for f in formats:
        compression_lib.by_name(f)  # fail fast on a typo
    if "none" not in formats:
        formats = ["none"] + formats  # ratio/speedup need the baseline

    hvd.init()
    ndev = hvd.num_devices()
    K = args.accum_steps
    global_batch = args.batch_size * ndev
    images, labels = synthetic_batch(global_batch, args.image_size)
    reg = telemetry.get_registry()

    def wire_totals():
        # bucket_* labels only: the pipeline's bucket counters aggregate
        # the primitive dispatches they wrap (alltoall/allgather/...),
        # which record under their own op labels too — summing every
        # label would double-count the same bytes
        out = []
        for name in (instruments.COLLECTIVE_BYTES,
                     instruments.COLLECTIVE_LOGICAL_BYTES):
            fam = reg.get(name)
            s = fam.sample() if fam is not None else {}
            if not isinstance(s, dict):
                out.append(float(s or 0.0))
                continue
            out.append(float(sum(
                v for k, v in s.items()
                if any(str(part).startswith("bucket_") for part in k))))
        return out

    result = {"metric": f"{args.model}_wire_compression_step_ms",
              "unit": "ms/step", "accum_steps": K, "devices": ndev,
              "per_chip_batch": args.batch_size, "repeats": args.repeats}
    for name in formats:
        model = make_model(args.model)
        tx = hvd.DistributedOptimizer(optax.sgd(1e-3, momentum=0.9),
                                      compression=name)
        step = training.make_train_step(model, tx, donate=True,
                                        accum_steps=K, overlap_grads=True)
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0),
                                            images[:1])
        w0, l0 = wire_totals()
        state, _ = step(state, images, labels)  # traces + compiles
        w1, l1 = wire_totals()
        wire_b, logical_b = w1 - w0, l1 - l0
        result[f"wire_bytes_per_step_{name}"] = int(wire_b)
        result[f"logical_bytes_per_step_{name}"] = int(logical_b)
        if wire_b > 0:
            result[f"compression_ratio_{name}"] = round(
                logical_b / wire_b, 3)
        _record_step_time(args, step, state, images, labels, result, name)
    if result.get("step_ms_none"):
        for name in formats:
            if name != "none" and result.get(f"step_ms_{name}"):
                result[f"speedup_{name}_vs_none"] = round(
                    result["step_ms_none"] / result[f"step_ms_{name}"], 3)
    result["telemetry"] = _telemetry_block()
    _attach_goodput(result)
    print(json.dumps(result))


def _record_lm_step_time(args, step, state, tokens, result, suffix):
    """LM-path timing summary for ``--spmd`` (the LM step takes
    ``(state, tokens)``): median slope-window step time into
    ``lm_step_ms_<suffix>`` plus the conservative-bound count — the
    same discipline as ``_record_step_time``, via the one shared
    warm-then-measure helper. Unlike the ResNet path — which burns one
    warmup on the state-materializing step call before its timing — the
    LM path arrives here cold, so the FULL ``num_warmup`` runs (the
    slope window's untimed flush would absorb a stray compile either
    way, but the two paths should enter their windows equally warm)."""
    from horovod_tpu.utils.benchmarks import repeat_step_windows

    dts, state = repeat_step_windows(
        lambda st: step(st, tokens), state,
        args.num_warmup, args.num_iters, args.repeats)
    ordered = sorted(float(d) for d in dts)
    result[f"lm_step_ms_{suffix}"] = round(
        1000 * ordered[len(ordered) // 2] / args.num_iters, 2)
    n_bound = sum(1 for d in dts if getattr(d, "upper_bound", False))
    if n_bound:
        result[f"lm_upper_bound_windows_{suffix}"] = n_bound
    return state


def spmd_comparison(args):
    """``--spmd``: the GSPMD-vs-explicit head-to-head (ROADMAP open item
    1; docs/PERFORMANCE.md, "The GSPMD path") on BOTH hot paths:

    * **ResNet**: explicit overlap+ZeRO-1 pipeline vs the
      NamedSharding-compiled GSPMD step (``make_train_step(spmd=True)``
      — no explicit collective calls, XLA inserts the exchange). With
      wire formats requested (``--compression``, or the ``--spmd-wire``
      default), each format adds a head-to-head PAIR: the explicit
      compressed pipeline (``explicit_wire_<fmt>``) and GSPMD with the
      compression compiled IN-PLACE (``gspmd_wire_<fmt>`` — the
      shard_map island for chunked fp8/int8, dtype-narrowed constraints
      for bf16 casts; ISSUE 17, no fallback).
    * **LM**: the shared ``make_lm_bench`` workload, batch-sharded over
      the full data mesh — GSPMD and the same per-format pairs vs the
      ``explicit`` LM step. The LM path has no overlap+ZeRO pipeline
      (``make_lm_train_step`` reduces via one fused allreduce), so its
      baseline is the explicit fused-AR step and its keys say
      ``lm_step_ms_explicit`` — deliberately NOT the ResNet half's
      ``explicit_overlap_zero1`` label.

    Emits per-variant step times, measured per-device optimizer-state
    bytes (the ZeRO-1 sharding must survive the path change), the
    compiled-HLO collective byte accounting for the GSPMD builds (the
    island's alltoall rides the same ``spmd_*`` counters — honest
    wire-width bytes off the module XLA produced), and the parity
    ratios the acceptance gates read: ``gspmd_over_explicit_step_time``
    <= 1.02 before GSPMD can become a default, and per format
    ``island_over_explicit_wire_<fmt>`` < 1 (the compiled island must
    beat the explicit compressed pipeline) plus
    ``island_over_gspmd_<fmt>`` (< 1 only where the wire is the
    bottleneck — see BENCH_NOTES.md). One JSON line, same contract as
    the headline bench."""
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.utils.benchmarks import (make_lm_bench, make_model,
                                              synthetic_batch)

    hvd.init()
    ndev = hvd.num_devices()
    global_batch = args.batch_size * ndev
    images, labels = synthetic_batch(global_batch, args.image_size)

    if args.compression is None:
        formats = [args.spmd_wire]
    elif args.compression:
        formats = [f for f in args.compression if f != "none"]
    else:  # bare --compression: the documented island matrix
        formats = ["bf16", "fp8", "int8"]

    result = {"metric": f"{args.model}_gspmd_vs_explicit_step_ms",
              "unit": "ms/step", "devices": ndev,
              "per_chip_batch": args.batch_size, "repeats": args.repeats,
              "spmd_wire_formats": formats}

    variants = {
        "explicit_overlap_zero1": dict(spmd=False, wire=None),
        "gspmd": dict(spmd=True, wire=None),
    }
    for fmt in formats:
        variants[f"explicit_wire_{fmt}"] = dict(spmd=False, wire=fmt)
        variants[f"gspmd_wire_{fmt}"] = dict(spmd=True, wire=fmt)
    for name, kind in variants.items():
        model = make_model(args.model)
        tx = hvd.DistributedOptimizer(optax.adamw(1e-3),
                                      sharded_update=True,
                                      compression=kind["wire"])
        step = training.make_train_step(
            model, tx, donate=True, spmd=kind["spmd"],
            overlap_grads=not kind["spmd"])
        state = training.create_train_state(model, tx,
                                            jax.random.PRNGKey(0),
                                            images[:1])
        state, _ = step(state, images, labels)
        result[f"opt_state_bytes_per_device_{name}"] = (
            _opt_state_bytes_per_device(state.opt_state))
        if getattr(step, "compiled_collectives", None):
            result[f"compiled_collective_bytes_{name}"] = {
                op: t["bytes"]
                for op, t in step.compiled_collectives.items()}
        if name == "gspmd":
            # X-ray the uncompressed GSPMD step: where the compiled
            # step's device time goes, gated on the classifier naming
            # >=95% of it (state threads through the traced steps)
            state = _attach_step_attribution(result, step, state,
                                             images, labels)
        _record_step_time(args, step, state, images, labels, result, name)

    # -- LM path (the shared make_lm_bench workload, data-sharded) -----
    lm_cfg = dict(batch=2 * ndev, seq_len=args.spmd_lm_seq_len,
                  layers=2, d_model=args.spmd_lm_d_model, heads=8,
                  vocab=2048)
    result["lm_config"] = lm_cfg
    # the LM baseline is the explicit fused-allreduce step — there is
    # no overlap+ZeRO LM pipeline to compare against, and labeling it
    # as one would publish a parity ratio against a baseline that is
    # not the named thing
    lm_variants = {
        "explicit": dict(spmd=False, wire=None),
        "gspmd": dict(spmd=True, wire=None),
    }
    for fmt in formats:
        lm_variants[f"explicit_wire_{fmt}"] = dict(spmd=False, wire=fmt)
        lm_variants[f"gspmd_wire_{fmt}"] = dict(spmd=True, wire=fmt)
    for name, kind in lm_variants.items():
        step, state, tokens = make_lm_bench(
            mesh=hvd.mesh(), seq_axis=None, flash=None,
            spmd=kind["spmd"], compression=kind["wire"], **lm_cfg)
        state = _record_lm_step_time(args, step, state, tokens, result,
                                     name)
        if getattr(step, "compiled_collectives", None):
            result[f"lm_compiled_collective_bytes_{name}"] = {
                op: t["bytes"]
                for op, t in step.compiled_collectives.items()}

    for prefix, base_name, key in (
            ("step_ms", "explicit_overlap_zero1",
             "gspmd_over_explicit_step_time"),
            ("lm_step_ms", "explicit",
             "lm_gspmd_over_explicit_step_time")):
        base = result.get(f"{prefix}_{base_name}")
        got = result.get(f"{prefix}_gspmd")
        if base and got:
            result[key] = round(got / base, 3)
            result[key + "_parity_within_2pct"] = bool(
                got / base <= 1.02)
    # per-format island gates: vs the explicit compressed pipeline
    # (must win) and vs uncompressed GSPMD (wins where the wire is the
    # bottleneck)
    for fmt in formats:
        for prefix, tag in (("step_ms", ""), ("lm_step_ms", "lm_")):
            island = result.get(f"{prefix}_gspmd_wire_{fmt}")
            exp_c = result.get(f"{prefix}_explicit_wire_{fmt}")
            base = result.get(f"{prefix}_gspmd")
            if island and exp_c:
                result[f"{tag}island_over_explicit_wire_{fmt}"] = (
                    round(island / exp_c, 3))
            if island and base:
                result[f"{tag}island_over_gspmd_{fmt}"] = (
                    round(island / base, 3))
    result["telemetry"] = _telemetry_block()
    _attach_goodput(result)
    print(json.dumps(result))


def data_plane_comparison(args):
    """``--data-plane``: the INPUT-BOUND configuration. The same compiled
    train step is driven two ways over the same deterministic batch
    stream: synchronously (batch assembly + the injected storage latency
    run on the TRAINING thread, the pre-data-plane behavior) and through
    the ``PrefetchLoader`` (assembly + host→device staging on the
    producer thread, overlapped with the running step). Reports both
    step times, the prefetch speedup, and the data-wait fraction the
    loader actually charged the training thread
    (``hvd_data_wait_seconds`` / wall) — when the pipeline keeps up the
    fraction is ~0 and prefetch-on step time collapses to compute
    (docs/DATA.md). ``--data-delay-ms`` is the per-batch synthetic
    storage latency that makes the run input-bound on purpose. One JSON
    line, same contract as the headline bench."""
    import time as _time

    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import telemetry, training
    from horovod_tpu.data import ArraySource, PrefetchLoader, segment
    from horovod_tpu.telemetry import instruments as ti
    from horovod_tpu.utils.benchmarks import (compute_dtype, make_model,
                                              sync)

    hvd.init()
    ndev = hvd.num_devices()
    global_batch = args.batch_size * ndev
    delay_s = args.data_delay_ms / 1e3
    iters, warmup = args.num_iters, args.num_warmup
    seed = 0

    # a host dataset 4 global batches deep, cycled across epochs — the
    # injected latency, not the resident size, is what models storage
    rng = np.random.default_rng(seed)
    n = global_batch * 4
    images_np = rng.standard_normal(
        (n, args.image_size, args.image_size, 3)).astype(compute_dtype())
    labels_np = rng.integers(0, 1000, size=(n,)).astype(np.int32)

    def batch_indices():
        """The loader's own deterministic plan, reproduced inline — the
        synchronous baseline consumes the IDENTICAL batch stream."""
        epoch = 0
        while True:
            seg = segment(n, seed=seed, epoch=epoch, world=1,
                          batch_size=global_batch, shuffle=True)
            for b in range(len(seg) // global_batch):
                yield seg[b * global_batch:(b + 1) * global_batch]
            epoch += 1

    def build():
        model = make_model(args.model)
        tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
        step_kw = dict(donate=True)
        return model, tx, step_kw

    result = {"metric": f"{args.model}_data_plane_step_ms",
              "unit": "ms/step", "devices": ndev,
              "per_chip_batch": args.batch_size,
              "data_delay_ms": args.data_delay_ms,
              "prefetch_depth": args.prefetch_depth,
              "timed_iters": iters}

    # -- prefetch OFF: the loader's work serializes with the step -------
    model, tx, step_kw = build()
    step = training.make_train_step(model, tx, **step_kw)
    src = ArraySource([images_np, labels_np], delay_s=delay_s)
    plan = batch_indices()
    state = training.create_train_state(
        model, tx, jax.random.PRNGKey(0), jnp_first(images_np))
    for _ in range(warmup):
        x, y = src.batch(next(plan))
        state, loss = step(state, x, y)
        sync(loss)
    t0 = _time.perf_counter()
    for _ in range(iters):
        x, y = src.batch(next(plan))
        state, loss = step(state, x, y)
        sync(loss)
    off_s = _time.perf_counter() - t0
    result["step_ms_prefetch_off"] = round(1000 * off_s / iters, 2)

    # -- prefetch ON: producer thread assembles + stages ahead ----------
    model, tx, step_kw = build()
    loader = PrefetchLoader(
        ArraySource([images_np, labels_np], delay_s=delay_s),
        global_batch, depth=args.prefetch_depth, rank=0, world=1,
        seed=seed, shuffle=True, drop_last=True)
    step = training.make_train_step(model, tx, loader=loader, **step_kw)
    state = training.create_train_state(
        model, tx, jax.random.PRNGKey(0), jnp_first(images_np))
    reg = telemetry.get_registry()

    def wait_sum():
        fam = reg.get(ti.DATA_WAIT_SECONDS)
        return float(fam.sum) if fam is not None else 0.0

    for _ in range(warmup):
        state, loss = step(state)
        sync(loss)
    w0 = wait_sum()
    t0 = _time.perf_counter()
    for _ in range(iters):
        state, loss = step(state)
        sync(loss)
    on_s = _time.perf_counter() - t0
    waited = wait_sum() - w0
    loader.close()
    result["step_ms_prefetch_on"] = round(1000 * on_s / iters, 2)
    result["data_wait_fraction"] = round(waited / on_s, 4) if on_s else 0.0
    if on_s > 0:
        result["prefetch_speedup"] = round(off_s / on_s, 3)
    fam = reg.get(ti.DATA_BYTES_STAGED)
    if fam is not None:
        result["bytes_staged_total"] = int(fam.value)
    result["telemetry"] = _telemetry_block()
    _attach_goodput(result)
    print(json.dumps(result))


def _churn_schedule(steps, preemptions, seed):
    """Map a seeded ChaosPlan's injection times onto step indices (same
    seed -> same schedule), so the churn bench is reproducible and
    comparable across runs the way the hvdrun chaos soak is."""
    from horovod_tpu.chaos import ChaosPlan
    plan = ChaosPlan.generate(seed=seed, interval=1.0, jitter=0.5,
                              kinds=("sigterm",), count=preemptions)
    if not plan.injections:
        return []
    t_max = plan.injections[-1].at or 1.0
    # never step 0 (nothing committed yet) and strictly increasing
    idxs, prev = [], 0
    for inj in plan.injections:
        idx = max(prev + 1, min(steps - 1,
                                int(inj.at / t_max * (steps - 1))))
        if idx >= steps:
            break
        idxs.append(idx)
        prev = idx
    return idxs


def churn_comparison(args):
    """``--churn``: goodput under a scripted preemption schedule — the
    SLO gate of the preemption-native story (docs/ELASTIC.md, "Running
    on spot capacity"). A small compiled train loop runs ``--churn-steps``
    steps; at seeded schedule points the loop simulates a graceful
    eviction exactly the way ``elastic/preempt.py`` spends it — a real
    ``AsyncCheckpointer`` force-commit plus the drain window — inside
    the ledger's ``preemption`` phase. The emitted ``goodput`` block
    must then (a) hold the sum≈wall invariant (every lost second
    attributed), (b) show a NON-ZERO ``preemption`` lane, and (c) keep
    ``goodput_ratio`` at or above ``--churn-budget``. Any violation is
    a loud nonzero exit — the gate, not a report. One JSON line, same
    contract as the headline bench."""
    import shutil
    import sys
    import tempfile
    import time as _time

    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ckpt import AsyncCheckpointer
    from horovod_tpu.telemetry import ledger as ledger_lib
    from horovod_tpu.telemetry import report as report_mod
    from horovod_tpu.telemetry.registry import MetricsRegistry
    from horovod_tpu.utils.benchmarks import sync

    hvd.init()
    steps = args.churn_steps
    schedule = _churn_schedule(steps, args.churn_preemptions,
                               args.churn_seed)

    # enough matmul per step that compute dominates the loop on a CPU
    # smoke run; the ratio gate is about attribution, not silicon speed
    n = 192
    rng = np.random.default_rng(args.churn_seed)
    b = jnp.asarray(rng.standard_normal((n, n)) / (n ** 0.5))

    @jax.jit
    def train_step(x):
        for _ in range(8):
            x = x @ b
        return x

    x = jnp.ones((n, n))
    tree = {"w": rng.standard_normal(1 << 16).astype(np.float32)}
    root = tempfile.mkdtemp(prefix="hvd_bench_churn_")
    ck = AsyncCheckpointer(root, keep=2, rank=0, world=1,
                           registry=MetricsRegistry())
    preempted_at = []
    try:
        sched = set(schedule)
        sync(train_step(x))  # compile outside the measured window
        # fresh attribution window: the SLO is about steady-state churn
        # cost, not one-time compilation (which has its own lane in the
        # headline modes)
        led = ledger_lib.reset_run()
        led.start()
        for i in range(steps):
            x = train_step(x)
            sync(x)
            led.settle_step()
            if i in sched:
                # one simulated graceful eviction: the grace commit (a
                # REAL async-checkpointer flush — its blocked time lands
                # in ckpt_stall, keeping phases exclusive) plus the
                # drain window (announce + exit + relaunch stand-in),
                # all inside the preemption lane like preempt.py spends
                # the real thing
                with led.phase("preemption"):
                    ck.save(i, tree)
                    ck.flush()
                    _time.sleep(args.churn_drain_ms / 1e3)
                preempted_at.append(i)
                _count_simulated_preemption()
        ck.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    result = {"metric": "goodput_under_churn", "unit": "ratio",
              "steps": steps, "churn_seed": args.churn_seed,
              "preemptions": len(preempted_at),
              "preempted_at_steps": preempted_at,
              "drain_ms": args.churn_drain_ms,
              "budget": args.churn_budget}
    failures = []
    try:
        block = report_mod.goodput_block()
        result["goodput"] = block
        preempt_s = float(block["phases"].get("preemption", 0.0))
        result["preemption_seconds"] = round(preempt_s, 4)
        result["value"] = block["goodput_ratio"]
        if preempted_at and preempt_s <= 0.0:
            failures.append(
                "preemption lane is EMPTY despite "
                f"{len(preempted_at)} scripted preemption(s) — the "
                "eviction window is not being attributed")
        if block["goodput_ratio"] < args.churn_budget:
            failures.append(
                f"goodput ratio {block['goodput_ratio']:.4f} under churn "
                f"fell below the {args.churn_budget:.4f} budget")
    except report_mod.GoodputInvariantError as e:
        result["goodput_error"] = str(e)
        failures.append(f"unattributed time under churn: {e}")
    if failures:
        result["slo"] = "FAIL"
        print(json.dumps(result))
        for f in failures:
            print(f"bench --churn: SLO GATE FAILED: {f}", file=sys.stderr)
        sys.exit(2)
    result["slo"] = "PASS"
    print(json.dumps(result))


def _count_simulated_preemption():
    from horovod_tpu.telemetry import instruments as _tele
    from horovod_tpu.telemetry.registry import get_registry
    get_registry().counter(
        _tele.PREEMPTIONS_TOTAL,
        "Preemption notices acted on, by source kind "
        "(docs/OBSERVABILITY.md)",
        label_names=("kind",)).labels("simulated").inc()


def jnp_first(images_np):
    """First example as the model-init sample input."""
    import jax.numpy as jnp
    return jnp.asarray(images_np[:1])


def _telemetry_block():
    """The registry snapshot for the BENCH json: collective bytes and
    bucket fill ride alongside throughput, so perf rounds can attribute
    a regression to wire volume / bucket structure without rerunning."""
    from horovod_tpu import telemetry
    snap = telemetry.get_registry().snapshot()
    keep = ("hvd_collective", "hvd_bucket", "hvd_step",
            "hvd_examples", "hvd_compile", "hvd_wire", "hvd_data")
    return {k: v for k, v in sorted(snap.items())
            if k.startswith(keep)}


def _attach_goodput(result):
    """The BENCH ``goodput`` block: the run ledger's phase breakdown
    with the *sum ≈ 100% of wall* invariant ENFORCED — an unattributed
    gap >2% of wall is a loud error (stderr + a ``goodput_error`` field),
    never silence, so perf regressions stay attributable
    (docs/OBSERVABILITY.md, "Where did my time go")."""
    import sys

    from horovod_tpu.telemetry import ledger as ledger_lib
    from horovod_tpu.telemetry import report as report_mod
    if not ledger_lib.get_ledger().enabled:
        return  # HOROVOD_GOODPUT=0 is an opt-out, not a violation
    try:
        result["goodput"] = report_mod.goodput_block()
    except report_mod.GoodputInvariantError as e:
        print(f"bench: GOODPUT INVARIANT VIOLATED: {e}", file=sys.stderr)
        result["goodput_error"] = str(e)
    # hvd-lint: disable=HVD-EXCEPT -- record, don't die: error lands in the result block
    except Exception as e:  # noqa: BLE001 — record, don't die
        result["goodput_error"] = (str(e) or repr(e)).splitlines()[0][:160]


def _attach_step_attribution(result, step, state, images, labels, k=3):
    """The BENCH ``step_attribution`` block (the training twin of
    bench_serve's ``tail_attribution``): X-ray K compiled steps
    (``step.xray`` → telemetry/xprof.py) and attach the device-time
    buckets, exposed-vs-overlapped collective split and verdict. The
    honesty gate is ENFORCED — a ``bucketed_fraction`` below 95% means
    the classifier can no longer name this backend's device time, and
    that is a loud error (stderr + ``step_attribution_error``), never
    silence. Returns the threaded ``state`` (the traced steps donate
    their inputs as usual)."""
    import sys

    from horovod_tpu.telemetry import xprof
    try:
        state, summary = step.xray(state, images, labels, k=k)
        result["step_attribution"] = summary
        if summary["bucketed_fraction"] < xprof.BUCKETED_GATE:
            msg = (f"step_attribution bucketed only "
                   f"{summary['bucketed_fraction']:.1%} of device time "
                   f"(gate {xprof.BUCKETED_GATE:.0%}) — unattributed "
                   f"{summary['unattributed_seconds']:.4f}s; the trace "
                   "classifier no longer understands this backend's "
                   "events")
            print(f"bench: STEP ATTRIBUTION GATE FAILED: {msg}",
                  file=sys.stderr)
            result["step_attribution_error"] = msg
    # hvd-lint: disable=HVD-EXCEPT -- record, don't die: error lands in the result block
    except Exception as e:  # noqa: BLE001 — record, don't die
        err = (str(e) or repr(e)).splitlines()[0][:160]
        print(f"bench: STEP ATTRIBUTION FAILED: {err}", file=sys.stderr)
        result["step_attribution_error"] = err
    return state


def _checkpoint_block(nbytes=32 << 20):
    """Async-checkpoint microbench for the BENCH json (docs/
    CHECKPOINT.md): for a synthetic ``nbytes`` state, the synchronous
    ``save_sharded`` wall time (the old stall-until-durable cost), the
    stall the async path actually charges the training thread
    (snapshot + budget wait), the end-to-end commit latency, and the
    background serialize+fsync bandwidth. One rank, local disk — the
    floor a real run's shared filesystem can only raise."""
    import shutil
    import tempfile
    import time as _time

    import numpy as np

    from horovod_tpu.ckpt import AsyncCheckpointer, save_sharded
    from horovod_tpu.telemetry.registry import MetricsRegistry

    rng = np.random.default_rng(0)
    leaves = 8
    tree = {f"p{i}": rng.standard_normal(nbytes // 4 // leaves)
            .astype(np.float32) for i in range(leaves)}
    root = tempfile.mkdtemp(prefix="hvd_bench_ckpt_")
    try:
        t0 = _time.perf_counter()
        man = save_sharded(root, 1, tree, rank=0, world=1)
        sync_s = _time.perf_counter() - t0
        written = sum(s["bytes"] for s in man["shards"].values())

        ck = AsyncCheckpointer(root, keep=2, rank=0, world=1,
                               registry=MetricsRegistry())
        t0 = _time.perf_counter()
        blocking_s = ck.save(2, tree)
        ck.flush()
        total_s = _time.perf_counter() - t0
        ck.close()
        bg_s = max(total_s - blocking_s, 1e-9)
        return {
            "state_mb": round(nbytes / 2**20, 1),
            "sync_write_ms": round(sync_s * 1e3, 2),
            "snapshot_stall_ms": round(blocking_s * 1e3, 2),
            "commit_latency_ms": round(total_s * 1e3, 2),
            "background_write_mb_per_s": round(written / 2**20 / bg_s, 1),
            "blocking_pct_of_sync": round(100 * blocking_s / sync_s, 1),
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _flightrec_overhead_ns(n=200_000):
    """Micro-bench the flight recorder's hot-path cost (one collective
    entry: deque append + CRC chain) so a regression in the
    "bounded append, no I/O, no locks" contract shows in the BENCH json
    as flightrec_overhead_ns_per_event."""
    import time as _time

    from horovod_tpu.diag.recorder import FlightRecorder
    rec = FlightRecorder(capacity=4096, rank=0, size=1)
    shape, dtype = (1024, 1024), "float32"
    t0 = _time.perf_counter()
    for i in range(n):
        rec.collective_enter("allreduce", shape=shape, dtype=dtype,
                             nbytes=4 << 20, mode="trace")
    dt = _time.perf_counter() - t0
    return dt / n * 1e9


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet101",
                        choices=["resnet18", "resnet50", "resnet101",
                                 "vgg16"])
    parser.add_argument("--batch-size", type=int, default=256,
                        help="per-chip batch size (64 = literal reference "
                             "config; 256 saturates a v5e MXU)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-warmup", type=int, default=3)
    parser.add_argument("--num-iters", type=int, default=20)
    parser.add_argument("--repeats", type=int, default=5,
                        help="timed windows; the median is reported "
                             "(tunnel/host noise made single windows "
                             "swing 3x, BENCH_NOTES.md)")
    parser.add_argument("--no-calibrate", action="store_true",
                        help="skip the empirical-peak matmul sweep")
    parser.add_argument("--no-lm", action="store_true",
                        help="skip the LM tokens/sec (flash on/off) runs")
    parser.add_argument("--calibrate", action="store_true",
                        help="run ONLY the empirical-peak calibration and "
                             "print its JSON line")
    parser.add_argument("--overlap", action="store_true",
                        help="run ONLY the overlapped-exchange comparison: "
                             "baseline fused-AR vs bucketed RS pipeline vs "
                             "RS pipeline + ZeRO-1 (docs/PERFORMANCE.md); "
                             "add --compression to extend the matrix with "
                             "compressed-wire overlap+ZeRO-1 variants")
    parser.add_argument("--accum-steps", type=int, default=4,
                        help="gradient-accumulation microbatches for "
                             "--overlap (the pipeline overlaps bucket k's "
                             "reduce-scatter with microbatch k+1's "
                             "backward)")
    parser.add_argument("--compression", nargs="*", default=None,
                        metavar="{none,bf16,fp8,int8}",
                        help="run the wire-compression comparison: the "
                             "overlapped pipeline at each listed wire "
                             "format (bare --compression = all four), "
                             "emitting step time, bytes-on-wire, and the "
                             "compression ratio (docs/PERFORMANCE.md). "
                             "Combined with --overlap it extends that "
                             "matrix with overlap+ZeRO-1 variants at "
                             "each wire format — the full pipeline in "
                             "one run")
    parser.add_argument("--spmd", action="store_true",
                        help="run ONLY the GSPMD-vs-explicit comparison: "
                             "explicit overlap+ZeRO-1 vs the NamedSharding-"
                             "compiled GSPMD step vs GSPMD+wire compiled "
                             "IN-PLACE (the shard_map island for chunked "
                             "formats, dtype-narrowed constraints for "
                             "casts), head-to-head with the explicit "
                             "compressed pipeline, on the ResNet AND LM "
                             "paths (docs/PERFORMANCE.md, 'The GSPMD "
                             "path'). Combine with --compression to list "
                             "the wire formats (bare --compression = "
                             "bf16 fp8 int8)")
    parser.add_argument("--spmd-wire", default="int8",
                        metavar="{bf16,fp8,int8}",
                        help="wire format for the --spmd compressed "
                             "variants when --compression is not given "
                             "(default int8)")
    parser.add_argument("--spmd-lm-d-model", type=int, default=256,
                        help="--spmd LM-path model width (small default "
                             "so the comparison runs on CPU meshes; "
                             "raise on real chips)")
    parser.add_argument("--spmd-lm-seq-len", type=int, default=256,
                        help="--spmd LM-path sequence length")
    parser.add_argument("--data-plane", action="store_true",
                        help="run ONLY the input-bound data-plane "
                             "comparison: the same step fed "
                             "synchronously vs through the "
                             "PrefetchLoader, with data-wait fraction "
                             "(docs/DATA.md)")
    parser.add_argument("--data-delay-ms", type=float, default=30.0,
                        help="synthetic per-batch storage latency for "
                             "--data-plane (what makes the config "
                             "input-bound)")
    parser.add_argument("--prefetch-depth", type=int, default=3,
                        help="PrefetchLoader queue depth for --data-plane")
    parser.add_argument("--churn", action="store_true",
                        help="run ONLY the goodput-under-churn SLO gate: "
                             "a compiled loop with seeded simulated "
                             "graceful evictions (real checkpointer "
                             "force-commit + drain window in the "
                             "ledger's preemption lane); exits nonzero "
                             "when the goodput ratio falls below "
                             "--churn-budget, the preemption lane is "
                             "empty, or any lost second is unattributed "
                             "(docs/ELASTIC.md)")
    parser.add_argument("--churn-steps", type=int, default=80,
                        help="train steps for --churn")
    parser.add_argument("--churn-preemptions", type=int, default=3,
                        help="scripted preemptions for --churn")
    parser.add_argument("--churn-seed", type=int, default=0,
                        help="seed of the --churn preemption schedule")
    parser.add_argument("--churn-budget", type=float, default=0.25,
                        help="minimum acceptable goodput ratio under "
                             "churn (CPU-smoke-tuned default; raise on "
                             "real chips where compute dominates)")
    parser.add_argument("--churn-drain-ms", type=float, default=40.0,
                        help="simulated drain window per preemption "
                             "(announce + exit + relaunch stand-in)")
    parser.add_argument("--compare", nargs="*", default=None,
                        metavar="DIR_OR_FILE",
                        help="run NO benchmark: diff the checked-in "
                             "BENCH_*.json and SCALING_*.json rounds "
                             "(default: current directory) and flag "
                             "regressions worse than "
                             "--compare-threshold on step_ms, MFU, "
                             "goodput, serve tokens/s and per-world "
                             "scaling efficiency (telemetry/trend.py); "
                             "exits 1 when any metric regressed")
    parser.add_argument("--compare-threshold", type=float, default=5.0,
                        help="--compare regression threshold in "
                             "percent (default 5)")
    args = parser.parse_args()
    if args.repeats < 1:
        parser.error("--repeats must be >= 1")
    if args.accum_steps < 1:
        parser.error("--accum-steps must be >= 1")
    if args.data_plane and (args.overlap or args.compression is not None):
        parser.error("--data-plane is its own comparison mode; run it "
                     "separately from --overlap/--compression")
    if args.spmd and (args.overlap or args.data_plane):
        parser.error("--spmd is its own comparison mode; run it "
                     "separately from --overlap/--data-plane "
                     "(--compression composes: it lists the wire "
                     "formats for the compiled-island variants)")
    if args.churn and (args.overlap or args.compression is not None
                       or args.data_plane or args.spmd):
        parser.error("--churn is its own comparison mode; run it "
                     "separately from --overlap/--compression/"
                     "--data-plane/--spmd")
    if args.compare is not None:
        if (args.overlap or args.compression is not None
                or args.data_plane or args.spmd or args.churn):
            parser.error("--compare reads past rounds; it does not "
                         "combine with a benchmark mode")
        import sys

        from horovod_tpu.telemetry import trend
        report = trend.run(args.compare,
                           threshold=args.compare_threshold / 100.0,
                           stream=sys.stderr)
        if report is None:
            sys.exit(2)
        print(json.dumps(report))
        sys.exit(1 if report["regressions"] else 0)
    if args.churn:
        if args.churn_steps < 2:
            parser.error("--churn-steps must be >= 2")
        if args.churn_preemptions < 1:
            parser.error("--churn-preemptions must be >= 1")
        churn_comparison(args)
        return

    if args.spmd:
        spmd_comparison(args)
        return

    if args.data_plane:
        data_plane_comparison(args)
        return

    if args.overlap:
        # with --compression too, the matrix gains the compressed
        # overlap+ZeRO-1 variants (the full pipeline in one run)
        overlap_comparison(args)
        return

    if args.compression is not None:
        compression_comparison(args)
        return

    if args.calibrate:
        peak, shape = calibrate_peak_tflops()
        print(json.dumps({
            "metric": "empirical_peak_tflops_bf16",
            "value": round(peak, 1), "unit": "TFLOP/s",
            "matmul_n": shape, "repeats": 3,
            "device_kind": jax.devices()[0].device_kind}))
        return

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.utils.benchmarks import (make_model,
                                              repeat_throughput,
                                              synthetic_batch)

    hvd.init()
    ndev = hvd.num_devices()
    model = make_model(args.model)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    global_batch = args.batch_size * ndev
    images, labels = synthetic_batch(global_batch, args.image_size)

    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        images[:1])
    step = training.make_train_step(model, tx, donate=True)

    # XLA's own FLOP count for the whole train step -> honest MFU.
    # step is already jitted: lower() reuses its cache entry (no second
    # compile) and reports the post-partitioning PER-DEVICE module.
    flops_per_device_step = 0.0
    try:
        # step.lower places args exactly like the timed path: same cache
        # key, so this is THE compile the loop reuses, not an extra one
        from horovod_tpu.utils.benchmarks import cost_analysis_dict
        cost = cost_analysis_dict(
            step.lower(state, images, labels).compile())
        flops_per_device_step = float(cost.get("flops", 0.0))
    # hvd-lint: disable=HVD-EXCEPT -- cost model is optional: missing flops only disables MFU
    except Exception:
        pass

    # fusion-threshold autotune on the real gradient pytree (reference
    # role: parameter_manager.h:186-220), timed by the shared
    # readback-slope primitive. Runs BEFORE the timed windows (donate=True
    # consumes `state` there) with apply=False so the headline workload
    # stays identical across rounds; the JSON records the winner.
    autotuned_mb = None
    autotune_error = None
    autotune_abstained = None
    autotune_escalations = None
    try:
        best_thr, at_timings = hvd.autotune_fusion_threshold(
            state.params, trials=5, apply=False)
        # measured-vs-guessed provenance: nonzero means some trials sat
        # at the noise floor and needed 4x iter escalation (a threshold
        # that stayed an upper bound after escalation abstains instead)
        autotune_escalations = at_timings.slope_window_escalations
        if best_thr is None:
            # abstention contract (docs/AUTOTUNE.md): no rankable signal
            # -> record null + the reason, never a noise argmin
            autotune_abstained = at_timings.abstain_reason
        else:
            autotuned_mb = best_thr >> 20
    # hvd-lint: disable=HVD-EXCEPT -- record, don't die: autotune failure is a bench result
    except Exception as e:  # noqa: BLE001 — record, don't die
        autotune_error = str(e).splitlines()[0][:160]

    runs = repeat_throughput(step, state, images, labels,
                             args.num_warmup, args.num_iters,
                             args.repeats)
    per_chip_runs = sorted(r[0] / ndev for r in runs)
    per_chip = statistics.median(per_chip_runs)
    dts = [r[1] for r in runs]
    dt = statistics.median(dts)
    n_bound = sum(1 for r in runs if getattr(r[1], "upper_bound", False))
    # cost_analysis is per-device already — no further /ndev
    achieved_tflops = flops_per_device_step * args.num_iters / dt / 1e12
    kind = jax.devices()[0].device_kind
    # bf16 peaks for chips we might land on; 0 = unknown -> omit MFU.
    # Exact device_kind match first, then LONGEST matching prefix — a
    # plain substring scan would let "TPU v4" (275) claim a
    # "TPU v4 lite" (138) and misstate MFU by ~2x.
    peaks = {"TPU v5 lite": 197.0, "TPU v5e": 197.0, "TPU v5p": 459.0,
             "TPU v4 lite": 138.0, "TPU v4i": 138.0, "TPU v4": 275.0,
             "TPU v6 lite": 918.0, "TPU v6e": 918.0}
    peak = peaks.get(kind, 0.0)
    if not peak:
        for k in sorted(peaks, key=len, reverse=True):
            if k in kind:
                peak = peaks[k]
                break
    result = {
        "metric": f"{args.model}_synthetic_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 3),
        "repeats": args.repeats,
        "img_per_sec_per_chip_min": round(per_chip_runs[0], 2),
        "img_per_sec_per_chip_max": round(per_chip_runs[-1], 2),
        "step_ms_median": round(1000 * dt / args.num_iters, 2),
    }
    if n_bound:  # inverted-window fallbacks: bounds, not measurements
        result["upper_bound_windows"] = n_bound
    if achieved_tflops:  # omit rather than publish 0.0 as a measurement
        result["achieved_tflops_per_chip"] = round(achieved_tflops, 1)

    # empirical peak (VERDICT r3 #3): the MFU denominator is MEASURED on
    # this chip — a swept pure-matmul bf16 chain — so the number stands
    # regardless of what the tunnel labels the device. Calibration is
    # gated ONLY on --no-calibrate: the LM MFU below needs the peak even
    # when the ResNet numerator is unavailable.
    emp_peak = 0.0
    if not args.no_calibrate:
        emp_peak, emp_shape = calibrate_peak_tflops()
        result["empirical_peak_tflops_bf16"] = round(emp_peak, 1)
        result["empirical_peak_matmul_n"] = emp_shape
        if emp_peak > 0 and achieved_tflops:
            result["mfu_vs_empirical_peak_pct"] = round(
                100 * achieved_tflops / emp_peak, 1)
    if peak and achieved_tflops:
        mfu = 100 * achieved_tflops / peak
        if mfu <= 100:
            result["mfu_vs_nominal_pct"] = round(mfu, 1)
        else:
            result["nominal_note"] = (
                f"achieved {achieved_tflops:.0f} TF/s exceeds {kind} "
                f"nominal {peak:.0f} TF/s - measurement or label "
                f"problem; trust mfu_vs_empirical_peak_pct")

    # LM path (VERDICT r3 #6): the flash kernel measured in the round
    # artifacts — tokens/sec with the kernel on vs off (and
    # seq-parallel over the mesh when >1 device is present). Dense
    # attention at the flash batch OOMs this chip's HBM (fp32
    # [B,12,2048,2048] scores) — itself the point of the kernel — so
    # the dense line runs at batch 2 and says so.
    if not args.no_lm:
        result["lm_seq_len"] = 2048

        def lm_try(key, mfu_key=None, **kw):
            try:
                toks, lm_tflops = lm_tokens_per_sec(**kw)
                result[key] = round(toks, 1)
                if mfu_key and lm_tflops and emp_peak > 0:
                    result[mfu_key] = round(100 * lm_tflops / emp_peak, 1)
            # hvd-lint: disable=HVD-EXCEPT -- record, don't die: per-variant errors land in the result
            except Exception as e:  # noqa: BLE001 — record, don't die
                result[key + "_error"] = str(e).splitlines()[0][:160]

        lm_try("lm_tokens_per_sec_flash_b8", flash=True, batch=8)
        lm_try("lm_tokens_per_sec_dense_b2", flash=False, batch=2)
        # MXU-saturating config (VERDICT r4 #3): d_model 2048 puts the
        # FLOPs in large matmuls; this line carries the LM MFU
        lm_try("lm_d2048_tokens_per_sec_flash",
               mfu_key="lm_mfu_vs_empirical_peak_pct",
               flash=True, batch=8, layers=8, d_model=2048, heads=16,
               steps=5, warmup=2)
        if ndev > 1:
            lm_try("lm_tokens_per_sec_seq_parallel_flash_b8",
                   flash=True, batch=8, seq_parallel=True)

    result["autotuned_fusion_threshold_mb"] = autotuned_mb
    if autotune_escalations is not None:
        result["autotune_slope_window_escalations"] = autotune_escalations
    if autotune_abstained is not None:
        result["autotune_abstained"] = autotune_abstained
    if autotune_error is not None:
        result["autotune_error"] = autotune_error
    result["flightrec_overhead_ns_per_event"] = round(
        _flightrec_overhead_ns(), 1)
    try:
        result["checkpoint"] = _checkpoint_block()
    # hvd-lint: disable=HVD-EXCEPT -- record, don't die: checkpoint-block error is a result
    except Exception as e:  # noqa: BLE001 — record, don't die
        result["checkpoint_error"] = str(e).splitlines()[0][:160]
    result["telemetry"] = _telemetry_block()
    _attach_goodput(result)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
