"""Headline benchmark: synthetic ResNet img/sec through the full framework
hot path (DistributedOptimizer -> fused allreduce -> optimizer update,
compiled over the global mesh).

The TPU analogue of the reference's synthetic benchmarks
(``/root/reference/examples/pytorch_synthetic_benchmark.py``: timed batches
after warmup, img/sec) and of ``tf_cnn_benchmarks`` as used for the
published numbers (``docs/benchmarks.rst:16-42``).

Baseline for ``vs_baseline``: the reference's documented sample output —
ResNet-101, batch 64/GPU, 16 Pascal GPUs: "total images/sec: 1656.82"
(``docs/benchmarks.rst:28-42``), i.e. **103.55 img/s per chip**. We run the
same workload (ResNet-101, synthetic data) per TPU chip.

Per-chip batch defaults to 256: the reference protocol is "the batch that
keeps the accelerator busy" (64 filled a 2017 P100); on a v5e the MXU is
launch-bound below ~256 — measured on this chip: bs64 = 1802 img/s
(41% MFU), bs256 = 3249 img/s (75% MFU). ``--batch-size 64`` reproduces
the literal reference configuration. See ``BENCH_NOTES.md`` for the
roofline analysis.

Prints ONE JSON line with metric/value/unit/vs_baseline plus achieved
TFLOP/s and MFU (XLA cost-analysis FLOPs over measured step time).
``--repeats`` (default 5) runs that many timed windows and reports the
MEDIAN with min/max spread — single-window numbers through the tunnel
swung 3x between runs (BENCH_NOTES.md), so the median is the number
that means something round over round.
"""

import argparse
import json
import statistics

import jax
import optax

# reference docs/benchmarks.rst:28-42 — 1656.82 img/s over 16 Pascal GPUs
BASELINE_IMG_PER_SEC_PER_CHIP = 1656.82 / 16


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet101",
                        choices=["resnet50", "resnet101", "vgg16"])
    parser.add_argument("--batch-size", type=int, default=256,
                        help="per-chip batch size (64 = literal reference "
                             "config; 256 saturates a v5e MXU)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-warmup", type=int, default=3)
    parser.add_argument("--num-iters", type=int, default=20)
    parser.add_argument("--repeats", type=int, default=5,
                        help="timed windows; the median is reported "
                             "(tunnel/host noise made single windows "
                             "swing 3x, BENCH_NOTES.md)")
    args = parser.parse_args()
    if args.repeats < 1:
        parser.error("--repeats must be >= 1")

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.utils.benchmarks import (make_model,
                                              repeat_throughput,
                                              synthetic_batch)

    hvd.init()
    ndev = hvd.num_devices()
    model = make_model(args.model)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    global_batch = args.batch_size * ndev
    images, labels = synthetic_batch(global_batch, args.image_size)

    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        images[:1])
    step = training.make_train_step(model, tx, donate=True)

    # XLA's own FLOP count for the whole train step -> honest MFU.
    # step is already jitted: lower() reuses its cache entry (no second
    # compile) and reports the post-partitioning PER-DEVICE module.
    flops_per_device_step = 0.0
    try:
        # step.lower places args exactly like the timed path: same cache
        # key, so this is THE compile the loop reuses, not an extra one
        cost = step.lower(state, images, labels).compile().cost_analysis()
        if cost:
            flops_per_device_step = float(cost.get("flops", 0.0))
    except Exception:
        pass

    runs = repeat_throughput(step, state, images, labels,
                             args.num_warmup, args.num_iters,
                             args.repeats)
    per_chip_runs = sorted(r[0] / ndev for r in runs)
    per_chip = statistics.median(per_chip_runs)
    dts = [r[1] for r in runs]
    dt = statistics.median(dts)
    # cost_analysis is per-device already — no further /ndev
    achieved_tflops = flops_per_device_step * args.num_iters / dt / 1e12
    kind = jax.devices()[0].device_kind
    # bf16 peaks for chips we might land on; 0 = unknown -> omit MFU.
    # Exact device_kind match first, then LONGEST matching prefix — a
    # plain substring scan would let "TPU v4" (275) claim a
    # "TPU v4 lite" (138) and misstate MFU by ~2x.
    peaks = {"TPU v5 lite": 197.0, "TPU v5e": 197.0, "TPU v5p": 459.0,
             "TPU v4 lite": 138.0, "TPU v4i": 138.0, "TPU v4": 275.0,
             "TPU v6 lite": 918.0, "TPU v6e": 918.0}
    peak = peaks.get(kind, 0.0)
    if not peak:
        for k in sorted(peaks, key=len, reverse=True):
            if k in kind:
                peak = peaks[k]
                break
    result = {
        "metric": f"{args.model}_synthetic_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 3),
        "repeats": args.repeats,
        "img_per_sec_per_chip_min": round(per_chip_runs[0], 2),
        "img_per_sec_per_chip_max": round(per_chip_runs[-1], 2),
        "step_ms_median": round(1000 * dt / args.num_iters, 2),
    }
    if achieved_tflops:  # omit rather than publish 0.0 as a measurement
        result["achieved_tflops_per_chip"] = round(achieved_tflops, 1)
    if peak and achieved_tflops:
        mfu = 100 * achieved_tflops / peak
        if mfu <= 100:
            result["mfu_pct"] = round(mfu, 1)
        else:
            # sustained > nominal peak means the labeled device_kind does
            # not match the hardware actually serving the tunnel; the
            # img/s and TFLOP/s stand on their own
            result["mfu_note"] = (f"achieved {achieved_tflops:.0f} TF/s "
                                  f"exceeds {kind} nominal {peak:.0f} TF/s"
                                  f" - device label unreliable")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
