"""Serving-plane benchmark: open-loop load against the inference engine.

The serving analogue of bench.py: drive `horovod_tpu/serve`'s
continuous-batching engine with a synthetic **open-loop** arrival
schedule (requests arrive on a fixed clock, independent of completions
— the honest way to measure a server at and past saturation; a
closed-loop client self-throttles and hides queueing) and report the
SLO numbers docs/SERVING.md names:

* ``ttft_ms_p50`` / ``ttft_ms_p99``   — time to first token (arrival →
  first streamed token: queueing + prefill),
* ``inter_token_ms_p50`` / ``_p99``   — gaps between streamed tokens
  (steady-state decode cadence),
* ``tokens_per_sec_per_chip``         — generated-token throughput,
  normalized by the mesh's device count,

plus a goodput-style **time-attribution block**: the engine's
prefill / decode / overhead phase accounting + the harness's idle
bookkeeping must explain ~100% of wall clock (the serving analogue of
bench.py's goodput invariant — `SERVE ATTRIBUTION VIOLATED` printed
loudly when it doesn't; tolerance mirrors
telemetry/report.UNATTRIBUTED_TOLERANCE).

Runs on the 8-device CPU mesh exactly like the rest of the bench suite
(`JAX_PLATFORMS=cpu python bench_serve.py`); the numbers are CPU-mesh
numbers — the harness, shapes and invariants are what transfer to TPU.
"""

import argparse
import json
import time

import numpy as np

ATTRIBUTION_TOLERANCE = 0.02  # mirror telemetry/report's goodput bound


def build_parser():
    p = argparse.ArgumentParser(description="horovod_tpu serving bench")
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--rate", type=float, default=8.0,
                   help="open-loop arrival rate, requests/second")
    p.add_argument("--prompt-len", type=int, default=24,
                   help="mean prompt length (uniform 0.5x..1.5x)")
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--max-slots", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--vocab-size", type=int, default=512)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--d-ff", type=int, default=512)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", default=None,
                   help="also write the result block to this path")
    return p


def _percentiles_ms(samples, qs=(50, 99)):
    if not samples:
        return {f"p{q}": None for q in qs}
    arr = np.asarray(samples, dtype=np.float64) * 1e3
    return {f"p{q}": round(float(np.percentile(arr, q)), 3) for q in qs}


def run_bench(args):
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.parallel import mesh as mesh_lib
    from horovod_tpu.serve import KVCacheConfig, Request, ServeEngine

    rng = np.random.default_rng(args.seed)
    cfg = TransformerConfig(
        vocab_size=args.vocab_size, num_layers=args.num_layers,
        num_heads=args.num_heads, d_model=args.d_model, d_ff=args.d_ff,
        dtype=jnp.float32, flash_attention=False)
    model = Transformer(cfg)
    init_toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.seed), init_toks)["params"]

    prompt_lens = rng.integers(max(1, args.prompt_len // 2),
                               args.prompt_len * 3 // 2 + 1,
                               args.requests)
    max_seq = int(prompt_lens.max()) + args.max_new
    mbps = -(-max_seq // args.block_size)
    kv = KVCacheConfig(
        num_blocks=args.max_slots * mbps + 1, block_size=args.block_size,
        num_layers=args.num_layers, num_heads=args.num_heads,
        head_dim=args.d_model // args.num_heads,
        max_blocks_per_seq=mbps, dtype=jnp.float32)
    mesh = mesh_lib.build_mesh(jax.devices())
    n_chips = int(np.prod(mesh.devices.shape))
    engine = ServeEngine(model, params, kv, mesh=mesh,
                         max_slots=args.max_slots,
                         prefill_chunk=args.prefill_chunk)

    requests = [Request(list(map(int, rng.integers(0, args.vocab_size,
                                                   int(n)))),
                        args.max_new)
                for n in prompt_lens]

    # warm both compiled programs OUTSIDE the measured window (compile
    # time is a startup cost, not a serving latency; bench.py does the
    # same for its step programs)
    warm = engine.submit(Request(list(map(
        int, rng.integers(0, args.vocab_size, 3))), 2))
    while warm.state != "done":
        engine.step()
    for k in engine.time_breakdown:
        engine.time_breakdown[k] = 0.0

    # open loop: arrival i at t0 + i/rate, submitted when its time comes
    # whether or not the engine kept up
    t0 = time.monotonic()
    arrivals = [t0 + i / args.rate for i in range(args.requests)]
    pending = list(zip(arrivals, requests))
    while pending or any(r.state not in ("done", "failed")
                         for r in requests):
        now = time.monotonic()
        while pending and pending[0][0] <= now:
            engine.submit(pending.pop(0)[1])
        stats = engine.step()
        if not stats and pending:
            wait = max(0.0, pending[0][0] - time.monotonic())
            if wait > 0:
                time.sleep(wait)
                engine.note_idle(wait)
    wall_s = time.monotonic() - t0

    failed = [r for r in requests if r.state == "failed"]
    if failed:
        raise RuntimeError(
            f"{len(failed)} bench request(s) failed: {failed[0].error}")

    ttft = [r.first_token_time - r.arrival for r in requests]
    itl = [b - a for r in requests
           for a, b in zip(r.token_times, r.token_times[1:])]
    total_tokens = sum(len(r.generated) for r in requests)

    breakdown = dict(engine.time_breakdown)
    attributed = sum(breakdown.values())
    unattributed = wall_s - attributed
    attribution = {
        "wall_s": round(wall_s, 4),
        **{f"{k}_s": round(v, 4) for k, v in breakdown.items()},
        "attributed_s": round(attributed, 4),
        "unattributed_fraction": round(unattributed / wall_s, 4),
    }
    attribution["valid"] = abs(unattributed) <= \
        ATTRIBUTION_TOLERANCE * wall_s

    result = {
        "mode": "serve",
        "devices": n_chips,
        "requests": args.requests,
        "rate_rps": args.rate,
        "max_new_tokens": args.max_new,
        "prompt_len_mean": float(np.mean(prompt_lens)),
        "max_slots": args.max_slots,
        "prefill_chunk": args.prefill_chunk,
        "kv_block_size": args.block_size,
        "kv_pool_blocks": kv.num_blocks,
        "kv_pool_mib": round(kv.pool_bytes() / 2 ** 20, 2),
        "ttft_ms": _percentiles_ms(ttft),
        "inter_token_ms": _percentiles_ms(itl),
        "tokens_generated": total_tokens,
        "tokens_per_sec": round(total_tokens / wall_s, 2),
        "tokens_per_sec_per_chip": round(total_tokens / wall_s / n_chips,
                                         3),
        "attribution": attribution,
    }
    return result


def main(argv=None):
    args = build_parser().parse_args(argv)
    result = run_bench(args)
    print(json.dumps(result, indent=1))
    if not result["attribution"]["valid"]:
        explained = 1 - abs(result["attribution"]["unattributed_fraction"])
        print("SERVE ATTRIBUTION VIOLATED: engine phases + idle explain "
              f"{explained:.1%} of wall clock (tolerance "
              f"{ATTRIBUTION_TOLERANCE:.0%}) — a scheduler phase is "
              "leaking unaccounted time")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
    return 0 if result["attribution"]["valid"] else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
