"""Serving-plane benchmark: open-loop load against the inference engine.

The serving analogue of bench.py: drive `horovod_tpu/serve`'s
continuous-batching engine with a synthetic **open-loop** arrival
schedule (requests arrive on a fixed clock, independent of completions
— the honest way to measure a server at and past saturation; a
closed-loop client self-throttles and hides queueing) and report the
SLO numbers docs/SERVING.md names:

* ``ttft_ms_p50`` / ``ttft_ms_p99``   — time to first token (arrival →
  first streamed token: queueing + prefill),
* ``ttft_admission_ms_{p50,p99}``     — first token measured from KV
  **admission** instead of arrival (prefill only; the spread between
  the two is pure queue/backpressure wait),
* ``inter_token_ms_p50`` / ``_p99``   — gaps between streamed tokens
  (steady-state decode cadence),
* ``tokens_per_sec_per_chip``         — generated-token throughput,
  normalized by the mesh's device count,

plus a goodput-style **time-attribution block**: the engine's
prefill / decode / overhead phase accounting + the harness's idle
bookkeeping must explain ~100% of wall clock (the serving analogue of
bench.py's goodput invariant — `SERVE ATTRIBUTION VIOLATED` printed
loudly when it doesn't; tolerance mirrors
telemetry/report.UNATTRIBUTED_TOLERANCE).

Fleet extensions (ISSUE 16): ``--shared-prefix L`` prepends one fixed
L-token system prompt to every request and reports the
**cached-prefill fraction** (prompt tokens skipped via
``kvcache.PrefixCache`` block reuse / all prompt tokens); ``--fleet N``
drives N engine replicas behind a ``serve/fleet`` router (client-side
TTFT through the router, per-replica attribution windows that end at a
replica's eviction time); ``--chaos-at F`` delivers a preemption
notice to replica r0 after fraction F of the arrival schedule — the
run FAILS on any dropped request; ``--acceptance`` runs the ISSUE-16
gate end to end (single-replica saturation probe → 2-replica fleet at
2x that load → chaos soak) and exits nonzero unless cached-prefill
fraction > 0.5, zero requests dropped, and every attribution block
explains wall clock within tolerance.

Per-request tail attribution (ISSUE 18): every bench run traces every
request (``serve/tracing.py``, sample=1.0) and reports a
``tail_attribution`` block — for each request in the p99 latency
bucket, the fraction of its latency tiled by NAMED spans/gaps must be
≥ ``TAIL_ATTRIBUTION_BOUND`` (98%), and each slow request's dominant
stall is classified with the `hvd-doctor serve` tables. Chaos runs
additionally require the doctor to name ``redispatch_hop`` dominant
for every cut-and-resumed stream. ``--trace-dir`` dumps the raw
ndjson + merged Chrome trace for offline `hvd-doctor serve`.

Runs on the 8-device CPU mesh exactly like the rest of the bench suite
(`JAX_PLATFORMS=cpu python bench_serve.py`); the numbers are CPU-mesh
numbers — the harness, shapes and invariants are what transfer to TPU.
"""

import argparse
import json
import time

import numpy as np

ATTRIBUTION_TOLERANCE = 0.02  # mirror telemetry/report's goodput bound
TAIL_ATTRIBUTION_BOUND = 0.98  # named-span coverage of every p99-bucket
                               # request's latency (ISSUE 18)


def build_parser():
    p = argparse.ArgumentParser(description="horovod_tpu serving bench")
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--rate", type=float, default=8.0,
                   help="open-loop arrival rate, requests/second")
    p.add_argument("--prompt-len", type=int, default=24,
                   help="mean prompt length (uniform 0.5x..1.5x)")
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--max-slots", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=16)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--vocab-size", type=int, default=512)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--d-ff", type=int, default=512)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--shared-prefix", type=int, default=0,
                   help="shared system-prompt length prepended to every "
                        "request (exercises the prefix cache)")
    p.add_argument("--fleet", type=int, default=0,
                   help="run N engine replicas behind the fleet router "
                        "(0 = single inline engine)")
    p.add_argument("--chaos-at", type=float, default=None,
                   help="preempt replica r0 after this fraction of the "
                        "arrival schedule (fleet mode)")
    p.add_argument("--grace", type=float, default=0.5,
                   help="preemption grace budget for --chaos-at, seconds")
    p.add_argument("--acceptance", action="store_true",
                   help="run the ISSUE-16 acceptance recipe (saturation "
                        "probe -> 2-replica fleet at 2x -> chaos soak)")
    p.add_argument("--trace-dir", default=None,
                   help="dump the per-request traces here "
                        "(servetrace.ndjson for `hvd-doctor serve` + "
                        "a merged Chrome trace)")
    p.add_argument("--json", default=None,
                   help="also write the result block to this path")
    return p


def _percentiles_ms(samples, qs=(50, 99)):
    if not samples:
        return {f"p{q}": None for q in qs}
    arr = np.asarray(samples, dtype=np.float64) * 1e3
    return {f"p{q}": round(float(np.percentile(arr, q)), 3) for q in qs}


def _setup(args):
    """Model, mesh, KV config and the request prompt list — shared by
    the single-engine and fleet paths. The KV pool is sized for worst
    case fully-fresh slots PLUS the shared prefix the cache retains."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import (Transformer,
                                                TransformerConfig)
    from horovod_tpu.parallel import mesh as mesh_lib
    from horovod_tpu.serve import KVCacheConfig

    rng = np.random.default_rng(args.seed)
    cfg = TransformerConfig(
        vocab_size=args.vocab_size, num_layers=args.num_layers,
        num_heads=args.num_heads, d_model=args.d_model, d_ff=args.d_ff,
        dtype=jnp.float32, flash_attention=False)
    model = Transformer(cfg)
    init_toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(args.seed), init_toks)["params"]

    prefix = list(map(int, rng.integers(0, args.vocab_size,
                                        args.shared_prefix)))
    tail_lens = rng.integers(max(1, args.prompt_len // 2),
                             args.prompt_len * 3 // 2 + 1,
                             args.requests)
    prompts = [prefix + list(map(int, rng.integers(0, args.vocab_size,
                                                   int(n))))
               for n in tail_lens]

    max_seq = max(len(p) for p in prompts) + args.max_new
    mbps = -(-max_seq // args.block_size)
    prefix_blocks = args.shared_prefix // args.block_size
    kv = KVCacheConfig(
        num_blocks=args.max_slots * mbps + prefix_blocks + 1,
        block_size=args.block_size,
        num_layers=args.num_layers, num_heads=args.num_heads,
        head_dim=args.d_model // args.num_heads,
        max_blocks_per_seq=mbps, dtype=jnp.float32)
    mesh = mesh_lib.build_mesh(jax.devices())
    n_chips = int(np.prod(mesh.devices.shape))
    return rng, model, params, kv, mesh, n_chips, prompts


def _cached_fraction(engines):
    prompt = sum(e.prompt_tokens for e in engines)
    cached = sum(e.cached_prefill_tokens for e in engines)
    return (cached / prompt) if prompt else 0.0


def _tail_attribution(tracer, chaos=False):
    """The ISSUE-18 gate block: every p99-bucket request's latency must
    be ≥ TAIL_ATTRIBUTION_BOUND tiled by named spans/gaps, and (chaos
    runs) the doctor must name redispatch_hop dominant for every
    cut-and-resumed stream."""
    from horovod_tpu.diag import serve_doctor

    per = []
    for tr in tracer.traces():
        totals = serve_doctor.phase_totals(tr)
        dom, _ = serve_doctor.dominant_stall(totals)
        per.append({"request_id": tr["request_id"],
                    "latency_ms": tr["latency_s"] * 1e3,
                    "attributed_fraction": tr["attributed_fraction"],
                    "hops": int(tr.get("hops", 0)),
                    "dominant_stall": dom})
    if not per:
        return {"traced": 0, "valid": False}
    p99 = float(np.percentile([r["latency_ms"] for r in per], 99))
    bucket = [r for r in per if r["latency_ms"] >= p99]
    min_attr = min(r["attributed_fraction"] for r in bucket)
    stalls = {}
    for r in bucket:
        stalls[r["dominant_stall"]] = \
            stalls.get(r["dominant_stall"], 0) + 1
    block = {
        "traced": len(per),
        "min_attributed_fraction": round(
            min(r["attributed_fraction"] for r in per), 4),
        "p99_ms": round(p99, 3),
        "p99_bucket": len(bucket),
        "p99_bucket_min_attributed_fraction": round(min_attr, 4),
        "p99_dominant_stalls": dict(sorted(stalls.items())),
        "valid": min_attr >= TAIL_ATTRIBUTION_BOUND,
    }
    if chaos:
        # vacuously true when the drain finished everything in grace
        # (no streams were cut — the graceful path, also a success);
        # when streams WERE cut, each one's dominant stall must be the
        # hop the eviction caused
        hopped = [r for r in per if r["hops"]]
        block["cut_streams"] = len(hopped)
        block["cut_streams_redispatch_dominant"] = all(
            r["dominant_stall"] == "redispatch_hop" for r in hopped)
        block["valid"] = (block["valid"]
                          and block["cut_streams_redispatch_dominant"])
    return block


def _dump_traces(tracer, trace_dir):
    if not trace_dir or not tracer.traces():
        return
    import os
    os.makedirs(trace_dir, exist_ok=True)
    tracer.write_ndjson(os.path.join(trace_dir, "servetrace.ndjson"))
    tracer.write_chrome(os.path.join(trace_dir,
                                     "servetrace.merged.json"))


def run_bench(args):
    from horovod_tpu.serve import Request, ServeEngine, ServeTracer

    rng, model, params, kv, mesh, n_chips, prompts = _setup(args)
    tracer = ServeTracer(sample=1.0)  # every request: the tail gate
    engine = ServeEngine(model, params, kv, mesh=mesh,
                         max_slots=args.max_slots,
                         prefill_chunk=args.prefill_chunk,
                         tracer=tracer)

    requests = [Request(p, args.max_new) for p in prompts]

    # warm both compiled programs OUTSIDE the measured window (compile
    # time is a startup cost, not a serving latency; bench.py does the
    # same for its step programs)
    warm = engine.submit(Request(list(map(
        int, rng.integers(0, args.vocab_size, 3))), 2))
    while warm.state != "done":
        engine.step()
    for k in engine.time_breakdown:
        engine.time_breakdown[k] = 0.0
    engine.prompt_tokens = 0
    engine.cached_prefill_tokens = 0
    tracer.clear()  # the warm request's trace is compile time, not load

    # open loop: arrival i at t0 + i/rate, submitted when its time comes
    # whether or not the engine kept up
    t0 = time.monotonic()
    arrivals = [t0 + i / args.rate for i in range(args.requests)]
    pending = list(zip(arrivals, requests))
    while pending or any(r.state not in ("done", "failed")
                         for r in requests):
        now = time.monotonic()
        while pending and pending[0][0] <= now:
            engine.submit(pending.pop(0)[1])
        stats = engine.step()
        if not stats and pending:
            wait = max(0.0, pending[0][0] - time.monotonic())
            if wait > 0:
                time.sleep(wait)
                engine.note_idle(wait)
    wall_s = time.monotonic() - t0

    failed = [r for r in requests if r.state == "failed"]
    if failed:
        raise RuntimeError(
            f"{len(failed)} bench request(s) failed: {failed[0].error}")

    ttft = [r.first_token_time - r.arrival for r in requests]
    ttft_adm = [r.first_token_time - r.admitted_at for r in requests
                if r.admitted_at is not None]
    itl = [b - a for r in requests
           for a, b in zip(r.token_times, r.token_times[1:])]
    total_tokens = sum(len(r.generated) for r in requests)

    breakdown = dict(engine.time_breakdown)
    attributed = sum(breakdown.values())
    unattributed = wall_s - attributed
    attribution = {
        "wall_s": round(wall_s, 4),
        **{f"{k}_s": round(v, 4) for k, v in breakdown.items()},
        "attributed_s": round(attributed, 4),
        "unattributed_fraction": round(unattributed / wall_s, 4),
    }
    attribution["valid"] = abs(unattributed) <= \
        ATTRIBUTION_TOLERANCE * wall_s

    result = {
        "mode": "serve",
        "devices": n_chips,
        "requests": args.requests,
        "rate_rps": args.rate,
        "max_new_tokens": args.max_new,
        "prompt_len_mean": round(float(np.mean([len(p)
                                                for p in prompts])), 1),
        "shared_prefix": args.shared_prefix,
        "max_slots": args.max_slots,
        "prefill_chunk": args.prefill_chunk,
        "kv_block_size": args.block_size,
        "kv_pool_blocks": kv.num_blocks,
        "kv_pool_mib": round(kv.pool_bytes() / 2 ** 20, 2),
        "ttft_ms": _percentiles_ms(ttft),
        "ttft_admission_ms": _percentiles_ms(ttft_adm),
        "inter_token_ms": _percentiles_ms(itl),
        "tokens_generated": total_tokens,
        "tokens_per_sec": round(total_tokens / wall_s, 2),
        "tokens_per_sec_per_chip": round(total_tokens / wall_s / n_chips,
                                         3),
        "cached_prefill_fraction": round(_cached_fraction([engine]), 4),
        "attribution": attribution,
        "tail_attribution": _tail_attribution(tracer),
    }
    _dump_traces(tracer, args.trace_dir)
    return result


def run_fleet_bench(args):
    """N replicas behind the fleet router, open-loop arrivals through
    the frontend path (router.generate), optional mid-run chaos
    preemption of r0. Attribution is per replica over its LIVE window
    (start -> its eviction or the end of the run), summed fleet-wide;
    any failed request fails the bench — the eviction path must drop
    nothing."""
    import jax

    from horovod_tpu.parallel import mesh as mesh_lib
    from horovod_tpu.serve import ServeEngine, ServeTracer
    from horovod_tpu.serve.fleet import FleetRouter

    rng, model, params, kv, mesh, n_chips, prompts = _setup(args)
    # each replica owns a DISJOINT submesh — the fleet topology is one
    # replica per slice, and two engines dispatching concurrent SPMD
    # programs over the SAME devices can deadlock their collectives
    devs = jax.devices()
    per = len(devs) // args.fleet
    if per >= 1:
        meshes = [mesh_lib.build_mesh(devs[i * per:(i + 1) * per])
                  for i in range(args.fleet)]
    else:  # fewer devices than replicas: single-device replicas
        meshes = [mesh_lib.build_mesh([devs[i % len(devs)]])
                  for i in range(args.fleet)]
    engines = [ServeEngine(model, params, kv, mesh=meshes[i],
                           max_slots=args.max_slots,
                           prefill_chunk=args.prefill_chunk,
                           name=f"r{i}")
               for i in range(args.fleet)]
    # the router owns fleet traces whole-life; engines see the SAME
    # RequestTrace riding each per-hop engine request, so a cut
    # stream's spans land in one trace across replicas
    tracer = ServeTracer(sample=1.0)
    router = FleetRouter(grace=args.grace, tracer=tracer)
    for i, eng in enumerate(engines):
        router.add_replica(f"r{i}", eng, env={})
    router.start()

    # warm each replica's two programs outside the measured window
    for eng in engines:
        warm = eng.generate(list(map(int, rng.integers(
            0, args.vocab_size, 3))), 2)
        warm.result(timeout=300)
    for eng in engines:
        eng.prompt_tokens = 0
        eng.cached_prefill_tokens = 0
    tracer.clear()  # drop the warm requests' traces

    chaos_index = (None if args.chaos_at is None
                   else max(1, int(args.chaos_at * args.requests)))
    chaos_thread = None
    # attribution by snapshot delta (attribution_snapshot charges the
    # in-progress idle tick exactly to each side of the boundary)
    base_snap = {r.name: r.engine.attribution_snapshot()
                 for r in router.replicas}
    t0 = time.monotonic()
    arrivals = [t0 + i / args.rate for i in range(args.requests)]
    reqs = []
    for i, (when, prompt) in enumerate(zip(arrivals, prompts)):
        wait = when - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        if chaos_index is not None and i == chaos_index:
            chaos_thread = router.preempt("r0", kind="notice:chaos")
        reqs.append(router.generate(prompt, args.max_new))
    while any(r.state not in ("done", "failed") for r in reqs):
        time.sleep(0.005)
    t_end = time.monotonic()
    end_snap = {r.name: r.engine.attribution_snapshot()
                for r in router.replicas}
    wall_s = t_end - t0
    if chaos_thread is not None:
        chaos_thread.join(timeout=60)

    failed = [r for r in reqs if r.state == "failed"]
    if failed:
        raise RuntimeError(f"{len(failed)} fleet request(s) DROPPED: "
                           f"{failed[0].error}")

    ttft = [r.first_token_time - r.arrival for r in reqs]
    ttft_adm = [r.first_token_time - r.admitted_at for r in reqs
                if r.admitted_at is not None]
    itl = [b - a for r in reqs
           for a, b in zip(r.token_times, r.token_times[1:])]
    total_tokens = sum(len(r.generated) for r in reqs)

    # per-replica attribution: each engine thread accounts its own
    # prefill/decode/overhead/idle; its window ends when it is evicted
    per_replica, live_wall, attributed = {}, 0.0, 0.0
    for rep in router.replicas:
        window = (rep.stopped_at if rep.stopped_at is not None
                  else t_end) - t0
        phases = {k: end_snap[rep.name][k] - base_snap[rep.name][k]
                  for k in end_snap[rep.name]}
        explained = sum(phases.values())
        live_wall += window
        attributed += explained
        per_replica[rep.name] = {
            "state": rep.state,
            "window_s": round(window, 4),
            **{f"{k}_s": round(v, 4) for k, v in phases.items()},
        }
    unattributed = live_wall - attributed
    attribution = {
        "wall_s": round(wall_s, 4),
        "replica_windows_s": round(live_wall, 4),
        "attributed_s": round(attributed, 4),
        "unattributed_fraction": round(unattributed / live_wall, 4),
        "valid": abs(unattributed) <= ATTRIBUTION_TOLERANCE * live_wall,
        "per_replica": per_replica,
    }

    result = {
        "mode": "serve_fleet",
        "devices": n_chips,
        "replicas": args.fleet,
        "requests": args.requests,
        "rate_rps": args.rate,
        "max_new_tokens": args.max_new,
        "prompt_len_mean": round(float(np.mean([len(p)
                                                for p in prompts])), 1),
        "shared_prefix": args.shared_prefix,
        "chaos_at": args.chaos_at,
        "ttft_ms": _percentiles_ms(ttft),
        "ttft_admission_ms": _percentiles_ms(ttft_adm),
        "inter_token_ms": _percentiles_ms(itl),
        "tokens_generated": total_tokens,
        "tokens_per_sec": round(total_tokens / wall_s, 2),
        "cached_prefill_fraction": round(_cached_fraction(engines), 4),
        "redispatched": router.redispatched,
        "dropped": router.dropped,
        "attribution": attribution,
        "tail_attribution": _tail_attribution(
            tracer, chaos=args.chaos_at is not None),
    }
    router.stop()
    _dump_traces(tracer, args.trace_dir)
    return result


def run_acceptance(args):
    """The ISSUE-16 gate: (A) single-replica saturation probe on the
    shared-prefix workload, (B) 2-replica fleet held at 2x that load
    — the p99 TTFT the fleet sustains, (C) chaos soak — one replica
    preempted mid-stream, zero drops, attribution still explaining
    the replica windows."""
    base = dict(vars(args))
    base["shared_prefix"] = args.shared_prefix or 48

    # (A) closed-system probe: everything arrives at once; measured
    # throughput IS the single-replica saturation rate
    probe = argparse.Namespace(**{**base, "rate": 10_000.0, "fleet": 0,
                                  "chaos_at": None})
    single = run_bench(probe)
    sat_rps = single["tokens_per_sec"] / args.max_new

    # (B) 2-replica fleet at 2x single-replica saturation
    fleet_args = argparse.Namespace(**{**base, "fleet": 2,
                                       "rate": 2.0 * sat_rps,
                                       "chaos_at": None})
    fleet = run_fleet_bench(fleet_args)

    # (C) chaos soak: same fleet, moderate overload, r0 preempted
    # mid-schedule — zero drops required (run_fleet_bench raises)
    chaos_args = argparse.Namespace(**{**base, "fleet": 2,
                                       "rate": 1.2 * sat_rps,
                                       "chaos_at": 0.4})
    chaos = run_fleet_bench(chaos_args)

    checks = {
        "cached_prefill_fraction_gt_half":
            fleet["cached_prefill_fraction"] > 0.5,
        "fleet_rate_ge_2x_saturation": fleet["rate_rps"] >= 2 * sat_rps,
        "zero_dropped": chaos["dropped"] == 0,
        "attribution_valid": (single["attribution"]["valid"]
                              and fleet["attribution"]["valid"]
                              and chaos["attribution"]["valid"]),
        # ISSUE 18: ≥98% of every p99-bucket request's latency named,
        # and the doctor blames redispatch_hop for every cut stream
        "tail_attribution_valid": (
            single["tail_attribution"]["valid"]
            and fleet["tail_attribution"]["valid"]
            and chaos["tail_attribution"]["valid"]),
    }
    return {
        "mode": "serve_fleet_acceptance",
        "single_saturation_rps": round(sat_rps, 2),
        "fleet_p99_ttft_ms": fleet["ttft_ms"]["p99"],
        "chaos_redispatched": chaos["redispatched"],
        "checks": checks,
        "passed": all(checks.values()),
        "single": single,
        "fleet_2x": fleet,
        "chaos_soak": chaos,
    }


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.acceptance:
        result = run_acceptance(args)
        ok = result["passed"]
    elif args.fleet:
        result = run_fleet_bench(args)
        ok = (result["attribution"]["valid"]
              and result["tail_attribution"]["valid"])
    else:
        result = run_bench(args)
        ok = (result["attribution"]["valid"]
              and result["tail_attribution"]["valid"])
    print(json.dumps(result, indent=1))
    if not ok:
        if args.acceptance:
            bad = [k for k, v in result["checks"].items() if not v]
            print(f"SERVE FLEET ACCEPTANCE FAILED: {', '.join(bad)}")
        elif not result["attribution"]["valid"]:
            explained = 1 - abs(
                result["attribution"]["unattributed_fraction"])
            print("SERVE ATTRIBUTION VIOLATED: engine phases + idle "
                  f"explain {explained:.1%} of wall clock (tolerance "
                  f"{ATTRIBUTION_TOLERANCE:.0%}) — a scheduler phase is "
                  "leaking unaccounted time")
        else:
            ta = result["tail_attribution"]
            print("SERVE TAIL ATTRIBUTION VIOLATED: a p99-bucket "
                  "request has only "
                  f"{ta.get('p99_bucket_min_attributed_fraction', 0):.1%}"
                  " of its latency named by trace spans (bound "
                  f"{TAIL_ATTRIBUTION_BOUND:.0%})"
                  + ("" if ta.get("cut_streams_redispatch_dominant",
                                  True)
                     else " — and the doctor does not name "
                          "redispatch_hop dominant for every cut "
                          "stream"))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
