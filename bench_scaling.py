"""Weak-scaling sweep over REAL multi-process worlds: the paper's
acceptance curve as a checked-in artifact.

The reference's published claim is 90% scaling efficiency for
ResNet-101 at 512 GPUs (docs/benchmarks.rst:12-14): efficiency =
(img/s at N chips) / (N x img/s at 1 chip), per-chip batch held
constant. This driver measures that curve across a sweep of *worlds*
— each ``PxD`` world is P real ``jax.distributed`` processes x D
local devices forming ONE logical ``(dcn, data)`` mesh via the
process-mesh subsystem (``horovod_tpu/cluster/``, docs/SCALING.md) —
and emits one JSON document per sweep:

* per-world median step time, img/s, img/s/chip and the
  **scaling-efficiency curve** against the sweep's smallest world;
* per-world **goodput breakdown** aggregated across all P processes
  from their goodput-ledger dumps (gate: <= 2% unattributed per
  world);
* per-world **compiled-collective bytes per mesh axis** — the DCN
  tier priced separately from ICI straight from the compiled HLO's
  replica groups (``gspmd.collective_axis_bytes_from_hlo``).

Checked in as ``SCALING_r<NN>.json``, diffed by ``bench.py --compare``
(efficiency is higher-is-better in telemetry/trend.py), so a scaling
regression bends a curve instead of hiding in an anecdote.

CPU stand-in (this is how the checked-in rounds are produced — CPU
timings are NOT meaningful TPU efficiency numbers, the curve's
*structure* and byte ledger are the regression anchors)::

    python bench_scaling.py --model resnet18 --batch-size 2 \
        --image-size 32 --worlds 1x1,1x2,2x1,2x2 --out SCALING_r01.json

On a real pod, point ``--worlds`` at the slice inventory (``4x4`` =
4 hosts x 4 chips) and the same artifact falls out.
"""

import argparse
import json
import os
import shlex
import socket
import subprocess
import sys
import tempfile
import time

WORLD_TIMEOUT_S = 600

BASELINE_EFFICIENCY = {  # reference docs/benchmarks.rst:12-14, 512 GPUs
    "resnet101": 0.90, "resnet50": 0.90, "vgg16": 0.68}


def parse_worlds(spec):
    """``"1x1,1x2,2x2"`` -> ``[(1, 1), (1, 2), (2, 2)]`` (processes x
    local devices per process)."""
    worlds = []
    for tok in spec.split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        try:
            procs, local = tok.split("x")
            worlds.append((int(procs), int(local)))
        except ValueError:
            raise SystemExit(
                f"bench_scaling: bad world {tok!r} (want PROCSxDEVICES, "
                "e.g. 2x2)")
        if worlds[-1][0] < 1 or worlds[-1][1] < 1:
            raise SystemExit(
                f"bench_scaling: bad world {tok!r}: processes and "
                "devices must both be >= 1")
    if not worlds:
        raise SystemExit("bench_scaling: --worlds is empty")
    return worlds


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _strip_forced_device_count(flags):
    return " ".join(f for f in flags.split()
                    if "xla_force_host_platform_device_count" not in f)


def _world_env(rank, procs, local_devices, coord, out_dir):
    env = dict(os.environ)
    env.update({
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(procs),
        "HOROVOD_LOCAL_RANK": str(rank),
        "HOROVOD_LOCAL_SIZE": str(procs),
        "HOROVOD_CROSS_RANK": "0",
        "HOROVOD_CROSS_SIZE": "1",
        "HOROVOD_SPMD_PROCS": str(procs),
        "HOROVOD_SPMD_LOCAL_DEVICES": str(local_devices),
        "HOROVOD_FLIGHTREC": "1",  # goodput dumps even for 1-proc worlds
        "HOROVOD_FLIGHTREC_DIR": out_dir,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (_strip_forced_device_count(
            env.get("XLA_FLAGS", ""))
            + f" --xla_force_host_platform_device_count={local_devices}"
        ).strip(),
    })
    if coord:
        env["HOROVOD_COORDINATOR_ADDR"] = coord
    else:
        env.pop("HOROVOD_COORDINATOR_ADDR", None)
    return env


def run_world(procs, local_devices, worker_args, out_dir,
              timeout=WORLD_TIMEOUT_S):
    """Launch one ``procs x local_devices`` world (every rank a real
    jax.distributed process of one coordinator) and wait. Raises on any
    nonzero rank."""
    coord = f"127.0.0.1:{_free_port()}" if procs > 1 else None
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--result-dir", out_dir] + worker_args
    children = []
    for rank in range(procs):
        log = open(os.path.join(out_dir, f"rank.{rank}.log"), "wb")
        children.append((rank, subprocess.Popen(
            cmd, env=_world_env(rank, procs, local_devices, coord,
                                out_dir),
            stdout=log, stderr=subprocess.STDOUT), log))
    deadline = time.monotonic() + timeout
    failed = []
    try:
        for rank, proc, _log in children:
            left = max(1.0, deadline - time.monotonic())
            try:
                rc = proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
                failed.append((rank, "timeout"))
                continue
            if rc != 0:
                failed.append((rank, f"exit {rc}"))
    finally:
        for _rank, proc, log in children:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            log.close()
    if failed:
        tails = []
        for rank, why in failed:
            path = os.path.join(out_dir, f"rank.{rank}.log")
            with open(path, "rb") as f:
                tail = f.read()[-2000:].decode("utf-8", "replace")
            tails.append(f"--- rank {rank} ({why}) ---\n{tail}")
        raise RuntimeError(
            f"world {procs}x{local_devices} failed: " + "\n".join(tails))


# ---------------------------------------------------------------------------
# Worker: one process of one world. Measures the GSPMD step on the
# process mesh, then writes world_result.rank<R>.json; the goodput dump
# lands via the normal shutdown path.
# ---------------------------------------------------------------------------

def worker(args):
    import jax
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import training
    from horovod_tpu.cluster import mesh_tiers
    from horovod_tpu.utils.benchmarks import (make_model, synthetic_batch,
                                              timed_throughput)

    hvd.init()
    mesh = hvd.mesh()
    chips = int(jax.device_count())
    model = make_model(args.model)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    images, labels = synthetic_batch(args.batch_size * chips,
                                     args.image_size)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        images[:1])
    step = training.make_train_step(model, tx, mesh=mesh, donate=True,
                                    spmd=True)
    ips, dt = timed_throughput(step, state, images, labels,
                               args.num_warmup, args.num_iters)
    result = {
        "rank": int(jax.process_index()),
        "procs": int(jax.process_count()),
        "local_devices": len(jax.local_devices()),
        "chips": chips,
        "global_batch": int(args.batch_size * chips),
        "img_per_sec": round(float(ips), 2),
        "step_ms_median": round(1e3 * dt / args.num_iters, 3),
        "mesh_tiers": mesh_tiers(mesh),
        "collective_bytes_per_axis": step.compiled_axis_collectives,
    }
    path = os.path.join(
        args.result_dir, f"world_result.rank{result['rank']}.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    hvd.shutdown()  # writes goodput.rank<R>.json next to the result


# ---------------------------------------------------------------------------
# Driver: sweep the worlds, aggregate, emit the curve.
# ---------------------------------------------------------------------------

def _world_entry(procs, local, out_dir):
    from horovod_tpu.telemetry import report as report_mod

    with open(os.path.join(out_dir, "world_result.rank0.json")) as f:
        res = json.load(f)
    dumps, skipped = report_mod.load_dumps(out_dir)
    if sorted(dumps) != list(range(procs)):
        raise RuntimeError(
            f"world {procs}x{local}: goodput dumps cover ranks "
            f"{sorted(dumps)}, want 0..{procs - 1} (skipped={skipped})")
    goodput = report_mod.aggregate(dumps)
    fleet = goodput["fleet"]
    unattributed_frac = (fleet["unattributed_seconds"]
                         / max(fleet["wall_seconds"], 1e-9))
    return {
        "world": f"{procs}x{local}",
        "procs": procs,
        "local_devices": local,
        "chips": res["chips"],
        "global_batch": res["global_batch"],
        "step_ms_median": res["step_ms_median"],
        "img_per_sec": res["img_per_sec"],
        "img_per_sec_per_chip": round(
            res["img_per_sec"] / res["chips"], 2),
        "mesh_tiers": res["mesh_tiers"],
        "collective_bytes_per_axis": res["collective_bytes_per_axis"],
        "goodput": {
            "ratio": round(fleet["goodput_ratio"], 4),
            "unattributed_frac": round(unattributed_frac, 4),
            "dominant_sink": fleet["dominant_sink"],
            "ranks": {
                str(r): {
                    "goodput_ratio": round(i["goodput_ratio"], 4),
                    "unattributed_seconds": round(
                        i["unattributed_seconds"], 4),
                    "wall_seconds": round(i["wall_seconds"], 4),
                }
                for r, i in goodput["ranks"].items()},
        },
    }


def drive(args):
    worlds = parse_worlds(args.worlds)
    passthrough = ["--model", args.model,
                   "--batch-size", str(args.batch_size),
                   "--image-size", str(args.image_size),
                   "--num-warmup", str(args.num_warmup),
                   "--num-iters", str(args.num_iters)]
    entries = []
    for procs, local in worlds:
        out_dir = tempfile.mkdtemp(
            prefix=f"scaling_{procs}x{local}_", dir=args.work_dir)
        print(f"bench_scaling: world {procs}x{local} "
              f"({procs * local} chips) ...", file=sys.stderr)
        run_world(procs, local, passthrough, out_dir,
                  timeout=args.world_timeout)
        entry = _world_entry(procs, local, out_dir)
        entries.append(entry)
        print(f"bench_scaling:   {entry['img_per_sec']} img/s "
              f"({entry['img_per_sec_per_chip']}/chip), "
              f"unattributed {entry['goodput']['unattributed_frac']:.2%}",
              file=sys.stderr)

    base = entries[0]
    curve = {}
    for e in entries:
        eff = (e["img_per_sec_per_chip"]
               / max(base["img_per_sec_per_chip"], 1e-9))
        e["efficiency"] = round(eff, 4)
        curve[e["world"]] = e["efficiency"]

    ref = BASELINE_EFFICIENCY.get(args.model)
    last = entries[-1]
    doc = {
        "bench": "scaling",
        "model": args.model,
        "per_chip_batch": args.batch_size,
        "image_size": args.image_size,
        "num_iters": args.num_iters,
        "platform": "cpu" if os.environ.get(
            "JAX_PLATFORMS", "cpu") == "cpu" else os.environ.get(
            "JAX_PLATFORMS"),
        "baseline_world": base["world"],
        "worlds": entries,
        "efficiency_curve": curve,
        "metric": (f"{args.model}_weak_scaling_efficiency_"
                   f"{last['chips']}chips"),
        "value": last["efficiency"],
        "unit": "fraction",
        "vs_baseline": (round(last["efficiency"] / ref, 3)
                        if ref else None),
        "cmd": "python bench_scaling.py " + " ".join(
            shlex.quote(a) for a in sys.argv[1:]),
    }
    bad = [e["world"] for e in entries
           if e["goodput"]["unattributed_frac"] > 0.02]
    if bad:
        doc["unattributed_violations"] = bad
    print(json.dumps(doc if args.verbose_json else {
        k: doc[k] for k in ("metric", "value", "unit", "vs_baseline",
                            "efficiency_curve", "baseline_world")}))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"bench_scaling: wrote {args.out}", file=sys.stderr)
    if bad:
        print(f"bench_scaling: UNATTRIBUTED > 2% in worlds {bad}",
              file=sys.stderr)
        return 1
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet101",
                    choices=["resnet18", "resnet50", "resnet101", "vgg16"])
    ap.add_argument("--batch-size", type=int, default=64,
                    help="PER-CHIP batch (held constant: weak scaling)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-warmup", type=int, default=3)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--worlds", default="1x1,1x2,2x1,2x2",
                    help="comma-separated PROCSxDEVICES worlds, smallest "
                         "first (the first world is the efficiency "
                         "baseline)")
    ap.add_argument("--out", default=None,
                    help="also write the full sweep document here "
                         "(SCALING_r<NN>.json)")
    ap.add_argument("--work-dir", default=None,
                    help="where per-world scratch dirs live (default: "
                         "system temp)")
    ap.add_argument("--world-timeout", type=int, default=WORLD_TIMEOUT_S)
    ap.add_argument("--verbose-json", action="store_true",
                    help="print the full document on stdout instead of "
                         "the one-line summary")
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)  # internal: one world rank
    ap.add_argument("--result-dir", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.worker:
        if not args.result_dir:
            raise SystemExit("bench_scaling: --worker needs --result-dir")
        return worker(args) or 0
    return drive(args)


if __name__ == "__main__":
    sys.exit(main())
