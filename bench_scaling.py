"""Scaling-efficiency harness (the reference's headline metric).

The reference's published claim is 90% scaling efficiency for
ResNet-101 at 512 GPUs (docs/benchmarks.rst:12-14): efficiency =
(img/s at N chips) / (N x img/s at 1 chip). This script measures the
same quantity on a TPU mesh — weak scaling, per-chip batch held
constant — and prints one JSON line.

Single-process (one host's chips): both the 1-chip baseline and the
full mesh are measured here. Multi-host (jax.distributed): a 1-chip
mesh is not constructible from every process, so pass the baseline
from a prior single-chip run via ``--baseline-img-s`` (the reference's
published efficiency numbers were computed the same way: against a
separately measured single-GPU rate).

The plumbing can be exercised anywhere with the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python bench_scaling.py --model resnet18 --batch-size 2 \
        --image-size 32 --num-iters 2
(CPU timings are NOT meaningful TPU efficiency numbers — the flag
exists to test the harness, matching how tests/ exercise sharding.)
"""

import argparse
import json

import jax
import numpy as np
import optax

from horovod_tpu.utils.benchmarks import (make_model, synthetic_batch,
                                          timed_throughput)

BASELINE_EFFICIENCY = {  # reference docs/benchmarks.rst:12-14, 512 GPUs
    "resnet101": 0.90, "resnet50": 0.90, "vgg16": 0.68}


def _throughput(model, tx, mesh, batch_per_chip, image_size, warmup,
                iters):
    from horovod_tpu import training
    images, labels = synthetic_batch(batch_per_chip * mesh.size,
                                     image_size)
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        images[:1])
    step = training.make_train_step(model, tx, mesh=mesh, donate=True)
    ips, _dt = timed_throughput(step, state, images, labels, warmup,
                                iters)
    return ips


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet101",
                    choices=["resnet18", "resnet50", "resnet101", "vgg16"])
    ap.add_argument("--batch-size", type=int, default=64,
                    help="PER-CHIP batch (held constant: weak scaling)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-warmup", type=int, default=3)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--baseline-img-s", type=float, default=None,
                    help="1-chip img/s from a prior run (required for "
                         "multi-host jobs, where a 1-chip mesh is not "
                         "constructible)")
    args = ap.parse_args()

    import horovod_tpu as hvd

    hvd.init()
    devs = np.asarray(jax.devices())
    model = make_model(args.model)
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))

    if args.baseline_img_s is not None:
        t1 = args.baseline_img_s
    elif jax.process_count() > 1:
        raise SystemExit(
            "bench_scaling: multi-host run needs --baseline-img-s from a "
            "prior single-chip measurement")
    else:
        mesh1 = jax.sharding.Mesh(devs[:1], ("data",))
        t1 = _throughput(model, tx, mesh1, args.batch_size,
                         args.image_size, args.num_warmup, args.num_iters)

    if devs.size == 1:
        tN, eff = t1, 1.0
    else:
        meshN = jax.sharding.Mesh(devs, ("data",))
        tN = _throughput(model, tx, meshN, args.batch_size,
                         args.image_size, args.num_warmup, args.num_iters)
        eff = tN / (devs.size * t1)

    ref = BASELINE_EFFICIENCY.get(args.model)
    out = {
        "metric": f"{args.model}_weak_scaling_efficiency_{devs.size}chips",
        "value": round(eff, 4),
        "unit": "fraction",
        "vs_baseline": round(eff / ref, 3) if ref else None,
        "img_per_sec_1chip": round(t1, 1),
        "img_per_sec_full_mesh": round(tN, 1),
        "n_devices": int(devs.size),
    }
    if devs.size == 1:
        out["note"] = ("single device: efficiency trivially 1.0; run on "
                       "a multi-chip mesh for the real number")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
