"""TF2/Keras MNIST-style training under hvdrun (reference
``examples/tensorflow2_keras_mnist.py``): DistributedOptimizer wrap,
rank-0-scaled learning rate, broadcast + metric-average callbacks, and
rank-0-only checkpointing — the canonical Horovod Keras recipe on the
horovod_tpu host plane.

Run:
    python -m horovod_tpu.run -np 2 -H localhost:2 \
        python examples/tensorflow2_keras_mnist.py --epochs 2

Synthetic MNIST-shaped data keeps it network-free; swap in
``tf.keras.datasets.mnist`` outside sandboxes.
"""

import argparse
import os
import tempfile

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd
import horovod_tpu.tensorflow.keras as hvd_keras
from horovod_tpu.tensorflow.callbacks import (
    BroadcastGlobalVariablesCallback, MetricAverageCallback)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--samples", type=int, default=256)
    args = ap.parse_args()

    hvd.init()

    # rank-disjoint synthetic data (each rank sees its own shard)
    rng = np.random.default_rng(hvd.rank())
    images = rng.normal(size=(args.samples, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, size=(args.samples,)).astype(np.int64)

    # resume conventions (reference keras_imagenet_resnet50.py:102-158):
    # rank 0 discovers the newest checkpoint epoch from disk, the epoch
    # number is BROADCAST so every rank agrees, and rank 0's model state
    # loads from the file (the BroadcastGlobalVariablesCallback then
    # syncs the weights to everyone)
    ckpt_dir = os.environ.get("CKPT_DIR", tempfile.mkdtemp())
    resume_from_epoch = 0
    if hvd.rank() == 0:
        for epoch in range(args.epochs, 0, -1):
            if os.path.exists(os.path.join(ckpt_dir,
                                           f"ckpt-{epoch}.keras")):
                resume_from_epoch = epoch
                break
    resume_from_epoch = int(hvd.broadcast(
        tf.constant(resume_from_epoch, tf.int64), root_rank=0,
        name="resume_from_epoch").numpy())

    if resume_from_epoch > 0 and hvd.rank() == 0:
        model = hvd_keras.load_model(
            os.path.join(ckpt_dir, f"ckpt-{resume_from_epoch}.keras"))
        print(f"resuming from epoch {resume_from_epoch}")
    else:
        model = tf.keras.Sequential([
            tf.keras.Input(shape=(28, 28, 1)),
            tf.keras.layers.Conv2D(8, [3, 3], activation="relu"),
            tf.keras.layers.MaxPooling2D(pool_size=(2, 2)),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(32, activation="relu"),
            tf.keras.layers.Dense(10, activation="softmax"),
        ])
        # reference recipe: scale lr by world size, wrap the optimizer
        opt = hvd_keras.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.01 * hvd.size(),
                                    momentum=0.9))
        model.compile(optimizer=opt,
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])

    callbacks = [BroadcastGlobalVariablesCallback(0),
                 MetricAverageCallback()]
    # rank-0-only checkpointing (SURVEY §5.4 conventions)
    if hvd.rank() == 0:
        callbacks.append(tf.keras.callbacks.ModelCheckpoint(
            os.path.join(ckpt_dir, "ckpt-{epoch}.keras")))

    hist = model.fit(images, labels, batch_size=args.batch_size,
                     epochs=args.epochs,
                     initial_epoch=resume_from_epoch,
                     verbose=1 if hvd.rank() == 0 else 0,
                     callbacks=callbacks)
    losses = hist.history.get("loss", [])
    final = losses[-1] if losses else float("nan")
    print(f"rank {hvd.rank()} final loss {final:.4f}")
    if hvd.rank() == 0:
        saved = sorted(os.listdir(ckpt_dir))
        assert saved, "rank-0 checkpoints missing"
        print(f"checkpoints: {saved}")
    print("done")


if __name__ == "__main__":
    main()
