"""Synthetic throughput benchmark (reference:
``examples/pytorch_synthetic_benchmark.py``): timed batches after warmup,
img/sec through the DistributedOptimizer hot path.

    python examples/jax_synthetic_benchmark.py --model resnet50 \
        --batch-size 64 --num-iters 10
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import models, training


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet18", "resnet34", "resnet50", "resnet101",
                            "resnet152", "vgg16"])
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-warmup-batches", type=int, default=3)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="bf16 wire compression for gradient allreduce")
    args = p.parse_args()

    hvd.init()
    ndev = hvd.num_devices()
    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32

    model_cls = {
        "resnet18": models.ResNet18, "resnet34": models.ResNet34,
        "resnet50": models.ResNet50, "resnet101": models.ResNet101,
        "resnet152": models.ResNet152, "vgg16": models.VGG16,
    }[args.model]
    model = model_cls(num_classes=1000, dtype=dtype)

    compression = hvd.Compression.bf16 if args.fp16_allreduce else None
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                  compression=compression)

    gb = args.batch_size * ndev
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal(
        (gb, args.image_size, args.image_size, 3)), dtype)
    labels = jnp.asarray(rng.integers(0, 1000, size=(gb,)), jnp.int32)

    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        images[:1])
    step = training.make_train_step(model, tx)

    print(f"Model: {args.model}, batch {args.batch_size}/chip x {ndev} "
          f"chips ({platform})")
    from horovod_tpu.utils.benchmarks import slope_window, sync
    for _ in range(args.num_warmup_batches):
        state, loss = step(state, images, labels)
        sync(loss)

    # readback-slope timing per iter (utils/benchmarks.slope_window: the
    # async tunnel makes block_until_ready-based windows undercount time)
    img_secs = []
    for i in range(args.num_iters):
        dt, state = slope_window(
            lambda st: step(st, images, labels), state,
            args.num_batches_per_iter, base_iters=1)
        rate = gb * args.num_batches_per_iter / dt
        img_secs.append(rate)
        print(f"Iter #{i}: {rate:.1f} img/sec total")
    print(f"Img/sec per chip: {np.mean(img_secs) / ndev:.1f} "
          f"+- {1.96 * np.std(img_secs) / ndev:.1f}")
    print(f"Total img/sec on {ndev} chip(s): {np.mean(img_secs):.1f} "
          f"+- {1.96 * np.std(img_secs):.1f}")


if __name__ == "__main__":
    main()
