"""ImageNet-class ResNet-50 training with per-rank dataset sharding.

The JAX counterpart of the reference's flagship real-data example
(``examples/pytorch_imagenet_resnet50.py``): every rank

* takes a DISJOINT shard of the dataset each epoch through the data
  plane's ``PrefetchLoader`` (docs/DATA.md): a background thread
  assembles the next batch while the current step computes, and the
  epoch-keyed shuffle stays deterministic per rank (the
  ``torch.utils.data.distributed.DistributedSampler`` role),
* computes gradients locally (jit-compiled), averages them across ranks
  with the fused eager allreduce,
* follows the full checkpoint/resume discipline (rank-0 atomic writes,
  broadcast restore — ``examples/keras_imagenet_resnet50.py:85-103``),
  repositioning the loader's cursor at the resume epoch.

Real data: ``--data-dir DIR`` with ``train.npz`` containing ``images``
(N, H, W, 3) uint8/float and ``labels`` (N,) int. Without it, a
deterministic synthetic ImageNet-shaped set is generated so the example
runs hermetically (the reference's synthetic fallback pattern).

Run:  hvdrun -np 2 python examples/jax_imagenet_resnet50.py \
          --depth 18 --num-filters 4 --image-size 32 --epochs 2
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import checkpoint, data, models, training


def load_or_synthesize(args, rank):
    path = os.path.join(args.data_dir or "", "train.npz")
    if args.data_dir and os.path.exists(path):
        with np.load(path) as z:
            images = np.asarray(z["images"], np.float32)
            if images.max() > 2.0:  # uint8-scaled
                images = images / 127.5 - 1.0
            return images, np.asarray(z["labels"], np.int64)
    if rank == 0:
        print("no --data-dir; using synthetic ImageNet-shaped data")
    rng = np.random.default_rng(1234)  # same data on every rank
    images = rng.standard_normal(
        (args.num_examples, args.image_size, args.image_size, 3)
    ).astype(np.float32)
    labels = rng.integers(0, args.num_classes, size=(args.num_examples,))
    return images, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_imagenet_ckpt")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=8,
                    help="PER-RANK batch size")
    ap.add_argument("--lr", type=float, default=0.0125,
                    help="per-worker base LR; scaled by world size like "
                         "the reference (linear scaling rule)")
    ap.add_argument("--depth", type=int, default=50, choices=[18, 50, 101])
    ap.add_argument("--num-filters", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--num-examples", type=int, default=64,
                    help="synthetic-fallback dataset size")
    args = ap.parse_args()

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    images, labels = load_or_synthesize(args, rank)
    n = len(images)

    arch = {18: models.ResNet18, 50: models.ResNet50,
            101: models.ResNet101}[args.depth]
    model = arch(num_classes=args.num_classes, num_filters=args.num_filters)
    tx = optax.sgd(args.lr * size, momentum=0.9)

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1,) + images.shape[1:]), train=False)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt_state = tx.init(params)

    # resume discipline: rank 0 restores the newest checkpoint, the
    # start epoch + params + optimizer state broadcast to everyone
    start, params, opt_state, _meta = checkpoint.restore_or_init(
        args.ckpt_dir, params, opt_state)
    if rank == 0 and start > 0:
        print(f"resuming from epoch {start}")

    @jax.jit
    def grad_step(params, batch_stats, x, y):
        def loss_fn(p):
            out, mut = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            return (training.softmax_cross_entropy(out, y),
                    mut["batch_stats"])
        (loss, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return loss, grads, stats

    # the data plane: per-rank disjoint shards, epoch-keyed reshuffle,
    # and the NEXT batch assembled on a background thread while this
    # one trains (docs/DATA.md). The cursor repositions the stream at
    # the resume epoch — same mechanism that rides the checkpoint
    # manifest under hvd.elastic.JaxState(loader=...).
    loader = data.PrefetchLoader(
        data.ArraySource([images, labels]), args.batch_size,
        rank=rank, world=size, epochs=args.epochs)
    if start:
        cur = loader.cursor()
        cur["epoch"] = start
        loader.set_cursor(cur)
    for epoch in range(start, args.epochs):
        losses, seen = [], 0
        for _ in range(loader.batches_remaining_in_epoch()):
            bx, by = next(loader)
            loss, grads, batch_stats = grad_step(
                params, batch_stats, jnp.asarray(bx),
                jnp.asarray(by, jnp.int32))
            # fused cross-rank gradient average (per-rank BN stats stay
            # local, matching the reference's torch example)
            grads = hvd.fused_allreduce(grads, op=hvd.Average)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            losses.append(float(loss))
            seen += len(bx)
        mean_loss = float(np.asarray(hvd.allreduce(
            np.float32(np.mean(losses)), op=hvd.Average)))
        checkpoint.save_checkpoint(args.ckpt_dir, epoch + 1, params,
                                   opt_state, meta={"epoch": epoch + 1},
                                   keep=3)
        if rank == 0:
            print(f"epoch {epoch + 1}: loss {mean_loss:.4f} "
                  f"({seen * size} examples/epoch across {size} ranks)")
    loader.close()
    print(f"rank {rank} done")


if __name__ == "__main__":
    main()
