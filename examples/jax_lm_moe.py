"""Switch-Transformer MoE LM training over a (data x expert) mesh.

Every 2nd block's MLP is a top-1 mixture-of-experts
(``TransformerConfig(moe_every=2)``); expert weights shard over the
``expert`` axis (num-experts / expert-parallel experts per device) and
GSPMD inserts the token all-to-alls (``docs/PARALLELISM.md`` — Expert
parallelism).

The full Switch training recipe is on: the router sows the
load-balancing auxiliary loss + router z-loss into the ``"losses"``
collection and ``make_tp_lm_train_step`` adds them to the LM loss
(weights 0.01 / 1e-3), and token dispatch is grouped
(``moe_num_groups``) so dispatch memory scales O(T^2/G) instead of
O(T^2).

Run on the virtual CPU mesh:
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/jax_lm_moe.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.parallel import make_tp_lm_train_step, shard_lm_state


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--expert-parallel", type=int, default=4)
    ap.add_argument("--num-experts", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()

    n = len(jax.devices())
    ep = args.expert_parallel
    assert n % ep == 0, f"{n} devices not divisible by expert={ep}"
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()).reshape(n // ep, ep), ("data", "expert"))

    cfg = TransformerConfig(vocab_size=256, num_layers=4, num_heads=4,
                            d_model=args.d_model, d_ff=4 * args.d_model,
                            dtype=jnp.float32, moe_every=2,
                            num_experts=args.num_experts, expert_mesh=mesh,
                            moe_num_groups=8, moe_group_axis="data")
    model = Transformer(cfg)
    tx = optax.adam(1e-3)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(8, args.seq_len)), jnp.int32)

    state = shard_lm_state(model, tx, jax.random.PRNGKey(0), tokens[:1],
                           mesh, model_axis=None, expert_axis="expert")
    w_in = state.params["block_1"]["moe"]["w_in"]
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}, "
          f"experts: {args.num_experts}, w_in sharding: "
          f"{w_in.sharding.spec}, per-device shard: "
          f"{w_in.addressable_shards[0].data.shape}")

    step = make_tp_lm_train_step(model, tx, mesh, model_axis=None,
                                 expert_axis="expert")
    for i in range(args.steps):
        state, loss = step(state, tokens)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d} loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()
