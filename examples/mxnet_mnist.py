"""MXNet/Gluon MNIST-style training under hvdrun (reference
``examples/mxnet_mnist.py``): DistributedTrainer, parameter broadcast,
rank-scaled learning rate — the canonical Horovod Gluon recipe on the
horovod_tpu host plane.

Run (requires mxnet — present in the real-frameworks CI job, not in the
Python-3.12 dev image):
    python -m horovod_tpu.run -np 2 -H localhost:2 \
        python examples/mxnet_mnist.py --epochs 2

Synthetic MNIST-shaped data keeps it network-free.
"""

import argparse

import numpy as np

import horovod_tpu.mxnet as hvd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--samples", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    import mxnet as mx
    from mxnet import autograd, gluon

    hvd.init()
    ctx = mx.cpu()

    rng = np.random.default_rng(hvd.rank())
    images = mx.nd.array(
        rng.normal(size=(args.samples, 1, 28, 28)).astype(np.float32))
    labels = mx.nd.array(
        rng.integers(0, 10, size=(args.samples,)).astype(np.float32))

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(channels=8, kernel_size=3, activation="relu"),
            gluon.nn.MaxPool2D(pool_size=2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier(), ctx=ctx)
    net.hybridize()

    params = net.collect_params()
    # reference recipe: broadcast initial params, scale lr by world size
    hvd.broadcast_parameters(params, root_rank=0)
    trainer = hvd.DistributedTrainer(
        params, "sgd",
        {"learning_rate": args.lr * hvd.size(), "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    n_batches = args.samples // args.batch_size
    for epoch in range(args.epochs):
        total = 0.0
        for b in range(n_batches):
            lo = b * args.batch_size
            x = images[lo:lo + args.batch_size].as_in_context(ctx)
            y = labels[lo:lo + args.batch_size].as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.mean().asscalar())
        if hvd.rank() == 0:
            print(f"epoch {epoch} loss {total / n_batches:.4f}")
    print("done")


if __name__ == "__main__":
    main()
