"""Checkpoint/resume example (reference pattern:
``examples/keras_imagenet_resnet50.py:85-103,156-158``).

Demonstrates the distributed checkpoint discipline:

* only rank 0 writes checkpoints (other workers would corrupt them),
* the resume step is discovered on rank 0 and broadcast,
* parameters + optimizer state are broadcast from root after restore so
  every worker starts identical.

Run, kill it mid-way (Ctrl-C), run again — it resumes where it left off:

    hvdrun -np 2 python examples/jax_checkpoint_resume.py --epochs 20
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import checkpoint


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", default="/tmp/hvd_tpu_ckpt")
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    hvd.init()
    rank, size = hvd.rank(), hvd.size()

    # deterministic synthetic regression task, sharded by rank
    rng = np.random.RandomState(42)
    X = rng.randn(256, 8).astype(np.float32)
    W_true = rng.randn(8, 1).astype(np.float32)
    Y = X @ W_true
    xs, ys = X[rank::size], Y[rank::size]

    params = {"w": jnp.zeros((8, 1))}
    opt = hvd.DistributedOptimizer(optax.adam(args.lr))
    state = opt.init(params)

    # the whole resume convention in one call: rank 0 restores the newest
    # checkpoint (if any), everyone gets the broadcast step/params/state
    start, params, state, meta = checkpoint.restore_or_init(
        args.ckpt_dir, params, state)
    if rank == 0 and start > 0:
        print(f"resuming from step {start} (meta={meta})")

    @jax.jit
    def loss_and_grad(p):
        return jax.value_and_grad(
            lambda p: jnp.mean((xs @ p["w"] - ys) ** 2))(p)

    for epoch in range(start, args.epochs):
        loss, grads = loss_and_grad(params)
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        # average the metric across workers before logging (§5.5)
        mean_loss = float(np.asarray(hvd.allreduce(
            np.asarray(loss, dtype=np.float32), op=hvd.Average)))
        # rank-0-only write; keep the 3 newest
        checkpoint.save_checkpoint(args.ckpt_dir, epoch + 1, params, state,
                                   meta={"epoch": epoch + 1}, keep=3)
        if rank == 0:
            print(f"epoch {epoch + 1}: loss {mean_loss:.6f}")

    if rank == 0:
        err = float(np.max(np.abs(np.asarray(params["w"]) - W_true)))
        print(f"done; max |w - w_true| = {err:.4f}")


if __name__ == "__main__":
    main()
