"""Hierarchical (ICI x DCN) data-parallel training.

The TPU rebuild of the reference's NCCLHierarchicalAllreduce
(``nccl_operations.cc:150``; SURVEY §2.7): on a multi-slice pod the
mesh is 2-D — a fast ICI axis within each slice and a slow DCN axis
across slices — and gradient reduction runs reduce-scatter over ICI,
allreduce of the 1/k shard over DCN, then all-gather over ICI, paying
the slow link only 1/k of the bytes.

The same code runs anywhere; on a laptop/CI simulate 2 slices x 4:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/jax_hierarchical_allreduce.py --slices 2
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import training
from horovod_tpu.parallel import mesh as mesh_lib


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slices", type=int, default=2,
                    help="DCN axis size (number of slices), >= 2")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-per-chip", type=int, default=4)
    args = ap.parse_args()
    if args.slices < 2:
        raise SystemExit("--slices must be >= 2: with one slice there is "
                         "no DCN axis and nothing hierarchical to show")
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")

    hvd.init()
    mesh = mesh_lib.build_mesh(num_slices=args.slices)
    mesh_lib.set_mesh(mesh)
    axes = mesh_lib.data_axis_names(mesh)
    ndev = mesh.size
    if hvd.rank() == 0:
        print(f"mesh axes {dict(mesh.shape)} -> reduce-scatter over "
              f"{axes[-1]!r} (ICI), allreduce over {axes[0]!r} (DCN)")

    from horovod_tpu import models
    model = models.ResNet18(num_classes=10, dtype=jnp.float32)
    tx = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9),
                                  axes=axes, hierarchical=True)

    rng = np.random.default_rng(0)
    n = args.batch_per_chip * ndev
    images = jnp.asarray(rng.standard_normal((n, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, size=(n,)), jnp.int32)

    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        images[:1])
    step = training.make_train_step(model, tx, mesh=mesh)
    first = last = None
    for i in range(args.steps):
        state, loss = step(state, images, labels)
        last = float(loss)
        first = first if first is not None else last
    if hvd.rank() == 0:
        print(f"done: loss {first:.4f} -> {last:.4f} over "
              f"{dict(mesh.shape)}")


if __name__ == "__main__":
    main()
