"""Elastic training example (reference: examples/elastic/* in Horovod
0.20+), runnable on CPU:

    JAX_PLATFORMS=cpu python examples/elastic_train.py

or elastically across hosts:

    hvdrun -np 2 --min-np 1 python examples/elastic_train.py
    hvdrun --min-np 2 --max-np 8 \
        --host-discovery-script ./hosts.sh python examples/elastic_train.py

Trains a small MLP on synthetic data under the elastic contract:
``JaxState`` holds the whole ``TrainState`` (disk-backed commits, so a
relaunched worker resumes from the last committed step), and the
``@hvd.elastic.run`` loop absorbs membership interrupts at commit
boundaries. To see a recovery locally, kill the process mid-run and
start it again — it resumes from the last commit.
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.training import TrainState

NUM_STEPS = 30
COMMIT_EVERY = 5
BATCH, DIM, HIDDEN = 32, 8, 16


def make_batch(step):
    """Step-indexed synthetic data: a restarted worker re-reads the same
    batch for the same step, keeping the trajectory deterministic."""
    rng = np.random.default_rng(step)
    x = rng.standard_normal((BATCH, DIM)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def init_params(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (DIM, HIDDEN)) * 0.1,
        "b1": jnp.zeros((HIDDEN,)),
        "w2": jax.random.normal(k2, (HIDDEN, 2)) * 0.1,
        "b2": jnp.zeros((2,)),
    }


def main():
    hvd.init()
    tx = hvd.DistributedOptimizer(optax.adam(1e-2))
    params = init_params(jax.random.PRNGKey(0))
    ts = TrainState(params=params, opt_state=tx.init(params),
                    batch_stats={}, step=jnp.zeros((), jnp.int32))

    ckpt_dir = os.environ.get(
        "ELASTIC_CKPT_DIR",
        os.path.join(tempfile.gettempdir(), "hvd_tpu_elastic_example"))
    state = hvd.elastic.JaxState(directory=ckpt_dir, train_state=ts)

    @jax.jit
    def train_step(ts, x, y):
        def loss_fn(p):
            h = jnp.tanh(x @ p["w1"] + p["b1"])
            logits = h @ p["w2"] + p["b2"]
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(
                logp, y[:, None], axis=1))

        loss, grads = jax.value_and_grad(loss_fn)(ts.params)
        updates, opt_state = tx.update(grads, ts.opt_state, ts.params)
        new_params = optax.apply_updates(ts.params, updates)
        return TrainState(params=new_params, opt_state=opt_state,
                          batch_stats={}, step=ts.step + 1), loss

    @hvd.elastic.run
    def train(state):
        while int(state.train_state.step) < NUM_STEPS:
            step = int(state.train_state.step)
            x, y = make_batch(step)
            state.train_state, loss = train_step(state.train_state, x, y)
            if (step + 1) % COMMIT_EVERY == 0:
                state.commit()
            if hvd.rank() == 0:
                print(f"step {step + 1:3d}  loss {float(loss):.4f}")
        state.commit()
        return state.train_state

    final = train(state)
    if hvd.rank() == 0:
        print(f"done at step {int(final.step)} "
              f"(committed checkpoints in {ckpt_dir})")


if __name__ == "__main__":
    main()
