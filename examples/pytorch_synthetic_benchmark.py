"""PyTorch synthetic benchmark under hvdrun (reference
``examples/pytorch_synthetic_benchmark.py`` — the script behind the
published numbers): timed batches after warmup, img/sec, through
``horovod_tpu.torch``'s DistributedOptimizer.

The torch adapter is the HOST data plane (CPU tensors through the C++
ring collectives) — the TPU headline lives in
``jax_synthetic_benchmark.py``; this script demonstrates and measures
the torch API surface on the same protocol.

Run:
    python -m horovod_tpu.run -np 2 -H localhost:2 \
        python examples/pytorch_synthetic_benchmark.py --num-iters 3
"""

import argparse
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class SmallNet(nn.Module):
    """A conv net sized so a CPU-plane benchmark finishes in seconds
    (``--model resnet50`` via torchvision is the reference config; this
    default keeps the smoke test torchvision-free)."""

    def __init__(self, image_size=32):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 16, 3, padding=1)
        self.conv2 = nn.Conv2d(16, 32, 3, padding=1, stride=2)
        self.fc = nn.Linear(32 * ((image_size + 1) // 2) ** 2, 10)

    def forward(self, x):
        x = F.relu(self.conv1(x))
        x = F.relu(self.conv2(x))
        return self.fc(x.flatten(1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--num-warmup-batches", type=int, default=2)
    ap.add_argument("--num-batches-per-iter", type=int, default=5)
    ap.add_argument("--num-iters", type=int, default=3)
    args = ap.parse_args()

    hvd.init()
    torch.manual_seed(hvd.rank())
    model = SmallNet(image_size=args.image_size)
    opt = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size(),
                          momentum=0.9)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    data = torch.randn(args.batch_size, 3, args.image_size,
                       args.image_size)
    target = torch.randint(0, 10, (args.batch_size,))

    def benchmark_step():
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()

    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.perf_counter() - t0
        rate = args.batch_size * args.num_batches_per_iter / dt
        img_secs.append(rate)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {rate:.1f} img/sec per process")
    if hvd.rank() == 0:
        print(f"Img/sec per process: {np.mean(img_secs):.1f} "
              f"+- {1.96 * np.std(img_secs):.1f}")
        print(f"Total img/sec on {hvd.size()} processes: "
              f"{np.mean(img_secs) * hvd.size():.1f}")
    print("done")


if __name__ == "__main__":
    main()
