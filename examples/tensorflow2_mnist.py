"""TF2 custom-training-loop MNIST-style example under hvdrun (reference
``examples/tensorflow2_mnist.py``): ``DistributedGradientTape`` wraps a
plain ``tf.GradientTape``, initial variables broadcast from rank 0,
rank-0-only checkpointing — the non-Keras TF2 recipe.

Run:
    python -m horovod_tpu.run -np 2 -H localhost:2 \
        python examples/tensorflow2_mnist.py --steps 20

Synthetic MNIST-shaped data keeps it network-free.
"""

import argparse
import os
import tempfile

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    hvd.init()
    rng = np.random.default_rng(hvd.rank())  # rank-disjoint data

    model = tf.keras.Sequential([
        tf.keras.Input(shape=(28, 28, 1)),
        tf.keras.layers.Conv2D(8, [3, 3], activation="relu"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(10),
    ])
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)
    # reference recipe: lr scaled by world size
    opt = tf.keras.optimizers.SGD(learning_rate=0.01 * hvd.size())

    @tf.function
    def train_step(images, labels):
        with tf.GradientTape() as tape:
            logits = model(images, training=True)
            loss = loss_fn(labels, logits)
        # DistributedGradientTape averages gradients across ranks
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    for step in range(args.steps):
        images = tf.constant(rng.normal(
            size=(args.batch_size, 28, 28, 1)).astype(np.float32))
        labels = tf.constant(rng.integers(
            0, 10, size=(args.batch_size,)).astype(np.int64))
        loss = train_step(images, labels)
        if step == 0:
            # reference: broadcast variables after the first step so
            # late-created slot variables sync too
            hvd.broadcast_variables(model.variables, root_rank=0)
            # Keras 3 exposes .variables as a property; legacy Keras 2
            # optimizers as a method
            opt_vars = opt.variables() if callable(opt.variables) \
                else opt.variables
            hvd.broadcast_variables(opt_vars, root_rank=0)
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step} loss {float(loss):.4f}")

    # rank-0-only checkpoint (SURVEY 5.4 conventions)
    if hvd.rank() == 0:
        ckpt_dir = os.environ.get("CKPT_DIR", tempfile.mkdtemp())
        path = os.path.join(ckpt_dir, "model.weights.h5")
        model.save_weights(path)
        print(f"checkpoint: {os.path.basename(path)}")
    # prove sync: weights must be identical across ranks
    flat = np.concatenate([v.numpy().ravel()
                           for v in model.trainable_variables])
    digest = float(np.sum(flat ** 2))
    gathered = hvd.allgather(
        tf.constant([digest], tf.float64), name="digest").numpy()
    assert np.allclose(gathered, gathered[0]), gathered
    print("done")


if __name__ == "__main__":
    main()
