"""Long-context LM training with ring-attention sequence parallelism.

The TPU answer to the reference's data-parallel-only scaling story
(SURVEY §5.7 beyond-parity): a (data, seq) mesh where the sequence
dimension is sharded across chips and attention runs as a ring —
each shard holds S/n tokens, K/V blocks rotate around the ring via
``ppermute`` with online-softmax accumulation (fp32), so the sequence
length a job can train on scales linearly with the ``seq`` axis while
the next-token loss stays EXACT (boundary targets stitched across
shards, ``training.make_lm_train_step``).

Runs on any device count — on a laptop/CI use the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/jax_lm_seq_parallel.py --data 2 --seq 4
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import training
from horovod_tpu.models.transformer import Transformer, TransformerConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", type=int, default=2, help="data-axis size")
    ap.add_argument("--seq", type=int, default=4, help="seq-axis size")
    ap.add_argument("--seq-len", type=int, default=256,
                    help="global sequence length (sharded seq-ways)")
    ap.add_argument("--batch", type=int, default=4,
                    help="global batch (sharded data-ways)")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--flash", action="store_true",
                    help="Pallas flash kernels per ring block (fused "
                         "forward AND backward). Per-shard seq len must "
                         "divide by the kernel block (128, or the shard "
                         "length itself when shorter, min multiple of 8) "
                         "and head dim by 8 — otherwise the ring "
                         "silently falls back to the jnp path")
    args = ap.parse_args()

    hvd.init()
    devs = np.asarray(jax.devices())
    assert devs.size >= args.data * args.seq, (
        f"need {args.data * args.seq} devices, have {devs.size}")
    mesh = jax.sharding.Mesh(
        devs[:args.data * args.seq].reshape(args.data, args.seq),
        ("data", "seq"))

    dtype = (jnp.bfloat16 if devs[0].platform == "tpu" else jnp.float32)
    cfg = TransformerConfig(vocab_size=256, num_layers=args.layers,
                            num_heads=4, d_model=args.d_model,
                            d_ff=4 * args.d_model, dtype=dtype,
                            sequence_axis="seq",
                            flash_attention=args.flash)
    model = Transformer(cfg)
    # params are seq-layout independent: init with the dense clone
    init_model = Transformer(
        TransformerConfig(**{**cfg.__dict__, "sequence_axis": None}))

    tx = hvd.DistributedOptimizer(optax.adam(3e-3), axes=("data", "seq"))

    # toy copy-task data: predictable next tokens so loss visibly drops
    rng = np.random.default_rng(0)
    pattern = rng.integers(0, 256, size=(args.seq_len // 8,))
    tokens = jnp.asarray(np.tile(pattern, (args.batch, 8)), jnp.int32)

    state = training.create_train_state(init_model, tx,
                                        jax.random.PRNGKey(0), tokens[:1])
    step = training.make_lm_train_step(model, tx, mesh=mesh,
                                       batch_axis="data", seq_axis="seq")
    first = last = None
    for i in range(args.steps):
        state, loss = step(state, tokens)
        loss = float(loss)
        first = first if first is not None else loss
        last = loss
        if hvd.rank() == 0 and (i + 1) % 10 == 0:
            print(f"step {i + 1}: loss {loss:.4f}")
    assert last < first, (first, last)
    if hvd.rank() == 0:
        print(f"done: loss {first:.4f} -> {last:.4f} on a "
              f"{args.data}x{args.seq} (data x seq) mesh, "
              f"global seq len {args.seq_len}")


if __name__ == "__main__":
    main()
