"""MNIST-scale training with horovod_tpu (reference:
``examples/tensorflow2_mnist.py``): wrap the optimizer, broadcast initial
state, shard the batch. Uses synthetic data so it runs hermetically.

Single chip:   python examples/jax_mnist.py
CPU 8-mesh:    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
               XLA_FLAGS=--xla_force_host_platform_device_count=8 \
               python examples/jax_mnist.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import training
from horovod_tpu.models import MNISTConvNet


def main():
    hvd.init()
    ndev = hvd.num_devices()
    rng = np.random.default_rng(0)

    # synthetic "MNIST": a bright column at 2*label over noise
    n = 128 * ndev
    labels = rng.integers(0, 10, size=(n,))
    images = (rng.standard_normal((n, 28, 28, 1)) * 0.1).astype(np.float32)
    images[np.arange(n), :, labels * 2, 0] += 1.0

    model = MNISTConvNet()
    tx = hvd.DistributedOptimizer(optax.adam(3e-3))
    state = training.create_train_state(model, tx, jax.random.PRNGKey(0),
                                        jnp.zeros((1, 28, 28, 1)))
    step = training.make_train_step(model, tx)

    batch = 16 * ndev
    first_epoch_loss = None
    for epoch in range(6):
        perm = rng.permutation(n)
        epoch_loss = []
        for i in range(0, n, batch):
            idx = perm[i:i + batch]
            if len(idx) < batch:
                break
            state, loss = step(state, jnp.asarray(images[idx]),
                               jnp.asarray(labels[idx]))
            epoch_loss.append(float(loss))
        print(f"epoch {epoch}: loss {np.mean(epoch_loss):.4f}")
        if first_epoch_loss is None:
            first_epoch_loss = np.mean(epoch_loss)
    assert np.mean(epoch_loss) < first_epoch_loss * 0.6, "did not learn"
    print("done")


if __name__ == "__main__":
    main()
