"""Tensor-parallel LM training over a (data x model) mesh.

The GSPMD path (``horovod_tpu/parallel/tensor.py``): attention heads and
the MLP hidden dim are sharded over the ``model`` axis by parameter
shardings alone; XLA inserts the Megatron-style all-reduces and the
cross-``data`` gradient reduction. Compare ``jax_lm_seq_parallel.py``
(ring attention over a ``seq`` axis) for the long-context strategy.

Run on the virtual CPU mesh:
    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/jax_lm_tensor_parallel.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu.models.transformer import Transformer, TransformerConfig
from horovod_tpu.parallel import tensor as tp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--model-parallel", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()

    n = len(jax.devices())
    mp = args.model_parallel
    assert n % mp == 0, f"{n} devices not divisible by model={mp}"
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()).reshape(n // mp, mp), ("data", "model"))

    cfg = TransformerConfig(vocab_size=256, num_layers=2, num_heads=mp,
                            d_model=args.d_model, d_ff=4 * args.d_model,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    tx = optax.adam(1e-3)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(8, args.seq_len)), jnp.int32)

    state = tp.shard_lm_state(model, tx, jax.random.PRNGKey(0), tokens[:1],
                              mesh)
    kern = state.params["block_0"]["Dense_0"]["kernel"]
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}, "
          f"d_ff kernel sharding: {kern.sharding.spec}, "
          f"per-device shard: {kern.addressable_shards[0].data.shape}")

    step = tp.make_tp_lm_train_step(model, tx, mesh)
    for i in range(args.steps):
        state, loss = step(state, tokens)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d} loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()
