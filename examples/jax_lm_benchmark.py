"""Synthetic LM training benchmark: tokens/sec through the framework
hot path (DistributedOptimizer -> exact sharded LM loss -> optimizer),
the language-model sibling of ``jax_synthetic_benchmark.py`` (reference
pattern: ``examples/pytorch_synthetic_benchmark.py`` timed batches).

Single chip (flash attention on TPU):

    python examples/jax_lm_benchmark.py --seq-len 2048

Sequence-parallel over a mesh (ring attention, flash per block):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/jax_lm_benchmark.py --data 2 --seq 4 --steps 3 \
        --layers 2 --d-model 64 --seq-len 1024
"""

import argparse
import json

import jax
import numpy as np

import horovod_tpu as hvd
from horovod_tpu.utils.benchmarks import (make_lm_bench, slope_window,
                                          sync)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", type=int, default=1, help="data-axis size")
    ap.add_argument("--seq", type=int, default=1, help="seq-axis size")
    ap.add_argument("--batch", type=int, default=8, help="global batch")
    ap.add_argument("--seq-len", type=int, default=2048,
                    help="global sequence length")
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--heads", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--no-flash", action="store_true")
    args = ap.parse_args()

    hvd.init()
    devs = np.asarray(jax.devices())
    n_used = args.data * args.seq
    assert devs.size >= n_used, f"need {n_used} devices, have {devs.size}"
    mesh = jax.sharding.Mesh(devs[:n_used].reshape(args.data, args.seq),
                             ("data", "seq"))

    seq_axis = "seq" if args.seq > 1 else None
    # the ONE copy of the workload (shared with bench.py's LM lines)
    step, state, tokens = make_lm_bench(
        mesh=mesh, seq_axis=seq_axis, batch=args.batch,
        seq_len=args.seq_len, layers=args.layers, d_model=args.d_model,
        heads=args.heads, vocab=args.vocab, flash=not args.no_flash)

    # one unconditional warm step (compile + prime the final-loss value;
    # safe at --warmup 0), then the requested extra warmup
    state, loss = step(state, tokens)
    sync(loss)
    for _ in range(args.warmup):
        state, loss = step(state, tokens)
        sync(loss)

    # readback-slope timing (utils/benchmarks.slope_window: the one copy
    # of the protocol; block_until_ready does not synchronize through
    # the async tunnel)
    def once(carry):
        st, _ = carry
        st, loss = step(st, tokens)
        return (st, loss), loss

    dt, (state, loss) = slope_window(once, (state, loss), args.steps)

    tok_s = args.batch * args.seq_len * args.steps / dt
    print(json.dumps({
        "metric": "transformer_lm_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/sec",
        "seq_len": args.seq_len,
        "mesh": {"data": args.data, "seq": args.seq},
        "flash_attention": not args.no_flash,
        "final_loss": round(float(loss), 4),
    }))


if __name__ == "__main__":
    main()
