"""Torch-adapter training example (reference: ``examples/pytorch_mnist.py``
— per-rank data shards, DistributedOptimizer, broadcast at start). CPU
torch; launch with:

    python -m horovod_tpu.run -np 2 python examples/pytorch_mnist.py
"""

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(784, 128)
        self.fc2 = torch.nn.Linear(128, 10)

    def forward(self, x):
        x = F.relu(self.fc1(x.reshape(x.shape[0], -1)))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    hvd.init()
    torch.manual_seed(42 + hvd.rank())

    rng = np.random.default_rng(0)
    n = 2048
    labels = rng.integers(0, 10, size=(n,))
    # synthetic digits: a bright column at 2*label over noise
    images = (rng.standard_normal((n, 28, 28)) * 0.1).astype(np.float32)
    images[np.arange(n), :, labels * 2] += 1.0
    # shard by rank (the DistributedSampler pattern)
    Xl = torch.from_numpy(images[hvd.rank()::hvd.size()])
    yl = torch.from_numpy(labels[hvd.rank()::hvd.size()])

    model = Net()
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05, momentum=0.9),
        named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    first = None
    for epoch in range(4):
        losses = []
        for i in range(0, len(Xl), 64):
            xb, yb = Xl[i:i + 64], yl[i:i + 64]
            opt.zero_grad()
            loss = F.nll_loss(model(xb), yb)
            loss.backward()
            opt.step()
            losses.append(loss.item())
        avg = float(np.asarray(hvd.allreduce(
            torch.tensor(np.mean(losses)), name=f"loss.{epoch}")))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {avg:.4f}")
        if first is None:
            first = avg
    assert avg < first * 0.6, (first, avg)
    if hvd.rank() == 0:
        print("done")


if __name__ == "__main__":
    main()
