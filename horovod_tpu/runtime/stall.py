"""Stall inspector: warn when ranks stop making progress together.

Reference: ``horovod/common/stall_inspector.cc`` — coordinator-side watchdog
that warns when a tensor has been submitted by some ranks but is missing on
others for >60 s (``stall_inspector.h:30-70``), with optional job shutdown
after ``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.

TPU version: the compiled data plane cannot stall *per-tensor* (one fused
program either runs or not), so the unit of progress is the **step**. Each
worker reports a heartbeat (step counter) through the controller; the
inspector warns when this worker's step outruns or lags the slowest/fastest
reported step for longer than the warning threshold, and can raise to abort
the job after the shutdown threshold.
"""

import logging
import threading
import time

logger = logging.getLogger("horovod_tpu")


class StallInspector:
    def __init__(self, warning_time=60.0, shutdown_time=0.0,
                 heartbeat_fn=None, check_interval=5.0):
        self._warning_time = warning_time
        self._shutdown_time = shutdown_time
        self._heartbeat_fn = heartbeat_fn  # () -> dict rank->last_step_time
        self._check_interval = check_interval
        self._last_progress = time.monotonic()
        self._stop_event = threading.Event()
        self._thread = None
        self._warned = False
        self._progress_listeners = []
        self.shutdown_requested = False

    def add_progress_listener(self, fn):
        """Register ``fn(step)`` to run on every ``record_progress`` —
        the elastic worker context hooks its driver-facing heartbeat here
        (elastic/worker.py), turning local step progress into the
        driver's liveness view."""
        self._progress_listeners.append(fn)

    def record_progress(self, step=None):
        """Call once per completed step (the analogue of a tensor being
        submitted by this rank)."""
        self._last_progress = time.monotonic()
        self._warned = False
        for fn in list(self._progress_listeners):
            try:
                fn(step)
            except Exception:
                logger.debug("progress listener failed", exc_info=True)

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name="hvd_tpu_stall", daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop_event.wait(self._check_interval):
            idle = time.monotonic() - self._last_progress
            if idle > self._warning_time and not self._warned:
                logger.warning(
                    "One or more ranks stalled for %.0f s (no training-step "
                    "progress). Check that all ranks are submitting steps.",
                    idle)
                self._warned = True
            if self._shutdown_time > 0 and idle > self._shutdown_time:
                logger.error(
                    "Stall exceeded HOROVOD_STALL_SHUTDOWN_TIME_SECONDS "
                    "(%.0f s); requesting shutdown.", self._shutdown_time)
                self.shutdown_requested = True
                break

    def stop(self):
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
