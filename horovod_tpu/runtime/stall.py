"""Stall inspector: warn when ranks stop making progress together.

Reference: ``horovod/common/stall_inspector.cc`` — coordinator-side watchdog
that warns when a tensor has been submitted by some ranks but is missing on
others for >60 s (``stall_inspector.h:30-70``), with optional job shutdown
after ``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.

TPU version: the compiled data plane cannot stall *per-tensor* (one fused
program either runs or not), so the unit of progress is the **step**. Each
worker reports a heartbeat (step counter) through the controller; the
inspector warns when this worker's step outruns or lags the slowest/fastest
reported step for longer than the warning threshold, and can raise to abort
the job after the shutdown threshold.

Telemetry: the inspector owns the ``hvd_stalled_ranks`` gauge — the
number of ranks currently past the warning threshold (from
``heartbeat_fn`` when a cluster view exists, else this rank's own 0/1).

Testability: the check is a pure function of time (``check_once``) driven
by an injectable ``clock``, so unit tests step a fake clock instead of
sleeping; the background loop's wake-up cadence is ``check_interval``,
deliberately independent of ``warning_time`` (a 600 s warning threshold
must not mean 600 s detection latency for the shutdown path).
"""

import logging
import threading
import time

logger = logging.getLogger("horovod_tpu")


class StallInspector:
    def __init__(self, warning_time=60.0, shutdown_time=0.0,
                 heartbeat_fn=None, check_interval=5.0,
                 clock=time.monotonic, on_shutdown=None):
        self._warning_time = warning_time
        self._shutdown_time = shutdown_time
        self._heartbeat_fn = heartbeat_fn  # () -> dict rank->last_progress
        self._check_interval = check_interval
        self._clock = clock
        self._on_shutdown = on_shutdown
        self._last_progress = clock()
        self._stop_event = threading.Event()
        self._thread = None
        self._warned = False
        self._progress_listeners = []
        self.shutdown_requested = False
        from horovod_tpu.telemetry import instruments as _tele
        self._stalled_gauge = _tele.stalled_ranks_gauge()
        self._stalled_gauge.set(0)

    def add_progress_listener(self, fn):
        """Register ``fn(step)`` to run on every ``record_progress`` —
        the elastic worker context hooks its driver-facing heartbeat here
        (elastic/worker.py), turning local step progress into the
        driver's liveness view."""
        self._progress_listeners.append(fn)

    def record_progress(self, step=None):
        """Call once per completed step (the analogue of a tensor being
        submitted by this rank)."""
        self._last_progress = self._clock()
        self._warned = False
        for fn in list(self._progress_listeners):
            try:
                fn(step)
            # hvd-lint: disable=HVD-EXCEPT -- a bad listener must not kill the progress watchdog
            except Exception:
                logger.debug("progress listener failed", exc_info=True)

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name="hvd_tpu_stall", daemon=True)
        self._thread.start()

    def _stalled_ranks(self, now):
        """Ranks past the warning threshold: the cluster heartbeat view
        when available, else this rank's own idleness as rank -1."""
        if self._heartbeat_fn is not None:
            try:
                beats = self._heartbeat_fn() or {}
                return [r for r, t in beats.items()
                        if now - t > self._warning_time]
            # hvd-lint: disable=HVD-EXCEPT -- heartbeat view is advisory; falls back to own idleness
            except Exception:
                logger.debug("heartbeat_fn failed", exc_info=True)
        idle = now - self._last_progress
        return [-1] if idle > self._warning_time else []

    def check_once(self, now=None):
        """One watchdog evaluation at time ``now`` (defaults to the
        injected clock). Updates the stalled-ranks gauge, logs the
        warning once per stall episode, and flips ``shutdown_requested``
        past the shutdown threshold. Returns the stalled rank list."""
        now = now if now is not None else self._clock()
        idle = now - self._last_progress
        stalled = self._stalled_ranks(now)
        self._stalled_gauge.set(len(stalled))
        if idle > self._warning_time and not self._warned:
            names = ("" if stalled == [-1] else
                     f" (stalled ranks: {sorted(stalled)})")
            logger.warning(
                "One or more ranks stalled for %.0f s (no training-step "
                "progress)%s. Check that all ranks are submitting steps.",
                idle, names)
            self._warned = True
            # the stall warning IS a dump trigger: the flight recorder
            # must hit disk while the evidence (which collective we are
            # parked in) is still in the ring — a later SIGKILL leaves
            # nothing (horovod_tpu.diag)
            try:
                from horovod_tpu.diag import recorder as _flightrec
                _flightrec.record_event("stall", idle_s=round(idle, 3),
                                        stalled=sorted(stalled))
                _flightrec.dump_now("stall")
            # hvd-lint: disable=HVD-EXCEPT -- forensics dump is best-effort on the warning path
            except Exception:
                logger.debug("stall flight-recorder dump failed",
                             exc_info=True)
        if (self._shutdown_time > 0 and idle > self._shutdown_time
                and not self.shutdown_requested):
            logger.error(
                "Stall exceeded HOROVOD_STALL_SHUTDOWN_TIME_SECONDS "
                "(%.0f s); requesting shutdown.", self._shutdown_time)
            self.shutdown_requested = True
            if self._on_shutdown is not None:
                try:
                    self._on_shutdown()
                # hvd-lint: disable=HVD-EXCEPT -- a shutdown-hook failure must not mask the stall itself
                except Exception:
                    logger.warning("stall shutdown hook failed",
                                   exc_info=True)
        return stalled

    def _loop(self):
        # the wake-up cadence is check_interval, never warning_time: a
        # long warning threshold must not delay shutdown detection
        while not self._stop_event.wait(self._check_interval):
            self.check_once()
            if self.shutdown_requested:
                break

    def stop(self):
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
