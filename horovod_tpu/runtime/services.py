"""Lifecycle of host-side services attached at ``init()`` time.

Reference equivalent: the service wiring in ``BackgroundThreadLoop``
(``horovod/common/operations.cc:328-528``) — timeline setup at
``operations.cc:388-395``, stall inspector, controller initialization.

Multi-process jobs start the **native core** (``cxx/`` via
``horovod_tpu._core``): its background thread owns the TCP control plane
(negotiation, Join, barrier) and the host CPU data plane (ring
collectives). Single-process jobs skip it entirely — the compiled XLA
path needs no host services.
"""

import logging
import os

logger = logging.getLogger("horovod_tpu")


def _resolve_controller_port(cfg):
    """Port 0 contract: rank 0 picks a free port on ITS host and publishes
    it through the launcher's rendezvous KV; everyone else polls for it.
    Avoids the launcher probing ports on a machine it doesn't run on."""
    import socket

    from horovod_tpu.run.rendezvous import kv_put, kv_wait
    if not cfg.rendezvous_addr:
        raise RuntimeError(
            "HOROVOD_CONTROLLER_PORT=0 requires the hvdrun rendezvous "
            "server (HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT)")
    if cfg.rank == 0:
        s = socket.socket()
        s.bind(("0.0.0.0", 0))
        port = s.getsockname()[1]
        s.close()
        kv_put(cfg.rendezvous_addr, cfg.rendezvous_port,
               "controller/port", str(port).encode())
        return port
    return int(kv_wait(cfg.rendezvous_addr, cfg.rendezvous_port,
                       "controller/port", timeout=120).decode())


def _rank_timeline_path(path, rank, size):
    """Per-rank trace paths for the cross-rank merge: multi-process jobs
    suffix the rank (``trace.rank<r>.json``), single-process keeps the
    plain path. The native core's C++ timeline still owns the PLAIN path
    on rank 0, so the Python per-rank files never collide with it."""
    if size <= 1:
        return path
    base, ext = os.path.splitext(path)
    return f"{base}.rank{rank}{ext or '.json'}"


def start(state):
    cfg = state.config
    native_core = bool(cfg.controller_addr and cfg.size > 1)
    # fresh goodput ledger per run: every wall-clock second from here to
    # shutdown gets attributed to a phase (telemetry/ledger.py); pure
    # host-side bookkeeping, disabled with HOROVOD_GOODPUT=0
    from horovod_tpu.telemetry import ledger as ledger_lib
    state.ledger = ledger_lib.reset_run()
    # flight recorder first: the black box must be armed before the
    # services whose failures it is meant to explain (controller
    # handshake, mesh build) can crash the process
    if cfg.flightrec_enabled:
        from horovod_tpu import diag
        state.flight_recorder = diag.install(
            capacity=cfg.flightrec_capacity, dump_dir=cfg.flightrec_dir,
            rank=cfg.rank, size=cfg.size, config=cfg)
        logger.info("flight recorder armed (capacity %d) -> %s",
                    cfg.flightrec_capacity,
                    state.flight_recorder.dump_path())
    # every rank writes its own host trace (pid = rank) so the telemetry
    # merge tool can build one cross-rank view; the native core's C++
    # timeline additionally records rank 0's negotiation plane at the
    # un-suffixed path
    if cfg.timeline:
        from horovod_tpu.utils.timeline import Timeline
        path = _rank_timeline_path(cfg.timeline, cfg.rank, cfg.size)
        state.timeline = Timeline(path,
                                  mark_cycles=cfg.timeline_mark_cycles,
                                  rank=cfg.rank,
                                  host=os.environ.get("HOROVOD_HOSTNAME"))
        logger.info("timeline enabled -> %s", path)
    if cfg.metrics_port is not None:
        from horovod_tpu import telemetry

        def _health():
            reg = telemetry.get_registry()
            steps = reg.get(telemetry.instruments.STEP_TOTAL)
            health = {"rank": cfg.rank, "size": cfg.size,
                      "step": int(steps.value) if steps is not None else 0}
            # elastic transitions flip the probe to 503 (server.py):
            # a rank parked in re-rendezvous or restoring a checkpoint
            # reports the phase it is parked in instead of "ok"
            phase = telemetry.get_ledger().active_health_label()
            if phase is not None:
                health["status"] = "recovering"
                health["phase"] = phase
            return health

        telemetry.install_compile_listeners()
        telemetry.build_info_gauge(cfg)
        # the stalled-ranks gauge must be scrapeable even before (or
        # without) a StallInspector: 0 = nothing known to be stalled
        telemetry.instruments.stalled_ranks_gauge().set(0)
        state.metrics_server = telemetry.MetricsServer(
            addr=cfg.metrics_addr, port=cfg.metrics_port,
            health_fn=_health, profile_dir=cfg.profile_dir)
        try:
            state.metrics_server.start()
        except OSError as e:
            logger.warning(
                "metrics endpoint failed to bind %s:%s (%s); telemetry "
                "recording stays on, the scrape plane is off",
                cfg.metrics_addr, cfg.metrics_port, e)
            state.metrics_server = None
    if native_core:
        from horovod_tpu import _core
        advertise = os.environ.get("HOROVOD_HOSTNAME", "127.0.0.1")
        if advertise in ("localhost",):
            advertise = "127.0.0.1"
        # hvdrun's NIC-discovery pre-flight (run/discovery.py) elects the
        # interfaces routable across all hosts; advertise this host's
        # address on the first elected interface we own, so the peer mesh
        # never hands out a NAT'ed/bridge address (reference: gloo
        # iface selection from the driver/task services)
        common = os.environ.get("HOROVOD_COMMON_INTERFACES")
        if common and advertise != "127.0.0.1":
            from horovod_tpu.run.discovery import local_interfaces
            mine = local_interfaces()
            for intf in common.split(","):
                if mine.get(intf):
                    advertise = mine[intf][0][0]
                    break
        controller_port = cfg.controller_port
        if controller_port == 0:
            controller_port = _resolve_controller_port(cfg)
        _core.init(rank=cfg.rank, size=cfg.size,
                   coord_host=cfg.controller_addr,
                   coord_port=controller_port,
                   advertise_host=advertise)
        state.controller = _core
        logger.info("native core started (controller %s:%d)",
                    cfg.controller_addr, cfg.controller_port)
    # elastic workers need the inspector even without the native core:
    # its progress hooks publish the heartbeats that form the elastic
    # driver's liveness view (elastic/worker.py)
    elastic = os.environ.get("HOROVOD_ELASTIC") == "1"
    if not cfg.stall_check_disable and (state.controller is not None
                                        or elastic):
        from horovod_tpu.runtime.stall import StallInspector
        state.stall_inspector = StallInspector(
            warning_time=cfg.stall_warning_time,
            shutdown_time=cfg.stall_shutdown_time)
        if elastic:
            try:
                from horovod_tpu.elastic import worker as elastic_worker
                elastic_worker.attach_progress_reporter(
                    state.stall_inspector)
            # hvd-lint: disable=HVD-EXCEPT -- optional elastic wiring; the stall inspector works alone
            except Exception:
                logger.warning("elastic worker context failed to attach",
                               exc_info=True)
        state.stall_inspector.start()
    # graceful eviction (elastic/preempt.py): armed for driver-managed
    # elastic workers, and for any run that opted in with a grace budget
    # or a spot-notice source in the env — installed AFTER the recorder
    # so SIGTERM rides its wakeup-fd watcher
    from horovod_tpu.elastic import preempt as _preempt
    if elastic or _preempt.configured():
        try:
            state.preempt_handler = _preempt.install()
            logger.info("graceful-eviction handler armed (grace %.0fs)",
                        _preempt.grace_seconds())
        # hvd-lint: disable=HVD-EXCEPT -- eviction is best-effort armor, not a startup dependency
        except Exception:
            logger.warning("graceful-eviction handler failed to install",
                           exc_info=True)


def stop(state):
    # the per-rank goodput dump rides shotgun with the flight-recorder
    # dumps: goodput.rank<r>.json next to flightrec.rank<r>.json, so the
    # end-of-run report (hvd-doctor perf / hvdrun --goodput-report) has
    # one directory to read
    try:
        from horovod_tpu.telemetry import ledger as ledger_lib
        led = getattr(state, "ledger", None) or ledger_lib.get_ledger()
        if led.enabled and led.started:
            dump_dir = (state.flight_recorder.dump_dir
                        if state.flight_recorder is not None
                        else state.config.flightrec_dir)
            if dump_dir:
                led.write_dump(dump_dir, state.config.rank)
        state.ledger = None
    # hvd-lint: disable=HVD-EXCEPT -- shutdown path: the ledger dump is best-effort
    except Exception:
        logger.warning("goodput ledger dump failed", exc_info=True)
    if getattr(state, "preempt_handler", None) is not None:
        from horovod_tpu.elastic import preempt as _preempt
        _preempt.uninstall()
        state.preempt_handler = None
    if state.metrics_server is not None:
        state.metrics_server.stop()
        state.metrics_server = None
    if state.stall_inspector is not None:
        state.stall_inspector.stop()
        state.stall_inspector = None
    if os.environ.get("HOROVOD_ELASTIC") == "1":
        from horovod_tpu.elastic import worker as elastic_worker
        elastic_worker.shutdown_worker_context()
    if state.controller is not None:
        state.controller.shutdown()
        state.controller = None
    if state.timeline is not None:
        state.timeline.close()
        state.timeline = None
    if state.flight_recorder is not None:
        # final dump on the clean path: "dump with a shutdown reason"
        # is how the doctor tells a clean exit from a hard kill
        from horovod_tpu import diag
        diag.uninstall(dump=True, reason="shutdown")
        state.flight_recorder = None
