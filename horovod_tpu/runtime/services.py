"""Lifecycle of host-side services attached at ``init()`` time.

Reference equivalent: the service wiring in ``BackgroundThreadLoop``
(``horovod/common/operations.cc:328-528``) — timeline setup at
``operations.cc:388-395``, stall inspector, controller initialization.
On TPU these attach as host threads/objects; there is no per-cycle
communication loop for the compiled path.
"""

import logging

logger = logging.getLogger("horovod_tpu")


def start(state):
    cfg = state.config
    if cfg.timeline and cfg.rank == 0:
        from horovod_tpu.utils.timeline import Timeline
        state.timeline = Timeline(cfg.timeline,
                                  mark_cycles=cfg.timeline_mark_cycles)
        logger.info("timeline enabled -> %s", cfg.timeline)
    if cfg.controller_addr and cfg.size > 1:
        from horovod_tpu.runtime.controller import ControllerClient
        state.controller = ControllerClient(
            cfg.controller_addr, cfg.controller_port, cfg.rank, cfg.size)
        state.controller.connect()
    if not cfg.stall_check_disable and state.controller is not None:
        from horovod_tpu.runtime.stall import StallInspector
        state.stall_inspector = StallInspector(
            warning_time=cfg.stall_warning_time,
            shutdown_time=cfg.stall_shutdown_time)
        state.stall_inspector.start()


def stop(state):
    if state.stall_inspector is not None:
        state.stall_inspector.stop()
        state.stall_inspector = None
    if state.controller is not None:
        state.controller.close()
        state.controller = None
    if state.timeline is not None:
        state.timeline.close()
        state.timeline = None
