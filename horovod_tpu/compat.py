"""Version bridging for the jax API surface this package targets.

The package is written against the current jax API (``jax.shard_map`` with
``check_vma``, ``jax.sharding.get_abstract_mesh``). Older runtimes (jax
0.4.x) spell these ``jax.experimental.shard_map.shard_map(check_rep=...)``
and have no abstract-mesh query — there the bound named axes are only
visible through ``jax.core``'s axis-env introspection. This module owns the
translation in ONE place and, when needed, installs ``jax.shard_map`` so
user code (and the test suite) written against the new spelling runs
unchanged on both.

Import-time side effect (installing the attribute on ``jax``) is deliberate:
``horovod_tpu/__init__`` imports this first, so anything imported after
``import horovod_tpu`` sees a working ``jax.shard_map`` regardless of the
runtime's jax version.
"""

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax < 0.6: experimental module, check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, **kwargs):
        if check_rep is None:
            # check_vma's varying-manual-axes type system has no 0.4.x
            # equivalent: this jax's check_rep rewrite rejects valid
            # programs the vma checker accepts (e.g. cond branches with
            # differing replication), so requests for vma checking
            # degrade to unchecked — semantics are unchanged, only the
            # soundness check is weaker
            check_rep = False
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_rep,
                          **kwargs)

    jax.shard_map = shard_map


# True when this jax has the varying-manual-axes (vma) type system —
# pcast/pvary with their AD transposes. The shims below keep FORWARD
# semantics on older jax, but code whose gradients rely on the
# pcast<->psum transpose pair (the 1F1B pipeline composed with a data
# axis) needs the real thing.
NATIVE_VMA = hasattr(jax.lax, "pcast")

if not hasattr(jax, "typeof"):  # jax < 0.6
    def _typeof(x):
        from jax import core
        return core.get_aval(x)

    jax.typeof = _typeof

if not hasattr(jax.lax, "pcast"):  # jax < 0.6: no vma type system
    def _pcast(x, axis_name, *, to):
        # the varying-manual-axes annotation only exists where shard_map
        # tracks per-axis replication (check_vma); on older jax the value
        # is already "varying" by construction — identity is exact
        del axis_name, to
        return x

    jax.lax.pcast = _pcast

if not hasattr(jax.lax, "axis_size"):  # jax < 0.4.38
    def _axis_size(axis_name):
        # psum of the constant 1 over a named axis is special-cased to
        # the (static) axis size — the pre-axis_size spelling
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = _axis_size


def gspmd_supported():
    """``(ok, reason)`` — whether this jax can run the GSPMD hot path
    (``training.make_train_step(spmd=True)`` / ``parallel/gspmd.py``):
    ``NamedSharding``, ``with_sharding_constraint`` and a ``jax.jit``
    that takes ``in_shardings``/``out_shardings``/``donate_argnums``.
    jax 0.4.x ships all three; genuinely older runtimes keep the
    explicit shard_map pipeline and get the reason string in the error.
    """
    import inspect

    try:
        from jax.sharding import NamedSharding  # noqa: F401
    except ImportError:
        return False, ("jax.sharding.NamedSharding is unavailable — "
                       "this jax predates the GSPMD sharding API")
    if not hasattr(jax.lax, "with_sharding_constraint"):
        return False, ("jax.lax.with_sharding_constraint is unavailable "
                       "— this jax cannot annotate in-program shardings")
    try:
        params = inspect.signature(jax.jit).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic builds
        return False, "jax.jit signature cannot be introspected"
    for kw in ("in_shardings", "out_shardings", "donate_argnums"):
        if kw not in params:
            return False, (f"jax.jit lacks {kw}= — this jax cannot "
                           "compile NamedSharding-annotated steps")
    return True, None


def bound_axis_names():
    """Mesh axis names bound in the current trace (inside ``shard_map`` /
    any named-axis context); ``()`` at top level. Works on both the
    abstract-mesh jax API and the 0.4.x axis-env internals."""
    get_abstract_mesh = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract_mesh is not None:
        try:
            abstract_mesh = get_abstract_mesh()
        # hvd-lint: disable=HVD-EXCEPT -- version-probe shim: failure means the feature is absent
        except Exception:
            return ()
        if abstract_mesh is None or abstract_mesh.empty:
            return ()
        return tuple(abstract_mesh.axis_names)
    try:  # jax 0.4.x
        from jax import core
        return tuple(core.unsafe_get_axis_names_DO_NOT_USE())
    # hvd-lint: disable=HVD-EXCEPT -- version-probe shim: failure means the feature is absent
    except Exception:
        return ()
